"""Program ledger: guarded introspection, donation verification (static
alias table + runtime buffer deletion), and the perf-regression gate.

The contracts under test (docs/observability.md "Program ledger"):

- the guarded accessors degrade to ``None`` on any backend path that
  lacks cost/memory analysis, and return normalized dicts on the CPU mesh;
- a ledger capture records compile wall-time, FLOPs, peak bytes and the
  donation map of the EXACT compiled program, and a donation XLA silently
  dropped is detected both statically (missing alias entry) and at
  runtime (input buffers left alive);
- every ``donate_argnums`` entry point the repo registers (gaussian tell,
  the bench and multichip generation steps, the batched functional
  search) has its aliasing verified at runtime — the dynamic complement
  of graftlint's static ``donation`` checker;
- the fast-tier REGRESSION GATE: the inventory captured at the gate
  shapes must sit inside ``ledger_baseline.json``'s tolerance bands — a
  program whose FLOPs or peak footprint inflates past the band fails
  tier-1 here instead of OOMing on the TPU months later, and a synthetic
  +20% violation demonstrably trips it; stale entries (improvements, or
  programs no longer captured) fail too, mirroring ``test_lint.py``'s
  baseline discipline.
"""

import copy
import dataclasses
import warnings
from functools import partial

import jax
import jax.numpy as jnp
import pytest

from evotorch_tpu.observability import (
    ProgramLedger,
    compare_to_baseline,
    counters,
    default_ledger_baseline_path,
    guarded_cost_analysis,
    guarded_memory_analysis,
    load_ledger_baseline,
    save_ledger_baseline,
    verify_runtime_donation,
)
from evotorch_tpu.observability.inventory import (
    GateConfig,
    capture_inventory,
    donated_programs,
    inventory_keys,
)
from evotorch_tpu.observability.programs import abstract_like, parse_alias_sources


@pytest.fixture(scope="module")
def gate_capture():
    """ONE inventory capture at the gate shapes, shared by every gate test
    (each capture is an AOT compile; sharing keeps the fast tier fast).

    The capture bypasses the persistent compile cache (conftest enables it
    suite-wide): an executable DESERIALIZED from the cache reports a
    constant +1408 bytes of peak memory on this backend, which would skew
    the fingerprints the gate bands against ledger_baseline.json — the
    instrument must measure the program, not the cache's framing. The dir
    knob alone is NOT enough: the cache singleton initializes once and
    keeps the directory it saw first, so the bypass must flip the enable
    flag and reset the singleton (restored afterwards, so the rest of the
    suite keeps its warm cache)."""
    from jax._src import compilation_cache as _compilation_cache

    enabled = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _compilation_cache.reset_cache()
    try:
        led = ProgramLedger()
        records, errors = capture_inventory(GateConfig(), led, strict=True)
    finally:
        jax.config.update("jax_enable_compilation_cache", enabled)
        _compilation_cache.reset_cache()
    assert errors == {}
    return records


# ---------------------------------------------------------------------------
# guarded introspection (backend-robust accessors)
# ---------------------------------------------------------------------------


class _RaisingStage:
    def cost_analysis(self):
        raise RuntimeError("analysis unavailable on this backend path")

    def memory_analysis(self):
        raise RuntimeError("analysis unavailable on this backend path")


class _NoneStage:
    def cost_analysis(self):
        return None

    def memory_analysis(self):
        return None


class _ListWrappedCost:
    """Some jax paths return a per-partition LIST of cost dicts."""

    def cost_analysis(self):
        return [{"flops": 3.0, "bytes accessed": 5.0, "utilization0{}": 9.0}]


class _EmptyListCost:
    def cost_analysis(self):
        return []


def test_guarded_accessors_degrade_to_none_instead_of_raising():
    assert guarded_cost_analysis(_RaisingStage()) is None
    assert guarded_memory_analysis(_RaisingStage()) is None
    assert guarded_cost_analysis(_NoneStage()) is None
    assert guarded_memory_analysis(_NoneStage()) is None
    assert guarded_cost_analysis(_EmptyListCost()) is None
    # list-wrapped dicts normalize; only the stable fields survive
    assert guarded_cost_analysis(_ListWrappedCost()) == {
        "flops": 3.0,
        "bytes_accessed": 5.0,
    }


def test_guarded_accessors_on_the_cpu_mesh():
    """The real path on this backend: normalized dicts with the documented
    fields, including the donation-aware peak_bytes derivation."""
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    lowered = fn.lower(jnp.zeros((64, 64)))
    cost = guarded_cost_analysis(lowered)
    assert cost is not None and cost["flops"] > 0
    memory = guarded_memory_analysis(lowered.compile())
    assert memory is not None
    for field in ("argument_bytes", "output_bytes", "temp_bytes", "peak_bytes"):
        assert field in memory and memory[field] >= 0
    assert memory["peak_bytes"] == (
        memory["argument_bytes"]
        + memory["output_bytes"]
        - memory.get("alias_bytes", 0)
        + memory["temp_bytes"]
    )


def test_parse_alias_sources_handles_nested_braces():
    text = (
        "ENTRY main, input_output_alias={ {0}: (0, {}, may-alias), "
        "{1}: (2, {}, may-alias) }, entry_computation_layout={...}"
    )
    assert parse_alias_sources(text) == [0, 2]
    assert parse_alias_sources("HloModule without any alias table") is None


# ---------------------------------------------------------------------------
# capture + donation verification
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0,))
def _donating_step(state, key):
    noise = jax.random.normal(key, state["mu"].shape)
    return {"mu": state["mu"] + noise, "sigma": state["sigma"] * 2.0}


@partial(jax.jit, donate_argnums=(0,))
def _dropping_step(state, key):
    # the only output is a scalar: nothing can alias the donated (64, 8)
    # buffer, XLA must drop the donation — the failure mode the ledger
    # exists to catch
    del key
    return jnp.sum(state)


def _toy_state():
    return {"mu": jnp.zeros((64, 8)), "sigma": jnp.ones((64, 8))}


def test_capture_records_costs_and_verified_donation():
    led = ProgramLedger()
    before = counters.get("peak_hbm_bytes")
    record = led.capture(
        "toy.step",
        _donating_step,
        abstract_like(_toy_state()),
        jax.random.key(0),
        shape={"n": 64},
    )
    assert record.key == "toy.step@n=64"
    assert record.compile_seconds > 0 and record.lower_seconds > 0
    assert record.flops is not None and record.flops > 0
    assert record.peak_bytes is not None and record.peak_bytes > 0
    assert record.donation is not None and record.donation.verified is True
    assert list(record.donation.missing) == []
    assert led.get("toy.step", {"n": 64}) is record
    # the registry's high-water gauge saw the capture
    assert counters.get("peak_hbm_bytes") >= record.peak_bytes
    assert counters.get("peak_hbm_bytes") >= before
    payload = record.to_json()
    assert payload["donation"]["verified"] is True


def test_capture_detects_silently_dropped_donation():
    led = ProgramLedger()
    with warnings.catch_warnings():
        # jax itself warns "Some donated buffers were not usable" at compile
        warnings.simplefilter("ignore")
        record = led.capture(
            "toy.dropped",
            _dropping_step,
            abstract_like(jnp.zeros((64, 8))),
            jax.random.key(0),
        )
    assert record.donation is not None
    assert record.donation.verified is False
    assert len(record.donation.missing) > 0
    # and the runtime ground truth agrees: the buffer was NOT invalidated
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, report = verify_runtime_donation(
            _dropping_step, (jnp.zeros((64, 8)), jax.random.key(0)), (0,)
        )
    assert report == {0: False}


_DONATED_PROGRAM_NAMES = [
    "gaussian.tell",
    "bench.generation",
    "multichip.generation",
    "gspmd.training_span",
    "functional_batched_search",
]


@pytest.mark.parametrize("name", _DONATED_PROGRAM_NAMES)
def test_runtime_donation_applied_for_every_registered_program(name):
    """The dynamic donation sweep: execute each donate_argnums entry point
    and assert jax invalidated the donated state — XLA consumed the
    aliasing, it was not silently dropped."""
    cases = {n: (fn, args, dn) for n, fn, args, dn in donated_programs()}
    fn, args, donate_argnums = cases[name]
    _, report = verify_runtime_donation(fn, args, donate_argnums)
    assert report == {argnum: True for argnum in donate_argnums}, name


def test_static_donation_verified_across_the_inventory(gate_capture):
    donating = [
        r for r in gate_capture if r.donation is not None and r.donation.donated
    ]
    assert {r.name for r in donating} >= set(_DONATED_PROGRAM_NAMES)
    for record in donating:
        assert record.donation.verified is True, (
            record.key,
            record.donation.to_json(),
        )


# ---------------------------------------------------------------------------
# the perf-regression gate
# ---------------------------------------------------------------------------


def test_regression_gate_matches_checked_in_baseline(gate_capture):
    """THE tier-1 gate: every inventory program's FLOPs and peak bytes sit
    inside ledger_baseline.json's tolerance bands, with no stale entries.
    Refresh flow on intended changes:
    ``python -m evotorch_tpu.observability.report --cpu --write-baseline``."""
    baseline = load_ledger_baseline(default_ledger_baseline_path())
    violations, stale = compare_to_baseline(gate_capture, baseline)
    assert violations == [], "program-ledger regressions:\n" + "\n".join(violations)
    assert stale == [], "stale ledger baseline entries:\n" + "\n".join(stale)


def test_gate_fails_on_synthetic_peak_inflation(gate_capture):
    """A +20% peak-HBM regression (simulated by deflating the baseline)
    demonstrably trips the gate."""
    baseline = copy.deepcopy(load_ledger_baseline(default_ledger_baseline_path()))
    for entry in baseline["programs"]:
        if entry.get("peak_bytes"):
            entry["peak_bytes"] = entry["peak_bytes"] / 1.2
    violations, _ = compare_to_baseline(gate_capture, baseline)
    assert violations, "a +20% peak inflation must violate the 15% band"
    assert any("peak_bytes" in message for message in violations)


def test_gate_flags_improvements_and_orphans_as_stale(gate_capture):
    baseline = copy.deepcopy(load_ledger_baseline(default_ledger_baseline_path()))
    for entry in baseline["programs"]:
        if entry.get("flops"):
            entry["flops"] = entry["flops"] * 1.3  # measured is now -23%
    baseline["programs"].append(
        {"key": "ghost.program@n=1", "flops": 1.0, "peak_bytes": 1}
    )
    violations, stale = compare_to_baseline(gate_capture, baseline)
    assert violations == []
    assert any("improved past" in message for message in stale)
    assert any("no longer captured" in message for message in stale)


def test_gate_flags_unbaselined_programs_as_violations(gate_capture):
    baseline = copy.deepcopy(load_ledger_baseline(default_ledger_baseline_path()))
    baseline["programs"] = [
        e for e in baseline["programs"] if not e["key"].startswith("rollout.budget")
    ]
    violations, _ = compare_to_baseline(gate_capture, baseline)
    assert any("not in ledger_baseline.json" in message for message in violations)


def test_write_baseline_refuses_partial_runs(tmp_path, gate_capture):
    expected = [r.key for r in gate_capture]
    # a program the run never captured -> refuse
    with pytest.raises(ValueError, match="not captured"):
        save_ledger_baseline(
            gate_capture,
            tmp_path / "partial.json",
            expected_keys=expected + ["missing.program@n=1"],
        )
    # a captured program whose gated analysis came back null -> refuse
    broken = dataclasses.replace(gate_capture[0], memory=None)
    with pytest.raises(ValueError, match="gated analysis"):
        save_ledger_baseline(
            [broken], tmp_path / "null.json", expected_keys=[broken.key]
        )
    # the complete run writes, round-trips, and self-compares clean
    path = save_ledger_baseline(
        gate_capture, tmp_path / "full.json", expected_keys=expected
    )
    violations, stale = compare_to_baseline(gate_capture, load_ledger_baseline(path))
    assert violations == [] and stale == []


def test_inventory_keys_match_capture(gate_capture):
    assert inventory_keys(GateConfig()) == [r.key for r in gate_capture]


# ---------------------------------------------------------------------------
# status keys + logger columns
# ---------------------------------------------------------------------------


def test_searcher_status_and_logger_rows_carry_ledger_keys():
    """The per-generation ledger/status keys thread through the scalar
    loggers like PR 8's `compiles`: compile_seconds (per-step compile
    wall-time delta) and peak_hbm_bytes (the ledger gauge) appear in every
    PandasLogger row."""
    from evotorch_tpu import Problem, vectorized
    from evotorch_tpu.algorithms.gaussian import SNES
    from evotorch_tpu.logging import PandasLogger

    @vectorized
    def sphere(xs):
        return jnp.sum(xs**2, axis=-1)

    problem = Problem(
        "min", sphere, solution_length=5, initial_bounds=(-3, 3), seed=0
    )
    searcher = SNES(problem, stdev_init=2.0)
    logger = PandasLogger(searcher)
    searcher.run(2)
    status = dict(searcher.status.items())
    assert isinstance(status["compile_seconds"], float)
    assert status["compile_seconds"] >= 0.0
    assert status["peak_hbm_bytes"] >= 0
    for row in logger._data:
        assert "compile_seconds" in row
        assert "peak_hbm_bytes" in row
