"""Shared-trunk + per-lane low-rank-delta policy form (docs/policies.md).

The contract under test: the trunk-delta forward, every rollout contract,
the PGPE update and the GSPMD sharded evaluator must agree numerically with
materializing the dense population ``theta_i = center + basis @ z_i`` —
and the sharded evaluations must be BIT-identical to the unsharded one
(the model-axis trunk sharding is pure storage layout).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.algorithms.functional import (
    pgpe,
    pgpe_ask_trunk_delta,
    pgpe_tell,
    pgpe_tell_trunk_delta,
)
from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import (
    RNN,
    FlatParamsPolicy,
    Linear,
    Tanh,
    trunk_delta_forward,
)
from evotorch_tpu.neuroevolution.net.lowrank import (
    prepare_trunk_delta,
    trunk_delta_supported,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.neuroevolution.net.vecrl import (
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)
from evotorch_tpu.tools.lowrank import TrunkDeltaParamsBatch, is_factored


def _mlp_policy(in_dim=9, hidden=16, out_dim=4):
    net = Linear(in_dim, hidden) >> Tanh() >> Linear(hidden, out_dim) >> Tanh()
    return FlatParamsPolicy(net)


def _fresh_state(L, stdev=0.5):
    return pgpe(
        center_init=jnp.asarray(
            np.random.default_rng(0).normal(size=L) * 0.2, jnp.float32
        ),
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=stdev,
    )


def _trunk_batch(policy, n=12, k=4, seed=0):
    state = _fresh_state(policy.parameter_count)
    return pgpe_ask_trunk_delta(
        jax.random.key(seed), state, popsize=n, rank=k, policy=policy
    )


def _dense_forward(policy, dense, obs):
    out, _ = jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    return out


def test_trunk_batch_shape_and_factored():
    policy = _mlp_policy()
    params = _trunk_batch(policy, n=10, k=3)
    assert isinstance(params, TrunkDeltaParamsBatch)
    assert is_factored(params)
    assert params.popsize == 10 and params.rank == 3
    assert trunk_delta_supported(policy.module)
    # take() keeps the factor tree (type-preserving per-lane gather)
    sub = params.take(jnp.asarray([1, 3, 5]))
    assert isinstance(sub, TrunkDeltaParamsBatch)
    assert sub.coeffs.shape[0] == 3
    # the materialized view and the factor view describe the same population:
    # basis column m is vec(b_m a_m^T) blockwise (sigma folded)
    assert params.materialize().shape == (10, policy.parameter_count)


def test_trunk_forward_matches_dense_mlp():
    policy = _mlp_policy()
    params = _trunk_batch(policy, n=12, k=4, seed=1)
    obs = jnp.asarray(np.random.default_rng(2).normal(size=(12, 9)), jnp.float32)
    out_td, state = trunk_delta_forward(policy, params, None, obs, None)
    assert state is None
    out_dense = _dense_forward(policy, params.materialize(), obs)
    np.testing.assert_allclose(
        np.asarray(out_td), np.asarray(out_dense), rtol=1e-4, atol=1e-5
    )


def test_trunk_forward_matches_dense_rnn():
    net = RNN(5, 7) >> Tanh() >> Linear(7, 3)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=8, k=3, seed=3)
    obs = jnp.asarray(np.random.default_rng(4).normal(size=(8, 5)), jnp.float32)
    proto = policy.initial_state()
    states = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (8,) + leaf.shape), proto
    )
    out_td, st_td = trunk_delta_forward(policy, params, None, obs, states)
    out_dense, st_dense = jax.vmap(policy)(params.materialize(), obs, states)
    np.testing.assert_allclose(
        np.asarray(out_td), np.asarray(out_dense), rtol=1e-4, atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        st_td,
        st_dense,
    )


def test_trunk_forward_blocked_bit_identical():
    # the blocked forward (static lane blocks through lax.map) runs the SAME
    # per-lane ops, so it must be bit-identical to the single-block form
    policy = _mlp_policy()
    params = _trunk_batch(policy, n=12, k=4, seed=5)
    obs = jnp.asarray(np.random.default_rng(6).normal(size=(12, 9)), jnp.float32)
    one, _ = trunk_delta_forward(
        policy, params, prepare_trunk_delta(policy, params), obs, None
    )
    blocked, _ = trunk_delta_forward(
        policy, params, prepare_trunk_delta(policy, params, trunk_block=4), obs, None
    )
    np.testing.assert_array_equal(np.asarray(one), np.asarray(blocked))


@pytest.mark.parametrize("mode", ["budget", "episodes", "episodes_refill"])
def test_rollout_trunk_matches_dense_rollout(mode):
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 16) >> Tanh() >> Linear(16, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=16, k=4, seed=7)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=60, observation_normalization=True)
    r_td = run_vectorized_rollout(
        env, policy, params, jax.random.key(9), stats, eval_mode=mode, **kw
    )
    r_dense = run_vectorized_rollout(
        env, policy, params.materialize(), jax.random.key(9), stats,
        eval_mode=mode, **kw,
    )
    np.testing.assert_allclose(
        np.asarray(r_td.scores), np.asarray(r_dense.scores), rtol=1e-4, atol=1e-4
    )
    assert int(r_td.total_steps) == int(r_dense.total_steps)


def test_compacting_rollout_accepts_trunk_delta():
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=16, k=4, seed=8)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=80)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats, eval_mode="episodes", **kw
    )
    comp = run_vectorized_rollout_compacting(
        env, policy, params, jax.random.key(2), stats,
        chunk_size=10, allowed_widths=(4, 8), **kw,
    )
    np.testing.assert_allclose(
        np.asarray(comp.scores), np.asarray(mono.scores), rtol=1e-5, atol=1e-5
    )


def test_rollout_trunk_block_bit_identical():
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=12, k=4, seed=9)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=40, eval_mode="budget")
    plain = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, **kw
    )
    blocked = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, trunk_block=4, **kw
    )
    np.testing.assert_array_equal(
        np.asarray(plain.scores), np.asarray(blocked.scores)
    )


def test_pgpe_trunk_tell_matches_dense_tell():
    # the factored gradients flow through the materialized effective basis:
    # the update must equal pgpe_tell on the materialized population
    policy = _mlp_policy()
    L = policy.parameter_count
    state = _fresh_state(L, stdev=0.7)
    params = pgpe_ask_trunk_delta(
        jax.random.key(3), state, popsize=24, rank=6, policy=policy
    )
    # antithetic layout (required by the factored gradient math)
    np.testing.assert_allclose(
        np.asarray(params.coeffs[0::2]), -np.asarray(params.coeffs[1::2])
    )
    evals = jnp.asarray(np.random.default_rng(11).normal(size=24), jnp.float32)
    s_td = pgpe_tell_trunk_delta(state, params, evals)
    s_dense = pgpe_tell(state, params.materialize(), evals)
    np.testing.assert_allclose(
        np.asarray(s_td.stdev), np.asarray(s_dense.stdev), rtol=1e-4, atol=1e-6
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s_td.optimizer_state,
        s_dense.optimizer_state,
    )


def test_pgpe_trunk_delta_improves_sphere():
    # end-to-end: trunk-delta PGPE optimizes (sphere on the materialized
    # population, mirroring test_pgpe_lowrank_improves_sphere) even though
    # each generation only explores the rank-k structured subspace
    policy = _mlp_policy(in_dim=4, hidden=8, out_dim=2)
    L = policy.parameter_count
    state = pgpe(
        center_init=jnp.full(L, 3.0),
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.5,
        optimizer="adam",
    )
    key = jax.random.key(0)

    first = None
    for _ in range(60):
        key, sub = jax.random.split(key)
        params = pgpe_ask_trunk_delta(sub, state, popsize=64, rank=8, policy=policy)
        evals = -jnp.sum(params.materialize() ** 2, axis=-1)
        state = pgpe_tell_trunk_delta(state, params, evals)
        mean_eval = float(jnp.mean(evals))
        if first is None:
            first = mean_eval
    assert mean_eval > first * 0.2  # losses shrink toward 0 (maximizing -||x||^2)
    assert mean_eval > -L  # well below the initial ~ -9L


# -- GSPMD: model-axis trunk sharding is bit-exact ----------------------------


def _mesh_evaluator_scores(env, policy, params, rkey, stats, mesh_shape, **kw):
    from evotorch_tpu.parallel import make_mesh
    from evotorch_tpu.parallel.evaluate import make_sharded_rollout_evaluator

    mesh = make_mesh(mesh_shape)
    evaluator = make_sharded_rollout_evaluator(env, policy, mesh=mesh, **kw)
    result, _ = evaluator(params, rkey, stats)
    return np.asarray(result.scores)


@pytest.mark.parametrize("mode", ["budget", "episodes_refill"])
def test_trunk_mesh_bit_identity(mode):
    # unsharded vs 1-D pop mesh vs 2-D pop x model mesh: the model-axis
    # sharding of center/basis is ZeRO-style storage layout — XLA gathers
    # the exact values at use, so scores must be BIT-identical
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=16, k=4, seed=11)
    stats = RunningNorm(env.observation_size).stats
    rkey = jax.random.key(13)
    kw = dict(num_episodes=2, episode_length=24, eval_mode=mode)
    base = run_vectorized_rollout(env, policy, params, rkey, stats, **kw)
    expected = np.asarray(base.scores)
    for mesh_shape in ({"pop": 8}, {"pop": 4, "model": 2}):
        got = _mesh_evaluator_scores(
            env, policy, params, rkey, stats, mesh_shape, **kw
        )
        np.testing.assert_array_equal(got, expected)


def test_trunk_mesh_bit_identity_padded():
    # indivisible popsize exercises the pad+mask path: the padded coeff rows
    # are masked out, the trunk is shared — still bit-identical
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _trunk_batch(policy, n=18, k=4, seed=15)
    stats = RunningNorm(env.observation_size).stats
    rkey = jax.random.key(17)
    kw = dict(num_episodes=1, episode_length=16, eval_mode="budget")
    base = run_vectorized_rollout(env, policy, params, rkey, stats, **kw)
    got = _mesh_evaluator_scores(
        env, policy, params, rkey, stats, {"pop": 4, "model": 2}, **kw
    )
    np.testing.assert_array_equal(got, np.asarray(base.scores))


def test_trunk_generation_step_2d_mesh():
    # the whole donated ask->eval->tell program with trunk-delta ask/tell
    # compiles and runs on a pop x model mesh
    from evotorch_tpu.parallel import make_mesh
    from evotorch_tpu.parallel.evaluate import make_generation_step

    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    state = _fresh_state(policy.parameter_count)
    stats = RunningNorm(env.observation_size).stats

    def ask(k, s):
        return pgpe_ask_trunk_delta(k, s, popsize=16, rank=4, policy=policy)

    step = make_generation_step(
        env, policy, ask=ask, tell=pgpe_tell_trunk_delta, popsize=16,
        mesh=make_mesh({"pop": 4, "model": 2}),
        num_episodes=1, episode_length=16, eval_mode="budget",
    )
    # the step program DONATES the input state: snapshot the center first.
    # The .copy() is load-bearing — np.asarray of a CPU jax array is a
    # zero-copy VIEW of the device buffer, and a donated program may write
    # its output into that very buffer in place (the persistent-compile-cache
    # deserialized executable does; a freshly compiled one happens not to),
    # which would silently turn this "snapshot" into the post-update center.
    center_before = np.asarray(state.optimizer_state.center).copy()
    state2, scores, stats2, steps, _telemetry = step(state, jax.random.key(1), stats)
    assert np.isfinite(np.asarray(scores)).all()
    assert int(np.asarray(steps)) == 16 * 16
    assert not np.allclose(np.asarray(state2.optimizer_state.center), center_before)


# -- autotuner pure core: rank preference inside the throughput band ----------


def test_select_winner_rank_preference_band():
    from evotorch_tpu.observability.autotune import CandidateStats, select_winner

    r4 = CandidateStats(config={"rank": 4}, samples=[100.0, 100.0, 100.0])
    r16 = CandidateStats(config={"rank": 16}, samples=[95.0, 95.0, 95.0])
    r64 = CandidateStats(config={"rank": 64}, samples=[70.0, 70.0, 70.0])
    results = [r4, r16, r64]
    # plain selection: fastest wins
    assert select_winner(results) is r4

    def prefer(config):
        return int(config.get("rank", 0))

    # rank preference inside a 10% band: r16 is within the band, r64 is not
    assert select_winner(results, tolerance=0.1, prefer=prefer) is r16
    # a wide band admits r64
    assert select_winner(results, tolerance=0.5, prefer=prefer) is r64
    # preference ties break on throughput
    r16b = CandidateStats(config={"rank": 16}, samples=[99.0, 99.0, 99.0])
    assert select_winner([r4, r16, r16b], tolerance=0.1, prefer=prefer) is r16b


def test_policy_harness_knobs():
    from evotorch_tpu.observability.autotune import PolicyHarness, TuneShape

    shape = TuneShape(env_name="cartpole", popsize=8, episode_length=10)
    harness = PolicyHarness(shape, ranks=(2, 4), trunk_blocks=(0, 4, 3))
    assert harness.group == "policy"
    specs = {spec.name: spec for spec in harness.knob_group().knobs}
    assert tuple(specs["rank"].values) == (2, 4)
    # trunk_blocks keeps 0 and the divisors of popsize strictly below it
    assert tuple(specs["trunk_block"].values) == (0, 4)
    assert harness.winner_tolerance == 0.1
    assert harness.winner_prefer({"rank": 16}) == 16
    config = {"rank": 4, "trunk_block": 0}
    assert harness.tuned_config(config) == {"rank": 4, "trunk_block": 0}
    assert harness.default_config()["rank"] == 2


# -- SLO: the min_model_efficiency rule ---------------------------------------


def test_slo_min_model_efficiency_rule():
    from evotorch_tpu.observability.slo import Rule, SLOWatchdog

    dog = SLOWatchdog([Rule("min_model_efficiency", threshold=0.5)])
    # no ledger columns: the rule is skipped, not violated
    report = dog.check(None, status={})
    assert report.ok and report.checked == 0
    report = dog.check(None, status={"model_efficiency": 0.62})
    assert report.ok and report.checked == 1
    report = dog.check(None, status={"model_efficiency": 0.31})
    assert not report.ok
    assert "model_efficiency=0.31" in report.violations[0]


def test_check_bench_line_min_model_efficiency():
    from evotorch_tpu.observability.slo import check_bench_line

    line = {
        "steady_compiles": 0,
        "occupancy": 0.9,
        "model_efficiency": 0.4,
        "modes": {
            "budget": {"occupancy": 0.9, "model_efficiency": 0.4},
            "episodes": {"occupancy": 0.5, "model_efficiency": 0.05},
        },
    }
    # floor unset: ledger columns are not checked at all
    assert check_bench_line(line).ok
    report = check_bench_line(line, min_model_efficiency=0.1)
    assert not report.ok
    assert any("modes.episodes.model_efficiency" in v for v in report.violations)
    # a BENCH_LEDGER=0 line (no efficiency columns) skips the checks
    bare = {"steady_compiles": 0, "occupancy": 0.9, "modes": {}}
    assert check_bench_line(bare, min_model_efficiency=0.1).ok
