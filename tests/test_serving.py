"""Multi-tenant evaluation service (evotorch_tpu/serving, docs/serving.md).

The acceptance spine: (1) items from >= 2 tenants packed into ONE resident
``episodes_refill`` dispatch produce per-tenant scores BIT-IDENTICAL to each
tenant evaluating standalone; (2) tenant admission/departure churn
re-dispatches the same executable — zero steady-state compiles under the
retrace sentinel; (3) the group-id plane credits scores/steps/episodes to
the right tenant whatever the lane rebinding.

Warm-up discipline: VecNE's eager counter bump compiles on its first TWO
evaluations (int+array then array+array), so every retrace-sentinel window
over a VecNE path warms twice first — same reason bench.py warms each A/B
leg twice.
"""

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.analysis import track_compiles
from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution import VecNE
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout
from evotorch_tpu.observability.devicemetrics import GroupTelemetry
from evotorch_tpu.parallel.evaluate import make_resident_rollout_program
from evotorch_tpu.serving import (
    EvalServer,
    FIFOAdmission,
    RemoteEvalBackend,
    StarvationAwareAdmission,
    serve_stdio,
)

pytestmark = pytest.mark.serving


def _env():
    return CartPole(continuous_actions=True)


def _policy(env):
    return FlatParamsPolicy(Linear(env.observation_size, env.action_size) >> Tanh())


def _values(policy, n, seed):
    # numpy, not jax.random.split: a varying n would compile a new split
    # program inside the retrace-sentinel windows below
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, policy.parameter_count)).astype(np.float32)


def _standalone_refill(env, policy, values, key, **kw):
    return run_vectorized_rollout(
        env, policy, values, key, None,
        eval_mode="episodes_refill", num_episodes=1, **kw,
    )


# ---------------------------------------------------------------- engine level


def test_refill_group_rebinding_credits_and_zero_compiles():
    """Satellite: lane rebinding A -> B must credit scores/steps/episodes to
    the right groups with ZERO steady compiles (groups are traced)."""
    env = _env()
    policy = _policy(env)
    n, width, num_groups = 8, 3, 3  # width < n forces lane recycling
    values = jnp.asarray(_values(policy, n, 0))
    solution_keys = jax.random.split(jax.random.key(5), n)

    def run(groups, key):
        return run_vectorized_rollout(
            env, policy, values, key, None,
            eval_mode="episodes_refill", num_episodes=1, refill_width=width,
            groups=groups, num_groups=num_groups,
            solution_keys=solution_keys,
        )

    fn = jax.jit(run)
    groups_a = jnp.asarray([1, 1, 1, 1, 2, 2, 2, 2], dtype=jnp.int32)
    groups_b = jnp.asarray([2, 2, 1, 1, 1, 1, 2, 2], dtype=jnp.int32)
    key = jax.random.key(9)
    res_a = fn(groups_a, key)
    jax.block_until_ready(res_a.scores)
    with track_compiles() as log:
        res_b = fn(groups_b, key)
        jax.block_until_ready(res_b.scores)
    assert log.count == 0, f"group rebinding retraced: {log.names}"

    # group binding is pure accounting: per-item randomness comes from
    # solution_keys, so the scores must be bit-identical across bindings
    np.testing.assert_array_equal(np.asarray(res_a.scores), np.asarray(res_b.scores))

    # credit: cartpole pays reward 1 per step, so each solution's score IS
    # its episode's step count — per-group steps/episodes must match the
    # binding exactly
    scores = np.asarray(res_b.scores)
    gt = GroupTelemetry.from_array(np.asarray(res_b.telemetry))
    binding = np.asarray(groups_b)
    for g in (1, 2):
        row = gt.group(g)
        mask = binding == g
        assert row.episodes == int(mask.sum())
        assert row.env_steps == int(scores[mask].sum())


def test_resident_program_packs_two_tenants_bit_identical():
    """Tentpole acceptance at the substrate layer: one resident program,
    one dispatch, two tenants — per-tenant scores bit-identical to each
    tenant's standalone episodes_refill run with its own key."""
    env = _env()
    policy = _policy(env)
    n1, n2 = 3, 5
    v1, v2 = _values(policy, n1, 1), _values(policy, n2, 2)
    k1, k2 = jax.random.key(11), jax.random.key(22)

    ref1 = _standalone_refill(env, policy, v1, k1, refill_width=2)
    ref2 = _standalone_refill(env, policy, v2, k2, refill_width=3)

    program = make_resident_rollout_program(
        env, policy, num_groups=3, refill_width=4, num_episodes=1,
        seed_stride=n1 + n2,
    )
    slab = np.concatenate([v1, v2])
    lane_ids = np.asarray(list(range(n1)) + list(range(n2)), dtype=np.int32)
    groups = np.asarray([1] * n1 + [2] * n2, dtype=np.int32)
    kd1, kd2 = np.asarray(jax.random.key_data(k1)), np.asarray(jax.random.key_data(k2))
    solution_keys = jax.random.wrap_key_data(np.stack([kd1] * n1 + [kd2] * n2))
    out = program(slab, jax.random.key(0), None, lane_ids, groups, solution_keys)
    packed = np.asarray(out.scores)
    np.testing.assert_array_equal(packed[:n1], np.asarray(ref1.scores))
    np.testing.assert_array_equal(packed[n1:], np.asarray(ref2.scores))
    assert program.dispatches == 1
    assert program.key[2] == "episodes_refill"


# ---------------------------------------------------------------- server level


def test_server_packs_two_tenants_one_dispatch_bit_identical():
    env = _env()
    policy = _policy(env)
    n1, n2 = 3, 5
    v1, v2 = _values(policy, n1, 1), _values(policy, n2, 2)
    k1, k2 = jax.random.key(11), jax.random.key(22)

    server = EvalServer(env, policy, slab_size=n1 + n2, max_tenants=2)
    t1, t2 = server.admit("a"), server.admit("b")
    f1 = server.submit(t1, v1, key=k1)
    f2 = server.submit(t2, v2, key=k2)
    assert not f1.done() and not f2.done()
    server.drain()
    assert server.dispatches == 1  # both tenants rode ONE slab
    assert server.occupancy() == 1.0

    r1, r2 = f1.result(), f2.result()
    ref1 = _standalone_refill(env, policy, v1, k1, refill_width=2)
    ref2 = _standalone_refill(env, policy, v2, k2, refill_width=3)
    np.testing.assert_array_equal(np.asarray(r1.scores), np.asarray(ref1.scores))
    np.testing.assert_array_equal(np.asarray(r2.scores), np.asarray(ref2.scores))
    # per-tenant accounting: cartpole scores count steps 1:1
    assert r1.total_episodes == n1 and r2.total_episodes == n2
    assert r1.total_steps == int(np.asarray(r1.scores).sum())
    assert r2.total_steps == int(np.asarray(r2.scores).sum())


def test_server_padding_rows_stay_in_group_zero():
    env = _env()
    policy = _policy(env)
    server = EvalServer(env, policy, slab_size=8, max_tenants=2)
    tenant = server.admit()
    values = _values(policy, 5, 3)
    future = server.submit(tenant, values, key=jax.random.key(7))
    server.drain()
    result = future.result()
    # 3 idle rows were padded into group 0; the tenant's episode count must
    # not see them, and its scores still match standalone
    assert result.total_episodes == 5
    assert server.occupancy() == 5 / 8
    ref = _standalone_refill(env, policy, values, jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))


def test_server_churn_zero_steady_compiles():
    env = _env()
    policy = _policy(env)
    server = EvalServer(env, policy, slab_size=6, max_tenants=3)
    t1, t2 = server.admit("a"), server.admit("b")

    def round_trip(tenant, seed):
        future = server.submit(tenant, _values(policy, 3, seed), key=jax.random.key(seed))
        server.drain()
        return future.result()

    # warm twice: first dispatch compiles the program + the eager host-side
    # key plumbing; the second pins the steady state
    round_trip(t1, 1), round_trip(t2, 2)
    round_trip(t1, 3), round_trip(t2, 4)

    with track_compiles() as log:
        server.depart(t2)
        t3 = server.admit("c")  # reuses t2's group row
        round_trip(t1, 5)
        round_trip(t3, 6)
        # partial slab (padding path) and a multi-request pack
        fa = server.submit(t1, _values(policy, 2, 7), key=jax.random.key(7))
        fb = server.submit(t3, _values(policy, 3, 8), key=jax.random.key(8))
        server.drain()
        fa.result(), fb.result()
    assert log.count == 0, f"tenant churn retraced: {log.names}"


def test_server_obs_norm_slots_are_isolated():
    env = _env()
    policy = _policy(env)
    server = EvalServer(
        env, policy, slab_size=6, max_tenants=2, observation_normalization=True
    )
    t1, t2 = server.admit("a"), server.admit("b")
    f1 = server.submit(t1, _values(policy, 3, 1), key=jax.random.key(1))
    f2 = server.submit(t2, _values(policy, 3, 2), key=jax.random.key(2))
    server.drain()
    f1.result(), f2.result()
    s1, s2 = server.tenant_stats(t1), server.tenant_stats(t2)
    assert float(s1.count) > 0 and float(s2.count) > 0
    # the tenants saw different trajectories, so their slots must differ —
    # shared stats would make them equal
    assert not np.array_equal(np.asarray(s1.sum), np.asarray(s2.sum))
    # departure zeroes the slot; the other tenant's history is untouched
    before = np.asarray(s2.sum)
    server.depart(t1)
    np.testing.assert_array_equal(np.asarray(server.tenant_stats(t2).sum), before)
    # the freed row admits clean
    t3 = server.admit("c")
    assert t3.group == t1.group
    assert float(server.tenant_stats(t3).count) == 0.0


def test_server_slo_suspension_gates_submits_but_drains():
    env = _env()
    policy = _policy(env)
    # occupancy is <= 1.0 by construction, so a floor of 1.5 trips on the
    # first dispatch — a deterministic per-tenant violation
    server = EvalServer(
        env, policy, slab_size=4, max_tenants=2,
        slo=[{"kind": "occupancy_floor", "threshold": 1.5}],
    )
    tenant = server.admit("hot")
    f1 = server.submit(tenant, _values(policy, 4, 1), key=jax.random.key(1))
    f2 = server.submit(tenant, _values(policy, 4, 2), key=jax.random.key(2))
    served = server.step()  # first slab: trips the tenant's watchdog
    assert served == 4 and tenant.suspended
    with pytest.raises(RuntimeError, match="suspended"):
        server.submit(tenant, _values(policy, 4, 3))
    # queued work still drains — suspension never deadlocks futures
    server.drain()
    assert f1.done() and f2.done()
    assert np.isfinite(np.asarray(f2.result().scores)).all()
    status = server.status()["tenants"]["hot"]
    assert status["suspended"] and status["slo_ok"] is False


def test_server_depart_cancel_errors_pending_futures():
    env = _env()
    policy = _policy(env)
    server = EvalServer(env, policy, slab_size=4, max_tenants=2)
    tenant = server.admit()
    future = server.submit(tenant, _values(policy, 4, 1))
    with pytest.raises(RuntimeError, match="pending work"):
        server.depart(tenant)
    server.depart(tenant, cancel=True)
    with pytest.raises(RuntimeError, match="cancelled"):
        future.result()
    # the row is free again
    assert server.admit("next") is not None


def test_server_full_and_bad_submit_shapes():
    env = _env()
    policy = _policy(env)
    server = EvalServer(env, policy, slab_size=4, max_tenants=1)
    tenant = server.admit()
    with pytest.raises(RuntimeError, match="full"):
        server.admit()
    with pytest.raises(ValueError, match="values must be"):
        server.submit(tenant, np.zeros((3, policy.parameter_count + 1), np.float32))
    with pytest.raises(ValueError, match="not admitted"):
        other = EvalServer(env, policy, slab_size=4).admit()
        server.submit(other, _values(policy, 2, 0))


# ----------------------------------------------------------- admission polices


class _FakeTenant:
    def __init__(self, group, oldest, telemetry=None):
        self.group = group
        self._oldest = oldest
        self.telemetry = telemetry

    def oldest_pending_dispatch(self):
        return self._oldest


class _FakeWaits:
    def __init__(self, starvation, p99):
        self._starvation = starvation
        self._p99 = p99

    def starvation_share(self):
        return self._starvation

    def queue_wait_quantile(self, q):
        return self._p99


def test_admission_fifo_orders_by_oldest_pending():
    a = _FakeTenant(1, oldest=7)
    b = _FakeTenant(2, oldest=3)
    c = _FakeTenant(3, oldest=7)
    assert FIFOAdmission().order([a, b, c], None) == [b, a, c]


def test_admission_starvation_prioritizes_starved_tenants():
    fresh = _FakeTenant(1, oldest=0)  # no telemetry yet: FIFO rank
    starved = _FakeTenant(2, oldest=5, telemetry=_FakeWaits(0.5, 64.0))
    healthy = _FakeTenant(3, oldest=1, telemetry=_FakeWaits(0.0, 2.0))
    order = StarvationAwareAdmission().order([fresh, healthy, starved], None)
    assert order[0] is starved
    # tail wait breaks the zero-starvation tie: healthy has histogrammed
    # waits, fresh has none
    assert order == [starved, healthy, fresh]
    # bias floats telemetry-less newcomers over clean incumbents
    biased = StarvationAwareAdmission(bias=1.0).order([healthy, fresh], None)
    assert biased[0] is fresh


# -------------------------------------------------------------- VecNE backend


def _vecne(**kw):
    return VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": True},
        seed=13,
        **kw,
    )


def _eval_scores(problem, values):
    batch = problem.generate_batch(len(values))
    batch.set_values(jnp.asarray(values))
    problem.evaluate(batch)
    return np.asarray(batch.evals[:, 0])


def _serving_server(max_tenants=2, slab=8, **kw):
    env = CartPole(continuous_actions=True)
    policy = FlatParamsPolicy(Linear(env.observation_size, env.action_size))
    return EvalServer(env, policy, slab_size=slab, max_tenants=max_tenants, **kw)


def test_remote_backend_bit_identical_to_standalone_vecne():
    """Acceptance: an unmodified VecNE through ``eval_backend=`` scores
    bit-identically to the same VecNE evaluating standalone."""
    server = _serving_server()
    rng = np.random.default_rng(0)
    ref = _vecne()
    values = rng.normal(size=(6, ref.solution_length)).astype(np.float32)
    expected = _eval_scores(ref, values)

    served = _vecne(eval_backend=RemoteEvalBackend(server, name="p1"))
    np.testing.assert_array_equal(_eval_scores(served, values), expected)
    assert served.eval_backend is not None
    assert server.dispatches >= 1

    # a second tenant on the SAME server — also bit-identical, and the
    # resident program keeps its identity (no second program)
    served2 = _vecne(eval_backend=server)  # coercion path: server -> backend
    values2 = rng.normal(size=(5, ref.solution_length)).astype(np.float32)
    expected2 = _eval_scores(_vecne(), values2)
    np.testing.assert_array_equal(_eval_scores(served2, values2), expected2)
    assert len(server.tenants) == 2


def test_remote_backend_rejects_contract_mismatch_and_groups():
    server = _serving_server()
    problem = _vecne(num_episodes=2)
    backend = RemoteEvalBackend(server, name="bad")
    rng = np.random.default_rng(1)
    values = jnp.asarray(rng.normal(size=(4, problem.solution_length)), jnp.float32)
    with pytest.raises(ValueError, match="num_episodes"):
        backend.evaluate(problem, values, jax.random.key(0))
    backend.close()
    with pytest.raises(ValueError, match="solution_groups"):
        _vecne(
            eval_backend=_serving_server(),
            solution_groups=np.zeros(4, dtype=np.int32),
        )
    with pytest.raises(TypeError, match="eval_backend"):
        _vecne(eval_backend=object())


def test_vecne_backend_churn_zero_steady_compiles():
    server = _serving_server(max_tenants=3)
    rng = np.random.default_rng(2)

    def fresh_problem(name):
        return _vecne(eval_backend=RemoteEvalBackend(server, name=name))

    p1 = fresh_problem("a")
    p2 = fresh_problem("b")
    n = 4
    # warm each problem TWICE (module docstring: the eager counter bump
    # compiles on the first two evaluations)
    for problem in (p1, p2):
        for _ in range(2):
            _eval_scores(problem, rng.normal(size=(n, p1.solution_length)).astype(np.float32))
    with track_compiles() as log:
        p2.eval_backend.close()
        p3 = fresh_problem("c")
        warm3 = rng.normal(size=(n, p1.solution_length)).astype(np.float32)
        _eval_scores(p3, warm3)  # new problem: its own counter warm-ups
        _eval_scores(p3, warm3)
    vecne_warmup = log.count  # p3's own eager counter compiles, if any
    with track_compiles() as log:
        _eval_scores(p1, rng.normal(size=(n, p1.solution_length)).astype(np.float32))
        _eval_scores(p3, rng.normal(size=(n, p1.solution_length)).astype(np.float32))
    assert log.count == 0, (
        f"backend churn retraced: {log.names} (warmup had {vecne_warmup})"
    )


# ------------------------------------------------------------------ stdio front


def test_stdio_protocol_roundtrip():
    server = _serving_server(slab=4)
    params = server.policy.parameter_count
    lines = [
        {"op": "admit", "tenant": "cli"},
        {
            "op": "submit", "tenant": "cli", "id": "s1",
            "values": [[0.0] * params for _ in range(3)], "seed": 5,
        },
        {"op": "poll", "request_id": 0},
        {"op": "result", "request_id": 0},
        {"op": "status"},
        {"op": "nope"},
        {"op": "depart", "tenant": "cli"},
        {"op": "shutdown"},
        {"op": "never-reached"},
    ]
    infile = io.StringIO("\n".join(json.dumps(l) for l in lines) + "\n")
    outfile = io.StringIO()
    handled = serve_stdio(server, infile, outfile)
    out = [json.loads(l) for l in outfile.getvalue().splitlines()]
    assert handled == 8  # shutdown consumed, trailing line never read
    admit, submit, poll, result, status, bogus, depart, shutdown = out
    assert admit == {"ok": True, "op": "admit", "tenant": "cli", "group": 1}
    assert submit["ok"] and submit["request_id"] == 0 and submit["id"] == "s1"
    assert poll["done"] is False  # nothing served yet
    assert result["ok"] and len(result["scores"]) == 3
    assert result["env_steps"] > 0 and "queue_wait_p99" in result
    assert status["ok"] and status["tenants"]["cli"]["requests_served"] == 1
    assert bogus["ok"] is False and "unknown op" in bogus["error"]
    assert depart["ok"] and shutdown == {"ok": True, "op": "shutdown"}


def test_stdio_errors_do_not_kill_the_server():
    server = _serving_server(slab=4)
    infile = io.StringIO(
        "not json\n"
        + json.dumps({"op": "submit", "tenant": "ghost", "values": [[0.0]]})
        + "\n"
        + json.dumps({"op": "admit", "tenant": "ok"})
        + "\n"
    )
    outfile = io.StringIO()
    serve_stdio(server, infile, outfile)
    out = [json.loads(l) for l in outfile.getvalue().splitlines()]
    assert out[0]["ok"] is False and out[1]["ok"] is False
    assert out[2]["ok"] is True and out[2]["tenant"] == "ok"


# ----------------------------------------------------------------- SLO plumbing


def test_check_bench_max_queue_wait_flag(tmp_path):
    from evotorch_tpu.observability.slo import _main, check_bench_line

    line = {
        "queue_wait_p99": 2.0,
        "serve_queue_wait_p99": 70.0,
        "modes": {"episodes_refill": {"queue_wait_p99": 8.0}},
    }
    assert check_bench_line(line, max_queue_wait_p99=100.0).ok
    report = check_bench_line(line, max_queue_wait_p99=10.0)
    assert not report.ok
    assert any("serve_queue_wait_p99" in v for v in report.violations)
    # the flag threads through the CLI; a line with NONE of the checked
    # keys exits 2 ("insufficient"), not 1
    log = tmp_path / "bench.log"
    log.write_text(json.dumps(line) + "\n")
    assert _main(["--check-bench", str(log), "--max-queue-wait-p99", "1"]) == 1
    assert _main(["--check-bench", str(log), "--max-queue-wait-p99", "1000"]) == 0
    log.write_text(json.dumps({"unrelated": 1}) + "\n")
    assert _main(["--check-bench", str(log), "--max-queue-wait-p99", "1"]) == 2


def test_tuned_cache_writes_are_atomic(tmp_path):
    from evotorch_tpu.observability.timings import (
        TunedEntry,
        lookup_tuned,
        save_tuned_entry,
    )
    from evotorch_tpu.resilience import faults

    cache = tmp_path / "tuned_configs.json"
    machine = {"host": "testbox"}
    entry = TunedEntry(
        group="refill",
        shape={"env": "cartpole", "popsize": 8},
        machine=machine,
        config={"refill_width": 4},
    )
    # one injected write fault: the retry site must absorb it and the final
    # file must be whole (tmp-file + fsync + rename; no partial JSON)
    faults.configure("timings.write:raise@1")
    try:
        save_tuned_entry(entry, path=cache)
    finally:
        faults.configure(None)
    assert json.loads(cache.read_text())  # whole, parseable
    assert not list(tmp_path.glob("*.tmp.*")), "tmp residue left behind"
    loaded = lookup_tuned("refill", entry.shape, machine=machine, path=cache)
    assert loaded is not None and loaded.config == {"refill_width": 4}


# ------------------------------------------------------------------- slow soak


@pytest.mark.slow
def test_server_soak_random_churn_stays_resident():
    """30 rounds of random admit/depart/submit against one server: every
    future completes, occupancy accounting stays consistent, and after the
    warm rounds the resident program never recompiles."""
    env = _env()
    policy = _policy(env)
    server = EvalServer(
        env, policy, slab_size=8, max_tenants=3, admission="starvation"
    )
    rng = np.random.default_rng(0)
    tenants = [server.admit(f"t{i}") for i in range(3)]
    seeds = iter(range(1, 1000))

    def submit_random(tenant):
        n = int(rng.integers(1, 7))
        seed = next(seeds)
        return server.submit(tenant, _values(policy, n, seed), key=jax.random.key(seed))

    # warm rounds
    for _ in range(2):
        futures = [submit_random(t) for t in tenants]
        server.drain()
        assert all(f.done() for f in futures)

    with track_compiles() as log:
        for round_idx in range(30):
            action = rng.integers(0, 4)
            if action == 0 and len(tenants) > 1:
                victim = tenants.pop(int(rng.integers(0, len(tenants))))
                server.depart(victim, cancel=True)
            elif action == 1 and len(tenants) < 3:
                tenants.append(server.admit(f"r{round_idx}"))
            futures = [submit_random(t) for t in tenants]
            server.drain()
            assert all(f.done() for f in futures)
    assert log.count == 0, f"soak churn retraced: {log.names}"
    assert 0.0 < server.occupancy() <= 1.0
    assert server.items_served <= server.dispatches * server.slab_size
