import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.distributions import (
    ExpGaussian,
    ExpSeparableGaussian,
    SeparableGaussian,
    SymmetricSeparableGaussian,
    make_functional_grad_estimator,
    make_functional_sampler,
)


def test_separable_sample_stats():
    d = SeparableGaussian({"mu": jnp.array([1.0, -2.0]), "sigma": jnp.array([0.5, 2.0])})
    s = d.sample(20000, key=jax.random.key(0))
    assert s.shape == (20000, 2)
    assert np.allclose(np.asarray(jnp.mean(s, axis=0)), [1.0, -2.0], atol=0.05)
    assert np.allclose(np.asarray(jnp.std(s, axis=0)), [0.5, 2.0], atol=0.05)


def test_separable_gradients_direction():
    # fitness = x[0]: gradient of mu[0] should be positive when maximizing
    mu = jnp.zeros(3)
    sigma = jnp.ones(3)
    d = SeparableGaussian({"mu": mu, "sigma": sigma})
    samples = d.sample(4000, key=jax.random.key(1))
    fit = samples[:, 0]
    grads = d.compute_gradients(samples, fit, objective_sense="max", ranking_method="centered")
    assert float(grads["mu"][0]) > 10.0 * abs(float(grads["mu"][1]))
    # minimizing flips the sign
    grads_min = d.compute_gradients(samples, fit, objective_sense="min", ranking_method="centered")
    assert float(grads_min["mu"][0]) < 0


def test_separable_update_with_learning_rates():
    d = SeparableGaussian({"mu": jnp.zeros(2), "sigma": jnp.ones(2)})
    new = d.update_parameters(
        {"mu": jnp.array([1.0, 0.0]), "sigma": jnp.array([0.0, -0.5])},
        learning_rates={"mu": 0.1, "sigma": 0.2},
    )
    assert np.allclose(np.asarray(new.mu), [0.1, 0.0])
    assert np.allclose(np.asarray(new.sigma), [1.0, 0.9])


def test_symmetric_sampling_antithetic():
    d = SymmetricSeparableGaussian({"mu": jnp.array([5.0, 5.0]), "sigma": jnp.ones(2)})
    s = d.sample(10, key=jax.random.key(0))
    # interleaved pairs: s[0] + s[1] == 2*mu
    assert np.allclose(np.asarray(s[0::2] + s[1::2]), 10.0, atol=1e-5)
    with pytest.raises(ValueError):
        d.sample(5, key=jax.random.key(0))


def test_symmetric_gradients_solve_simple_quadratic():
    # maximize -|x - 3|^2 via symmetric PGPE-style updates
    d = SymmetricSeparableGaussian({"mu": jnp.zeros(4), "sigma": jnp.full((4,), 1.0)})
    key = jax.random.key(42)
    for _ in range(40):
        key, sub = jax.random.split(key)
        samples = d.sample(100, key=sub)
        fit = -jnp.sum((samples - 3.0) ** 2, axis=-1)
        grads = d.compute_gradients(samples, fit, objective_sense="max", ranking_method="centered")
        d = d.update_parameters(grads, learning_rates={"mu": 0.3, "sigma": 0.05})
    assert np.allclose(np.asarray(d.mu), 3.0, atol=0.5)


def test_exp_separable_snes_update():
    d = ExpSeparableGaussian({"mu": jnp.zeros(2), "sigma": jnp.ones(2)})
    new = d.update_parameters(
        {"mu": jnp.array([0.5, 0.0]), "sigma": jnp.array([1.0, -1.0])},
        learning_rates={"mu": 1.0, "sigma": 0.2},
    )
    assert np.allclose(np.asarray(new.mu), [0.5, 0.0])
    # sigma multiplied by exp(0.5 * lr * grad)
    assert np.allclose(np.asarray(new.sigma), [np.exp(0.1), np.exp(-0.1)], atol=1e-6)


def test_expgaussian_roundtrip_and_update():
    A = jnp.array([[2.0, 0.0], [0.5, 1.0]])
    d = ExpGaussian({"mu": jnp.array([1.0, 2.0]), "sigma": A})
    z = jax.random.normal(jax.random.key(0), (7, 2))
    x = d.to_global_coordinates(z)
    z2 = d.to_local_coordinates(x)
    assert np.allclose(np.asarray(z), np.asarray(z2), atol=1e-4)

    samples = d.sample(3000, key=jax.random.key(1))
    fit = samples[:, 0]
    grads = d.compute_gradients(samples, fit, objective_sense="max", ranking_method="centered")
    assert set(grads) == {"d", "M"}
    new = d.update_parameters(grads, learning_rates={"mu": 0.1, "sigma": 0.01})
    # A_inv stays the inverse of A after the expm update (float32 tolerance)
    assert np.allclose(np.asarray(new.A @ new.A_inv), np.eye(2), atol=2e-2)
    assert float(new.mu[0]) > float(d.mu[0])


def test_functional_sampler_batched():
    sampler = make_functional_sampler(SeparableGaussian)
    mu = jnp.stack([jnp.zeros(3), jnp.full((3,), 10.0)])  # batch of 2 searches
    sigma = jnp.ones(3)
    out = sampler(jax.random.key(0), 50, {"mu": mu, "sigma": sigma})
    assert out.shape == (2, 50, 3)
    assert abs(float(jnp.mean(out[0]))) < 0.5
    assert abs(float(jnp.mean(out[1])) - 10.0) < 0.5
    # batches get different noise
    assert not np.allclose(np.asarray(out[0]), np.asarray(out[1]) - 10.0)


def test_functional_grad_estimator_batched():
    est = make_functional_grad_estimator(
        SeparableGaussian, objective_sense="max", ranking_method="centered"
    )
    key = jax.random.key(0)
    mu = jnp.zeros((2, 3))
    sigma = jnp.ones(3)
    sampler = make_functional_sampler(SeparableGaussian)
    samples = sampler(key, 200, {"mu": mu, "sigma": sigma})
    fits = samples[..., 0]
    grads = est(samples, fits, {"mu": mu, "sigma": sigma})
    assert grads["mu"].shape == (2, 3)
    assert float(grads["mu"][0, 0]) > 0 and float(grads["mu"][1, 0]) > 0


def test_bound_function_grad_estimator():
    est = make_functional_grad_estimator(
        SymmetricSeparableGaussian,
        function=lambda xs: -jnp.sum(xs**2, axis=-1),
        objective_sense="max",
        ranking_method="centered",
        return_samples=True,
        return_fitnesses=True,
    )
    grads, samples, fits = est(
        jax.random.key(3), 100, {"mu": jnp.full((4,), 5.0), "sigma": jnp.ones(4)}
    )
    assert samples.shape == (100, 4)
    assert fits.shape == (100,)
    # maximizing -x^2 from mu=5: gradient pulls mu down
    assert all(float(g) < 0 for g in grads["mu"])


def test_unknown_parameter_rejected():
    with pytest.raises(ValueError):
        SeparableGaussian({"mu": jnp.zeros(2), "sigma": jnp.ones(2), "bogus": 1})


def test_kl_divergence():
    a = SeparableGaussian({"mu": jnp.zeros(2), "sigma": jnp.ones(2)})
    b = SeparableGaussian({"mu": jnp.zeros(2), "sigma": jnp.ones(2)})
    assert a.relative_entropy(b) == pytest.approx(0.0, abs=1e-6)
    c = SeparableGaussian({"mu": jnp.ones(2), "sigma": jnp.ones(2)})
    assert a.relative_entropy(c) > 0
