import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import log_barrier, penalty, violation


def test_violation_scalar():
    assert float(violation(3.0, "<=", 5.0)) == 0.0
    assert float(violation(7.0, "<=", 5.0)) == pytest.approx(2.0)
    assert float(violation(3.0, ">=", 5.0)) == pytest.approx(2.0)
    assert float(violation(7.0, ">=", 5.0)) == 0.0


def test_violation_batched():
    lhs = jnp.array([1.0, 6.0, 10.0])
    out = violation(lhs, "<=", 5.0)
    assert np.allclose(np.asarray(out), [0.0, 1.0, 5.0])


def test_log_barrier():
    inside = float(log_barrier(0.0, "<=", 10.0, sharpness=1.0))
    near = float(log_barrier(9.99, "<=", 10.0, sharpness=1.0))
    assert near < inside <= 0.0
    crossed = float(log_barrier(11.0, "<=", 10.0, sharpness=1.0))
    assert crossed == -np.inf


def test_penalty_signs():
    p = float(penalty(7.0, "<=", 5.0, penalty_sign="-", linear=2.0))
    assert p == pytest.approx(-4.0)
    p = float(penalty(7.0, "<=", 5.0, penalty_sign="+", linear=2.0, step=1.0))
    assert p == pytest.approx(5.0)
    assert float(penalty(3.0, "<=", 5.0, penalty_sign="-", linear=2.0, step=9.0)) == 0.0
    with pytest.raises(ValueError):
        penalty(1.0, "<=", 2.0, penalty_sign="x")
    with pytest.raises(ValueError):
        violation(1.0, "~=", 2.0)


def test_equality_constraint():
    assert float(violation(1.5, "==", 1.0)) == pytest.approx(0.5)
    assert float(violation(1.0, "==", 1.0)) == 0.0
    assert float(penalty(1.5, "==", 1.0, penalty_sign="-", linear=2.0)) == pytest.approx(-1.0)
    with pytest.raises(ValueError):
        log_barrier(1.0, "==", 2.0)
