"""GSPMD named-sharding rewrite (docs/sharding.md): bit-identity, padding,
the one-program donated generation step, mesh-scoped tuned-cache keys, and
the persistent compile cache.

The load-bearing claim of the rewrite is that the mesh is an EXECUTION
DETAIL: the global program is the single-device program, so sharded scores
and counters are bit-identical to unsharded at any mesh shape, and popsizes
that don't divide the device grid are padded + masked without touching the
numbers. These tests pin that contract on the pytest 8-virtual-device CPU
mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.neuroevolution.net.vecrl import (
    run_vectorized_rollout,
    run_vectorized_rollout_compacting_sharded,
)
from evotorch_tpu.parallel import (
    make_generation_step,
    make_mesh,
    make_sharded_rollout_evaluator,
    mesh_label,
    parse_mesh_shape,
)
from evotorch_tpu.observability import EvalTelemetry, GroupTelemetry
from evotorch_tpu.observability.devicemetrics import GROUP_TELEMETRY_WIDTH


@pytest.fixture(scope="module")
def cartpole_setup():
    env = CartPole()
    policy = FlatParamsPolicy(
        Linear(env.observation_size, 4) >> Tanh() >> Linear(4, env.action_size)
    )
    stats = RunningNorm(env.observation_size).stats
    return env, policy, stats


def _population(policy, popsize, seed=0):
    return 0.1 * jax.random.normal(
        jax.random.key(seed), (popsize, policy.parameter_count)
    )


# ---------------------------------------------------------------------------
# mesh helpers
# ---------------------------------------------------------------------------


def test_parse_mesh_shape_forms():
    assert parse_mesh_shape("8") == {"pop": 8}
    assert parse_mesh_shape(8) == {"pop": 8}
    assert parse_mesh_shape("4x2") == {"pop": 4, "model": 2}
    assert parse_mesh_shape("pop=4,model=2") == {"pop": 4, "model": 2}
    with pytest.raises(ValueError):
        parse_mesh_shape("2x2x2")  # more axes than MESH_AXES names


def test_mesh_label_canonical_forms():
    assert mesh_label(None) == "none"
    assert mesh_label(make_mesh({"pop": 8})) == "pop8"
    assert mesh_label(make_mesh({"pop": 4, "model": 2})) == "pop4.model2"
    # size-1 axes drop: an (8, 1) mesh lays out like the 1-D 8-mesh
    assert mesh_label(make_mesh({"pop": 8, "model": 1})) == "pop8"
    assert mesh_label(make_mesh({"pop": 1, "model": 1})) == "none"


# ---------------------------------------------------------------------------
# bit-identity: the global program IS the single-device program
# ---------------------------------------------------------------------------

# explicit refill knobs so the sharded and unsharded runs cannot diverge
# through the tuned-config cache (override provenance on both sides)
_MODE_KWARGS = {
    "budget": {},
    "episodes": {},
    "episodes_refill": {"refill_width": 4, "refill_period": 1},
}


@pytest.mark.parametrize("eval_mode", sorted(_MODE_KWARGS))
def test_gspmd_bit_identity_2d_mesh(cartpole_setup, eval_mode):
    env, policy, stats = cartpole_setup
    values = _population(policy, 16)
    key = jax.random.key(3)
    kwargs = dict(
        num_episodes=1, episode_length=8, eval_mode=eval_mode,
        **_MODE_KWARGS[eval_mode],
    )

    ref = run_vectorized_rollout(env, policy, values, key, stats, **kwargs)
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 4, "model": 2}), **kwargs
    )
    result, per_shard = ev(values, key, stats)

    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    assert int(result.total_steps) == int(ref.total_steps)
    assert int(result.total_episodes) == int(ref.total_episodes)
    # GSPMD has no per-shard accounting: the 1-element form carries the total
    assert np.asarray(per_shard).shape == (1,)
    assert int(np.asarray(per_shard)[0]) == int(ref.total_steps)


def test_compacting_sharded_bit_identity_2d_mesh(cartpole_setup):
    # the fourth contract: host-chunked lane compaction, sharded over the
    # pop axis of the same 2-D mesh (the model axis replicates)
    env, policy, stats = cartpole_setup
    values = _population(policy, 16)
    key = jax.random.key(3)
    ref = run_vectorized_rollout(
        env, policy, values, key, stats,
        num_episodes=1, episode_length=8, eval_mode="episodes",
    )
    result = run_vectorized_rollout_compacting_sharded(
        env, policy, values, key, stats,
        mesh=make_mesh({"pop": 4, "model": 2}),
        num_episodes=1, episode_length=8, chunk_size=4,
    )
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    assert int(result.total_episodes) == int(ref.total_episodes)


# ---------------------------------------------------------------------------
# padding: popsizes that don't divide the mesh
# ---------------------------------------------------------------------------


def test_gspmd_popsize_1000_on_8_device_mesh(cartpole_setup):
    env, policy, stats = cartpole_setup
    values = _population(policy, 1000, seed=5)
    key = jax.random.key(7)
    kwargs = dict(num_episodes=1, episode_length=2, eval_mode="budget")

    ref = run_vectorized_rollout(env, policy, values, key, stats, **kwargs)
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 8}), **kwargs
    )
    result, _ = ev(values, key, stats)
    assert result.scores.shape == (1000,)
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    assert int(result.total_steps) == int(ref.total_steps) == 1000 * 2

    # the same 1000 lanes on a 3-device mesh (1000 % 3 != 0): padded to
    # 1002, sliced back, numbers untouched — what used to be an error
    ev3 = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 3}), **kwargs
    )
    result3, _ = ev3(values, key, stats)
    assert result3.scores.shape == (1000,)
    np.testing.assert_array_equal(np.asarray(result3.scores), np.asarray(ref.scores))
    assert int(result3.total_steps) == 1000 * 2


def test_gspmd_padding_masks_counters_and_telemetry(cartpole_setup):
    # 13 lanes on the 8-device grid: padded to 16, the 3 synthetic lanes
    # must contribute NOTHING to scores, counters, or the genuine telemetry
    # slots (capacity/lane_width count PHYSICAL lanes by design — padding
    # is idle capacity you pay for; docs/sharding.md)
    env, policy, stats = cartpole_setup
    values = _population(policy, 13, seed=11)
    key = jax.random.key(13)
    kwargs = dict(num_episodes=1, episode_length=4, eval_mode="budget")

    ref = run_vectorized_rollout(env, policy, values, key, stats, **kwargs)
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 8}), **kwargs
    )
    result, _ = ev(values, key, stats)
    assert result.scores.shape == (13,)
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    assert int(result.total_steps) == 13 * 4
    assert int(result.total_episodes) == int(ref.total_episodes)
    telem = EvalTelemetry.from_array(result.telemetry)
    assert telem.env_steps == 13 * 4  # genuine work only
    assert telem.lane_width == 16  # physical (padded) lanes


# ---------------------------------------------------------------------------
# per-group telemetry: the (G, 14) matrix is mesh-invariant
# ---------------------------------------------------------------------------


def test_gspmd_per_group_matrix_bit_identical_across_meshes(cartpole_setup):
    # the per-group matrix is part of the GLOBAL program's output, so it
    # must be BIT-identical unsharded vs 1-D vs 2-D pop x model — including
    # the queue-wait histogram block (refill is the contract that fills it)
    env, policy, stats = cartpole_setup
    values = _population(policy, 16)
    key = jax.random.key(3)
    groups = np.arange(16, dtype=np.int32) % 2
    kwargs = dict(
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
        refill_width=8, refill_period=1, groups=groups, num_groups=2,
    )
    ref = run_vectorized_rollout(env, policy, values, key, stats, **kwargs)
    tref = GroupTelemetry.from_array(ref.telemetry)
    assert tref.data.shape == (2, GROUP_TELEMETRY_WIDTH)
    for mesh_shape in ({"pop": 8}, {"pop": 4, "model": 2}):
        ev = make_sharded_rollout_evaluator(
            env, policy, mesh=make_mesh(mesh_shape), **kwargs
        )
        result, _ = ev(values, key, stats)
        np.testing.assert_array_equal(
            np.asarray(result.scores), np.asarray(ref.scores)
        )
        t = GroupTelemetry.from_array(result.telemetry)
        np.testing.assert_array_equal(t.data, tref.data)


def test_gspmd_per_group_padding_masks_popsize_1000(cartpole_setup):
    # 1000 lanes on the 3-device mesh (1000 % 3 != 0 -> padded to 1002
    # physical lanes): the pad lanes never activate, so the per-group
    # env-step/episode columns match unsharded exactly; capacity/lane_width
    # count physical lanes (the pads charge group 0, the row they were
    # copied from)
    env, policy, stats = cartpole_setup
    values = _population(policy, 1000, seed=5)
    key = jax.random.key(7)
    groups = np.arange(1000, dtype=np.int32) % 2
    kwargs = dict(
        num_episodes=1, episode_length=2, eval_mode="episodes",
        groups=groups, num_groups=2,
    )
    ref = run_vectorized_rollout(env, policy, values, key, stats, **kwargs)
    tref = GroupTelemetry.from_array(ref.telemetry)
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 3}), **kwargs
    )
    result, _ = ev(values, key, stats)
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    t = GroupTelemetry.from_array(result.telemetry)
    np.testing.assert_array_equal(t.data[:, 0], tref.data[:, 0])  # env_steps
    np.testing.assert_array_equal(t.data[:, 1], tref.data[:, 1])  # episodes
    assert int(t.data[:, 3].sum()) == 1002  # physical (padded) lanes


def test_shard_map_per_group_psum_additivity(cartpole_setup):
    # legacy explicit path: each shard segment-sums its own partial matrix,
    # psum makes it mesh-global — the G=2 matrix must column-sum to the same
    # path's G=1 globals and the histogram must count every refill
    env, policy, stats = cartpole_setup
    values = _population(policy, 16)
    key = jax.random.key(3)
    groups = np.arange(16, dtype=np.int32) % 2
    kwargs = dict(
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
        refill_width=8, refill_period=1, use_shard_map=True,
    )
    ev1 = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 8}), **kwargs
    )
    res1, _ = ev1(values, key, stats)
    ev2 = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 8}), groups=groups, num_groups=2,
        **kwargs,
    )
    res2, _ = ev2(values, key, stats)
    np.testing.assert_array_equal(np.asarray(res1.scores), np.asarray(res2.scores))
    t1 = GroupTelemetry.from_array(res1.telemetry)
    t2 = GroupTelemetry.from_array(res2.telemetry)
    assert t2.data.shape == (2, GROUP_TELEMETRY_WIDTH)
    s1, s2 = t1.total(), t2.total()
    for field in (
        "env_steps", "episodes", "capacity", "lane_width",
        "refill_events", "queue_wait",
    ):
        assert getattr(s1, field) == getattr(s2, field), field
    assert int(t2.hist.sum()) == s2.refill_events


def test_compacting_sharded_per_group_counts(cartpole_setup):
    env, policy, stats = cartpole_setup
    values = _population(policy, 16)
    key = jax.random.key(3)
    groups = np.arange(16, dtype=np.int32) % 2
    ref = run_vectorized_rollout_compacting_sharded(
        env, policy, values, key, stats, mesh=make_mesh({"pop": 8}),
        num_episodes=1, episode_length=8, chunk_size=4,
    )
    result = run_vectorized_rollout_compacting_sharded(
        env, policy, values, key, stats, mesh=make_mesh({"pop": 8}),
        num_episodes=1, episode_length=8, chunk_size=4,
        groups=groups, num_groups=2,
    )
    np.testing.assert_array_equal(np.asarray(result.scores), np.asarray(ref.scores))
    t = GroupTelemetry.from_array(result.telemetry)
    assert t.data.shape == (2, GROUP_TELEMETRY_WIDTH)
    tref = GroupTelemetry.from_array(ref.telemetry)
    s, sref = t.total(), tref.total()
    for field in ("env_steps", "episodes", "capacity", "lane_width"):
        assert getattr(s, field) == getattr(sref, field), field


# ---------------------------------------------------------------------------
# the one-program donated generation step
# ---------------------------------------------------------------------------


def test_generation_step_runs_and_donates(cartpole_setup):
    from evotorch_tpu.algorithms.functional import pgpe, pgpe_ask, pgpe_tell
    from evotorch_tpu.observability import ledger
    from evotorch_tpu.observability.programs import abstract_like

    env, policy, stats = cartpole_setup
    popsize = 8

    def ask(k, s):
        return pgpe_ask(k, s, popsize=popsize)

    generation = make_generation_step(
        env, policy, ask=ask, tell=pgpe_tell, popsize=popsize,
        mesh=make_mesh({"pop": 4, "model": 2}),
        num_episodes=1, episode_length=4, eval_mode="budget",
    )
    state = pgpe(
        center_init=jnp.zeros(policy.parameter_count),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )

    donated = state
    state, scores, stats_out, total_steps, _telem = generation(
        state, jax.random.key(0), stats
    )
    assert scores.shape == (popsize,)
    assert int(total_steps) == popsize * 4
    # runtime ground truth: jax deletes exactly the donated inputs whose
    # aliasing the executable consumed
    assert donated.stdev.is_deleted()

    # second generation (the committed-layout fixed point) still runs, and
    # donates the first generation's output state in turn
    state2, scores2, _, _, _ = generation(state, jax.random.key(1), stats_out)
    assert scores2.shape == (popsize,)
    assert state.stdev.is_deleted()

    # the ledger's AOT donation verification agrees: every donated
    # parameter is aliased in the compiled module
    record = ledger.capture(
        "test.gspmd.generation",
        generation,
        abstract_like(state2),
        jax.random.key(2),
        abstract_like(stats),
        shape={"popsize": popsize, "mesh": "pop4.model2"},
    )
    assert record.donation is not None
    assert record.donation.missing == ()


# ---------------------------------------------------------------------------
# mesh-scoped tuned-config cache keys (schema v2, backward-compatible read)
# ---------------------------------------------------------------------------


def test_tuned_cache_mesh_scoping_and_legacy_read(tmp_path, monkeypatch):
    import json

    from evotorch_tpu.observability.timings import (
        TunedEntry,
        load_tuned_cache,
        lookup_tuned,
        machine_fingerprint,
        save_tuned_entry,
    )

    path = tmp_path / "tuned.json"
    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(path))
    machine = machine_fingerprint()
    base = {"env": "cartpole", "popsize": 8, "episode_length": 8,
            "num_episodes": 1, "params": 10, "dtype": "float32"}

    # a version-1 (pre-mesh) entry, as an already-checked-in cache holds
    legacy = TunedEntry(group="refill", shape=dict(base), machine=machine,
                        config={"width": 4}, evidence={})
    save_tuned_entry(legacy)
    # unsharded consumers (mesh "none") keep hitting it via the fallback
    hit = lookup_tuned("refill", dict(base, mesh="none"))
    assert hit is not None and hit.config["width"] == 4
    # sharded lookups NEVER inherit a mesh-less entry
    assert lookup_tuned("refill", dict(base, mesh="pop8")) is None

    # a mesh-scoped entry serves exactly its own label
    sharded = TunedEntry(group="refill", shape=dict(base, mesh="pop8"),
                         machine=machine, config={"width": 8}, evidence={})
    save_tuned_entry(sharded)
    assert lookup_tuned("refill", dict(base, mesh="pop8")).config["width"] == 8
    assert lookup_tuned("refill", dict(base, mesh="pop4.model2")) is None
    # the "none" lookup still resolves to the legacy entry, not the sharded
    assert lookup_tuned("refill", dict(base, mesh="none")).config["width"] == 4

    # the save path stamps schema version 2
    with open(path) as f:
        payload = json.load(f)
    assert payload["version"] == 2
    assert len(load_tuned_cache(path)) == 2


# ---------------------------------------------------------------------------
# graftlint: MESH_AXES is the canonical axis registry
# ---------------------------------------------------------------------------


def test_graftlint_collects_mesh_axes_declaration():
    from evotorch_tpu.analysis.graftlint import lint_sources

    src_ok = (
        'import jax\n'
        'MESH_AXES = ("pop", "model")\n'
        'def f(x):\n'
        '    return jax.lax.psum(x, "model")\n'
    )
    findings = [f for f in lint_sources({"mod.py": src_ok}) if f.checker == "axis-name"]
    assert findings == []

    # an axis OUTSIDE the declaration fires (the checker needs at least one
    # declaration to know the project's vocabulary)
    src_bad = (
        'import jax\n'
        'MESH_AXES = ("pop", "model")\n'
        'def f(x):\n'
        '    return jax.lax.psum(x, "modell")\n'
    )
    findings = [f for f in lint_sources({"mod.py": src_bad}) if f.checker == "axis-name"]
    assert findings


# ---------------------------------------------------------------------------
# persistent compile cache: warm processes deserialize instead of compiling
# ---------------------------------------------------------------------------

_CACHE_WORKER = """
import os, sys, json, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")

from evotorch_tpu.observability import cache_stats, enable_persistent_cache
enable_persistent_cache(sys.argv[1])

from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.observability import ledger
from evotorch_tpu.observability.programs import abstract_like
from evotorch_tpu.parallel import make_mesh, make_sharded_rollout_evaluator

env = CartPole()
policy = FlatParamsPolicy(
    Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
)
stats = RunningNorm(env.observation_size).stats
ev = make_sharded_rollout_evaluator(
    env, policy, mesh=make_mesh({"pop": 4, "model": 2}),
    num_episodes=1, episode_length=16, eval_mode="budget",
)
record = ledger.capture(
    "cache_probe",
    ev.program_builder(False, 64),
    abstract_like(jax.numpy.zeros((64, policy.parameter_count))),
    jax.random.key(0),
    abstract_like(stats),
)
print("CACHE", json.dumps({
    "compile_seconds": record.compile_seconds, **cache_stats()
}))
"""


@pytest.mark.slow
def test_persistent_compile_cache_warm_process(tmp_path):
    # the acceptance criterion: a second process's compile_seconds for the
    # same program is < 25% of the first's (deserialization, not XLA)
    import json
    import os
    import subprocess
    import sys

    worker = tmp_path / "cache_worker.py"
    worker.write_text(_CACHE_WORKER)
    cache_dir = tmp_path / "compile_cache"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")

    def run():
        out = subprocess.run(
            [sys.executable, str(worker), str(cache_dir)],
            env=env, capture_output=True, text=True, timeout=240,
        )
        assert out.returncode == 0, f"worker failed:\n{out.stdout}\n{out.stderr}"
        for line in out.stdout.splitlines():
            if line.startswith("CACHE "):
                return json.loads(line[len("CACHE "):])
        raise AssertionError(f"no CACHE line in:\n{out.stdout}")

    cold = run()
    warm = run()
    assert cold["enabled"] and warm["enabled"]
    assert cold["hits"] == 0 and cold["misses"] > 0
    assert warm["misses"] == 0 and warm["hits"] > 0
    assert warm["compile_seconds"] < 0.25 * cold["compile_seconds"], (cold, warm)
