import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.operators import functional as F
from evotorch_tpu.tools import ObjectArray


# ---------------------------------------------------------------- pareto ----


def brute_force_dominates(e1, e2, senses):
    adj = lambda v, s: v if s == "max" else -v  # noqa: E731
    a1 = [adj(x, s) for x, s in zip(e1, senses)]
    a2 = [adj(x, s) for x, s in zip(e2, senses)]
    return all(x >= y for x, y in zip(a1, a2)) and any(x > y for x, y in zip(a1, a2))


def brute_force_ranks(evals, senses):
    n = len(evals)
    remaining = set(range(n))
    ranks = [None] * n
    k = 0
    while remaining:
        front = [
            i
            for i in remaining
            if not any(brute_force_dominates(evals[j], evals[i], senses) for j in remaining if j != i)
        ]
        for i in front:
            ranks[i] = k
        remaining -= set(front)
        k += 1
    return ranks


def test_pareto_ranks_against_brute_force():
    key = jax.random.key(0)
    evals = jax.random.normal(key, (40, 3))
    senses = ["max", "min", "max"]
    got = np.asarray(F.pareto_ranks(evals, objective_sense=senses))
    expected = brute_force_ranks(np.asarray(evals).tolist(), senses)
    assert got.tolist() == expected


def test_dominates_and_matrix():
    senses = ["max", "max"]
    assert bool(F.dominates(jnp.array([2.0, 2.0]), jnp.array([1.0, 1.0]), objective_sense=senses))
    assert not bool(F.dominates(jnp.array([2.0, 0.0]), jnp.array([1.0, 1.0]), objective_sense=senses))
    evals = jnp.array([[2.0, 2.0], [1.0, 1.0], [3.0, 0.0]])
    m = np.asarray(F.domination_matrix(evals, objective_sense=senses))
    assert m[0, 1] and not m[1, 0] and not m[0, 2] and not m[2, 0]
    counts = np.asarray(F.domination_counts(evals, objective_sense=senses))
    assert counts.tolist() == [0, 1, 0]


def test_crowding_boundaries_infinite():
    evals = jnp.array([[0.0, 3.0], [1.0, 2.0], [2.0, 1.0], [3.0, 0.0]])
    senses = ["max", "max"]
    d = np.asarray(F.crowding_distances(evals, objective_sense=senses))
    # all on one front; extremes get inf
    assert np.isinf(d[0]) and np.isinf(d[3])
    assert np.isfinite(d[1]) and np.isfinite(d[2])


def test_pareto_utility_ordering():
    # solution 0 dominates all; 1 and 2 are a front; 3 is dominated by all
    evals = jnp.array([[5.0, 5.0], [3.0, 4.0], [4.0, 3.0], [1.0, 1.0]])
    u = np.asarray(F.pareto_utility(evals, objective_sense=["max", "max"]))
    assert u[0] > u[1] and u[0] > u[2] and min(u[1], u[2]) > u[3]


# ------------------------------------------------------------ tournament ----


def test_tournament_indices_and_quality():
    key = jax.random.key(1)
    values = jnp.arange(20.0)[:, None] * jnp.ones((1, 3))
    evals = jnp.arange(20.0)  # higher index = higher fitness
    idx = F.tournament(
        key, values, evals,
        num_tournaments=100, tournament_size=4,
        objective_sense="max", return_indices=True,
    )
    assert idx.shape == (100,)
    # tournament selection should favor good solutions strongly
    assert float(jnp.mean(evals[idx])) > float(jnp.mean(evals))
    p1, p2 = F.tournament(
        key, values, evals,
        num_tournaments=10, tournament_size=4,
        objective_sense="max", split_results=True,
    )
    assert p1.shape == (5, 3) and p2.shape == (5, 3)


def test_tournament_objectarray():
    key = jax.random.key(2)
    solutions = ObjectArray.from_values([f"s{i}" for i in range(10)])
    evals = jnp.arange(10.0)
    picked = F.tournament(
        key, solutions, evals,
        num_tournaments=6, tournament_size=3, objective_sense="max",
    )
    assert len(picked) == 6
    assert all(isinstance(p, str) for p in picked)


# ------------------------------------------------------------- crossover ----


def test_multi_point_crossover_children_are_recombinations():
    key = jax.random.key(3)
    p1 = jnp.zeros((4, 10))
    p2 = jnp.ones((4, 10))
    parents = jnp.concatenate([p1, p2])
    children = F.multi_point_cross_over(key, parents, num_points=2)
    assert children.shape == (8, 10)
    vals = np.asarray(children)
    assert set(np.unique(vals)).issubset({0.0, 1.0})
    # complementary children: first half + second half == all ones
    assert np.allclose(vals[:4] + vals[4:], 1.0)
    # at least one child mixes genes from both parents
    mixed = [(0.0 in row) and (1.0 in row) for row in vals]
    assert any(mixed)


def test_one_point_crossover_structure():
    key = jax.random.key(4)
    parents = jnp.concatenate([jnp.zeros((3, 8)), jnp.ones((3, 8))])
    children = np.asarray(F.one_point_cross_over(key, parents))
    for row in children:
        # a single cut: at most one 0->1 or 1->0 transition
        transitions = np.sum(row[1:] != row[:-1])
        assert transitions <= 1


def test_crossover_with_tournament():
    key = jax.random.key(5)
    pop = jax.random.normal(key, (20, 5))
    evals = jnp.sum(pop, axis=-1)
    children = F.multi_point_cross_over(
        key, pop, evals, num_points=1, tournament_size=3,
        num_children=10, objective_sense="max",
    )
    assert children.shape == (10, 5)


def test_sbx_preserves_mean():
    key = jax.random.key(6)
    parents = jax.random.normal(key, (40, 6))
    children = F.simulated_binary_cross_over(key, parents, eta=15.0)
    assert children.shape == (40, 6)
    # SBX children are symmetric around parent means
    p1, p2 = parents[:20], parents[20:]
    c1, c2 = children[:20], children[20:]
    assert np.allclose(np.asarray(c1 + c2), np.asarray(p1 + p2), atol=1e-4)


# -------------------------------------------------------------- mutation ----


def test_gaussian_mutation():
    key = jax.random.key(7)
    values = jnp.zeros((10, 4))
    out = F.gaussian_mutation(key, values, stdev=1.0)
    assert out.shape == values.shape
    assert float(jnp.std(out)) > 0.5
    gated = F.gaussian_mutation(key, values, stdev=1.0, mutation_probability=0.0)
    assert np.allclose(np.asarray(gated), 0.0)


def test_polynomial_mutation_bounds():
    key = jax.random.key(8)
    values = jax.random.uniform(key, (30, 5), minval=-1.0, maxval=1.0)
    out = F.polynomial_mutation(key, values, lb=-1.0, ub=1.0, eta=20.0)
    assert float(jnp.min(out)) >= -1.0 and float(jnp.max(out)) <= 1.0
    assert not np.allclose(np.asarray(out), np.asarray(values))


# ------------------------------------------------------------ permutation ----


def test_cosyne_permutation_full():
    key = jax.random.key(9)
    values = jnp.arange(30.0).reshape(10, 3)
    out = F.cosyne_permutation(key, values, permute_all=True)
    # each column is a permutation of the original column
    for j in range(3):
        assert sorted(np.asarray(out[:, j]).tolist()) == sorted(np.asarray(values[:, j]).tolist())
    assert not np.allclose(np.asarray(out), np.asarray(values))


def test_cosyne_permutation_partial_respects_fitness():
    key = jax.random.key(10)
    values = jnp.arange(200.0).reshape(100, 2)
    evals = jnp.arange(100.0)
    out = F.cosyne_permutation(key, values, evals, permute_all=False, objective_sense="max")
    # best solutions mostly keep their values; worst mostly change
    changed = np.asarray(jnp.any(out != values, axis=-1))
    assert changed[:50].sum() > changed[50:].sum()


# ------------------------------------------------------- combine/take_best --


def test_combine_and_take_best_single_objective():
    v1, e1 = jnp.zeros((3, 2)), jnp.array([1.0, 2.0, 3.0])
    v2, e2 = jnp.ones((2, 2)), jnp.array([5.0, 0.0])
    values, evals = F.combine((v1, e1), (v2, e2))
    assert values.shape == (5, 2) and evals.shape == (5,)
    best_v, best_e = F.take_best(values, evals, objective_sense="max")
    assert float(best_e) == 5.0
    top_v, top_e = F.take_best(values, evals, 2, objective_sense="max")
    assert np.asarray(top_e).tolist() == [5.0, 3.0]
    low_v, low_e = F.take_best(values, evals, 2, objective_sense="min")
    assert np.asarray(low_e).tolist() == [0.0, 1.0]


def test_take_best_multiobjective_prefers_first_front():
    evals = jnp.array([[5.0, 5.0], [3.0, 4.0], [4.0, 3.0], [1.0, 1.0]])
    values = jnp.arange(4.0)[:, None] * jnp.ones((1, 2))
    top_v, top_e = F.take_best(values, evals, 3, objective_sense=["max", "max"])
    picked = set(np.asarray(top_v[:, 0]).astype(int).tolist())
    assert 0 in picked and 3 not in picked


def test_take_best_objectarray():
    values = ObjectArray.from_values(["a", "b", "c"])
    evals = jnp.array([3.0, 1.0, 2.0])
    v, e = F.take_best(values, evals, objective_sense="min")
    assert v == "b" and float(e) == 1.0


def test_combine_objectarray():
    a = ObjectArray.from_values([1, 2])
    b = ObjectArray.from_values([3])
    merged = F.combine(a, b)
    assert list(merged) == [1, 2, 3]


# ------------------------------------------------------------- jit-ability --


def test_pareto_selection_under_jit():
    @jax.jit
    def select(values, evals):
        return F.take_best(values, evals, 4, objective_sense=["max", "max"])

    key = jax.random.key(11)
    values = jax.random.normal(key, (16, 3))
    evals = jax.random.normal(key, (16, 2))
    v, e = select(values, evals)
    assert v.shape == (4, 3) and e.shape == (4, 2)


def test_batched_utility():
    evals = jnp.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    u = F.utility(evals, objective_sense="min", ranking_method="centered")
    assert u.shape == (2, 3)
    assert np.allclose(np.asarray(u[0]), [0.5, 0.0, -0.5])


def test_batched_mutation_independent_noise():
    # review regression: batch lanes must get independent randomness
    key = jax.random.key(0)
    values = jnp.zeros((2, 8, 5))
    out = F.gaussian_mutation(key, values, stdev=1.0)
    assert out.shape == (2, 8, 5)
    assert not np.allclose(np.asarray(out[0]), np.asarray(out[1]))
    gated = F.gaussian_mutation(key, values, stdev=1.0, mutation_probability=0.5)
    assert not np.allclose(np.asarray(gated[0]), np.asarray(gated[1]))

    perm = F.cosyne_permutation(key, jnp.broadcast_to(jnp.arange(24.0).reshape(8, 3), (2, 8, 3)))
    assert not np.allclose(np.asarray(perm[0]), np.asarray(perm[1]))

    poly = F.polynomial_mutation(key, jnp.zeros((2, 8, 4)), lb=-1.0, ub=1.0)
    assert not np.allclose(np.asarray(poly[0]), np.asarray(poly[1]))

    parents = jnp.broadcast_to(
        jnp.concatenate([jnp.zeros((4, 6)), jnp.ones((4, 6))]), (2, 8, 6)
    )
    kids = F.multi_point_cross_over(key, parents, num_points=1)
    assert not np.allclose(np.asarray(kids[0]), np.asarray(kids[1]))

    sbx = F.simulated_binary_cross_over(key, jnp.broadcast_to(jnp.linspace(-1, 1, 48).reshape(8, 6), (2, 8, 6)), eta=10.0)
    assert not np.allclose(np.asarray(sbx[0]), np.asarray(sbx[1]))


def test_int_array_arguments_accepted():
    # review regression: 0-d integer arrays at public boundaries
    values = jnp.arange(20.0).reshape(10, 2)
    evals = jnp.arange(10.0)
    idx = F.tournament(
        jax.random.key(0), values, evals,
        num_tournaments=jnp.asarray(6), tournament_size=np.int64(3),
        objective_sense="max", return_indices=True,
    )
    assert idx.shape == (6,)
    top_v, top_e = F.take_best(values, evals, np.asarray(2), objective_sense="max")
    assert top_v.shape == (2, 2)


def test_annealed_mutation_probability_no_retrace():
    # probability is traced: many distinct values reuse one executable
    key = jax.random.key(1)
    values = jnp.zeros((16, 4))
    outs = [
        F.gaussian_mutation(key, values, stdev=1.0, mutation_probability=p)
        for p in (0.1, 0.2, 0.3, 0.4, 0.5)
    ]
    dens = [float((o != 0).mean()) for o in outs]
    assert dens == sorted(dens)  # higher probability -> more mutated entries
