"""Multi-host GSPMD: the whole-generation program spanning processes.

``test_multihost.py`` proves the sharded grad estimator crosses process
boundaries; this file covers the ISSUE-13 rewrite's multi-host entry — two
real OS processes, 4 virtual CPU devices each, running
``parallel.dryrun_multihost`` (``make_generation_step`` over the GLOBAL
8-device mesh). Both processes must print identical mesh-global telemetry,
and that telemetry must match a SINGLE-host run of the same global shape
(the in-process pytest mesh is exactly 8 devices) — the mesh-global numbers
cannot depend on how the devices are carved into hosts.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

_WORKER = textwrap.dedent(
    """
    import json, os, sys
    proc_id = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from evotorch_tpu.parallel import dryrun_multihost, init_distributed

    init_distributed(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
    )
    assert jax.device_count() == 8, jax.device_count()
    out = dryrun_multihost(popsize=16, episode_length=6, generations=2, seed=4)
    print("SUMMARY", json.dumps(out))
    """
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_generation_step_matches_single_host(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    summaries = {}
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        for line in out.splitlines():
            if line.startswith("SUMMARY "):
                s = json.loads(line[len("SUMMARY "):])
                summaries[s["process_index"]] = s
    assert set(summaries) == {0, 1}

    # every process reports the SAME mesh-global numbers (the generation
    # program is one SPMD computation; per-host Python only reads back
    # fully-replicated reductions)
    agree_keys = ("mesh", "total_steps", "mean_score", "stdev_norm", "devices")
    for k in agree_keys:
        assert summaries[0][k] == summaries[1][k], (k, summaries)
    assert summaries[0]["process_count"] == 2
    assert summaries[0]["local_devices"] == 4
    assert summaries[0]["mesh"] == "hosts2.pop8"

    # single-host reference at the SAME global shape: the pytest process IS
    # an 8-virtual-device single host, so run the dryrun in-process
    from evotorch_tpu.parallel import dryrun_multihost

    ref = dryrun_multihost(popsize=16, episode_length=6, generations=2, seed=4)
    assert ref["process_count"] == 1 and ref["devices"] == 8
    assert summaries[0]["total_steps"] == ref["total_steps"]
    # mean_score/stdev_norm are rounded to 6 places in the summary; the
    # global program is identical, so they must agree exactly at that grain
    assert summaries[0]["mean_score"] == pytest.approx(ref["mean_score"], abs=1e-5)
    assert summaries[0]["stdev_norm"] == pytest.approx(ref["stdev_norm"], abs=1e-5)
