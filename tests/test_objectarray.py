import numpy as np
import pytest

from evotorch_tpu.tools import ImmutableList, ObjectArray


def test_basic_set_get():
    a = ObjectArray(3)
    a[0] = [1, 2]
    a[1] = "hello"
    a[2] = {"k": 4}
    assert isinstance(a[0], ImmutableList)
    assert list(a[0]) == [1, 2]
    assert a[1] == "hello"
    assert a[2]["k"] == 4
    assert len(a) == 3


def test_slicing_shares_storage():
    a = ObjectArray(4)
    for i in range(4):
        a[i] = i
    view = a[1:3]
    assert len(view) == 2
    view[0] = 99
    assert a[1] == 99


def test_read_only_view():
    a = ObjectArray(2)
    a[0] = 1
    ro = a.get_read_only_view()
    assert ro.is_read_only
    with pytest.raises(ValueError):
        ro[0] = 5


def test_clone_is_mutable_deep_copy():
    a = ObjectArray(1)
    a[0] = [1, 2, 3]
    b = a.clone()
    assert list(b[0]) == [1, 2, 3]
    assert isinstance(b[0], list)  # mutable copy
    b[0] = "changed"
    assert list(a[0]) == [1, 2, 3]


def test_fancy_indexing():
    a = ObjectArray.from_values(["a", "b", "c", "d"])
    picked = a[[0, 2]]
    assert list(picked) == ["a", "c"]
    mask = np.array([True, False, False, True])
    picked = a[mask]
    assert list(picked) == ["a", "d"]


def test_slice_assignment():
    a = ObjectArray(3)
    a[:] = [1, 2, 3]
    assert list(a) == [1, 2, 3]
    with pytest.raises(ValueError):
        a[0:2] = [1, 2, 3]


def test_nested_objectarray():
    from evotorch_tpu.tools import ObjectArray, as_immutable, is_immutable

    outer = ObjectArray(2)
    outer[0] = ObjectArray.from_values([1, 2])
    assert isinstance(outer[0], ObjectArray)
    assert outer[0].is_read_only
    assert is_immutable(outer[0])
    assert not is_immutable(ObjectArray(1))


def test_eq_with_array_elements():
    a = ObjectArray.from_values([np.array([1, 2]), 5])
    result = a == [np.array([1, 2]), 5]
    assert result.tolist() == [True, True]
    result = a == [np.array([1, 3]), 5]
    assert result.tolist() == [False, True]
    assert (a == [1]).tolist() == [False, False]


# -- tensor-like introspection breadth (reference objectarray.py:204-534) ----


def test_shape_size_numel():
    from evotorch_tpu.tools import ObjectArray

    arr = ObjectArray.from_values(["a", [1, 2], 3])
    assert arr.shape == (3,)
    assert arr.size() == (3,)
    assert arr.size(0) == 3
    assert arr.ndim == 1 and arr.dim() == 1
    assert arr.numel() == 3
    assert arr.device == "cpu"


def test_repeat():
    from evotorch_tpu.tools import ObjectArray

    arr = ObjectArray.from_values([1, "x"])
    rep = arr.repeat(3)
    assert list(rep) == [1, "x", 1, "x", 1, "x"]
    import pytest

    with pytest.raises(ValueError):
        arr.repeat(2, 2)


def test_from_numpy_and_storage_ptr():
    import numpy as np

    from evotorch_tpu.tools import ObjectArray

    src = np.empty(3, dtype=object)
    src[0], src[1], src[2] = "a", "b", "c"
    arr = ObjectArray.from_numpy(src)
    assert list(arr) == ["a", "b", "c"]
    # views share storage; clones do not
    view = arr[1:]
    assert view.storage_ptr() == arr.storage_ptr()
    assert arr.clone().storage_ptr() != arr.storage_ptr()


def test_clone_preserve_read_only_and_copy():
    import copy

    from evotorch_tpu.tools import ObjectArray

    arr = ObjectArray.from_values([[1, 2], "y"]).get_read_only_view()
    plain = arr.clone()
    assert not plain.is_read_only
    kept = arr.clone(preserve_read_only=True)
    assert kept.is_read_only
    via_copy = copy.copy(arr)
    assert via_copy.is_read_only
    deep = copy.deepcopy(arr)
    assert list(deep[0]) == [1, 2]


def test_set_item_and_pickle():
    import pickle

    from evotorch_tpu.tools import ObjectArray

    arr = ObjectArray(2)
    arr.set_item(0, [3, 4])
    arr.set_item(1, "z")
    back = pickle.loads(pickle.dumps(arr))
    assert list(back[0]) == [3, 4] and back[1] == "z"
