import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms.cmaes import CMAES
from evotorch_tpu.algorithms.ga import Cosyne, GeneticAlgorithm, SteadyStateGA
from evotorch_tpu.algorithms.gaussian import CEM, PGPE, SNES, XNES
from evotorch_tpu.operators.real import GaussianMutation, OnePointCrossOver, SimulatedBinaryCrossOver


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def make_problem(n=10, seed=1):
    return Problem("min", sphere, solution_length=n, initial_bounds=(-5, 5), seed=seed)


def improvement(searcher, gens=30):
    searcher.step()
    first = searcher.status["mean_eval"]
    searcher.run(gens)
    return first, searcher.status["mean_eval"]


# ------------------------------------------------------ quickstart parity ---
# reference test_examples.py:29-60 parametrizes the sphere problem over every
# algorithm for a few generations (smoke-level convergence)


def test_snes_improves():
    first, last = improvement(SNES(make_problem(), stdev_init=5.0))
    assert last < first


def test_pgpe_improves():
    s = PGPE(
        make_problem(),
        popsize=50,
        center_learning_rate=0.4,
        stdev_learning_rate=0.1,
        stdev_init=2.0,
    )
    first, last = improvement(s)
    assert last < first


def test_cem_improves():
    s = CEM(make_problem(), popsize=50, parenthood_ratio=0.5, stdev_init=2.0)
    first, last = improvement(s)
    assert last < first


def test_xnes_improves():
    s = XNES(make_problem(n=6), stdev_init=2.0)
    first, last = improvement(s, gens=50)
    assert last < first


def test_cmaes_improves():
    s = CMAES(make_problem(n=6), stdev_init=2.0)
    first, last = improvement(s, gens=60)
    assert last < first
    assert s.status["iter"] == 61


def test_cmaes_separable():
    s = CMAES(make_problem(n=8), stdev_init=2.0, separable=True, popsize=20)
    first, last = improvement(s, gens=60)
    assert last < first


def test_pgpe_distributed_mode():
    # distributed=True goes through problem.sample_and_compute_gradients
    s = PGPE(
        make_problem(),
        popsize=64,
        center_learning_rate=0.4,
        stdev_learning_rate=0.1,
        stdev_init=2.0,
        distributed=True,
    )
    s.run(20)
    assert s.status["mean_eval"] is not None
    center = s.status["center"]
    assert center.shape == (10,)


def test_ga_improves():
    p = make_problem()
    ga = GeneticAlgorithm(
        p,
        operators=[
            OnePointCrossOver(p, tournament_size=4),
            GaussianMutation(p, stdev=0.5),
        ],
        popsize=40,
    )
    first, last = improvement(ga, gens=25)
    assert last < first


def test_ga_multiobjective_nsga2_like():
    @vectorized
    def two_obj(xs):
        # classic convex front: f1 = x0^2 stuff, f2 = (x-1)^2 stuff
        return jnp.stack(
            [jnp.sum(xs**2, axis=-1), jnp.sum((xs - 2.0) ** 2, axis=-1)], axis=1
        )

    p = Problem(["min", "min"], two_obj, solution_length=5, initial_bounds=(-5, 5))
    ga = GeneticAlgorithm(
        p,
        operators=[
            SimulatedBinaryCrossOver(p, tournament_size=3, eta=8.0),
            GaussianMutation(p, stdev=0.3),
        ],
        popsize=32,
    )
    ga.run(15)
    pop = ga.population
    ranks = np.asarray(pop.compute_pareto_ranks())
    # after selection pressure most of the population should be near front 0
    assert (ranks == 0).sum() >= len(pop) // 4


def test_steady_state_ga_use():
    p = make_problem()
    ga = SteadyStateGA(p, popsize=30)
    with pytest.raises(RuntimeError):
        ga.step()
    ga.use(OnePointCrossOver(p, tournament_size=3))
    ga.use(GaussianMutation(p, stdev=0.3))
    first, last = improvement(ga, gens=20)
    assert last < first


def test_cosyne_improves():
    s = Cosyne(
        make_problem(),
        popsize=40,
        tournament_size=4,
        mutation_stdev=0.3,
        num_elites=2,
    )
    first, last = improvement(s, gens=25)
    assert last < first


def test_status_and_hooks_machinery():
    s = SNES(make_problem(), stdev_init=5.0)
    events = []
    s.before_step_hook.append(lambda: events.append("before"))
    s.after_step_hook.append(lambda: {"extra_metric": 1.23})
    logged = []
    s.log_hook.append(lambda status: logged.append(status))
    ended = []
    s.end_of_run_hook.append(lambda status: ended.append(status))
    s.run(3)
    assert events == ["before"] * 3
    assert len(logged) == 3
    assert logged[-1]["iter"] == 3
    assert logged[-1]["extra_metric"] == 1.23
    assert len(ended) == 1
    assert "pop_best_eval" in logged[-1]
    assert "median_eval" in dict(s.status.items())


def test_searcher_population_property():
    s = CEM(make_problem(), popsize=20, parenthood_ratio=0.5, stdev_init=1.0)
    with pytest.raises(RuntimeError):
        _ = s.population
    s.step()
    assert len(s.population) == 20


def test_pgpe_adaptive_popsize_by_interactions():
    # reference gaussian.py:296-349: with num_interactions set, the searcher
    # keeps sampling sub-populations until the interaction budget is met
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        episode_length=20,
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=8,
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        stdev_init=0.3,
        num_interactions=500,  # 8 envs x 20 steps = 160 per sub-population
        popsize_max=64,
    )
    searcher.step()
    # the population grew beyond the base popsize to satisfy the budget
    assert searcher.status["popsize"] > 8
    assert searcher.status["popsize"] <= 64
    searcher.run(2)  # subsequent generations keep working


def test_cosyne_sbx_branch():
    s = Cosyne(
        make_problem(),
        popsize=32,
        tournament_size=3,
        mutation_stdev=0.3,
        eta=12.0,  # SBX crossover instead of one-point
        elitism_ratio=0.1,
    )
    first, last = improvement(s, gens=15)
    assert last < first
