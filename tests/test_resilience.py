"""Fault tolerance (ISSUE 17; docs/resilience.md): durable run bundles,
non-finite score quarantine in every eval contract, the retry/backoff +
watchdog edges, and the deterministic ``EVOTORCH_FAULTS`` harness.

The contract under test is three-legged: a SIGKILL at any instant costs at
most one checkpoint interval (and the resumed trajectory is BIT-IDENTICAL
to the uninterrupted one); one diverged rollout cannot NaN-poison ranking
(scores are scrubbed inside the same jitted program, counted in telemetry,
and the counts are sharding-invariant); and every recovery path stays
exercised because faults are injectable deterministically.
"""

import json
import os
import pickle
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs.base import Env, EnvState, Space
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.neuroevolution.net.vecrl import (
    _quarantine_nonfinite,
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)
from evotorch_tpu.observability import GroupTelemetry
from evotorch_tpu.observability.registry import counters
from evotorch_tpu.resilience import (
    BUNDLE_SCHEMA_VERSION,
    CorruptBundleError,
    DeviceProbeTimeout,
    InjectedFault,
    RunCheckpointer,
    configure,
    fault_point,
    parse_spec,
    probe_devices,
    retry_call,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CPU_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=8",
)


@pytest.fixture(autouse=True)
def _clear_faults():
    # fault rules are process-global; no test may leak its spec
    yield
    configure(None)


# ---------------------------------------------------------------------------
# a deterministic diverging environment: non-finite rewards keyed purely on
# the policy parameters, so specific lanes diverge on purpose
# ---------------------------------------------------------------------------


class DivergingEnv(Env):
    """reward = action; actions above 2 produce NaN, below -2 produce +inf.

    With a ``Linear`` policy over an all-zero observation the action is the
    bias alone, so a population row filled with the constant ``c`` yields
    per-step reward ``c`` (finite) or NaN/inf — the non-finite lanes are
    chosen exactly by the parameter values."""

    max_episode_steps = 4

    def __init__(self):
        self.observation_space = Space(shape=(2,))
        self.action_space = Space(
            shape=(1,), lb=jnp.array([-10.0]), ub=jnp.array([10.0])
        )

    def reset(self, key):
        key, _ = jax.random.split(key)
        obs = jnp.zeros(2)
        return EnvState(obs_state=obs, t=jnp.zeros((), jnp.int32), key=key), obs

    def step(self, state, action):
        from dataclasses import replace

        a = jnp.reshape(action, ())
        reward = jnp.where(a > 2.0, jnp.nan, jnp.where(a < -2.0, jnp.inf, a))
        t = state.t + 1
        obs = jnp.zeros(2)
        done = t >= self.max_episode_steps
        return replace(state, t=t), obs, reward, done


def _diverging_setup(biases):
    env = DivergingEnv()
    policy = FlatParamsPolicy(Linear(env.observation_size, env.action_size))
    values = jnp.stack(
        [jnp.full(policy.parameter_count, b, jnp.float32) for b in biases]
    )
    stats = RunningNorm(env.observation_size).stats
    return env, policy, values, stats


# 3 of 8 lanes diverge (one NaN-high, one inf, one NaN-high); the finite
# lanes' scores are their bias values, so worst-finite == -1.5
_BIASES = (-1.5, 0.5, 3.0, -3.0, 1.5, 3.5, 0.0, -0.5)
_BAD = np.array([b > 2.0 or b < -2.0 for b in _BIASES])


_MODE_KWARGS = {
    "budget": {},
    "episodes": {},
    "episodes_refill": {"refill_width": 2, "refill_period": 1},
}


@pytest.mark.parametrize("eval_mode", sorted(_MODE_KWARGS))
def test_quarantine_scrubs_nonfinite_scores(eval_mode):
    env, policy, values, stats = _diverging_setup(_BIASES)
    kwargs = dict(
        num_episodes=1, episode_length=4, eval_mode=eval_mode,
        **_MODE_KWARGS[eval_mode],
    )
    off = run_vectorized_rollout(
        env, policy, values, jax.random.key(0), stats, **kwargs
    )
    on = run_vectorized_rollout(
        env, policy, values, jax.random.key(0), stats,
        nonfinite_quarantine=True, **kwargs,
    )
    raw = np.asarray(off.scores)
    scrubbed = np.asarray(on.scores)
    assert not np.isfinite(raw[_BAD]).any()  # the env really diverged
    assert np.isfinite(scrubbed).all()
    # finite lanes ride through BIT-identically; bad lanes get worst-finite
    np.testing.assert_array_equal(scrubbed[~_BAD], raw[~_BAD])
    worst = raw[~_BAD].min()
    np.testing.assert_array_equal(scrubbed[_BAD], np.full(_BAD.sum(), worst))
    # counted in the telemetry's nonfinite slot — and only when quarantining
    assert GroupTelemetry.from_array(on.telemetry).total().nonfinite == _BAD.sum()
    assert GroupTelemetry.from_array(off.telemetry).total().nonfinite == 0


def test_quarantine_compacting_contract():
    env, policy, values, stats = _diverging_setup(_BIASES)
    kwargs = dict(num_episodes=1, episode_length=4, chunk_size=2, allowed_widths=(1,))
    off = run_vectorized_rollout_compacting(
        env, policy, values, jax.random.key(0), stats, **kwargs
    )
    on = run_vectorized_rollout_compacting(
        env, policy, values, jax.random.key(0), stats,
        nonfinite_quarantine=True, **kwargs,
    )
    raw, scrubbed = np.asarray(off.scores), np.asarray(on.scores)
    assert not np.isfinite(raw[_BAD]).any()
    assert np.isfinite(scrubbed).all()
    np.testing.assert_array_equal(scrubbed[~_BAD], raw[~_BAD])
    np.testing.assert_array_equal(
        scrubbed[_BAD], np.full(_BAD.sum(), raw[~_BAD].min())
    )
    assert GroupTelemetry.from_array(on.telemetry).total().nonfinite == _BAD.sum()


def test_quarantine_fixed_penalty():
    env, policy, values, stats = _diverging_setup(_BIASES)
    r = run_vectorized_rollout(
        env, policy, values, jax.random.key(0), stats,
        num_episodes=1, episode_length=4, eval_mode="episodes",
        nonfinite_quarantine=True, nonfinite_penalty=-100.0,
    )
    scores = np.asarray(r.scores)
    np.testing.assert_array_equal(scores[_BAD], np.full(_BAD.sum(), -100.0))
    assert np.isfinite(scores).all()


def test_quarantine_identity_on_finite_scores():
    # the default-on contract: an all-finite population is BIT-untouched
    env, policy, values, stats = _diverging_setup((0.5, -0.5, 1.0, -1.0))
    kwargs = dict(num_episodes=1, episode_length=4, eval_mode="episodes")
    off = run_vectorized_rollout(
        env, policy, values, jax.random.key(1), stats, **kwargs
    )
    on = run_vectorized_rollout(
        env, policy, values, jax.random.key(1), stats,
        nonfinite_quarantine=True, **kwargs,
    )
    np.testing.assert_array_equal(np.asarray(on.scores), np.asarray(off.scores))
    assert GroupTelemetry.from_array(on.telemetry).total().nonfinite == 0


def test_quarantine_per_group_counts():
    env, policy, values, stats = _diverging_setup(_BIASES)
    groups = jnp.asarray([0, 0, 0, 1, 1, 1, 0, 1], jnp.int32)
    r = run_vectorized_rollout(
        env, policy, values, jax.random.key(0), stats,
        num_episodes=1, episode_length=4, eval_mode="episodes",
        nonfinite_quarantine=True, groups=groups, num_groups=2,
    )
    t = GroupTelemetry.from_array(r.telemetry)
    per_group = [
        int(np.sum(_BAD[np.asarray(groups) == g])) for g in range(2)
    ]
    assert [t.group(g).nonfinite for g in range(2)] == per_group
    assert t.total().nonfinite == _BAD.sum()
    assert t.nonfinite_share(group=None) > 0.0


def test_quarantine_helper_all_nonfinite_and_valid_mask():
    scores = jnp.asarray([jnp.nan, jnp.inf, -jnp.inf])
    out, bad = _quarantine_nonfinite(scores)
    # no finite score to borrow: the fallback replacement is 0.0
    np.testing.assert_array_equal(np.asarray(out), np.zeros(3))
    assert int(bad.sum()) == 3
    # padding lanes are scrubbed (so downstream stays finite) but NOT counted
    scores = jnp.asarray([1.0, -5.0, jnp.nan, jnp.nan])
    valid = jnp.asarray([True, True, True, False])
    out, bad = _quarantine_nonfinite(scores, valid_mask=valid)
    assert np.isfinite(np.asarray(out)).all()
    assert int(bad.sum()) == 1


# ---------------------------------------------------------------------------
# sharding invariance: quarantined scores AND counts are mesh-independent
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh_shape", [{"pop": 8}, {"pop": 4, "model": 2}])
@pytest.mark.parametrize("eval_mode", ["budget", "episodes_refill"])
def test_quarantine_sharded_bit_identity(mesh_shape, eval_mode):
    from evotorch_tpu.parallel import make_mesh, make_sharded_rollout_evaluator

    biases = _BIASES + (2.5, -2.5, 0.25, -0.25, 5.0, 1.0, -1.0, 0.75)
    bad = np.array([b > 2.0 or b < -2.0 for b in biases])
    env, policy, values, stats = _diverging_setup(biases)
    kwargs = dict(
        num_episodes=1, episode_length=4, eval_mode=eval_mode,
        nonfinite_quarantine=True, **_MODE_KWARGS[eval_mode],
    )
    ref = run_vectorized_rollout(
        env, policy, values, jax.random.key(3), stats, **kwargs
    )
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh(mesh_shape), **kwargs
    )
    result, _ = ev(values, jax.random.key(3), stats)
    np.testing.assert_array_equal(
        np.asarray(result.scores), np.asarray(ref.scores)
    )
    assert np.isfinite(np.asarray(result.scores)).all()
    n_ref = GroupTelemetry.from_array(ref.telemetry).total().nonfinite
    n_sharded = GroupTelemetry.from_array(result.telemetry).total().nonfinite
    assert n_ref == n_sharded == bad.sum()


# ---------------------------------------------------------------------------
# VecNE integration: default-on quarantine, status keys, score injection
# ---------------------------------------------------------------------------


def _small_vecne(**kwargs):
    from evotorch_tpu.neuroevolution import VecNE

    return VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": True},
        episode_length=10,
        eval_mode="episodes",
        seed=11,
        **kwargs,
    )


def test_vecne_quarantine_default_on_and_status_share():
    from evotorch_tpu.core import SolutionBatch

    p = _small_vecne()
    assert p._nonfinite_quarantine is True
    batch = SolutionBatch(p, 8)
    p.evaluate(batch)
    # telemetry-derived status is lag-by-one (one metered fetch per
    # generation): the share of eval #1 surfaces after eval #2
    p.evaluate(SolutionBatch(p, 8))
    assert float(p.status["eval_nonfinite_share"]) == 0.0
    assert np.isfinite(np.asarray(batch.evals)).all()


def test_vecne_injected_nonfinite_scores_are_quarantined():
    from evotorch_tpu.core import SolutionBatch

    configure("eval.scores:nonfinite@1+:0.25")
    before = counters.get("faults.injected_nonfinite")
    p = _small_vecne()
    batch = SolutionBatch(p, 8)
    p.evaluate(batch)
    # the injected NaNs were replaced by the same rule the engines compile
    assert np.isfinite(np.asarray(batch.evals)).all()
    assert counters.get("faults.injected_nonfinite") - before >= 2


def test_injected_nan_quarantine_keeps_improving(monkeypatch):
    # the load-bearing value claim: with 25% of every generation's scores
    # NaN, a quarantined run keeps optimizing while the pre-resilience
    # configuration (no quarantine, unguarded ranking) NaN-poisons the
    # distribution and stalls forever. The rank() guard is disabled for BOTH
    # arms so the contrast isolates the quarantine itself, and the ranking is
    # "raw" — the method where fitness values reach the utilities unshaped
    # (centered/linear argsort any NaN into a finite rank by construction).
    import evotorch_tpu.tools.ranking as ranking_mod
    from evotorch_tpu.algorithms.functional import pgpe, pgpe_ask, pgpe_tell

    monkeypatch.setattr(
        ranking_mod, "_nonfinite_to_worst", lambda x, **kw: x
    )

    def run(quarantined):
        state = pgpe(
            center_init=jnp.full(4, 3.0),
            center_learning_rate=0.3,
            stdev_learning_rate=0.1,
            stdev_init=0.5,
            objective_sense="max",
            ranking_method="raw",
        )
        key = jax.random.key(5)
        first = last = None
        for _ in range(12):
            key, sub = jax.random.split(key)
            pop = pgpe_ask(sub, state, popsize=32)
            fits = -jnp.sum(pop**2, axis=-1)
            clean_mean = float(jnp.mean(fits))
            fits = fits.at[::4].set(jnp.nan)  # every 4th solution diverges
            if quarantined:
                fits, _ = _quarantine_nonfinite(fits)
            state = pgpe_tell(state, pop, fits)
            if first is None:
                first = clean_mean
            last = clean_mean
        return first, last, state

    first_q, last_q, _ = run(quarantined=True)
    assert np.isfinite(last_q) and last_q > first_q  # still optimizing

    _, last_raw, state_raw = run(quarantined=False)
    # NaN utilities poison the center: the unquarantined run is dead
    assert not np.isfinite(np.asarray(state_raw.stdev)).all() or not np.isfinite(
        last_raw
    )


# ---------------------------------------------------------------------------
# ranking guard (defense in depth below the quarantine)
# ---------------------------------------------------------------------------


def test_rank_guard_sanitizes_nonfinite():
    from evotorch_tpu.tools.ranking import rank

    dirty = jnp.asarray([1.0, jnp.nan, 3.0, -jnp.inf, 2.0])
    clean = jnp.asarray([1.0, 1.0, 3.0, 1.0, 2.0])  # worst finite = 1.0
    for method in ("centered", "linear", "raw"):
        np.testing.assert_array_equal(
            np.asarray(rank(dirty, method, higher_is_better=True)),
            np.asarray(rank(clean, method, higher_is_better=True)),
        )
    # minimizing: the worst FINITE value is the max
    clean_min = jnp.asarray([1.0, 3.0, 3.0, 3.0, 2.0])
    np.testing.assert_array_equal(
        np.asarray(rank(dirty, "centered", higher_is_better=False)),
        np.asarray(rank(clean_min, "centered", higher_is_better=False)),
    )
    # the reference's unguarded semantics remain reachable
    unguarded = rank(
        dirty, "raw", higher_is_better=True, guard_nonfinite=False
    )
    assert np.isnan(np.asarray(unguarded)).any()


# ---------------------------------------------------------------------------
# SLO rule: max_nonfinite_share
# ---------------------------------------------------------------------------


def test_slo_max_nonfinite_share_rule():
    from evotorch_tpu.observability.slo import SLOWatchdog, check_bench_line

    dog = SLOWatchdog([{"kind": "max_nonfinite_share", "threshold": 0.1}])
    ok = dog.check(None, status={"eval_nonfinite_share": 0.05})
    assert ok.ok and ok.checked == 1
    bad = dog.check(None, status={"eval_nonfinite_share": 0.5})
    assert not bad.ok and "nonfinite_share" in bad.violations[0]
    # no status key + no telemetry: rule skips (missing data is not a fail)
    assert dog.check(None, status={}).checked == 0
    # bench-line form
    report = check_bench_line(
        {"steady_compiles": 0, "occupancy": 0.9, "eval_nonfinite_share": 0.3},
        max_nonfinite_share=0.02,
    )
    assert not report.ok and any("eval_nonfinite_share" in v for v in report.violations)


def test_slo_cli_exit_codes(tmp_path):
    def verdict(text):
        log = tmp_path / "bench.log"
        log.write_text(text)
        proc = subprocess.run(
            [
                sys.executable, "-m", "evotorch_tpu.observability.slo",
                "--check-bench", str(log),
            ],
            cwd=_REPO, env=_CPU_ENV, capture_output=True, text=True, timeout=120,
        )
        return proc.returncode, proc.stdout

    ok_line = json.dumps({"steady_compiles": 0, "occupancy": 0.8})
    rc, _ = verdict(ok_line + "\n")
    assert rc == 0
    rc, _ = verdict(json.dumps({"steady_compiles": 3, "occupancy": 0.8}) + "\n")
    assert rc == 1
    # a BENCH_TELEMETRY=0-style line carries none of the checked keys:
    # "insufficient data" is its own exit code, distinct from pass and fail
    rc, out = verdict(json.dumps({"value": 123.0}) + "\n")
    assert rc == 2 and "insufficient" in out
    rc, _ = verdict("")  # empty log: insufficient too
    assert rc == 2
    # a partial trailing line (crashed writer) is skipped, the last COMPLETE
    # line wins — no traceback, normal verdict
    rc, _ = verdict(ok_line + "\n" + '{"steady_compiles": 9, "occup')
    assert rc == 0


# ---------------------------------------------------------------------------
# durable run bundles
# ---------------------------------------------------------------------------


def test_bundle_roundtrip_and_registry_snapshot(tmp_path):
    ck = RunCheckpointer(tmp_path)
    ck.save(3, {"x": np.arange(4), "note": "gen three"})
    ck.save(7, {"x": np.arange(5), "note": "gen seven"})
    gen, state = ck.load_latest()
    assert gen == 7 and state["note"] == "gen seven"
    np.testing.assert_array_equal(state["x"], np.arange(5))
    # the payload carries schema/git/registry metadata beyond the state
    blob = open(ck.bundle_paths()[-1], "rb").read()
    record = pickle.loads(blob[8 + 32 :])
    assert record["schema"] == BUNDLE_SCHEMA_VERSION
    assert isinstance(record["registry"], dict)


def test_bundle_retention_keeps_last_k(tmp_path):
    ck = RunCheckpointer(tmp_path, keep=2)
    for gen in range(1, 6):
        ck.save(gen, {"gen": gen})
    names = [os.path.basename(p) for p in ck.bundle_paths()]
    assert names == ["bundle_00000004.ckpt", "bundle_00000005.ckpt"]


def test_bundle_cadence(tmp_path):
    ck = RunCheckpointer(tmp_path, every=3)
    for gen in range(1, 8):
        ck.maybe_save(gen, {"gen": gen})
    names = [os.path.basename(p) for p in ck.bundle_paths()]
    assert names == ["bundle_00000003.ckpt", "bundle_00000006.ckpt"]


def test_bundle_corrupt_fallback(tmp_path):
    ck = RunCheckpointer(tmp_path)
    ck.save(1, {"gen": 1})
    ck.save(2, {"gen": 2})
    newest = ck.bundle_paths()[-1]
    blob = open(newest, "rb").read()
    open(newest, "wb").write(blob[: len(blob) // 2])  # truncated write
    before = counters.get("checkpoint.corrupt_skipped")
    gen, state = ck.load_latest()
    assert (gen, state["gen"]) == (1, 1)  # one interval lost, not the run
    assert counters.get("checkpoint.corrupt_skipped") == before + 1
    # every bundle corrupt -> None (fresh start), never an exception
    open(ck.bundle_paths()[0], "wb").write(b"garbage")
    assert ck.load_latest() is None


def test_bundle_verification_errors(tmp_path):
    ck = RunCheckpointer(tmp_path)
    path = ck.save(1, {"gen": 1})
    blob = open(path, "rb").read()
    with pytest.raises(CorruptBundleError, match="magic|truncated"):
        bad = tmp_path / "bundle_00000009.ckpt"
        bad.write_bytes(b"NOTMAGIC" + blob[8:])
        RunCheckpointer.read_bundle(str(bad))
    with pytest.raises(CorruptBundleError, match="SHA-256"):
        flipped = bytearray(blob)
        flipped[-1] ^= 0xFF
        bad.write_bytes(bytes(flipped))
        RunCheckpointer.read_bundle(str(bad))
    # a NEWER schema is refused (an older reader cannot know what it means)
    payload = pickle.dumps({"schema": BUNDLE_SCHEMA_VERSION + 1, "generation": 1, "state": {}})
    import hashlib

    bad.write_bytes(b"EVTRUNB1" + hashlib.sha256(payload).digest() + payload)
    with pytest.raises(CorruptBundleError, match="schema"):
        RunCheckpointer.read_bundle(str(bad))


def test_save_searcher_atomic_and_corrupt_message(tmp_path):
    from evotorch_tpu.checkpoint import load_searcher, save_searcher

    path = tmp_path / "searcher.pickle"
    save_searcher(str(path), {"stand-in": "object"})
    assert load_searcher(str(path)) == {"stand-in": "object"}
    assert not os.path.exists(str(path) + ".tmp")  # tmp renamed away
    path.write_bytes(path.read_bytes()[:-4])  # truncated pickle
    with pytest.raises(RuntimeError, match="corrupt or truncated"):
        load_searcher(str(path))


def test_whole_searcher_pickle_roundtrip_with_dsl_activations():
    # jnp.tanh does not pickle by qualified name on this jax; the layer
    # __reduce__ hooks keep the default network DSL checkpointable
    from evotorch_tpu.neuroevolution.net import ReLU, Sigmoid, Softmax, Tanh

    for mod in (Tanh(), ReLU(), Sigmoid(), Softmax(axis=-1)):
        clone = pickle.loads(pickle.dumps(mod))
        x = jnp.asarray([-1.0, 0.5])
        out, _ = clone.apply((), x)
        ref, _ = mod.apply((), x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# fault injection harness
# ---------------------------------------------------------------------------


def test_parse_spec_grammar():
    rules = parse_spec("a.b:raise@2; hostpool.worker:kill@1:3 ;x:nonfinite@4+:0.5")
    assert [(r.site, r.kind, r.at, r.arg, r.sticky) for r in rules] == [
        ("a.b", "raise", 2, None, False),
        ("hostpool.worker", "kill", 1, "3", False),
        ("x", "nonfinite", 4, "0.5", True),
    ]
    assert rules[2].float_arg(0.0) == 0.5
    assert rules[0].float_arg(0.25) == 0.25
    for bad in ("nosite@1", "a:b", "a:b@x"):
        with pytest.raises(ValueError, match="EVOTORCH_FAULTS"):
            parse_spec(bad)


def test_fault_point_fires_at_nth_and_sticky():
    configure("s:raise@2;t:kill@1+")
    assert fault_point("s") is None  # invocation 1: no fire
    with pytest.raises(InjectedFault):
        fault_point("s")  # invocation 2: fires
    assert fault_point("s") is None  # @N (non-sticky) fired once, done
    for _ in range(3):  # sticky fires every time from the N-th on
        rule = fault_point("t")
        assert rule is not None and rule.kind == "kill"
    assert fault_point("unrelated.site") is None


def test_fault_counters_and_clear():
    before = counters.get("faults.fired.c.kill")
    configure("c:kill@1")
    assert fault_point("c").kind == "kill"
    assert counters.get("faults.fired.c.kill") == before + 1
    configure(None)  # back to (empty) env spec
    assert fault_point("c") is None


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky(value):
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return value * 2

    before = counters.snapshot(("retry.t.attempts", "retry.t.retries"))
    out = retry_call(flaky, 21, site="t", retries=3, base_delay=0.001)
    assert out == 42 and calls["n"] == 3
    delta = counters.delta(before)
    assert delta["retry.t.attempts"] == 3
    assert delta["retry.t.retries"] == 2


def test_retry_gives_up_and_reraises_original():
    def always_fails():
        raise OSError("permanent")

    before = counters.get("retry.g.giveups")
    with pytest.raises(OSError, match="permanent"):
        retry_call(always_fails, site="g", retries=2, base_delay=0.001)
    assert counters.get("retry.g.giveups") == before + 1


def test_retry_sites_are_fault_injectable():
    # the harness integration: an injected fault at the site consumes one
    # attempt, then the real call succeeds — no caller cooperation needed
    configure("io.op:raise@1")
    out = retry_call(lambda: "ok", site="io.op", retries=2, base_delay=0.001)
    assert out == "ok"


def test_retry_does_not_catch_unlisted_exceptions():
    with pytest.raises(KeyError):
        retry_call(
            lambda: {}["missing"], site="u", retries=3, base_delay=0.001
        )


# ---------------------------------------------------------------------------
# first-device-use watchdog
# ---------------------------------------------------------------------------


def test_probe_devices_returns_devices():
    devices = probe_devices(timeout=60)
    assert len(devices) >= 1


def test_probe_devices_flags_silent_cpu_fallback():
    # under pytest the backend IS cpu, which is exactly the plugin's silent-
    # fallback signature: expect_accelerator must turn it into an error
    with pytest.raises(DeviceProbeTimeout, match="accelerator"):
        probe_devices(timeout=60, expect_accelerator=True)


# ---------------------------------------------------------------------------
# MetricsHub: nonfinite export + crash-safe feed
# ---------------------------------------------------------------------------


def test_metricshub_exports_nonfinite(tmp_path):
    from evotorch_tpu.observability.metricshub import MetricsHub

    env, policy, values, stats = _diverging_setup(_BIASES)
    r = run_vectorized_rollout(
        env, policy, values, jax.random.key(0), stats,
        num_episodes=1, episode_length=4, eval_mode="episodes",
        nonfinite_quarantine=True,
    )
    telemetry = GroupTelemetry.from_array(r.telemetry)
    path = tmp_path / "feed.jsonl"
    hub = MetricsHub(str(path), manifest={"source": "test"})
    hub.emit({"gen": 1, "mean_eval": 1.0}, telemetry=telemetry)
    rows = [json.loads(line) for line in open(path)]
    assert "manifest" in rows[0]
    data = rows[1]
    assert data["eval_nonfinite"] == int(_BAD.sum())
    # every line the writer produced is complete JSON (fsync'd append path)
    for line in open(path):
        json.loads(line)


# ---------------------------------------------------------------------------
# slow tier: process-level fault tolerance
# ---------------------------------------------------------------------------


_CURVE_ARGS = [
    "--env", "cartpole", "--cpu", "--popsize", "16", "--episode-length", "20",
    "--eval-every", "4", "--eval-episodes", "2", "--checkpoint-every", "2",
]


def _run_curve(tmp_path, tag, generations, wait_then_kill=None):
    out = tmp_path / f"{tag}.jsonl"
    cmd = [
        sys.executable, os.path.join(_REPO, "examples", "locomotion_curve.py"),
        *_CURVE_ARGS, "--generations", str(generations),
        "--checkpoint-dir", str(tmp_path / f"ck_{tag}"), "--out", str(out),
    ]
    if wait_then_kill is None:
        proc = subprocess.run(
            cmd, env=_CPU_ENV, check=True, timeout=600, capture_output=True,
            text=True,
        )
        return out, proc.stdout
    proc = subprocess.Popen(
        cmd, env=_CPU_ENV, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
    )
    try:
        # SIGKILL the instant the bundle appears: generation wait_then_kill+2
        # is the first --eval-every generation, whose center-eval program
        # compiles for seconds — the kill reliably lands mid-run
        marker = tmp_path / f"ck_{tag}" / f"bundle_{wait_then_kill:08d}.ckpt"
        deadline = time.monotonic() + 540
        while not marker.exists():
            assert proc.poll() is None, "curve process exited before the kill"
            assert time.monotonic() < deadline, "bundle never appeared"
            time.sleep(0.2)
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=60)
    return out, None


def _curve_rows(*paths):
    rows = {}
    for path in paths:
        for line in open(path):
            row = json.loads(line)
            if "gen" in row:
                rows[row["gen"]] = row  # duplicates after resume: last wins
    return rows


@pytest.mark.slow
def test_sigkill_mid_curve_resume_is_bit_identical(tmp_path):
    # the tentpole acceptance: SIGKILL the curve mid-run, re-launch with the
    # same checkpoint dir, and the completed trajectory matches the never-
    # killed run BIT for bit on every deterministic column
    ref, _ = _run_curve(tmp_path, "ref", generations=8)
    _run_curve(tmp_path, "killed", generations=8, wait_then_kill=2)
    resumed, stdout = _run_curve(tmp_path, "killed", generations=8)  # same dir
    assert "resumed_from_generation" in stdout  # resume really happened
    a, b = _curve_rows(ref), _curve_rows(resumed)
    assert sorted(a) == sorted(b) == list(range(1, 9))
    for gen in a:
        for key in ("mean_eval", "best_eval", "stdev_norm", "clipup_velocity_norm"):
            assert a[gen].get(key) == b[gen].get(key), (gen, key)
        if a[gen].get("center_full") is not None and b[gen].get("center_full") is not None:
            assert a[gen]["center_full"] == b[gen]["center_full"]


def _slow_sphere_row(row):
    # module-level (worker processes unpickle the objective); slow enough
    # that pieces are still in flight when the injected kill lands AND that
    # the result-queue poll times out at least once (the death detector)
    time.sleep(0.3)
    return float(np.sum(np.asarray(row) ** 2))


@pytest.mark.slow
def test_hostpool_worker_death_respawns_and_completes():
    from evotorch_tpu.core import Problem

    sphere = _slow_sphere_row

    configure("hostpool.worker:kill@1")
    before = counters.snapshot(
        ("hostpool.worker_deaths", "hostpool.respawns", "hostpool.redispatched_pieces")
    )
    p = Problem(
        "min", sphere, solution_length=4, initial_bounds=(-1, 1), num_actors=2
    )
    try:
        batch = p.generate_batch(8)
        p.evaluate(batch)  # worker 0 is SIGKILLed right after dispatch
        expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
        np.testing.assert_allclose(
            np.asarray(batch.evals[:, 0]), expected, atol=1e-5
        )
        delta = counters.delta(before)
        assert delta["hostpool.worker_deaths"] >= 1
        assert delta["hostpool.respawns"] >= 1
        assert p._host_pool.is_alive()
    finally:
        p.kill_actors()


@pytest.mark.slow
def test_quarantine_overhead_refill_contract():
    # acceptance A/B: always-on quarantine must be ~free on the refill
    # contract. Interleaved samples, medians — this box times ±20% run to
    # run (CLAUDE.md), so the assert uses a variance-tolerant ceiling; the
    # measured median ratio is printed for the record.
    from evotorch_tpu.envs import CartPole

    env = CartPole(continuous_actions=True)
    policy = FlatParamsPolicy(Linear(env.observation_size, env.action_size))
    values = 0.1 * jax.random.normal(
        jax.random.key(0), (256, policy.parameter_count)
    )
    stats = RunningNorm(env.observation_size).stats
    kwargs = dict(
        num_episodes=1, episode_length=100, eval_mode="episodes_refill",
        refill_width=32, refill_period=1,
    )

    def run(quarantine):
        r = run_vectorized_rollout(
            env, policy, values, jax.random.key(1), stats,
            nonfinite_quarantine=quarantine, **kwargs,
        )
        jax.block_until_ready(r.scores)
        return r

    run(False), run(True)  # warm both programs
    compile_mark = counters.snapshot(("compiles",))
    samples = {False: [], True: []}
    for _ in range(5):
        for flag in (False, True):  # interleaved: drift hits both arms
            t0 = time.perf_counter()
            run(flag)
            samples[flag].append(time.perf_counter() - t0)
    # the timed loops must be retrace-free or the numbers mean nothing
    assert counters.delta(compile_mark).get("compiles", 0) == 0
    import statistics

    ratio = statistics.median(samples[True]) / statistics.median(samples[False])
    print(f"quarantine overhead ratio (refill contract): {ratio:.4f}")
    assert ratio <= 1.15  # target is 1.02; ceiling absorbs box variance
