import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import vectorized
from evotorch_tpu.core import Problem
from evotorch_tpu.distributions import SymmetricSeparableGaussian
from evotorch_tpu.parallel import (
    default_mesh,
    device_count,
    make_mesh,
    make_sharded_evaluator,
    make_sharded_grad_estimator,
    shard_population,
)


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def test_virtual_device_mesh_available():
    # conftest forces an 8-device CPU topology — the analog of the
    # reference's Ray local-mode testing (reference tests/conftest.py:24-40)
    assert device_count() == 8


def test_default_and_nd_mesh():
    mesh = default_mesh()
    assert mesh.axis_names == ("pop",)
    assert mesh.shape["pop"] == 8
    mesh2 = make_mesh({"pop": 4, "model": 2})
    assert mesh2.shape == {"pop": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh({"pop": 16})


def test_sharded_evaluator_matches_local():
    ev = make_sharded_evaluator(sphere)
    values = jax.random.normal(jax.random.key(0), (64, 10))
    out = ev(values)
    assert np.allclose(np.asarray(out), np.asarray(sphere(values)), atol=1e-5)


def test_sharded_evaluator_unaligned_popsize():
    ev = make_sharded_evaluator(sphere)
    values = jax.random.normal(jax.random.key(1), (13, 4))  # 13 % 8 != 0
    out = ev(values)
    assert out.shape == (13,)
    assert np.allclose(np.asarray(out), np.asarray(sphere(values)), atol=1e-5)


def test_shard_population_layout():
    mesh = default_mesh()
    values = jnp.zeros((32, 5))
    sharded = shard_population(values, mesh)
    assert sharded.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("pop")), 2
    )


def test_problem_sharded_evaluation():
    p = Problem("min", sphere, solution_length=6, initial_bounds=(-1, 1))
    p.use_sharded_evaluation()
    batch = p.generate_batch(40)
    p.evaluate(batch)
    assert batch.is_evaluated
    expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
    assert np.allclose(np.asarray(batch.evals[:, 0]), expected, atol=1e-5)


def test_sharded_grad_estimator_direction_and_replication():
    est = make_sharded_grad_estimator(
        SymmetricSeparableGaussian,
        sphere,
        objective_sense="min",
        ranking_method="centered",
    )
    params = {"mu": jnp.full((4,), 5.0), "sigma": jnp.ones(4),
              "divide_mu_grad_by": "num_directions", "divide_sigma_grad_by": "num_directions"}
    grads = est(jax.random.key(0), 160, params)
    # minimizing sphere from mu=5: ascent gradient of mu points down
    assert all(float(g) < 0 for g in np.asarray(grads["mu"]))
    with pytest.raises(ValueError):
        est(jax.random.key(0), 161, params)


def test_sharded_grad_estimator_converges():
    est = make_sharded_grad_estimator(
        SymmetricSeparableGaussian,
        sphere,
        objective_sense="min",
        ranking_method="centered",
    )
    mu = jnp.full((4,), 3.0)
    sigma = jnp.ones(4)

    @jax.jit
    def run(mu, key):
        def step(mu, key):
            grads = est(key, 80, {"mu": mu, "sigma": sigma,
                                  "divide_mu_grad_by": "num_directions",
                                  "divide_sigma_grad_by": "num_directions"})
            return mu + 0.3 * grads["mu"], None

        return jax.lax.scan(step, mu, jax.random.split(key, 120))[0]

    mu = run(mu, jax.random.key(1))
    assert float(jnp.linalg.norm(mu)) < 1.0


@pytest.mark.slow
def test_dryrun_multichip_various_topologies(monkeypatch):
    import __graft_entry__ as g

    # small flagship sizes: this test is about topology (divisibility,
    # odd device counts), not scale — the driver's n=8 dryrun covers the
    # flagship-scale step. popsize 10 on 3 devices exercises the
    # lcm(2, n_devices) rounding (10 -> 6, even AND divisible by 3).
    monkeypatch.setenv("MULTICHIP_POPSIZE", "10")
    monkeypatch.setenv("MULTICHIP_EPISODE_LENGTH", "5")
    # even and odd device counts; both must compile + execute
    g.dryrun_multichip(2)
    g.dryrun_multichip(3)


def test_sharded_evaluator_multi_output():
    # fitness functions may return (fitness, eval_data) pytrees
    @vectorized
    def with_extra(xs):
        return jnp.sum(xs**2, axis=-1), jnp.stack([xs[:, 0], xs[:, 1]], axis=1)

    ev = make_sharded_evaluator(with_extra)
    values = jax.random.normal(jax.random.key(7), (24, 4))
    fit, extra = ev(values)
    assert fit.shape == (24,)
    assert extra.shape == (24, 2)
    ref_fit, ref_extra = with_extra(values)
    assert np.allclose(np.asarray(fit), np.asarray(ref_fit), atol=1e-5)
    assert np.allclose(np.asarray(extra), np.asarray(ref_extra), atol=1e-5)
    # unaligned popsize too
    fit13, extra13 = ev(values[:13])
    assert fit13.shape == (13,) and extra13.shape == (13, 2)


@pytest.mark.slow
def test_sharded_training_identical_across_topologies():
    """3 PGPE generations on the flagship Humanoid with the population
    sharded over pop x model meshes 8x1 / 4x2 / 2x4: the mesh topology is an
    execution detail under GSPMD, so the trained center must be identical
    (VERDICT r1 item 10)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from evotorch_tpu.algorithms.functional import pgpe, pgpe_ask, pgpe_tell
    from evotorch_tpu.envs import Humanoid
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")

    env = Humanoid()
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    stats = RunningNorm(env.observation_size).stats
    popsize, episode_length, generations = 8, 3, 3

    def train(pop_axis, model_axis):
        mesh = Mesh(
            np.asarray(jax.devices()[:8]).reshape(pop_axis, model_axis),
            axis_names=("pop", "model"),
        )
        sharding = NamedSharding(mesh, P("pop", "model"))
        state = pgpe(
            center_init=jnp.zeros(policy.parameter_count, dtype=jnp.float32),
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            objective_sense="max",
            stdev_init=0.1,
        )

        @jax.jit
        def step(state, key):
            k1, k2 = jax.random.split(key)
            values = pgpe_ask(k1, state, popsize=popsize)
            values = jax.lax.with_sharding_constraint(values, sharding)
            result = run_vectorized_rollout(
                env, policy, values, k2, stats,
                num_episodes=1, episode_length=episode_length,
                eval_mode="budget",
            )
            return pgpe_tell(state, values, result.scores), result.scores

        key = jax.random.key(42)
        for _ in range(generations):
            key, sub = jax.random.split(key)
            state, scores = step(state, sub)
        return np.asarray(state.optimizer_state.center), np.asarray(scores)

    center_81, scores_81 = train(8, 1)
    center_42, scores_42 = train(4, 2)
    center_24, scores_24 = train(2, 4)
    np.testing.assert_allclose(center_42, center_81, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(center_24, center_81, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(scores_42, scores_81, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(scores_24, scores_81, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_sharded_lowrank_obsnorm_identical_across_topologies():
    """VERDICT r4 #8: the two newest representations — factored (low-rank)
    populations and observation normalization — exercised TOGETHER under
    sharding, across pop x model mesh layouts. Under GSPMD the obs-norm
    statistics contract over the sharded population axis, so their psum
    GROUPING changes with the pop-shard count (8/4/2) and the last-ulp
    differences amplify through Humanoid's chaotic dynamics — exact identity
    holds WITHIN a topology (determinism), and closeness across topologies.
    The factored coefficients shard over "pop"; the shared center and basis
    replicate (the representation's intended layout: O(L*k) replicated beats
    O(N_local*L) sharded)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from evotorch_tpu.algorithms.functional import (
        pgpe,
        pgpe_ask_lowrank,
        pgpe_tell_lowrank,
    )
    from evotorch_tpu.envs import Humanoid
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.lowrank import LowRankParamsBatch
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")

    env = Humanoid()
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    stats = RunningNorm(env.observation_size).stats
    popsize, rank, episode_length, generations = 8, 4, 3, 3

    def train(pop_axis, model_axis):
        mesh = Mesh(
            np.asarray(jax.devices()[:8]).reshape(pop_axis, model_axis),
            axis_names=("pop", "model"),
        )

        def constrain(values: LowRankParamsBatch) -> LowRankParamsBatch:
            return LowRankParamsBatch(
                center=jax.lax.with_sharding_constraint(
                    values.center, NamedSharding(mesh, P())
                ),
                basis=jax.lax.with_sharding_constraint(
                    values.basis, NamedSharding(mesh, P())
                ),
                coeffs=jax.lax.with_sharding_constraint(
                    values.coeffs, NamedSharding(mesh, P("pop", None))
                ),
            )

        state = pgpe(
            center_init=jnp.zeros(policy.parameter_count, dtype=jnp.float32),
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            objective_sense="max",
            stdev_init=0.1,
        )

        @jax.jit
        def step(state, key):
            k1, k2 = jax.random.split(key)
            values = constrain(pgpe_ask_lowrank(k1, state, popsize=popsize, rank=rank))
            result = run_vectorized_rollout(
                env, policy, values, k2, stats,
                num_episodes=1, episode_length=episode_length,
                eval_mode="budget", observation_normalization=True,
            )
            return pgpe_tell_lowrank(state, values, result.scores), result.scores

        key = jax.random.key(43)
        for _ in range(generations):
            key, sub = jax.random.split(key)
            state, scores = step(state, sub)
        return np.asarray(state.optimizer_state.center), np.asarray(scores)

    center_81, scores_81 = train(8, 1)
    center_81b, scores_81b = train(8, 1)
    # determinism: the same topology reproduces bit-for-bit
    np.testing.assert_array_equal(center_81b, center_81)
    np.testing.assert_array_equal(scores_81b, scores_81)
    # across pop-shard counts: bounded closeness (measured max |delta| was
    # ~8e-4 after 3 generations; bound set with ~6x margin)
    center_42, scores_42 = train(4, 2)
    center_24, scores_24 = train(2, 4)
    np.testing.assert_allclose(center_42, center_81, atol=5e-3)
    np.testing.assert_allclose(center_24, center_81, atol=5e-3)
    np.testing.assert_allclose(scores_42, scores_81, rtol=2e-2)
    np.testing.assert_allclose(scores_24, scores_81, rtol=2e-2)
