"""OO ``distributed=True`` semantics under both SPMD forms.

The reference's distributed mode (``core.py:3156-3301`` +
``algorithms/distributed/gaussian.py:199-272``) has each actor sample its own
sub-population, rank **locally**, and compute local gradients; the main
process averages them. After the GSPMD rewrite those exact statistics live
behind the ``EVOTORCH_SHARD_MAP=1`` compat knob (local ranking is a
*semantic*, not a layout — rank weights depend on the cohort), and the
default is the reference's SINGLE-process semantics: one global program,
global key, global ranking, identical at any mesh shape. Both are pinned
here.
"""

import jax
import jax.numpy as jnp
import numpy as np

from evotorch_tpu import vectorized
from evotorch_tpu.core import Problem
from evotorch_tpu.algorithms import PGPE
from evotorch_tpu.distributions import SymmetricSeparableGaussian
from evotorch_tpu.tools.ranking import rank


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def _make_problem(**kwargs):
    return Problem("min", sphere, solution_length=6, initial_bounds=(-1, 1), **kwargs)


def _dist_params():
    return {
        "mu": jnp.full((6,), 4.0),
        "sigma": jnp.ones(6),
        "divide_mu_grad_by": "num_directions",
        "divide_sigma_grad_by": "num_directions",
    }


def _local_ranking_oracle(key, params, popsize, n_shards):
    """Hand-rolled reference semantics: per-shard sample + local centered
    ranking + local grads, equal-weight average (equal shard sizes)."""
    local = popsize // n_shards
    grads = []
    all_samples, all_fits = [], []
    for i in range(n_shards):
        ki = jax.random.fold_in(key, i)
        samples = SymmetricSeparableGaussian._sample(ki, params, local)
        fits = sphere(samples)
        weights = rank(fits, "centered", higher_is_better=False)
        grads.append(
            SymmetricSeparableGaussian._compute_gradients(params, samples, weights, "centered")
        )
        all_samples.append(samples)
        all_fits.append(fits)
    avg = {k: np.mean([np.asarray(g[k]) for g in grads], axis=0) for k in grads[0]}
    return avg, jnp.concatenate(all_samples), jnp.concatenate(all_fits)


def test_distributed_gradients_gspmd_ranks_globally():
    # the GSPMD default: global key, global ranking — the estimate is
    # exactly what a one-device run computes, at any mesh shape
    p = _make_problem(num_actors="max")
    dist = SymmetricSeparableGaussian(_dist_params())
    key = jax.random.key(123)
    results = p.sample_and_compute_gradients(dist, 16, ranking_method="centered", key=key)
    assert len(results) == 1
    got = results[0]
    assert got["num_solutions"] == 16

    samples = SymmetricSeparableGaussian._sample(key, _dist_params(), 16)
    fits = sphere(samples)
    weights = rank(fits, "centered", higher_is_better=False)
    oracle = SymmetricSeparableGaussian._compute_gradients(
        _dist_params(), samples, weights, "centered"
    )
    for k in ("mu", "sigma"):
        assert np.allclose(np.asarray(got["gradients"][k]), np.asarray(oracle[k]), atol=1e-5), k
    assert np.isclose(got["mean_eval"], float(jnp.mean(fits)), atol=1e-4)


def test_distributed_gradients_rank_locally(monkeypatch):
    monkeypatch.setenv("EVOTORCH_SHARD_MAP", "1")
    p = _make_problem(num_actors="max")
    dist = SymmetricSeparableGaussian(_dist_params())
    key = jax.random.key(123)
    results = p.sample_and_compute_gradients(dist, 16, ranking_method="centered", key=key)
    assert len(results) == 1
    got = results[0]
    assert got["num_solutions"] == 16

    oracle, all_samples, all_fits = _local_ranking_oracle(key, _dist_params(), 16, 8)
    for k in ("mu", "sigma"):
        assert np.allclose(np.asarray(got["gradients"][k]), oracle[k], atol=1e-5), k

    # and local ranking is genuinely different from global ranking: the
    # globally-ranked gradient over the same concatenated samples must differ
    global_grads = dist.compute_gradients(
        all_samples, all_fits, objective_sense="min", ranking_method="centered"
    )
    assert not np.allclose(
        np.asarray(got["gradients"]["mu"]), np.asarray(global_grads["mu"]), atol=1e-6
    )
    assert np.isclose(got["mean_eval"], float(jnp.mean(all_fits)), atol=1e-4)


def test_distributed_gradients_round_up_uneven_popsize():
    p = _make_problem(num_actors="max")
    dist = SymmetricSeparableGaussian(_dist_params())
    # 20 does not divide over 8 shards; antithetic needs even local size
    # -> local 2 everywhere, total rounds up to 16? no: ceil(20/8)=3 -> even 4 -> 32
    results = p.sample_and_compute_gradients(dist, 20, ranking_method="centered")
    assert results[0]["num_solutions"] == 32


def test_pgpe_distributed_converges_on_sphere():
    p = _make_problem(num_actors="max")
    searcher = PGPE(
        p,
        popsize=64,
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        stdev_init=1.0,
        center_init=jnp.full((6,), 3.0),
        distributed=True,
    )
    searcher.run(40)
    center = np.asarray(searcher.status["center"])
    assert float(np.sum(center**2)) < 1.0
    assert "mean_eval" in searcher.status


def test_distributed_non_traceable_objective_falls_back():
    # review regression: a host-side objective with num_actors must degrade
    # to the single-program (global-ranking) path, not crash inside shard_map
    import numpy as onp

    @vectorized
    def host_objective(xs):
        return jnp.asarray(onp.sum(onp.asarray(xs) ** 2, axis=-1))

    p = Problem("min", host_objective, solution_length=6, initial_bounds=(-1, 1), num_actors=4)
    dist = SymmetricSeparableGaussian(_dist_params())
    results = p.sample_and_compute_gradients(dist, 16, ranking_method="centered")
    assert results[0]["num_solutions"] == 16
    assert p._eval_mesh is None  # sharded machinery fully dropped
    # and subsequent steps keep working on the fallback path
    results = p.sample_and_compute_gradients(dist, 16, ranking_method="centered")
    assert results[0]["num_solutions"] == 16


def test_distributed_without_mesh_falls_back_to_single_program():
    # no sharded evaluator: one global-ranking program, exactly one result
    p = _make_problem()
    dist = SymmetricSeparableGaussian(_dist_params())
    key = jax.random.key(7)
    results = p.sample_and_compute_gradients(dist, 16, ranking_method="centered", key=key)
    assert len(results) == 1
    assert results[0]["num_solutions"] == 16
