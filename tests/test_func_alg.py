import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.algorithms.functional import cem, cem_ask, cem_tell, pgpe, pgpe_ask, pgpe_tell

from helpers import run_functional_search


def sphere(x):
    return jnp.sum(x**2, axis=-1)


def test_cem_minimizes_sphere():
    state = cem(
        center_init=jnp.full((5,), 3.0),
        parenthood_ratio=0.5,
        objective_sense="min",
        stdev_init=2.0,
        stdev_max_change=0.2,
    )
    state, _ = run_functional_search(
        state, jax.random.key(0),
        ask=cem_ask, tell=cem_tell, fitness=sphere, popsize=50, num_generations=100,
    )
    assert float(sphere(state.center[None])[0]) < 0.1


def test_cem_maximization():
    fitness = lambda pop: -sphere(pop - 2.0)  # noqa: E731
    state = cem(
        center_init=jnp.zeros(3),
        parenthood_ratio=0.5,
        objective_sense="max",
        stdev_init=1.0,
        stdev_max_change=0.3,  # guard against premature stdev collapse
    )
    state, _ = run_functional_search(
        state, jax.random.key(1),
        ask=cem_ask, tell=cem_tell, fitness=fitness, popsize=40, num_generations=80,
    )
    assert np.allclose(np.asarray(state.center), 2.0, atol=0.3)


def test_pgpe_minimizes_sphere_with_clipup():
    # ClipUp takes fixed-norm steps, so the steady-state error is O(stepsize)
    state = pgpe(
        center_init=jnp.full((6,), 5.0),
        center_learning_rate=0.15,
        stdev_learning_rate=0.1,
        objective_sense="min",
        ranking_method="centered",
        optimizer="clipup",
        stdev_init=2.0,
    )
    state, means = run_functional_search(
        state, jax.random.key(2),
        ask=pgpe_ask, tell=pgpe_tell, fitness=sphere, popsize=40, num_generations=300,
    )
    assert float(sphere(state.optimizer_state.center[None])[0]) < 0.5
    assert float(means[-1]) < float(means[0])


def test_pgpe_nonsymmetric_adam():
    fitness = lambda pop: -sphere(pop - 1.0)  # noqa: E731
    state = pgpe(
        center_init=jnp.zeros(4),
        center_learning_rate=0.1,
        stdev_learning_rate=0.05,
        objective_sense="max",
        optimizer="adam",
        stdev_init=1.0,
        symmetric=False,
    )
    state, _ = run_functional_search(
        state, jax.random.key(3),
        ask=pgpe_ask, tell=pgpe_tell, fitness=fitness, popsize=50, num_generations=150,
    )
    assert np.allclose(np.asarray(state.optimizer_state.center), 1.0, atol=0.4)


def test_pgpe_rejects_odd_popsize_when_symmetric():
    state = pgpe(
        center_init=jnp.zeros(2),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="min",
        stdev_init=1.0,
    )
    with pytest.raises(ValueError):
        pgpe_ask(jax.random.key(0), state, popsize=7)


def test_batched_cem_search():
    # two batched searches tracking *different* targets must progress
    # independently (this fails if the batch lanes share sampling noise)
    targets = jnp.array([[0.0, 0.0, 0.0], [3.0, 3.0, 3.0]])
    fitness = lambda pop: sphere(pop - targets[:, None, :])  # noqa: E731
    state = cem(
        center_init=jnp.zeros((2, 3)),
        parenthood_ratio=0.5,
        objective_sense="min",
        stdev_init=2.0,
        stdev_max_change=0.3,
    )
    state, _ = run_functional_search(
        state, jax.random.key(4),
        ask=cem_ask, tell=cem_tell, fitness=fitness, popsize=30, num_generations=80,
    )
    assert np.allclose(np.asarray(state.center), np.asarray(targets), atol=0.5)


def test_cem_ask_population_shape_batched():
    state = cem(
        center_init=jnp.zeros((2, 3)),
        parenthood_ratio=0.5,
        objective_sense="min",
        stdev_init=1.0,
    )
    pop = cem_ask(jax.random.key(0), state, popsize=30)
    assert pop.shape == (2, 30, 3)


def test_func_alg_under_jit_scan():
    # a PGPE run driven through the shared scan helper, then resumed:
    # states must round-trip through scan carries
    state = pgpe(
        center_init=jnp.full((5,), 3.0),
        center_learning_rate=0.3,
        stdev_learning_rate=0.1,
        objective_sense="min",
        stdev_init=1.0,
    )
    state, means1 = run_functional_search(
        state, jax.random.key(5),
        ask=pgpe_ask, tell=pgpe_tell, fitness=sphere, popsize=40, num_generations=75,
    )
    state, means2 = run_functional_search(
        state, jax.random.key(6),
        ask=pgpe_ask, tell=pgpe_tell, fitness=sphere, popsize=40, num_generations=75,
    )
    assert float(means2[-1]) < float(means1[0])


def test_functional_snes_and_xnes():
    from evotorch_tpu.algorithms.functional import snes, snes_ask, snes_tell, xnes, xnes_ask, xnes_tell

    s = snes(center_init=jnp.full((6,), 3.0), objective_sense="min", stdev_init=1.0)
    s, _ = run_functional_search(
        s, jax.random.key(0),
        ask=snes_ask, tell=snes_tell, fitness=sphere, popsize=20, num_generations=150,
    )
    assert float(sphere(s.center[None])[0]) < 1e-3

    x = xnes(center_init=jnp.full((5,), 3.0), objective_sense="min", stdev_init=1.0)
    x, _ = run_functional_search(
        x, jax.random.key(1),
        ask=xnes_ask, tell=xnes_tell, fitness=sphere, popsize=20, num_generations=200,
    )
    assert float(sphere(x.center[None])[0]) < 1e-3


def test_batched_xnes_and_snes():
    from evotorch_tpu.algorithms.functional import snes, snes_ask, snes_tell, xnes, xnes_ask, xnes_tell

    targets = jnp.array([[0.0] * 4, [2.0] * 4])
    fitness = lambda pop: sphere(pop - targets[:, None, :])  # noqa: E731

    for init, ask, tell in (
        (snes, snes_ask, snes_tell),
        (xnes, xnes_ask, xnes_tell),
    ):
        state = init(center_init=jnp.ones((2, 4)), objective_sense="min", stdev_init=1.0)
        pop = ask(jax.random.key(0), state, popsize=16)
        assert pop.shape == (2, 16, 4)
        state, _ = run_functional_search(
            state, jax.random.key(1),
            ask=ask, tell=tell, fitness=fitness, popsize=16, num_generations=120,
        )
        assert np.allclose(np.asarray(state.center), np.asarray(targets), atol=0.5)


def test_batched_radius_init():
    from evotorch_tpu.algorithms.functional import snes, xnes

    s = snes(center_init=jnp.ones((2, 4)), objective_sense="min", radius_init=jnp.array([1.0, 2.0]))
    assert s.stdev.shape == (2, 4)
    assert np.allclose(np.asarray(s.stdev[:, 0]), [0.5, 1.0])
    x = xnes(center_init=jnp.ones((2, 4)), objective_sense="min", radius_init=jnp.array([1.0, 2.0]))
    assert x.A.shape == (2, 4, 4)
    assert np.allclose(np.asarray(x.A[1, 0, 0]), 1.0)


def test_functional_ga_single_objective():
    from evotorch_tpu.algorithms.functional import default_variation, ga, ga_ask, ga_tell

    key = jax.random.key(0)
    init = jax.random.uniform(key, (32, 6), minval=-5.0, maxval=5.0)
    # a fresh evaluated state enters lax.scan directly (constant treedef)
    state = ga(values_init=init, evals_init=sphere(init), objective_sense="min")
    variation = default_variation(tournament_size=4, mutation_stdev=0.2)

    @jax.jit
    def run(state, key):
        def gen(state, key):
            children = ga_ask(key, state, variation=variation)
            return ga_tell(state, children, sphere(children)), None

        return jax.lax.scan(gen, state, jax.random.split(key, 60))[0]

    state = run(state, jax.random.key(1))
    assert float(jnp.min(state.evals)) < 0.5


def test_functional_ga_multiobjective():
    from evotorch_tpu.algorithms.functional import default_variation, ga, ga_ask, ga_tell
    from evotorch_tpu.operators.functional import pareto_ranks

    def two_obj(xs):
        return jnp.stack([sphere(xs), sphere(xs - 2.0)], axis=-1)

    key = jax.random.key(2)
    init = jax.random.uniform(key, (24, 4), minval=-3.0, maxval=3.0)
    state = ga(values_init=init, evals_init=two_obj(init), objective_sense=["min", "min"])
    variation = default_variation(tournament_size=3, eta=10.0, mutation_stdev=0.1)
    for i in range(25):
        k = jax.random.key(10 + i)
        children = ga_ask(k, state, variation=variation)
        state = ga_tell(state, children, two_obj(children))
    ranks = np.asarray(pareto_ranks(state.evals, objective_sense=["min", "min"]))
    assert (ranks == 0).sum() >= len(ranks) // 2



def test_functional_ga_misuse():
    from evotorch_tpu.algorithms.functional import default_variation, ga

    with pytest.raises(ValueError):
        default_variation(num_points=3, eta=10.0)
    with pytest.raises(ValueError):
        ga(values_init=jnp.zeros(5), evals_init=jnp.zeros(5), objective_sense="min")
    with pytest.raises(ValueError):
        ga(values_init=jnp.zeros((4, 2)), evals_init=jnp.zeros(3), objective_sense="min")


def test_functional_api_with_problem_bound_evaluator():
    # the functional algorithms consume an OO Problem through
    # make_callable_evaluator (reference core.py:3309 bridge)
    from evotorch_tpu import Problem, vectorized
    from evotorch_tpu.algorithms.functional import snes, snes_ask, snes_tell

    @vectorized
    def rastrigin(x):
        return 10 * x.shape[-1] + jnp.sum(x**2 - 10 * jnp.cos(2 * jnp.pi * x), axis=-1)

    problem = Problem("min", rastrigin, solution_length=8, initial_bounds=(-5.12, 5.12), seed=0)
    f = problem.make_callable_evaluator()
    state = snes(center_init=problem.generate_values(1).reshape(-1), objective_sense="min", stdev_init=3.0)
    key = jax.random.key(0)
    first = None
    for _ in range(60):
        key, sub = jax.random.split(key)
        pop = snes_ask(sub, state, popsize=20)
        fits = f(pop)
        if first is None:
            first = float(jnp.mean(fits))
        state = snes_tell(state, pop, fits)
    assert float(jnp.mean(f(state.center[None]))) < first
    # best/worst tracking on the problem side kept working through the bridge
    assert "best_eval" in problem.status
