import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import tools
from evotorch_tpu.tools import misc


def test_to_jax_dtype():
    assert misc.to_jax_dtype("float32") == jnp.float32
    assert misc.to_jax_dtype(np.float32) == jnp.float32
    assert misc.to_jax_dtype(object) is object
    assert misc.to_jax_dtype("bool") == jnp.bool_
    assert misc.is_dtype_object(object)
    assert not misc.is_dtype_object("float32")
    assert misc.is_dtype_float("float32")
    assert misc.is_dtype_integer("int32")
    assert misc.is_dtype_real("int32") and misc.is_dtype_real("float32")
    assert misc.is_dtype_bool("bool")


def test_modify_tensor_max_change():
    original = jnp.array([1.0, 10.0, -10.0])
    target = jnp.array([2.0, 10.5, -20.0])
    out = misc.modify_tensor(original, target, max_change=0.2)
    # change limited to 20% of |original|
    assert np.allclose(np.asarray(out), [1.2, 10.5, -12.0])


def test_modify_tensor_bounds():
    original = jnp.array([0.0, 0.0])
    target = jnp.array([5.0, -5.0])
    out = misc.modify_tensor(original, target, lb=-1.0, ub=2.0)
    assert np.allclose(np.asarray(out), [2.0, -1.0])


def test_split_workload():
    assert misc.split_workload(10, 3) == [4, 3, 3]
    assert sum(misc.split_workload(113, 8)) == 113


def test_stdev_from_radius():
    assert misc.stdev_from_radius(4.0, 16) == pytest.approx(1.0)
    assert misc.to_stdev_init(solution_length=16, radius_init=4.0) == pytest.approx(1.0)
    assert misc.to_stdev_init(solution_length=16, stdev_init=0.5) == pytest.approx(0.5)
    with pytest.raises(ValueError):
        misc.to_stdev_init(solution_length=16)
    with pytest.raises(ValueError):
        misc.to_stdev_init(solution_length=16, stdev_init=1.0, radius_init=1.0)


def test_ensure_array_length_and_dtype():
    out = misc.ensure_array_length_and_dtype(3.0, 4, "float32")
    assert out.shape == (4,)
    out = misc.ensure_array_length_and_dtype([1, 2, 3], 3, "float32")
    assert out.dtype == jnp.float32
    with pytest.raises(ValueError):
        misc.ensure_array_length_and_dtype([1, 2], 3, "float32")


def test_erroneous_result():
    def boom():
        raise RuntimeError("nope")

    r = misc.ErroneousResult.call(boom)
    assert isinstance(r, misc.ErroneousResult)
    assert not r
    ok = misc.ErroneousResult.call(lambda: 5)
    assert ok == 5


def test_cast_arrays_in_container():
    container = {"a": jnp.zeros(3), "b": [jnp.ones(2, dtype=jnp.int32)]}
    out = misc.cast_arrays_in_container(container, dtype="float32")
    assert out["a"].dtype == jnp.float32
    assert out["b"][0].dtype == jnp.float32
    assert misc.dtype_of_container(out) == jnp.float32


def test_tensormaker():
    class Owner(tools.TensorMakerMixin):
        dtype = jnp.float32
        solution_length = 5

        def __init__(self):
            import jax

            self._key = jax.random.key(0)

        def next_rng_key(self):
            import jax

            self._key, sub = jax.random.split(self._key)
            return sub

    o = Owner()
    assert o.make_zeros(num_solutions=3).shape == (3, 5)
    assert o.make_ones().shape == (5,)
    assert bool(jnp.all(jnp.isnan(o.make_nan(2))))
    assert o.make_I().shape == (5, 5)
    u = o.make_uniform(num_solutions=10, lb=-1.0, ub=1.0)
    assert u.shape == (10, 5)
    assert float(jnp.min(u)) >= -1.0 and float(jnp.max(u)) <= 1.0
    g = o.make_gaussian(num_solutions=4, center=2.0, stdev=0.0)
    assert np.allclose(np.asarray(g), 2.0)
    sym = o.make_gaussian(num_solutions=4, symmetric=True)
    # antithetic pairs are interleaved: [+e0, -e0, +e1, -e1]
    assert np.allclose(np.asarray(sym[0::2]), -np.asarray(sym[1::2]))
    ri = o.make_randint(num_solutions=6, n=3)
    assert int(jnp.min(ri)) >= 0 and int(jnp.max(ri)) < 3
    # make_tensor (reference tensormaker.py:142): owner dtype by default
    t = o.make_tensor([[1, 2], [3, 4]])
    assert t.dtype == jnp.float32 and t.shape == (2, 2)
    assert o.make_tensor([1], dtype=jnp.int32).dtype == jnp.int32
    obj = o.make_tensor(["a_string", (1, 2)], dtype=object)
    assert len(obj) == 2 and obj[0] == "a_string"
    ro = o.make_tensor(["x"], dtype=object, read_only=True)
    assert ro.is_read_only
    # *_shaped_like (reference tensormaker.py:866,893)
    template = jnp.zeros((3, 2), dtype=jnp.float32)
    us = o.make_uniform_shaped_like(template, lb=0.5, ub=1.5)
    assert us.shape == (3, 2) and float(jnp.min(us)) >= 0.5
    gs = o.make_gaussian_shaped_like(template, center=7.0, stdev=0.0)
    assert gs.shape == (3, 2) and np.allclose(np.asarray(gs), 7.0)


def test_ensure_array_object_dtype():
    from evotorch_tpu.tools import ObjectArray

    out = misc.ensure_array_length_and_dtype([[1, 2], "x", None], 3, object)
    assert isinstance(out, ObjectArray)
    assert len(out) == 3
    with pytest.raises(ValueError):
        misc.ensure_array_length_and_dtype([1, 2], 3, object)


def test_tensormaker_eval_dtype():
    class Owner(tools.TensorMakerMixin):
        dtype = jnp.bfloat16
        eval_dtype = jnp.float32
        solution_length = 4

        def next_rng_key(self):
            import jax

            return jax.random.key(0)

    o = Owner()
    assert o.make_zeros(num_solutions=2).dtype == jnp.bfloat16
    assert o.make_zeros(num_solutions=2, use_eval_dtype=True).dtype == jnp.float32
    assert o.make_uniform(num_solutions=2, use_eval_dtype=True).dtype == jnp.float32


def test_ensure_array_object_scalar_payloads():
    from evotorch_tpu.tools import ObjectArray

    out = misc.ensure_array_length_and_dtype(5, 3, object)
    assert isinstance(out, ObjectArray) and list(out) == [5, 5, 5]
    payload = {"a": 1}
    out = misc.ensure_array_length_and_dtype(payload, 2, object)
    assert out[0]["a"] == 1 and out[1]["a"] == 1
