import pytest

from evotorch_tpu.tools import Hook


def test_hook_accumulates_dicts():
    h = Hook()
    h.append(lambda: {"a": 1})
    h.append(lambda: {"b": 2})
    h.append(lambda: None)
    assert h() == {"a": 1, "b": 2}
    assert h.accumulate_dict() == {"a": 1, "b": 2}


def test_hook_accumulates_lists():
    h = Hook([lambda: [1, 2], lambda: [3]])
    assert h() == [1, 2, 3]
    assert h.accumulate_sequence() == [1, 2, 3]


def test_hook_mixed_results_error():
    h = Hook([lambda: {"a": 1}, lambda: [2]])
    with pytest.raises(TypeError):
        h()


def test_hook_args_kwargs_passed():
    seen = []
    h = Hook([lambda x, y=0: seen.append((x, y))], args=[10], kwargs={"y": 5})
    h()
    assert seen == [(10, 5)]


def test_hook_is_mutable_sequence():
    h = Hook()
    f = lambda: None  # noqa: E731
    h.append(f)
    assert len(h) == 1 and h[0] is f
    h.insert(0, f)
    assert len(h) == 2
    del h[0]
    assert len(h) == 1


def test_hook_empty_returns_none():
    assert Hook()() is None
    assert Hook().accumulate_dict() == {}
