import os
import pickle

import jax.numpy as jnp
import pytest

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms.gaussian import SNES
from evotorch_tpu.logging import PandasLogger, PicklingLogger, StdOutLogger


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def make_searcher():
    p = Problem("min", sphere, solution_length=5, initial_bounds=(-3, 3), seed=0)
    return SNES(p, stdev_init=2.0)


def test_stdout_logger(capsys):
    s = make_searcher()
    StdOutLogger(s)
    s.run(2)
    out = capsys.readouterr().out
    assert "iter" in out
    assert "mean_eval" in out


def test_stdout_logger_interval(capsys):
    s = make_searcher()
    StdOutLogger(s, interval=2)
    s.run(4)
    out = capsys.readouterr().out
    assert out.count("iter") == 2


def test_pandas_logger():
    s = make_searcher()
    logger = PandasLogger(s)
    s.run(5)
    frame = logger.to_dataframe()
    assert len(frame) == 5
    assert "mean_eval" in frame.columns


def test_pickling_logger(tmp_path):
    s = make_searcher()
    logger = PicklingLogger(s, interval=2, directory=str(tmp_path), verbose=False)
    s.run(4)
    assert logger.last_file_name is not None
    payload = logger.unpickle_last_file()
    assert "center" in payload
    assert payload["iter"] == 4
    # a final save fires at end_of_run
    files = [f for f in os.listdir(tmp_path) if f.endswith(".pickle")]
    assert len(files) >= 2


def test_scalar_filtering():
    s = make_searcher()
    logger = PandasLogger(s)
    s.run(1)
    row = logger._data[0]
    # non-scalar entries (center vector, best Solution) are filtered out
    assert "center" not in row
    assert "best" not in row
    assert isinstance(row["best_eval"], float)
