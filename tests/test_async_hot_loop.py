"""The OO hot loop must not force device->host syncs (VERDICT r1 item 6).

``jax.transfer_guard_device_to_host("disallow")`` turns any device->host pull
into an error — a *stronger* assertion than inspecting a profiler trace:
best/worst tracking, mean_eval, and rollout counters must all stay on device
until a status entry is actually read.
"""

import jax
import jax.numpy as jnp
import numpy as np

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms import PGPE


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def test_pgpe_steps_make_no_device_to_host_transfers():
    p = Problem("min", sphere, solution_length=8, initial_bounds=(-1, 1))
    s = PGPE(
        p, popsize=16, center_learning_rate=0.3, stdev_learning_rate=0.1, stdev_init=1.0
    )
    s.step()  # compile outside the guard
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(5):
            s.step()
    # ...and the lazily-materialized statuses are still correct afterwards
    status = s.status
    assert np.isfinite(status["mean_eval"])
    assert status["best_eval"] <= status["worst_eval"]
    best = status["best"]
    assert np.isclose(
        float(np.sum(np.asarray(best.values) ** 2)), status["best_eval"], atol=1e-5
    )


def test_vecne_rollout_steps_make_no_device_to_host_transfers():
    from evotorch_tpu.neuroevolution import VecNE

    p = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        episode_length=20,
        observation_normalization=True,
    )
    s = PGPE(
        p, popsize=16, center_learning_rate=0.3, stdev_learning_rate=0.1, stdev_init=0.3
    )
    s.step()
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            s.step()
    assert int(p.status["total_interaction_count"]) > 0
    assert int(p.status["total_episode_count"]) > 0


def test_best_status_not_ready_until_valid_eval():
    # review regression: an all-NaN first evaluation must not surface a bogus
    # zeros best solution — the entries stay "not ready" (absent-like) until
    # a real fitness arrives, matching the host/object-dtype path's contract
    calls = {"n": 0}

    @vectorized
    def flaky(xs):
        calls["n"] += 1
        if calls["n"] == 1:
            return jnp.full(xs.shape[0], jnp.nan)
        return jnp.sum(xs**2, axis=-1)

    p = Problem("min", flaky, solution_length=3, initial_bounds=(-1, 1))
    p.evaluate(p.generate_batch(4))
    assert p.status.get("best") is None
    assert p.status.get("best_eval") is None
    assert dict(p.status.items()) is not None  # iteration skips not-ready keys
    p.evaluate(p.generate_batch(4))
    assert np.isfinite(p.status["best_eval"])
    assert p.status["best"] is not None


def test_run_with_profile_dir_writes_trace(tmp_path):
    p = Problem("min", sphere, solution_length=4, initial_bounds=(-1, 1))
    s = PGPE(
        p, popsize=8, center_learning_rate=0.3, stdev_learning_rate=0.1, stdev_init=1.0
    )
    profile_dir = tmp_path / "trace"
    s.run(3, profile_dir=str(profile_dir))
    # jax.profiler.trace writes plugins/profile/<ts>/*; assert non-empty capture
    captured = list(profile_dir.rglob("*"))
    assert any(f.is_file() for f in captured)
