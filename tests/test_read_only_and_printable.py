import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools.readonlytensor import (
    ReadOnlyTensor,
    as_read_only_tensor,
    is_read_only,
    read_only_tensor,
)
from evotorch_tpu.tools.recursiveprintable import RecursivePrintable


# reference test_read_only_tensor.py analog: on TPU the discipline is
# immutability-by-construction, so the checks are about coercion semantics


def test_jax_arrays_are_read_only():
    x = jnp.ones(3)
    assert isinstance(x, ReadOnlyTensor)
    assert is_read_only(x)
    assert as_read_only_tensor(x) is x


def test_numpy_becomes_unwritable_view():
    arr = np.arange(4.0)
    view = as_read_only_tensor(arr)
    assert is_read_only(view)
    with pytest.raises(ValueError):
        view[0] = 9.0
    # the original stays writable; the view shares storage
    arr[0] = 5.0
    assert view[0] == 5.0


def test_read_only_tensor_copies():
    out = read_only_tensor([1.0, 2.0])
    assert is_read_only(out)
    assert out.shape == (2,)


def test_recursive_printable():
    class Thing(RecursivePrintable):
        def _printable_items(self):
            return {"a": 1, "nested": [1, {"b": 2}]}

    s = str(Thing())
    assert "Thing" in s and "a=1" in s and "'b': 2" in s

    class Looper(RecursivePrintable):
        def _printable_items(self):
            return {"self": self}

    # bounded depth: no infinite recursion
    s = Looper().to_string(max_depth=3)
    assert "<...>" in s
