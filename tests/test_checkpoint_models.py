import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms.functional import pgpe, pgpe_ask, pgpe_tell, snes, snes_ask, snes_tell
from evotorch_tpu.checkpoint import load_searcher, load_state, save_searcher, save_state
from evotorch_tpu.models import LSTMPolicy, MLPPolicy, RNNPolicy, locomotor_policy
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy


def sphere(x):
    return jnp.sum(x**2, axis=-1)


def test_models_factories():
    for factory in (MLPPolicy, RNNPolicy, LSTMPolicy, locomotor_policy):
        net = factory(4, 2)
        policy = FlatParamsPolicy(net)
        flat = policy.init_parameters(jax.random.key(0))
        y, _ = policy(flat, jnp.ones(4))
        assert y.shape == (2,)


def test_mlp_policy_hidden_config():
    net = MLPPolicy(3, 1, hidden=(8,))
    policy = FlatParamsPolicy(net)
    assert policy.parameter_count == 3 * 8 + 8 + 8 * 1 + 1


def test_functional_state_checkpoint_roundtrip(tmp_path):
    state = pgpe(
        center_init=jnp.full((5,), 2.0),
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        objective_sense="min",
        stdev_init=1.0,
    )
    key = jax.random.key(0)
    for _ in range(5):
        key, sub = jax.random.split(key)
        pop = pgpe_ask(sub, state, popsize=20)
        state = pgpe_tell(state, pop, sphere(pop))

    path = os.path.join(tmp_path, "pgpe_state")
    save_state(path, state)
    template = pgpe(
        center_init=jnp.zeros(5),
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        objective_sense="min",
        stdev_init=1.0,
    )
    restored = load_state(path, template)
    assert np.allclose(
        np.asarray(restored.optimizer_state.center), np.asarray(state.optimizer_state.center)
    )
    assert restored.maximize == state.maximize
    # the restored state continues the run seamlessly
    key, sub = jax.random.split(key)
    pop = pgpe_ask(sub, restored, popsize=20)
    restored = pgpe_tell(restored, pop, sphere(pop))


@vectorized
def _sphere_fitness(xs):
    return jnp.sum(xs**2, axis=-1)


def test_searcher_pickle_checkpoint(tmp_path):
    from evotorch_tpu.algorithms import SNES

    p = Problem("min", _sphere_fitness, solution_length=6, initial_bounds=(-3, 3), seed=0)
    searcher = SNES(p, stdev_init=2.0)
    searcher.run(5)
    best_before = searcher.status["best_eval"]

    path = os.path.join(tmp_path, "searcher.pkl")
    save_searcher(path, searcher)
    restored = load_searcher(path)
    assert restored.step_count == 5
    assert restored.status["best_eval"] == best_before
    restored.run(5)
    assert restored.step_count == 10
    assert restored.status["best_eval"] <= best_before


def test_step_seconds_in_status():
    @vectorized
    def fitness(xs):
        return jnp.sum(xs**2, axis=-1)

    from evotorch_tpu.algorithms import CEM

    p = Problem("min", fitness, solution_length=4, initial_bounds=(-1, 1))
    s = CEM(p, popsize=10, parenthood_ratio=0.5, stdev_init=1.0)
    s.step()
    assert s.status["step_seconds"] > 0


def test_ne_searcher_pickles_whole():
    # VecNE problems (with env + flat-params policy inside) checkpoint whole
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE("pendulum", "Linear(obs_length, act_length)", episode_length=10, seed=0)
    searcher = PGPE(
        problem, popsize=8, center_learning_rate=0.3, stdev_learning_rate=0.1, stdev_init=0.3
    )
    searcher.run(2)
    import pickle

    restored = pickle.loads(pickle.dumps(searcher))
    restored.run(2)
    assert restored.step_count == 4
