"""Step-for-step dynamics parity between the pure-JAX envs and gymnasium's
reference implementations: from identical physical states and identical
action sequences, trajectories must match numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from evotorch_tpu.envs import CartPole, Pendulum
from evotorch_tpu.tools.pytree import replace


def test_cartpole_dynamics_match_gymnasium():
    ref = gym.make("CartPole-v1").unwrapped
    ours = CartPole()
    rng = np.random.default_rng(0)

    ref.reset(seed=0)
    start = np.asarray(ref.state, dtype=np.float64)
    state, _ = ours.reset(jax.random.key(0))
    state = replace(state, obs_state=jnp.asarray(start, dtype=jnp.float32))

    for t in range(60):
        action = int(rng.integers(0, 2))
        ref_obs, _, ref_term, _, _ = ref.step(action)
        state, obs, _, done = ours.step(state, jnp.asarray(action))
        assert np.allclose(np.asarray(obs), ref_obs, atol=1e-4), f"diverged at step {t}"
        if ref_term:
            assert bool(done)
            break


def test_pendulum_dynamics_match_gymnasium():
    ref = gym.make("Pendulum-v1").unwrapped
    ours = Pendulum()
    rng = np.random.default_rng(1)

    ref.reset(seed=0)
    th, thdot = np.asarray(ref.state, dtype=np.float64)
    state, _ = ours.reset(jax.random.key(0))
    state = replace(state, obs_state=jnp.asarray([th, thdot], dtype=jnp.float32))

    for t in range(80):
        action = rng.uniform(-2.0, 2.0, size=(1,))
        ref_obs, ref_reward, _, _, _ = ref.step(action)
        state, obs, reward, _ = ours.step(state, jnp.asarray(action, dtype=jnp.float32))
        assert np.allclose(np.asarray(obs), ref_obs, atol=1e-3), f"obs diverged at step {t}"
        assert abs(float(reward) - float(ref_reward)) < 1e-3, f"reward diverged at step {t}"


def test_acrobot_dynamics_match_gymnasium():
    from evotorch_tpu.envs import Acrobot

    ref = gym.make("Acrobot-v1").unwrapped
    ours = Acrobot()
    rng = np.random.default_rng(2)

    ref.reset(seed=0)
    start = np.asarray(ref.state, dtype=np.float64)
    state, _ = ours.reset(jax.random.key(0))
    state = replace(state, obs_state=jnp.asarray(start, dtype=jnp.float32))

    for t in range(40):
        action = int(rng.integers(0, 3))
        ref_obs, *_ = ref.step(action)
        state, obs, _, _ = ours.step(state, jnp.asarray(action))
        assert np.allclose(np.asarray(obs), ref_obs, atol=1e-4), f"diverged at step {t}"


def test_mountain_car_dynamics_match_gymnasium():
    from evotorch_tpu.envs import MountainCarContinuous

    ref = gym.make("MountainCarContinuous-v0").unwrapped
    ours = MountainCarContinuous()
    rng = np.random.default_rng(3)

    ref.reset(seed=0)
    start = np.asarray(ref.state, dtype=np.float64)
    state, _ = ours.reset(jax.random.key(0))
    state = replace(state, obs_state=jnp.asarray(start, dtype=jnp.float32))

    for t in range(50):
        action = rng.uniform(-1.0, 1.0, size=(1,))
        ref_obs, *_ = ref.step(action)
        state, obs, _, _ = ours.step(state, jnp.asarray(action, dtype=jnp.float32))
        assert np.allclose(np.asarray(obs), ref_obs, atol=1e-4), f"diverged at step {t}"
