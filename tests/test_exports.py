"""Every public export listed in an ``__all__`` must resolve — guards broken
re-export lists across the package."""

import importlib
import pkgutil

import evotorch_tpu


def _walk_modules():
    yield evotorch_tpu
    for info in pkgutil.walk_packages(evotorch_tpu.__path__, prefix="evotorch_tpu."):
        yield importlib.import_module(info.name)


def test_all_exports_resolve():
    checked = 0
    for mod in _walk_modules():
        for name in getattr(mod, "__all__", ()):
            assert hasattr(mod, name), f"{mod.__name__}.__all__ lists missing name {name!r}"
            checked += 1
    assert checked > 200  # the public surface is large; a collapse would show


def test_reference_entry_symbols():
    # the reference package entry re-exports these (SURVEY §1)
    for name in ("Problem", "Solution", "SolutionBatch", "ProblemBoundEvaluator"):
        assert hasattr(evotorch_tpu, name)
