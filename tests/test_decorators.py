import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import expects_ndim, rowwise, vectorized


def test_vectorized_marker():
    @vectorized
    def f(x):
        return x

    assert f.__evotorch_vectorized__


def test_expects_ndim_no_batch():
    @expects_ndim(1, 1)
    def dot(a, b):
        return jnp.sum(a * b)

    out = dot(jnp.array([1.0, 2.0]), jnp.array([3.0, 4.0]))
    assert float(out) == pytest.approx(11.0)


def test_expects_ndim_batched_first_arg():
    @expects_ndim(1, 1)
    def dot(a, b):
        return jnp.sum(a * b)

    a = jnp.array([[1.0, 2.0], [0.0, 1.0]])
    b = jnp.array([3.0, 4.0])
    out = dot(a, b)
    assert out.shape == (2,)
    assert np.allclose(np.asarray(out), [11.0, 4.0])


def test_expects_ndim_broadcast_batches():
    @expects_ndim(1, 0)
    def scale(v, s):
        return v * s

    v = jnp.ones((2, 3, 4))  # batch (2, 3), core (4,)
    s = jnp.array([1.0, 2.0, 3.0])  # batch (3,), core ()
    out = scale(v, s)
    assert out.shape == (2, 3, 4)
    assert np.allclose(np.asarray(out[:, 1]), 2.0)


def test_expects_ndim_static_arg():
    @expects_ndim(1, None)
    def top(v, mode):
        assert isinstance(mode, str)
        return jnp.max(v) if mode == "max" else jnp.min(v)

    v = jnp.arange(12.0).reshape(3, 4)
    out = top(v, "max")
    assert out.shape == (3,)
    assert np.allclose(np.asarray(out), [3.0, 7.0, 11.0])


def test_expects_ndim_too_small():
    @expects_ndim(2)
    def f(m):
        return jnp.sum(m)

    with pytest.raises(ValueError):
        f(jnp.ones(3))


def test_rowwise():
    @rowwise
    def norm(x):
        return jnp.sqrt(jnp.sum(x**2))

    assert float(norm(jnp.array([3.0, 4.0]))) == pytest.approx(5.0)
    batched = norm(jnp.ones((5, 4, 9)))
    assert batched.shape == (5, 4)
    assert norm.__evotorch_vectorized__


# -- expects_ndim kwargs participation + coercion (reference 613-874) --------


def test_expects_ndim_kwargs_participate():
    from evotorch_tpu.decorators import expects_ndim

    @expects_ndim(1, 0)
    def scaled_norm(x, scale):
        return scale * jnp.sum(x * x)

    x = jnp.ones((4, 3))  # batch of 4 rows
    # scale passed by keyword must still batch against its declared ndim
    out = scaled_norm(x, scale=jnp.asarray([1.0, 2.0, 3.0, 4.0]))
    assert out.shape == (4,)
    np.testing.assert_allclose(np.asarray(out), [3.0, 6.0, 9.0, 12.0])
    # both by keyword, out of order
    out2 = scaled_norm(scale=jnp.asarray(2.0), x=x)
    np.testing.assert_allclose(np.asarray(out2), 6.0)


def test_expects_ndim_kwargs_with_defaults_and_static():
    from evotorch_tpu.decorators import expects_ndim

    @expects_ndim(1, 0)
    def f(x, scale=2.0, *, mode="sum"):
        agg = jnp.sum if mode == "sum" else jnp.max
        return scale * agg(x)

    x = jnp.ones((3, 2))
    np.testing.assert_allclose(np.asarray(f(x)), [4.0, 4.0, 4.0])
    np.testing.assert_allclose(np.asarray(f(x, mode="max")), [2.0, 2.0, 2.0])


def test_expects_ndim_scalar_coercion_follows_float_dtype():
    from evotorch_tpu.decorators import expects_ndim

    seen = {}

    @expects_ndim(1, 0)
    def f(x, s):
        seen["s_dtype"] = s.dtype
        return x * s

    x16 = jnp.ones(3, dtype=jnp.bfloat16)
    out = f(x16, 0.5)  # python float adopts the array's dtype
    assert seen["s_dtype"] == jnp.bfloat16
    assert out.dtype == jnp.bfloat16

    # numpy float64 input likewise follows the jax argument's dtype
    f(jnp.ones(3, dtype=jnp.float32), np.float64(0.25))
    assert seen["s_dtype"] == jnp.float32

    # integer scalars are not forced to float
    @expects_ndim(1, 0)
    def g(x, n):
        seen["n_dtype"] = n.dtype
        return x * n

    g(jnp.ones(3), 4)
    assert jnp.issubdtype(seen["n_dtype"], jnp.integer)


def test_expects_ndim_kwargs_batched_search():
    # the batched-searches pattern with keyword call style: a (B, L) center
    # batch against a per-search stdev batch
    from evotorch_tpu.decorators import expects_ndim

    @expects_ndim(1, 1)
    def quad(center, stdev):
        return jnp.sum(center**2) + jnp.sum(stdev)

    out = quad(
        center=jnp.ones((2, 5)),
        stdev=jnp.stack([jnp.full(5, 0.1), jnp.full(5, 0.2)]),
    )
    assert out.shape == (2,)
    np.testing.assert_allclose(np.asarray(out), [5.5, 6.0], atol=1e-6)


def test_expects_ndim_varargs_function_with_kwargs():
    # review regression: a *args-bearing function called with a keyword must
    # not trip the VAR_POSITIONAL guard (apply_defaults inserts an empty tuple)
    from evotorch_tpu.decorators import expects_ndim

    @expects_ndim(1, 0)
    def f(x, s, *extra):
        return s * jnp.sum(x)

    out = f(jnp.ones((2, 3)), s=jnp.asarray(2.0))
    np.testing.assert_allclose(np.asarray(out), [6.0, 6.0])
