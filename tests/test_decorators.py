import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import expects_ndim, rowwise, vectorized


def test_vectorized_marker():
    @vectorized
    def f(x):
        return x

    assert f.__evotorch_vectorized__


def test_expects_ndim_no_batch():
    @expects_ndim(1, 1)
    def dot(a, b):
        return jnp.sum(a * b)

    out = dot(jnp.array([1.0, 2.0]), jnp.array([3.0, 4.0]))
    assert float(out) == pytest.approx(11.0)


def test_expects_ndim_batched_first_arg():
    @expects_ndim(1, 1)
    def dot(a, b):
        return jnp.sum(a * b)

    a = jnp.array([[1.0, 2.0], [0.0, 1.0]])
    b = jnp.array([3.0, 4.0])
    out = dot(a, b)
    assert out.shape == (2,)
    assert np.allclose(np.asarray(out), [11.0, 4.0])


def test_expects_ndim_broadcast_batches():
    @expects_ndim(1, 0)
    def scale(v, s):
        return v * s

    v = jnp.ones((2, 3, 4))  # batch (2, 3), core (4,)
    s = jnp.array([1.0, 2.0, 3.0])  # batch (3,), core ()
    out = scale(v, s)
    assert out.shape == (2, 3, 4)
    assert np.allclose(np.asarray(out[:, 1]), 2.0)


def test_expects_ndim_static_arg():
    @expects_ndim(1, None)
    def top(v, mode):
        assert isinstance(mode, str)
        return jnp.max(v) if mode == "max" else jnp.min(v)

    v = jnp.arange(12.0).reshape(3, 4)
    out = top(v, "max")
    assert out.shape == (3,)
    assert np.allclose(np.asarray(out), [3.0, 7.0, 11.0])


def test_expects_ndim_too_small():
    @expects_ndim(2)
    def f(m):
        return jnp.sum(m)

    with pytest.raises(ValueError):
        f(jnp.ones(3))


def test_rowwise():
    @rowwise
    def norm(x):
        return jnp.sqrt(jnp.sum(x**2))

    assert float(norm(jnp.array([3.0, 4.0]))) == pytest.approx(5.0)
    batched = norm(jnp.ones((5, 4, 9)))
    assert batched.shape == (5, 4)
    assert norm.__evotorch_vectorized__
