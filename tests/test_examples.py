"""Examples smoke tier (mirrors reference ``tests/test_examples.py``): run
every ``examples/*.py`` end-to-end on the CPU backend at minimal sizes, so
doc rot in the examples becomes detectable instead of silently accumulating.

Each script runs in a subprocess (its own backend setup — the examples pick
their platform before first device use) with ``cwd`` in a temp directory, so
artifacts the examples write (solution pickles, curve JSONLs) never land in
the repo. ``rl_enjoy`` consumes the pickle ``rl_clipup`` saves, so the two
are chained into one case.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")

# script -> extra args beyond the common `--cpu --generations N` smoke knobs
CASES = {
    "bbo_vectorized.py": [],
    "functional_batched_search.py": [],
    "humanoid_pgpe.py": [],
    "locomotion_curve.py": [
        "--env", "hopper", "--popsize", "8", "--episode-length", "5",
        "--eval-every", "1", "--eval-episodes", "2",
    ],
    "mapelites_illumination.py": [],
    "moo_pareto.py": [],
    "mpc_cem.py": [],
    "mujoco_curve.py": [  # real-MuJoCo backend; skipped where mujoco is absent
        "--env", "InvertedPendulum-v5", "--popsize", "6", "--num-envs", "4",
        "--episode-length", "20", "--eval-every", "1", "--eval-episodes", "1",
    ],
    "object_dtype_ga.py": [],
    "rl_clipup.py": [],  # + rl_enjoy on its saved solution, below
    "wide_policy_lowrank.py": [],
}


def _run_example(script, extra, cwd, generations="2"):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, script), "--cpu",
         "--generations", generations, *extra],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (
        f"{script} failed (rc={proc.returncode})\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}"
    )
    return proc


def test_examples_directory_is_covered():
    # a new example must either join CASES or be excluded here on purpose
    scripts = {
        f for f in os.listdir(EXAMPLES_DIR)
        if f.endswith(".py") and not f.startswith("_")
    }
    assert scripts == set(CASES) | {"rl_enjoy.py"}, scripts


@pytest.mark.parametrize("script", sorted(CASES))
def test_example_smoke(script, tmp_path):
    if script == "mujoco_curve.py":
        pytest.importorskip("mujoco")
    _run_example(script, CASES[script], str(tmp_path))
    if script == "rl_clipup.py":
        # the companion example: replay the solution rl_clipup just saved
        assert (tmp_path / "rl_clipup_solution.pkl").exists()
        proc = _run_example(
            "rl_enjoy.py", ["--solution", "rl_clipup_solution.pkl"], str(tmp_path)
        )
        assert "episodic return" in proc.stdout
