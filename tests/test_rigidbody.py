"""Unit tests for the maximal-coordinates rigid-body engine and the Humanoid.

The engine is the substrate of the flagship workload (see
``evotorch_tpu/envs/rigidbody.py``); these tests pin down the math kernels
(quaternions), conservation-level dynamics sanity (free fall, constraint
integrity), and the Humanoid env contract (protocol, metastable standing,
fall termination, vmap/jit).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import Humanoid, make_env
from evotorch_tpu.envs.rigidbody import (
    BodyState,
    SystemBuilder,
    capsule_inertia,
    joint_angles,
    physics_step,
    quat_conj,
    quat_integrate,
    quat_mul,
    quat_rotate,
    quat_rotate_inv,
    quat_to_rotvec,
    sphere_inertia,
)


def _quat_from_axis_angle(axis, angle):
    axis = np.asarray(axis, dtype=np.float64)
    axis = axis / np.linalg.norm(axis)
    return jnp.asarray(
        np.concatenate([[np.cos(angle / 2)], np.sin(angle / 2) * axis]),
        dtype=jnp.float32,
    )


class TestQuaternions:
    def test_mul_identity(self):
        q = _quat_from_axis_angle([0, 0, 1], 0.7)
        e = jnp.asarray([1.0, 0, 0, 0])
        np.testing.assert_allclose(np.asarray(quat_mul(e, q)), np.asarray(q), atol=1e-6)
        np.testing.assert_allclose(np.asarray(quat_mul(q, e)), np.asarray(q), atol=1e-6)

    def test_rotate_matches_known_rotation(self):
        # 90 deg about z sends x to y
        q = _quat_from_axis_angle([0, 0, 1], np.pi / 2)
        v = jnp.asarray([1.0, 0.0, 0.0])
        np.testing.assert_allclose(
            np.asarray(quat_rotate(q, v)), [0.0, 1.0, 0.0], atol=1e-6
        )

    def test_rotate_inv_roundtrip(self):
        key = jax.random.key(0)
        q = jax.random.normal(key, (5, 4))
        q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
        v = jax.random.normal(jax.random.key(1), (5, 3))
        back = quat_rotate_inv(q, quat_rotate(q, v))
        np.testing.assert_allclose(np.asarray(back), np.asarray(v), atol=1e-5)

    def test_conj_is_inverse(self):
        q = _quat_from_axis_angle([1, 2, 3], 0.9)
        e = quat_mul(q, quat_conj(q))
        np.testing.assert_allclose(np.asarray(e), [1, 0, 0, 0], atol=1e-6)

    def test_rotvec_roundtrip(self):
        for axis, angle in [([0, 0, 1], 0.3), ([1, 0, 0], 1.2), ([1, 1, 0], 2.0)]:
            q = _quat_from_axis_angle(axis, angle)
            rv = np.asarray(quat_to_rotvec(q))
            expected = np.asarray(axis, dtype=np.float64)
            expected = expected / np.linalg.norm(expected) * angle
            np.testing.assert_allclose(rv, expected, atol=1e-5)

    def test_rotvec_identity_is_zero(self):
        rv = quat_to_rotvec(jnp.asarray([1.0, 0.0, 0.0, 0.0]))
        np.testing.assert_allclose(np.asarray(rv), [0, 0, 0], atol=1e-7)

    def test_rotvec_takes_shortest_arc(self):
        # q and -q are the same rotation; rotvec must not return a >pi arc
        q = _quat_from_axis_angle([0, 0, 1], 0.5)
        rv_neg = np.asarray(quat_to_rotvec(-q))
        np.testing.assert_allclose(rv_neg, [0, 0, 0.5], atol=1e-5)

    def test_integrate_constant_rate(self):
        # integrating omega = (0,0,w) for t seconds yields angle ~ w*t
        q = jnp.asarray([1.0, 0.0, 0.0, 0.0])
        omega = jnp.asarray([0.0, 0.0, 2.0])
        h = 0.001
        for _ in range(500):
            q = quat_integrate(q, omega, h)
        angle = float(jnp.linalg.norm(quat_to_rotvec(q)))
        assert abs(angle - 1.0) < 1e-2

    def test_inertia_helpers(self):
        c = capsule_inertia(2.0, 0.1, 0.4, "z")
        assert c[0] == c[1] and c[2] == pytest.approx(0.5 * 2.0 * 0.01)
        s = sphere_inertia(1.0, 0.1)
        assert np.allclose(s, 0.4 * 1.0 * 0.01)


def _single_body_system():
    b = SystemBuilder()
    b.add_body("ball", (0, 0, 2.0), 1.0, sphere_inertia(1.0, 0.1))
    b.add_sphere("ball", (0, 0, 2.0), 0.1)
    return b.build()


class TestEngine:
    def test_free_fall_parabola(self):
        sys_, pos0 = _single_body_system()
        st = BodyState(
            pos=pos0,
            quat=jnp.asarray([[1.0, 0, 0, 0]]),
            vel=jnp.zeros((1, 3)),
            ang=jnp.zeros((1, 3)),
        )
        t, dt, sub = 0.5, 0.01, 4
        step = jax.jit(lambda s: physics_step(sys_, s, jnp.zeros(0), dt, sub))
        for _ in range(int(t / dt)):
            st = step(st)
        # z = z0 - g t^2 / 2 (semi-implicit Euler is first-order accurate)
        expected = 2.0 - 0.5 * 9.81 * t**2
        assert abs(float(st.pos[0, 2]) - expected) < 0.05

    def test_ground_contact_stops_fall(self):
        sys_, pos0 = _single_body_system()
        st = BodyState(
            pos=pos0.at[0, 2].set(0.3),
            quat=jnp.asarray([[1.0, 0, 0, 0]]),
            vel=jnp.zeros((1, 3)),
            ang=jnp.zeros((1, 3)),
        )
        step = jax.jit(lambda s: physics_step(sys_, s, jnp.zeros(0), 0.01, 4))
        for _ in range(200):
            st = step(st)
        # rests near the surface: sphere radius 0.1 minus static penetration
        z = float(st.pos[0, 2])
        assert 0.05 < z < 0.12
        assert abs(float(st.vel[0, 2])) < 0.05

    def test_pendulum_joint_holds(self):
        # one body hanging from a fixed-ish heavy anchor body by a hinge:
        # anchor separation must stay small while the pendulum swings
        b = SystemBuilder()
        b.add_body("anchor", (0, 0, 2.0), 1000.0, sphere_inertia(1000.0, 0.5))
        b.add_body("bob", (0, 0, 1.5), 1.0, capsule_inertia(1.0, 0.05, 0.5, "z"))
        b.add_joint(
            "anchor", "bob", (0, 0, 1.75),
            free_axes=("y",), limits=[(-3.0, 3.0)], gears=(0.0,), tone=0.0,
        )
        sys_, pos0 = b.build()
        st = BodyState(
            pos=pos0,
            quat=jnp.tile(jnp.asarray([1.0, 0, 0, 0]), (2, 1)),
            vel=jnp.asarray([[0, 0, 0], [1.0, 0, 0]]),  # kick the bob
            ang=jnp.zeros((2, 3)),
        )
        from evotorch_tpu.envs.rigidbody import quat_rotate as qr

        step = jax.jit(lambda s: physics_step(sys_, s, jnp.zeros(1), 0.01, 8))
        peak = 0.0
        for _ in range(100):
            st = step(st)
            pa = st.pos[0] + qr(st.quat[0], sys_.anchor_p[0])
            pb = st.pos[1] + qr(st.quat[1], sys_.anchor_c[0])
            assert float(jnp.linalg.norm(pb - pa)) < 0.02
            peak = max(peak, abs(float(joint_angles(sys_, st)[0, 1])))
        # the bob should actually have swung
        assert peak > 0.05

    def test_actuation_position_vs_torque(self):
        def build(mode):
            b = SystemBuilder(act_mode=mode)
            b.add_body("anchor", (0, 0, 2.0), 1000.0, sphere_inertia(1000.0, 0.5))
            b.add_body("bob", (0, 0, 1.5), 1.0, capsule_inertia(1.0, 0.05, 0.5, "z"))
            b.add_joint(
                "anchor", "bob", (0, 0, 1.75),
                free_axes=("y",), limits=[(-1.0, 1.0)], gears=(30.0,),
            )
            return b.build()

        for mode in ("position", "torque"):
            sys_, pos0 = build(mode)
            st = BodyState(
                pos=pos0,
                quat=jnp.tile(jnp.asarray([1.0, 0, 0, 0]), (2, 1)),
                vel=jnp.zeros((2, 3)),
                ang=jnp.zeros((2, 3)),
            )
            step = jax.jit(lambda s, _sys=sys_: physics_step(_sys, s, jnp.asarray([0.5]), 0.01, 8))
            for _ in range(150):
                st = step(st)
            angle = float(joint_angles(sys_, st)[0, 1])
            assert angle > 0.2, f"{mode}: actuation did not move the joint"
        # position mode tracks the commanded target (0.5 * hi = 0.5 rad)
        sys_, pos0 = build("position")
        st = BodyState(
            pos=pos0,
            quat=jnp.tile(jnp.asarray([1.0, 0, 0, 0]), (2, 1)),
            vel=jnp.zeros((2, 3)),
            ang=jnp.zeros((2, 3)),
        )
        step = jax.jit(lambda s: physics_step(sys_, s, jnp.asarray([0.5]), 0.01, 8))
        for _ in range(300):
            st = step(st)
        angle = float(joint_angles(sys_, st)[0, 1])
        assert abs(angle - 0.5) < 0.15


class TestHumanoid:
    def test_protocol_and_shapes(self):
        env = Humanoid()
        assert env.observation_size == 109
        assert env.action_size == 17
        state, obs = env.reset(jax.random.key(0))
        assert obs.shape == (109,)
        state, obs, reward, done = env.step(state, jnp.zeros(17))
        assert obs.shape == (109,)
        assert reward.shape == () and done.shape == ()
        assert np.isfinite(np.asarray(obs)).all()

    def test_metastable_standing(self):
        # zero action (PD holds the reference pose) must survive >= 50
        # control steps (0.75 s) before tipping — i.e. episodes are not
        # dead-on-arrival, but balance still requires active control
        env = Humanoid()
        step = jax.jit(env.step)
        s, _ = env.reset(jax.random.key(0))
        for i in range(50):
            s, obs, r, d = step(s, jnp.zeros(17))
            assert not bool(d), f"fell at step {i}"
        assert float(s.obs_state.pos[0, 2]) > 1.0

    def test_random_actions_stay_finite(self):
        env = Humanoid()
        step = jax.jit(env.step)
        s, _ = env.reset(jax.random.key(1))
        key = jax.random.key(2)
        for _ in range(150):
            key, sub = jax.random.split(key)
            a = jax.random.uniform(sub, (17,), minval=-1, maxval=1)
            s, obs, r, d = step(s, a)
            assert np.isfinite(np.asarray(obs)).all()
            assert np.isfinite(float(r))

    def test_joint_integrity_under_load(self):
        from evotorch_tpu.envs.rigidbody import quat_rotate as qr

        env = Humanoid()
        step = jax.jit(env.step)
        s, _ = env.reset(jax.random.key(3))
        key = jax.random.key(4)
        for _ in range(100):
            key, sub = jax.random.split(key)
            s, obs, r, d = step(s, jax.random.uniform(sub, (17,), minval=-1, maxval=1))
        st = s.obs_state
        sys_ = env.sys
        pa = st.pos[sys_.joint_parent] + qr(st.quat[sys_.joint_parent], sys_.anchor_p)
        pb = st.pos[sys_.joint_child] + qr(st.quat[sys_.joint_child], sys_.anchor_c)
        sep = jnp.linalg.norm(pb - pa, axis=-1)
        assert float(sep.max()) < 0.05

    def test_falls_terminate(self):
        env = Humanoid()
        step = jax.jit(env.step)
        s, _ = env.reset(jax.random.key(5))
        # command an extreme asymmetric crouch-twist: must fall eventually
        a = jnp.ones(17).at[3:7].set(-1.0)
        fell = False
        for _ in range(300):
            s, obs, r, d = step(s, a)
            if bool(d):
                fell = True
                break
        assert fell

    def test_unhealthy_reward_drops_alive_bonus(self):
        env = Humanoid()
        s, _ = env.reset(jax.random.key(0))
        # teleport the torso below the healthy band
        from evotorch_tpu.tools.pytree import replace

        st = s.obs_state
        low = replace(s, obs_state=st._replace(pos=st.pos.at[:, 2].add(-1.0)))
        _, _, r_unhealthy, d = env.step(low, jnp.zeros(17))
        assert bool(d)
        _, _, r_healthy, _ = env.step(s, jnp.zeros(17))
        # the alive bonus is withdrawn on the unhealthy terminal step
        assert float(r_healthy) - float(r_unhealthy) > 0.5 * env.alive_bonus

    def test_vmapped_and_jitted(self):
        env = Humanoid()
        n = 4
        keys = jax.random.split(jax.random.key(0), n)
        states, obs = jax.vmap(env.reset)(keys)
        assert obs.shape == (n, 109)
        vstep = jax.jit(jax.vmap(env.step))
        states, obs, rewards, dones = vstep(states, jnp.zeros((n, 17)))
        assert rewards.shape == (n,)
        assert np.isfinite(np.asarray(obs)).all()

    def test_registry_and_torque_mode(self):
        assert isinstance(make_env("humanoid"), Humanoid)
        env = make_env("humanoid", act_mode="torque")
        s, _ = env.reset(jax.random.key(0))
        s, obs, r, d = env.step(s, jnp.zeros(17))
        assert np.isfinite(np.asarray(obs)).all()

    def test_determinism(self):
        env = Humanoid()
        s1, o1 = env.reset(jax.random.key(11))
        s2, o2 = env.reset(jax.random.key(11))
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))
        s1, o1, r1, _ = env.step(s1, jnp.ones(17) * 0.3)
        s2, o2, r2, _ = env.step(s2, jnp.ones(17) * 0.3)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))

    def test_forward_motion_rewarded(self):
        from evotorch_tpu.tools.pytree import replace

        env = Humanoid()
        s, _ = env.reset(jax.random.key(0))
        st = s.obs_state
        moving = st._replace(vel=st.vel.at[:, 0].add(2.0))
        s_moving = replace(s, obs_state=moving)
        _, _, r_moving, _ = env.step(s_moving, jnp.zeros(17))
        _, _, r_still, _ = env.step(s, jnp.zeros(17))
        assert float(r_moving) > float(r_still)
