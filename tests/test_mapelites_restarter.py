import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms import IPOP, MAPElites, Restart, SNES
from evotorch_tpu.operators.real import GaussianMutation


def test_make_feature_grid():
    grid = MAPElites.make_feature_grid(
        lower_bounds=[0.0, -1.0], upper_bounds=[1.0, 1.0], num_bins=[4, 3]
    )
    assert grid.shape == (12, 2, 2)
    # outermost bins extend to +-inf
    assert float(grid[0, 0, 0]) == -np.inf
    assert float(grid[-1, 0, 1]) == np.inf
    # cell bounds are ordered
    assert bool(jnp.all(grid[:, :, 0] <= grid[:, :, 1]))


def test_mapelites_fills_archive():
    # fitness = sphere; feature = x[0] (first decision variable)
    @vectorized
    def fit_and_feature(xs):
        return jnp.sum(xs**2, axis=-1)[:, None], xs[:, :1]

    p = Problem(
        "min",
        fit_and_feature,
        solution_length=3,
        initial_bounds=(-2, 2),
        eval_data_length=1,
        seed=0,
    )
    grid = MAPElites.make_feature_grid([-2.0], [2.0], num_bins=[8])
    searcher = MAPElites(
        p,
        operators=[GaussianMutation(p, stdev=0.5)],
        feature_grid=grid,
    )
    searcher.run(10)
    assert len(searcher.population) == 8
    filled = np.asarray(searcher.filled)
    assert filled.sum() >= 4  # most cells found an occupant
    # each filled cell's occupant feature lies within the cell bounds
    evals = np.asarray(searcher.population.evals)
    g = np.asarray(grid)
    for i in range(8):
        if filled[i]:
            feat = evals[i, 1]
            assert g[i, 0, 0] <= feat <= g[i, 0, 1]


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


class TerminatingSNES(SNES):
    @property
    def is_terminated(self):
        return self.step_count > 0 and self.step_count % 5 == 0


def test_restart_reinstantiates():
    p = Problem("min", sphere, solution_length=4, initial_bounds=(-3, 3), seed=0)
    r = Restart(p, TerminatingSNES, {"stdev_init": 1.0})
    r.run(11)
    assert r.num_restarts >= 3
    assert r.status["num_restarts"] == r.num_restarts


def test_ipop_grows_popsize():
    p = Problem("min", sphere, solution_length=4, initial_bounds=(-3, 3), seed=0)

    from evotorch_tpu.algorithms import CEM

    r = IPOP(
        p,
        CEM,
        {"popsize": 10, "parenthood_ratio": 0.5, "stdev_init": 1.0},
        min_fitness_stdev=1e-3,
        popsize_multiplier=2,
    )
    r.run(60)
    if r.num_restarts > 1:
        assert r._algorithm_args["popsize"] > 10


def test_functional_cmaes():
    import jax

    from evotorch_tpu.algorithms.functional.funccmaes import cmaes, cmaes_ask, cmaes_tell

    state = cmaes(
        center_init=jnp.full((5,), 3.0),
        stdev_init=1.0,
        objective_sense="min",
        popsize=12,
    )

    @jax.jit
    def run(state, key):
        def gen(state, key):
            state, xs = cmaes_ask(key, state)
            fits = jnp.sum(xs**2, axis=-1)
            return cmaes_tell(state, xs, fits), jnp.min(fits)

        return jax.lax.scan(gen, state, jax.random.split(key, 120))

    state, best = run(state, jax.random.key(0))
    assert float(best[-1]) < 0.05
    assert float(best[-1]) < float(best[0])
    assert int(state.iteration) == 120


def test_functional_mapelites_scan():
    import jax

    from evotorch_tpu.algorithms import MAPElites
    from evotorch_tpu.algorithms.functional import mapelites, mapelites_ask, mapelites_tell

    def fit_and_features(xs):
        fitness = jnp.sum(xs**2, axis=-1)
        return jnp.concatenate([fitness[:, None], xs[:, :1]], axis=1)

    grid = MAPElites.make_feature_grid([-2.0], [2.0], num_bins=[8])
    key = jax.random.key(0)
    seed_pop = jax.random.uniform(key, (32, 3), minval=-2.0, maxval=2.0)
    state = mapelites(
        values_init=seed_pop,
        evals_init=fit_and_features(seed_pop),
        feature_grid=grid,
        objective_sense="min",
    )
    initial_filled = int(np.asarray(state.filled).sum())

    def mutate(key, values):
        return values + 0.2 * jax.random.normal(key, values.shape)

    @jax.jit
    def run(state, key):
        def gen(state, key):
            children = mapelites_ask(key, state, mutate=mutate)
            return mapelites_tell(state, children, fit_and_features(children)), None

        return jax.lax.scan(gen, state, jax.random.split(key, 40))[0]

    state = run(state, jax.random.key(1))
    assert int(np.asarray(state.filled).sum()) >= max(initial_filled, 6)
    # occupants' features actually lie inside their cells
    g = np.asarray(grid)
    evals = np.asarray(state.evals)
    filled = np.asarray(state.filled)
    for i in range(8):
        if filled[i]:
            assert g[i, 0, 0] <= evals[i, 1] <= g[i, 0, 1]
    # fitness within each filled cell only improves across further telling
    state2 = run(state, jax.random.key(2))
    both = filled & np.asarray(state2.filled)
    assert (np.asarray(state2.evals)[both, 0] <= evals[both, 0] + 1e-6).all()


def test_cmaes_separable_higher_dim():
    import jax

    from evotorch_tpu.algorithms.functional.funccmaes import cmaes, cmaes_ask, cmaes_tell

    d = 128
    state = cmaes(
        center_init=jnp.full((d,), 2.0),
        stdev_init=1.0,
        objective_sense="min",
        popsize=32,
        separable=True,
    )
    assert state.decompose_C_freq >= 1

    @jax.jit
    def run(state, key):
        def gen(state, key):
            state, xs = cmaes_ask(key, state)
            return cmaes_tell(state, xs, jnp.sum(xs**2, axis=-1)), None

        return jax.lax.scan(gen, state, jax.random.split(key, 150))[0]

    state = run(state, jax.random.key(0))
    assert float(jnp.linalg.norm(state.m)) < float(jnp.linalg.norm(jnp.full((d,), 2.0)))


def test_functional_mapelites_shape_validation():
    from evotorch_tpu.algorithms import MAPElites
    from evotorch_tpu.algorithms.functional import mapelites, mapelites_tell

    grid = MAPElites.make_feature_grid([-1.0], [1.0], num_bins=[4])
    good_vals = jnp.zeros((5, 2))
    good_evals = jnp.zeros((5, 2))
    with pytest.raises(ValueError):
        mapelites(values_init=good_vals, evals_init=jnp.zeros((8, 2)),
                  feature_grid=grid, objective_sense="min")
    with pytest.raises(ValueError):
        mapelites(values_init=jnp.zeros(5), evals_init=good_evals,
                  feature_grid=grid, objective_sense="min")
    state = mapelites(values_init=good_vals, evals_init=good_evals,
                      feature_grid=grid, objective_sense="min")
    with pytest.raises(ValueError):
        mapelites_tell(state, jnp.zeros((3, 2)), jnp.zeros((4, 2)))
