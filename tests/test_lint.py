"""graftlint: per-checker unit tests on synthetic sources, plus the repo
gate — the whole linted surface must carry zero non-baselined findings (and
no stale baseline entries), so any new PRNG-reuse / retrace / host-sync /
donation / axis-name / dtype hazard fails the fast tier at the moment it is
introduced."""

import textwrap

from evotorch_tpu.analysis import (
    apply_baseline,
    default_baseline_path,
    lint_sources,
    load_baseline,
    run_lint,
)


def _lint(src, path="mod.py", checkers=None, extra=None):
    sources = {path: textwrap.dedent(src)}
    if extra:
        sources.update({k: textwrap.dedent(v) for k, v in extra.items()})
    return lint_sources(sources, checkers=checkers)


def _checkers(findings):
    return [f.checker for f in findings]


# ---------------------------------------------------------------------------
# prng
# ---------------------------------------------------------------------------


def test_prng_flags_double_consumption():
    findings = _lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """,
        checkers=["prng"],
    )
    assert _checkers(findings) == ["prng"]
    assert "key" in findings[0].message


def test_prng_flags_loop_reuse():
    findings = _lint(
        """
        import jax

        def f(key):
            out = []
            for i in range(4):
                out.append(jax.random.normal(key, (3,)))
            return out
        """,
        checkers=["prng"],
    )
    assert _checkers(findings) == ["prng"]
    assert "loop" in findings[0].message


def test_prng_accepts_fresh_key_per_loop_iteration():
    # `for k in split(key, n)` hands a NEW key to every iteration — the
    # canonical batching idiom must not read as cross-iteration reuse
    findings = _lint(
        """
        import jax

        def f(key):
            out = []
            for k in jax.random.split(key, 4):
                out.append(jax.random.normal(k, (3,)))
            return out
        """,
        checkers=["prng"],
    )
    assert findings == []


def test_prng_accepts_split_discipline():
    findings = _lint(
        """
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (3,))
            b = jax.random.uniform(k2, (3,))
            return a + b

        def g(key):
            for i in range(4):
                key, sub = jax.random.split(key)
                yield jax.random.normal(sub, (3,))

        def h(key, interpret):
            # mutually exclusive paths may both consume the same key
            if interpret:
                return jax.random.normal(key, (2,))
            return jax.random.uniform(key, (2,))
        """,
        checkers=["prng"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# retrace
# ---------------------------------------------------------------------------


def test_retrace_flags_jit_in_loop_and_fresh_callees():
    findings = _lint(
        """
        import jax

        def bench(env):
            for n in (1, 2, 3):
                f = jax.jit(lambda x: x * n)    # jit-in-loop
                f(n)

        def harness(env, x):
            step = jax.jit(env.batch_step)      # fresh bound method
            fwd = jax.jit(lambda a: a + 1)      # fresh lambda
            return step(x), fwd(x)
        """,
        checkers=["retrace"],
    )
    details = sorted(f.detail for f in findings)
    assert details == [
        "jit-fresh-callee:env.batch_step",
        "jit-fresh-callee:lambda",
        "jit-in-loop",
    ]


def test_retrace_accepts_cached_builders_and_module_scope():
    findings = _lint(
        """
        import functools
        import jax

        _CACHE = {}

        def get(env):
            fn = _CACHE.get(env)
            if fn is None:
                fn = jax.jit(env.batch_step)
                _CACHE[env] = fn
            return fn

        @functools.lru_cache(maxsize=8)
        def build(env):
            return jax.jit(lambda s, a: env.step(s, a))

        top = jax.jit(lambda x: x + 1)  # module scope: built once per import

        def warm(envs):
            # cache-filling warm-up loop: one jit per cache key, not per call
            for env in envs:
                _CACHE[env] = jax.jit(env.batch_step)
        """,
        checkers=["retrace"],
    )
    assert findings == []


def test_retrace_cache_exemption_matches_real_memoizers_only():
    # a decorator merely NAMED like a cache does not memoize: the fresh
    # bound-method jit under it must still be reported
    findings = _lint(
        """
        import jax

        def clear_cache(fn):
            return fn

        @clear_cache
        def harness(env, x):
            step = jax.jit(env.batch_step)
            return step(x)
        """,
        checkers=["retrace"],
    )
    assert [f.detail for f in findings] == ["jit-fresh-callee:env.batch_step"]


def test_retrace_flags_fstring_args_to_jitted_callable():
    findings = _lint(
        """
        import jax

        run = jax.jit(lambda tag, x: x)

        def f(x, i):
            return run(f"step{i}", x)
        """,
        checkers=["retrace"],
    )
    assert [f.detail for f in findings] == ["str-arg:run"]


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


def test_host_sync_flags_traced_conversions():
    findings = _lint(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return float(x) * 2

        @jax.jit
        def g(x):
            return np.asarray(x).sum()

        def h(x):
            return jax.lax.while_loop(lambda c: c[0].item() < 3, body, x)

        def body(c):
            return c
        """,
        checkers=["host-sync"],
    )
    details = sorted(f.detail for f in findings)
    assert details == ["float-in-trace", "item", "np-asarray"]


def test_host_sync_exempts_static_args_and_shapes():
    findings = _lint(
        """
        from functools import partial

        import jax

        @partial(jax.jit, static_argnames=("n", "mode"))
        def f(x, n, mode):
            k = int(n) + len(mode)
            m = int(x.shape[0])
            return x[: k + m]
        """,
        checkers=["host-sync"],
    )
    assert findings == []


def test_host_sync_flags_per_iteration_device_sync_in_host_loop():
    helper = """
        import jax.numpy as jnp

        def bonus(t, schedule):
            return jnp.where(t >= schedule[0], schedule[1], 0.0)
        """
    findings = _lint(
        """
        from helper import bonus

        def rollout(schedule):
            total = 0.0
            for t in range(100):
                total += float(bonus(t, schedule))
            return total
        """,
        checkers=["host-sync"],
        extra={"helper.py": helper},
    )
    assert [f.detail for f in findings] == ["loop-sync:bonus"]


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def test_donation_flags_undonated_state_steps():
    findings = _lint(
        """
        import jax

        def tell(state, values, evals):
            return state

        @jax.jit
        def step(state, key):
            return state

        def main():
            tell_jit = jax.jit(tell)
            return tell_jit
        """,
        checkers=["donation"],
    )
    details = sorted(f.detail for f in findings)
    assert details == ["undonated-state:step", "undonated-state:tell"]


def test_donation_resolves_cross_module_aliases():
    algo = """
        def pgpe_tell(state, values, evals):
            return state
        """
    findings = _lint(
        """
        import jax

        from algo import pgpe_tell

        def main(lowrank):
            if lowrank:
                tell = pgpe_tell
            else:
                tell = pgpe_tell
            tell_jit = jax.jit(tell)
            return tell_jit

        def chained():
            a = b = pgpe_tell  # chained alias: both names must resolve
            return jax.jit(b)
        """,
        checkers=["donation"],
        extra={"algo.py": algo},
    )
    assert sorted(f.detail for f in findings) == [
        "undonated-state:b",
        "undonated-state:tell",
    ]


def test_donation_accepts_donated_or_non_state_firsts():
    findings = _lint(
        """
        from functools import partial

        import jax

        def tell(state, values):
            return state

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, key):
            return state

        @jax.jit
        def evaluate(values, key):
            return values

        def main():
            return jax.jit(tell, donate_argnums=(0,))
        """,
        checkers=["donation"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# axis-name
# ---------------------------------------------------------------------------


def test_axis_name_flags_undeclared_literals():
    findings = _lint(
        """
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), axis_names=("pop",))

        def local(x):
            good = jax.lax.pmean(x, "pop")
            bad = jax.lax.psum(x, "popp")
            spec = P("batch")
            return good + bad, spec
        """,
        checkers=["axis-name"],
    )
    details = sorted(f.detail for f in findings)
    assert details == ["unknown-axis:batch", "unknown-axis:popp"]


def test_axis_name_collects_defaults_and_make_mesh():
    findings = _lint(
        """
        import jax

        def make_mesh(shape):
            ...

        def helper(x, axis_name="pop"):
            return jax.lax.pmean(x, axis_name)

        def entry(x):
            mesh = make_mesh({"pop": 4, "model": 2})
            return jax.lax.pmean(jax.lax.psum(x, "model"), "pop")
        """,
        checkers=["axis-name"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# dtype
# ---------------------------------------------------------------------------


def test_dtype_flags_x64_references():
    findings = _lint(
        """
        import jax
        import jax.numpy as jnp
        import numpy as np

        BAD = jnp.float64

        def f(x):
            return jnp.asarray(x, dtype="float64")

        @jax.jit
        def g(x):
            return x * np.float64(2.0)

        def host():
            return np.float64(1.0)  # host-side: allowed
        """,
        checkers=["dtype"],
    )
    details = sorted(f.detail for f in findings)
    assert details == ["dtype-str:float64", "np-x64:float64", "x64:float64"]


def test_dtype_flags_enable_x64():
    findings = _lint(
        """
        import jax

        jax.config.update("jax_enable_x64", True)
        """,
        checkers=["dtype"],
    )
    assert [f.detail for f in findings] == ["enable-x64"]


# ---------------------------------------------------------------------------
# timing
# ---------------------------------------------------------------------------


def test_timing_flags_unsynced_measurement_of_jitted_call():
    findings = _lint(
        """
        import time

        import jax

        step = jax.jit(lambda s: s + 1)

        def bench(state):
            t0 = time.perf_counter()
            for _ in range(10):
                state = step(state)
            return time.perf_counter() - t0
        """,
        checkers=["timing"],
    )
    assert [f.detail for f in findings] == ["unsynced-timing:step"]
    assert "block_until_ready" in findings[0].message


def test_timing_accepts_block_until_ready_in_region():
    findings = _lint(
        """
        import time

        import jax

        step = jax.jit(lambda s: s + 1)

        def bench(state):
            t0 = time.perf_counter()
            for _ in range(10):
                state = step(state)
            jax.block_until_ready(state)
            return time.perf_counter() - t0
        """,
        checkers=["timing"],
    )
    assert findings == []


def test_timing_accepts_blocking_local_helper():
    # the `once()` pattern (scripts/tune_compact.py): the dispatch + block
    # live inside a locally-defined helper the timed loop calls
    findings = _lint(
        """
        import time

        import jax

        step = jax.jit(lambda s: s + 1)

        def bench(state):
            def once(s):
                out = step(s)
                jax.block_until_ready(out)
                return out

            once(state)
            t0 = time.perf_counter()
            for _ in range(10):
                state = once(state)
            return time.perf_counter() - t0
        """,
        checkers=["timing"],
    )
    assert findings == []


def test_timing_ignores_nested_def_merely_defined_in_region():
    # a helper DEFINED between the clock reads neither dispatches nor syncs:
    # its body's jitted call must not create a finding, and a
    # block_until_ready inside it must not excuse one
    findings = _lint(
        """
        import time

        import jax

        step = jax.jit(lambda s: s + 1)

        def defines_but_never_calls():
            t0 = time.perf_counter()
            def helper(s):
                return step(s)
            total = sum(range(100))
            return time.perf_counter() - t0, helper, total

        def dead_block_does_not_excuse(state):
            t0 = time.perf_counter()
            def never_called(s):
                jax.block_until_ready(s)
            state = step(state)
            return state, time.perf_counter() - t0
        """,
        checkers=["timing"],
    )
    assert [f.detail for f in findings] == ["unsynced-timing:step"]
    assert findings[0].symbol == "dead_block_does_not_excuse"


def test_timing_flags_unsynced_helper_called_in_region():
    # a called local helper contributes what its body does: jitted dispatch
    # without a block inside -> the region is an unsynced measurement
    findings = _lint(
        """
        import time

        import jax

        step = jax.jit(lambda s: s + 1)

        def bench(state):
            def once(s):
                return step(s)

            t0 = time.perf_counter()
            for _ in range(10):
                state = once(state)
            return time.perf_counter() - t0
        """,
        checkers=["timing"],
    )
    assert [f.detail for f in findings] == ["unsynced-timing:once"]


def test_timing_ignores_host_only_timing_and_jit_decorated_defs():
    findings = _lint(
        """
        import time

        import jax

        @jax.jit
        def step(state):
            return state + 1

        def host_bench():
            t0 = time.perf_counter()
            total = sum(range(100))
            return time.perf_counter() - t0, total

        def device_bench(state):
            t0 = time.perf_counter()
            state = step(state)
            dt = time.perf_counter() - t0
            return state, dt
        """,
        checkers=["timing"],
    )
    # host_bench times no jitted call; device_bench times the @jax.jit def
    # without blocking -> exactly one finding
    assert [f.detail for f in findings] == ["unsynced-timing:step"]
    assert findings[0].symbol == "device_bench"


# ---------------------------------------------------------------------------
# swallow
# ---------------------------------------------------------------------------


def test_swallow_flags_silent_broad_handlers():
    findings = _lint(
        """
        def f():
            try:
                risky()
            except:
                pass

        def g():
            try:
                risky()
            except Exception:
                x = 1
        """,
        checkers=["swallow"],
    )
    details = sorted(f.detail for f in findings)
    assert details == ["swallow:bare except", "swallow:except Exception"]


def test_swallow_accepts_reported_or_narrow_handlers():
    findings = _lint(
        """
        import logging
        import traceback

        from evotorch_tpu.observability.registry import counters

        log = logging.getLogger(__name__)

        def logged():
            try:
                risky()
            except Exception:
                log.warning("risky failed")

        def counted():
            try:
                risky()
            except Exception:
                counters.increment("risky.failures")

        def reraised():
            try:
                risky()
            except Exception:
                cleanup()
                raise

        def captured():
            try:
                risky()
            except Exception:
                tb = traceback.format_exc()
                record(tb)

        def narrow():
            try:
                risky()
            except (KeyError, OSError):
                pass
        """,
        checkers=["swallow"],
    )
    assert findings == []


def test_swallow_allow_comment_suppresses_with_reason():
    silent = """
        def f():
            try:
                risky()
            except Exception:  # graftlint: allow(swallow): teardown is best-effort
                pass
        """
    assert _lint(silent, checkers=["swallow"]) == []
    reasonless = """
        def f():
            try:
                risky()
            except Exception:  # graftlint: allow(swallow)
                pass
        """
    findings = _lint(reasonless, checkers=["swallow"])
    details = sorted(f.detail for f in findings)
    # the reasonless allow does NOT suppress, and is itself a finding
    assert details == ["missing-reason", "swallow:except Exception"]


# ---------------------------------------------------------------------------
# scoped allow-comments
# ---------------------------------------------------------------------------


def test_scoped_allow_suppresses_named_checker_only():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            # graftlint: allow(host-sync): swap-point sync is this test's point
            a = np.asarray(x).sum()
            b = float(x)  # not covered by the allow above
            return a + b
        """
    findings = _lint(src, checkers=["host-sync"])
    assert [f.detail for f in findings] == ["float-in-trace"]


def test_scoped_allow_trailing_same_line():
    src = """
        import jax

        @jax.jit
        def f(x):
            return float(x)  # graftlint: allow(host-sync): intentional demo
        """
    assert _lint(src, checkers=["host-sync"]) == []


def test_scoped_allow_requires_reason():
    src = """
        import jax

        @jax.jit
        def f(x):
            return float(x)  # graftlint: allow(host-sync)
        """
    findings = _lint(src, checkers=["host-sync"])
    details = sorted(f.detail for f in findings)
    # the reasonless allow does NOT suppress, and is itself a finding
    assert details == ["float-in-trace", "missing-reason"]


def test_scoped_allow_trailing_does_not_cover_next_line():
    # a trailing allow excuses its own line ONLY: the adjacent violation
    # below it must still be reported
    src = """
        import jax

        @jax.jit
        def f(a, b):
            x = float(a)  # graftlint: allow(host-sync): intentional demo
            y = float(b)
            return x + y
        """
    findings = _lint(src, checkers=["host-sync"])
    assert [f.detail for f in findings] == ["float-in-trace"]
    assert findings[0].line == 7  # the uncovered second float()


def test_scoped_allow_inside_string_literal_is_inert():
    # allow-syntax in a string is data, not a directive: it must neither
    # suppress findings nor be reported as a reasonless allow
    src = """
        import jax

        HELP = "# graftlint: allow(host-sync)"

        @jax.jit
        def f(x):
            doc = "# graftlint: allow(host-sync): not a comment"
            return float(x), doc
        """
    findings = _lint(src, checkers=["host-sync"])
    assert [f.detail for f in findings] == ["float-in-trace"]


def test_scoped_allow_multiple_checkers():
    src = """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            # graftlint: allow(host-sync, dtype): x64 host pull is deliberate here
            return np.asarray(x) * np.float64(2.0)
        """
    assert _lint(src, checkers=["host-sync", "dtype"]) == []


# ---------------------------------------------------------------------------
# the repo gate
# ---------------------------------------------------------------------------


def test_repo_is_clean_modulo_baseline():
    """The acceptance gate: zero non-baselined findings on the whole linted
    surface, and no stale baseline entries (fixed findings must drop their
    grandfathering in the same change)."""
    findings = run_lint()
    baseline = load_baseline(default_baseline_path())
    new, stale = apply_baseline(findings, baseline)
    assert new == [], "non-baselined graftlint findings:\n" + "\n".join(
        f.format() for f in new
    )
    assert stale == [], "stale baseline entries (remove them):\n" + "\n".join(
        e["signature"] for e in stale
    )


def test_baseline_is_multiset_matched():
    findings = _lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            c = jax.random.gumbel(key, (3,))
            return a + b + c
        """,
        checkers=["prng"],
    )
    assert len(findings) == 2  # second and third consumption
    one_entry = [{"signature": findings[0].signature}]
    new, stale = apply_baseline(findings, one_entry)
    assert len(new) == 1 and stale == []
