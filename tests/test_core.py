import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import rowwise, vectorized
from evotorch_tpu.core import Problem, Solution, SolutionBatch
from evotorch_tpu.tools import ObjectArray


@vectorized
def sphere(xs):
    return jnp.sum(xs**2, axis=-1)


def make_problem(**kwargs):
    defaults = dict(
        objective_sense="min",
        objective_func=sphere,
        solution_length=4,
        initial_bounds=(-1.0, 1.0),
        seed=3,
    )
    defaults.update(kwargs)
    return Problem(defaults.pop("objective_sense"), defaults.pop("objective_func"), **defaults)


# ----------------------------------------------------------------- Problem --


def test_problem_basics():
    p = make_problem()
    assert p.senses == ["min"]
    assert not p.is_multi_objective
    assert p.solution_length == 4
    assert p.dtype == jnp.float32
    with pytest.raises(ValueError):
        Problem("minimize", sphere, solution_length=2)


def test_generate_batch_within_initial_bounds():
    p = make_problem()
    batch = p.generate_batch(10)
    vals = np.asarray(batch.values)
    assert vals.shape == (10, 4)
    assert vals.min() >= -1.0 and vals.max() <= 1.0
    assert not batch.is_evaluated


def test_evaluate_vectorized():
    p = make_problem()
    batch = p.generate_batch(8)
    p.evaluate(batch)
    assert batch.is_evaluated
    expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
    assert np.allclose(np.asarray(batch.evals[:, 0]), expected, atol=1e-6)


def test_evaluate_per_solution_loop():
    # non-vectorized objective: gets one row at a time
    def row_fitness(x):
        assert x.ndim == 1
        return jnp.sum(jnp.abs(x))

    p = Problem("min", row_fitness, solution_length=3, initial_bounds=(-1, 1))
    batch = p.generate_batch(5)
    p.evaluate(batch)
    assert batch.is_evaluated


def test_best_worst_tracking_and_status():
    p = make_problem()
    batch = p.generate_batch(20)
    p.evaluate(batch)
    status = p.status
    assert "best" in status and "best_eval" in status
    assert status["best_eval"] <= status["worst_eval"]
    # best only improves over generations
    first_best = status["best_eval"]
    batch2 = p.generate_batch(20)
    p.evaluate(batch2)
    assert p.status["best_eval"] <= first_best


def test_eval_hooks():
    p = make_problem()
    seen = []
    p.before_eval_hook.append(lambda b: seen.append(len(b)))
    p.after_eval_hook.append(lambda b: {"custom_metric": 42})
    p.evaluate(p.generate_batch(6))
    assert seen == [6]
    assert p.status["custom_metric"] == 42


def test_manual_seed_determinism():
    p1 = make_problem(seed=7)
    p2 = make_problem(seed=7)
    assert np.allclose(np.asarray(p1.generate_values(5)), np.asarray(p2.generate_values(5)))


def test_multiobjective_problem():
    @vectorized
    def two_obj(xs):
        return jnp.stack([jnp.sum(xs**2, axis=-1), jnp.sum(jnp.abs(xs), axis=-1)], axis=1)

    p = Problem(["min", "min"], two_obj, solution_length=3, initial_bounds=(-1, 1))
    assert p.is_multi_objective
    batch = p.generate_batch(12)
    p.evaluate(batch)
    assert batch.evals.shape == (12, 2)
    ranks = batch.compute_pareto_ranks()
    assert ranks.shape == (12,)
    fronts = batch.arg_pareto_sort()
    assert sum(len(f) for f in fronts) == 12
    best2 = batch.take_best(5)
    assert len(best2) == 5


def test_eval_data_length():
    @vectorized
    def with_extra(xs):
        fit = jnp.sum(xs**2, axis=-1)
        extra = jnp.ones((xs.shape[0], 2))
        return fit[:, None], extra

    p = Problem("min", with_extra, solution_length=3, initial_bounds=(-1, 1), eval_data_length=2)
    batch = p.generate_batch(4)
    p.evaluate(batch)
    assert batch.evals.shape == (4, 3)
    assert np.allclose(np.asarray(batch.evdata), 1.0)


def test_bounds_validation():
    with pytest.raises(ValueError):
        Problem("min", sphere, solution_length=2, bounds=(1.0, -1.0))
    p = Problem("min", sphere, solution_length=2, bounds=(-2.0, 2.0))
    assert np.allclose(np.asarray(p.lower_bounds), -2.0)
    assert np.allclose(np.asarray(p.upper_bounds), 2.0)


def test_object_dtype_problem():
    class ListProblem(Problem):
        def __init__(self):
            super().__init__("max", dtype=object)

        def _fill(self, n, key):
            arr = ObjectArray(n)
            for i in range(n):
                arr[i] = [i, i + 1]
            return arr

        def _evaluate(self, solution):
            solution.set_evals(float(sum(solution.values)))

    p = ListProblem()
    batch = p.generate_batch(3)
    p.evaluate(batch)
    assert np.asarray(batch.evals[:, 0]).tolist() == [1.0, 3.0, 5.0]


# ----------------------------------------------------------- SolutionBatch --


def test_batch_nan_semantics_and_set_evals():
    p = make_problem()
    batch = p.generate_batch(5)
    assert not batch.is_evaluated
    batch.set_evals(jnp.arange(5.0))
    assert batch.is_evaluated
    assert np.allclose(np.asarray(batch.evals[:, 0]), np.arange(5.0))


def test_access_values_clears_evals():
    p = make_problem()
    batch = p.generate_batch(5)
    batch.set_evals(jnp.arange(5.0))
    _ = batch.access_values()
    assert not batch.is_evaluated
    batch.set_evals(jnp.arange(5.0))
    _ = batch.access_values(keep_evals=True)
    assert batch.is_evaluated


def test_argsort_argbest_take():
    p = make_problem()
    batch = p.generate_batch(6)
    batch.set_evals(jnp.array([3.0, 1.0, 2.0, 6.0, 5.0, 4.0]))
    order = np.asarray(batch.argsort())
    assert order[0] == 1  # min problem: best is lowest
    assert int(batch.argbest()) == 1
    assert int(batch.argworst()) == 3
    best3 = batch.take_best(3)
    assert np.asarray(best3.evals[:, 0]).tolist() == [1.0, 2.0, 3.0]


def test_slice_scatter_back():
    # evaluating a piece must write results into the parent batch
    p = make_problem()
    batch = p.generate_batch(10)
    pieces = batch.split(2)
    assert len(pieces) == 2
    p.evaluate(pieces[0])
    p.evaluate(pieces[1])
    assert batch.is_evaluated
    lo, hi = pieces.indices_of(1)
    assert (lo, hi) == (5, 10)


def test_getitem_solution_and_subbatch():
    p = make_problem()
    batch = p.generate_batch(6)
    sln = batch[2]
    assert isinstance(sln, Solution)
    sub = batch[1:4]
    assert isinstance(sub, SolutionBatch) and len(sub) == 3
    sln.set_evals(7.5)
    assert float(batch.evals[2, 0]) == 7.5


def test_solution_set_values_invalidates_evals():
    p = make_problem()
    batch = p.generate_batch(3)
    batch.set_evals(jnp.ones(3))
    batch[0].set_values(jnp.zeros(4))
    assert np.isnan(float(batch.evals[0, 0]))
    assert float(batch.evals[1, 0]) == 1.0
    assert np.allclose(np.asarray(batch[0].values), 0.0)


def test_merge_and_cat():
    p = make_problem()
    b1 = p.generate_batch(3)
    b2 = p.generate_batch(2)
    merged = b1.concat(b2)
    assert len(merged) == 5
    assert len(SolutionBatch.cat([b1, b2, b1])) == 8


def test_utility_and_utils():
    p = make_problem()
    batch = p.generate_batch(4)
    batch.set_evals(jnp.array([4.0, 1.0, 3.0, 2.0]))
    u = np.asarray(batch.utility(ranking_method="centered"))
    assert u[1] == 0.5  # best (lowest, min problem)
    assert batch.utils(ranking_method="centered").shape == (4, 1)


def test_clone_independent():
    p = make_problem()
    batch = p.generate_batch(3)
    batch.set_evals(jnp.ones(3))
    c = batch.clone()
    c.set_evals(jnp.zeros(3))
    assert float(batch.evals[0, 0]) == 1.0


# --------------------------------------------------- ProblemBoundEvaluator --


def test_problem_bound_evaluator():
    p = make_problem()
    f = p.make_callable_evaluator()
    values = jnp.ones((5, 4))
    fits = f(values)
    assert np.allclose(np.asarray(fits), 4.0)
    # extra batch dims by reshape
    fits_b = f(jnp.ones((2, 5, 4)))
    assert fits_b.shape == (2, 5)


def test_problem_pickling():
    import pickle

    p = make_problem()
    p.evaluate(p.generate_batch(4))
    # objective_func is module-level, so the problem pickles
    restored = pickle.loads(pickle.dumps(p))
    assert restored.senses == ["min"]
    batch = restored.generate_batch(3)
    restored.evaluate(batch)
    assert batch.is_evaluated


def test_object_piece_value_writes_propagate():
    # review regression: object-dtype pieces must propagate value writes
    class ListProblem2(Problem):
        def __init__(self):
            super().__init__("max", dtype=object)

        def _fill(self, n, key):
            arr = ObjectArray(n)
            for i in range(n):
                arr[i] = [i]
            return arr

        def _evaluate(self, solution):
            solution.set_evals(float(sum(solution.values)))

    p = ListProblem2()
    batch = p.generate_batch(4)
    piece = batch[0:2]
    piece[0].set_values([99, 1])
    assert list(batch[0].values) == [99, 1]
    taken = batch.take([1, 3])
    taken[0].set_values([7])
    assert list(batch[1].values) == [7]


def test_sample_and_compute_gradients_adaptive():
    from evotorch_tpu.distributions import SeparableGaussian

    interactions = {"n": 0}

    class CountingProblem(Problem):
        def __init__(self):
            super().__init__("min", solution_length=3, initial_bounds=(-1, 1))
            self.after_eval_hook.append(self._report)

        def _evaluate_batch(self, batch):
            interactions["n"] += len(batch) * 10
            batch.set_evals(jnp.sum(jnp.asarray(batch.values) ** 2, axis=-1))

        def _report(self, batch):
            return {"total_interaction_count": interactions["n"]}

    p = CountingProblem()
    dist = SeparableGaussian({"mu": jnp.zeros(3), "sigma": jnp.ones(3)})
    [result] = p.sample_and_compute_gradients(
        dist, 10, num_interactions=250, popsize_max=100, ranking_method="centered"
    )
    # 10 solutions -> 100 interactions per chunk; threshold 250 -> 3 chunks
    assert result["num_solutions"] == 30


def test_getitem_with_zero_d_index():
    # review regression: batch[batch.argbest()] must return a Solution
    p = make_problem()
    batch = p.generate_batch(5)
    batch.set_evals(jnp.array([3.0, 1.0, 2.0, 5.0, 4.0]))
    sln = batch[batch.argbest()]
    assert isinstance(sln, Solution)
    assert float(sln.evals[0]) == 1.0
    # 1-d index arrays still produce sub-batches
    sub = batch[jnp.array([0, 2])]
    assert isinstance(sub, SolutionBatch) and len(sub) == 2


def test_num_actors_triggers_sharded_evaluation():
    # drop-in parity: num_actors requests become mesh sharding
    p = Problem("min", sphere, solution_length=4, initial_bounds=(-1, 1), num_actors=4)
    batch = p.generate_batch(16)
    p.evaluate(batch)
    assert batch.is_evaluated
    assert p._sharded_evaluator is not None

    p2 = Problem("min", sphere, solution_length=4, initial_bounds=(-1, 1), num_actors="max")
    p2.evaluate(p2.generate_batch(8))
    assert p2._sharded_evaluator is not None

    # per-solution problems silently stay host-side (no actor pool exists)
    p3 = Problem("min", lambda row: jnp.sum(row**2), solution_length=3,
                 initial_bounds=(-1, 1), num_actors=4)
    p3.evaluate(p3.generate_batch(4))
    assert p3._sharded_evaluator is None


def test_non_traceable_objective_falls_back(caplog):
    # review regression: a host-side (non-jax) vectorized objective with
    # num_actors must degrade gracefully, not crash in tracing
    import numpy as onp

    @vectorized
    def host_objective(xs):
        return jnp.asarray(onp.sum(onp.asarray(xs) ** 2, axis=-1))

    p = Problem("min", host_objective, solution_length=3, initial_bounds=(-1, 1), num_actors=4)
    batch = p.generate_batch(8)
    p.evaluate(batch)
    assert batch.is_evaluated
    assert p._sharded_evaluator is None  # fell back


def test_num_actors_single_device_noop():
    p = Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1), num_actors=1)
    p.evaluate(p.generate_batch(4))
    assert p._sharded_evaluator is None


def test_evaluate_single_solution():
    p = make_problem()
    batch = p.generate_batch(3)
    p.evaluate(batch[1])
    assert bool(batch[1].is_evaluated)
    # the rest of the batch is untouched
    assert not batch.is_evaluated


def test_split_max_size():
    p = make_problem()
    batch = p.generate_batch(10)
    pieces = batch.split(max_size=3)
    assert [len(pc) for pc in pieces] == [3, 3, 2, 2]


def test_subbatch_evaluation():
    # reference core.py:1282-1295: evaluation proceeds in pieces
    seen_sizes = []

    @vectorized
    def spying_sphere(xs):
        seen_sizes.append(int(xs.shape[0]))
        return jnp.sum(xs**2, axis=-1)

    p = Problem("min", spying_sphere, solution_length=3, initial_bounds=(-1, 1),
                subbatch_size=4)
    batch = p.generate_batch(10)
    p.evaluate(batch)
    assert batch.is_evaluated
    assert seen_sizes == [4, 4, 2] or seen_sizes == [4, 3, 3]
    expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
    assert np.allclose(np.asarray(batch.evals[:, 0]), expected, atol=1e-6)

    seen_sizes.clear()
    p2 = Problem("min", spying_sphere, solution_length=3, initial_bounds=(-1, 1),
                 num_subbatches=2)
    batch2 = p2.generate_batch(10)
    p2.evaluate(batch2)
    assert seen_sizes == [5, 5]
    assert batch2.is_evaluated

    # both knobs at once are mutually exclusive (reference core.py:1288-1293)
    with pytest.raises(ValueError):
        Problem("min", spying_sphere, solution_length=3, initial_bounds=(-1, 1),
                num_subbatches=2, subbatch_size=3)

    # more subbatches than solutions: clamps (no empty pieces), and a single
    # Solution evaluates fine
    p3 = Problem("min", spying_sphere, solution_length=3, initial_bounds=(-1, 1),
                 num_subbatches=8)
    b3 = p3.generate_batch(3)
    p3.evaluate(b3)
    assert b3.is_evaluated
    p3.evaluate(p3.generate_batch(2)[0])

    # sharded evaluator active: sub-batching is skipped (mesh bounds rows)
    p4 = Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1),
                 subbatch_size=2, num_actors="max")
    b4 = p4.generate_batch(16)
    p4.evaluate(b4)
    assert b4.is_evaluated


def test_subbatch_validation_and_edge_cases():
    with pytest.raises(ValueError):
        Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1), num_subbatches=0)
    with pytest.raises(ValueError):
        Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1), subbatch_size=-3)
    # empty batch with subbatching flows through without error
    p = Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1), num_subbatches=2)
    empty = p.generate_batch(0)
    p.evaluate(empty)
    assert len(empty) == 0


def test_non_traceable_fallback_honors_subbatching():
    import numpy as onp

    seen = []

    @vectorized
    def host_objective(xs):
        seen.append(int(xs.shape[0]))
        return jnp.asarray(onp.sum(onp.asarray(xs) ** 2, axis=-1))

    p = Problem("min", host_objective, solution_length=3, initial_bounds=(-1, 1),
                num_actors=4, subbatch_size=4)
    batch = p.generate_batch(12)
    p.evaluate(batch)
    assert batch.is_evaluated
    # a failed sharded *trace* may record one abstract-shape call first
    # (only when multiple devices are present); the real evaluations
    # proceeded in pieces of at most subbatch_size
    real_calls = [s for s in seen if s <= 4]
    assert real_calls == [4, 4, 4]
