import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import CartPole, Pendulum
from evotorch_tpu.neuroevolution.net import (
    LSTM,
    RNN,
    FlatParamsPolicy,
    Linear,
    Policy,
    Tanh,
    reset_tensors,
    run_vectorized_rollout,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm


# -- Policy wrapper (reference test_vecrl.py:142-274 analog) -----------------


def test_policy_plain():
    net = Linear(3, 2)
    p = Policy(net)
    flat = jnp.zeros(p.parameter_count)
    p.set_parameters(flat)
    out = p(jnp.ones(3))
    assert out.shape == (2,)


def test_policy_batched():
    net = Linear(3, 2)
    p = Policy(net)
    flat = FlatParamsPolicy(net).init_parameters(jax.random.key(0))
    p.set_parameters(jnp.stack([flat, flat * 0]))
    out = p(jnp.ones((2, 3)))
    assert out.shape == (2, 2)
    assert np.allclose(np.asarray(out[1]), 0.0)


def test_policy_recurrent():
    net = RNN(3, 4)
    p = Policy(net)
    p.set_parameters(FlatParamsPolicy(net).init_parameters(jax.random.key(0)))
    o1 = p(jnp.ones(3))
    o2 = p(jnp.ones(3))
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    p.reset()
    o3 = p(jnp.ones(3))
    assert np.allclose(np.asarray(o1), np.asarray(o3))


def test_policy_batched_recurrent_partial_reset():
    net = LSTM(3, 4)
    p = Policy(net)
    flat = FlatParamsPolicy(net).init_parameters(jax.random.key(0))
    p.set_parameters(jnp.stack([flat, flat]))
    first = p(jnp.ones((2, 3)))
    _ = p(jnp.ones((2, 3)))
    # reset only env 0; env 1 keeps its state
    p.reset(jnp.array([True, False]))
    out = p(jnp.ones((2, 3)))
    assert np.allclose(np.asarray(out[0]), np.asarray(first[0]), atol=1e-6)
    assert not np.allclose(np.asarray(out[1]), np.asarray(first[1]))


def test_reset_tensors():
    tree = {"a": jnp.ones((4, 3)), "b": (jnp.full((4,), 7.0),)}
    out = reset_tensors(tree, jnp.array([True, False, True, False]))
    assert np.allclose(np.asarray(out["a"][0]), 0.0)
    assert np.allclose(np.asarray(out["a"][1]), 1.0)
    assert float(out["b"][0][0]) == 0.0
    assert float(out["b"][0][1]) == 7.0


# -- the jitted rollout engine ------------------------------------------------


def _linear_policy(env):
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    return FlatParamsPolicy(net)


def test_rollout_shapes_and_accounting():
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 8
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), n))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1
    )
    assert result.scores.shape == (n,)
    assert int(result.total_episodes) == n
    # cartpole returns are in [1, 500]
    assert float(jnp.min(result.scores)) >= 1.0
    assert float(jnp.max(result.scores)) <= 500.0
    assert int(result.total_steps) >= n


def test_rollout_num_episodes_mean():
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    params = jnp.zeros((4, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    r1 = run_vectorized_rollout(env, policy, params, jax.random.key(0), stats, num_episodes=3)
    assert int(r1.total_episodes) == 12
    # zero-params policy scores should be similar across episodes
    assert r1.scores.shape == (4,)


def test_rollout_episode_length_truncation():
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((3, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats, num_episodes=1, episode_length=10
    )
    assert int(result.total_steps) == 30  # 3 envs x 10 steps


def test_rollout_observation_normalization_collects_stats():
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((2, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats,
        num_episodes=1, episode_length=50, observation_normalization=True,
    )
    assert float(result.stats.count) == 100  # 2 envs x 50 steps


def test_rollout_reward_adjustments():
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((2, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    base = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats, num_episodes=1, episode_length=20
    )
    adjusted = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats,
        num_episodes=1, episode_length=20, decrease_rewards_by=1.0,
    )
    assert np.allclose(np.asarray(base.scores - adjusted.scores), 20.0, atol=1e-3)


def test_rollout_recurrent_policy():
    env = Pendulum()
    net = RNN(env.observation_size, 8) >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), 3))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1, episode_length=25
    )
    assert result.scores.shape == (3,)


def test_rollout_bf16_compute():
    env = Pendulum()
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    policy = FlatParamsPolicy(net)
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), 4))
    stats = RunningNorm(env.observation_size).stats
    r32 = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1, episode_length=20
    )
    rbf = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1, episode_length=20,
        compute_dtype=jnp.bfloat16,
    )
    assert rbf.scores.dtype == jnp.float32
    # bf16 forward changes actions slightly but scores stay in the same regime
    assert np.allclose(np.asarray(rbf.scores), np.asarray(r32.scores), rtol=0.3, atol=30.0)


def test_rollout_bf16_recurrent():
    env = Pendulum()
    net = RNN(env.observation_size, 8) >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(2), 3))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, num_episodes=1, episode_length=15,
        compute_dtype=jnp.bfloat16,
    )
    assert result.scores.shape == (3,)
    assert np.isfinite(np.asarray(result.scores)).all()


# -- fixed-budget evaluation (the throughput-optimal contract) ----------------


def test_rollout_budget_counts_every_step():
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 6
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), n))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats,
        num_episodes=1, episode_length=40, eval_mode="budget",
    )
    # every lane consumes exactly its budget: all computed steps are counted
    assert int(result.total_steps) == n * 40
    assert result.scores.shape == (n,)
    assert np.isfinite(np.asarray(result.scores)).all()


def test_rollout_budget_matches_episodes_on_full_horizon():
    # Pendulum never terminates internally: each lane runs one truncated
    # episode in both modes, so the two contracts must agree exactly
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((3, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=25)
    r_ep = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats, eval_mode="episodes", **kw
    )
    r_bu = run_vectorized_rollout(
        env, policy, params, jax.random.key(0), stats, eval_mode="budget", **kw
    )
    assert np.allclose(np.asarray(r_ep.scores), np.asarray(r_bu.scores), rtol=1e-5)
    assert int(r_ep.total_steps) == int(r_bu.total_steps) == 75
    assert int(r_ep.total_episodes) == int(r_bu.total_episodes) == 3


def test_rollout_budget_average_episodic_return():
    # CartPole with a bad policy dies early and auto-resets: the budget-mode
    # score is the average episodic return across those episodes, so it must
    # sit inside the per-episode score range of the same policy
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(3)
    params = jnp.asarray(rng.normal(size=(4, policy.parameter_count)) * 2.0, jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(5), stats,
        num_episodes=1, episode_length=200, eval_mode="budget",
    )
    # several episodes fit in the budget for a falling policy
    assert int(result.total_episodes) >= 4
    # cartpole per-episode returns are in [1, 200] at this budget
    assert float(jnp.min(result.scores)) >= 1.0
    assert float(jnp.max(result.scores)) <= 200.0


def test_rollout_budget_invalid_mode():
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((2, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    with pytest.raises(ValueError, match="eval_mode"):
        run_vectorized_rollout(
            env, policy, params, jax.random.key(0), stats, eval_mode="nope"
        )


# -- lane-compacting episodes runner ------------------------------------------


def _compacting(env, policy, params, key, stats, **kw):
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout_compacting

    return run_vectorized_rollout_compacting(env, policy, params, key, stats, **kw)


def test_compacting_matches_monolithic_single_episode():
    # num_episodes=1, no action noise: per-lane dynamics are deterministic, so
    # the compacting runner must reproduce the monolithic episodes-mode scores
    # exactly (compaction only reorders lanes)
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 32
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=120)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(7), stats, eval_mode="episodes", **kw
    )
    comp = _compacting(
        env, policy, params, jax.random.key(7), stats,
        chunk_size=10, allowed_widths=(4, 8, 16), **kw,
    )
    assert np.allclose(np.asarray(comp.scores), np.asarray(mono.scores), atol=1e-5)
    assert int(comp.total_episodes) == int(mono.total_episodes) == n
    assert int(comp.total_steps) == int(mono.total_steps)


def test_compacting_obs_norm_stats_match():
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 16
    rng = np.random.default_rng(1)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=80, observation_normalization=True)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, eval_mode="episodes", **kw
    )
    comp = _compacting(
        env, policy, params, jax.random.key(3), stats,
        chunk_size=7, allowed_widths=(4, 8), **kw,
    )
    assert float(comp.stats.count) == float(mono.stats.count)
    assert np.allclose(np.asarray(comp.stats.sum), np.asarray(mono.stats.sum), rtol=1e-5)


def test_compacting_multi_episode_accounting():
    # with num_episodes > 1 the per-step RNG fan-out differs across widths, so
    # scores are only distribution-equivalent; the contract accounting must
    # still hold exactly: every lane finishes all its episodes
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 12
    rng = np.random.default_rng(2)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    comp = _compacting(
        env, policy, params, jax.random.key(5), stats,
        num_episodes=3, episode_length=60, chunk_size=9, allowed_widths=(4, 8),
    )
    assert int(comp.total_episodes) == 3 * n
    assert np.isfinite(np.asarray(comp.scores)).all()
    assert float(jnp.min(comp.scores)) >= 1.0


def test_compacting_on_batched_native_env():
    # the rigid-body envs use the batch-trailing layout: exercises batch_take
    from evotorch_tpu.envs import make_env

    env = make_env("hopper")
    policy = _linear_policy(env)
    n = 16
    rng = np.random.default_rng(4)
    params = jnp.asarray(
        rng.normal(size=(n, policy.parameter_count)) * 0.1, jnp.float32
    )
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=40)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(11), stats, eval_mode="episodes", **kw
    )
    comp = _compacting(
        env, policy, params, jax.random.key(11), stats,
        chunk_size=8, allowed_widths=(4, 8), **kw,
    )
    assert np.allclose(
        np.asarray(comp.scores), np.asarray(mono.scores), rtol=1e-4, atol=1e-4
    )
    assert int(comp.total_steps) == int(mono.total_steps)


def test_compacting_recurrent_policy_state_travels():
    env = Pendulum()
    net = RNN(env.observation_size, 8) >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    n = 8
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), n))
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=30)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, eval_mode="episodes", **kw
    )
    comp = _compacting(
        env, policy, params, jax.random.key(1), stats,
        chunk_size=10, allowed_widths=(2, 4), **kw,
    )
    # pendulum never terminates early: no compaction actually triggers, but
    # the chunked path must still agree with the monolithic one
    assert np.allclose(np.asarray(comp.scores), np.asarray(mono.scores), atol=1e-4)


# -- sharded lane-compacting runner (VERDICT r3 #5) ---------------------------


def _sharded_monolithic_episodes(env, policy, params, key, stats, mesh, **kw):
    """The sharded episodes-mode reference: shard_map the monolithic runner
    with the same global-lane-id PRNG derivation the compacting runner uses."""
    from jax.sharding import PartitionSpec as P

    def local(values_shard, key, stats):
        from evotorch_tpu.neuroevolution.net.vecrl import global_lane_ids

        r = run_vectorized_rollout(
            env, policy, values_shard, key, stats, eval_mode="episodes",
            lane_ids=global_lane_ids("pop", values_shard.shape[0]), **kw
        )
        return r.scores, jax.lax.psum(r.total_steps, "pop"), jax.lax.psum(
            r.total_episodes, "pop"
        )

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P("pop"), P(), P()),
            out_specs=(P("pop"), P(), P()),
            check_vma=False,
        )
    )(params, key, stats)


def test_sharded_compacting_matches_sharded_monolithic():
    # same per-shard key folding, num_episodes=1, no noise: the sharded
    # compacting runner must reproduce the sharded monolithic episodes
    # scores exactly — compaction narrows each shard but never changes any
    # lane's dynamics
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout_compacting_sharded,
    )
    from evotorch_tpu.parallel.mesh import default_mesh

    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 32
    rng = np.random.default_rng(5)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    mesh = default_mesh(("pop",))
    kw = dict(num_episodes=1, episode_length=100)

    scores_mono, steps_mono, eps_mono = _sharded_monolithic_episodes(
        env, policy, params, jax.random.key(21), stats, mesh, **kw
    )
    comp = run_vectorized_rollout_compacting_sharded(
        env, policy, params, jax.random.key(21), stats, mesh=mesh,
        chunk_size=10, allowed_widths=(1, 2), **kw,
    )
    np.testing.assert_allclose(
        np.asarray(comp.scores), np.asarray(scores_mono), atol=1e-5
    )
    assert int(comp.total_episodes) == int(eps_mono) == n
    # counted interactions are invariant under compaction (total_steps sums
    # active lanes only): identical accounting, less wall-clock
    assert int(comp.total_steps) == int(steps_mono)


def test_sharded_compacting_obs_norm_psum_merge():
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout_compacting_sharded,
    )
    from evotorch_tpu.parallel.mesh import default_mesh

    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 16
    rng = np.random.default_rng(6)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    mesh = default_mesh(("pop",))
    r = run_vectorized_rollout_compacting_sharded(
        env, policy, params, jax.random.key(22), stats, mesh=mesh,
        num_episodes=1, episode_length=50, observation_normalization=True,
        chunk_size=10, allowed_widths=(1,),
    )
    # every lane's initial reset obs + one obs per computed step land in the
    # merged statistics; the count must equal total computed interactions + n
    assert float(r.stats.count) >= float(r.total_steps)
    assert np.isfinite(np.asarray(r.scores)).all()


def test_vecne_sharded_eval_honors_episodes_compact():
    # evaluate_sharded must no longer silently rewrite episodes_compact ->
    # episodes: same seeds => identical scores between a compact-sharded
    # problem and a monolithic-episodes sharded problem, with counted steps
    # LESS OR EQUAL (that's the whole point)
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make(mode):
        return VecNE(
            "cartpole",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            env_config={"continuous_actions": True},
            episode_length=60,
            eval_mode=mode,
            seed=9,
        )

    p_comp = make("episodes_compact")
    p_mono = make("episodes")
    rng = np.random.default_rng(7)
    values = jnp.asarray(
        rng.normal(size=(24, p_comp.solution_length)) * 0.3, jnp.float32
    )
    b_comp = SolutionBatch(p_comp, values=values)
    b_mono = SolutionBatch(p_mono, values=values)
    p_comp.evaluate_sharded(b_comp)
    p_mono.evaluate_sharded(b_mono)
    np.testing.assert_allclose(
        np.asarray(b_comp.evals_of(0)), np.asarray(b_mono.evals_of(0)), atol=1e-5
    )
    assert int(p_comp.status["total_episode_count"]) == 24


def test_sharded_compacting_lowrank():
    # factored populations ride through the sharded compacting runner:
    # coefficients shard, center/basis replicate, compaction gathers lanes
    from evotorch_tpu.distributions import SymmetricSeparableGaussian
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout_compacting_sharded,
    )
    from evotorch_tpu.parallel.mesh import default_mesh

    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    dist = SymmetricSeparableGaussian(
        {"mu": jnp.zeros(policy.parameter_count), "sigma": jnp.full(policy.parameter_count, 0.3)}
    )
    params = dist.sample_lowrank(16, 4, key=jax.random.key(31))
    stats = RunningNorm(env.observation_size).stats
    mesh = default_mesh(("pop",))
    kw = dict(num_episodes=1, episode_length=60, chunk_size=10, allowed_widths=(1,))
    r_lr = run_vectorized_rollout_compacting_sharded(
        env, policy, params, jax.random.key(32), stats, mesh=mesh, **kw
    )
    r_dense = run_vectorized_rollout_compacting_sharded(
        env, policy, params.materialize(), jax.random.key(32), stats, mesh=mesh, **kw
    )
    np.testing.assert_allclose(
        np.asarray(r_lr.scores), np.asarray(r_dense.scores), rtol=1e-4, atol=1e-4
    )


# -- per-lane PRNG chains: randomness as a per-lane property ------------------


@pytest.mark.slow
def test_compacting_bit_exact_with_noise_and_multi_episode():
    # the former caveat config: multi-episode + action noise used to be only
    # distribution-equivalent under compaction; per-lane PRNG chains make it
    # bit-exact
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout_compacting,
    )

    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(9)
    params = jnp.asarray(rng.normal(size=(16, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=3, episode_length=40, action_noise_stdev=0.05)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, eval_mode="episodes", **kw
    )
    comp = run_vectorized_rollout_compacting(
        env, policy, params, jax.random.key(3), stats,
        chunk_size=9, allowed_widths=(4, 8), **kw,
    )
    np.testing.assert_array_equal(np.asarray(comp.scores), np.asarray(mono.scores))
    assert int(comp.total_episodes) == int(mono.total_episodes) == 48


def test_rollout_invariant_to_batch_composition():
    # a lane's score depends only on its parameters and its lane id — NOT on
    # which other lanes share the batch: evaluating a subset with the same
    # lane ids reproduces the full run's rows exactly
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(10)
    params = jnp.asarray(rng.normal(size=(12, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=2, episode_length=30, action_noise_stdev=0.1)
    full = run_vectorized_rollout(
        env, policy, params, jax.random.key(5), stats, eval_mode="episodes", **kw
    )
    idx = jnp.asarray([2, 5, 11], dtype=jnp.int32)
    part = run_vectorized_rollout(
        env, policy, params[idx], jax.random.key(5), stats,
        eval_mode="episodes", lane_ids=idx, **kw,
    )
    np.testing.assert_array_equal(
        np.asarray(part.scores), np.asarray(full.scores)[np.asarray(idx)]
    )


def test_vecne_sharded_equals_unsharded_bit_exact():
    # the mesh is an execution detail: same seed => identical scores whether
    # the population is evaluated sharded (8-way) or unsharded, even with
    # action noise and multi-episode evaluation
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make():
        return VecNE(
            "cartpole",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            env_config={"continuous_actions": True},
            episode_length=30,
            num_episodes=2,
            action_noise_stdev=0.05,
            seed=21,
        )

    rng = np.random.default_rng(12)
    p_plain, p_sharded = make(), make()
    values = jnp.asarray(
        rng.normal(size=(24, p_plain.solution_length)) * 0.3, jnp.float32
    )
    b1 = SolutionBatch(p_plain, values=values)
    b2 = SolutionBatch(p_sharded, values=values)
    p_plain.evaluate(b1)
    p_sharded.evaluate_sharded(b2)
    np.testing.assert_array_equal(
        np.asarray(b1.evals_of(0)), np.asarray(b2.evals_of(0))
    )


@pytest.mark.slow
def test_vecne_sharded_obs_norm_divergence_bounded():
    # VERDICT r4 #6: with observation normalization ON, each shard normalizes
    # its lanes by shard-local cohort statistics mid-rollout (parity with the
    # reference's per-actor stats), so sharded scores legitimately differ
    # from unsharded ones. This test CHARACTERIZES that divergence instead of
    # just documenting it: same population, same seeds, flagship-like config
    # (locomotion env, obs-norm, multi-step episodes) — the deviation must
    # stay within the stated bounds.
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make():
        return VecNE(
            "hopper",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            episode_length=40,
            observation_normalization=True,
            seed=33,
        )

    rng = np.random.default_rng(14)
    p_plain, p_sharded = make(), make()
    values = jnp.asarray(
        rng.normal(size=(64, p_plain.solution_length)) * 0.2, jnp.float32
    )
    b_plain = SolutionBatch(p_plain, values=values)
    b_shard = SolutionBatch(p_sharded, values=values)
    p_plain.evaluate(b_plain)
    p_sharded.evaluate_sharded(b_shard)

    s_plain = np.asarray(b_plain.evals_of(0))
    s_shard = np.asarray(b_shard.evals_of(0))

    # population-mean scores agree within 10% relative
    m_plain, m_shard = s_plain.mean(), s_shard.mean()
    assert abs(m_shard - m_plain) <= 0.10 * abs(m_plain) + 1e-6, (m_plain, m_shard)

    # per-lane scores stay strongly rank-correlated (the selection signal the
    # search actually consumes survives the cohort semantics)
    def ranks(x):
        order = np.argsort(x)
        r = np.empty_like(order)
        r[order] = np.arange(len(x))
        return r

    ra, rb = ranks(s_plain).astype(np.float64), ranks(s_shard).astype(np.float64)
    spearman = np.corrcoef(ra, rb)[0, 1]
    assert spearman > 0.85, spearman

    # the merged running statistics agree closely with the global ones: the
    # same observations are absorbed, only the normalization each lane SAW
    # mid-rollout differed. Counts within 5%, moments within 15% rel.
    st_plain, st_shard = p_plain._obs_norm, p_sharded._obs_norm
    c_plain, c_shard = float(st_plain.count), float(st_shard.count)
    assert abs(c_shard - c_plain) <= 0.05 * c_plain, (c_plain, c_shard)
    mean_diff = np.max(
        np.abs(np.asarray(st_shard.mean) - np.asarray(st_plain.mean))
        / (np.abs(np.asarray(st_plain.mean)) + 0.1)
    )
    assert mean_diff < 0.15, mean_diff


def test_vecne_sharded_obs_norm_step_sync_matches_unsharded():
    # obs_norm_sync="step": the stat deltas psum-merge every control step, so
    # every shard normalizes by the MESH-GLOBAL cohort — the cohort
    # divergence (characterized in the test above) collapses to float
    # summation order. Reduction-order noise is amplified exponentially by
    # the contact dynamics (measured on hopper: max per-lane score diff
    # 9e-7 at T=2, 4e-3 at T=10, 0.3 at T=40), so the per-lane assertion
    # runs at a short horizon where it is meaningful; the absorbed
    # observation COUNT must match exactly at any horizon (the semantic
    # invariant — cohort mode can diverge even there, since actions differ).
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make(sync):
        return VecNE(
            "hopper",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            episode_length=10,
            observation_normalization=True,
            obs_norm_sync=sync,
            seed=33,
        )

    rng = np.random.default_rng(14)
    p_plain, p_sync = make("cohort"), make("step")
    values = jnp.asarray(
        rng.normal(size=(64, p_plain.solution_length)) * 0.2, jnp.float32
    )
    b_plain = SolutionBatch(p_plain, values=values)
    b_sync = SolutionBatch(p_sync, values=values)
    p_plain.evaluate(b_plain)         # unsharded: the global cohort
    p_sync.evaluate_sharded(b_sync)   # sharded with per-step stat sync

    np.testing.assert_allclose(
        np.asarray(b_sync.evals_of(0)), np.asarray(b_plain.evals_of(0)),
        atol=2e-2,
    )
    # the absorbed observation count matches EXACTLY: every shard saw the
    # global cohort, so the same episodes terminated at the same steps
    assert float(p_sync._obs_norm.count) == float(p_plain._obs_norm.count)
    np.testing.assert_allclose(
        np.asarray(p_sync._obs_norm.mean), np.asarray(p_plain._obs_norm.mean),
        rtol=1e-4, atol=1e-4,
    )


# -- work-conserving lane-refill scheduler (episodes_refill) ------------------


def test_refill_matches_monolithic_episodes_any_width():
    # the core contract: matched seeds => refill scores == plain `episodes`
    # scores BIT-FOR-BIT for every lane, at any fixed width — including a
    # popsize that is not divisible by W (the queue handles the remainder)
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 37  # deliberately not divisible by any tested width
    rng = np.random.default_rng(0)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=120)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(7), stats, eval_mode="episodes", **kw
    )
    for width in (5, 16):
        ref = run_vectorized_rollout(
            env, policy, params, jax.random.key(7), stats,
            eval_mode="episodes_refill", refill_width=width, **kw,
        )
        np.testing.assert_array_equal(
            np.asarray(ref.scores), np.asarray(mono.scores)
        )
        assert int(ref.total_steps) == int(mono.total_steps)
        assert int(ref.total_episodes) == n


def test_refill_accepts_legacy_uint32_key():
    # a legacy raw uint32 PRNGKey must work (the monolithic engine accepts
    # it, and the refill engine wraps it into a typed key array so the
    # lane-select jnp.where stays rank-1) and keep matched-seed bit-identity
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(5)
    params = jnp.asarray(rng.normal(size=(11, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=60)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.PRNGKey(2), stats,
        eval_mode="episodes", **kw,
    )
    ref = run_vectorized_rollout(
        env, policy, params, jax.random.PRNGKey(2), stats,
        eval_mode="episodes_refill", refill_width=4, **kw,
    )
    np.testing.assert_array_equal(np.asarray(ref.scores), np.asarray(mono.scores))


def test_refill_bit_exact_with_action_noise():
    # refill lanes carry the same per-lane PRNG chains (3-way split per step)
    # as the monolithic engine, so even the noise draws match draw-for-draw
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(9)
    params = jnp.asarray(rng.normal(size=(16, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=60, action_noise_stdev=0.1)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats, eval_mode="episodes", **kw
    )
    ref = run_vectorized_rollout(
        env, policy, params, jax.random.key(3), stats,
        eval_mode="episodes_refill", refill_width=6, **kw,
    )
    np.testing.assert_array_equal(np.asarray(ref.scores), np.asarray(mono.scores))


def test_refill_obs_norm_counts_only_live_lane_steps():
    # the step-count invariant: every counted interaction contributes exactly
    # one observation to the running statistics — idle (finished, waiting)
    # and drained lanes contribute nothing, refilled lanes contribute their
    # fresh reset observation
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(1)
    params = jnp.asarray(rng.normal(size=(24, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    for period in (1, 3):
        ref = run_vectorized_rollout(
            env, policy, params, jax.random.key(4), stats,
            eval_mode="episodes_refill", refill_width=8, refill_period=period,
            num_episodes=1, episode_length=80, observation_normalization=True,
        )
        assert float(ref.stats.count) == float(ref.total_steps)
        assert int(ref.total_episodes) == 24
        assert np.isfinite(np.asarray(ref.scores)).all()


def test_refill_multi_episode_accounting_and_period():
    # num_episodes > 1: every (solution, episode) item runs on its own PRNG
    # chain (distribution-equivalent to the monolithic engine, not bit-equal)
    # but the contract accounting must hold exactly, also with a refill
    # period > 1 (finished lanes wait masked between refill boundaries)
    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    rng = np.random.default_rng(2)
    n = 12
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    ref = run_vectorized_rollout(
        env, policy, params, jax.random.key(5), stats,
        eval_mode="episodes_refill", refill_width=16, refill_period=4,
        num_episodes=3, episode_length=60,
    )
    assert int(ref.total_episodes) == 3 * n
    assert np.isfinite(np.asarray(ref.scores)).all()
    assert float(jnp.min(ref.scores)) >= 1.0


def test_refill_sharded_matches_unsharded_and_monolithic():
    # per-shard queues under shard_map: global lane ids + a global seed
    # stride make the sharded refill evaluation reproduce BOTH the unsharded
    # refill one and the unsharded monolithic episodes contract bit-for-bit
    from jax.sharding import PartitionSpec as P

    from evotorch_tpu.neuroevolution.net.vecrl import global_lane_ids
    from evotorch_tpu.parallel.mesh import default_mesh

    env = CartPole(continuous_actions=True)
    policy = _linear_policy(env)
    n = 32
    rng = np.random.default_rng(5)
    params = jnp.asarray(rng.normal(size=(n, policy.parameter_count)), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    mesh = default_mesh(("pop",))
    kw = dict(num_episodes=1, episode_length=100)

    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(21), stats, eval_mode="episodes", **kw
    )

    def local(values_shard, key, stats):
        r = run_vectorized_rollout(
            env, policy, values_shard, key, stats,
            eval_mode="episodes_refill", refill_width=2, seed_stride=n,
            lane_ids=global_lane_ids("pop", values_shard.shape[0]), **kw,
        )
        return (
            r.scores,
            jax.lax.psum(r.total_steps, "pop"),
            jax.lax.psum(r.total_episodes, "pop"),
        )

    scores, steps, episodes = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P("pop"), P(), P()),
            out_specs=(P("pop"), P(), P()),
            check_vma=False,
        )
    )(params, jax.random.key(21), stats)
    np.testing.assert_array_equal(np.asarray(scores), np.asarray(mono.scores))
    assert int(steps) == int(mono.total_steps)
    assert int(episodes) == n


def test_vecne_refill_eval_mode_plain_and_sharded():
    # VecNE wiring: eval_mode="episodes_refill" with a refill_config, through
    # both the plain and the sharded evaluation paths — scores must equal the
    # episodes-mode problem's bit-for-bit, and the counters must agree
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make(mode, **extra):
        return VecNE(
            "cartpole",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            env_config={"continuous_actions": True},
            episode_length=60,
            eval_mode=mode,
            seed=9,
            **extra,
        )

    p_mono = make("episodes")
    p_ref = make("episodes_refill", refill_config={"width": 8})
    p_ref_sh = make("episodes_refill", refill_config={"width": 8, "period": 2})
    rng = np.random.default_rng(7)
    values = jnp.asarray(
        rng.normal(size=(24, p_mono.solution_length)) * 0.3, jnp.float32
    )
    b_mono = SolutionBatch(p_mono, values=values)
    b_ref = SolutionBatch(p_ref, values=values)
    b_sh = SolutionBatch(p_ref_sh, values=values)
    p_mono.evaluate(b_mono)
    p_ref.evaluate(b_ref)
    p_ref_sh.evaluate_sharded(b_sh)
    np.testing.assert_array_equal(
        np.asarray(b_ref.evals_of(0)), np.asarray(b_mono.evals_of(0))
    )
    np.testing.assert_array_equal(
        np.asarray(b_sh.evals_of(0)), np.asarray(b_mono.evals_of(0))
    )
    assert int(p_ref.status["total_episode_count"]) == 24
    assert int(p_ref.status["total_interaction_count"]) == int(
        p_mono.status["total_interaction_count"]
    )


def test_refill_nonzero_initial_policy_state_bit_exact():
    # refilled lanes must start their episode from policy.initial_state(),
    # NOT zeros: with a stateful module whose initial state is nonzero, a
    # solution evaluated in a refilled lane (any solution beyond the first
    # W) would otherwise diverge from the monolithic episodes evaluation
    from evotorch_tpu.neuroevolution.net.layers import Module

    class BiasedStateCell(Module):
        """Minimal stateful cell with a NONZERO initial state."""

        hidden = 4

        def init(self, key):
            return {"w": 0.1 * jnp.ones((self.hidden, 3))}

        def initial_state(self):
            return jnp.ones(self.hidden)  # deliberately not zeros

        def apply(self, params, x, state=None):
            if state is None:
                state = jnp.ones(x.shape[:-1] + (self.hidden,), dtype=x.dtype)
            h = jnp.tanh(x @ params["w"].T + state)
            return h, h

    env = Pendulum()
    net = BiasedStateCell() >> Linear(4, env.action_size)
    policy = FlatParamsPolicy(net)
    n = 12
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), n))
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=25)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, eval_mode="episodes", **kw
    )
    ref = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats,
        eval_mode="episodes_refill", refill_width=3, **kw,
    )
    np.testing.assert_array_equal(np.asarray(ref.scores), np.asarray(mono.scores))


def test_refill_invalid_mode_still_rejected():
    env = Pendulum()
    policy = _linear_policy(env)
    params = jnp.zeros((2, policy.parameter_count))
    stats = RunningNorm(env.observation_size).stats
    with pytest.raises(ValueError, match="eval_mode"):
        run_vectorized_rollout(
            env, policy, params, jax.random.key(0), stats, eval_mode="refill"
        )


def test_sharded_compacting_obs_norm_step_sync():
    # the compacting sharded runner with stats_sync=True: scores match the
    # unsharded monolithic episodes evaluation to float-order tolerance,
    # and the returned stats are already mesh-global (no double count)
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout,
        run_vectorized_rollout_compacting_sharded,
    )
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.parallel.mesh import default_mesh

    env = make_env("hopper")
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    rng = np.random.default_rng(7)
    values = jnp.asarray(
        rng.normal(size=(32, policy.parameter_count)) * 0.2, jnp.float32
    )
    stats = RunningNorm(env.observation_size).stats
    mesh = default_mesh(("pop",))

    # short horizon: reduction-order noise amplifies exponentially through
    # the contact dynamics (see the step-sync VecNE test above)
    r_ref = run_vectorized_rollout(
        env, policy, values, jax.random.key(5), stats,
        num_episodes=1, episode_length=10, observation_normalization=True,
        eval_mode="episodes",
    )
    # min_width=1 -> per-shard widths (1, 2) actually exist (n_local=4), so
    # the rollout exercises real compaction jumps WITH the per-step stat
    # collectives — the riskiest interaction of the feature
    r_sync = run_vectorized_rollout_compacting_sharded(
        env, policy, values, jax.random.key(5), stats,
        mesh=mesh, num_episodes=1, episode_length=10,
        observation_normalization=True, stats_sync=True,
        min_width=1, chunk_size=2,
    )
    np.testing.assert_allclose(
        np.asarray(r_sync.scores), np.asarray(r_ref.scores), atol=2e-2
    )
    # exact: every shard absorbed the global cohort every step
    assert float(r_sync.stats.count) == float(r_ref.stats.count)
    assert int(r_sync.total_episodes) == 32
