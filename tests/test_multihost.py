"""Multi-host (multi-process) SPMD: the DCN form of the Ray-cluster mode.

The reference attaches to clusters via ``ray start --head``
(``docs/advanced_usage/ray_cluster.md``); the TPU-native equivalent is
``jax.distributed.initialize`` — after which the same shard_map programs span
processes. This test launches two real OS processes, each owning 2 virtual
CPU devices, builds the 4-device global mesh, and runs this framework's
sharded ES-gradient estimator over it. Both processes must agree on the
(pmean-reduced) gradients.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    proc_id = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from evotorch_tpu.parallel import init_distributed

    init_distributed(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
    )
    assert jax.device_count() == 4, jax.device_count()

    import jax.numpy as jnp
    from evotorch_tpu.distributions import SymmetricSeparableGaussian
    from evotorch_tpu.parallel import default_mesh, make_sharded_grad_estimator

    def sphere(x):
        return jnp.sum(x**2, axis=-1)

    est = make_sharded_grad_estimator(
        SymmetricSeparableGaussian,
        sphere,
        objective_sense="min",
        ranking_method="centered",
        mesh=default_mesh(),  # global 4-device mesh spanning both processes
    )
    grads = est(
        jax.random.key(0),
        32,
        {"mu": jnp.full((4,), 3.0), "sigma": jnp.ones(4),
         "divide_mu_grad_by": "num_directions", "divide_sigma_grad_by": "num_directions"},
    )
    mu_grad = np.asarray(grads["mu"].addressable_data(0)) if hasattr(grads["mu"], "addressable_data") else np.asarray(grads["mu"])
    print("GRAD", proc_id, ",".join(f"{v:.6f}" for v in np.asarray(mu_grad)))
    """
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_sharded_gradients(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append(out)
    grads = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("GRAD"):
                _, pid, vals = line.split(" ", 2)
                grads[pid] = np.asarray([float(v) for v in vals.split(",")])
    assert set(grads) == {"0", "1"}
    # both hosts hold the identical pmean-reduced gradient
    assert np.allclose(grads["0"], grads["1"], atol=1e-6)
    # minimizing the sphere from mu=3: ascent gradient points down
    assert (grads["0"] < 0).all()
