"""Low-rank-perturbation evaluation (net/lowrank.py + funcpgpe lowrank mode).

The contract under test: everything about the low-rank path — the structured
policy forward, the rollout, and the PGPE update — must agree numerically
with materializing the dense population ``theta_i = c + B z_i`` and running
the ordinary dense path on it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from evotorch_tpu.algorithms.functional import (
    pgpe,
    pgpe_ask_lowrank,
    pgpe_tell,
    pgpe_tell_lowrank,
)
from evotorch_tpu.envs import CartPole, make_env
from evotorch_tpu.neuroevolution.net import (
    LSTM,
    FlatParamsPolicy,
    Linear,
    LowRankParamsBatch,
    Tanh,
    lowrank_forward,
)
from evotorch_tpu.neuroevolution.net.lowrank import lowrank_supported, prepare_lowrank
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.neuroevolution.net.vecrl import (
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)


def _mlp_policy(in_dim=9, hidden=16, out_dim=4):
    net = Linear(in_dim, hidden) >> Tanh() >> Linear(hidden, out_dim) >> Tanh()
    return FlatParamsPolicy(net)


def _random_lowrank(policy, n=12, k=5, seed=0):
    rng = np.random.default_rng(seed)
    L = policy.parameter_count
    return LowRankParamsBatch(
        center=jnp.asarray(rng.normal(size=L) * 0.3, jnp.float32),
        basis=jnp.asarray(rng.normal(size=(L, k)) * 0.1, jnp.float32),
        coeffs=jnp.asarray(rng.normal(size=(n, k)), jnp.float32),
    )


def test_supported_detection():
    assert lowrank_supported(_mlp_policy().module)
    assert not lowrank_supported((LSTM(4, 8) >> Linear(8, 2)))


def test_structured_forward_matches_dense():
    policy = _mlp_policy()
    params = _random_lowrank(policy)
    obs = jnp.asarray(np.random.default_rng(1).normal(size=(12, 9)), jnp.float32)

    out_lr, state = lowrank_forward(policy, params, None, obs, None)
    assert state is None

    dense = params.materialize()
    out_dense, _ = jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    np.testing.assert_allclose(np.asarray(out_lr), np.asarray(out_dense), rtol=1e-5, atol=1e-5)


def test_structured_forward_under_jit_with_prepared():
    policy = _mlp_policy()
    params = _random_lowrank(policy, n=8, k=3, seed=2)
    obs = jnp.asarray(np.random.default_rng(3).normal(size=(8, 9)), jnp.float32)

    @jax.jit
    def fwd(params, obs):
        prepared = prepare_lowrank(policy, params)
        out, _ = lowrank_forward(policy, params, prepared, obs, None)
        return out

    out = fwd(params, obs)
    dense, _ = jax.vmap(lambda p, o: policy(p, o))(params.materialize(), obs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=1e-5, atol=1e-5)


def test_recurrent_fallback_matches_dense():
    net = LSTM(5, 7) >> Linear(7, 3)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=6, k=4, seed=4)
    obs = jnp.asarray(np.random.default_rng(5).normal(size=(6, 5)), jnp.float32)
    proto = policy.initial_state()
    states = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (6,) + leaf.shape), proto
    )
    out_lr, st_lr = lowrank_forward(policy, params, None, obs, states)
    out_dense, st_dense = jax.vmap(policy)(params.materialize(), obs, states)
    np.testing.assert_allclose(np.asarray(out_lr), np.asarray(out_dense), rtol=1e-5, atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5),
        st_lr,
        st_dense,
    )


def test_rollout_lowrank_matches_dense_rollout():
    # the WHOLE jitted rollout must agree: same env keys, low-rank params vs
    # their materialization
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 16) >> Tanh() >> Linear(16, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=16, k=6, seed=6)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=60, observation_normalization=True)

    r_lr = run_vectorized_rollout(
        env, policy, params, jax.random.key(9), stats, eval_mode="episodes", **kw
    )
    r_dense = run_vectorized_rollout(
        env, policy, params.materialize(), jax.random.key(9), stats,
        eval_mode="episodes", **kw,
    )
    np.testing.assert_allclose(
        np.asarray(r_lr.scores), np.asarray(r_dense.scores), rtol=1e-4, atol=1e-4
    )
    assert int(r_lr.total_steps) == int(r_dense.total_steps)
    np.testing.assert_allclose(
        float(r_lr.stats.count), float(r_dense.stats.count)
    )


def test_rollout_lowrank_budget_and_bf16():
    env = make_env("hopper")
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=8, k=4, seed=7)
    stats = RunningNorm(env.observation_size).stats
    r = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats,
        num_episodes=1, episode_length=30, eval_mode="budget",
        compute_dtype=jnp.bfloat16,
    )
    assert int(r.total_steps) == 8 * 30
    assert np.isfinite(np.asarray(r.scores)).all()


def test_compacting_rollout_accepts_lowrank():
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=16, k=4, seed=8)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=80)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats, eval_mode="episodes", **kw
    )
    comp = run_vectorized_rollout_compacting(
        env, policy, params, jax.random.key(2), stats,
        chunk_size=10, allowed_widths=(4, 8), **kw,
    )
    np.testing.assert_allclose(
        np.asarray(comp.scores), np.asarray(mono.scores), rtol=1e-5, atol=1e-5
    )


def test_pgpe_lowrank_tell_matches_dense_tell():
    # the factored gradient math must equal pgpe_tell on the materialized
    # population exactly (same optimizer state, same stdev update)
    L = 40
    state = pgpe(
        center_init=jnp.zeros(L),
        center_learning_rate=0.3,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.7,
        optimizer="clipup",
        optimizer_config={"max_speed": 0.3},
    )
    params = pgpe_ask_lowrank(jax.random.key(3), state, popsize=24, rank=6)
    assert params.coeffs.shape == (24, 6)
    # antithetic layout
    np.testing.assert_allclose(
        np.asarray(params.coeffs[0::2]), -np.asarray(params.coeffs[1::2])
    )
    evals = jnp.asarray(np.random.default_rng(11).normal(size=24), jnp.float32)

    s_lr = pgpe_tell_lowrank(state, params, evals)
    s_dense = pgpe_tell(state, params.materialize(), evals)

    np.testing.assert_allclose(
        np.asarray(s_lr.stdev), np.asarray(s_dense.stdev), rtol=1e-4, atol=1e-6
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s_lr.optimizer_state,
        s_dense.optimizer_state,
    )


def test_pgpe_lowrank_improves_sphere():
    # end-to-end sanity: low-rank PGPE actually optimizes (sphere, max of -||x||^2)
    L = 30
    state = pgpe(
        center_init=jnp.full(L, 3.0),
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.5,
        optimizer="adam",
    )
    key = jax.random.key(0)

    def gen(state, key):
        params = pgpe_ask_lowrank(key, state, popsize=64, rank=8)
        dense = params.materialize()
        evals = -jnp.sum(dense**2, axis=-1)
        return pgpe_tell_lowrank(state, params, evals), jnp.mean(evals)

    first = None
    for i in range(60):
        key, sub = jax.random.split(key)
        state, mean_eval = gen(state, sub)
        if first is None:
            first = float(mean_eval)
    assert float(mean_eval) > first * 0.2  # losses shrink toward 0 (maximizing -||x||^2)
    assert float(mean_eval) > -L  # well below the initial ~ -9L
