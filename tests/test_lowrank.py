"""Low-rank-perturbation evaluation (net/lowrank.py + funcpgpe lowrank mode).

The contract under test: everything about the low-rank path — the structured
policy forward, the rollout, and the PGPE update — must agree numerically
with materializing the dense population ``theta_i = c + B z_i`` and running
the ordinary dense path on it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.algorithms.functional import (
    pgpe,
    pgpe_ask_lowrank,
    pgpe_tell,
    pgpe_tell_lowrank,
)
from evotorch_tpu.envs import CartPole, make_env
from evotorch_tpu.neuroevolution.net import (
    LSTM,
    RNN,
    FlatParamsPolicy,
    Linear,
    LowRankParamsBatch,
    Tanh,
    lowrank_forward,
)
from evotorch_tpu.neuroevolution.net.layers import Module as ModuleBase
from evotorch_tpu.neuroevolution.net.lowrank import lowrank_supported, prepare_lowrank
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.neuroevolution.net.vecrl import (
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)


def _mlp_policy(in_dim=9, hidden=16, out_dim=4):
    net = Linear(in_dim, hidden) >> Tanh() >> Linear(hidden, out_dim) >> Tanh()
    return FlatParamsPolicy(net)


def _random_lowrank(policy, n=12, k=5, seed=0):
    rng = np.random.default_rng(seed)
    L = policy.parameter_count
    return LowRankParamsBatch(
        center=jnp.asarray(rng.normal(size=L) * 0.3, jnp.float32),
        basis=jnp.asarray(rng.normal(size=(L, k)) * 0.1, jnp.float32),
        coeffs=jnp.asarray(rng.normal(size=(n, k)), jnp.float32),
    )


class _UnstructuredModule(ModuleBase):
    """A parameterized module with no structured low-rank path (its parameter
    enters multiplicatively per-feature, not through a matmul)."""

    def init(self, key):
        return {"scale": jnp.ones(3)}

    def apply(self, params, x, state=None):
        return x * params["scale"], state


def test_supported_detection():
    assert lowrank_supported(_mlp_policy().module)
    # recurrent cells now have a structured path (VERDICT r3 #4)
    assert lowrank_supported(LSTM(4, 8) >> Linear(8, 2))
    assert lowrank_supported(RNN(4, 8) >> Linear(8, 2))
    assert not lowrank_supported(Linear(4, 3) >> _UnstructuredModule())


def test_unsupported_module_falls_back_with_warning():
    policy = FlatParamsPolicy(Linear(3, 3) >> _UnstructuredModule())
    params = _random_lowrank(policy, n=4, k=2, seed=10)
    obs = jnp.asarray(np.random.default_rng(12).normal(size=(4, 3)), jnp.float32)
    with pytest.warns(UserWarning, match="materializ"):
        out_lr, _ = lowrank_forward(policy, params, None, obs, None)
    out_dense, _ = jax.vmap(lambda p, o: policy(p, o))(params.materialize(), obs)
    np.testing.assert_allclose(np.asarray(out_lr), np.asarray(out_dense), rtol=1e-5, atol=1e-5)


def test_structured_forward_matches_dense():
    policy = _mlp_policy()
    params = _random_lowrank(policy)
    obs = jnp.asarray(np.random.default_rng(1).normal(size=(12, 9)), jnp.float32)

    out_lr, state = lowrank_forward(policy, params, None, obs, None)
    assert state is None

    dense = params.materialize()
    out_dense, _ = jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    np.testing.assert_allclose(np.asarray(out_lr), np.asarray(out_dense), rtol=1e-5, atol=1e-5)


def test_structured_forward_under_jit_with_prepared():
    policy = _mlp_policy()
    params = _random_lowrank(policy, n=8, k=3, seed=2)
    obs = jnp.asarray(np.random.default_rng(3).normal(size=(8, 9)), jnp.float32)

    @jax.jit
    def fwd(params, obs):
        prepared = prepare_lowrank(policy, params)
        out, _ = lowrank_forward(policy, params, prepared, obs, None)
        return out

    out = fwd(params, obs)
    dense, _ = jax.vmap(lambda p, o: policy(p, o))(params.materialize(), obs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "net_fn",
    [
        lambda: LSTM(5, 7) >> Linear(7, 3),
        lambda: RNN(5, 7) >> Tanh() >> Linear(7, 3),
        lambda: Linear(5, 6) >> Tanh() >> LSTM(6, 8) >> Linear(8, 3),
    ],
    ids=["lstm", "rnn", "mixed"],
)
def test_recurrent_structured_matches_dense(net_fn):
    # the structured recurrent path (augmented matmuls on both the input and
    # hidden contractions) must agree with the dense vmap step-by-step,
    # INCLUDING the threaded hidden state, over several steps
    policy = FlatParamsPolicy(net_fn())
    params = _random_lowrank(policy, n=6, k=4, seed=4)
    rng = np.random.default_rng(5)
    proto = policy.initial_state()
    states_lr = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (6,) + leaf.shape), proto
    )
    states_dense = states_lr
    dense = params.materialize()
    for t in range(4):
        obs = jnp.asarray(rng.normal(size=(6, 5)), jnp.float32)
        out_lr, states_lr = lowrank_forward(policy, params, None, obs, states_lr)
        out_dense, states_dense = jax.vmap(policy)(dense, obs, states_dense)
        np.testing.assert_allclose(
            np.asarray(out_lr), np.asarray(out_dense), rtol=1e-4, atol=1e-5
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            states_lr,
            states_dense,
        )


def test_recurrent_rollout_lowrank_matches_dense():
    # the whole jitted rollout with a recurrent policy: low-rank vs dense
    env = CartPole(continuous_actions=True)
    net = RNN(env.observation_size, 8) >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=8, k=3, seed=13)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=40)
    r_lr = run_vectorized_rollout(
        env, policy, params, jax.random.key(4), stats, eval_mode="episodes", **kw
    )
    r_dense = run_vectorized_rollout(
        env, policy, params.materialize(), jax.random.key(4), stats,
        eval_mode="episodes", **kw,
    )
    np.testing.assert_allclose(
        np.asarray(r_lr.scores), np.asarray(r_dense.scores), rtol=1e-4, atol=1e-4
    )
    assert int(r_lr.total_steps) == int(r_dense.total_steps)


def test_rollout_lowrank_matches_dense_rollout():
    # the WHOLE jitted rollout must agree: same env keys, low-rank params vs
    # their materialization
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 16) >> Tanh() >> Linear(16, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=16, k=6, seed=6)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=60, observation_normalization=True)

    r_lr = run_vectorized_rollout(
        env, policy, params, jax.random.key(9), stats, eval_mode="episodes", **kw
    )
    r_dense = run_vectorized_rollout(
        env, policy, params.materialize(), jax.random.key(9), stats,
        eval_mode="episodes", **kw,
    )
    np.testing.assert_allclose(
        np.asarray(r_lr.scores), np.asarray(r_dense.scores), rtol=1e-4, atol=1e-4
    )
    assert int(r_lr.total_steps) == int(r_dense.total_steps)
    np.testing.assert_allclose(
        float(r_lr.stats.count), float(r_dense.stats.count)
    )


def test_rollout_lowrank_budget_and_bf16():
    env = make_env("hopper")
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=8, k=4, seed=7)
    stats = RunningNorm(env.observation_size).stats
    r = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats,
        num_episodes=1, episode_length=30, eval_mode="budget",
        compute_dtype=jnp.bfloat16,
    )
    assert int(r.total_steps) == 8 * 30
    assert np.isfinite(np.asarray(r.scores)).all()


def test_compacting_rollout_accepts_lowrank():
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=16, k=4, seed=8)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=80)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats, eval_mode="episodes", **kw
    )
    comp = run_vectorized_rollout_compacting(
        env, policy, params, jax.random.key(2), stats,
        chunk_size=10, allowed_widths=(4, 8), **kw,
    )
    np.testing.assert_allclose(
        np.asarray(comp.scores), np.asarray(mono.scores), rtol=1e-5, atol=1e-5
    )


def test_refill_rollout_accepts_lowrank():
    # the lane-refill scheduler carries per-lane COEFFICIENTS only (the
    # shared center/basis stay loop-invariant) and must agree with the
    # monolithic episodes evaluation of the same factored population
    env = CartPole(continuous_actions=True)
    net = Linear(env.observation_size, 8) >> Tanh() >> Linear(8, env.action_size)
    policy = FlatParamsPolicy(net)
    params = _random_lowrank(policy, n=16, k=4, seed=8)
    stats = RunningNorm(env.observation_size).stats
    kw = dict(num_episodes=1, episode_length=80)
    mono = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats, eval_mode="episodes", **kw
    )
    refill = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats,
        eval_mode="episodes_refill", refill_width=4, **kw,
    )
    np.testing.assert_array_equal(np.asarray(refill.scores), np.asarray(mono.scores))
    assert int(refill.total_steps) == int(mono.total_steps)


# -- basis_capture: the subspace-exhaustion guardrail --------------------------


def test_basis_capture_math():
    from evotorch_tpu.tools.lowrank import basis_capture

    L, k = 2000, 16
    basis = jax.random.normal(jax.random.key(0), (L, k))
    # a random direction: captured fraction concentrates around sqrt(k/L)
    v = jax.random.normal(jax.random.key(1), (L,))
    cap = float(basis_capture(basis, v))
    expected = (k / L) ** 0.5
    assert 0.2 * expected < cap < 5 * expected
    # an in-span vector is fully captured; the zero vector reports 1.0
    v_in = basis @ jax.random.normal(jax.random.key(2), (k,))
    assert float(basis_capture(basis, v_in)) > 0.999
    assert float(basis_capture(basis, jnp.zeros(L))) == 1.0


@pytest.mark.slow
def test_lowrank_rank32_halfcheetah_exhaustion_warns():
    """Miniature of the HalfCheetah rank-32 stall
    (bench_curves/halfcheetah_lowrank_cpu_r5.jsonl: rank 32 plateaus at ~470
    while rank 64 and dense reach ~1050): at the stalling configuration's
    rank/L ratio the per-generation basis captures well under 10% of the
    accumulated gradient direction, and the subspace-exhaustion guardrail
    must both report it (status basis_capture) and warn."""
    import warnings

    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        "halfcheetah",
        # the curve's network shape: rank 32 against L ~ 8.6k
        "Linear(obs_length, 64) >> Tanh() >> Linear(64, 64) >> Tanh()"
        " >> Linear(64, act_length)",
        episode_length=5,
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=8,
        center_learning_rate=0.05,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
        lowrank_rank=32,
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for _ in range(7):
            searcher.step()
    capture = searcher.status["basis_capture"]
    assert capture is not None and capture < 0.1
    exhaustion = [
        w for w in caught if "subspace exhaustion" in str(w.message)
    ]
    assert len(exhaustion) == 1  # fires once, not every generation
    assert "rank-32" in str(exhaustion[0].message)


def test_pgpe_lowrank_tell_matches_dense_tell():
    # the factored gradient math must equal pgpe_tell on the materialized
    # population exactly (same optimizer state, same stdev update)
    L = 40
    state = pgpe(
        center_init=jnp.zeros(L),
        center_learning_rate=0.3,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.7,
        optimizer="clipup",
        optimizer_config={"max_speed": 0.3},
    )
    params = pgpe_ask_lowrank(jax.random.key(3), state, popsize=24, rank=6)
    assert params.coeffs.shape == (24, 6)
    # antithetic layout
    np.testing.assert_allclose(
        np.asarray(params.coeffs[0::2]), -np.asarray(params.coeffs[1::2])
    )
    evals = jnp.asarray(np.random.default_rng(11).normal(size=24), jnp.float32)

    s_lr = pgpe_tell_lowrank(state, params, evals)
    s_dense = pgpe_tell(state, params.materialize(), evals)

    np.testing.assert_allclose(
        np.asarray(s_lr.stdev), np.asarray(s_dense.stdev), rtol=1e-4, atol=1e-6
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s_lr.optimizer_state,
        s_dense.optimizer_state,
    )


def test_pgpe_lowrank_improves_sphere():
    # end-to-end sanity: low-rank PGPE actually optimizes (sphere, max of -||x||^2)
    L = 30
    state = pgpe(
        center_init=jnp.full(L, 3.0),
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.5,
        optimizer="adam",
    )
    key = jax.random.key(0)

    def gen(state, key):
        params = pgpe_ask_lowrank(key, state, popsize=64, rank=8)
        dense = params.materialize()
        evals = -jnp.sum(dense**2, axis=-1)
        return pgpe_tell_lowrank(state, params, evals), jnp.mean(evals)

    first = None
    for i in range(60):
        key, sub = jax.random.split(key)
        state, mean_eval = gen(state, sub)
        if first is None:
            first = float(mean_eval)
    assert float(mean_eval) > first * 0.2  # losses shrink toward 0 (maximizing -||x||^2)
    assert float(mean_eval) > -L  # well below the initial ~ -9L


# ---------------------------- OO API wiring ----------------------------------
# VERDICT r3 #3: the low-rank path must be reachable from the OO API —
# PGPE(..., lowrank_rank=k) end-to-end ask -> rollout -> tell without
# densifying.


def _sphere_problem():
    from evotorch_tpu import Problem, vectorized

    @vectorized
    def sphere(xs):
        return jnp.sum(xs**2, axis=-1)

    return Problem("min", sphere, solution_length=30, initial_bounds=(2.5, 3.5))


def test_oo_pgpe_lowrank_improves_sphere():
    from evotorch_tpu.algorithms import PGPE

    problem = _sphere_problem()
    searcher = PGPE(
        problem,
        popsize=64,
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        stdev_init=0.5,
        optimizer="adam",
        lowrank_rank=8,
    )
    searcher.run(40)
    assert float(searcher.status["mean_eval"]) < 30.0  # from ~9*30 initially
    # best tracking worked through the factored batches
    assert float(searcher.status["best_eval"]) < 30.0
    best = searcher.status["best"]
    assert best.values.shape == (30,)


def test_oo_pgpe_lowrank_population_is_factored():
    # the population batch must HOLD the factored representation (not a
    # densified copy), and slicing it must gather coefficient lanes
    from evotorch_tpu.algorithms import PGPE

    problem = _sphere_problem()
    searcher = PGPE(
        problem,
        popsize=16,
        center_learning_rate=0.3,
        stdev_learning_rate=0.1,
        stdev_init=0.5,
        lowrank_rank=4,
    )
    searcher.step()
    pop = searcher.population
    assert isinstance(pop.values, LowRankParamsBatch)
    assert pop.values.coeffs.shape == (16, 4)
    sub = pop[2:6]
    assert isinstance(sub.values, LowRankParamsBatch)
    assert sub.values.coeffs.shape == (4, 4)
    np.testing.assert_allclose(
        np.asarray(sub.values.coeffs), np.asarray(pop.values.coeffs[2:6])
    )
    # a single Solution densifies just its row
    sln = pop[3]
    np.testing.assert_allclose(
        np.asarray(sln.values), np.asarray(pop.values.materialize()[3]), rtol=1e-6
    )


def test_oo_lowrank_gradients_match_dense_gradients():
    # the OO gradient dispatch (compute_gradients on a LowRankParamsBatch)
    # must equal the dense gradients on the materialized population
    from evotorch_tpu.distributions import SymmetricSeparableGaussian

    L, n, k = 20, 12, 5
    dist = SymmetricSeparableGaussian(
        {
            "mu": jnp.zeros(L),
            "sigma": jnp.full(L, 0.6),
            "divide_mu_grad_by": "num_directions",
            "divide_sigma_grad_by": "num_directions",
        }
    )
    params = dist.sample_lowrank(n, k, key=jax.random.key(7))
    fitnesses = jnp.asarray(np.random.default_rng(8).normal(size=n), jnp.float32)
    g_lr = dist.compute_gradients(
        params, fitnesses, objective_sense="max", ranking_method="centered"
    )
    g_dense = dist.compute_gradients(
        params.materialize(), fitnesses, objective_sense="max", ranking_method="centered"
    )
    np.testing.assert_allclose(
        np.asarray(g_lr["mu"]), np.asarray(g_dense["mu"]), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(g_lr["sigma"]), np.asarray(g_dense["sigma"]), rtol=1e-4, atol=1e-6
    )


def test_oo_pgpe_lowrank_validation():
    from evotorch_tpu.algorithms import PGPE

    problem = _sphere_problem()
    with pytest.raises(ValueError, match="symmetric"):
        PGPE(
            problem, popsize=16, center_learning_rate=0.3, stdev_learning_rate=0.1,
            stdev_init=0.5, symmetric=False, lowrank_rank=4,
        )


def test_oo_vecne_pgpe_lowrank_never_densifies(monkeypatch):
    # end-to-end: PGPE(lowrank_rank=k) over a VecNE problem with an MLP
    # policy — the dense (N, L) population must never be materialized
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE
    from evotorch_tpu.tools.lowrank import LowRankParamsBatch as LRB

    problem = VecNE(
        "cartpole",
        "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
        env_config={"continuous_actions": True},
        episode_length=24,
        observation_normalization=True,
    )
    calls = {"n": 0}
    orig = LRB.materialize

    def counting(self):
        calls["n"] += 1
        return orig(self)

    monkeypatch.setattr(LRB, "materialize", counting)
    searcher = PGPE(
        problem,
        popsize=12,
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
        lowrank_rank=4,
    )
    searcher.run(2)
    assert calls["n"] == 0, "the dense population was materialized on the hot path"
    assert np.isfinite(float(searcher.status["mean_eval"]))


def test_vecne_evaluate_sharded_lowrank():
    # the factored population shards its coefficients over the pop mesh; with
    # identical problem seeds the sharded factored evaluation must match the
    # sharded DENSE evaluation of the materialized population exactly (same
    # per-shard key folding, same rollout — only the representation differs)
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.distributions import SymmetricSeparableGaussian
    from evotorch_tpu.neuroevolution import VecNE

    def make():
        return VecNE(
            "cartpole",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            env_config={"continuous_actions": True},
            episode_length=16,
            seed=5,
        )

    factored_problem = make()
    L = factored_problem.solution_length
    dist = SymmetricSeparableGaussian({"mu": jnp.zeros(L), "sigma": jnp.full(L, 0.2)})
    params = dist.sample_lowrank(16, 4, key=jax.random.key(11))

    dense_problem = make()
    b_lr = SolutionBatch(factored_problem, values=params)
    b_dense = SolutionBatch(dense_problem, values=params.materialize())
    factored_problem.evaluate_sharded(b_lr)
    dense_problem.evaluate_sharded(b_dense)
    np.testing.assert_allclose(
        np.asarray(b_lr.evals_of(0)), np.asarray(b_dense.evals_of(0)),
        rtol=1e-4, atol=1e-4,
    )


# -------------------- lifted restrictions (VERDICT r4 #5) --------------------
# Factored batches concatenate when they share a generation's basis, so
# lowrank_rank composes with num_interactions/popsize_max (the reference's
# flagship adaptive-popsize recipe, rl_clipup.py:184-191) and with
# distributed=True.


def test_factored_cat_shared_basis():
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.distributions import SymmetricSeparableGaussian

    problem = _sphere_problem()
    L = problem.solution_length
    dist = SymmetricSeparableGaussian({"mu": jnp.zeros(L), "sigma": jnp.full(L, 0.5)})
    first = dist.sample_lowrank(8, 3, key=jax.random.key(0))
    second = dist.sample_lowrank(6, 3, key=jax.random.key(1), basis=first.basis)
    # the shared-basis sampler reuses the basis array (no copy, no re-fold)
    assert second.basis is first.basis
    b1 = SolutionBatch(problem, values=first)
    b2 = SolutionBatch(problem, values=second)
    merged = SolutionBatch.cat([b1, b2])
    assert isinstance(merged.values, LowRankParamsBatch)
    assert merged.values.coeffs.shape == (14, 3)
    np.testing.assert_allclose(
        np.asarray(merged.values.materialize()),
        np.vstack([np.asarray(first.materialize()), np.asarray(second.materialize())]),
        rtol=1e-6,
    )


def test_factored_cat_rejects_mismatched_basis_and_mixed():
    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.distributions import SymmetricSeparableGaussian

    problem = _sphere_problem()
    L = problem.solution_length
    dist = SymmetricSeparableGaussian({"mu": jnp.zeros(L), "sigma": jnp.full(L, 0.5)})
    a = dist.sample_lowrank(8, 3, key=jax.random.key(0))
    b = dist.sample_lowrank(8, 3, key=jax.random.key(99))  # fresh basis
    with pytest.raises(TypeError, match="share one generation's"):
        SolutionBatch.cat(
            [SolutionBatch(problem, values=a), SolutionBatch(problem, values=b)]
        )
    dense = SolutionBatch(problem, values=a.materialize())
    with pytest.raises(TypeError, match="factored"):
        SolutionBatch.cat([SolutionBatch(problem, values=a), dense])


@pytest.mark.slow
def test_oo_pgpe_lowrank_adaptive_popsize_vecne():
    # the reference's flagship recipe shape (popsize -> popsize_max under an
    # interaction budget, rl_clipup.py:184-191) running factored end-to-end:
    # per-generation shared basis keeps the adaptive rounds concatenable
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        "cartpole",
        "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
        env_config={"continuous_actions": True},
        episode_length=8,
        observation_normalization=True,
    )
    searcher = PGPE(
        problem,
        popsize=8,
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
        lowrank_rank=4,
        num_interactions=8 * 8 * 3,  # force ~3 sampling rounds per generation
        popsize_max=64,
    )
    searcher.run(3)
    pop = searcher.population
    assert isinstance(pop.values, LowRankParamsBatch)
    assert len(pop) > 8  # the budget actually grew the population
    assert len(pop) <= 64
    assert pop.values.coeffs.shape[0] == len(pop)
    assert np.isfinite(float(searcher.status["mean_eval"]))
    assert searcher.status["popsize"] == len(pop)


def test_oo_pgpe_lowrank_distributed_improves_sphere():
    # distributed=True routes through sample_and_compute_gradients; the
    # factored path must both run and actually optimize
    from evotorch_tpu.algorithms import PGPE

    problem = _sphere_problem()
    searcher = PGPE(
        problem,
        popsize=64,
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        stdev_init=0.5,
        optimizer="adam",
        distributed=True,
        lowrank_rank=8,
    )
    searcher.run(40)
    assert float(searcher.status["mean_eval"]) < 30.0  # from ~9*30 initially


def test_oo_pgpe_lowrank_distributed_reports_basis_capture():
    # the subspace-exhaustion guardrail must also cover the distributed
    # step path (both the single-program fallback and the sharded
    # estimator surface the generation's basis in the gradient results)
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.parallel import default_mesh

    for sharded in (False, True):
        problem = _sphere_problem()
        if sharded:
            problem.use_sharded_evaluation(default_mesh(("pop",)))
        searcher = PGPE(
            problem,
            popsize=64,
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            stdev_init=0.5,
            distributed=True,
            lowrank_rank=8,
        )
        # capture compares the basis against the PREVIOUS generations'
        # gradient-direction EMA, so it needs at least two steps
        searcher.run(3)
        capture = searcher.status["basis_capture"]
        assert capture is not None, f"sharded={sharded}"
        assert 0.0 <= float(capture) <= 1.0


def test_oo_pgpe_lowrank_distributed_adaptive_vecne():
    # distributed + num_interactions + lowrank all at once (the full
    # reference Humanoid configuration, minus the scale)
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        "cartpole",
        "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
        env_config={"continuous_actions": True},
        episode_length=8,
    )
    searcher = PGPE(
        problem,
        popsize=8,
        center_learning_rate=0.2,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
        lowrank_rank=4,
        distributed=True,
        num_interactions=8 * 8 * 2,
        popsize_max=32,
    )
    searcher.run(2)
    assert np.isfinite(float(searcher.status["mean_eval"]))


def test_sharded_grad_estimator_lowrank_matches_local_math():
    # the GSPMD factored estimator samples with the GLOBAL key (single-
    # process semantics): on a 1-shard mesh it must equal the classmethod
    # pipeline run by hand with the same key, no per-shard fold
    from evotorch_tpu.distributions import SymmetricSeparableGaussian
    from evotorch_tpu.parallel.grad import make_sharded_grad_estimator
    from evotorch_tpu.parallel.mesh import default_mesh
    from evotorch_tpu.tools.ranking import rank

    L, n, k = 24, 16, 4
    params = {
        "mu": jnp.zeros(L),
        "sigma": jnp.full(L, 0.4),
        "divide_mu_grad_by": "num_directions",
        "divide_sigma_grad_by": "num_directions",
    }

    def fitness(xs):
        return -jnp.sum(xs**2, axis=-1)

    mesh = default_mesh(("pop",), devices=jax.devices()[:1])
    est = make_sharded_grad_estimator(
        SymmetricSeparableGaussian,
        fitness,
        objective_sense="max",
        ranking_method="centered",
        mesh=mesh,
        axis_name="pop",
        lowrank_rank=k,
    )
    key = jax.random.key(3)
    grads = est(key, n, params)

    samples = SymmetricSeparableGaussian._sample_lowrank(key, params, n, k)
    weights = rank(fitness(samples.materialize()), "centered", higher_is_better=True)
    want = SymmetricSeparableGaussian._compute_gradients(
        params, samples, weights, "centered"
    )
    np.testing.assert_allclose(
        np.asarray(grads["mu"]), np.asarray(want["mu"]), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(grads["sigma"]), np.asarray(want["sigma"]), rtol=1e-5, atol=1e-6
    )


def test_sharded_grad_estimator_lowrank_multishard_runs():
    # 4 shards, per-shard bases (per-actor analog): grads replicate and are
    # finite; mean_eval aux agrees with a plausible fitness scale
    from evotorch_tpu.distributions import SymmetricSeparableGaussian
    from evotorch_tpu.parallel.grad import make_sharded_grad_estimator
    from evotorch_tpu.parallel.mesh import default_mesh

    L, k = 24, 4
    params = {
        "mu": jnp.zeros(L),
        "sigma": jnp.full(L, 0.4),
        "divide_mu_grad_by": "num_directions",
        "divide_sigma_grad_by": "num_directions",
    }

    def fitness(xs):
        return -jnp.sum(xs**2, axis=-1)

    mesh = default_mesh(("pop",), devices=jax.devices()[:4])
    est = make_sharded_grad_estimator(
        SymmetricSeparableGaussian,
        fitness,
        objective_sense="max",
        ranking_method="centered",
        mesh=mesh,
        axis_name="pop",
        lowrank_rank=k,
        with_aux=True,
    )
    grads, aux = est(jax.random.key(5), 32, params)
    assert grads["mu"].shape == (L,)
    assert grads["sigma"].shape == (L,)
    assert bool(jnp.all(jnp.isfinite(grads["mu"])))
    assert bool(jnp.all(jnp.isfinite(grads["sigma"])))
    assert float(aux["mean_eval"]) < 0  # -||x||^2 is negative
