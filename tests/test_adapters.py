"""Optional-dependency adapters exercised against vendored API doubles.

brax and cma are not installed in this image; previously ``braxenv.py`` and
``PyCMAES`` were import-gated dead code (VERDICT r1 "what's weak" #2/#7).
These tests inject minimal fakes that mimic the upstream API surfaces
(brax's ``envs.get_environment``/``State`` and cma's
``CMAEvolutionStrategy``), so the adapter logic — state threading, truncation,
registry strings, sense flipping, ask/tell plumbing — is genuinely executed.
When the real packages are present the same tests run against them unchanged
for the brax case (the fake is only installed if the import fails).
"""

import sys
import types
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# ---------------------------------------------------------------- fake brax
class _FakeBraxState(NamedTuple):
    pipeline_state: jnp.ndarray  # stands in for brax's physics state
    obs: jnp.ndarray
    reward: jnp.ndarray
    done: jnp.ndarray


class _FakeBraxEnv:
    """Point-mass: action accelerates a 2-D point; episode ends if |pos|>10.
    API shape matches brax.envs 'Env' closely enough for the adapter."""

    observation_size = 4
    action_size = 2

    def reset(self, rng):
        pos = jax.random.uniform(rng, (2,), minval=-0.1, maxval=0.1)
        obs = jnp.concatenate([pos, jnp.zeros(2)])
        return _FakeBraxState(
            pipeline_state=obs, obs=obs, reward=jnp.zeros(()), done=jnp.zeros(())
        )

    def step(self, state, action):
        pos, vel = state.obs[:2], state.obs[2:]
        vel = vel + 0.1 * jnp.clip(action, -1.0, 1.0)
        pos = pos + 0.1 * vel
        obs = jnp.concatenate([pos, vel])
        reward = -jnp.sum(pos**2)
        done = (jnp.linalg.norm(pos) > 10.0).astype(jnp.float32)
        return _FakeBraxState(pipeline_state=obs, obs=obs, reward=reward, done=done)


def _install_fake_brax(monkeypatch):
    try:
        import brax.envs  # noqa: F401 — real brax wins when available

        return
    except ImportError:
        pass
    brax_mod = types.ModuleType("brax")
    envs_mod = types.ModuleType("brax.envs")

    def get_environment(name, **kwargs):
        assert name == "fakepoint"
        return _FakeBraxEnv()

    envs_mod.get_environment = get_environment
    brax_mod.envs = envs_mod
    monkeypatch.setitem(sys.modules, "brax", brax_mod)
    monkeypatch.setitem(sys.modules, "brax.envs", envs_mod)


def test_brax_adapter_rollout(monkeypatch):
    _install_fake_brax(monkeypatch)
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    env = make_env("brax::fakepoint", episode_length=25)
    assert env.observation_size == 4 and env.action_size == 2

    # single reset/step contract
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (4,)
    state2, obs2, reward, done = env.step(state, jnp.ones(2))
    assert obs2.shape == (4,) and np.isfinite(float(reward))
    assert not bool(done)

    # full jitted rollout across a population, truncation at 25 steps
    policy = FlatParamsPolicy(Linear(4, 2))
    params = jax.random.normal(jax.random.key(1), (8, policy.parameter_count)) * 0.1
    stats = RunningNorm(4).stats
    res = run_vectorized_rollout(
        env, policy, params, jax.random.key(2), stats, num_episodes=1
    )
    assert int(res.total_episodes) == 8
    assert int(res.total_steps) == 8 * 25  # nothing leaves the bowl => all truncate
    assert np.isfinite(np.asarray(res.scores)).all()


def test_brax_adapter_through_vecne(monkeypatch):
    _install_fake_brax(monkeypatch)
    from evotorch_tpu.algorithms import SNES
    from evotorch_tpu.neuroevolution import VecNE

    prob = VecNE(
        "brax::fakepoint",
        "Linear(obs_length, act_length)",
        episode_length=10,
        num_episodes=1,
    )
    searcher = SNES(prob, popsize=8, stdev_init=0.1)
    searcher.run(3)
    assert np.isfinite(searcher.status["mean_eval"])
    assert prob.status["total_interaction_count"] > 0


# ----------------------------------------------------------------- fake cma
class _FakeCMAES:
    """Mimics cma.CMAEvolutionStrategy's ask/tell/popsize surface with a
    plain (mu, sigma) random search — enough to exercise the wrapper."""

    def __init__(self, x0, sigma0, opts):
        self._mu = np.asarray(x0, dtype=np.float64)
        self._sigma = float(sigma0)
        self.popsize = int(opts.get("popsize", 8))
        self._rng = np.random.default_rng(0)
        self._told = 0

    def ask(self):
        return [
            self._mu + self._sigma * self._rng.standard_normal(self._mu.shape)
            for _ in range(self.popsize)
        ]

    def tell(self, solutions, fitnesses):
        order = np.argsort(fitnesses)  # cma minimizes
        elite = np.asarray(solutions)[order[: max(1, self.popsize // 4)]]
        self._mu = elite.mean(axis=0)
        self._sigma *= 0.95
        self._told += 1


def _install_fake_cma(monkeypatch):
    cma_mod = types.ModuleType("cma")
    cma_mod.CMAEvolutionStrategy = _FakeCMAES
    monkeypatch.setitem(sys.modules, "cma", cma_mod)


def test_pycmaes_wrapper_ask_tell(monkeypatch):
    pytest.importorskip("numpy")
    try:
        import cma  # noqa: F401
    except ImportError:
        _install_fake_cma(monkeypatch)
    from evotorch_tpu import Problem, vectorized
    from evotorch_tpu.algorithms import PyCMAES

    # "max" sense exercises the fitness sign flip (cma minimizes)
    @vectorized
    def neg_sphere(xs):
        return -jnp.sum(xs**2, axis=-1)

    p = Problem("max", neg_sphere, solution_length=5, initial_bounds=(-1, 1))
    searcher = PyCMAES(p, stdev_init=0.5, popsize=8, center_init=jnp.full((5,), 2.0))
    searcher.run(20)
    best = np.asarray(searcher.status["pop_best"].values)
    assert float(np.sum(best**2)) < float(np.sum(np.full(5, 2.0) ** 2))
    assert len(searcher.population) == 8
