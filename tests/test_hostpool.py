"""Host-side parallel evaluation: the multiprocessing actor pool.

The reference fans arbitrary Python fitness functions and ``GymNE`` rollouts
across Ray actors (``core.py:1977-2052``, ``2583-2600``); here the same
``num_actors`` knob spawns worker processes. On this 1-core CI box we assert
the *concurrency structure* (work really ran in distinct worker processes,
sync deltas merged back), not a speedup.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from evotorch_tpu.core import Problem


def slow_sphere(row):
    # per-solution (non-vectorized) objective: the host-pool use class
    return float(np.sum(np.asarray(row) ** 2))


def test_host_pool_evaluates_correctly_in_worker_processes():
    p = Problem("min", slow_sphere, solution_length=4, initial_bounds=(-1, 1), num_actors=2)
    batch = p.generate_batch(6)
    p.evaluate(batch)
    try:
        assert batch.is_evaluated
        expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
        assert np.allclose(np.asarray(batch.evals[:, 0]), expected, atol=1e-5)
        # the work really happened in two live non-main processes
        pool = p._host_pool
        assert pool is not None and pool.num_workers == 2
        assert pool.is_alive()
        assert all(pid != os.getpid() for pid in pool.worker_pids)
        assert len(set(pool.worker_pids)) == 2
        # best/worst tracking still works through the pooled path
        assert "best_eval" in p.status
        # second evaluation reuses the same pool
        batch2 = p.generate_batch(5)
        p.evaluate(batch2)
        assert batch2.is_evaluated
        assert p._host_pool is pool
    finally:
        p.kill_actors()
    assert p._host_pool is None


def test_gymne_num_actors_parallel_rollouts():
    from evotorch_tpu.neuroevolution import GymNE

    p = GymNE(
        "CartPole-v1",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        num_actors=2,
    )
    batch = p.generate_batch(4)
    p.evaluate(batch)
    try:
        assert batch.is_evaluated
        assert np.isfinite(np.asarray(batch.evals[:, 0])).all()
        # rollouts happened in the workers, and their deltas merged home:
        # interaction/episode counters and obs-norm statistics all advanced
        assert p.status["total_interaction_count"] > 0
        assert p.status["total_episode_count"] >= 4
        assert p.get_observation_stats().count > 0
        pool = p._host_pool
        assert pool is not None and pool.is_alive()
        assert all(pid != os.getpid() for pid in pool.worker_pids)

        # a second round must keep counters cumulative (deltas, not absolutes)
        first_count = p.status["total_interaction_count"]
        stats_count = p.get_observation_stats().count
        batch2 = p.generate_batch(4)
        p.evaluate(batch2)
        assert p.status["total_interaction_count"] > first_count
        assert p.get_observation_stats().count > stats_count
    finally:
        p.kill_actors()


class VarLengthProblem(Problem):
    """Object-dtype (variable-length solutions) — must fan out through the
    pool as pickled ObjectArrays, never through np.asarray."""

    def __init__(self, **kwargs):
        super().__init__("max", dtype=object, **kwargs)

    def _fill(self, n, key):
        from evotorch_tpu.tools import ObjectArray

        arr = ObjectArray(n)
        for i in range(n):
            arr[i] = list(range(i + 1))  # inhomogeneous lengths
        return arr

    def _evaluate(self, solution):
        solution.set_evals(float(sum(solution.values)))


def test_host_pool_object_dtype():
    p = VarLengthProblem(num_actors=2)
    batch = p.generate_batch(5)
    p.evaluate(batch)
    try:
        assert batch.is_evaluated
        # solution i is [0..i] -> fitness = i*(i+1)/2
        expected = [i * (i + 1) / 2 for i in range(5)]
        assert np.asarray(batch.evals[:, 0]).tolist() == expected
        assert p._host_pool is not None and p._host_pool.is_alive()
    finally:
        p.kill_actors()


def test_unpicklable_objective_falls_back_to_serial():
    # review regression: lambdas cannot pickle for worker processes; must
    # warn + evaluate serially, not crash (the reference ships cloudpickle)
    p = Problem(
        "min",
        lambda row: float(np.sum(np.asarray(row) ** 2)),
        solution_length=3,
        initial_bounds=(-1, 1),
        num_actors=2,
    )
    batch = p.generate_batch(4)
    p.evaluate(batch)
    assert batch.is_evaluated
    assert p._host_pool is None
    expected = np.sum(np.asarray(batch.values) ** 2, axis=-1)
    assert np.allclose(np.asarray(batch.evals[:, 0]), expected, atol=1e-5)


def always_broken(row):
    raise RuntimeError("deliberate objective failure")


def test_host_pool_worker_failure_raises():
    p = Problem("min", always_broken, solution_length=3, initial_bounds=(-1, 1), num_actors=2)
    batch = p.generate_batch(4)
    with pytest.raises(RuntimeError, match="worker failed"):
        p.evaluate(batch)
    assert p._host_pool is None or not p._host_pool.is_alive()
