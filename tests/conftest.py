"""Test-session configuration: deterministic seeds (reference tests/conftest.py:21-27)."""

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_numpy():
    np.random.seed(0)
    yield
