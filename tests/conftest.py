"""Test-session configuration: deterministic seeds (reference tests/conftest.py:21-27)
and the ``mujoco`` marker guard (real-MuJoCo tests skip cleanly where the
optional mujoco/gymnasium packages are absent)."""

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_numpy():
    np.random.seed(0)
    yield


def pytest_collection_modifyitems(config, items):
    # same check as evotorch_tpu.envs.mujoco.mujoco_available, inlined so
    # collection never pays the full package import
    from importlib import util

    if util.find_spec("mujoco") is not None and util.find_spec("gymnasium") is not None:
        return
    skip = pytest.mark.skip(reason="mujoco/gymnasium not installed")
    for item in items:
        if "mujoco" in item.keywords:
            item.add_marker(skip)
