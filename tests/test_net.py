import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.neuroevolution.net import (
    LSTM,
    RNN,
    Clip,
    FeedForwardNet,
    FlatParamsPolicy,
    Linear,
    LocomotorNet,
    NetParsingError,
    Sequential,
    StructuredControlNet,
    Tanh,
    count_parameters,
    fill_parameters,
    parameter_vector,
    str_to_net,
)


def test_linear_layer():
    layer = Linear(3, 2)
    params = layer.init(jax.random.key(0))
    assert params["weight"].shape == (2, 3)
    assert params["bias"].shape == (2,)
    y, _ = layer.apply(params, jnp.ones(3))
    assert y.shape == (2,)
    # batched input works without modification
    y, _ = layer.apply(params, jnp.ones((7, 3)))
    assert y.shape == (7, 2)


def test_sequential_composition():
    net = Linear(4, 8) >> Tanh() >> Linear(8, 2)
    assert isinstance(net, Sequential)
    params = net.init(jax.random.key(0))
    y, state = net.apply(params, jnp.ones(4))
    assert y.shape == (2,)
    assert state is None
    assert float(jnp.max(jnp.abs(y))) < 10.0


def test_rnn_state_threading():
    net = RNN(3, 5)
    params = net.init(jax.random.key(0))
    x = jnp.ones(3)
    y1, h1 = net.apply(params, x, None)
    y2, h2 = net.apply(params, x, h1)
    assert y1.shape == (5,)
    assert not np.allclose(np.asarray(y1), np.asarray(y2))
    # state threads through Sequential around stateless layers
    seq = Linear(3, 3) >> RNN(3, 4) >> Linear(4, 2)
    p = seq.init(jax.random.key(1))
    out, st = seq.apply(p, x)
    assert out.shape == (2,)
    assert st is not None
    out2, st2 = seq.apply(p, x, st)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_lstm_cell():
    net = LSTM(2, 3)
    params = net.init(jax.random.key(0))
    y, (h, c) = net.apply(params, jnp.ones(2))
    assert y.shape == (3,) and h.shape == (3,) and c.shape == (3,)
    assert np.allclose(np.asarray(y), np.asarray(h))


def test_flat_params_policy_and_vmap():
    net = Linear(4, 3) >> Tanh()
    policy = FlatParamsPolicy(net)
    n = policy.parameter_count
    assert n == 4 * 3 + 3
    flat = policy.init_parameters(jax.random.key(1))
    y, _ = policy(flat, jnp.ones(4))
    assert y.shape == (3,)
    # population-batched forward: vmap over params
    pop = jnp.stack([flat, flat * 0.0])
    ys, _ = jax.vmap(lambda p, x: policy(p, x))(pop, jnp.ones((2, 4)))
    assert ys.shape == (2, 3)
    assert np.allclose(np.asarray(ys[1]), 0.0)


def test_parameter_vector_roundtrip():
    net = Linear(3, 2)
    params = net.init(jax.random.key(0))
    vec = parameter_vector(params)
    restored = fill_parameters(params, vec)
    assert np.allclose(np.asarray(restored["weight"]), np.asarray(params["weight"]))
    assert count_parameters(net) == vec.shape[0]


def test_str_to_net():
    net = str_to_net(
        "Linear(obs_length, 16) >> Tanh() >> Linear(16, act_length)",
        obs_length=4,
        act_length=2,
    )
    params = net.init(jax.random.key(0))
    y, _ = net.apply(params, jnp.ones(4))
    assert y.shape == (2,)


def test_str_to_net_arithmetic_and_kwargs():
    net = str_to_net("Linear(n, n * 2, bias=False) >> Clip(-1.0, 1.0)", n=3)
    params = net.init(jax.random.key(0))
    y, _ = net.apply(params, jnp.full((3,), 100.0))
    assert y.shape == (6,)
    assert float(jnp.max(y)) <= 1.0


def test_str_to_net_errors():
    with pytest.raises(NetParsingError):
        str_to_net("NotALayer(3, 4)")
    with pytest.raises(NetParsingError):
        str_to_net("Linear(3, unknown_name)")
    with pytest.raises(NetParsingError):
        str_to_net("__import__('os')")
    with pytest.raises(NetParsingError):
        str_to_net("1 + 2")


def test_structured_control_net_and_locomotor():
    scn = StructuredControlNet(in_features=4, out_features=2, num_layers=2, hidden_size=8)
    params = scn.init(jax.random.key(0))
    y, _ = scn.apply(params, jnp.ones(4))
    assert y.shape == (2,)

    loco = LocomotorNet(in_features=4, out_features=2, num_sinusoids=4)
    params = loco.init(jax.random.key(0))
    y, _ = loco.apply(params, jnp.ones(4))
    assert y.shape == (2,)


def test_feed_forward_net():
    net = FeedForwardNet(4, [(8, jnp.tanh), (2, None)])
    params = net.init(jax.random.key(0))
    y, _ = net.apply(params, jnp.ones(4))
    assert y.shape == (2,)
