"""Search-health plane (docs/observability.md "Search health").

The contracts under test:

- the v4 wire decodes next to every older schema (golden vectors for
  v1 ``(6,)`` / v2 ``(G, 14)`` / v3 ``(G, 15)`` / v4 ``(G, 20)``), and the
  health block combines Chan-style under ``__add__``;
- ``health=False`` compiles a DISTINCT, v3 byte-compatible program, and
  both variants run retrace-free in steady state;
- the per-group health rows are bit-identical unsharded vs 1-D vs 2-D
  mesh, including a padded indivisible popsize;
- the EWMA trend detectors are variance-gated (a noisy-but-progressing
  stream never stalls; a flat one does) and serialize round-trip;
- the plateau / stdev_collapse / score_snr_floor rules trip on injected
  degeneracy with named violations while a healthy run stays ``slo_ok``;
- the bench-CLI health flags follow the 0/1/2 exit taxonomy;
- the ``telemetry-schema`` graftlint checker flags hard-coded column
  literals outside devicemetrics.py.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from evotorch_tpu.analysis import assert_compiles, track_compiles
from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import (
    FlatParamsPolicy,
    Linear,
    Tanh,
    run_vectorized_rollout,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.observability import (
    EvalTelemetry,
    GroupTelemetry,
    HEALTH_TELEMETRY_WIDTH,
    HEALTH_WIDTH,
    Rule,
    SLOWatchdog,
    append_health_block,
    compute_health_block,
)
from evotorch_tpu.observability.devicemetrics import (
    GROUP_TELEMETRY_WIDTH,
    QUEUE_WAIT_BUCKETS,
    TELEMETRY_WIDTH,
    _LEGACY_GROUP_TELEMETRY_WIDTH,
    _LEGACY_TELEMETRY_WIDTH,
)
from evotorch_tpu.observability.health import EWMATrend, HealthMonitor
from evotorch_tpu.observability.slo import check_bench_line
from evotorch_tpu.parallel import make_mesh, make_sharded_rollout_evaluator


def _health_matrix(counter_rows, score_rows):
    """Assemble a v4 wire host-side: counter block + bit-cast health."""
    counter = np.asarray(counter_rows, dtype=np.int32)
    health = np.asarray(score_rows, dtype=np.float32)
    return np.concatenate([counter, health.view(np.int32)], axis=1)


# ---------------------------------------------------------------------------
# golden decode: every schema through the one decoder
# ---------------------------------------------------------------------------


def test_golden_decode_v1_vector():
    v1 = np.array([10, 2, 20, 4, 3, 5], dtype=np.int32)
    assert v1.shape == (_LEGACY_TELEMETRY_WIDTH,)
    gt = GroupTelemetry.from_array(v1)
    assert gt.num_groups == 1 and not gt.has_health
    assert gt.score_stats() is None
    t = gt.total()
    assert (t.env_steps, t.episodes, t.nonfinite) == (10, 2, 0)
    assert EvalTelemetry.from_array(v1).env_steps == 10


def test_golden_decode_v2_matrix():
    v2 = np.zeros((2, _LEGACY_GROUP_TELEMETRY_WIDTH), dtype=np.int32)
    v2[0, :_LEGACY_TELEMETRY_WIDTH] = [90, 10, 100, 4, 10, 5]
    v2[1, _LEGACY_TELEMETRY_WIDTH:] = [0, 0, 0, 0, 0, 1, 0, 5]
    gt = GroupTelemetry.from_array(v2)
    assert gt.num_groups == 2 and not gt.has_health
    assert gt.data.shape == (2, GROUP_TELEMETRY_WIDTH)
    assert gt.total().env_steps == 90
    assert gt.total().nonfinite == 0  # missing column decodes as 0
    assert gt.hist.shape == (2, QUEUE_WAIT_BUCKETS)
    assert int(gt.hist[1].sum()) == 6


def test_golden_decode_v3_matrix():
    v3 = np.zeros((2, GROUP_TELEMETRY_WIDTH), dtype=np.int32)
    v3[0, :TELEMETRY_WIDTH] = [90, 10, 100, 4, 10, 5, 1]
    gt = GroupTelemetry.from_array(v3)
    assert gt.num_groups == 2 and not gt.has_health
    assert gt.total().nonfinite == 1
    assert gt.score_stats() is None


def test_golden_decode_v4_matrix_and_stats():
    counter = np.zeros((2, GROUP_TELEMETRY_WIDTH), dtype=np.int32)
    counter[0, :TELEMETRY_WIDTH] = [90, 10, 100, 4, 10, 5, 0]
    counter[1, :TELEMETRY_WIDTH] = [30, 4, 50, 4, 2, 8, 0]
    # g0: scores {1, 2, 3}; g1: scores {-4, -6}
    health = [
        [3.0, 6.0, 14.0, 1.0, 3.0],
        [2.0, -10.0, 52.0, -6.0, -4.0],
    ]
    gt = GroupTelemetry.from_array(_health_matrix(counter, health))
    assert gt.has_health and gt.health.shape == (2, HEALTH_WIDTH)
    s0 = gt.score_stats(group=0)
    assert s0["count"] == 3 and s0["mean"] == pytest.approx(2.0)
    assert s0["std"] == pytest.approx(np.std([1.0, 2.0, 3.0]))
    assert (s0["min"], s0["max"]) == (1.0, 3.0)
    s1 = gt.score_stats(group=1)
    assert s1["mean"] == pytest.approx(-5.0)
    assert (s1["min"], s1["max"]) == (-6.0, -4.0)
    g = gt.score_stats()
    assert g["count"] == 5
    assert g["mean"] == pytest.approx(np.mean([1, 2, 3, -4, -6]))
    assert g["std"] == pytest.approx(np.std([1, 2, 3, -4, -6]))
    assert (g["min"], g["max"]) == (-6.0, 3.0)
    # the counter decoders keep reading the v4 wire unchanged
    assert gt.total().env_steps == 120
    assert EvalTelemetry.from_array(_health_matrix(counter, health)).env_steps == 120


def test_health_block_chan_addition():
    counter = np.zeros((1, GROUP_TELEMETRY_WIDTH), dtype=np.int32)
    a = GroupTelemetry.from_array(
        _health_matrix(counter, [[2.0, 3.0, 5.0, 1.0, 2.0]])  # {1, 2}
    )
    b = GroupTelemetry.from_array(
        _health_matrix(counter, [[2.0, 7.0, 25.0, 3.0, 4.0]])  # {3, 4}
    )
    s = (a + b).score_stats()
    assert s["count"] == 4
    assert s["mean"] == pytest.approx(2.5)
    assert s["std"] == pytest.approx(np.std([1.0, 2.0, 3.0, 4.0]))
    assert (s["min"], s["max"]) == (1.0, 4.0)
    # empty rows (count 0, min/max masked to 0.0) are identity elements
    empty = GroupTelemetry.from_array(
        _health_matrix(counter, [[0.0, 0.0, 0.0, 0.0, 0.0]])
    )
    s2 = (a + empty).score_stats()
    assert (s2["count"], s2["min"], s2["max"]) == (2, 1.0, 2.0)
    # mixed-schema addition degrades to counters-only (no fabricated stats)
    v3_only = GroupTelemetry.from_array(counter.copy())
    assert not (a + v3_only).has_health


def test_compute_health_block_empty_group_masking():
    # group 1 receives no solutions: its row must be all-zero (min/max
    # masked), not +/-inf — inf would poison the int32 psum wire
    scores = jnp.asarray([1.0, 2.0, 3.0])
    groups = jnp.zeros(3, dtype=jnp.int32)
    block = np.asarray(jax.jit(
        lambda s, g: compute_health_block(s, g, 2)
    )(scores, groups))
    assert block.shape == (2, HEALTH_WIDTH)
    np.testing.assert_array_equal(block[1], np.zeros(HEALTH_WIDTH))
    assert block[0, 0] == 3.0 and (block[0, 3], block[0, 4]) == (1.0, 3.0)


def test_append_health_block_width_and_bitcast():
    counter = jnp.zeros((2, GROUP_TELEMETRY_WIDTH), dtype=jnp.int32)
    health = jnp.asarray(
        [[1.0, 2.5, 6.25, 2.5, 2.5], [0.0, 0.0, 0.0, 0.0, 0.0]],
        dtype=jnp.float32,
    )
    wire = np.asarray(jax.jit(append_health_block)(counter, health))
    assert wire.shape == (2, HEALTH_TELEMETRY_WIDTH)
    assert wire.dtype == np.int32
    gt = GroupTelemetry.from_array(wire)
    assert gt.score_stats(group=0)["mean"] == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# compiled programs: health on/off, steady state
# ---------------------------------------------------------------------------


def _rollout_setup(popsize=8):
    env = CartPole()
    policy = FlatParamsPolicy(
        Linear(env.observation_size, 4) >> Tanh() >> Linear(4, env.action_size)
    )
    stats = RunningNorm(env.observation_size).stats
    params = 0.1 * jax.random.normal(
        jax.random.key(0), (popsize, policy.parameter_count)
    )
    return env, policy, stats, params


@pytest.mark.parametrize(
    "eval_mode", ["budget", "episodes", "episodes_refill"]
)
def test_health_toggle_compiles_distinct_steady_programs(eval_mode):
    env, policy, stats, params = _rollout_setup()
    key = jax.random.key(1)
    kwargs = dict(num_episodes=1, episode_length=8, eval_mode=eval_mode)
    if eval_mode == "episodes_refill":
        kwargs.update(refill_width=4, refill_period=1)

    with track_compiles() as log:
        on = run_vectorized_rollout(env, policy, params, key, stats, **kwargs)
    assert log.count > 0
    with track_compiles() as log_off:
        off = run_vectorized_rollout(
            env, policy, params, key, stats, health=False, **kwargs
        )
    assert log_off.count > 0  # health=False is its OWN cached program
    # same scores, v4 vs v3 wire
    np.testing.assert_array_equal(np.asarray(on.scores), np.asarray(off.scores))
    assert np.asarray(on.telemetry).shape[-1] == HEALTH_TELEMETRY_WIDTH
    assert np.asarray(off.telemetry).shape[-1] == GROUP_TELEMETRY_WIDTH
    assert GroupTelemetry.from_array(on.telemetry).has_health
    assert not GroupTelemetry.from_array(off.telemetry).has_health
    # both variants are steady after the first trace
    with assert_compiles(0):
        run_vectorized_rollout(env, policy, params, key, stats, **kwargs)
        run_vectorized_rollout(
            env, policy, params, key, stats, health=False, **kwargs
        )


def test_health_stats_match_scores_per_contract():
    env, policy, stats, params = _rollout_setup(popsize=12)
    key = jax.random.key(2)
    groups = np.arange(12, dtype=np.int32) % 3
    for eval_mode in ("budget", "episodes"):
        r = run_vectorized_rollout(
            env, policy, params, key, stats,
            num_episodes=1, episode_length=8, eval_mode=eval_mode,
            groups=groups, num_groups=3,
        )
        scores = np.asarray(r.scores, dtype=np.float32)
        gt = GroupTelemetry.from_array(r.telemetry)
        g = gt.score_stats()
        assert g["count"] == 12
        assert g["mean"] == pytest.approx(scores.mean(), rel=1e-6)
        assert g["min"] == pytest.approx(scores.min())
        assert g["max"] == pytest.approx(scores.max())
        for gid in range(3):
            s = gt.score_stats(group=gid)
            mine = scores[groups == gid]
            assert s["count"] == len(mine)
            assert s["mean"] == pytest.approx(mine.mean(), rel=1e-6)


# ---------------------------------------------------------------------------
# mesh bit-identity (the GSPMD acceptance clause)
# ---------------------------------------------------------------------------


def test_health_rows_bit_identical_across_meshes():
    env, policy, stats, params = _rollout_setup(popsize=16)
    key = jax.random.key(3)
    groups = np.arange(16, dtype=np.int32) % 2
    kwargs = dict(
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
        refill_width=8, refill_period=1, groups=groups, num_groups=2,
    )
    ref = run_vectorized_rollout(env, policy, params, key, stats, **kwargs)
    href = GroupTelemetry.from_array(ref.telemetry).health
    assert href is not None
    for mesh_shape in ({"pop": 8}, {"pop": 4, "model": 2}):
        ev = make_sharded_rollout_evaluator(
            env, policy, mesh=make_mesh(mesh_shape), **kwargs
        )
        result, _ = ev(params, key, stats)
        h = GroupTelemetry.from_array(result.telemetry).health
        # BIT-identical: compare the raw float32 words, no tolerance
        np.testing.assert_array_equal(
            h.view(np.int32), href.view(np.int32), err_msg=str(mesh_shape)
        )


def test_health_rows_bit_identical_padded_popsize():
    # 12 % 8 != 0: the GSPMD path pads to 16 physical lanes; pad lanes are
    # masked out of the score fold, so the health block (unlike the
    # capacity/lane-width counter columns, which account physical lanes)
    # matches unsharded EXACTLY
    env, policy, stats, params = _rollout_setup(popsize=12)
    key = jax.random.key(4)
    groups = np.arange(12, dtype=np.int32) % 2
    kwargs = dict(
        num_episodes=1, episode_length=4, eval_mode="episodes",
        groups=groups, num_groups=2,
    )
    ref = run_vectorized_rollout(env, policy, params, key, stats, **kwargs)
    href = GroupTelemetry.from_array(ref.telemetry).health
    ev = make_sharded_rollout_evaluator(
        env, policy, mesh=make_mesh({"pop": 8}), **kwargs
    )
    result, _ = ev(params, key, stats)
    h = GroupTelemetry.from_array(result.telemetry).health
    np.testing.assert_array_equal(h.view(np.int32), href.view(np.int32))


# ---------------------------------------------------------------------------
# EWMA trend detectors
# ---------------------------------------------------------------------------


def test_ewma_trend_progressing_stream_never_stalls():
    rng = np.random.default_rng(0)
    trend = EWMATrend()
    for i in range(60):
        trend.observe(10.0 * i + rng.normal(0.0, 2.0))
    assert trend.warmed_up and trend.significant
    assert trend.stall_streak == 0


def test_ewma_trend_flat_stream_stalls_and_worsening_is_not_plateau():
    rng = np.random.default_rng(1)
    flat = EWMATrend()
    for _ in range(60):
        flat.observe(5.0 + rng.normal(0.0, 2.0))
    assert flat.stall_streak > 0 and not flat.significant
    # a clearly WORSENING stream has a significant (negative) trend — the
    # plateau detector must not call regression a plateau
    down = EWMATrend()
    for i in range(60):
        down.observe(-10.0 * i + rng.normal(0.0, 2.0))
    assert down.significant and down.stall_streak == 0
    assert down.delta_ewma < 0


def test_ewma_trend_nonfinite_observations_are_noops():
    trend = EWMATrend()
    for i in range(10):
        trend.observe(float(i))
    before = trend.state_dict()
    trend.observe(float("nan")).observe(float("inf"))
    assert trend.state_dict() == before


def test_trend_and_monitor_state_roundtrip():
    rng = np.random.default_rng(2)
    a = EWMATrend()
    values = [5.0 + rng.normal(0.0, 2.0) for _ in range(20)]
    for v in values:
        a.observe(v)
    b = EWMATrend()
    b.load_state_dict(a.state_dict())
    tail = [5.0 + rng.normal(0.0, 2.0) for _ in range(20)]
    for v in tail:
        a.observe(v)
        b.observe(v)
    assert a.state_dict() == b.state_dict()
    assert json.loads(json.dumps(a.state_dict())) == a.state_dict()

    m = HealthMonitor()
    m.observe("score_mean", 1.0)
    m.observe("score_mean", 2.0, group=1)
    m.observe("stdev_norm", 3.0)
    m2 = HealthMonitor()
    m2.load_state_dict(json.loads(json.dumps(m.state_dict())))
    assert sorted(m2.keys()) == sorted(m.keys())
    assert m2.baseline("stdev_norm") == 3.0
    assert m2.trend("score_mean", group=1).value == 2.0


# ---------------------------------------------------------------------------
# the three health SLO rules
# ---------------------------------------------------------------------------


def _v4_with_scores(scores, num_groups=1, groups=None):
    scores = np.asarray(scores, dtype=np.float32)
    if groups is None:
        groups = np.zeros(len(scores), dtype=np.int32)
    block = np.asarray(
        compute_health_block(
            jnp.asarray(scores), jnp.asarray(groups), num_groups
        )
    )
    counter = np.zeros((num_groups, GROUP_TELEMETRY_WIDTH), dtype=np.int32)
    return GroupTelemetry.from_array(_health_matrix(counter, block))


def test_plateau_rule_trips_on_flat_scores_with_named_violation():
    rng = np.random.default_rng(3)
    dog = SLOWatchdog([Rule("plateau", threshold=10)])
    tripped = None
    for gen in range(80):
        scores = 5.0 + rng.normal(0.0, 1.0, size=16)
        report = dog.check(_v4_with_scores(scores))
        if not report.ok:
            tripped = (gen, report)
            break
    assert tripped is not None
    assert "plateau global" in tripped[1].violations[0]
    assert tripped[1].as_status()["slo_ok"] is False


def test_plateau_rule_quiet_on_progressing_scores():
    rng = np.random.default_rng(4)
    dog = SLOWatchdog([Rule("plateau", threshold=10)])
    for gen in range(80):
        scores = 10.0 * gen + rng.normal(0.0, 1.0, size=16)
        report = dog.check(_v4_with_scores(scores))
        assert report.ok, report.violations


def test_plateau_rule_status_fallback_for_prev4_feeds():
    # a replayed v3 feed has no health block; the global rule falls back to
    # the score_mean / mean_eval status keys instead of going blind
    dog = SLOWatchdog([Rule("plateau", threshold=5)])
    report = None
    for _ in range(40):
        report = dog.check(None, status={"mean_eval": 5.0})
    assert report is not None and not report.ok


def test_stdev_collapse_rule_vs_first_seen_baseline():
    dog = SLOWatchdog([Rule("stdev_collapse", threshold=0.01)])
    assert dog.check(None, status={"stdev_norm": 1.0}).ok
    assert dog.check(None, status={"stdev_norm": 0.5}).ok
    report = dog.check(None, status={"stdev_norm": 0.001})
    assert not report.ok and "collapse" in report.violations[0]
    # no stdev_norm key -> rule skipped, not failed
    skipped = dog.check(None, status={})
    assert skipped.ok and skipped.checked == 0


def test_score_snr_floor_rule():
    dog = SLOWatchdog([Rule("score_snr_floor", threshold=1e6)])
    # degenerate: every score identical -> std 0 -> SNR inf -> passes the
    # floor (the collapse side is the --max-score-collapse ceiling)
    assert dog.check(_v4_with_scores([5.0] * 8)).ok
    report = dog.check(_v4_with_scores([5.0, 5.1, 4.9, 5.05, 4.95]))
    assert not report.ok and "score_snr" in report.violations[0]
    # fewer than two samples: skipped
    assert dog.check(_v4_with_scores([5.0])).checked == 0


def test_watchdog_health_state_rides_state_dict():
    rng = np.random.default_rng(5)
    rules = [Rule("plateau", threshold=10), Rule("stdev_collapse", threshold=0.01)]
    a = SLOWatchdog(rules)
    history = []
    for _ in range(30):
        scores = 5.0 + rng.normal(0.0, 1.0, size=16)
        history.append(scores)
        a.check(_v4_with_scores(scores), status={"stdev_norm": 1.0})
    b = SLOWatchdog(rules)
    b.load_state_dict(json.loads(json.dumps(a.state_dict())))
    rng2 = np.random.default_rng(6)
    for _ in range(60):
        scores = 5.0 + rng2.normal(0.0, 1.0, size=16)
        ra = a.check(_v4_with_scores(scores), status={"stdev_norm": 1.0})
        rb = b.check(_v4_with_scores(scores), status={"stdev_norm": 1.0})
        assert ra.as_status() == rb.as_status()


def test_healthy_cartpole_run_stays_slo_ok():
    # end-to-end: a healthy searcher on CartPole under the health rules
    # never trips — and the status dict carries the new score keys
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        "cartpole",
        "Linear(obs_length, 4) >> Tanh() >> Linear(4, act_length)",
        episode_length=16,
        eval_mode="episodes",
        slo=[
            {"kind": "plateau", "threshold": 3},
            {"kind": "score_snr_floor", "threshold": 1e-6},
            {"kind": "stdev_collapse", "threshold": 0.01},
        ],
        seed=0,
    )
    searcher = PGPE(problem, popsize=8, center_learning_rate=0.1,
                    stdev_learning_rate=0.1, radius_init=0.3)
    for _ in range(6):
        searcher.step()
    status = searcher.status
    assert status["slo_ok"] is True, status.get("slo_detail")
    assert "eval_score_mean" in status and "eval_score_std" in status
    assert status["stdev_norm"] > 0.0
    assert status["center_update_norm"] is not None


# ---------------------------------------------------------------------------
# bench-line CLI checks
# ---------------------------------------------------------------------------


def _bench_line(**over):
    line = {
        "occupancy": 0.9,
        "steady_compiles": 0,
        "score_mean": 100.0,
        "score_std": 10.0,
        "modes": {"episodes": {"occupancy": 0.9, "score_mean": 100.0, "score_std": 10.0}},
    }
    line.update(over)
    return line


def test_check_bench_line_score_collapse_and_snr():
    assert check_bench_line(_bench_line(), max_score_collapse=100.0).ok
    report = check_bench_line(
        _bench_line(score_std=1e-9), max_score_collapse=100.0
    )
    assert not report.ok
    assert any("score spread collapsed" in v for v in report.violations)
    # the per-mode columns are checked under their modes.<mode>. label
    report = check_bench_line(
        _bench_line(modes={"episodes": {"score_mean": 100.0, "score_std": 1e-9}}),
        max_score_collapse=100.0,
    )
    assert any(v.startswith("modes.episodes.") for v in report.violations)
    assert not check_bench_line(_bench_line(), min_score_snr=100.0).ok
    assert check_bench_line(_bench_line(), min_score_snr=1.0).ok


def test_check_bench_cli_exit_taxonomy(tmp_path, capsys):
    from evotorch_tpu.observability.slo import _main

    log = tmp_path / "bench.log"
    log.write_text(json.dumps(_bench_line()) + "\n")
    assert _main(["--check-bench", str(log), "--max-score-collapse", "1e6"]) == 0
    log.write_text(json.dumps(_bench_line(score_std=1e-12)) + "\n")
    assert _main(["--check-bench", str(log), "--max-score-collapse", "1e6"]) == 1
    # a BENCH_HEALTH=0 line lacks the score columns: with ONLY health checks
    # requested there is nothing to verify -> insufficient (2), not pass
    bare = {"score_note": "none"}
    log.write_text(json.dumps(bare) + "\n")
    assert _main(["--check-bench", str(log), "--max-score-collapse", "1e6"]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# the telemetry-schema lint checker
# ---------------------------------------------------------------------------


def test_lint_telemetry_schema_checker():
    from evotorch_tpu.analysis import lint_sources

    findings = lint_sources(
        {
            "pkg/bad.py": (
                "def f(telemetry, group_counts, other):\n"
                "    a = telemetry[:, 15]\n"
                "    b = group_counts[0, 6]\n"
                "    c = other[3]\n"           # unrelated array: fine
                "    d = telemetry[:, i]\n"    # no literal: fine
                "    return a, b, c, d\n"
            ),
            # the owner module may spell its own layout
            "evotorch_tpu/observability/devicemetrics.py": (
                "def g(telemetry):\n    return telemetry[:, 15]\n"
            ),
            # allow-comments still apply
            "pkg/allowed.py": (
                "def h(lane_counts):\n"
                "    # graftlint: allow(telemetry-schema): leading axis squeeze\n"
                "    return lane_counts[0]\n"
            ),
        },
        checkers=["telemetry-schema"],
    )
    sigs = sorted(f.signature for f in findings)
    assert len(sigs) == 2
    assert all(s.startswith("pkg/bad.py::telemetry-schema") for s in sigs)
    assert any("telemetry-index:telemetry:[15]" in s for s in sigs)
    assert any("telemetry-index:group_counts:[0,6]" in s for s in sigs)
