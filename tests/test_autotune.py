"""Autotuner + measured-timing ledger + tuned-config cache.

The contracts under test (docs/observability.md "The autotuner"):

- the SEARCH CORE is pure and deterministic: candidate grids and
  neighborhoods enumerate in stable order, analytic pruning rejects on
  the PR 9 cost model BEFORE any measurement, successive halving selects
  on MEDIANS (robust to the box's ±20% run-to-run noise — injected
  synthetically here, zero wall-clock), and the winner respects the
  occupancy floor;
- the measured-timing ledger keys per (program, shape, machine) and
  ranks configs by median steps/s;
- the tuned-config cache resolves with ONE precedence rule everywhere:
  explicit knobs ("override") > cache hit ("cache") > built-in default
  ("fallback"), and every consumer — VecNE status, the sharded
  evaluator, the host pipeline, bench_common — reports the branch taken
  as `tuned_config_source`.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from evotorch_tpu.observability.autotune import (
    CandidateStats,
    KnobGroup,
    KnobSpec,
    analytic_prune,
    autotune_search,
    candidate_grid,
    neighborhood,
    select_winner,
    successive_halving,
)
from evotorch_tpu.observability.timings import (
    TimingLedger,
    TimingRecord,
    TunedEntry,
    canonical_env_label,
    load_tuned_cache,
    lookup_tuned,
    machine_fingerprint,
    resolve_knobs,
    save_tuned_entry,
    timing_key,
)

GROUP = KnobGroup(
    "refill",
    (
        KnobSpec("width", (64, 128, 256, 512)),
        KnobSpec("period", (1, 2), refine=False),
    ),
)

#: the synthetic ground truth: a planted optimum at width=256, period=1,
#: with gaps wide enough that ±20% multiplicative noise cannot flip a
#: median-of-3 (max competitor 60*1.2=72 < min optimum 100*0.8=80)
_TRUE_RATE = {64: 40.0, 128: 60.0, 256: 100.0, 512: 55.0}


def _synthetic_measure(noise_rng=None, log=None):
    """A MeasureFn over the planted-optimum landscape; ``log`` collects
    every measured config (for pruned-never-measured assertions)."""

    def measure(configs, trials, round_index):
        out = []
        for config in configs:
            if log is not None:
                log.append(dict(config))
            base = _TRUE_RATE.get(config["width"], 50.0)
            if config.get("period", 1) == 2:
                base *= 0.5
            samples = []
            for _ in range(trials):
                factor = 1.0 if noise_rng is None else noise_rng.uniform(0.8, 1.2)
                samples.append(base * factor)
            out.append(
                {
                    "samples": samples,
                    "occupancies": [0.95] * trials,
                    "steady_compiles": 0,
                }
            )
        return out

    return measure


# ---------------------------------------------------------------------------
# the pure search core
# ---------------------------------------------------------------------------


def test_candidate_grid_order_and_size():
    grid = candidate_grid(GROUP)
    assert len(grid) == 8
    # knob-major deterministic order: first knob varies slowest
    assert grid[0] == {"width": 64, "period": 1}
    assert grid[1] == {"width": 64, "period": 2}
    assert grid[-1] == {"width": 512, "period": 2}


def test_neighborhood_midpoints_skip_unrefinable_knobs():
    nbrs = neighborhood(GROUP, {"width": 256, "period": 1})
    # midpoints toward the adjacent grid rungs, one knob at a time; the
    # period knob (refine=False) must not generate anything
    assert nbrs == [
        {"width": 192, "period": 1},
        {"width": 384, "period": 1},
    ]
    # an edge value refines inward only
    assert neighborhood(GROUP, {"width": 64, "period": 1}) == [
        {"width": 96, "period": 1}
    ]


def test_analytic_prune_hbm_and_flops_bounds():
    def cost_fn(config):
        if config["width"] == 128:
            return None  # no analysis available: must NEVER prune
        return {
            "peak_bytes": config["width"] * 1000,
            "flops": config["width"] * 10.0,
            "compile_seconds": 0.1,
        }

    grid = [{"width": w, "period": 1} for w in (64, 128, 256, 512)]
    kept, pruned, costs = analytic_prune(
        grid, cost_fn, hbm_budget_bytes=300_000
    )
    assert [c["width"] for c in kept] == [64, 128, 256]
    assert len(pruned) == 1 and pruned[0][0]["width"] == 512
    assert "peak_bytes" in pruned[0][1]
    # costs are keyed by KEPT index, skipping the analysis-less candidate
    assert set(costs) == {0, 2} and costs[2]["peak_bytes"] == 256_000

    kept, pruned, _ = analytic_prune(grid, cost_fn, flops_bound=1000.0)
    assert [c["width"] for c in kept] == [64, 128]
    assert {p[0]["width"] for p in pruned} == {256, 512}

    # no cost_fn at all: everything is kept
    kept, pruned, costs = analytic_prune(grid, None, hbm_budget_bytes=1)
    assert len(kept) == 4 and not pruned and not costs


def test_median_selection_is_outlier_robust():
    stats = CandidateStats(config={}, samples=[10.0, 100.0, 11.0])
    assert stats.steps_per_sec == 11.0  # the median, not the lucky max


def test_successive_halving_converges_under_injected_noise():
    rng = np.random.default_rng(7)
    results = successive_halving(
        candidate_grid(GROUP),
        _synthetic_measure(noise_rng=rng),
        trials_per_round=3,
        survivor_frac=0.5,
        max_rounds=3,
    )
    winner = select_winner(results, min_occupancy=0.9)
    assert winner.config == {"width": 256, "period": 1}
    # survivors accumulated more samples than first-round casualties
    assert len(winner.samples) > 3
    casualties = [r for r in results if r.config["width"] == 64]
    assert all(len(r.samples) == 3 for r in casualties)


def test_successive_halving_measures_fewer_candidates_each_round():
    per_round = []

    def measure(configs, trials, round_index):
        per_round.append(len(configs))
        return _synthetic_measure()(configs, trials, round_index)

    successive_halving(
        candidate_grid(GROUP),
        measure,
        trials_per_round=3,
        survivor_frac=0.5,
        min_survivors=2,
        max_rounds=3,
    )
    assert per_round[0] == 8
    assert all(b < a for a, b in zip(per_round, per_round[1:]))


def test_select_winner_occupancy_floor_and_clean_timing_preference():
    fast_starved = CandidateStats(
        config={"width": 512}, samples=[100.0], occupancies=[0.5]
    )
    slower_full = CandidateStats(
        config={"width": 128}, samples=[80.0], occupancies=[0.95]
    )
    assert (
        select_winner([fast_starved, slower_full], min_occupancy=0.9)
        is slower_full
    )
    # no candidate meets the floor: fall back to the throughput winner
    assert (
        select_winner([fast_starved], min_occupancy=0.9) is fast_starved
    )
    # a steady-state compile mid-trial invalidates the timing: the dirty
    # candidate loses to any clean one regardless of its median
    dirty = CandidateStats(
        config={"width": 256},
        samples=[200.0],
        occupancies=[0.99],
        steady_compiles=1,
    )
    assert select_winner([dirty, slower_full], min_occupancy=0.9) is slower_full


def test_autotune_search_prunes_before_measuring_and_refines_around_winner():
    measured = []

    def cost_fn(config):
        return {
            "peak_bytes": config["width"] * 1000,
            "flops": None,
            "compile_seconds": 0.0,
        }

    outcome = autotune_search(
        GROUP,
        _synthetic_measure(log=measured),
        cost_fn=cost_fn,
        hbm_budget_bytes=300_000,  # prunes width 512 analytically
        trials_per_round=3,
        max_rounds=2,
        min_occupancy=0.9,
        refine=True,
    )
    # the grid's 512 AND the refinement midpoint 384 (peak 384k > budget)
    # are both rejected analytically — and neither is ever timed
    assert {p[0]["width"] for p in outcome.pruned} == {512, 384}
    assert all(c["width"] not in (384, 512) for c in measured)
    assert outcome.winner.config["width"] == 256
    # the surviving off-grid midpoint of the winner was measured
    assert 192 in {c["width"] for c in measured}
    assert outcome.winner.cost is not None  # costs attached to grid stats


# ---------------------------------------------------------------------------
# the measured-timing ledger
# ---------------------------------------------------------------------------


def test_timing_key_is_shape_and_machine_scoped():
    machine = {"backend": "cpu", "device_kind": "cpu", "core_count": 1}
    key = timing_key("rollout.episodes_refill", {"popsize": 1024, "env": "humanoid"}, machine)
    assert key == (
        "rollout.episodes_refill@env=humanoid,popsize=1024"
        "|backend=cpu,core_count=1,device_kind=cpu"
    )
    other = timing_key(
        "rollout.episodes_refill",
        {"popsize": 1024, "env": "humanoid"},
        dict(machine, core_count=8),
    )
    assert other != key  # a different box is a different row


def test_timing_ledger_best_roundtrip(tmp_path):
    led = TimingLedger()
    machine = machine_fingerprint()
    shape = {"env": "humanoid", "popsize": 1024}
    led.add(TimingRecord(
        program="p", shape=shape, machine=machine,
        config={"width": 512}, samples=(100.0, 90.0, 110.0), occupancy=0.5,
    ))
    led.add(TimingRecord(
        program="p", shape=shape, machine=machine,
        config={"width": 128}, samples=(80.0, 85.0, 82.0), occupancy=0.97,
    ))
    led.add(TimingRecord(  # pruned: never timed, never "best"
        program="p", shape=shape, machine=machine,
        config={"width": 4096}, pruned="peak_bytes over budget",
    ))
    assert led.best("p", shape).config == {"width": 512}
    assert led.best("p", shape, min_occupancy=0.9).config == {"width": 128}
    path = led.save(tmp_path / "timings.json")
    reloaded = TimingLedger.load(path)
    assert len(reloaded.records()) == 3
    assert reloaded.best("p", shape, min_occupancy=0.9).config == {"width": 128}
    assert reloaded.records("p")[2].pruned == "peak_bytes over budget"


# ---------------------------------------------------------------------------
# the tuned-config cache
# ---------------------------------------------------------------------------


def _cartpole_linear_params() -> int:
    """The parameter count of the Linear(obs→act) cartpole policy every
    consumer in this file builds — part of the cache key."""
    from evotorch_tpu.envs import CartPole
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear

    env = CartPole()
    return FlatParamsPolicy(
        Linear(env.observation_size, env.action_size)
    ).parameter_count


def _cartpole_shape(popsize: int = 8) -> dict:
    return {
        "env": "cartpole",
        "popsize": popsize,
        "episode_length": 8,
        "num_episodes": 1,
        "params": _cartpole_linear_params(),
        "dtype": "float32",
    }


@pytest.fixture
def tuned_cache(tmp_path, monkeypatch):
    """An isolated cache file (EVOTORCH_TUNED_CACHE is the supported
    override) preloaded with a cartpole@popsize-8 refill entry for THIS
    machine + policy shape."""
    path = tmp_path / "tuned_configs.json"
    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(path))
    entry = TunedEntry(
        group="refill",
        shape=_cartpole_shape(),
        machine=machine_fingerprint(),
        config={"width": 4, "period": 1},
        evidence={"steps_per_sec": 1.0},
    )
    save_tuned_entry(entry)
    return path


@pytest.fixture
def empty_cache(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "EVOTORCH_TUNED_CACHE", str(tmp_path / "no_such_cache.json")
    )


def test_cache_lookup_exact_key_only(tuned_cache):
    shape = _cartpole_shape()
    hit = lookup_tuned("refill", shape)
    assert hit is not None and hit.config["width"] == 4
    assert lookup_tuned("refill", dict(shape, popsize=16)) is None
    assert lookup_tuned("refill", dict(shape, env="hopper")) is None
    assert lookup_tuned("compact", shape) is None
    # a different policy size or compute dtype is a different workload
    assert lookup_tuned("refill", dict(shape, params=999)) is None
    assert lookup_tuned("refill", dict(shape, dtype="bfloat16")) is None
    other_box = dict(machine_fingerprint(), core_count=99)
    assert lookup_tuned("refill", shape, machine=other_box) is None


def test_resolve_knobs_precedence(tuned_cache):
    shape = _cartpole_shape()
    # explicit beats cache, cache is not even consulted
    config, source = resolve_knobs({"width": 2}, "refill", shape)
    assert source == "override" and config == {"width": 2}
    # None-valued knobs do not count as explicit
    config, source = resolve_knobs({"width": None}, "refill", shape)
    assert source == "cache" and config == {"width": 4, "period": 1}
    # a miss is the engine default
    config, source = resolve_knobs({}, "refill", dict(shape, popsize=99))
    assert source == "fallback" and config == {}
    # use_cache=False (BENCH_TUNED=0) forces the fallback branch
    config, source = resolve_knobs({}, "refill", shape, use_cache=False)
    assert source == "fallback" and config == {}


def test_corrupt_cache_degrades_to_fallback(tmp_path, monkeypatch):
    path = tmp_path / "corrupt.json"
    path.write_text("{not json")
    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(path))
    assert load_tuned_cache(force=True) == {}
    _, source = resolve_knobs({}, "refill", _cartpole_shape())
    assert source == "fallback"


def test_canonical_env_label():
    from evotorch_tpu.envs import CartPole
    from evotorch_tpu.envs.classic import Swimmer2D

    assert canonical_env_label("Humanoid-v5") == "humanoid"
    assert canonical_env_label("gym::Hopper-v5") == "hopper"
    assert canonical_env_label("halfcheetah") == "halfcheetah"
    assert canonical_env_label(CartPole()) == "cartpole"
    # registry ALIASES fold to one identity — entries tuned under one
    # spelling must hit lookups under any other
    assert canonical_env_label("half_cheetah") == "halfcheetah"
    assert canonical_env_label("walker") == canonical_env_label("walker2d")
    assert (
        canonical_env_label("mountaincarcontinuous")
        == canonical_env_label("mountain_car_continuous")
    )
    # a live instance's class name folds too (Swimmer2D registers as
    # "swimmer")
    assert canonical_env_label(Swimmer2D()) == canonical_env_label("swimmer")


def test_seeded_cache_has_the_r8_refill_entries():
    """The checked-in cache ships the r8 CPU-box measurements, so this box
    stops defaulting to the mistuned work/8 width at the bench shapes
    (BENCH_NOTES.md r8; 512 stays the documented no-cache fallback)."""
    import pathlib

    import evotorch_tpu.observability as obs

    machine = {"backend": "cpu", "device_kind": "cpu", "core_count": 1}
    # read the REAL checked-in file regardless of test-env overrides
    checked_in = pathlib.Path(obs.__file__).parent / "tuned_configs.json"
    entries = json.loads(checked_in.read_text())["entries"]
    by_key = {e["key"]: e for e in entries}
    # the bench policy at BENCH_HIDDEN default (64,64), f32, CPU bench
    # episode length — the shape the r8 lines were measured at
    shape = {
        "env": "humanoid",
        "episode_length": 100,
        "num_episodes": 1,
        "params": 12305,
        "dtype": "float32",
    }
    k1024 = timing_key("refill", dict(shape, popsize=1024), machine)
    k4096 = timing_key("refill", dict(shape, popsize=4096), machine)
    assert by_key[k1024]["config"]["width"] == 128
    assert by_key[k4096]["config"]["width"] == 256


# ---------------------------------------------------------------------------
# consumers: tuned_config_source provenance end to end
# ---------------------------------------------------------------------------


class _StubHarness:
    """A pure harness over the synthetic landscape — lets tune_group run
    end to end (ledger + cache write policy) with zero jax work."""

    group = "refill"
    program = "rollout.episodes_refill"

    def __init__(self, occupancy: float):
        self._occupancy = occupancy
        from evotorch_tpu.observability.autotune import TuneShape

        self.shape = TuneShape(env_name="cartpole", popsize=8)

        class _Policy:
            parameter_count = 7

        self.policy = _Policy()

    def knob_group(self):
        return KnobGroup("refill", (KnobSpec("width", (64, 128, 256)),))

    def default_config(self):
        return {"width": 128}

    def cost(self, config):
        return None

    def measure(self, configs, trials, round_index):
        return [
            {
                "samples": [float(_TRUE_RATE.get(c["width"], 50.0))] * trials,
                "occupancies": [self._occupancy] * trials,
                "steady_compiles": 0,
            }
            for c in configs
        ]

    def tuned_config(self, config):
        return {"width": config["width"], "period": 1}

    def baseline(self, trials=3):
        return {"steps_per_sec": 50.0, "occupancy": None, "samples": [50.0]}


def test_tune_group_withholds_floor_failing_winner_from_cache(
    tmp_path, monkeypatch
):
    from evotorch_tpu.observability.autotune import tune_group

    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(tmp_path / "floor.json"))
    # every candidate starves (occupancy 0.4): select_winner falls back to
    # the throughput winner, but the cache write is withheld — a lucky-run
    # wide rung must not become this machine's persisted schedule
    outcome = tune_group(_StubHarness(occupancy=0.4), min_occupancy=0.9)
    assert outcome.winner is not None
    assert outcome.cache_written is False
    assert lookup_tuned("refill", _stub_shape()) is None
    # with the floor met, the same search persists
    outcome = tune_group(_StubHarness(occupancy=0.95), min_occupancy=0.9)
    assert outcome.cache_written is True
    hit = lookup_tuned("refill", _stub_shape())
    assert hit is not None and hit.config["width"] == 256


def _stub_shape() -> dict:
    # matches _StubHarness's TuneShape defaults (episode_length 100, one
    # episode) + its stub policy; the autotuner measures unsharded, so its
    # saved entries carry the "none" mesh label (ISSUE-13 schema v2)
    return {
        "env": "cartpole",
        "popsize": 8,
        "episode_length": 100,
        "num_episodes": 1,
        "params": 7,
        "dtype": "float32",
        "mesh": "none",
    }


def _tiny_vecne(**kwargs):
    from evotorch_tpu.neuroevolution import VecNE

    return VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        eval_mode="episodes_refill",
        num_episodes=1,
        episode_length=8,
        **kwargs,
    )


def test_vecne_applies_cached_width_and_reports_source(tuned_cache):
    prob = _tiny_vecne()
    batch = prob.generate_batch(8)
    prob.evaluate(batch)
    prob.evaluate(batch)  # decode the lag-by-one telemetry
    status = prob._report_counters(batch)
    assert status["tuned_config_source"] == "cache"
    # the tuned width actually reached the engine: the telemetry's
    # lane_width IS the compiled program's fixed width
    assert prob._last_telemetry.lane_width == 4


def test_vecne_override_and_fallback_sources(tuned_cache, empty_cache):
    # note: empty_cache re-points EVOTORCH_TUNED_CACHE after tuned_cache
    # seeded its file, proving the explicit-knob branch never reads a file
    prob = _tiny_vecne(refill_config={"width": 8})
    batch = prob.generate_batch(8)
    prob.evaluate(batch)
    assert prob._report_counters(batch)["tuned_config_source"] == "override"

    prob = _tiny_vecne()
    batch = prob.generate_batch(8)
    prob.evaluate(batch)
    assert prob._report_counters(batch)["tuned_config_source"] == "fallback"


def test_sharded_evaluator_consults_cache_per_popsize(tuned_cache, monkeypatch):
    from jax.sharding import Mesh

    from evotorch_tpu.envs import CartPole
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.observability import EvalTelemetry
    from evotorch_tpu.parallel.evaluate import make_sharded_rollout_evaluator

    env = CartPole()
    policy = FlatParamsPolicy(Linear(env.observation_size, env.action_size) >> Tanh())
    mesh = Mesh(np.asarray(jax.devices()[:2]), axis_names=("pop",))
    # sharded lookups are mesh-scoped (ISSUE 13): the fixture's unsharded
    # entry must NOT serve this pop2 evaluation, so seed the pop2 entry
    evaluator = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh,
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
    )
    assert evaluator.tuned_config_source is None  # nothing dispatched yet
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (8, policy.parameter_count))
    result, _ = evaluator(params, jax.random.key(1), stats)
    # the fixture entry was tuned UNSHARDED — a pop2 mesh never inherits it
    assert evaluator.tuned_config_source == "fallback"

    save_tuned_entry(
        TunedEntry(
            group="refill",
            shape=dict(_cartpole_shape(), mesh="pop2"),
            machine=machine_fingerprint(),
            config={"width": 4, "period": 1},
            evidence={"steps_per_sec": 1.0},
        )
    )
    evaluator = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh,
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
    )
    result, _ = evaluator(params, jax.random.key(1), stats)
    assert evaluator.tuned_config_source == "cache"
    # GSPMD: the cached width is GLOBAL and applies undivided (4 mesh-wide)
    assert EvalTelemetry.from_array(result.telemetry).lane_width == 4

    explicit = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh,
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
        refill_width=8,
    )
    explicit(params, jax.random.key(1), stats)
    assert explicit.tuned_config_source == "override"

    # GROUP-level override semantics (the one precedence rule): an explicit
    # period ALSO disables the cache — the cached width was measured at its
    # cached period, and an unmeasured width/period mix must not wear a
    # "cache" label
    period_only = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh,
        num_episodes=1, episode_length=8, eval_mode="episodes_refill",
        refill_period=2,
    )
    result, _ = period_only(params, jax.random.key(1), stats)
    assert period_only.tuned_config_source == "override"
    # the engine default width applied, not the cached 4
    assert EvalTelemetry.from_array(result.telemetry).lane_width != 4


def test_per_group_occupancy_floors(tmp_path, monkeypatch):
    """Compaction structurally runs ~0.5 occupancy (each chunk pads to its
    slowest survivor), so a refill-style 0.9 floor would make the compact
    winner permanently unpersistable — the floors are per group, and
    ``min_occupancy="auto"`` resolves through the harness."""
    from evotorch_tpu.observability.autotune import (
        CompactHarness,
        HostPipelineHarness,
        RefillHarness,
        tune_group,
    )

    assert RefillHarness.default_min_occupancy == 0.9
    assert CompactHarness.default_min_occupancy is None
    assert HostPipelineHarness.default_min_occupancy is None

    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(tmp_path / "auto.json"))
    harness = _StubHarness(occupancy=0.5)
    harness.default_min_occupancy = None  # a floorless group, e.g. compact
    outcome = tune_group(harness)  # min_occupancy="auto"
    assert outcome.cache_written is True
    # the same sub-floor landscape with a refill-style floor is withheld
    harness = _StubHarness(occupancy=0.5)
    harness.default_min_occupancy = 0.9
    outcome = tune_group(harness, cache_path=str(tmp_path / "other.json"))
    assert outcome.cache_written is False


def test_host_pipeline_harness_has_tune_group_surface():
    """tune_group's budget derivation calls harness.default_config() on
    EVERY group under the default hbm_budget_ratio — the host harness must
    provide the full surface (it once lacked default_config and crashed
    `--group host_pipeline` before any trial)."""
    gym = pytest.importorskip("gymnasium")
    from evotorch_tpu.observability.autotune import (
        HostPipelineHarness,
        candidate_grid,
    )

    harness = HostPipelineHarness(env_id="CartPole-v1", num_envs=2, popsize=4)
    assert harness.default_config() is None
    assert harness.cost({"num_blocks": 1}) is None
    grid = candidate_grid(harness.knob_group())
    assert grid and all("num_blocks" in c for c in grid)
    # the anchor expression tune_group evaluates
    anchor = harness.default_config() or grid[0]
    assert anchor in grid


class _FixedLenEnv:
    """Minimal gym-API env: 1-dim obs, 3-step episodes, deterministic."""

    class _Box:
        low = np.asarray([-1.0])
        high = np.asarray([1.0])
        shape = (1,)

    observation_space = _Box()
    action_space = _Box()

    def __init__(self):
        self._t = 0

    def reset(self, seed=None):
        self._t = 0
        return np.asarray([1.0], dtype=np.float32), {}

    def step(self, action):
        self._t += 1
        return np.asarray([1.0], dtype=np.float32), 1.0, self._t >= 3, False, {}

    def close(self):
        pass


def test_host_pipeline_reports_tuned_source(tmp_path, monkeypatch, empty_cache):
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear
    from evotorch_tpu.neuroevolution.net.hostvecenv import (
        SyncVectorEnv,
        run_host_pipelined_rollout,
    )

    policy = FlatParamsPolicy(Linear(1, 1))
    params = jnp.zeros((4, policy.parameter_count), dtype=jnp.float32)

    def run(num_blocks=None, **kw):
        vec = SyncVectorEnv(_FixedLenEnv, 2)
        try:
            return run_host_pipelined_rollout(
                vec, policy, params, num_episodes=1, episode_length=5,
                mode="sync", num_blocks=num_blocks,
                rng=np.random.default_rng(0), **kw,
            )
        finally:
            vec.close()

    assert run(num_blocks=2)["tuned_config_source"] == "override"
    assert run()["tuned_config_source"] == "fallback"
    # a caller that resolved the group at its own altitude (GymNE) stamps
    # the TRUE provenance: its cache-sourced concrete block count must not
    # be relabeled "override" here
    out = run(num_blocks=2, use_tuned_cache=False, tuned_config_source="cache")
    assert out["tuned_config_source"] == "cache"

    # a machine-scoped host_pipeline entry flips the auto branch to cache
    monkeypatch.setenv("EVOTORCH_TUNED_CACHE", str(tmp_path / "host.json"))
    save_tuned_entry(
        TunedEntry(
            group="host_pipeline", shape={}, machine=machine_fingerprint(),
            config={"num_blocks": 2}, evidence={},
        )
    )
    out = run()
    assert out["tuned_config_source"] == "cache"
    assert len(out["block_iters"]) == 2  # the cached split was applied

    # an entry measured as a JOINT config (blocks + nthread together) must
    # NOT be half-applied at this altitude (nthread is baked into the
    # vec env) — partial application labeled "cache" would attribute the
    # run to a configuration never measured
    save_tuned_entry(
        TunedEntry(
            group="host_pipeline", shape={}, machine=machine_fingerprint(),
            config={"num_blocks": 2, "mj_nthread": 2}, evidence={},
        )
    )
    out = run()
    assert out["tuned_config_source"] == "fallback"
    assert len(out["block_iters"]) == 1  # the 1-core heuristic, not 2


def test_bench_common_tuned_resolution(tuned_cache, monkeypatch):
    import bench_common

    L = _cartpole_linear_params()
    base_cfg = {
        "env_name": "cartpole",
        "env_kwargs": {},
        "popsize": 8,
        "episode_length": 8,
        "tuned": True,
        "compute_dtype": None,
        "refill_width": None,
        "refill_period": 1,
        "refill_period_explicit": False,
        "compact_chunk": 25,
        "compact_chunk_explicit": False,
        "compact_min_width": None,
    }
    # cache hit: the r8-style entry supplies the schedule
    kwargs, source = bench_common.tuned_refill(base_cfg, params=L)
    assert source == "cache"
    assert kwargs == {"refill_period": 1, "refill_width": 4}
    # explicit BENCH_REFILL_WIDTH wins, and the global width divides per shard
    kwargs, source = bench_common.tuned_refill(
        dict(base_cfg, refill_width=8), n_shards=2, params=L
    )
    assert source == "override" and kwargs["refill_width"] == 4
    # BENCH_TUNED=0: byte-compatible fallback, no cache consult
    kwargs, source = bench_common.tuned_refill(
        dict(base_cfg, tuned=False), params=L
    )
    assert source == "fallback" and kwargs == {"refill_period": 1}
    # BENCH_ENV_ARGS mutates the env: the plain-name cache entry is wrong
    # evidence, so the consult is skipped; same for an unknown policy size
    kwargs, source = bench_common.tuned_refill(
        dict(base_cfg, env_kwargs={"n_links": 6}), params=L
    )
    assert source == "fallback"
    kwargs, source = bench_common.tuned_refill(base_cfg, params=None)
    assert source == "fallback"
    # a different policy size is a different workload: no hit
    kwargs, source = bench_common.tuned_refill(base_cfg, params=L + 1)
    assert source == "fallback"
    # compact goes through the same rule
    kwargs, source = bench_common.tuned_compact(base_cfg, params=L)
    assert source == "fallback" and kwargs == {"chunk_size": 25}
    kwargs, source = bench_common.tuned_compact(
        dict(base_cfg, compact_min_width=128), params=L
    )
    assert source == "override" and kwargs == {"chunk_size": 25, "min_width": 128}


def test_gymne_reports_tuned_source(empty_cache):
    pytest.importorskip("gymnasium")
    from evotorch_tpu.neuroevolution import GymNE

    prob = GymNE(
        env="gym::CartPole-v1",
        network="Linear(obs_length, act_length)",
        num_envs=2,
        episode_length=8,
    )
    batch = prob.generate_batch(2)
    prob.evaluate(batch)
    assert prob._report_counters(batch)["tuned_config_source"] == "fallback"

    prob = GymNE(
        env="gym::CartPole-v1",
        network="Linear(obs_length, act_length)",
        num_envs=2,
        episode_length=8,
        host_pipeline_blocks=1,
    )
    batch = prob.generate_batch(2)
    prob.evaluate(batch)
    assert prob._report_counters(batch)["tuned_config_source"] == "override"
