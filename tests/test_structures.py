import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import CBag, CDict, CList, CMemory, do_where


def test_do_where_pytree():
    a = {"x": jnp.ones((3, 2)), "y": jnp.ones(3)}
    b = {"x": jnp.zeros((3, 2)), "y": jnp.zeros(3)}
    out = do_where(jnp.array([True, False, True]), a, b)
    assert np.allclose(np.asarray(out["x"][:, 0]), [1, 0, 1])
    assert np.allclose(np.asarray(out["y"]), [1, 0, 1])


def test_cmemory_masked_ops():
    m = CMemory.create(4, 2)
    m = m.set_(1, jnp.array([5.0, 6.0]))
    assert np.allclose(np.asarray(m[1]), [5.0, 6.0])
    # masked-out update is a no-op
    m2 = m.set_(1, jnp.array([9.0, 9.0]), where=jnp.asarray(False))
    assert np.allclose(np.asarray(m2[1]), [5.0, 6.0])
    m3 = m.add_(1, jnp.array([1.0, 1.0]))
    assert np.allclose(np.asarray(m3[1]), [6.0, 7.0])
    # out-of-range get with default
    out = m.get(jnp.asarray(10), default=-1.0)
    assert np.allclose(np.asarray(out), -1.0)


def test_cmemory_under_vmap():
    # a batch of independent memories via vmap
    memories = CMemory(data=jnp.zeros((5, 3, 2)))  # batch of 5, 3 keys, values (2,)
    keys = jnp.arange(5) % 3
    values = jnp.ones((5, 2))
    updated = jax.vmap(lambda m, k, v: m.set_(k, v))(memories, keys, values)
    assert float(updated.data[0, 0, 0]) == 1.0
    assert float(updated.data[1, 1, 0]) == 1.0
    assert float(updated.data[1, 0, 0]) == 0.0


def test_cdict():
    d = CDict.create(["alpha", "beta"], 3)
    d = d.set_("alpha", jnp.ones(3))
    assert np.allclose(np.asarray(d["alpha"]), 1.0)
    assert np.allclose(np.asarray(d["beta"]), 0.0)
    with pytest.raises(KeyError):
        d.get("gamma")


def test_clist_push_pop():
    lst = CList.create(3)
    lst = lst.append_(1.0).append_(2.0).append_(3.0)
    assert bool(lst.is_full)
    # append on full is a masked no-op
    lst2 = lst.append_(9.0)
    assert int(lst2.length) == 3
    lst, v = lst.pop_()
    assert float(v) == 3.0 and int(lst.length) == 2
    lst, v = lst.popleft_()
    assert float(v) == 1.0 and int(lst.length) == 1
    assert float(lst[0]) == 2.0
    lst = lst.appendleft_(0.5)
    assert float(lst[0]) == 0.5


def test_clist_negative_index_and_jit():
    lst = CList.create(4)
    lst = lst.append_(1.0).append_(2.0)
    assert float(lst[-1]) == 2.0

    @jax.jit
    def push_many(lst, values):
        def step(lst, v):
            return lst.append_(v), None

        return jax.lax.scan(step, lst, values)[0]

    lst = push_many(CList.create(8), jnp.arange(5.0))
    assert int(lst.length) == 5
    assert float(lst[4]) == 4.0


def test_cbag():
    bag = CBag.create(4)
    bag = bag.push_(2).push_(2).push_(0)
    assert int(bag.total) == 3
    bag, k, ok = bag.pop_(2)
    assert bool(ok) and int(k) == 2
    bag, k, ok = bag.pop_(jax.random.key(0))
    assert bool(ok) and int(k) in (0, 2)
    bag, _, ok = bag.pop_(1)
    assert not bool(ok)


def test_cbag_legacy_prng_key():
    # review regression: a legacy uint32 PRNGKey must hit the random-pop
    # branch, not be misread as an element index
    bag = CBag.create(4).push_(2).push_(2).push_(0)
    bag2, k, ok = bag.pop_(jax.random.PRNGKey(0))
    assert np.asarray(k).shape == ()
    assert bool(ok) and int(k) in (0, 2)
    assert int(bag2.total) == 2
