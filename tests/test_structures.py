import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import CBag, CDict, CList, CMemory, do_where


def test_do_where_pytree():
    a = {"x": jnp.ones((3, 2)), "y": jnp.ones(3)}
    b = {"x": jnp.zeros((3, 2)), "y": jnp.zeros(3)}
    out = do_where(jnp.array([True, False, True]), a, b)
    assert np.allclose(np.asarray(out["x"][:, 0]), [1, 0, 1])
    assert np.allclose(np.asarray(out["y"]), [1, 0, 1])


def test_cmemory_masked_ops():
    m = CMemory.create(4, 2)
    m = m.set_(1, jnp.array([5.0, 6.0]))
    assert np.allclose(np.asarray(m[1]), [5.0, 6.0])
    # masked-out update is a no-op
    m2 = m.set_(1, jnp.array([9.0, 9.0]), where=jnp.asarray(False))
    assert np.allclose(np.asarray(m2[1]), [5.0, 6.0])
    m3 = m.add_(1, jnp.array([1.0, 1.0]))
    assert np.allclose(np.asarray(m3[1]), [6.0, 7.0])
    # out-of-range get with default
    out = m.get(jnp.asarray(10), default=-1.0)
    assert np.allclose(np.asarray(out), -1.0)


def test_cmemory_under_vmap():
    # a batch of independent memories via vmap
    memories = CMemory(data=jnp.zeros((5, 3, 2)))  # batch of 5, 3 keys, values (2,)
    keys = jnp.arange(5) % 3
    values = jnp.ones((5, 2))
    updated = jax.vmap(lambda m, k, v: m.set_(k, v))(memories, keys, values)
    assert float(updated.data[0, 0, 0]) == 1.0
    assert float(updated.data[1, 1, 0]) == 1.0
    assert float(updated.data[1, 0, 0]) == 0.0


def test_cdict():
    d = CDict.create(["alpha", "beta"], 3)
    d = d.set_("alpha", jnp.ones(3))
    assert np.allclose(np.asarray(d["alpha"]), 1.0)
    assert np.allclose(np.asarray(d["beta"]), 0.0)
    with pytest.raises(KeyError):
        d.get("gamma")


def test_clist_push_pop():
    lst = CList.create(3)
    lst = lst.append_(1.0).append_(2.0).append_(3.0)
    assert bool(lst.is_full)
    # append on full is a masked no-op
    lst2 = lst.append_(9.0)
    assert int(lst2.length) == 3
    lst, v = lst.pop_()
    assert float(v) == 3.0 and int(lst.length) == 2
    lst, v = lst.popleft_()
    assert float(v) == 1.0 and int(lst.length) == 1
    assert float(lst[0]) == 2.0
    lst = lst.appendleft_(0.5)
    assert float(lst[0]) == 0.5


def test_clist_negative_index_and_jit():
    lst = CList.create(4)
    lst = lst.append_(1.0).append_(2.0)
    assert float(lst[-1]) == 2.0

    @jax.jit
    def push_many(lst, values):
        def step(lst, v):
            return lst.append_(v), None

        return jax.lax.scan(step, lst, values)[0]

    lst = push_many(CList.create(8), jnp.arange(5.0))
    assert int(lst.length) == 5
    assert float(lst[4]) == 4.0


def test_cbag():
    bag = CBag.create(4)
    bag = bag.push_(2).push_(2).push_(0)
    assert int(bag.total) == 3
    bag, k, ok = bag.pop_(2)
    assert bool(ok) and int(k) == 2
    bag, k, ok = bag.pop_(jax.random.key(0))
    assert bool(ok) and int(k) in (0, 2)
    bag, _, ok = bag.pop_(1)
    assert not bool(ok)


def test_cbag_legacy_prng_key():
    # review regression: a legacy uint32 PRNGKey must hit the random-pop
    # branch, not be misread as an element index
    bag = CBag.create(4).push_(2).push_(2).push_(0)
    bag2, k, ok = bag.pop_(jax.random.PRNGKey(0))
    assert np.asarray(k).shape == ()
    assert bool(ok) and int(k) in (0, 2)
    assert int(bag2.total) == 2


# -- explicit batch shapes (reference structures.py batch semantics) ---------


def test_cmemory_batched_per_element_keys():
    m = CMemory.create(4, 2, batch_shape=(3,))
    assert m.is_batched and m.batch_shape == (3,)
    keys = jnp.asarray([0, 1, 3])
    vals = jnp.stack([jnp.full(2, 10.0), jnp.full(2, 20.0), jnp.full(2, 30.0)])
    m = m.set_(keys, vals)
    got = m.get(keys)
    assert got.shape == (3, 2)
    np.testing.assert_allclose(np.asarray(got[:, 0]), [10.0, 20.0, 30.0])
    # other slots untouched
    np.testing.assert_allclose(np.asarray(m.get(jnp.asarray([1, 0, 0]))), 0.0)


def test_cmemory_batched_where_mask():
    m = CMemory.create(3, batch_shape=(4,))
    m = m.set_(jnp.zeros(4, jnp.int32), jnp.asarray([1.0, 2.0, 3.0, 4.0]),
               where=jnp.asarray([True, False, True, False]))
    np.testing.assert_allclose(np.asarray(m.get(jnp.zeros(4, jnp.int32))),
                               [1.0, 0.0, 3.0, 0.0])
    m = m.add_(jnp.zeros(4, jnp.int32), 10.0, where=jnp.asarray([False, True, True, True]))
    np.testing.assert_allclose(np.asarray(m.get(jnp.zeros(4, jnp.int32))),
                               [1.0, 10.0, 13.0, 10.0])


def test_cmemory_multidim_keys_and_offset():
    # num_keys=(3, 5) with key_offset=1: keys range over (1..3, 1..5)
    m = CMemory.create((3, 5), key_offset=1)
    m = m.set_((jnp.asarray(2), jnp.asarray(4)), 7.0)
    assert float(m.get((2, 4))) == 7.0
    # key given as a trailing-dim array
    assert float(m.get(jnp.asarray([2, 4]))) == 7.0
    # out-of-range -> default
    assert float(m.get((0, 1), default=-1.0)) == -1.0
    assert float(m.get((1, 1), default=-1.0)) == 0.0


def test_cmemory_add_circular():
    m = CMemory.create(2, fill=5.0)
    m = m.add_circular_(0, 4.0, 6.0)  # (5 + 4) % 6 = 3
    assert float(m[0]) == 3.0
    assert float(m[1]) == 5.0


def test_cmemory_invalid_key_write_is_noop():
    m = CMemory.create(3, batch_shape=(2,))
    m = m.set_(jnp.asarray([1, 9]), jnp.asarray([5.0, 5.0]))  # 9 invalid
    np.testing.assert_allclose(np.asarray(m.data[1]), 0.0)
    np.testing.assert_allclose(np.asarray(m.data[0, 1]), 5.0)


def test_cdict_integer_keys_existence():
    d = CDict.create(5, 2)
    assert not bool(d.contains(3))
    # arithmetic on a missing key does not create it (reference semantics)
    d = d.add_(3, 1.0)
    assert not bool(d.contains(3))
    assert float(d.get(3, default=-9.0)[0]) == -9.0
    d = d.set_(3, jnp.asarray([4.0, 5.0]))
    assert bool(d.contains(3))
    np.testing.assert_allclose(np.asarray(d.get(3, default=-9.0)), [4.0, 5.0])
    # arithmetic on an existing key updates it but existence is unchanged
    d = d.add_(3, 1.0)
    np.testing.assert_allclose(np.asarray(d.get(3, default=-9.0)), [5.0, 6.0])
    # clear resets existence, not values
    d = d.clear()
    assert not bool(d.contains(3))
    np.testing.assert_allclose(np.asarray(d.memory.get(3)), [5.0, 6.0])


def test_cdict_batched_clear_where():
    d = CDict.create(3, batch_shape=(2,))
    d = d.set_(jnp.asarray([0, 1]), jnp.asarray([1.0, 2.0]))
    assert np.asarray(d.contains(jnp.asarray([0, 1]))).all()
    d = d.clear(where=jnp.asarray([True, False]))
    got = np.asarray(d.contains(jnp.asarray([0, 1])))
    assert not got[0] and got[1]


def test_clist_batched_independent_cursors():
    lst = CList.create(3, batch_shape=(2,))
    lst = lst.append_(jnp.asarray([1.0, 10.0]))
    lst = lst.append_(jnp.asarray([2.0, 20.0]), where=jnp.asarray([True, False]))
    np.testing.assert_array_equal(np.asarray(lst.length), [2, 1])
    np.testing.assert_allclose(np.asarray(lst.get(jnp.asarray([1, 0]))), [2.0, 10.0])
    lst, v = lst.pop_(where=jnp.asarray([True, True]))
    np.testing.assert_array_equal(np.asarray(lst.length), [1, 0])
    np.testing.assert_allclose(np.asarray(v), [2.0, 10.0])
    # arithmetic at a logical index
    lst = lst.add_(jnp.asarray([0, 0]), 5.0)  # lane 1 is empty -> masked no-op
    np.testing.assert_allclose(float(lst.get(jnp.asarray([0, 0]))[0]), 6.0)


def test_clist_clear_and_get_default():
    lst = CList.create(4).append_(1.0).append_(2.0)
    assert float(lst.get(5, default=-1.0)) == -1.0
    lst = lst.clear()
    assert int(lst.length) == 0
    assert float(lst.get(0, default=-1.0)) == -1.0


def test_cbag_capacity_and_batch():
    bag = CBag.create(3, capacity=2, batch_shape=(2,))
    bag = bag.push_(jnp.asarray([0, 1]))
    bag = bag.push_(jnp.asarray([0, 2]))
    bag = bag.push_(jnp.asarray([1, 2]))  # both full -> masked no-op
    np.testing.assert_array_equal(np.asarray(bag.total), [2, 2])
    bag, keys, ok = bag.pop_(jax.random.key(0))
    assert np.asarray(ok).all()
    assert keys.shape == (2,)
    np.testing.assert_array_equal(np.asarray(bag.total), [1, 1])
    bag = bag.clear(where=jnp.asarray([True, False]))
    np.testing.assert_array_equal(np.asarray(bag.total), [0, 1])


def test_structures_batched_under_jit():
    @jax.jit
    def roundtrip(m, d, lst):
        m = m.set_(jnp.asarray([1, 2]), jnp.asarray([1.0, 2.0]))
        d = d.set_(jnp.asarray([0, 0]), jnp.asarray([3.0, 4.0]))
        lst = lst.append_(jnp.asarray([5.0, 6.0]))
        return m, d, lst

    m, d, lst = roundtrip(
        CMemory.create(4, batch_shape=(2,)),
        CDict.create(4, batch_shape=(2,)),
        CList.create(4, batch_shape=(2,)),
    )
    np.testing.assert_allclose(np.asarray(m.get(jnp.asarray([1, 2]))), [1.0, 2.0])
    assert np.asarray(d.contains(jnp.asarray([0, 0]))).all()
    np.testing.assert_array_equal(np.asarray(lst.length), [1, 1])


def test_cmemory_unbatched_array_key_gather():
    # review regression: an unbatched memory indexed with an ARRAY of keys
    # gathers multiple slots (plain multi-element indexing)
    m = CMemory.create(4, 2)
    m = m.set_(1, jnp.asarray([5.0, 6.0])).set_(2, jnp.asarray([7.0, 8.0]))
    got = m.get(jnp.asarray([1, 2, 1]))
    assert got.shape == (3, 2)
    np.testing.assert_allclose(np.asarray(got[:, 0]), [5.0, 7.0, 5.0])
    # and on a batched memory, a (K, B) key stack gathers K per element
    mb = CMemory.create(4, batch_shape=(2,))
    mb = mb.set_(jnp.asarray([0, 1]), jnp.asarray([1.0, 2.0]))
    got = mb.get(jnp.asarray([[0, 1], [1, 0]]))
    assert got.shape == (2, 2)
    np.testing.assert_allclose(np.asarray(got), [[1.0, 2.0], [0.0, 0.0]])


def test_clist_unbatched_array_index_gather():
    lst = CList.create(4).append_(1.0).append_(2.0).append_(3.0)
    got = lst.get(jnp.asarray([0, 2, -1]))
    np.testing.assert_allclose(np.asarray(got), [1.0, 3.0, 3.0])


def test_cbag_unbatched_multi_key_push_accumulates():
    # ADVICE r2: pushing an array of keys on an unbatched bag must accumulate
    # (scatter-add), including duplicates in the same call
    bag = CBag.create(4)
    bag = bag.push_(jnp.asarray([2, 2, 0]))
    assert int(bag.counts[2]) == 2
    assert int(bag.counts[0]) == 1
    assert int(bag.total) == 3
    # multi-key specific pop on the unbatched bag
    bag, popped, ok = bag.pop_(jnp.asarray([2, 0]))
    assert bool(ok.all())
    assert int(bag.total) == 1
    assert int(bag.counts[2]) == 1


def test_cdict_create_explicit_keywords():
    import pytest

    # integer names are only reachable through the explicit keyword
    d = CDict.create(names=[10, 20])
    d = d.set_(10, jnp.asarray(3.0))
    assert float(d.get(10)) == 3.0
    assert not bool(d.contains(20))
    d2 = CDict.create(num_keys=4)
    d2 = d2.set_(1, jnp.asarray(2.0))
    assert float(d2.get(1)) == 2.0
    with pytest.raises(TypeError):
        CDict.create(4, num_keys=4)
    with pytest.raises(TypeError):
        CDict.create(names=["a"], num_keys=2)
    with pytest.raises(TypeError):
        CDict.create()


def test_cbag_duplicate_pop_clamps_at_zero():
    # code-review r3: duplicate keys in one multi-key pop must not drive
    # counts negative (ok may over-report — documented — but the bag stays valid)
    bag = CBag.create(4).push_(2)
    bag, popped, ok = bag.pop_(jnp.asarray([2, 2]))
    assert int(bag.counts[2]) == 0
    assert int(bag.total) == 0
