"""Shared test helpers: run functional ask/tell searches as one jitted scan."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("ask", "tell", "fitness", "popsize", "num_generations"))
def run_functional_search(state, key, *, ask, tell, fitness, popsize, num_generations):
    """Run `num_generations` of ask/eval/tell inside one lax.scan."""

    def gen(state, key):
        pop = ask(key, state, popsize=popsize)
        fits = fitness(pop)
        state = tell(state, pop, fits)
        return state, jnp.mean(fits)

    keys = jax.random.split(key, num_generations)
    return jax.lax.scan(gen, state, keys)
