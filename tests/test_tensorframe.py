import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import TensorFrame


def make_frame():
    return TensorFrame.create(
        fitness=jnp.array([3.0, 1.0, 2.0]),
        values=jnp.arange(6.0).reshape(3, 2),
        tag=jnp.array([0, 1, 0]),
    )


def test_create_and_access():
    f = make_frame()
    assert len(f) == 3
    assert f.column_names == ("fitness", "values", "tag")
    assert np.allclose(np.asarray(f["fitness"]), [3.0, 1.0, 2.0])
    assert f.values.shape == (3, 2)
    with pytest.raises(KeyError):
        f["nope"]


def test_scalar_broadcast():
    f = TensorFrame.create(a=jnp.arange(4.0), b=7.0)
    assert np.allclose(np.asarray(f["b"]), 7.0)
    assert len(f) == 4


def test_length_mismatch_rejected():
    with pytest.raises(ValueError):
        TensorFrame.create(a=jnp.zeros(3), b=jnp.zeros(4))


def test_with_and_without_columns():
    f = make_frame()
    g = f.with_columns(rank=jnp.array([2, 0, 1]))
    assert "rank" in g.column_names
    h = g.without_columns("values")
    assert "values" not in h.column_names
    # original untouched
    assert "rank" not in f.column_names


def test_pick_rows():
    f = make_frame()
    sub = f.pick[jnp.array([True, False, True])]
    assert len(sub) == 2
    assert np.allclose(np.asarray(sub["fitness"]), [3.0, 2.0])
    sub2 = f.pick[jnp.array([1])]
    assert float(sub2["fitness"][0]) == 1.0
    sub3 = f.pick[0:2]
    assert len(sub3) == 2
    # frame[mask] routes to pick
    assert len(f[jnp.array([True, True, False])]) == 2


def test_sort_and_concat():
    f = make_frame()
    s = f.sort_values("fitness")
    assert np.asarray(s["fitness"]).tolist() == [1.0, 2.0, 3.0]
    s = f.sort_values("fitness", descending=True)
    assert np.asarray(s["fitness"]).tolist() == [3.0, 2.0, 1.0]
    both = f.concat(f)
    assert len(both) == 6


def test_frame_through_jit():
    f = make_frame()

    @jax.jit
    def double_fitness(frame):
        return frame.with_columns(fitness=frame["fitness"] * 2)

    out = double_fitness(f)
    assert np.allclose(np.asarray(out["fitness"]), [6.0, 2.0, 4.0])


def test_to_pandas():
    df = make_frame().without_columns("values").to_pandas()
    assert list(df.columns) == ["fitness", "tag"]
    assert len(df) == 3


# -- reference-parity surface: pick[rows, cols], pick_set, joins, each -------


def test_pick_rows_and_columns():
    f = make_frame()
    sub = f.pick[jnp.asarray([0, 2]), "fitness"]
    assert sub.column_names == ("fitness",)
    assert np.asarray(sub["fitness"]).tolist() == [3.0, 2.0]
    sub2 = f.pick[jnp.asarray([True, False, True]), ["fitness", "tag"]]
    assert sub2.column_names == ("fitness", "tag")
    assert len(sub2) == 2
    sub3 = f.pick[1:, :]
    assert len(sub3) == 2 and sub3.column_names == f.column_names


def test_pick_set_functional_assignment():
    f = make_frame()
    # index-array write to one column
    f2 = f.pick_set(jnp.asarray([0, 1]), jnp.asarray([9.0, 8.0]), columns="fitness")
    assert np.asarray(f2["fitness"]).tolist() == [9.0, 8.0, 2.0]
    assert np.asarray(f["fitness"]).tolist() == [3.0, 1.0, 2.0]  # original intact
    # boolean-mask write via a mapping (jit/vmap-safe form)
    f3 = f.pick_set(
        jnp.asarray([True, False, True]),
        {"fitness": 0.0, "tag": jnp.asarray(7)},
    )
    assert np.asarray(f3["fitness"]).tolist() == [0.0, 1.0, 0.0]
    assert np.asarray(f3["tag"]).tolist() == [7, 1, 7]
    # frame right-hand side + slice rows
    f4 = f.pick_set(slice(0, 2), TensorFrame.create(fitness=jnp.asarray([5.0, 5.0])))
    assert np.asarray(f4["fitness"]).tolist() == [5.0, 5.0, 2.0]
    # in-place pick assignment is rejected with a pointer to pick_set
    with pytest.raises(TypeError, match="pick_set"):
        f.pick[jnp.asarray([0])] = 1.0


def test_pick_set_under_jit_with_mask():
    f = make_frame()

    @jax.jit
    def zero_where_tagged(frame):
        return frame.pick_set(frame["tag"] == 0, jnp.asarray(0.0), columns="fitness")

    out = zero_where_tagged(f)
    assert np.asarray(out["fitness"]).tolist() == [0.0, 1.0, 0.0]


def test_hstack_join_drop():
    f = make_frame()
    g = TensorFrame.create(extra=jnp.asarray([10.0, 20.0, 30.0]))
    joined = f.join(g)
    assert joined.column_names == ("fitness", "values", "tag", "extra")
    with pytest.raises(ValueError, match="override"):
        f.hstack(f)
    overridden = f.hstack(
        TensorFrame.create(fitness=jnp.zeros(3)), override=True
    )
    assert np.asarray(overridden["fitness"]).tolist() == [0.0, 0.0, 0.0]
    dropped = joined.drop(columns=["values", "extra"])
    assert dropped.column_names == ("fitness", "tag")
    with pytest.raises(ValueError, match="unknown"):
        f.drop(columns="nope")


def test_vstack_argsort_nlargest():
    f = make_frame()
    assert len(f.vstack(f)) == 6
    assert np.asarray(f.argsort("fitness")).tolist() == [1, 2, 0]
    top2 = f.nlargest(2, "fitness")
    assert np.asarray(top2["fitness"]).tolist() == [3.0, 2.0]
    bottom = f.nsmallest(1, "fitness")
    assert np.asarray(bottom["fitness"]).tolist() == [1.0]
    assert np.asarray(f.sort("fitness")["fitness"]).tolist() == [1.0, 2.0, 3.0]


def test_each_vmapped_rowwise():
    f = make_frame()
    out = f.each(lambda row: {"double": row["fitness"] * 2})
    assert out.column_names == ("double",)
    assert np.asarray(out["double"]).tolist() == [6.0, 2.0, 4.0]
    joined = f.each(
        lambda row: {"fitness": row["fitness"] + row["tag"]}, join=True, override=True
    )
    assert np.asarray(joined["fitness"]).tolist() == [3.0, 2.0, 2.0]
    assert "values" in joined.column_names
    with pytest.raises(ValueError, match="join"):
        f.each(lambda row: {"x": row["tag"]}, override=True)
