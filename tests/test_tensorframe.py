import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import TensorFrame


def make_frame():
    return TensorFrame.create(
        fitness=jnp.array([3.0, 1.0, 2.0]),
        values=jnp.arange(6.0).reshape(3, 2),
        tag=jnp.array([0, 1, 0]),
    )


def test_create_and_access():
    f = make_frame()
    assert len(f) == 3
    assert f.column_names == ("fitness", "values", "tag")
    assert np.allclose(np.asarray(f["fitness"]), [3.0, 1.0, 2.0])
    assert f.values.shape == (3, 2)
    with pytest.raises(KeyError):
        f["nope"]


def test_scalar_broadcast():
    f = TensorFrame.create(a=jnp.arange(4.0), b=7.0)
    assert np.allclose(np.asarray(f["b"]), 7.0)
    assert len(f) == 4


def test_length_mismatch_rejected():
    with pytest.raises(ValueError):
        TensorFrame.create(a=jnp.zeros(3), b=jnp.zeros(4))


def test_with_and_without_columns():
    f = make_frame()
    g = f.with_columns(rank=jnp.array([2, 0, 1]))
    assert "rank" in g.column_names
    h = g.without_columns("values")
    assert "values" not in h.column_names
    # original untouched
    assert "rank" not in f.column_names


def test_pick_rows():
    f = make_frame()
    sub = f.pick[jnp.array([True, False, True])]
    assert len(sub) == 2
    assert np.allclose(np.asarray(sub["fitness"]), [3.0, 2.0])
    sub2 = f.pick[jnp.array([1])]
    assert float(sub2["fitness"][0]) == 1.0
    sub3 = f.pick[0:2]
    assert len(sub3) == 2
    # frame[mask] routes to pick
    assert len(f[jnp.array([True, True, False])]) == 2


def test_sort_and_concat():
    f = make_frame()
    s = f.sort_values("fitness")
    assert np.asarray(s["fitness"]).tolist() == [1.0, 2.0, 3.0]
    s = f.sort_values("fitness", descending=True)
    assert np.asarray(s["fitness"]).tolist() == [3.0, 2.0, 1.0]
    both = f.concat(f)
    assert len(both) == 6


def test_frame_through_jit():
    f = make_frame()

    @jax.jit
    def double_fitness(frame):
        return frame.with_columns(fitness=frame["fitness"] * 2)

    out = double_fitness(f)
    assert np.allclose(np.asarray(out["fitness"]), [6.0, 2.0, 4.0])


def test_to_pandas():
    df = make_frame().without_columns("values").to_pandas()
    assert list(df.columns) == ["fitness", "tag"]
    assert len(df) == 3
