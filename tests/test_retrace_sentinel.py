"""Runtime retrace sentinel: steady-state compile counts for the hot paths.

Every test follows the same shape: warm the program up (first call compiles),
run more generations of IDENTICAL shape, and assert with
``assert_compiles(0)`` that the steady state never re-traces. These guard the
contract the whole framework is built on — "stays compiled, stays on
device" — for all four eval contracts and the jitted PGPE/SNES ask-tell
steps; any change that starts recompiling per generation fails here, in the
fast tier.
"""

from functools import partial

import jax
import jax.numpy as jnp
import pytest

from evotorch_tpu.algorithms.functional import (
    pgpe,
    pgpe_ask,
    pgpe_tell,
    snes,
    snes_ask,
    snes_tell,
)
from evotorch_tpu.analysis import RetraceError, assert_compiles, track_compiles
from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import (
    FlatParamsPolicy,
    Linear,
    Tanh,
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm

POPSIZE = 8
EPISODE_LENGTH = 16


def _env_policy():
    env = CartPole()
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    return env, FlatParamsPolicy(net)


def _pgpe_state(n_params: int):
    return pgpe(
        center_init=jnp.zeros(n_params),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )


# ---------------------------------------------------------------------------
# the sentinel itself
# ---------------------------------------------------------------------------


def test_sentinel_canary_detects_fresh_compiles():
    """If jax's compile-log format ever drifts, the sentinel would silently
    count zero and every steady-state assertion would pass vacuously — this
    canary fails instead."""
    f = jax.jit(lambda x: x * 2.0 + 1.0)
    with track_compiles() as log:
        f(jnp.ones(7))
    assert log.count >= 1, "sentinel missed a guaranteed fresh compile"
    assert log.count_matching("<lambda>") == 1
    with track_compiles() as warm:
        f(jnp.ones(7))
    assert warm.count == 0, f"warm call recompiled: {warm.names}"


def test_sentinel_assert_compiles_raises():
    f = jax.jit(lambda x: x - 3.0)
    x11, x13 = jnp.ones(11), jnp.ones(13)  # their own tiny compiles stay outside
    with pytest.raises(RetraceError):
        with assert_compiles(0):
            f(x11)
    # the budgeted + name-filtered form passes: one compile of f itself
    with assert_compiles(1, match="<lambda>"):
        f(x13)


def test_sentinel_is_nestable():
    """Overlapping tracking blocks each see every compile — the property
    that lets the always-on registry promotion and test-scoped sentinels
    compose (sink scope is the process, not the block)."""
    f = jax.jit(lambda x: x * 5.0)
    with track_compiles() as outer:
        f(jnp.ones(17))  # outer-only compile
        with track_compiles() as inner:
            f(jnp.ones(19))  # seen by both
        f(jnp.ones(23))  # outer-only again
    assert inner.count_matching("<lambda>") == 1
    assert outer.count_matching("<lambda>") == 3


def test_sentinel_is_thread_safe():
    """Compiles triggered on other threads are observed, and concurrent
    tracking blocks do not corrupt each other's logs."""
    import threading

    errors = []
    inner_counts = []

    def compile_on_thread(width):
        try:
            # a fresh jit wrapper per thread, each at its own shape
            f = jax.jit(lambda x: x / 7.0)
            with track_compiles() as log:
                jax.block_until_ready(f(jnp.ones(width)))
            assert log.count_matching("<lambda>") >= 1, log.names
            inner_counts.append(log.count_matching("<lambda>"))
        except Exception as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    with track_compiles() as outer:
        threads = [
            threading.Thread(target=compile_on_thread, args=(29 + i,))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    assert outer.count_matching("<lambda>") == 3
    # the outer sink is registered for the inner blocks' whole lifetime, so
    # it must see AT LEAST whatever any inner block saw — under-counting
    # here is the wrong-sink-unregistered registry bug this test once
    # caught (value-equal CompileLogs + remove-by-equality)
    assert outer.count_matching("<lambda>") >= max(inner_counts)


def test_unregister_removes_by_identity_not_equality():
    """Two overlapping logs that observed the SAME records are value-equal;
    one block exiting must unregister ITS sink, not the first equal one —
    the outer block keeps receiving later compiles (the exact silent
    under-count the thread-safety test flushed out under full-suite load)."""
    with track_compiles() as outer:
        with track_compiles() as inner:
            f = jax.jit(lambda x: x * 3.0)
            jax.block_until_ready(f(jnp.ones(41)))
        # inner exited with names == outer.names; outer MUST still be live
        assert inner.names == outer.names
        g = jax.jit(lambda x: x * 5.0)
        jax.block_until_ready(g(jnp.ones(43)))
    assert outer.count_matching("<lambda>") == 2
    assert inner.count_matching("<lambda>") == 1


def test_global_compile_counter_composes_with_scoped_sentinels():
    """The session-wide promotion (observability registry) keeps counting
    while scoped blocks come and go."""
    from evotorch_tpu.observability import counters, ensure_compile_counter

    ensure_compile_counter()
    f = jax.jit(lambda x: x + 11.0)
    before = counters.get("compiles")
    with track_compiles() as log:
        f(jnp.ones(31))
    assert log.count_matching("<lambda>") == 1
    assert counters.get("compiles") - before >= 1


# ---------------------------------------------------------------------------
# eval contracts: one compile, then steady state
# ---------------------------------------------------------------------------


def _generation_fn(env, policy, eval_mode, **rollout_kwargs):
    stats = RunningNorm(env.observation_size).stats

    def generation(state, key):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask(k1, state, popsize=POPSIZE)
        result = run_vectorized_rollout(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=EPISODE_LENGTH,
            eval_mode=eval_mode,
            **rollout_kwargs,
        )
        state = pgpe_tell(state, values, result.scores)
        return state, result.scores

    return jax.jit(generation, donate_argnums=(0,))


@pytest.mark.parametrize(
    "eval_mode,kwargs",
    [
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": 4}),
    ],
)
def test_eval_contract_steady_state(eval_mode, kwargs):
    env, policy = _env_policy()
    gen = _generation_fn(env, policy, eval_mode, **kwargs)
    state = _pgpe_state(policy.parameter_count)
    key = jax.random.key(0)

    # warmup: exactly one compile of the generation program
    with track_compiles() as log:
        key, sub = jax.random.split(key)
        state, scores = gen(state, sub)
        jax.block_until_ready(scores)
    assert log.count_matching("generation") == 1, log.names

    # second call settles any remaining first-use programs (donation reuse)
    key, sub = jax.random.split(key)
    state, scores = gen(state, sub)
    jax.block_until_ready(scores)

    # steady state: ZERO compiles of any kind across further generations
    with assert_compiles(0):
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, scores = gen(state, sub)
            jax.block_until_ready(scores)


def test_eval_contract_steady_state_episodes_compact():
    """The host-orchestrated compacting runner: its jitted building blocks
    (init/chunk/compact/finalize) are cached per config, so generations after
    the first must not trace anything new."""
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    ask_jit = jax.jit(partial(pgpe_ask, popsize=POPSIZE))
    tell_jit = jax.jit(pgpe_tell, donate_argnums=(0,))
    state = _pgpe_state(policy.parameter_count)
    key = jax.random.key(0)

    def generation(state, key):
        k1, k2 = jax.random.split(key)
        values = ask_jit(k1, state)
        result = run_vectorized_rollout_compacting(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=EPISODE_LENGTH,
        )
        state = tell_jit(state, values, result.scores)
        return state, result.scores

    for _ in range(2):  # warmup: compile + settle
        key, sub = jax.random.split(key)
        state, scores = generation(state, sub)
        jax.block_until_ready(scores)

    with assert_compiles(0):
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, scores = generation(state, sub)
            jax.block_until_ready(scores)


# ---------------------------------------------------------------------------
# telemetry-ON: zero extra compiles, zero extra transfers (all 4 contracts)
# ---------------------------------------------------------------------------


def _telemetry_generation_fn(env, policy, eval_mode, **rollout_kwargs):
    stats = RunningNorm(env.observation_size).stats

    def generation(state, key):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask(k1, state, popsize=POPSIZE)
        result = run_vectorized_rollout(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=EPISODE_LENGTH,
            eval_mode=eval_mode,
            **rollout_kwargs,
        )
        state = pgpe_tell(state, values, result.scores)
        return state, result.scores, result.telemetry

    return jax.jit(generation, donate_argnums=(0,))


@pytest.mark.parametrize(
    "eval_mode,kwargs",
    [
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": 4}),
    ],
)
def test_telemetry_on_adds_zero_steady_state_compiles(eval_mode, kwargs):
    """The zero-sync contract, sentinel-asserted: with the accumulators ON
    and the telemetry vector CONSUMED every generation, the steady state
    compiles nothing — the vector is an output of the already-compiled
    generation program (same transfer as the scores), never a new
    dispatch."""
    from evotorch_tpu.observability import EvalTelemetry

    env, policy = _env_policy()
    gen = _telemetry_generation_fn(env, policy, eval_mode, **kwargs)
    state = _pgpe_state(policy.parameter_count)
    key = jax.random.key(0)

    for _ in range(2):  # warmup + donation settle
        key, sub = jax.random.split(key)
        state, scores, telemetry = gen(state, sub)
        jax.block_until_ready(scores)

    with assert_compiles(0):
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, scores, telemetry = gen(state, sub)
            jax.block_until_ready(scores)
            decoded = EvalTelemetry.from_array(telemetry)  # the one fetch
    assert decoded.env_steps > 0
    if eval_mode == "budget":
        assert decoded.occupancy == 1.0


def test_telemetry_on_adds_zero_steady_state_compiles_episodes_compact():
    from evotorch_tpu.observability import EvalTelemetry

    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    ask_jit = jax.jit(partial(pgpe_ask, popsize=POPSIZE))
    tell_jit = jax.jit(pgpe_tell, donate_argnums=(0,))
    state = _pgpe_state(policy.parameter_count)
    key = jax.random.key(0)

    def generation(state, key):
        k1, k2 = jax.random.split(key)
        values = ask_jit(k1, state)
        result = run_vectorized_rollout_compacting(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=EPISODE_LENGTH,
        )
        state = tell_jit(state, values, result.scores)
        return state, result.scores, result.telemetry

    for _ in range(2):
        key, sub = jax.random.split(key)
        state, scores, telemetry = generation(state, sub)
        jax.block_until_ready(scores)

    with assert_compiles(0):
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, scores, telemetry = generation(state, sub)
            jax.block_until_ready(scores)
            decoded = EvalTelemetry.from_array(telemetry)
    assert decoded.episodes == POPSIZE


# ---------------------------------------------------------------------------
# functional ask-tell steps (PGPE / SNES) on a plain fitness function
# ---------------------------------------------------------------------------


def _sphere(values):
    return -jnp.sum(values**2, axis=-1)


@pytest.mark.parametrize("algo", ["pgpe", "snes"])
def test_ask_tell_step_steady_state(algo):
    if algo == "pgpe":
        state = _pgpe_state(12)
        ask, tell = pgpe_ask, pgpe_tell
    else:
        state = snes(center_init=jnp.zeros(12), objective_sense="max", stdev_init=0.1)
        ask, tell = snes_ask, snes_tell

    def step(state, key):
        values = ask(key, state, popsize=POPSIZE)
        return tell(state, values, _sphere(values))

    step_jit = jax.jit(step, donate_argnums=(0,))
    key = jax.random.key(1)

    with track_compiles() as log:
        key, sub = jax.random.split(key)
        state = step_jit(state, sub)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    assert log.count_matching("step") == 1, log.names

    key, sub = jax.random.split(key)
    state = step_jit(state, sub)

    with assert_compiles(0):
        for _ in range(3):
            key, sub = jax.random.split(key)
            state = step_jit(state, sub)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
