import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.algorithms.functional import (
    adam,
    adam_ask,
    adam_tell,
    clipup,
    clipup_ask,
    clipup_tell,
    get_functional_optimizer,
    sgd,
    sgd_ask,
    sgd_tell,
)


def test_adam_converges_to_maximum():
    # maximize -(x-2)^2: gradient = -2(x-2)
    state = adam(center_init=jnp.zeros(3), center_learning_rate=0.1)

    @jax.jit
    def run(state):
        def step(state, _):
            x = adam_ask(state)
            return adam_tell(state, follow_grad=-2 * (x - 2.0)), None

        return jax.lax.scan(step, state, None, length=200)[0]

    state = run(state)
    assert np.allclose(np.asarray(adam_ask(state)), 2.0, atol=0.05)


def test_clipup_velocity_clip():
    state = clipup(center_init=jnp.zeros(2), center_learning_rate=0.1)
    assert float(state.max_speed) == pytest.approx(0.2)
    big_grad = jnp.array([1000.0, 0.0])

    @jax.jit
    def run(state):
        def step(state, _):
            return clipup_tell(state, follow_grad=big_grad), None

        return jax.lax.scan(step, state, None, length=50)[0]

    state = run(state)
    # velocity normalized: after many steps the speed stays at max_speed
    assert float(jnp.linalg.norm(state.velocity)) <= 0.2 + 1e-6
    # center advanced in the gradient direction only
    assert float(state.center[0]) > 0
    assert float(state.center[1]) == pytest.approx(0.0, abs=1e-6)


def test_clipup_requires_lr_or_max_speed():
    with pytest.raises(ValueError):
        clipup(center_init=jnp.zeros(2))
    st = clipup(center_init=jnp.zeros(2), max_speed=0.4)
    assert float(st.center_learning_rate) == pytest.approx(0.2)


def test_sgd_momentum():
    state = sgd(center_init=jnp.zeros(1), center_learning_rate=0.1, momentum=0.9)
    state = sgd_tell(state, follow_grad=jnp.ones(1))
    assert float(state.center[0]) == pytest.approx(0.1)
    state = sgd_tell(state, follow_grad=jnp.ones(1))
    assert float(state.center[0]) == pytest.approx(0.1 + 0.19)


def test_batched_optimizer():
    # two independent Adam searches in one state
    state = adam(center_init=jnp.zeros((2, 3)), center_learning_rate=0.1)
    targets = jnp.array([[1.0, 1.0, 1.0], [-1.0, -1.0, -1.0]])

    @jax.jit
    def run(state):
        def step(state, _):
            x = adam_ask(state)
            return adam_tell(state, follow_grad=-2 * (x - targets)), None

        return jax.lax.scan(step, state, None, length=100)[0]

    state = run(state)
    assert np.allclose(np.asarray(adam_ask(state)), np.asarray(targets), atol=0.1)


def test_registry():
    init, ask, tell = get_functional_optimizer("clipup")
    assert init is clipup and ask is clipup_ask and tell is clipup_tell
    custom = (sgd, sgd_ask, sgd_tell)
    assert get_functional_optimizer(custom) == custom
    with pytest.raises(ValueError):
        get_functional_optimizer("bogus")


def test_optimizer_state_jits():
    state = adam(center_init=jnp.zeros(4), center_learning_rate=0.05)

    @jax.jit
    def step(state):
        x = adam_ask(state)
        return adam_tell(state, follow_grad=-x)

    for _ in range(3):
        state = step(state)
    assert state.center.shape == (4,)


def test_oo_optimizers():
    from evotorch_tpu.optimizers import SGD, Adam, ClipUp, get_optimizer_class

    cu = ClipUp(solution_length=3, dtype="float32", stepsize=0.1)
    step1 = cu.ascent(jnp.array([100.0, 0.0, 0.0]))
    assert float(jnp.linalg.norm(step1)) == pytest.approx(0.1, abs=1e-5)

    ad = Adam(solution_length=2, dtype="float32", stepsize=0.01)
    s = ad.ascent(jnp.ones(2))
    assert s.shape == (2,)
    assert float(s[0]) == pytest.approx(0.01, rel=0.01)

    sg = SGD(solution_length=2, dtype="float32", stepsize=0.5)
    assert np.allclose(np.asarray(sg.ascent(jnp.ones(2))), 0.5)

    assert get_optimizer_class("clipup") is ClipUp
    factory = get_optimizer_class("adam", {"stepsize": 0.5})
    inst = factory(solution_length=2, dtype="float32")
    assert inst._stepsize == 0.5
    with pytest.raises(ValueError):
        get_optimizer_class("bogus")


def test_optax_adapter():
    import optax

    from evotorch_tpu.optimizers import OptaxOptimizer

    opt = OptaxOptimizer(optax.sgd(0.5), solution_length=2, dtype="float32")
    step = opt.ascent(jnp.array([1.0, -1.0]))
    assert np.allclose(np.asarray(step), [0.5, -0.5])
