"""Zero-sync telemetry: device counters, span tracer, counter registry.

The contracts under test (docs/observability.md):

- the on-device telemetry vector is produced by the SAME jitted program as
  the scores, is additive (sharded evals psum it), and its figures agree
  with the ground-truth counters for every eval contract;
- the Chrome-trace tracer emits schema-valid, properly-nesting events,
  keeps threads on separate tracks, ring-buffers, and is a shared no-op
  when disabled;
- the registry counts compiles/spans/fetches process-wide and surfaces
  per-step deltas in searcher status dicts.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import (
    FlatParamsPolicy,
    Linear,
    Tanh,
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.observability import (
    EvalTelemetry,
    TELEMETRY_WIDTH,
    counters,
    pack_eval_telemetry,
    tracer,
)

POPSIZE = 8
EPISODE_LENGTH = 16


def _env_policy():
    env = CartPole()
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    return env, FlatParamsPolicy(net)


@pytest.fixture
def fresh_tracer():
    t = tracer.start_tracing()
    yield t
    tracer.stop_tracing(write=False)


# ---------------------------------------------------------------------------
# device telemetry
# ---------------------------------------------------------------------------


def test_pack_decode_roundtrip_and_addition():
    vec = jax.jit(
        lambda: pack_eval_telemetry(
            env_steps=10, episodes=2, capacity=20, lane_width=4,
            refill_events=3, queue_wait=5,
        )
    )()
    assert vec.shape == (TELEMETRY_WIDTH,) and vec.dtype == jnp.int32
    t = EvalTelemetry.from_array(vec)
    assert (t.env_steps, t.episodes, t.capacity, t.lane_width) == (10, 2, 20, 4)
    assert (t.refill_events, t.queue_wait) == (3, 5)
    assert t.occupancy == 0.5
    assert t.mean_item_wait == pytest.approx(5 / 3)
    summed = t + t
    assert summed.env_steps == 20 and summed.capacity == 40
    assert summed.occupancy == 0.5  # additivity preserves the ratio
    with pytest.raises(ValueError):
        EvalTelemetry.from_array(np.zeros(3))


def test_telemetry_figures_per_contract():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    common = dict(num_episodes=1, episode_length=EPISODE_LENGTH)

    budget = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="budget", **common
    )
    t = EvalTelemetry.from_array(budget.telemetry)
    # budget: every executed lane-step is a counted interaction, by definition
    assert t.occupancy == 1.0
    assert t.env_steps == int(budget.total_steps) == POPSIZE * EPISODE_LENGTH
    assert t.lane_width == POPSIZE and t.refill_events == 0

    episodes = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="episodes", **common
    )
    t = EvalTelemetry.from_array(episodes.telemetry)
    assert t.env_steps == int(episodes.total_steps)
    assert t.episodes == int(episodes.total_episodes) == POPSIZE
    # idle masked lanes burn capacity: occupancy is the waste diagnostic
    assert 0.0 < t.occupancy <= 1.0

    refill = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="episodes_refill",
        refill_width=4, **common,
    )
    t = EvalTelemetry.from_array(refill.telemetry)
    assert t.lane_width == 4
    assert t.refill_events == POPSIZE - 4  # every item beyond the seed set
    assert t.env_steps == int(refill.total_steps)

    compact = run_vectorized_rollout_compacting(
        env, policy, params, key, stats, allowed_widths=(4,), **common
    )
    t = EvalTelemetry.from_array(compact.telemetry)
    assert t.env_steps == int(compact.total_steps)
    assert t.episodes == POPSIZE
    # capacity through the width descent never exceeds full-width-forever
    assert t.capacity <= POPSIZE * (EPISODE_LENGTH + 1)


def test_telemetry_off_is_none_and_scores_identical():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    for mode, kw in [
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": 4}),
    ]:
        on = run_vectorized_rollout(
            env, policy, params, key, stats, num_episodes=1,
            episode_length=EPISODE_LENGTH, eval_mode=mode, **kw,
        )
        off = run_vectorized_rollout(
            env, policy, params, key, stats, num_episodes=1,
            episode_length=EPISODE_LENGTH, eval_mode=mode, telemetry=False, **kw,
        )
        assert off.telemetry is None
        assert jnp.array_equal(on.scores, off.scores), mode


def test_sharded_evaluator_psums_telemetry():
    from evotorch_tpu.parallel.evaluate import make_sharded_rollout_evaluator
    from evotorch_tpu.parallel.mesh import default_mesh

    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    mesh = default_mesh(("pop",))
    evaluator = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh, num_episodes=1, episode_length=EPISODE_LENGTH,
        eval_mode="episodes_refill", refill_width=8,
    )
    result, _ = evaluator(params, jax.random.key(1), stats)
    t = EvalTelemetry.from_array(result.telemetry)
    # psum'd across shards: mesh-global figures
    assert t.env_steps == int(result.total_steps)
    assert t.episodes == int(result.total_episodes) == POPSIZE
    assert t.lane_width == 8  # the GLOBAL refill width, summed over shards


def test_refill_queue_wait_counts_gated_idle_lanes():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    # refill_period > 1 forces finished lanes to idle masked while the queue
    # still holds work — exactly what queue_wait meters
    r = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1,
        episode_length=EPISODE_LENGTH, eval_mode="episodes_refill",
        refill_width=2, refill_period=7,
    )
    t = EvalTelemetry.from_array(r.telemetry)
    assert t.refill_events == POPSIZE - 2
    assert t.queue_wait > 0
    assert t.mean_item_wait > 0.0


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_tracer_schema_and_nesting(fresh_tracer):
    with tracer.span("outer", "test", level=1):
        with tracer.span("inner", "test"):
            pass
        tracer.instant("marker", "test")
    events = fresh_tracer.events()
    payload = json.loads(json.dumps(fresh_tracer.to_chrome_trace()))
    assert set(payload.keys()) == {"traceEvents", "displayTimeUnit"}
    by_name = {e["name"]: e for e in events if e.get("ph") != "M"}
    for e in events:
        assert isinstance(e["name"], str)
        assert e["ph"] in ("X", "M", "i")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    outer, inner = by_name["outer"], by_name["inner"]
    # spans NEST: the inner complete event is contained in the outer one
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert outer["args"] == {"level": 1}
    # a thread_name metadata event identifies the track
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)


def test_tracer_threads_get_separate_tracks(fresh_tracer):
    def worker():
        with tracer.span("in_thread", "test"):
            pass

    th = threading.Thread(target=worker, name="test-worker")
    with tracer.span("in_main", "test"):
        pass
    th.start()
    th.join()
    events = fresh_tracer.events()
    main_tid = next(e["tid"] for e in events if e["name"] == "in_main")
    thread_tid = next(e["tid"] for e in events if e["name"] == "in_thread")
    assert main_tid != thread_tid
    names = {
        e["args"]["name"] for e in events if e.get("ph") == "M"
    }
    assert "test-worker" in names


def test_tracer_ring_buffer_bounds_events():
    t = tracer.SpanTracer(capacity=10)
    for i in range(50):
        with t.span(f"s{i}"):
            pass
    events = [e for e in t.events() if e.get("ph") == "X"]
    assert len(events) == 10
    assert events[-1]["name"] == "s49"  # the ring keeps the most recent tail


def test_span_is_shared_noop_when_disabled():
    assert tracer.get_tracer() is None
    before = counters.get("trace_spans")
    s1 = tracer.span("anything", "x", a=1)
    s2 = tracer.span("else")
    assert s1 is s2  # one shared no-op object: no allocation per call
    with s1:
        pass
    tracer.instant("nothing")
    assert counters.get("trace_spans") == before


def test_manual_complete_spans(fresh_tracer):
    t0 = fresh_tracer.now_us()
    fresh_tracer.complete("manual", t0, 123.0, "test", block=2)
    e = [x for x in fresh_tracer.events() if x["name"] == "manual"][0]
    assert e["dur"] == 123.0 and e["args"] == {"block": 2}


# ---------------------------------------------------------------------------
# registry + status surfacing
# ---------------------------------------------------------------------------


def test_registry_increment_snapshot_delta_threadsafe():
    from evotorch_tpu.observability import CounterRegistry

    reg = CounterRegistry()
    snap = reg.snapshot(("a", "b"))

    def bump():
        for _ in range(1000):
            reg.increment("a")

    threads = [threading.Thread(target=bump) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    reg.increment("b", 5)
    assert reg.delta(snap) == {"a": 4000, "b": 5}
    assert reg.get("missing") == 0


def test_searcher_status_carries_registry_deltas_and_eval_telemetry():
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        CartPole(),
        "Linear(obs_length, act_length)",
        episode_length=EPISODE_LENGTH,
        eval_mode="episodes_refill",
        refill_config={"width": 4},
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=POPSIZE,
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
    )
    searcher.step()
    status = dict(searcher.status.items())
    # registry deltas are status keys from the very first step
    assert status["compiles"] >= 1  # warmup generation compiled
    assert "trace_spans" in status and "telemetry_fetches" in status
    searcher.step()
    searcher.step()
    status = dict(searcher.status.items())
    # eval telemetry lags one generation (device-scalar discipline) — by
    # step 3 it reports the refill contract's figures
    assert 0.0 < status["eval_occupancy"] <= 1.0
    assert status["eval_refill_events"] == POPSIZE - 4
    assert status["eval_queue_wait"] >= 0
    # steady state: nothing recompiles once warm
    assert status["compiles"] == 0


def test_host_pipeline_reports_occupancy():
    from evotorch_tpu.neuroevolution.net.hostvecenv import (
        SyncVectorEnv,
        run_host_pipelined_rollout,
    )

    gym = pytest.importorskip("gymnasium")

    class ToyEnv:
        def __init__(self, horizon=6):
            self.h = horizon
            self.t = 0
            self.observation_space = gym.spaces.Box(-1, 1, (3,))
            self.action_space = gym.spaces.Box(-1, 1, (2,))

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(3, np.float32), {}

        def step(self, action):
            self.t += 1
            return np.zeros(3, np.float32), 1.0, self.t >= self.h, False, {}

    policy = FlatParamsPolicy(Linear(3, 2) >> Tanh())
    vec = SyncVectorEnv(lambda: ToyEnv(), 4)
    params = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, policy.parameter_count)),
        jnp.float32,
    )
    result = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=10, mode="sync"
    )
    # equal-length toy episodes + work-conserving refill: every executed
    # lane-step is counted
    assert result["occupancy"] == 1.0
    assert result["interactions"] == 8 * 6
