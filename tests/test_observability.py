"""Zero-sync telemetry: device counters, span tracer, counter registry.

The contracts under test (docs/observability.md):

- the on-device telemetry vector is produced by the SAME jitted program as
  the scores, is additive (sharded evals psum it), and its figures agree
  with the ground-truth counters for every eval contract;
- the Chrome-trace tracer emits schema-valid, properly-nesting events,
  keeps threads on separate tracks, ring-buffers, and is a shared no-op
  when disabled;
- the registry counts compiles/spans/fetches process-wide and surfaces
  per-step deltas in searcher status dicts.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import (
    FlatParamsPolicy,
    Linear,
    Tanh,
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.observability import (
    EvalTelemetry,
    GROUP_TELEMETRY_WIDTH,
    GroupTelemetry,
    MetricsHub,
    QUEUE_WAIT_BUCKETS,
    Rule,
    SLOWatchdog,
    TELEMETRY_SCHEMA_VERSION,
    TELEMETRY_WIDTH,
    counters,
    pack_eval_telemetry,
    tracer,
)

POPSIZE = 8
EPISODE_LENGTH = 16


def _env_policy():
    env = CartPole()
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    return env, FlatParamsPolicy(net)


@pytest.fixture
def fresh_tracer():
    t = tracer.start_tracing()
    yield t
    tracer.stop_tracing(write=False)


# ---------------------------------------------------------------------------
# device telemetry
# ---------------------------------------------------------------------------


def test_pack_decode_roundtrip_and_addition():
    vec = jax.jit(
        lambda: pack_eval_telemetry(
            env_steps=10, episodes=2, capacity=20, lane_width=4,
            refill_events=3, queue_wait=5,
        )
    )()
    assert vec.shape == (TELEMETRY_WIDTH,) and vec.dtype == jnp.int32
    t = EvalTelemetry.from_array(vec)
    assert (t.env_steps, t.episodes, t.capacity, t.lane_width) == (10, 2, 20, 4)
    assert (t.refill_events, t.queue_wait) == (3, 5)
    assert t.occupancy == 0.5
    assert t.mean_item_wait == pytest.approx(5 / 3)
    summed = t + t
    assert summed.env_steps == 20 and summed.capacity == 40
    assert summed.occupancy == 0.5  # additivity preserves the ratio
    with pytest.raises(ValueError):
        EvalTelemetry.from_array(np.zeros(3))


def test_telemetry_figures_per_contract():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    common = dict(num_episodes=1, episode_length=EPISODE_LENGTH)

    budget = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="budget", **common
    )
    t = EvalTelemetry.from_array(budget.telemetry)
    # budget: every executed lane-step is a counted interaction, by definition
    assert t.occupancy == 1.0
    assert t.env_steps == int(budget.total_steps) == POPSIZE * EPISODE_LENGTH
    assert t.lane_width == POPSIZE and t.refill_events == 0

    episodes = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="episodes", **common
    )
    t = EvalTelemetry.from_array(episodes.telemetry)
    assert t.env_steps == int(episodes.total_steps)
    assert t.episodes == int(episodes.total_episodes) == POPSIZE
    # idle masked lanes burn capacity: occupancy is the waste diagnostic
    assert 0.0 < t.occupancy <= 1.0

    refill = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode="episodes_refill",
        refill_width=4, **common,
    )
    t = EvalTelemetry.from_array(refill.telemetry)
    assert t.lane_width == 4
    assert t.refill_events == POPSIZE - 4  # every item beyond the seed set
    assert t.env_steps == int(refill.total_steps)

    compact = run_vectorized_rollout_compacting(
        env, policy, params, key, stats, allowed_widths=(4,), **common
    )
    t = EvalTelemetry.from_array(compact.telemetry)
    assert t.env_steps == int(compact.total_steps)
    assert t.episodes == POPSIZE
    # capacity through the width descent never exceeds full-width-forever
    assert t.capacity <= POPSIZE * (EPISODE_LENGTH + 1)


def test_telemetry_off_is_none_and_scores_identical():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    for mode, kw in [
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": 4}),
    ]:
        on = run_vectorized_rollout(
            env, policy, params, key, stats, num_episodes=1,
            episode_length=EPISODE_LENGTH, eval_mode=mode, **kw,
        )
        off = run_vectorized_rollout(
            env, policy, params, key, stats, num_episodes=1,
            episode_length=EPISODE_LENGTH, eval_mode=mode, telemetry=False, **kw,
        )
        assert off.telemetry is None
        assert jnp.array_equal(on.scores, off.scores), mode


def test_sharded_evaluator_psums_telemetry():
    from evotorch_tpu.parallel.evaluate import make_sharded_rollout_evaluator
    from evotorch_tpu.parallel.mesh import default_mesh

    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    mesh = default_mesh(("pop",))
    evaluator = make_sharded_rollout_evaluator(
        env, policy, mesh=mesh, num_episodes=1, episode_length=EPISODE_LENGTH,
        eval_mode="episodes_refill", refill_width=8,
    )
    result, _ = evaluator(params, jax.random.key(1), stats)
    t = EvalTelemetry.from_array(result.telemetry)
    # psum'd across shards: mesh-global figures
    assert t.env_steps == int(result.total_steps)
    assert t.episodes == int(result.total_episodes) == POPSIZE
    assert t.lane_width == 8  # the GLOBAL refill width, summed over shards


def test_refill_queue_wait_counts_gated_idle_lanes():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    # refill_period > 1 forces finished lanes to idle masked while the queue
    # still holds work — exactly what queue_wait meters
    r = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1,
        episode_length=EPISODE_LENGTH, eval_mode="episodes_refill",
        refill_width=2, refill_period=7,
    )
    t = EvalTelemetry.from_array(r.telemetry)
    assert t.refill_events == POPSIZE - 2
    assert t.queue_wait > 0
    assert t.mean_item_wait > 0.0


# ---------------------------------------------------------------------------
# per-group telemetry (the per-group counter wire; health block in test_health.py)
# ---------------------------------------------------------------------------


def _group_matrix():
    """A synthetic two-group matrix: g0 healthy, g1 starved."""
    data = np.zeros((2, GROUP_TELEMETRY_WIDTH), dtype=np.int64)
    data[0, :TELEMETRY_WIDTH] = [90, 10, 100, 4, 10, 5, 1]
    data[1, :TELEMETRY_WIDTH] = [2, 0, 100, 4, 6, 300, 0]
    data[0, TELEMETRY_WIDTH:] = [8, 1, 1, 0, 0, 0, 0, 0]
    data[1, TELEMETRY_WIDTH:] = [0, 0, 0, 0, 0, 1, 0, 5]
    return data


def test_group_telemetry_decode_total_and_quantiles():
    assert TELEMETRY_SCHEMA_VERSION == 4
    gt = GroupTelemetry.from_array(_group_matrix())
    assert gt.num_groups == 2
    assert gt.hist.shape == (2, QUEUE_WAIT_BUCKETS)
    # total() collapses to the v1 global figures
    total = gt.total()
    assert total.env_steps == 92 and total.capacity == 200
    assert gt.group(0).occupancy == 0.9
    # Prometheus-style upper-edge quantiles off the bucketed histogram
    assert gt.queue_wait_quantile(0.5, group=0) == 0.0  # bucket 0 = waits of 0
    assert gt.queue_wait_quantile(0.99) >= gt.queue_wait_quantile(0.5)
    assert gt.queue_wait_quantile(0.99, group=1) == 64.0  # overflow bucket
    # starvation = the overflow bucket's share of refills
    assert gt.starvation_share(group=0) == 0.0
    assert gt.starvation_share(group=1) == pytest.approx(5 / 6)
    # nonfinite (the quarantine column, schema 3) over finished episodes
    assert gt.nonfinite_share(group=0) == pytest.approx(1 / 10)
    assert gt.nonfinite_share(group=1) == 0.0
    # addition pads the shorter matrix (sub-batch additivity)
    summed = gt + GroupTelemetry.from_array(_group_matrix()[:1])
    assert summed.total().env_steps == 92 + 90
    # the v1 decoder reads the same wire (column sums)
    assert EvalTelemetry.from_array(_group_matrix()).env_steps == 92


def test_v1_wire_golden_decode_still_works():
    # the frozen v1 contract: a (6,) vector decodes field-for-field, and
    # GroupTelemetry lifts it into a single-group matrix with empty buckets
    golden = np.array([160, 8, 160, 8, 4, 12], dtype=np.int32)
    t = EvalTelemetry.from_array(golden)
    assert (t.env_steps, t.episodes, t.capacity, t.lane_width) == (160, 8, 160, 8)
    assert (t.refill_events, t.queue_wait) == (4, 12)
    gt = GroupTelemetry.from_array(golden)
    assert gt.num_groups == 1
    assert gt.hist.sum() == 0
    assert gt.total() == t


@pytest.mark.parametrize(
    "mode,kw",
    [
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": 4}),
    ],
)
def test_group_counters_sum_to_global(mode, kw):
    # the acceptance contract: a two-group split of the same population
    # yields identical scores and per-group counters that column-sum
    # EXACTLY to the G=1 globals, on every contract
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    groups = np.arange(POPSIZE, dtype=np.int32) % 2
    common = dict(num_episodes=1, episode_length=EPISODE_LENGTH)
    base = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode=mode, **common, **kw
    )
    split = run_vectorized_rollout(
        env, policy, params, key, stats, eval_mode=mode,
        groups=groups, num_groups=2, **common, **kw,
    )
    assert jnp.array_equal(base.scores, split.scores)
    t1 = GroupTelemetry.from_array(base.telemetry)
    t2 = GroupTelemetry.from_array(split.telemetry)
    assert t1.num_groups == 1 and t2.num_groups == 2
    assert t1.total() == t2.total()


def test_group_counters_sum_to_global_compacting():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    key = jax.random.key(1)
    groups = np.arange(POPSIZE, dtype=np.int32) % 2
    common = dict(num_episodes=1, episode_length=EPISODE_LENGTH)
    base = run_vectorized_rollout_compacting(
        env, policy, params, key, stats, allowed_widths=(4,), **common
    )
    split = run_vectorized_rollout_compacting(
        env, policy, params, key, stats, allowed_widths=(4,),
        groups=groups, num_groups=2, **common,
    )
    assert jnp.array_equal(base.scores, split.scores)
    assert (
        GroupTelemetry.from_array(base.telemetry).total()
        == GroupTelemetry.from_array(split.telemetry).total()
    )


def test_refill_group_histogram_counts_every_refill():
    env, policy = _env_policy()
    stats = RunningNorm(env.observation_size).stats
    params = jax.random.normal(jax.random.key(0), (POPSIZE, policy.parameter_count))
    groups = np.arange(POPSIZE, dtype=np.int32) % 2
    # refill_period > 1 makes lanes idle before refilling, so waits land in
    # nonzero buckets
    r = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats, num_episodes=1,
        episode_length=EPISODE_LENGTH, eval_mode="episodes_refill",
        refill_width=2, refill_period=7, groups=groups, num_groups=2,
    )
    gt = GroupTelemetry.from_array(r.telemetry)
    # every refill lands in exactly one bucket of its group's histogram
    assert int(gt.hist.sum()) == gt.total().refill_events == POPSIZE - 2
    assert gt.queue_wait_quantile(0.99) >= gt.queue_wait_quantile(0.5)


def test_vecne_solution_groups_status_and_slo():
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        CartPole(),
        "Linear(obs_length, act_length)",
        episode_length=EPISODE_LENGTH,
        eval_mode="episodes_refill",
        refill_config={"width": 4},
        solution_groups=np.arange(POPSIZE, dtype=np.int32) % 2,
        slo=[
            {"kind": "occupancy_floor", "threshold": 0.01},
            {"kind": "min_progress", "threshold": 1},
        ],
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=POPSIZE,
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
    )
    searcher.step()
    searcher.step()
    status = dict(searcher.status.items())
    # per-group status keys appear at G > 1 (lag-by-one, live by step 2)
    assert 0.0 < status["eval_g0_occupancy"] <= 1.0
    assert 0.0 < status["eval_g1_occupancy"] <= 1.0
    assert (
        status["eval_g0_env_steps"] + status["eval_g1_env_steps"]
        == problem.last_group_telemetry.total().env_steps
    )
    # the watchdog ran and passed (both groups make progress)
    assert status["slo_ok"] is True and status["slo_violations"] == 0
    # mismatched mapping fails loudly
    with pytest.raises(ValueError, match="solution_groups maps"):
        problem._check_solution_groups(POPSIZE + 1)


# ---------------------------------------------------------------------------
# SLO watchdog
# ---------------------------------------------------------------------------


def test_slo_watchdog_flags_starved_group():
    gt = GroupTelemetry.from_array(_group_matrix())
    watchdog = SLOWatchdog([
        Rule("occupancy_floor", threshold=0.5),
        Rule("starvation_ceiling", threshold=0.25, group=1),
        Rule("min_progress", threshold=5),
        Rule("no_steady_compiles"),
    ])
    report = watchdog.check(gt, status={"steady_compiles": 0})
    assert not report.ok
    detail = "; ".join(report.violations)
    # the starved group is named in every violated rule
    assert "g1" in detail and "starvation" in detail and "env_steps" in detail
    status = report.as_status()
    assert status["slo_ok"] is False and status["slo_violations"] == 3
    # the healthy group alone passes the same rules
    healthy = SLOWatchdog([
        Rule("occupancy_floor", threshold=0.5, group=0),
        Rule("starvation_ceiling", threshold=0.25, group=0),
    ]).check(gt)
    assert healthy.ok and healthy.as_status()["slo_ok"] is True
    # a steady-state retrace violates regardless of telemetry
    retrace = SLOWatchdog([Rule("no_steady_compiles")]).check(
        None, status={"steady_compiles": 2}
    )
    assert not retrace.ok
    with pytest.raises(ValueError, match="unknown SLO rule kind"):
        Rule("bogus")


def test_slo_bench_line_verdict(tmp_path):
    from evotorch_tpu.observability.slo import _main, check_bench_line

    good = {"occupancy": 0.62, "steady_compiles": 0,
            "modes": {"budget": {"occupancy": 0.9}}}
    assert check_bench_line(good).ok
    bad = {"occupancy": 0.02, "steady_compiles": 1}
    report = check_bench_line(bad)
    assert not report.ok and len(report.violations) == 2
    # the CLI form tpu_window.sh's slo_check step runs: last JSON line of
    # the log, one-word verdict file, exit status as the step verdict
    log = tmp_path / "bench.log"
    log.write_text("noise\n" + json.dumps(good) + "\n" + json.dumps(bad) + "\n")
    verdict = tmp_path / "slo_verdict.txt"
    rc = _main(["--check-bench", str(log), "--verdict-out", str(verdict)])
    assert rc == 1 and verdict.read_text().strip() == "fail"
    log.write_text(json.dumps(good) + "\n")
    rc = _main(["--check-bench", str(log), "--verdict-out", str(verdict)])
    assert rc == 0 and verdict.read_text().strip() == "pass"


# ---------------------------------------------------------------------------
# MetricsHub
# ---------------------------------------------------------------------------


def test_metricshub_jsonl_stream(tmp_path, monkeypatch):
    gt = GroupTelemetry.from_array(_group_matrix())
    path = tmp_path / "metrics.jsonl"
    hub = MetricsHub(str(path), manifest={"mesh": "none", "env": "cartpole"})
    hub.emit({"gen": 1, "mean_eval": 3.5}, telemetry=gt)
    hub.emit({"gen": 2}, telemetry=gt.total())
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    manifest = lines[0]["manifest"]
    assert manifest["schema_version"] == TELEMETRY_SCHEMA_VERSION
    assert manifest["mesh"] == "none" and "created_unix" in manifest
    row = lines[1]
    assert row["row"] == 0 and row["gen"] == 1
    assert row["eval_env_steps"] == 92 and len(row["groups"]) == 2
    assert "counters" in row and "queue_wait_p99" in row
    # an EvalTelemetry lifts to G=1: no per-group block
    assert lines[2]["row"] == 1 and "groups" not in lines[2]
    # the env knob: unset -> no hub; set -> a hub at that path
    monkeypatch.delenv("EVOTORCH_METRICS", raising=False)
    assert MetricsHub.from_env() is None
    monkeypatch.setenv("EVOTORCH_METRICS", str(tmp_path / "envhub.jsonl"))
    assert MetricsHub.from_env().path.endswith("envhub.jsonl")


def test_metricshub_prometheus_rewrite(tmp_path):
    gt = GroupTelemetry.from_array(_group_matrix())
    path = tmp_path / "metrics.prom"
    hub = MetricsHub(str(path))
    hub.emit({"gen": 7, "mean_eval": 1.25}, telemetry=gt)
    text = path.read_text()
    assert 'evotorch_eval_occupancy{group="1"}' in text
    assert "evotorch_gen 7" in text
    # full rewrite, not append: a second emit leaves ONE copy of each series
    # (count SAMPLE lines — the HELP/TYPE headers also name the metric)
    hub.emit({"gen": 8}, telemetry=gt)
    rows = [l for l in path.read_text().splitlines() if l.startswith("evotorch_gen ")]
    assert rows == ["evotorch_gen 8"]


def test_metricshub_prometheus_help_and_type(tmp_path):
    # textfile-collector contract: every exported metric family carries a
    # `# HELP` and a `# TYPE` header, exactly once, BEFORE its samples;
    # registry counters are typed `counter`, everything else `gauge`
    gt = GroupTelemetry.from_array(_group_matrix())
    path = tmp_path / "metrics.prom"
    hub = MetricsHub(str(path))
    hub.emit({"gen": 7, "mean_eval": 1.25}, telemetry=gt)
    lines = path.read_text().splitlines()
    helps, types, samples = {}, {}, {}
    for i, line in enumerate(lines):
        if line.startswith("# HELP "):
            helps[line.split()[2]] = i
        elif line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            types[name] = (i, mtype)
        elif line and not line.startswith("#"):
            name = line.split("{")[0].split()[0]
            samples.setdefault(name, i)
    assert samples, lines
    for name, first in samples.items():
        assert name in helps, f"no HELP for {name}"
        assert name in types, f"no TYPE for {name}"
        assert helps[name] < types[name][0] < first
    assert types["evotorch_gen"][1] == "gauge"
    # the per-group family shares ONE header over its labelled samples
    assert "evotorch_eval_occupancy" in types
    grouped = [l for l in lines if l.startswith("evotorch_eval_occupancy{")]
    assert len(grouped) == 2
    assert sum(l.startswith("# TYPE evotorch_eval_occupancy ") for l in lines) == 1
    # registry counters (when present) are typed counter
    counter_types = {
        mtype for _, mtype in types.values()
    }
    assert counter_types <= {"gauge", "counter"}


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_tracer_schema_and_nesting(fresh_tracer):
    with tracer.span("outer", "test", level=1):
        with tracer.span("inner", "test"):
            pass
        tracer.instant("marker", "test")
    events = fresh_tracer.events()
    payload = json.loads(json.dumps(fresh_tracer.to_chrome_trace()))
    assert set(payload.keys()) == {"traceEvents", "displayTimeUnit"}
    by_name = {e["name"]: e for e in events if e.get("ph") != "M"}
    for e in events:
        assert isinstance(e["name"], str)
        assert e["ph"] in ("X", "M", "i")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    outer, inner = by_name["outer"], by_name["inner"]
    # spans NEST: the inner complete event is contained in the outer one
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert outer["args"] == {"level": 1}
    # a thread_name metadata event identifies the track
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)


def test_tracer_threads_get_separate_tracks(fresh_tracer):
    def worker():
        with tracer.span("in_thread", "test"):
            pass

    th = threading.Thread(target=worker, name="test-worker")
    with tracer.span("in_main", "test"):
        pass
    th.start()
    th.join()
    events = fresh_tracer.events()
    main_tid = next(e["tid"] for e in events if e["name"] == "in_main")
    thread_tid = next(e["tid"] for e in events if e["name"] == "in_thread")
    assert main_tid != thread_tid
    names = {
        e["args"]["name"] for e in events if e.get("ph") == "M"
    }
    assert "test-worker" in names


def test_tracer_ring_buffer_bounds_events():
    t = tracer.SpanTracer(capacity=10)
    for i in range(50):
        with t.span(f"s{i}"):
            pass
    events = [e for e in t.events() if e.get("ph") == "X"]
    assert len(events) == 10
    assert events[-1]["name"] == "s49"  # the ring keeps the most recent tail


def test_span_is_shared_noop_when_disabled():
    assert tracer.get_tracer() is None
    before = counters.get("trace_spans")
    s1 = tracer.span("anything", "x", a=1)
    s2 = tracer.span("else")
    assert s1 is s2  # one shared no-op object: no allocation per call
    with s1:
        pass
    tracer.instant("nothing")
    assert counters.get("trace_spans") == before


def test_manual_complete_spans(fresh_tracer):
    t0 = fresh_tracer.now_us()
    fresh_tracer.complete("manual", t0, 123.0, "test", block=2)
    e = [x for x in fresh_tracer.events() if x["name"] == "manual"][0]
    assert e["dur"] == 123.0 and e["args"] == {"block": 2}


# ---------------------------------------------------------------------------
# registry + status surfacing
# ---------------------------------------------------------------------------


def test_tracer_periodic_flush_keeps_partial_trace(tmp_path):
    # EVOTORCH_TRACE_FLUSH_SECS: a killed run keeps the last flushed window
    # instead of losing the whole trace at the missed atexit hook
    path = str(tmp_path / "trace.json")
    tracer.start_tracing(path, flush_secs=0.01)
    try:
        import time as _time

        with tracer.span("first"):
            pass
        _time.sleep(0.02)
        with tracer.span("second"):  # completion past the interval -> flush
            pass
        data = json.loads(open(path).read())
        names = {e["name"] for e in data["traceEvents"] if e.get("ph") == "X"}
        assert {"first", "second"} <= names
    finally:
        tracer.stop_tracing(write=False)
    # flush stays off without the knob: nothing written before stop
    path2 = str(tmp_path / "trace2.json")
    tracer.start_tracing(path2)
    with tracer.span("quiet"):
        pass
    import os as _os

    assert not _os.path.exists(path2)
    assert tracer.stop_tracing() == path2


def test_registry_increment_snapshot_delta_threadsafe():
    from evotorch_tpu.observability import CounterRegistry

    reg = CounterRegistry()
    snap = reg.snapshot(("a", "b"))

    def bump():
        for _ in range(1000):
            reg.increment("a")

    threads = [threading.Thread(target=bump) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    reg.increment("b", 5)
    assert reg.delta(snap) == {"a": 4000, "b": 5}
    assert reg.get("missing") == 0


def test_searcher_status_carries_registry_deltas_and_eval_telemetry():
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import VecNE

    problem = VecNE(
        CartPole(),
        "Linear(obs_length, act_length)",
        episode_length=EPISODE_LENGTH,
        eval_mode="episodes_refill",
        refill_config={"width": 4},
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=POPSIZE,
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        stdev_init=0.1,
    )
    searcher.step()
    status = dict(searcher.status.items())
    # registry deltas are status keys from the very first step
    assert status["compiles"] >= 1  # warmup generation compiled
    assert "trace_spans" in status and "telemetry_fetches" in status
    searcher.step()
    searcher.step()
    status = dict(searcher.status.items())
    # eval telemetry lags one generation (device-scalar discipline) — by
    # step 3 it reports the refill contract's figures
    assert 0.0 < status["eval_occupancy"] <= 1.0
    assert status["eval_refill_events"] == POPSIZE - 4
    assert status["eval_queue_wait"] >= 0
    # steady state: nothing recompiles once warm
    assert status["compiles"] == 0


def test_host_pipeline_reports_occupancy():
    from evotorch_tpu.neuroevolution.net.hostvecenv import (
        SyncVectorEnv,
        run_host_pipelined_rollout,
    )

    gym = pytest.importorskip("gymnasium")

    class ToyEnv:
        def __init__(self, horizon=6):
            self.h = horizon
            self.t = 0
            self.observation_space = gym.spaces.Box(-1, 1, (3,))
            self.action_space = gym.spaces.Box(-1, 1, (2,))

        def reset(self, seed=None):
            self.t = 0
            return np.zeros(3, np.float32), {}

        def step(self, action):
            self.t += 1
            return np.zeros(3, np.float32), 1.0, self.t >= self.h, False, {}

    policy = FlatParamsPolicy(Linear(3, 2) >> Tanh())
    vec = SyncVectorEnv(lambda: ToyEnv(), 4)
    params = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, policy.parameter_count)),
        jnp.float32,
    )
    result = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=10, mode="sync"
    )
    # equal-length toy episodes + work-conserving refill: every executed
    # lane-step is counted
    assert result["occupancy"] == 1.0
    assert result["interactions"] == 8 * 6
