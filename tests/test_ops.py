import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.ops import fused_centered_rank, sample_symmetric_gaussian
from evotorch_tpu.tools.ranking import centered


def test_xla_sampling_path():
    mu = jnp.array([1.0, -2.0, 0.0])
    sigma = jnp.array([0.5, 1.0, 2.0])
    out = sample_symmetric_gaussian(jax.random.key(0), mu, sigma, 1000)
    assert out.shape == (1000, 3)
    # antithetic pairs interleaved
    assert np.allclose(np.asarray(out[0::2] + out[1::2]), 2 * np.asarray(mu), atol=1e-5)
    assert np.allclose(np.asarray(jnp.mean(out, axis=0)), np.asarray(mu), atol=0.15)


def test_pallas_sampling_interpret_mode():
    mu = jnp.zeros(16)
    sigma = jnp.ones(16)
    out = sample_symmetric_gaussian(
        jax.random.key(1), mu, sigma, 512, use_pallas=True, interpret=True
    )
    assert out.shape == (512, 16)
    vals = np.asarray(out)
    # correct antithetic structure
    assert np.allclose(vals[0::2] + vals[1::2], 0.0, atol=1e-5)
    # statistically gaussian: mean ~0, std ~1
    assert abs(vals.mean()) < 0.05
    assert abs(vals.std() - 1.0) < 0.05


def test_pallas_sampling_rejects_odd():
    with pytest.raises(ValueError):
        sample_symmetric_gaussian(jax.random.key(0), jnp.zeros(3), jnp.ones(3), 7)


def test_fused_centered_rank_matches_library():
    fit = jax.random.normal(jax.random.key(2), (64,))
    expected = np.asarray(centered(fit, higher_is_better=True))
    got = np.asarray(
        fused_centered_rank(fit, higher_is_better=True, use_pallas=True, interpret=True)
    )
    assert np.allclose(got, expected, atol=1e-6)
    # minimization direction
    expected = np.asarray(centered(fit, higher_is_better=False))
    got = np.asarray(
        fused_centered_rank(fit, higher_is_better=False, use_pallas=True, interpret=True)
    )
    assert np.allclose(got, expected, atol=1e-6)


def test_fused_centered_rank_with_ties():
    fit = jnp.array([1.0, 1.0, 2.0, 0.0])
    got = np.asarray(fused_centered_rank(fit, use_pallas=True, interpret=True))
    expected = np.asarray(centered(fit, higher_is_better=True))
    assert np.allclose(sorted(got), sorted(expected))
    assert got.sum() == pytest.approx(0.0, abs=1e-6)


def test_box_muller_math():
    # validate the in-kernel Box-Muller transform statistically (pure jnp)
    from evotorch_tpu.ops.sampling import _box_muller

    key = jax.random.key(3)
    bits_a = jax.random.bits(key, (200, 128), dtype=jnp.uint32)
    bits_b = jax.random.bits(jax.random.key(4), (200, 128), dtype=jnp.uint32)
    eps = np.asarray(_box_muller(bits_a, bits_b))
    assert abs(eps.mean()) < 0.02
    assert abs(eps.std() - 1.0) < 0.02


def test_fused_centered_rank_batched_pallas():
    fit = jax.random.normal(jax.random.key(5), (3, 32))
    got = np.asarray(fused_centered_rank(fit, use_pallas=True, interpret=True))
    expected = np.asarray(centered(fit, higher_is_better=True))
    assert got.shape == (3, 32)
    assert np.allclose(got, expected, atol=1e-6)


def test_pallas_sampling_on_tpu():
    # exercises the REAL on-chip-PRNG kernel; only runs on TPU hardware
    if jax.default_backend() not in ("tpu",):
        pytest.skip("real pallas kernel requires TPU hardware")
    mu = jnp.zeros(128)
    sigma = jnp.ones(128)
    out = sample_symmetric_gaussian(jax.random.key(0), mu, sigma, 256, use_pallas=True)
    vals = np.asarray(out)
    assert np.allclose(vals[0::2] + vals[1::2], 0.0, atol=1e-5)
    assert abs(vals.mean()) < 0.05
    assert abs(vals.std() - 1.0) < 0.05


def test_fused_centered_rank_degenerate_and_dtype():
    # review regression: n == 1 must match the XLA fallback (no NaN)
    out = fused_centered_rank(jnp.array([5.0]), use_pallas=True, interpret=True)
    assert float(out[0]) == 0.0
    f32 = fused_centered_rank(
        jnp.arange(4, dtype=jnp.float32), use_pallas=True, interpret=True
    )
    assert f32.dtype == jnp.float32
