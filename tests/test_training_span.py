"""Fused multi-generation training spans (docs/sharding.md): K generations
scanned into ONE donated GSPMD program (``parallel.make_training_span``).

The load-bearing claim is that the span is an EXECUTION DETAIL, exactly like
the mesh: the scanned body is the same ``make_generation_step`` trace, so a
span-K call is bit-identical — search state, scores, telemetry, obs-norm
stats — to K sequential generation-step calls at any mesh shape, including
padded indivisible popsizes. These tests pin that contract on the pytest
8-virtual-device CPU mesh, plus the donation/retrace properties that make
the fused program safe to put on the hot path.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from evotorch_tpu.algorithms.functional import (
    make_search_span,
    pgpe,
    pgpe_ask,
    pgpe_health,
    pgpe_tell,
)
from evotorch_tpu.envs import CartPole
from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
from evotorch_tpu.parallel import (
    make_generation_step,
    make_mesh,
    make_training_span,
)

SPAN = 3
# even (symmetric PGPE) but NOT divisible by the 8-device grid: every span
# test also exercises the pad-and-mask path
POPSIZE = 12

# explicit refill knobs so the two legs cannot diverge through the
# tuned-config cache (override provenance on both sides)
_MODE_KWARGS = {
    "budget": {},
    "episodes": {},
    "episodes_refill": {"refill_width": 4, "refill_period": 1},
}


@pytest.fixture(scope="module")
def cartpole_setup():
    env = CartPole()
    policy = FlatParamsPolicy(
        Linear(env.observation_size, 4) >> Tanh() >> Linear(4, env.action_size)
    )
    stats = RunningNorm(env.observation_size).stats
    return env, policy, stats


def _fresh_state(policy):
    return pgpe(
        center_init=jnp.zeros(policy.parameter_count),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )


def _ask(popsize):
    def ask(k, s):
        return pgpe_ask(k, s, popsize=popsize)

    return ask


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# bit-identity: span K == K sequential generation steps
# ---------------------------------------------------------------------------


def _run_both(env, policy, stats0, *, mesh_shape, eval_mode, popsize=POPSIZE):
    kwargs = dict(
        num_episodes=1, episode_length=4, eval_mode=eval_mode,
        **_MODE_KWARGS[eval_mode],
    )
    mesh = make_mesh(mesh_shape)
    gen = make_generation_step(
        env, policy, ask=_ask(popsize), tell=pgpe_tell, popsize=popsize,
        mesh=mesh, donate_state=False, **kwargs,
    )
    span_fn = make_training_span(
        env, policy, ask=_ask(popsize), tell=pgpe_tell, popsize=popsize,
        span=SPAN, mesh=mesh, donate_state=False,
        state_metrics=pgpe_health, **kwargs,
    )
    keys = jax.random.split(jax.random.key(42), SPAN)

    st, stats = _fresh_state(policy), stats0
    seq_scores, seq_steps, seq_telem = [], [], []
    for i in range(SPAN):
        st, scores, stats, steps, telem = gen(st, keys[i], stats)
        seq_scores.append(np.asarray(scores))
        seq_steps.append(int(steps))
        seq_telem.append(np.asarray(telem))
    seq = (st, np.stack(seq_scores), stats, np.asarray(seq_steps),
           np.stack(seq_telem))
    fused = span_fn(_fresh_state(policy), keys, stats0)
    return seq, fused


# budget pins the contract in the fast tier; the episodes/refill variants
# and the 2-D mesh recheck compile the same body again (~16s of pure
# compile on this box), so they ride the slow tier with the other
# sharded-topology sweeps
@pytest.mark.parametrize(
    "eval_mode",
    [
        "budget",
        pytest.param("episodes", marks=pytest.mark.slow),
        pytest.param("episodes_refill", marks=pytest.mark.slow),
    ],
)
def test_span_bit_identity_padded_popsize(cartpole_setup, eval_mode):
    env, policy, stats0 = cartpole_setup
    seq, fused = _run_both(
        env, policy, stats0, mesh_shape={"pop": 8}, eval_mode=eval_mode
    )
    st, scores, stats, steps, telem = seq
    st2, scores2, stats2, steps2, telem2, metrics2 = fused
    assert scores2.shape == (SPAN, POPSIZE)
    np.testing.assert_array_equal(scores, np.asarray(scores2))
    np.testing.assert_array_equal(steps, np.asarray(steps2))
    np.testing.assert_array_equal(telem, np.asarray(telem2))
    _assert_trees_equal(st, st2)  # the search state itself, every leaf
    _assert_trees_equal(stats, stats2)  # obs-norm sufficient statistics
    # state_metrics stacks one row per generation
    assert np.asarray(metrics2["stdev_norm"]).shape == (SPAN,)


@pytest.mark.slow
def test_span_bit_identity_2d_mesh(cartpole_setup):
    env, policy, stats0 = cartpole_setup
    seq, fused = _run_both(
        env, policy, stats0,
        mesh_shape={"pop": 4, "model": 2}, eval_mode="budget",
    )
    st, scores, stats, steps, telem = seq
    st2, scores2, stats2, steps2, telem2, _ = fused
    np.testing.assert_array_equal(scores, np.asarray(scores2))
    np.testing.assert_array_equal(steps, np.asarray(steps2))
    np.testing.assert_array_equal(telem, np.asarray(telem2))
    _assert_trees_equal(st, st2)
    _assert_trees_equal(stats, stats2)


# ---------------------------------------------------------------------------
# contract validation
# ---------------------------------------------------------------------------


def test_span_rejects_compact_and_bad_span(cartpole_setup):
    env, policy, _ = cartpole_setup
    with pytest.raises(ValueError, match="episodes_compact"):
        make_training_span(
            env, policy, ask=_ask(8), tell=pgpe_tell, popsize=8, span=2,
            eval_mode="episodes_compact",
        )
    with pytest.raises(ValueError, match="span"):
        make_training_span(
            env, policy, ask=_ask(8), tell=pgpe_tell, popsize=8, span=0,
        )


# ---------------------------------------------------------------------------
# donation + retrace discipline
# ---------------------------------------------------------------------------


def test_span_donates_and_stays_compile_free(cartpole_setup):
    from evotorch_tpu.analysis import track_compiles
    from evotorch_tpu.observability import ledger
    from evotorch_tpu.observability.programs import abstract_like

    env, policy, stats = cartpole_setup
    span_fn = make_training_span(
        env, policy, ask=_ask(8), tell=pgpe_tell, popsize=8, span=SPAN,
        mesh=make_mesh({"pop": 8}),
        num_episodes=1, episode_length=4, eval_mode="budget",
    )

    def call(state, seed):
        return span_fn(state, jax.random.split(jax.random.key(seed), SPAN), stats)

    donated = _fresh_state(policy)
    state, scores, _, steps, _ = call(donated, 0)
    assert scores.shape == (SPAN, 8)
    assert np.asarray(steps).tolist() == [8 * 4] * SPAN
    # runtime ground truth: jax deletes exactly the donated inputs whose
    # aliasing the executable consumed
    assert donated.stdev.is_deleted()

    # with donation the second call commits the steady-state layout; after
    # it, further spans must run with ZERO fresh compiles (the retrace
    # sentinel — the property the whole fusion exists to buy)
    state, *_ = call(state, 1)
    with track_compiles() as compile_log:
        for seed in (2, 3):
            state, scores, _, _, _ = call(state, seed)
        jax.block_until_ready(scores)
    assert compile_log.count == 0

    # the ledger's AOT donation verification agrees: every donated
    # parameter is aliased in the compiled module
    record = ledger.capture(
        "test.training_span",
        span_fn,
        abstract_like(state),
        jax.random.split(jax.random.key(9), SPAN),
        abstract_like(stats),
        shape={"popsize": 8, "span": SPAN, "mesh": "pop8"},
    )
    assert record.donation is not None
    assert record.donation.missing == ()


# ---------------------------------------------------------------------------
# the functional-searcher span: one scanned-generations idiom
# ---------------------------------------------------------------------------


def test_make_search_span_matches_sequential():
    from functools import partial

    def fitness(pop):
        return -jnp.sum(pop**2, axis=-1)

    ask = partial(pgpe_ask, popsize=8)
    state0 = pgpe(
        center_init=jnp.zeros(5),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )
    keys = jax.random.split(jax.random.key(5), 4)

    # the hand-rolled scan the helper replaces (satellite: ONE
    # scanned-generations idiom) — the SAME trace, so bit-identical
    def generation(state, key):
        pop = ask(key, state)
        evals = fitness(pop)
        return pgpe_tell(state, pop, evals), evals

    st, seq_evals = jax.jit(
        lambda s, k: jax.lax.scan(generation, s, k)
    )(state0, keys)

    span_fn = make_search_span(
        fitness, ask=ask, tell=pgpe_tell, donate_state=False
    )
    st2, ys = span_fn(state0, keys)
    np.testing.assert_array_equal(np.asarray(seq_evals), np.asarray(ys))
    _assert_trees_equal(st, st2)

    # eager per-generation calls agree numerically (XLA may reassociate
    # float reductions differently across the per-call jit boundaries, so
    # this anchor is allclose, not bit-equality)
    st3 = state0
    for i in range(4):
        pop = ask(keys[i], st3)
        st3 = pgpe_tell(st3, pop, fitness(pop))
    for a, b in zip(
        jax.tree_util.tree_leaves(st2), jax.tree_util.tree_leaves(st3)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


# ---------------------------------------------------------------------------
# VecNE wiring: stacked telemetry feeds the lag-by-span decode
# ---------------------------------------------------------------------------


def test_vecne_consume_span_counters_and_lag(cartpole_setup):
    import evotorch_tpu  # noqa: F401  (shard_map alias)
    from evotorch_tpu.neuroevolution import VecNE

    prob = VecNE(
        "cartpole",
        "Linear(obs_length, 4) >> Tanh() >> Linear(4, act_length)",
        eval_mode="episodes_refill",
        refill_config={"width": 4, "period": 1},
        observation_normalization=True,
        num_episodes=1,
        episode_length=4,
    )
    state = _fresh_state(prob._policy)
    span_fn = prob.make_training_span(
        ask=_ask(POPSIZE), tell=pgpe_tell, popsize=POPSIZE, span=SPAN,
        donate_state=False,
    )
    result = span_fn(state, jax.random.split(jax.random.key(7), SPAN),
                     prob.obs_norm.stats)
    scores = prob.consume_span(result)
    assert scores.shape == (SPAN, POPSIZE)
    # every generation ran to episode end: exact counters, no estimate
    assert int(prob.status["total_episode_count"]) == SPAN * POPSIZE
    assert int(prob.status["total_interaction_count"]) == int(
        np.asarray(result[3]).sum()
    )
    # lag-by-span: rows 0..K-2 decoded into status, the final row pending
    assert prob._pending_telemetry is not None
    assert "eval_occupancy" in prob.status
    assert "eval_score_mean" in prob.status

    # the compact contract cannot fuse — the method says so up front
    prob2 = VecNE(
        "cartpole",
        "Linear(obs_length, 4) >> Tanh() >> Linear(4, act_length)",
        eval_mode="episodes_compact",
    )
    with pytest.raises(ValueError, match="episodes_compact"):
        prob2.make_training_span(
            ask=_ask(8), tell=pgpe_tell, popsize=8, span=2
        )


# ---------------------------------------------------------------------------
# checkpoint cadence: --checkpoint-every rounds UP to a span boundary
# ---------------------------------------------------------------------------


def _load_locomotion_curve():
    path = (
        pathlib.Path(__file__).resolve().parent.parent
        / "examples"
        / "locomotion_curve.py"
    )
    spec = importlib.util.spec_from_file_location("locomotion_curve", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_span_checkpoint_every_rounds_up():
    mod = _load_locomotion_curve()
    f = mod.span_checkpoint_every
    assert f(25, 8) == 32  # not a multiple: round UP to the next boundary
    assert f(32, 8) == 32  # already aligned: unchanged
    assert f(1, 8) == 8  # never below one span
    assert f(10, 1) == 10  # span 1 is the host-loop cadence
