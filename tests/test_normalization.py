import jax
import jax.numpy as jnp
import numpy as np

from evotorch_tpu.neuroevolution.net.runningnorm import (
    RunningNorm,
    RunningStat,
    stats_merge,
    stats_normalize,
    stats_update,
)


def test_running_norm_matches_numpy():
    rn = RunningNorm(3)
    data = np.random.randn(100, 3) * 2.0 + 5.0
    rn.update(jnp.asarray(data))
    assert np.allclose(np.asarray(rn.mean), data.mean(axis=0), atol=1e-4)
    assert np.allclose(np.asarray(rn.stdev), data.std(axis=0, ddof=1), atol=1e-3)
    normalized = np.asarray(rn.normalize(jnp.asarray(data)))
    assert abs(normalized.mean()) < 0.01
    assert abs(normalized.std() - 1.0) < 0.05


def test_running_norm_masked_update():
    rn = RunningNorm(2)
    obs = jnp.array([[1.0, 1.0], [100.0, 100.0], [3.0, 3.0]])
    mask = jnp.array([True, False, True])
    rn.update(obs, mask)
    assert rn.count == 2
    assert np.allclose(np.asarray(rn.mean), [2.0, 2.0])


def test_merge_equals_combined():
    # merging two partial stats equals stats over the full data —
    # the property that makes psum a valid distributed merge
    data = np.random.randn(60, 4)
    a = RunningNorm(4)
    b = RunningNorm(4)
    full = RunningNorm(4)
    a.update(jnp.asarray(data[:25]))
    b.update(jnp.asarray(data[25:]))
    full.update(jnp.asarray(data))
    a.update(b)
    assert np.allclose(np.asarray(a.mean), np.asarray(full.mean), atol=1e-5)
    assert np.allclose(np.asarray(a.stdev), np.asarray(full.stdev), atol=1e-5)


def test_running_stat_equivalence():
    # RunningStat (host) and RunningNorm (device) agree — the reference's
    # test_normalization.py checks the same equivalence
    data = np.random.randn(50, 3)
    rs = RunningStat()
    rn = RunningNorm(3)
    rs.update(data)
    rn.update(jnp.asarray(data))
    assert np.allclose(rs.mean, np.asarray(rn.mean), atol=1e-4)
    assert np.allclose(rs.stdev, np.asarray(rn.stdev), atol=1e-4)
    # cross-merge: RunningNorm absorbs a RunningStat (the actor-delta path)
    rn2 = RunningNorm(3)
    rn2.update(rs)
    assert np.allclose(np.asarray(rn2.mean), rs.mean, atol=1e-4)


def test_running_stat_delta():
    rs = RunningStat()
    rs.update(np.ones((10, 2)))
    snapshot = RunningStat()
    snapshot.update(rs)
    rs.update(np.zeros((10, 2)))
    delta = rs.to_delta(snapshot)
    assert delta.count == 10
    assert np.allclose(delta.mean, 0.0)


def test_normalize_identity_before_enough_data():
    rn = RunningNorm(2)
    x = jnp.array([5.0, -3.0])
    assert np.allclose(np.asarray(rn.normalize(x)), np.asarray(x))


def test_stats_update_inside_jit():
    rn = RunningNorm(2)

    @jax.jit
    def roll(stats, xs):
        def step(stats, x):
            return stats_update(stats, x[None, :]), None

        return jax.lax.scan(step, stats, xs)[0]

    stats = roll(rn.stats, jnp.asarray(np.random.randn(20, 2)))
    assert float(stats.count) == 20


def test_to_layer():
    rn = RunningNorm(2)
    rn.update(jnp.asarray(np.random.randn(30, 2) * 3 + 1))
    layer = rn.to_layer()
    y, _ = layer.apply((), jnp.asarray([1.0, 1.0]))
    assert y.shape == (2,)
