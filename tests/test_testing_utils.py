import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import testing as T


def test_assert_allclose():
    T.assert_allclose(jnp.ones(3), np.ones(3), atol=1e-8)
    with pytest.raises(T.TestingError):
        T.assert_allclose(jnp.ones(3), jnp.zeros(3), atol=0.5)
    with pytest.raises(ValueError):
        T.assert_allclose(jnp.ones(3), jnp.ones(3))


def test_assert_almost_between():
    T.assert_almost_between(jnp.array([0.1, 0.9]), 0.0, 1.0)
    T.assert_almost_between(jnp.array([-0.05]), 0.0, 1.0, atol=0.1)
    with pytest.raises(T.TestingError):
        T.assert_almost_between(jnp.array([2.0]), 0.0, 1.0)


def test_assert_dtype_matches():
    T.assert_dtype_matches(jnp.ones(2), "float32")
    T.assert_dtype_matches(jnp.ones(2), "float")
    T.assert_dtype_matches(jnp.arange(3), "int")
    with pytest.raises(T.TestingError):
        T.assert_dtype_matches(jnp.ones(2), "int")


def test_assert_shape_matches():
    T.assert_shape_matches(jnp.zeros((3, 4)), (3, 4))
    T.assert_shape_matches(jnp.zeros((3, 4)), (3, "*"))
    T.assert_shape_matches(jnp.zeros(5), 5)
    with pytest.raises(T.TestingError):
        T.assert_shape_matches(jnp.zeros((3, 4)), (4, 3))
    with pytest.raises(T.TestingError):
        T.assert_shape_matches(jnp.zeros((3, 4)), (3,))


def test_assert_eachclose_and_batch_support():
    from evotorch_tpu import Problem, vectorized

    T.assert_eachclose(jnp.full((4,), 2.0), 2.0, atol=1e-8)
    with pytest.raises(T.TestingError):
        T.assert_eachclose(jnp.array([1.0, 2.0]), 1.0, atol=0.1)

    @vectorized
    def sphere(xs):
        return jnp.sum(xs**2, axis=-1)

    p = Problem("min", sphere, solution_length=3, initial_bounds=(-1, 1))
    batch = p.generate_batch(4)
    T.assert_shape_matches(batch, (4, 3))
    T.assert_dtype_matches(batch, "float32")


def test_assert_eachclose_integer_truncation():
    # review regression: integer arrays must not pass against fractional targets
    with pytest.raises(T.TestingError):
        T.assert_eachclose(jnp.array([2, 2]), 2.9, atol=0.1)
