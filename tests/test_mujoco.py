"""Real-MuJoCo evaluation backend (``envs/mujoco/``).

Three layers of grounding, all against the *installed* mujoco + gymnasium
(never fakes): the batched ``MjVecEnv`` engine reproduces single-env
gymnasium stepping (observations, rewards, terminations); ``GymNE`` over a
real ``-v5`` env runs through both the vectorized lane path and the
``num_actors`` host pool with obs-norm delta sync; and the env-fidelity
harness emits a structurally complete report. The native-env reward-term
decomposition test at the bottom is pure JAX (fast tier).

Horizon note: MuJoCo locomotion dynamics are chaotic — any driver that does
not carry ``qacc_warmstart`` bit-exactly diverges from gymnasium's stepping
at the Lyapunov rate from an initial ~1e-12 (solver-tolerance) difference.
Per-step transitions are identical to ~1e-12; trajectory-level assertions
therefore use a short horizon for the non-terminating chaotic env
(HalfCheetah) and full episodes for the stiff/terminating ones (measured:
Hopper/Walker2d/InvertedPendulum/Swimmer track to float32 precision over
entire episodes).
"""

import numpy as np
import pytest

mujoco_mark = [pytest.mark.slow, pytest.mark.mujoco]


def _make_pair(env_id, n):
    import gymnasium as gym

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv

    venv = MjVecEnv(lambda: gym.make(env_id), n)
    refs = [gym.make(env_id) for _ in range(n)]
    venv.seed(range(100, 100 + n))
    obs_v = venv.reset()
    obs_r = []
    for i, e in enumerate(refs):
        e.reset(seed=100 + i)  # prime the lane RNG exactly like venv.seed
        o, _ = e.reset()
        obs_r.append(o)
    return venv, refs, obs_v, np.stack(obs_r)


@pytest.mark.parametrize(
    "env_id,horizon,atol",
    [
        ("Hopper-v5", 300, 1e-5),
        ("Walker2d-v5", 300, 1e-5),
        ("InvertedPendulum-v5", 300, 1e-5),
        ("Swimmer-v5", 60, 1e-5),
        # chaotic + non-terminating: per-step fidelity is ~1e-12 but
        # trajectories diverge at the Lyapunov rate (module docstring)
        ("HalfCheetah-v5", 40, 1e-3),
    ],
)
@pytest.mark.slow
@pytest.mark.mujoco
def test_mjvecenv_matches_single_env_gymnasium_stepping(env_id, horizon, atol):
    n = 3
    venv, refs, obs_v, obs_r = _make_pair(env_id, n)
    try:
        np.testing.assert_allclose(obs_v, obs_r, atol=1e-6)
        rng = np.random.default_rng(7)
        done_r = np.zeros(n, dtype=bool)
        for t in range(horizon):
            act = rng.uniform(-1, 1, (n,) + refs[0].action_space.shape)
            obs_v, rew_v, done_v = venv.step(act, active=~done_r)
            for i, e in enumerate(refs):
                if done_r[i]:
                    continue
                o, r, term, trunc, _ = e.step(act[i])
                assert bool(term or trunc) == bool(done_v[i]), (env_id, t, i)
                assert abs(r - rew_v[i]) < atol, (env_id, t, i, r, rew_v[i])
                if term or trunc:
                    done_r[i] = True
                else:
                    np.testing.assert_allclose(o, obs_v[i], atol=atol, err_msg=f"{env_id} t={t} lane={i}")
            if done_r.all():
                break
    finally:
        venv.close()
        for e in refs:
            e.close()


@pytest.mark.slow
@pytest.mark.mujoco
def test_mjvecenv_reward_terms_decompose_the_reward():
    import gymnasium as gym

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv

    venv = MjVecEnv(lambda: gym.make("Hopper-v5"), 4)
    try:
        venv.seed(range(4))
        venv.reset()
        rng = np.random.default_rng(0)
        for _ in range(20):
            _, rewards, dones = venv.step(rng.uniform(-1, 1, (4, 3)))
            terms = venv.last_terms
            assert {"x_velocity", "reward_forward", "reward_ctrl", "reward_survive"} <= set(terms)
            total = terms["reward_forward"] + terms["reward_ctrl"] + terms["reward_survive"]
            np.testing.assert_allclose(total, rewards, atol=1e-5)
    finally:
        venv.close()


@pytest.mark.slow
@pytest.mark.mujoco
def test_mjvecenv_inactive_lanes_and_autoreset():
    import gymnasium as gym

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv

    venv = MjVecEnv(lambda: gym.make("InvertedPendulum-v5"), 3)
    try:
        venv.seed(range(3))
        venv.reset()
        active = np.array([True, False, True])
        obs, rewards, dones = venv.step(np.ones((3, 1)), active=active)
        assert np.isnan(obs[1]).all() and rewards[1] == 0.0 and not dones[1]
        assert np.isfinite(obs[0]).all() and np.isfinite(obs[2]).all()
        # drive lane 0 to termination; its returned obs must be a fresh reset
        for _ in range(200):
            obs, _, dones = venv.step(np.ones((3, 1)), active=active)
            if dones[0]:
                break
        assert dones[0]
        assert np.isfinite(obs[0]).all()  # eager auto-reset observation
        assert venv._steps[0] == 0
    finally:
        venv.close()


@pytest.mark.slow
@pytest.mark.mujoco
def test_gymne_vectorized_lane_block_uses_mjvecenv():
    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv
    from evotorch_tpu.neuroevolution import GymNE

    p = GymNE(
        "InvertedPendulum-v5",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        num_envs=6,
        episode_length=60,
    )
    batch = p.generate_batch(8)  # 6-lane blocks: exercises the short chunk too
    p.evaluate(batch)
    assert isinstance(p._make_vector_env(), MjVecEnv)
    evals = np.asarray(batch.evals[:, 0])
    assert np.isfinite(evals).all() and (evals >= 1).all()
    assert p.status["total_interaction_count"] > 0
    assert p.get_observation_stats().count > 0
    # generic envs must keep falling back to the lockstep SyncVectorEnv
    from evotorch_tpu.neuroevolution.net.hostvecenv import SyncVectorEnv

    q = GymNE("CartPole-v1", "Linear(obs_length, act_length)", num_envs=2, episode_length=20)
    q.generate_batch(2)
    assert isinstance(q._make_vector_env(), SyncVectorEnv)


@pytest.mark.slow
@pytest.mark.mujoco
def test_gymne_hopper_host_pool_two_generations_with_obs_norm_sync():
    """The issue's acceptance workload: GymNE("Hopper-v5") for >= 2
    generations through the ``num_actors`` host pool, with observation
    normalization delta-synced against the real env each round."""
    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import GymNE

    p = GymNE(
        "Hopper-v5",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        episode_length=80,
        num_actors=2,
    )
    try:
        searcher = PGPE(
            p,
            popsize=6,
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            radius_init=0.3,
            optimizer="clipup",
            ranking_method="centered",
        )
        searcher.step()
        count_gen1 = p.get_observation_stats().count
        interactions_gen1 = p.status["total_interaction_count"]
        assert count_gen1 > 0  # worker deltas merged home
        assert interactions_gen1 > 0
        assert p.status["total_episode_count"] >= 6
        pool = p._host_pool
        assert pool is not None and pool.is_alive()
        import os

        assert all(pid != os.getpid() for pid in pool.worker_pids)

        searcher.step()  # second generation: deltas stay cumulative
        assert p.get_observation_stats().count > count_gen1
        assert p.status["total_interaction_count"] > interactions_gen1
        assert np.isfinite(float(searcher.status["mean_eval"]))
    finally:
        p.kill_actors()


@pytest.mark.slow
@pytest.mark.mujoco
def test_fidelity_harness_smoke_invertedpendulum():
    from evotorch_tpu.envs.mujoco.fidelity import (
        format_fidelity_markdown,
        run_fidelity,
    )

    report = run_fidelity(["cartpole"], n_seqs=3, n_steps=40, seed=0)
    pair = report["pairs"]["cartpole"]
    assert pair["mujoco_env"] == "InvertedPendulum-v5"
    total = pair["terms"]["reward_total"]
    assert np.isfinite(total["native_mean"]) and np.isfinite(total["mujoco_mean"])
    assert pair["episode"]["mujoco_mean_length"] > 0
    md = format_fidelity_markdown(report)
    assert "InvertedPendulum-v5" in md and "reward_total" in md
    import json

    json.dumps(report)  # the report must be JSON-serializable as checked in


def _hopper_policy_and_params(popsize, *, scale=3.0, straggler_zero=True):
    import gymnasium as gym

    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear

    env = gym.make("Hopper-v5")
    obs_dim = env.observation_space.shape[0]
    act_dim = env.action_space.shape[0]
    env.close()
    policy = FlatParamsPolicy(Linear(obs_dim, act_dim))
    rng = np.random.default_rng(0)
    params = np.asarray(
        rng.normal(size=(popsize, policy.parameter_count)) * scale, np.float32
    )
    if straggler_zero:
        # the zero policy survives far longer than aggressive random ones —
        # a deterministic straggler among fast-dying episodes
        params[0, :] = 0.0
    return policy, params


def _seeded_hopper_vec(n):
    import gymnasium as gym

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv

    vec = MjVecEnv(lambda: gym.make("Hopper-v5"), n)
    vec.seed(range(200, 200 + n))
    return vec


@pytest.mark.slow
@pytest.mark.mujoco
def test_hopper_pipelined_matches_sync_bit_identical():
    """The host pipeline on real physics: the worker-thread overlap must not
    change a bit of the scores, step counts or obs-norm statistics relative
    to the sync fallback (identical event order by construction)."""
    import jax.numpy as jnp

    from evotorch_tpu.neuroevolution.net.hostvecenv import run_host_pipelined_rollout
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningStat

    policy, params = _hopper_policy_and_params(8)
    out = {}
    for mode in ("pipelined", "sync"):
        vec = _seeded_hopper_vec(4)
        stats = RunningStat()
        result = run_host_pipelined_rollout(
            vec,
            policy,
            jnp.asarray(params),
            num_episodes=1,
            episode_length=100,
            obs_stats=stats,
            mode=mode,
        )
        vec.close()
        out[mode] = (result, stats)
    r_pipe, s_pipe = out["pipelined"]
    r_sync, s_sync = out["sync"]
    assert np.array_equal(r_pipe["scores"], r_sync["scores"])
    assert np.array_equal(r_pipe["episode_steps"], r_sync["episode_steps"])
    assert r_pipe["interactions"] == r_sync["interactions"]
    assert s_pipe.count == s_sync.count
    assert np.array_equal(np.asarray(s_pipe.sum), np.asarray(s_sync.sum))
    assert np.array_equal(
        np.asarray(s_pipe.sum_of_squares), np.asarray(s_sync.sum_of_squares)
    )


@pytest.mark.slow
@pytest.mark.mujoco
def test_hopper_pipelined_matches_chunked_reference():
    """At matched width (one chunk, one episode, no obs-norm) the pipelined
    scheduler reproduces the PR-2 synchronous path's Hopper scores exactly:
    per-lane trajectories are scheduling-independent, so any difference would
    be a scheduler bug."""
    import jax.numpy as jnp

    from evotorch_tpu.neuroevolution.net.hostvecenv import (
        run_host_pipelined_rollout,
        run_host_vectorized_rollout,
    )

    policy, params = _hopper_policy_and_params(4, straggler_zero=False)
    vec = _seeded_hopper_vec(4)
    reference = run_host_vectorized_rollout(
        vec, policy, jnp.asarray(params), num_episodes=1, episode_length=100
    )
    vec.close()
    vec = _seeded_hopper_vec(4)
    pipelined = run_host_pipelined_rollout(
        vec, policy, jnp.asarray(params), num_episodes=1, episode_length=100, mode="pipelined"
    )
    vec.close()
    assert np.array_equal(reference["scores"], pipelined["scores"])
    assert reference["interactions"] == pipelined["interactions"]


@pytest.mark.slow
@pytest.mark.mujoco
def test_hopper_refill_straggler_no_longer_serializes_the_block():
    """Work conservation on real physics: one long-lived episode among
    fast-dying ones. The chunked path pays sum-over-chunks-of-max lockstep
    iterations; the refill scheduler stalls only the straggler's lane while
    freed lanes drain the rest of the queue."""
    import jax.numpy as jnp

    from evotorch_tpu.neuroevolution.net.hostvecenv import run_host_pipelined_rollout

    policy, params = _hopper_policy_and_params(8)
    vec = _seeded_hopper_vec(4)
    result = run_host_pipelined_rollout(
        vec, policy, jnp.asarray(params), num_episodes=1, episode_length=100, mode="pipelined"
    )
    vec.close()
    lengths = result["episode_steps"][:, 0]
    assert (lengths > 0).all()
    # the zero-policy straggler outlives the aggressive random policies
    assert lengths[0] > np.median(lengths[1:])
    # what the serial fixed-chunk loop would have paid: each num_envs-sized
    # chunk padded to its slowest episode
    serialized = sum(int(lengths[s : s + 4].max()) for s in range(0, 8, 4))
    assert max(result["block_iters"]) < serialized
    # refilled-lane accounting: freed lanes served multiple items from the
    # whole-batch queue (that is what kept the block from serializing)
    assert result["lane_episodes"].sum() == 8
    assert result["lane_episodes"].max() >= 2
    assert result["interactions"] == int(lengths.sum())


@pytest.mark.slow
@pytest.mark.mujoco
def test_mjvecenv_nthread_knob(monkeypatch):
    import gymnasium as gym

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv
    from evotorch_tpu.neuroevolution import GymNE

    # env-var knob
    monkeypatch.setenv("EVOTORCH_MJ_NTHREAD", "2")
    vec = MjVecEnv(lambda: gym.make("InvertedPendulum-v5"), 3)
    assert vec.nthread == 2
    vec.close()
    # explicit argument wins over the env var
    vec = MjVecEnv(lambda: gym.make("InvertedPendulum-v5"), 3, nthread=1)
    assert vec.nthread == 1
    vec.close()
    # GymNE constructor passthrough
    p = GymNE(
        "InvertedPendulum-v5",
        "Linear(obs_length, act_length)",
        num_envs=2,
        episode_length=10,
        mj_nthread=1,
    )
    assert p._make_vector_env().nthread == 1


def test_native_reward_terms_sum_to_batch_step_reward():
    """Fast tier, pure JAX: the per-term decomposition added for the
    fidelity harness must exactly re-compose each env's step reward."""
    import jax
    import jax.numpy as jnp

    from evotorch_tpu.envs import make_env

    for name in ("halfcheetah", "walker2d"):
        env = make_env(name)
        keys = jax.random.split(jax.random.key(0), 3)
        state, _ = env.batch_reset(keys)
        actions = jax.random.uniform(jax.random.key(1), (3, env.sys.num_act), minval=-1, maxval=1)
        state, _, reward, _ = env.batch_step(state, actions)
        terms = env.batch_reward_terms(state.obs_state, jnp.clip(actions, -1, 1).T)
        total = terms["reward_forward"] + terms["reward_ctrl"] + terms["reward_survive"]
        np.testing.assert_allclose(np.asarray(total), np.asarray(reward), atol=1e-5, err_msg=name)
