"""The Sebulba-style host evaluation pipeline (``run_host_pipelined_rollout``).

Three invariants, each load-bearing for the real-MuJoCo backend:

- **pipelined == sync, bit-identical**: the worker-thread overlap must not
  change a single bit of scores, per-episode step counts, or obs-norm
  statistics — all bookkeeping lives on the main thread in a fixed event
  order, and these tests are the proof that the order survives the thread.
- **pipelined == the PR-2 chunked reference** at matched width (one chunk,
  one episode per solution, no obs-norm): the new scheduler is a superset,
  not a reinterpretation, of the synchronous path's semantics.
- **work conservation**: a straggler episode stalls one lane, not its whole
  block — freed lanes immediately serve the next pending (solution, episode)
  item, mirroring the on-device ``episodes_refill`` contract.

All fast-tier tests run on the generic ``SyncVectorEnv`` (no mujoco marker);
the real-MuJoCo pipeline tests live in ``tests/test_mujoco.py``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from evotorch_tpu.neuroevolution.net import RNN, FlatParamsPolicy, Linear
from evotorch_tpu.neuroevolution.net.hostvecenv import (
    SyncVectorEnv,
    run_host_pipelined_rollout,
    run_host_vectorized_rollout,
)
from evotorch_tpu.neuroevolution.net.runningnorm import RunningStat


# ---------------------------------------------------------------------------
# a deterministic gym-API env with policy-controlled episode length
# ---------------------------------------------------------------------------


class _ProgrammableLengthEnv:
    """obs = [1.0]; the FIRST action of an episode programs its length:
    ``L = clip(round(10 * a0), 1, 60)``. Purely deterministic, so straggler
    scenarios (one long episode among short ones) can be constructed exactly
    from the policy parameters."""

    class _Box:
        low = np.asarray([-10.0])
        high = np.asarray([10.0])
        shape = (1,)

    observation_space = _Box()
    action_space = _Box()

    def __init__(self):
        self._t = 0
        self._length = 1

    def reset(self, seed=None):
        self._t = 0
        self._length = 1
        return np.asarray([1.0], dtype=np.float32), {}

    def step(self, action):
        if self._t == 0:
            self._length = int(np.clip(round(10.0 * float(np.asarray(action).reshape(-1)[0])), 1, 60))
        self._t += 1
        done = self._t >= self._length
        return np.asarray([1.0], dtype=np.float32), 1.0, done, False, {}

    def close(self):
        pass


def _cartpole_vec(n):
    gym = pytest.importorskip("gymnasium")
    vec = SyncVectorEnv(lambda: gym.make("CartPole-v1"), n)
    vec.seed(range(100, 100 + n))
    return vec


def _params(policy, popsize, seed=0, scale=0.5):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.normal(size=(popsize, policy.parameter_count)) * scale, jnp.float32
    )


# ---------------------------------------------------------------------------
# bit-identical determinism: pipelined vs the sync fallback
# ---------------------------------------------------------------------------


def _run_both_modes(popsize, num_envs, num_episodes, episode_length, *, noise=None):
    policy = FlatParamsPolicy(Linear(4, 2))
    params = _params(policy, popsize)
    out = {}
    for mode in ("pipelined", "sync"):
        vec = _cartpole_vec(num_envs)
        stats = RunningStat()
        result = run_host_pipelined_rollout(
            vec,
            policy,
            params,
            num_episodes=num_episodes,
            episode_length=episode_length,
            obs_stats=stats,
            action_noise_stdev=noise,
            rng=np.random.default_rng(7),
            mode=mode,
        )
        vec.close()
        out[mode] = (result, stats)
    return out


def _assert_bit_identical(out):
    r_pipe, s_pipe = out["pipelined"]
    r_sync, s_sync = out["sync"]
    # scores, step counts and interaction accounting: exact, not allclose
    assert np.array_equal(r_pipe["scores"], r_sync["scores"])
    assert np.array_equal(r_pipe["episode_steps"], r_sync["episode_steps"])
    assert np.array_equal(r_pipe["lane_episodes"], r_sync["lane_episodes"])
    assert r_pipe["interactions"] == r_sync["interactions"]
    assert r_pipe["episodes"] == r_sync["episodes"]
    # final obs-norm statistics: same count, same sums to the last bit (the
    # accumulation order is part of the scheduler's contract)
    assert s_pipe.count == s_sync.count
    assert np.array_equal(np.asarray(s_pipe.sum), np.asarray(s_sync.sum))
    assert np.array_equal(
        np.asarray(s_pipe.sum_of_squares), np.asarray(s_sync.sum_of_squares)
    )


def test_pipelined_matches_sync_bit_identical_tiny():
    # popsize > lanes exercises refill; obs-norm on; discrete actions
    _assert_bit_identical(_run_both_modes(6, 4, 2, 30))


@pytest.mark.slow
def test_pipelined_matches_sync_bit_identical_larger_shape():
    _assert_bit_identical(_run_both_modes(24, 10, 3, 60))


def test_pipelined_matches_sync_stateful_policy():
    # recurrent policy: per-lane state pytrees ride the blocks, get zeroed on
    # refill (reset_tensors), and must not break bit-identity either
    policy = FlatParamsPolicy(RNN(1, 4) >> Linear(4, 1))
    params = _params(policy, 5, seed=2, scale=2.0)
    out = {}
    for mode in ("pipelined", "sync"):
        vec = SyncVectorEnv(_ProgrammableLengthEnv, 3)
        result = run_host_pipelined_rollout(
            vec, policy, params, num_episodes=1, episode_length=40, mode=mode
        )
        vec.close()
        out[mode] = result
    assert np.array_equal(out["pipelined"]["scores"], out["sync"]["scores"])
    assert np.array_equal(
        out["pipelined"]["episode_steps"], out["sync"]["episode_steps"]
    )
    assert (out["pipelined"]["episode_steps"] > 0).all()


def test_pipelined_matches_sync_with_action_noise():
    # the continuous-action path: noise draws come from the caller's rng in
    # the scheduler's fixed S2 order, so they too must be bit-identical
    policy = FlatParamsPolicy(Linear(1, 1))
    params = _params(policy, 5, scale=0.2)
    out = {}
    for mode in ("pipelined", "sync"):
        vec = SyncVectorEnv(_ProgrammableLengthEnv, 3)
        result = run_host_pipelined_rollout(
            vec,
            policy,
            params,
            num_episodes=1,
            episode_length=50,
            action_noise_stdev=0.05,
            rng=np.random.default_rng(3),
            mode=mode,
        )
        vec.close()
        out[mode] = (result, None)
    assert np.array_equal(out["pipelined"][0]["scores"], out["sync"][0]["scores"])
    assert np.array_equal(
        out["pipelined"][0]["episode_steps"], out["sync"][0]["episode_steps"]
    )


# ---------------------------------------------------------------------------
# equivalence with the PR-2 synchronous reference path
# ---------------------------------------------------------------------------


def test_pipelined_matches_chunked_reference_at_matched_width():
    # one chunk (popsize == num_envs), one episode, no obs-norm: each lane's
    # trajectory is independent of scheduling, so the pipelined scheduler must
    # reproduce the synchronous loop's scores exactly
    policy = FlatParamsPolicy(Linear(4, 2))
    params = _params(policy, 4)

    vec = _cartpole_vec(4)
    reference = run_host_vectorized_rollout(
        vec, policy, params, num_episodes=1, episode_length=40
    )
    vec.close()

    vec = _cartpole_vec(4)
    pipelined = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=40, mode="pipelined"
    )
    vec.close()

    assert np.array_equal(reference["scores"], pipelined["scores"])
    assert reference["interactions"] == pipelined["interactions"]
    assert reference["episodes"] == pipelined["episodes"]


# ---------------------------------------------------------------------------
# work conservation: the straggler no longer serializes its block
# ---------------------------------------------------------------------------


def test_refill_straggler_accounting():
    # 8 items on 4 lanes; solution 0 programs a 50-step episode, the other 7
    # program 3-step episodes. The chunked reference pays
    # max(chunk1) + max(chunk2) lockstep iterations; the refill scheduler
    # stalls only the straggler's lane while the freed lanes drain the queue.
    policy = FlatParamsPolicy(Linear(1, 1))
    # Linear(1,1) on obs=[1.0]: action = w + b; pick (w, b) directly
    params = np.full((8, policy.parameter_count), 0.15, dtype=np.float32)
    params[:, 1] = 0.15  # a = 0.3 -> length 3
    params[0, :] = 2.5  # a = 5.0 -> length 50 (the straggler)
    params = jnp.asarray(params)

    vec = SyncVectorEnv(_ProgrammableLengthEnv, 4)
    result = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=60, mode="pipelined"
    )
    vec.close()

    lengths = result["episode_steps"][:, 0]
    assert lengths[0] == 50 and (lengths[1:] == 3).all()
    # the chunked path's cost: each chunk padded to its slowest episode
    serialized = sum(
        int(lengths[start : start + 4].max()) for start in range(0, 8, 4)
    )
    assert serialized == 53
    # work conservation: no block ran anywhere near the serialized schedule,
    # and the straggler's lane kept the others from idling (they served the
    # whole rest of the queue)
    assert max(result["block_iters"]) == 50  # the straggler's own length
    assert max(result["block_iters"]) < serialized
    assert result["lane_episodes"].sum() == 8
    assert result["lane_episodes"].max() >= 3  # a freed lane served >= 3 items
    assert result["interactions"] == int(lengths.sum())


def test_pipelined_single_lane_and_empty_batch_edges():
    policy = FlatParamsPolicy(Linear(1, 1))
    params = jnp.asarray(np.full((3, policy.parameter_count), 0.15, dtype=np.float32))
    # one lane: the pipeline degenerates to the sync schedule but must still
    # drain all items through refill
    vec = SyncVectorEnv(_ProgrammableLengthEnv, 1)
    result = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=2, episode_length=10, mode="pipelined"
    )
    vec.close()
    assert result["episodes"] == 6
    assert result["lane_episodes"][0] == 6
    assert (result["episode_steps"] > 0).all()
    # empty batch
    vec = SyncVectorEnv(_ProgrammableLengthEnv, 1)
    empty = run_host_pipelined_rollout(
        vec, policy, jnp.zeros((0, policy.parameter_count)), mode="sync"
    )
    vec.close()
    assert empty["episodes"] == 0 and empty["scores"].shape == (0,)


def test_pipelined_rejects_unknown_mode():
    policy = FlatParamsPolicy(Linear(1, 1))
    vec = SyncVectorEnv(_ProgrammableLengthEnv, 1)
    with pytest.raises(ValueError, match="mode"):
        run_host_pipelined_rollout(
            vec, policy, jnp.zeros((1, policy.parameter_count)), mode="async"
        )
    vec.close()


# ---------------------------------------------------------------------------
# GymNE integration: whole-batch submission + the host_pipeline knob
# ---------------------------------------------------------------------------


def test_gymne_host_pipeline_knob_and_counters():
    pytest.importorskip("gymnasium")
    from evotorch_tpu.neuroevolution import GymNE

    with pytest.raises(ValueError, match="host_pipeline"):
        GymNE("CartPole-v1", "Linear(obs_length, act_length)", host_pipeline="turbo")

    for hp in ("pipelined", "sync", "chunked"):
        p = GymNE(
            "CartPole-v1",
            "Linear(obs_length, act_length)",
            num_envs=3,
            episode_length=25,
            observation_normalization=True,
            seed=0,
            host_pipeline=hp,
        )
        batch = p.generate_batch(5)  # > num_envs: refill (or a short chunk)
        p.evaluate(batch)
        scores = np.asarray(batch.evals[:, 0])
        assert scores.shape == (5,)
        assert (scores >= 1.0).all() and (scores <= 25.0).all()
        assert int(p.status["total_episode_count"]) == 5
        assert p.get_observation_stats().count > 0
