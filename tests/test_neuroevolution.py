import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu import pass_info
from evotorch_tpu.algorithms import PGPE, SNES
from evotorch_tpu.neuroevolution import GymNE, NEProblem, SupervisedNE, VecGymNE, VecNE
from evotorch_tpu.neuroevolution.net import Linear, Tanh


# ---------------------------------------------------------------- NEProblem --


def test_neproblem_solution_length_and_eval():
    def eval_func(policy, flat_params):
        # fitness: negative L2 norm of network output on a fixed input
        y, _ = policy(flat_params, jnp.ones(4))
        return -jnp.sum(y**2)

    p = NEProblem("max", "Linear(4, 2)", eval_func)
    assert p.solution_length == 4 * 2 + 2
    batch = p.generate_batch(6)
    p.evaluate(batch)
    assert batch.is_evaluated


def test_neproblem_network_forms():
    # Module instance
    p1 = NEProblem("max", Linear(3, 1), lambda pol, f: jnp.zeros(()))
    assert p1.solution_length == 4

    # plain callable
    p2 = NEProblem("max", lambda: Linear(3, 1) >> Tanh(), lambda pol, f: jnp.zeros(()))
    assert p2.solution_length == 4

    # @pass_info callable receives constants (none for plain NEProblem)
    @pass_info
    def factory(**kwargs):
        return Linear(2, 1)

    p3 = NEProblem("max", factory, lambda pol, f: jnp.zeros(()))
    assert p3.solution_length == 3


def test_neproblem_parameterize_net():
    p = NEProblem("max", "Linear(2, 2, bias=False)", lambda pol, f: jnp.zeros(()))
    apply = p.parameterize_net(jnp.asarray([1.0, 0.0, 0.0, 1.0]))
    y, _ = apply(jnp.asarray([3.0, 7.0]))
    assert np.allclose(np.asarray(y), [3.0, 7.0])


# -------------------------------------------------------------- SupervisedNE --


def test_supervised_ne_learns_linear_map():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 3)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5]], dtype=np.float32)
    y = X @ w_true

    problem = SupervisedNE(
        (X, y),
        "Linear(3, 1)",
        minibatch_size=64,
        seed=1,
    )
    searcher = SNES(problem, stdev_init=0.3, popsize=30)
    searcher.run(40)
    assert searcher.status["best_eval"] < 0.5

    # evals are losses on a shared minibatch
    batch = problem.generate_batch(4)
    problem.evaluate(batch)
    assert batch.evals.shape == (4, 1)


# --------------------------------------------------------------------- VecNE --


def test_vecne_cartpole_evaluation():
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        seed=0,
    )
    assert problem.solution_length == 4 * 2 + 2
    batch = problem.generate_batch(8)
    problem.evaluate(batch)
    scores = np.asarray(batch.evals[:, 0])
    assert scores.shape == (8,)
    assert (scores >= 1.0).all() and (scores <= 500.0).all()
    status = problem.status
    assert status["total_interaction_count"] > 0
    assert status["total_episode_count"] == 8


def test_vecne_pgpe_improves_cartpole():
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        seed=2,
    )
    searcher = PGPE(
        problem,
        popsize=32,
        center_learning_rate=0.4,
        stdev_learning_rate=0.1,
        stdev_init=0.5,
    )
    searcher.step()
    first = searcher.status["mean_eval"]
    searcher.run(12)
    assert searcher.status["mean_eval"] > first


def test_vecne_observation_normalization_and_episode_budget():
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
        observation_normalization=True,
        episode_length=30,
        num_episodes=2,
        seed=1,
    )
    batch = problem.generate_batch(4)
    problem.evaluate(batch)
    assert problem.obs_norm.count > 0
    assert problem.status["total_episode_count"] == 8
    assert problem.status["total_interaction_count"] == 4 * 30 * 2


def test_vecne_max_num_envs_subbatching():
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        episode_length=10,
        max_num_envs=3,
        seed=1,
    )
    batch = problem.generate_batch(8)
    problem.evaluate(batch)
    assert batch.is_evaluated


def test_vecne_to_policy_and_save(tmp_path):
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        episode_length=20,
        seed=3,
    )
    batch = problem.generate_batch(4)
    problem.evaluate(batch)
    best = batch[int(np.asarray(batch.argbest()))]

    apply = problem.to_policy_callable(best)
    act, _ = apply(jnp.zeros(3))
    assert act.shape == (1,)
    assert -2.0 <= float(act[0]) <= 2.0

    module = problem.to_policy(best)
    fname = os.path.join(tmp_path, "sol.pkl")
    problem.save_solution(best, fname)
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    assert payload["values"].shape == (problem.solution_length,)
    assert payload["obs_mean"] is not None


def test_vecne_sharded_evaluation():
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        episode_length=15,
        seed=4,
    )
    batch = problem.generate_batch(16)
    problem.evaluate_sharded(batch)
    assert batch.is_evaluated
    # stats merged across shards: 16 envs x 15 steps
    assert problem.obs_norm.count == 16 * 15
    assert problem.status["total_interaction_count"] == 240


def test_vecgymne_alias():
    assert VecGymNE is VecNE


# --------------------------------------------------------------------- GymNE --


def test_gymne_cartpole():
    gym = pytest.importorskip("gymnasium")
    problem = GymNE(
        "CartPole-v1",
        "Linear(obs_length, act_length)",
        num_episodes=1,
        episode_length=60,
        seed=0,
    )
    assert problem.solution_length == 4 * 2 + 2
    batch = problem.generate_batch(3)
    problem.evaluate(batch)
    scores = np.asarray(batch.evals[:, 0])
    assert (scores >= 1.0).all()
    assert problem.status["total_episode_count"] == 3

    # deterministic re-run of a solution
    score = problem.run_solution(batch[0], num_episodes=1)
    assert score >= 1.0

    # to_policy produces a module
    module = problem.to_policy(batch[0])
    params = module.init(jax.random.key(0))
    y, _ = module.apply(params, jnp.zeros(4))
    assert y.shape == (2,)


def test_gymne_observation_normalization(tmp_path):
    pytest.importorskip("gymnasium")
    problem = GymNE(
        "CartPole-v1",
        "Linear(obs_length, act_length)",
        observation_normalization=True,
        episode_length=30,
        seed=0,
    )
    batch = problem.generate_batch(2)
    problem.evaluate(batch)
    assert problem.get_observation_stats().count > 0
    fname = os.path.join(tmp_path, "gym_sol.pkl")
    problem.save_solution(batch[0], fname)
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    assert payload["obs_mean"] is not None


def test_to_policy_carries_evolved_weights():
    # review regression: the exported policy must reproduce the solution's
    # behavior, not a random reinitialization
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        episode_length=10,
        seed=7,
    )
    batch = problem.generate_batch(3)
    problem.evaluate(batch)
    sln = batch[0]
    module = problem.to_policy(sln)
    params = module.init(jax.random.key(99))  # arbitrary key: weights are frozen
    obs = jnp.asarray([0.3, -0.2, 0.5])
    y_module, _ = module.apply(params, obs)
    y_callable, _ = problem.to_policy_callable(sln)(obs)
    assert np.allclose(np.asarray(y_module), np.asarray(y_callable), atol=1e-6)


def test_vecne_num_actors_uses_sharded_path():
    # review regression: num_actors must not be silently ignored by VecNE
    problem = VecNE(
        "pendulum",
        "Linear(obs_length, act_length)",
        episode_length=10,
        num_actors="max",
        seed=9,
    )
    batch = problem.generate_batch(16)
    problem.evaluate(batch)
    assert batch.is_evaluated
    assert problem.status["total_interaction_count"] == 160
    # popsize not divisible by any shard count > 1 falls back to local
    batch2 = problem.generate_batch(7)
    problem.evaluate(batch2)
    assert batch2.is_evaluated


def test_vecne_discrete_env_sharded():
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        seed=6,
    )
    batch = problem.generate_batch(16)
    problem.evaluate_sharded(batch)
    scores = np.asarray(batch.evals[:, 0])
    assert (scores >= 1.0).all() and (scores <= 500.0).all()


def test_supervised_ne_multiple_minibatches():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(128, 2)).astype(np.float32)
    y = (X @ np.array([[2.0], [-1.0]], dtype=np.float32))
    problem = SupervisedNE((X, y), "Linear(2, 1)", minibatch_size=16, num_minibatches=4, seed=0)
    batch = problem.generate_batch(5)
    problem.evaluate(batch)
    assert batch.is_evaluated
    assert (np.asarray(batch.evals[:, 0]) >= 0).all()  # averaged MSE losses


def test_pickling_logger_exports_vecne_policy(tmp_path):
    import pickle

    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.logging import PicklingLogger

    problem = VecNE("pendulum", "Linear(obs_length, act_length)", episode_length=10, seed=0)
    searcher = PGPE(
        problem, popsize=8, center_learning_rate=0.3, stdev_learning_rate=0.1, stdev_init=0.3
    )
    logger = PicklingLogger(searcher, interval=1, directory=str(tmp_path), verbose=False)
    searcher.run(2)
    with open(logger.last_file_name, "rb") as f:
        payload = pickle.load(f)
    assert "policy" in payload  # to_policy export of the center
    module = payload["policy"]
    y, _ = module.apply(module.init(jax.random.key(0)), jnp.zeros(3))
    assert y.shape == (1,)


# ---------------------- in-process vectorized host-gym evaluation (SyncVectorEnv)


def test_sync_vector_env_lockstep_and_autoreset():
    pytest.importorskip("gymnasium")
    from evotorch_tpu.neuroevolution.net.hostvecenv import SyncVectorEnv

    def factory():
        import gymnasium as gym

        return gym.make("CartPole-v1")

    vec = SyncVectorEnv(factory, 3)
    obs = vec.reset()
    assert obs.shape == (3, 4)
    # drive with a constant action until some env terminates and auto-resets
    saw_done = False
    for _ in range(200):
        obs, rewards, dones = vec.step(np.zeros(3, dtype=np.int64))
        assert obs.shape == (3, 4) and not np.isnan(obs).any()
        if dones.any():
            saw_done = True
            break
    assert saw_done
    # inactive lanes are skipped and return NaN dummies
    obs, rewards, dones = vec.step(
        np.zeros(3, dtype=np.int64), active=np.asarray([True, False, True])
    )
    assert np.isnan(obs[1]).all() and rewards[1] == 0.0
    vec.close()


def test_gymne_vectorized_host_evaluation():
    pytest.importorskip("gymnasium")
    problem = GymNE(
        "CartPole-v1",
        "Linear(obs_length, act_length)",
        num_episodes=1,
        num_envs=4,
        episode_length=50,
        observation_normalization=True,
        seed=0,
    )
    batch = problem.generate_batch(6)  # 4 lanes -> two chunks (4 + 2)
    problem.evaluate(batch)
    scores = np.asarray(batch.evals[:, 0])
    assert scores.shape == (6,)
    assert (scores >= 1.0).all() and (scores <= 50.0).all()
    assert int(problem.status["total_episode_count"]) == 6
    assert int(problem.status["total_interaction_count"]) >= 6
    assert problem.get_observation_stats().count > 0


def test_gymne_vectorized_matches_serial_regime():
    pytest.importorskip("gymnasium")
    kwargs = dict(
        num_episodes=2,
        episode_length=40,
        seed=3,
    )
    serial = GymNE("CartPole-v1", "Linear(obs_length, act_length)", **kwargs)
    vectorized_p = GymNE(
        "CartPole-v1", "Linear(obs_length, act_length)", num_envs=5, **kwargs
    )
    batch_s = serial.generate_batch(5)
    batch_v = vectorized_p.generate_batch(5)
    # same seed -> same decision values
    np.testing.assert_allclose(
        np.asarray(batch_s.values), np.asarray(batch_v.values)
    )
    serial.evaluate(batch_s)
    vectorized_p.evaluate(batch_v)
    s = np.asarray(batch_s.evals[:, 0])
    v = np.asarray(batch_v.evals[:, 0])
    # env stochasticity differs, but both are valid per-episode means in the
    # same regime for the same policies
    assert (v >= 1.0).all() and (v <= 40.0).all()
    assert (s >= 1.0).all() and (s <= 40.0).all()


def test_vecne_episodes_compact_eval_mode():
    # the lane-compacting evaluator behind the OO problem (verify r3: the
    # dispatch path itself must be exercised, not only the runner)
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        eval_mode="episodes_compact",
        observation_normalization=True,
        episode_length=100,
        seed=0,
    )
    batch = problem.generate_batch(16)
    problem.evaluate(batch)
    scores = np.asarray(batch.evals[:, 0])
    assert np.isfinite(scores).all()
    assert (scores >= 1.0).all() and (scores <= 100.0).all()
    assert problem.status["total_episode_count"] == 16
    assert problem.obs_norm.count > 0

    # same contract as plain episodes mode: a fresh identical problem in
    # monolithic episodes mode must agree on the scores
    problem2 = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        eval_mode="episodes",
        observation_normalization=True,
        episode_length=100,
        seed=0,
    )
    batch2 = problem2.generate_batch(16)
    problem2.evaluate(batch2)
    np.testing.assert_allclose(
        np.asarray(batch2.evals[:, 0]), scores, rtol=1e-5, atol=1e-5
    )


def test_vecne_compact_config_knobs():
    # compaction tuning knobs change scheduling, never scores (num_episodes=1)
    import numpy as np

    from evotorch_tpu.core import SolutionBatch
    from evotorch_tpu.neuroevolution import VecNE

    def make(cfg=None, **kw):
        return VecNE(
            "cartpole",
            "Linear(obs_length, 8) >> Tanh() >> Linear(8, act_length)",
            env_config={"continuous_actions": True},
            episode_length=40,
            eval_mode="episodes_compact",
            compact_config=cfg,
            seed=3,
            **kw,
        )

    rng = np.random.default_rng(11)
    p_default = make()
    values = jnp.asarray(rng.normal(size=(16, p_default.solution_length)) * 0.3, jnp.float32)
    p_tuned = make({"chunk_size": 7, "allowed_widths": (2, 4), "prewarm": True})
    b1 = SolutionBatch(p_default, values=values)
    b2 = SolutionBatch(p_tuned, values=values)
    p_default.evaluate(b1)
    p_tuned.evaluate(b2)
    np.testing.assert_allclose(
        np.asarray(b1.evals_of(0)), np.asarray(b2.evals_of(0)), atol=1e-5
    )

    # the SHARDED path translates the same (global-width) config per shard:
    # same scores as the unsharded default-config evaluation of a sharded
    # problem, and the kwargs must actually reach the sharded runner
    p_sharded = make(
        {"chunk_size": 7, "allowed_widths": (4, 8), "prewarm": True}, num_actors=2
    )
    b3 = SolutionBatch(p_sharded, values=values)
    p_sharded.evaluate(b3)  # resolves num_actors -> 2-shard mesh
    p_sharded_default = make(num_actors=2)
    b4 = SolutionBatch(p_sharded_default, values=values)
    p_sharded_default.evaluate(b4)
    np.testing.assert_allclose(
        np.asarray(b3.evals_of(0)), np.asarray(b4.evals_of(0)), atol=1e-5
    )

    with pytest.raises(ValueError, match="compact_config"):
        make({"chunk": 5})
