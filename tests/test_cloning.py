import pickle

import jax.numpy as jnp
import numpy as np

from evotorch_tpu.tools import Clonable, Serializable, deep_clone


def test_deep_clone_numpy_copies():
    x = np.array([1.0, 2.0])
    y = deep_clone(x)
    y[0] = 99.0
    assert x[0] == 1.0


def test_deep_clone_jax_identity():
    x = jnp.array([1.0])
    assert deep_clone(x) is x


def test_deep_clone_containers_with_memo():
    inner = [1, 2]
    x = {"a": inner, "b": inner}
    y = deep_clone(x)
    assert y["a"] is y["b"]
    assert y["a"] is not inner


class Thing(Serializable):
    def __init__(self):
        self.data = np.zeros(3)
        self.name = "thing"


def test_clonable_and_serializable():
    t = Thing()
    c = t.clone()
    c.data[0] = 5.0
    assert t.data[0] == 0.0
    assert c.name == "thing"

    p = pickle.loads(pickle.dumps(t))
    assert isinstance(p, Thing)
    assert p.name == "thing"
    assert np.allclose(p.data, t.data)


def test_recursive_clonable():
    class Node(Clonable):
        def __init__(self):
            self.other = None

    a = Node()
    b = Node()
    a.other = b
    b.other = a
    a2 = a.clone()
    assert a2.other.other is a2
