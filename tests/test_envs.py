import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import Acrobot, CartPole, MountainCarContinuous, Pendulum, Swimmer2D, make_env


@pytest.mark.parametrize(
    "env_ctor",
    [CartPole, Pendulum, Acrobot, MountainCarContinuous, Swimmer2D],
)
def test_env_protocol(env_ctor):
    env = env_ctor()
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == env.observation_space.shape
    if env.action_space.is_discrete:
        action = jnp.zeros((), dtype=jnp.int32)
    else:
        action = jnp.zeros(env.action_space.shape)
    state, obs, reward, done = env.step(state, action)
    assert obs.shape == env.observation_space.shape
    assert reward.shape == ()
    assert done.shape == ()


@pytest.mark.parametrize("env_ctor", [CartPole, Pendulum])
def test_env_vmapped_and_jitted(env_ctor):
    env = env_ctor()
    n = 6
    keys = jax.random.split(jax.random.key(0), n)
    states, obs = jax.vmap(env.reset)(keys)
    assert obs.shape == (n,) + env.observation_space.shape
    if env.action_space.is_discrete:
        actions = jnp.zeros(n, dtype=jnp.int32)
    else:
        actions = jnp.zeros((n,) + env.action_space.shape)

    @jax.jit
    def multi_step(states, actions):
        return jax.vmap(env.step)(states, actions)

    states, obs, rewards, dones = multi_step(states, actions)
    assert rewards.shape == (n,)


def test_cartpole_terminates_on_pole_fall():
    env = CartPole()
    state, obs = env.reset(jax.random.key(1))
    done = jnp.zeros((), bool)
    # always push right: the pole falls within the episode
    for _ in range(200):
        state, obs, reward, done = env.step(state, jnp.ones((), dtype=jnp.int32))
        if bool(done):
            break
    assert bool(done)


def test_pendulum_reward_negative_cost():
    env = Pendulum()
    state, obs = env.reset(jax.random.key(0))
    _, _, reward, _ = env.step(state, jnp.zeros(1))
    assert float(reward) <= 0.0


def test_registry():
    env = make_env("cartpole")
    assert isinstance(env, CartPole)
    assert isinstance(make_env("CartPole-v1"), CartPole)
    assert isinstance(make_env("pendulum"), Pendulum)
    with pytest.raises(ValueError):
        make_env("nonexistent_env")
    with pytest.raises(ImportError):
        make_env("brax::humanoid")


def test_env_determinism():
    env = Pendulum()
    s1, o1 = env.reset(jax.random.key(5))
    s2, o2 = env.reset(jax.random.key(5))
    assert np.allclose(np.asarray(o1), np.asarray(o2))


def test_hopper_physics_and_learning_signal():
    from evotorch_tpu.envs import Hopper

    env = Hopper()
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (7,)
    # passive drop: touches down (stance flag rises) and does not explode
    stances = 0
    for _ in range(100):
        state, obs, reward, done = env.step(state, jnp.zeros(2))
        stances += int(state.obs_state[6])
        assert np.isfinite(float(reward))
    assert stances > 0
    # vmapped + jitted stepping works
    keys = jax.random.split(jax.random.key(1), 4)
    states, obs = jax.vmap(env.reset)(keys)
    step = jax.jit(jax.vmap(env.step))
    states, obs, rewards, dones = step(states, jnp.zeros((4, 2)))
    assert rewards.shape == (4,)


def test_hopper_registry():
    from evotorch_tpu.envs import Hopper, make_env

    assert isinstance(make_env("hopper"), Hopper)


# -- batched-native env protocol (population-minor physics layout) -----------


def test_humanoid_batched_protocol_matches_vmap():
    """batch_reset/batch_step must be numerically the vmap path: same keys,
    same noise, same dynamics (the engine is one implementation — the single
    API is its B=1 case — but obs assembly and reductions differ in order)."""
    from evotorch_tpu.envs import Humanoid

    env = Humanoid()
    B = 4
    keys = jax.random.split(jax.random.key(7), B)
    bstate, bobs = env.batch_reset(keys)
    sstate, sobs = jax.vmap(env.reset)(keys)
    np.testing.assert_allclose(np.asarray(bobs), np.asarray(sobs), atol=1e-6)

    rng = np.random.default_rng(0)
    for i in range(5):
        actions = jnp.asarray(
            rng.uniform(-1.0, 1.0, size=(B, env.action_size)), jnp.float32
        )
        bstate, bobs, brew, bdone = env.batch_step(bstate, actions)
        sstate, sobs, srew, sdone = jax.vmap(env.step)(sstate, actions)
        np.testing.assert_allclose(
            np.asarray(bobs), np.asarray(sobs), atol=2e-4, rtol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(brew), np.asarray(srew), atol=2e-4, rtol=1e-3
        )
        assert np.array_equal(np.asarray(bdone), np.asarray(sdone))


def test_humanoid_batched_where_selects_lanes():
    from evotorch_tpu.envs import Humanoid

    env = Humanoid()
    B = 3
    s1, _ = env.batch_reset(jax.random.split(jax.random.key(0), B))
    s2, _ = env.batch_reset(jax.random.split(jax.random.key(1), B))
    mask = jnp.asarray([True, False, True])
    out = env.batch_where(mask, s1, s2)
    np.testing.assert_allclose(
        np.asarray(out.obs_state.vel[..., 0]), np.asarray(s1.obs_state.vel[..., 0])
    )
    np.testing.assert_allclose(
        np.asarray(out.obs_state.vel[..., 1]), np.asarray(s2.obs_state.vel[..., 1])
    )
    assert int(out.t[0]) == int(s1.t[0])


def test_humanoid_rollout_uses_batched_path():
    """End-to-end: run_vectorized_rollout over the batched-native Humanoid."""
    from evotorch_tpu.envs import Humanoid
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    env = Humanoid()
    assert env.batched_native  # the engine dispatches on this flag
    # pin the dispatch: the rollout must actually trace through batch_step
    # (a silent fallback to the vmap path would pass every other assertion
    # while reverting the flagship workload to the slow layout)
    calls = []
    orig_batch_step = env.batch_step

    def counting_batch_step(state, actions):
        calls.append(1)
        return orig_batch_step(state, actions)

    env.batch_step = counting_batch_step
    net = Linear(env.observation_size, env.action_size) >> Tanh()
    policy = FlatParamsPolicy(net)
    n = 4
    params = jax.vmap(policy.init_parameters)(jax.random.split(jax.random.key(0), n))
    stats = RunningNorm(env.observation_size).stats
    result = run_vectorized_rollout(
        env, policy, params, jax.random.key(1), stats,
        num_episodes=1, episode_length=20, eval_mode="budget",
        observation_normalization=True,
    )
    assert int(result.total_steps) == n * 20
    assert np.isfinite(np.asarray(result.scores)).all()
    assert float(result.stats.count) > 0
    assert calls, "rollout fell back to the vmap path instead of batch_step"


# ------------------------------------------------------------------ Ant -----


def test_ant_protocol_and_standing():
    from evotorch_tpu.envs import Ant, make_env

    env = make_env("ant")
    assert isinstance(env, Ant)
    assert env.observation_size == 79 and env.action_size == 8
    assert env.batched_native

    B = 8
    state, obs = env.batch_reset(jax.random.split(jax.random.key(0), B))
    assert obs.shape == (B, 79)
    step = jax.jit(env.batch_step)
    # zero action (PD reference pose): the quadruped settles on its legs and
    # stays healthy — quadrupeds are statically stable, unlike the humanoid
    for _ in range(150):
        state, obs, reward, done = step(state, jnp.zeros((B, 8)))
    h = np.asarray(state.obs_state.pos[0, 2, :])
    assert (h > 0.25).all() and (~np.asarray(done)).all()
    assert np.isfinite(np.asarray(obs)).all()


def test_ant_random_actions_finite_and_single_api():
    from evotorch_tpu.envs import Ant

    env = Ant()
    s, o = env.reset(jax.random.key(3))
    assert o.shape == (79,)
    key = jax.random.key(4)
    for _ in range(50):
        key, sub = jax.random.split(key)
        s, o, r, d = env.step(s, jax.random.uniform(sub, (8,), minval=-1, maxval=1))
        assert np.isfinite(float(r))
    assert np.isfinite(np.asarray(o)).all()


def test_ant_rollout_learning_signal():
    # actuation must matter: a leg-cycling open-loop policy displaces the
    # torso measurably more than the zero policy
    from evotorch_tpu.envs import Ant

    env = Ant()
    B = 4
    state0, _ = env.batch_reset(jax.random.split(jax.random.key(0), B))
    step = jax.jit(env.batch_step)

    def drive(state, amp):
        s = state
        for t in range(120):
            phase = 2.0 * jnp.pi * t / 30.0
            # diagonal gait: opposite legs in phase
            knees = jnp.asarray(
                [jnp.sin(phase), jnp.sin(phase + jnp.pi), jnp.sin(phase), jnp.sin(phase + jnp.pi)]
            )
            hips = jnp.asarray(
                [jnp.cos(phase), jnp.cos(phase + jnp.pi), jnp.cos(phase), jnp.cos(phase + jnp.pi)]
            )
            a = amp * jnp.stack([hips[0], knees[0], hips[1], knees[1],
                                 hips[2], knees[2], hips[3], knees[3]])
            s, o, r, d = step(s, jnp.broadcast_to(a, (B, 8)))
        return np.abs(np.asarray(s.obs_state.pos[0, 0, :])).mean()

    moved = drive(state0, 0.5)
    still = drive(state0, 0.0)
    assert moved > still + 0.05, (moved, still)


def test_locomotion_legacy_prng_key_and_substep_validation():
    # review regressions: legacy raw uint32 keys must work through the
    # single-instance API, and an unstable substep count must fail loudly
    from evotorch_tpu.envs import Ant, Humanoid

    env = Ant()
    s, o = env.reset(jax.random.PRNGKey(0))
    s, o, r, d = env.step(s, jnp.zeros(8))
    assert np.isfinite(float(r))
    with pytest.raises(ValueError, match="stability"):
        Ant(substeps=1)
    with pytest.raises(ValueError, match="substeps"):
        Humanoid(substeps=0)


def test_walker2d_protocol_standing_and_planarity():
    from evotorch_tpu.envs import Walker2D, make_env

    env = make_env("walker2d")
    assert isinstance(env, Walker2D)
    assert env.action_size == 6 and env.batched_native and env.planar

    B = 8
    state, obs = env.batch_reset(jax.random.split(jax.random.key(0), B))
    step = jax.jit(env.batch_step)
    # zero action (PD reference pose): the biped stands in the healthy band
    for _ in range(150):
        state, obs, reward, done = step(state, jnp.zeros((B, 6)))
    h = np.asarray(state.obs_state.pos[0, 2, :])
    assert (h > 0.8).all() and (h < 2.0).all() and (~np.asarray(done)).all()
    # planar projection: no lateral drift, orientations stay pure-y rotations
    y = np.asarray(state.obs_state.pos[:, 1, :])
    assert np.allclose(np.abs(y).max(axis=-1), np.abs(np.asarray(env._default_pos[:, 1])), atol=1e-6)
    quat = np.asarray(state.obs_state.quat)
    assert np.abs(quat[:, 1, :]).max() < 1e-6 and np.abs(quat[:, 3, :]).max() < 1e-6
    assert np.isfinite(np.asarray(obs)).all()


def test_walker2d_gait_learning_signal():
    # actuation must matter: an alternating-leg open-loop cycle displaces the
    # torso more than standing still
    from evotorch_tpu.envs import Walker2D

    env = Walker2D()
    B = 4
    state0, _ = env.batch_reset(jax.random.split(jax.random.key(1), B))
    step = jax.jit(env.batch_step)

    def drive(state, amp):
        s = state
        for t in range(120):
            phase = 2.0 * jnp.pi * t / 30.0
            a = amp * jnp.asarray(
                [jnp.sin(phase), -0.3 * jnp.cos(phase), 0.2 * jnp.sin(phase),
                 jnp.sin(phase + jnp.pi), -0.3 * jnp.cos(phase + jnp.pi), 0.2 * jnp.sin(phase + jnp.pi)]
            )
            s, o, r, d = step(s, jnp.broadcast_to(a, (B, 6)))
        return np.abs(np.asarray(s.obs_state.pos[0, 0, :])).mean()

    assert drive(state0, 0.5) > drive(state0, 0.0) + 0.05


def test_halfcheetah_no_termination_and_bounded_zero_action_drift():
    from evotorch_tpu.envs import HalfCheetah, make_env

    env = make_env("halfcheetah")
    assert isinstance(env, HalfCheetah)
    assert env.action_size == 6 and env.planar

    B = 4
    state, obs = env.batch_reset(jax.random.split(jax.random.key(0), B))
    step = jax.jit(env.batch_step)
    for _ in range(200):
        state, obs, reward, done = step(state, jnp.zeros((B, 6)))
    # never terminates before the time limit, even tumbling
    assert (~np.asarray(done)).all()
    # zero action must not be a free-reward glide (the single-sphere foot
    # ratchet produced 1.5 m/s): displacement stays bounded
    x = np.abs(np.asarray(state.obs_state.pos[0, 0, :]))
    assert (x < 0.5).all(), x
    assert np.isfinite(np.asarray(obs)).all()


def test_halfcheetah_actuation_moves_it():
    from evotorch_tpu.envs import HalfCheetah

    env = HalfCheetah()
    B = 4
    state0, _ = env.batch_reset(jax.random.split(jax.random.key(2), B))
    step = jax.jit(env.batch_step)

    def drive(state, amp):
        s = state
        total_r = 0.0
        for t in range(120):
            phase = 2.0 * jnp.pi * t / 25.0
            a = amp * jnp.asarray(
                [jnp.sin(phase), 0.5 * jnp.sin(phase + 0.8), 0.3 * jnp.sin(phase + 1.6),
                 jnp.sin(phase + jnp.pi), 0.5 * jnp.sin(phase + jnp.pi + 0.8), 0.3 * jnp.sin(phase + jnp.pi + 1.6)]
            )
            s, o, r, d = step(s, jnp.broadcast_to(a, (B, 6)))
            total_r += float(jnp.mean(r))
        return np.abs(np.asarray(s.obs_state.pos[0, 0, :])).mean()

    assert drive(state0, 0.8) > drive(state0, 0.0) + 0.05
