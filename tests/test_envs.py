import jax
import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.envs import Acrobot, CartPole, MountainCarContinuous, Pendulum, Swimmer2D, make_env


@pytest.mark.parametrize(
    "env_ctor",
    [CartPole, Pendulum, Acrobot, MountainCarContinuous, Swimmer2D],
)
def test_env_protocol(env_ctor):
    env = env_ctor()
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == env.observation_space.shape
    if env.action_space.is_discrete:
        action = jnp.zeros((), dtype=jnp.int32)
    else:
        action = jnp.zeros(env.action_space.shape)
    state, obs, reward, done = env.step(state, action)
    assert obs.shape == env.observation_space.shape
    assert reward.shape == ()
    assert done.shape == ()


@pytest.mark.parametrize("env_ctor", [CartPole, Pendulum])
def test_env_vmapped_and_jitted(env_ctor):
    env = env_ctor()
    n = 6
    keys = jax.random.split(jax.random.key(0), n)
    states, obs = jax.vmap(env.reset)(keys)
    assert obs.shape == (n,) + env.observation_space.shape
    if env.action_space.is_discrete:
        actions = jnp.zeros(n, dtype=jnp.int32)
    else:
        actions = jnp.zeros((n,) + env.action_space.shape)

    @jax.jit
    def multi_step(states, actions):
        return jax.vmap(env.step)(states, actions)

    states, obs, rewards, dones = multi_step(states, actions)
    assert rewards.shape == (n,)


def test_cartpole_terminates_on_pole_fall():
    env = CartPole()
    state, obs = env.reset(jax.random.key(1))
    done = jnp.zeros((), bool)
    # always push right: the pole falls within the episode
    for _ in range(200):
        state, obs, reward, done = env.step(state, jnp.ones((), dtype=jnp.int32))
        if bool(done):
            break
    assert bool(done)


def test_pendulum_reward_negative_cost():
    env = Pendulum()
    state, obs = env.reset(jax.random.key(0))
    _, _, reward, _ = env.step(state, jnp.zeros(1))
    assert float(reward) <= 0.0


def test_registry():
    env = make_env("cartpole")
    assert isinstance(env, CartPole)
    assert isinstance(make_env("CartPole-v1"), CartPole)
    assert isinstance(make_env("pendulum"), Pendulum)
    with pytest.raises(ValueError):
        make_env("nonexistent_env")
    with pytest.raises(ImportError):
        make_env("brax::humanoid")


def test_env_determinism():
    env = Pendulum()
    s1, o1 = env.reset(jax.random.key(5))
    s2, o2 = env.reset(jax.random.key(5))
    assert np.allclose(np.asarray(o1), np.asarray(o2))


def test_hopper_physics_and_learning_signal():
    from evotorch_tpu.envs import Hopper

    env = Hopper()
    state, obs = env.reset(jax.random.key(0))
    assert obs.shape == (7,)
    # passive drop: touches down (stance flag rises) and does not explode
    stances = 0
    for _ in range(100):
        state, obs, reward, done = env.step(state, jnp.zeros(2))
        stances += int(state.obs_state[6])
        assert np.isfinite(float(reward))
    assert stances > 0
    # vmapped + jitted stepping works
    keys = jax.random.split(jax.random.key(1), 4)
    states, obs = jax.vmap(env.reset)(keys)
    step = jax.jit(jax.vmap(env.step))
    states, obs, rewards, dones = step(states, jnp.zeros((4, 2)))
    assert rewards.shape == (4,)


def test_hopper_registry():
    from evotorch_tpu.envs import Hopper, make_env

    assert isinstance(make_env("hopper"), Hopper)
