import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import ranking


def test_centered_basic():
    f = jnp.array([1.0, 3.0, 2.0, 4.0])
    u = ranking.centered(f, higher_is_better=True)
    # best solution (4.0) gets +0.5, worst (1.0) gets -0.5
    assert np.isclose(float(u[3]), 0.5)
    assert np.isclose(float(u[0]), -0.5)
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)


def test_centered_minimization():
    f = jnp.array([1.0, 3.0, 2.0, 4.0])
    u = ranking.centered(f, higher_is_better=False)
    assert np.isclose(float(u[0]), 0.5)
    assert np.isclose(float(u[3]), -0.5)


def test_linear_range():
    f = jnp.array([5.0, 1.0, 3.0])
    u = ranking.linear(f, higher_is_better=True)
    assert np.isclose(float(jnp.min(u)), 0.0)
    assert np.isclose(float(jnp.max(u)), 1.0)


def test_nes_properties():
    f = jnp.array([0.1, 0.9, 0.5, 0.3, 0.7])
    u = ranking.nes(f, higher_is_better=True)
    # weights sum to ~0 and the best solution has the largest weight
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)
    assert int(jnp.argmax(u)) == int(jnp.argmax(f))
    # worst weights are all equal to -1/n (clipped utilities)
    assert float(u[0]) == pytest.approx(-1.0 / 5.0, abs=1e-6)


def test_normalized():
    f = jnp.array([1.0, 2.0, 3.0])
    u = ranking.normalized(f, higher_is_better=True)
    assert np.isclose(float(jnp.mean(u)), 0.0, atol=1e-6)
    # unbiased stdev (ddof=1), matching the reference's torch.std
    assert np.isclose(float(np.std(np.asarray(u), ddof=1)), 1.0, atol=1e-5)
    # reference values for [3,1,2,5] (torch.std semantics)
    u = ranking.normalized(jnp.array([3.0, 1.0, 2.0, 5.0]), higher_is_better=True)
    assert np.allclose(np.asarray(u), [0.1462, -1.0247, -0.4392, 1.3178], atol=1e-3)


def test_raw_sign():
    f = jnp.array([1.0, -2.0])
    assert np.allclose(np.asarray(ranking.raw(f, higher_is_better=True)), [1.0, -2.0])
    assert np.allclose(np.asarray(ranking.raw(f, higher_is_better=False)), [-1.0, 2.0])


def test_rank_dispatcher_and_batching():
    f = jnp.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    u = ranking.rank(f, "centered", higher_is_better=True)
    assert u.shape == (2, 3)
    assert np.allclose(np.asarray(u[0]), [-0.5, 0.0, 0.5])
    assert np.allclose(np.asarray(u[1]), [0.5, 0.0, -0.5])
    with pytest.raises(ValueError):
        ranking.rank(f, "bogus", higher_is_better=True)


def test_ties_get_distinct_ranks():
    f = jnp.array([1.0, 1.0, 1.0])
    u = ranking.centered(f, higher_is_better=True)
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)
    assert len(set(np.asarray(u).tolist())) == 3
