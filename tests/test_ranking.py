import jax.numpy as jnp
import numpy as np
import pytest

from evotorch_tpu.tools import ranking


def test_centered_basic():
    f = jnp.array([1.0, 3.0, 2.0, 4.0])
    u = ranking.centered(f, higher_is_better=True)
    # best solution (4.0) gets +0.5, worst (1.0) gets -0.5
    assert np.isclose(float(u[3]), 0.5)
    assert np.isclose(float(u[0]), -0.5)
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)


def test_centered_minimization():
    f = jnp.array([1.0, 3.0, 2.0, 4.0])
    u = ranking.centered(f, higher_is_better=False)
    assert np.isclose(float(u[0]), 0.5)
    assert np.isclose(float(u[3]), -0.5)


def test_linear_range():
    f = jnp.array([5.0, 1.0, 3.0])
    u = ranking.linear(f, higher_is_better=True)
    assert np.isclose(float(jnp.min(u)), 0.0)
    assert np.isclose(float(jnp.max(u)), 1.0)


def test_nes_properties():
    f = jnp.array([0.1, 0.9, 0.5, 0.3, 0.7])
    u = ranking.nes(f, higher_is_better=True)
    # weights sum to ~0 and the best solution has the largest weight
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)
    assert int(jnp.argmax(u)) == int(jnp.argmax(f))
    # worst weights are all equal to -1/n (clipped utilities)
    assert float(u[0]) == pytest.approx(-1.0 / 5.0, abs=1e-6)


def test_normalized():
    f = jnp.array([1.0, 2.0, 3.0])
    u = ranking.normalized(f, higher_is_better=True)
    assert np.isclose(float(jnp.mean(u)), 0.0, atol=1e-6)
    # unbiased stdev (ddof=1), matching the reference's torch.std
    assert np.isclose(float(np.std(np.asarray(u), ddof=1)), 1.0, atol=1e-5)
    # reference values for [3,1,2,5] (torch.std semantics)
    u = ranking.normalized(jnp.array([3.0, 1.0, 2.0, 5.0]), higher_is_better=True)
    assert np.allclose(np.asarray(u), [0.1462, -1.0247, -0.4392, 1.3178], atol=1e-3)


def test_raw_sign():
    f = jnp.array([1.0, -2.0])
    assert np.allclose(np.asarray(ranking.raw(f, higher_is_better=True)), [1.0, -2.0])
    assert np.allclose(np.asarray(ranking.raw(f, higher_is_better=False)), [-1.0, 2.0])


def test_rank_dispatcher_and_batching():
    f = jnp.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    u = ranking.rank(f, "centered", higher_is_better=True)
    assert u.shape == (2, 3)
    assert np.allclose(np.asarray(u[0]), [-0.5, 0.0, 0.5])
    assert np.allclose(np.asarray(u[1]), [0.5, 0.0, -0.5])
    with pytest.raises(ValueError):
        ranking.rank(f, "bogus", higher_is_better=True)


def test_ties_get_distinct_ranks():
    f = jnp.array([1.0, 1.0, 1.0])
    u = ranking.centered(f, higher_is_better=True)
    assert np.isclose(float(jnp.sum(u)), 0.0, atol=1e-6)
    assert len(set(np.asarray(u).tolist())) == 3


def test_centered_dispatches_to_fused_kernel(monkeypatch):
    # EVOTORCH_TPU_FUSED_RANK=1 forces the fused path on any backend
    # (interpret-mode off-TPU); results must be identical to the XLA form,
    # through the public rank() entry the algorithms actually call
    import numpy as np

    from evotorch_tpu.tools.ranking import centered_xla, rank

    fit = jnp.asarray(np.random.default_rng(0).normal(size=257), jnp.float32)
    monkeypatch.setenv("EVOTORCH_TPU_FUSED_RANK", "1")
    got = rank(fit, "centered", higher_is_better=True)
    monkeypatch.setenv("EVOTORCH_TPU_FUSED_RANK", "0")
    want = rank(fit, "centered", higher_is_better=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(want), np.asarray(centered_xla(fit, higher_is_better=True)), atol=0
    )


def test_centered_fused_dispatch_bounds(monkeypatch):
    # outside [2, 1024] the dispatcher must stay on XLA even when forced
    import numpy as np

    from evotorch_tpu.tools import ranking as ranking_mod

    monkeypatch.setenv("EVOTORCH_TPU_FUSED_RANK", "1")
    assert not ranking_mod._use_fused_centered(1)
    assert not ranking_mod._use_fused_centered(4096)
    assert not ranking_mod._use_fused_centered(2048)  # over the VMEM budget
    assert ranking_mod._use_fused_centered(1024)
    monkeypatch.setenv("EVOTORCH_TPU_FUSED_RANK", "0")
    assert not ranking_mod._use_fused_centered(512)
    # big-n always works through the public entry regardless of the flag
    monkeypatch.setenv("EVOTORCH_TPU_FUSED_RANK", "1")
    fit = jnp.asarray(np.random.default_rng(1).normal(size=5000), jnp.float32)
    out = ranking_mod.rank(fit, "centered", higher_is_better=False)
    assert out.shape == (5000,)


def test_fused_rank_nan_semantics_match_xla():
    # a NaN fitness (diverged rollout) must rank identically in both paths:
    # argsort places NaN last, i.e. "best" — the fused kernel's total order
    # is lexicographic on (isnan, value, index)
    import numpy as np

    from evotorch_tpu.ops.ranking import fused_centered_rank
    from evotorch_tpu.tools.ranking import centered_xla

    fit = jnp.asarray([1.0, jnp.nan, 3.0, 2.0, jnp.nan, -1.0], jnp.float32)
    for hib in (True, False):
        got = fused_centered_rank(fit, higher_is_better=hib, use_pallas=True, interpret=True)
        want = centered_xla(fit, higher_is_better=hib)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_fused_sampling_optin_dispatch(monkeypatch):
    # EVOTORCH_TPU_FUSED_SAMPLING is opt-in; the dispatcher must be OFF by
    # default (the kernel changes the random stream, not just the speed)
    import jax
    import pytest

    from evotorch_tpu.distributions import _use_fused_sampling

    monkeypatch.delenv("EVOTORCH_TPU_FUSED_SAMPLING", raising=False)
    assert not _use_fused_sampling()
    monkeypatch.setenv("EVOTORCH_TPU_FUSED_SAMPLING", "1")
    if jax.default_backend() == "tpu":
        assert _use_fused_sampling()
    else:
        # the on-chip PRNG only lowers on TPU: elsewhere the flag must warn
        # and fall back to the XLA sampler instead of crashing the first ask
        with pytest.warns(UserWarning, match="only lowers on TPU"):
            assert not _use_fused_sampling()
