"""Micro-bench for the fused Pallas kernels (ops/) vs their XLA fallbacks.

Run on the backend under test (TPU when the tunnel is healthy; the ranking
kernel also interprets on CPU but interpret-mode timings are meaningless).
Prints one JSON line per comparison; the opt-in flags
``EVOTORCH_TPU_FUSED_RANK`` (both kernels ship off by default until a chip
win is recorded here) and ``EVOTORCH_TPU_FUSED_SAMPLING`` are
justified/refuted by these numbers — recorded in BENCH_NOTES.md. The sweep
times XLA beyond the fused VMEM bound (n <= 1024) for context; the fused
kernel is only timed inside the bound, where the flag would select it.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_common import setup_backend  # noqa: E402


def _time(fn, *args, iters=200):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    use_cpu = setup_backend()
    import jax
    import jax.numpy as jnp

    from evotorch_tpu.ops.ranking import fused_centered_rank
    from evotorch_tpu.ops.sampling import sample_symmetric_gaussian
    from evotorch_tpu.tools.ranking import centered_xla

    backend = "cpu" if use_cpu else jax.default_backend()
    key = jax.random.key(0)

    # jitted once, outside the timing loops (graftlint `retrace`: a jit built
    # per iteration discards its trace cache every time)
    xla = jax.jit(partial(centered_xla, higher_is_better=True))
    # fused_centered_rank is itself jitted (ops/ranking.py): partial only
    fused = partial(fused_centered_rank, higher_is_better=True, use_pallas=True)

    for n in (256, 512, 1024, 2048):
        # each size draws from its own subkey (graftlint `prng`: reusing the
        # base key across iterations would replay the same stream)
        key, sub = jax.random.split(key)
        fit = jax.random.normal(sub, (n,))
        t_xla = _time(xla, fit)
        # only time the fused kernel where the dispatch would select it
        # (n <= 1024: the O(n^2) comparison block fits VMEM; 2048 would not)
        if backend == "tpu" and n <= 1024:
            try:
                t_fused = _time(fused, fit)
            except Exception as e:  # record the failure instead of aborting
                print(json.dumps({"metric": "fused_centered_rank_us", "n": n,
                                  "error": f"{type(e).__name__}: {e}"[:200]}))
                t_fused = None
        else:
            t_fused = None
        print(
            json.dumps(
                {
                    "metric": "fused_centered_rank_us",
                    "n": n,
                    "xla_us": round(t_xla * 1e6, 2),
                    "pallas_us": None if t_fused is None else round(t_fused * 1e6, 2),
                    "speedup": None if t_fused is None else round(t_xla / t_fused, 3),
                    "backend": backend,
                }
            )
        )

    if backend == "tpu":
        for popsize, length in ((10_000, 12_305), (1_024, 66_048)):
            mu = jnp.zeros(length)
            sigma = jnp.full(length, 0.1)
            # sample_symmetric_gaussian is itself jitted (ops/sampling.py);
            # re-wrapping it in a per-iteration jit(lambda) would rebuild the
            # trace cache every loop pass
            t_xla = _time(
                partial(
                    sample_symmetric_gaussian,
                    mu=mu, sigma=sigma, num_solutions=popsize, use_pallas=False,
                ),
                key,
                iters=20,
            )
            t_fused = _time(
                partial(
                    sample_symmetric_gaussian,
                    mu=mu, sigma=sigma, num_solutions=popsize, use_pallas=True,
                ),
                key,
                iters=20,
            )
            print(
                json.dumps(
                    {
                        "metric": "fused_antithetic_sampling_ms",
                        "popsize": popsize,
                        "solution_length": length,
                        "xla_ms": round(t_xla * 1e3, 3),
                        "pallas_ms": round(t_fused * 1e3, 3),
                        "speedup": round(t_xla / t_fused, 3),
                        "backend": backend,
                    }
                )
            )


if __name__ == "__main__":
    main()
