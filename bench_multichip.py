"""Multi-chip benchmark: the exact `bench.py` program, population-sharded.

The generation program is identical to ``bench.py`` (PGPE ask -> fully
vectorized Humanoid rollout -> tell); the only difference is that the
population axis is sharded over a ``("pop",)`` ``jax.sharding.Mesh`` and the
rollout runs as a ``shard_map`` — each shard rolls out its own rows locally,
observation statistics and interaction counters merge with ``psum``, and the
per-shard step counts come back as a ``P("pop")`` array so the accounting of
every chip is visible (VERDICT r2 #4).

Runs unchanged on real multi-chip hardware (e.g. v5e-8): with a healthy
multi-device backend the mesh spans the real chips. On this rig it is
exercised on the 8-virtual-device CPU mesh
(``JAX_PLATFORMS=cpu python bench_multichip.py``) and on the single real TPU
chip (mesh of 1).

Knobs: the same BENCH_* env vars as bench.py, plus BENCH_MESH (number of
devices to use; default all). With BENCH_LEDGER on (default), the sharded
generation program is AOT-captured into the program ledger and the line
carries ``compile_seconds`` / ``flops_per_step`` / ``peak_hbm_bytes`` /
``model_efficiency`` (null for the host-orchestrated episodes_compact
path, which has no single whole-generation program).
"""

import json
import os
import sys
import time
from functools import partial

from bench_common import (
    bench_config,
    build_policy,
    compact_kwargs,
    fresh_pgpe_state,
    ledger_columns,
    refill_kwargs,
    setup_backend,
)


def main():
    use_cpu = setup_backend()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    from evotorch_tpu.algorithms.functional import pgpe_ask, pgpe_tell
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import (
        global_lane_ids,
        run_vectorized_rollout,
        run_vectorized_rollout_compacting_sharded,
    )

    cfg = bench_config(use_cpu, cpu_episode_length=50)
    popsize = cfg["popsize"]
    episode_length = cfg["episode_length"]
    generations = cfg["generations"]
    compute_dtype = cfg["compute_dtype"]
    eval_mode = cfg["eval_mode"]

    n_devices = len(jax.devices())
    mesh_size = int(os.environ.get("BENCH_MESH", n_devices))
    devices = np.asarray(jax.devices()[:mesh_size])
    mesh = Mesh(devices, axis_names=("pop",))
    if popsize % mesh_size != 0:
        raise SystemExit(
            f"popsize {popsize} must be divisible by the mesh size {mesh_size}"
        )

    env = make_env(cfg["env_name"], **cfg["env_kwargs"])
    policy = build_policy(env)
    print(
        f"mesh={dict(mesh.shape)} devices={mesh_size} popsize={popsize} "
        f"(={popsize // mesh_size}/shard) params={policy.parameter_count} "
        f"episode_length={episode_length} eval_mode={eval_mode}",
        file=sys.stderr,
    )

    stats = RunningNorm(env.observation_size).stats
    state = fresh_pgpe_state(policy.parameter_count)

    # per-shard refill queues: the width knob is global, the seed stride is
    # the global popsize (unique (solution, episode) seeds across shards)
    rkw = (
        dict(
            refill_kwargs(cfg, n_shards=mesh_size, params=policy.parameter_count),
            seed_stride=popsize,
        )
        if eval_mode == "episodes_refill"
        else {}
    )

    def local_rollout(values_shard, key, stats):
        # per-lane PRNG chains seeded by GLOBAL lane ids (same key on every
        # shard): the sharded program's realized randomness is identical to
        # the unsharded one. Stat deltas and step counters merge across the
        # pop axis with psums (the collective form of the reference's actor
        # delta-sync, gymne.py:524-573)
        ids = global_lane_ids("pop", values_shard.shape[0])
        result = run_vectorized_rollout(
            env,
            policy,
            values_shard,
            key,
            stats,
            lane_ids=ids,
            num_episodes=1,
            episode_length=episode_length,
            compute_dtype=compute_dtype,
            eval_mode=eval_mode,
            **rkw,
        )
        delta = jax.tree_util.tree_map(lambda new, old: new - old, result.stats, stats)
        merged = jax.tree_util.tree_map(
            lambda old, d: old + jax.lax.psum(d, "pop"), stats, delta
        )
        local_steps = result.total_steps[None]  # P("pop") -> per-shard array
        return result.scores, merged, local_steps

    sharded_rollout = jax.shard_map(
        local_rollout,
        mesh=mesh,
        in_specs=(P("pop"), P(), P()),
        out_specs=(P("pop"), P(), P("pop")),
        check_vma=False,
    )

    pop_sharding = NamedSharding(mesh, P("pop"))

    if eval_mode == "episodes_compact":
        # the sharded lane-compacting runner (host-orchestrated chunks over
        # shard_mapped building blocks): ask and tell stay jitted programs
        # around it, with the population pinned to the pop sharding
        ask_jit = jax.jit(
            lambda k, s: jax.lax.with_sharding_constraint(
                pgpe_ask(k, s, popsize=popsize), pop_sharding
            )
        )
        tell_jit = jax.jit(pgpe_tell, donate_argnums=(0,))

        first_gen = [True]
        ckw = compact_kwargs(cfg, n_shards=mesh_size, params=policy.parameter_count)

        def generation(state, key, stats):
            k1, k2 = jax.random.split(key)
            values = ask_jit(k1, state)
            result, per_shard_steps = run_vectorized_rollout_compacting_sharded(
                env, policy, values, k2, stats,
                mesh=mesh,
                num_episodes=1,
                episode_length=episode_length,
                compute_dtype=compute_dtype,
                **ckw,
                # compile the full width-descent chain during the warmup
                # generation so no compile lands in the timed loop
                prewarm=first_gen[0],
                return_per_shard_steps=True,
            )
            first_gen[0] = False
            state = tell_jit(state, values, result.scores)
            return state, result.stats, per_shard_steps, result.scores

    else:

        @partial(jax.jit, donate_argnums=(0,))
        def generation(state, key, stats):
            k1, k2 = jax.random.split(key)
            values = pgpe_ask(k1, state, popsize=popsize)
            values = jax.lax.with_sharding_constraint(values, pop_sharding)
            scores, stats, per_shard_steps = sharded_rollout(values, k2, stats)
            state = pgpe_tell(state, values, scores)
            return state, stats, per_shard_steps, scores

    key = jax.random.key(0)
    key, sub = jax.random.split(key)
    state, stats, per_shard, scores = generation(state, sub, stats)
    jax.block_until_ready(scores)
    print(
        f"compiled; warmup per-shard steps={np.asarray(per_shard).tolist()}",
        file=sys.stderr,
    )

    # program ledger (BENCH_LEDGER, like bench.py): AOT-capture the sharded
    # generation program — compile wall-time, FLOPs, peak memory, donation
    # verification — outside the timed loop. The compact path is
    # host-orchestrated (no single whole-generation program), so its ledger
    # columns stay null.
    record = None
    if cfg["ledger"] and eval_mode != "episodes_compact":
        from evotorch_tpu.observability import ledger as program_ledger
        from evotorch_tpu.observability.programs import abstract_like

        record = program_ledger.capture(
            f"bench_multichip.generation[{eval_mode}]",
            generation,
            abstract_like(fresh_pgpe_state(policy.parameter_count)),
            jax.random.key(0),
            abstract_like(stats),
            shape={
                "env": cfg["env_name"],
                "popsize": popsize,
                "episode_length": episode_length,
                "mesh": mesh_size,
            },
        )

    t0 = time.perf_counter()
    total_steps = 0
    shard_steps = np.zeros(mesh_size, dtype=np.int64)
    for _ in range(generations):
        key, sub = jax.random.split(key)
        state, stats, per_shard, scores = generation(state, sub, stats)
        jax.block_until_ready(scores)
        shard_steps += np.asarray(per_shard)
        total_steps += int(np.sum(np.asarray(per_shard)))
    elapsed = time.perf_counter() - t0

    steps_per_sec = total_steps / elapsed
    ledger_cols = {}
    if cfg["ledger"]:
        ledger_cols = (
            ledger_columns(
                record,
                steps_per_sec=steps_per_sec,
                steps_per_generation=total_steps / generations,
            )
            if record is not None
            else {
                "compile_seconds": None,
                "flops_per_step": None,
                "peak_hbm_bytes": None,
                "model_efficiency": None,
            }
        )
    print(
        f"{generations} generations, {total_steps} env-steps in {elapsed:.2f}s; "
        f"mean score {float(jnp.mean(scores)):.3f}; "
        f"per-shard steps {shard_steps.tolist()}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "pgpe_sharded_rollout_env_steps_per_sec",
                "value": round(steps_per_sec, 1),
                "unit": "env_steps/sec",
                "vs_baseline": round(steps_per_sec / 1_000_000, 4),
                **ledger_cols,
                "mesh": {"pop": mesh_size},
                "per_shard_steps": shard_steps.tolist(),
                "env": cfg["env_name"],
                "popsize": popsize,
                "episode_length": episode_length,
                "eval_mode": eval_mode,
                "compute_dtype": str(compute_dtype.__name__ if compute_dtype else "float32"),
                "backend": "cpu-mesh" if use_cpu else "tpu",
            }
        )
    )


if __name__ == "__main__":
    main()
