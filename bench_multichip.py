"""Multi-chip benchmark: the exact `bench.py` program, population-sharded.

The generation program is identical to ``bench.py`` (PGPE ask -> fully
vectorized Humanoid rollout -> tell); the only difference is that the
population axis is laid out over a named device mesh. Two SPMD forms are
supported (``BENCH_SPMD``, docs/sharding.md):

- ``gspmd`` (default): ONE global jitted generation
  (``parallel.make_generation_step``) with the population pinned to the
  mesh via ``NamedSharding`` — XLA's SPMD partitioner inserts the
  collectives, the evolution state is donated end-to-end, popsizes that
  don't divide the mesh are padded+masked, and 2-D ``pop x model`` meshes
  work (``BENCH_MESH=4x2``).
- ``shard_map``: the pre-GSPMD explicit per-shard form (global lane ids,
  psum'd stat deltas and counters, per-shard refill queues) — kept as the
  measured A/B baseline.
- ``ab``: BOTH, interleaved on the same process (this box times ±20%
  run-to-run; ``BENCH_AB_REPEATS`` samples each, default 3, medians
  reported) with ``spmd_speedup`` = gspmd / shard_map median steps/s.

Runs unchanged on real multi-chip hardware (e.g. v5e-8): with a healthy
multi-device backend the mesh spans the real chips. On this rig it is
exercised on the 8-virtual-device CPU mesh
(``JAX_PLATFORMS=cpu python bench_multichip.py``) and on the single real TPU
chip (mesh of 1).

Knobs: the same BENCH_* env vars as bench.py, plus ``BENCH_MESH`` (``"8"``
= 1-D pop mesh of 8, ``"4x2"`` / ``"pop=4,model=2"`` = 2-D; default all
local devices on ``pop``) and ``BENCH_SPMD`` above. ``BENCH_TRUNK_DELTA=1``
evaluates the shared-trunk + per-lane low-rank-delta form (GSPMD path only:
the evaluator pins the trunk to the ``model`` axis, the per-lane
coefficients to ``pop`` — docs/policies.md). The refill schedule
resolves through the tuned-config cache under THIS mesh's label (a width
tuned unsharded is not evidence for a sharded layout). With BENCH_LEDGER
on (default), the generation program is AOT-captured into the program
ledger — the line carries ``compile_seconds`` / ``flops_per_step`` /
``peak_hbm_bytes`` / ``model_efficiency`` plus ``donation_verified``
(runtime-checked ``donate_argnums`` aliasing; null for the
host-orchestrated episodes_compact path, which has no single
whole-generation program). ``steady_compiles`` is the retrace-sentinel
count over every timed loop — anything but 0 is a retrace bug.
"""

import json
import os
import statistics
import sys
import time

from bench_common import (
    bench_config,
    build_policy,
    compact_kwargs,
    fresh_pgpe_state,
    ledger_columns,
    refill_kwargs,
    setup_backend,
    tuned_policy,
    tuned_refill,
)


def main():
    use_cpu = setup_backend()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    from evotorch_tpu.algorithms.functional import (
        pgpe_ask,
        pgpe_ask_trunk_delta,
        pgpe_tell,
        pgpe_tell_trunk_delta,
    )
    from evotorch_tpu.analysis import track_compiles
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import (
        global_lane_ids,
        run_vectorized_rollout,
        run_vectorized_rollout_compacting_sharded,
    )
    from evotorch_tpu.parallel import make_generation_step, make_mesh, parse_mesh_shape
    from evotorch_tpu.parallel import mesh_label as mesh_label_of

    cfg = bench_config(use_cpu, cpu_episode_length=50)
    if cfg["compile_cache"]:
        from evotorch_tpu.observability import enable_persistent_cache

        enable_persistent_cache()
    popsize = cfg["popsize"]
    episode_length = cfg["episode_length"]
    generations = cfg["generations"]
    compute_dtype = cfg["compute_dtype"]
    eval_mode = cfg["eval_mode"]

    spmd = os.environ.get("BENCH_SPMD", "gspmd")
    if spmd not in ("gspmd", "shard_map", "ab"):
        raise SystemExit(f"BENCH_SPMD must be gspmd|shard_map|ab, got {spmd!r}")
    n_devices = len(jax.devices())
    mesh_shape = parse_mesh_shape(os.environ.get("BENCH_MESH", n_devices))
    mesh = make_mesh(mesh_shape)
    mesh_size = int(np.prod([int(s) for s in mesh_shape.values()]))

    if eval_mode == "episodes_compact":
        # the lane-compacting runner is host-orchestrated over shard_map
        # building blocks — there is no GSPMD monolith to A/B against
        if spmd == "ab":
            raise SystemExit("BENCH_SPMD=ab has no GSPMD form for episodes_compact")
        variants = ["host_compact"]
    else:
        variants = {"gspmd": ["gspmd"], "shard_map": ["shard_map"],
                    "ab": ["gspmd", "shard_map"]}[spmd]

    trunk_delta = cfg["trunk_delta"]
    needs_legacy = any(v in ("shard_map", "host_compact") for v in variants)
    if trunk_delta and needs_legacy:
        # the trunk-delta population shards through the GSPMD evaluator's
        # pytree-aware constraints (trunk over `model`, coeffs over `pop`);
        # the explicit shard_map / host-compact harnesses here are dense-only
        raise SystemExit(
            "BENCH_TRUNK_DELTA=1 needs the GSPMD path (BENCH_SPMD=gspmd, "
            f"eval_mode != episodes_compact); got spmd variants {variants}"
        )
    if needs_legacy:
        sharded_axes = [n for n, s in mesh.shape.items() if int(s) > 1]
        if sharded_axes not in ([], ["pop"]):
            raise SystemExit(
                f"the shard_map path needs a 1-D pop mesh, got {dict(mesh.shape)}"
            )
        if popsize % mesh_size != 0:
            raise SystemExit(
                f"popsize {popsize} must be divisible by the mesh size "
                f"{mesh_size} on the shard_map path (GSPMD pads instead)"
            )
        mesh_1d = Mesh(np.asarray(jax.devices()[:mesh_size]), axis_names=("pop",))

    env = make_env(cfg["env_name"], **cfg["env_kwargs"])
    policy = build_policy(env)
    print(
        f"mesh={dict(mesh.shape)} ({mesh_label_of(mesh)}) devices={mesh_size} "
        f"popsize={popsize} params={policy.parameter_count} "
        f"episode_length={episode_length} eval_mode={eval_mode} spmd={variants}",
        file=sys.stderr,
    )

    stats0 = RunningNorm(env.observation_size).stats

    # every variant's generation has the same host contract:
    #   gen(state, key, stats) -> (state, stats, per_shard_steps, scores)
    # build_* returns (gen, capture_target) — capture_target is the jitted
    # whole-generation program for the ledger, or None (host_compact)
    refill_src = None

    trunk_cfg, trunk_src = {}, None
    if trunk_delta:
        trunk_cfg, trunk_src = tuned_policy(
            cfg, params=policy.parameter_count, mesh_label=mesh_label_of(mesh)
        )

    def gspmd_recipe():
        """The GSPMD ask/tell callables + rollout knobs, shared by the
        per-generation program and the fused-span program so the two legs
        of the BENCH_SPAN A/B cannot silently diverge."""
        nonlocal refill_src
        rkw = {}
        if eval_mode == "episodes_refill":
            # GLOBAL width (the GSPMD program is the unsharded program),
            # looked up under THIS mesh's label
            rkw, refill_src = tuned_refill(
                cfg, params=policy.parameter_count, mesh_label=mesh_label_of(mesh)
            )
        if trunk_delta:
            # shared-trunk + per-lane delta population: the evaluator pins
            # the trunk (center + effective basis) to the `model` axis and
            # the per-lane coefficients to `pop` (parallel/evaluate.py)
            def ask_fn(k, s):
                return pgpe_ask_trunk_delta(
                    k, s, popsize=popsize, rank=trunk_cfg["rank"], policy=policy
                )

            tell_fn = pgpe_tell_trunk_delta
            rkw["trunk_block"] = trunk_cfg["trunk_block"]
        else:
            def ask_fn(k, s):
                return pgpe_ask(k, s, popsize=popsize)

            tell_fn = pgpe_tell
        return ask_fn, tell_fn, rkw

    def build_gspmd():
        ask_fn, tell_fn, rkw = gspmd_recipe()
        step = make_generation_step(
            env,
            policy,
            ask=ask_fn,
            tell=tell_fn,
            popsize=popsize,
            mesh=mesh,
            num_episodes=1,
            episode_length=episode_length,
            compute_dtype=compute_dtype,
            eval_mode=eval_mode,
            **rkw,
        )

        def gen(state, key, stats):
            state, scores, stats, total_steps, _telemetry = step(state, key, stats)
            # one global program: per-shard accounting is XLA's business,
            # the 1-element form keeps the harness contract
            return state, stats, total_steps[None], scores

        return gen, step

    def build_shard_map():
        # per-shard refill queues: the width knob is global, divided across
        # the mesh; the seed stride is the global popsize (unique
        # (solution, episode) seeds across shards)
        rkw = (
            dict(
                refill_kwargs(
                    cfg,
                    n_shards=mesh_size,
                    params=policy.parameter_count,
                    mesh_label=mesh_label_of(mesh_1d),
                ),
                seed_stride=popsize,
            )
            if eval_mode == "episodes_refill"
            else {}
        )

        def local_rollout(values_shard, key, stats):
            # per-lane PRNG chains seeded by GLOBAL lane ids (same key on
            # every shard): the sharded program's realized randomness is
            # identical to the unsharded one. Stat deltas and step counters
            # merge across the pop axis with psums (the collective form of
            # the reference's actor delta-sync, gymne.py:524-573)
            ids = global_lane_ids("pop", values_shard.shape[0])
            result = run_vectorized_rollout(
                env,
                policy,
                values_shard,
                key,
                stats,
                lane_ids=ids,
                num_episodes=1,
                episode_length=episode_length,
                compute_dtype=compute_dtype,
                eval_mode=eval_mode,
                **rkw,
            )
            delta = jax.tree_util.tree_map(
                lambda new, old: new - old, result.stats, stats
            )
            merged = jax.tree_util.tree_map(
                lambda old, d: old + jax.lax.psum(d, "pop"), stats, delta
            )
            local_steps = result.total_steps[None]  # P("pop") per-shard array
            return result.scores, merged, local_steps

        sharded_rollout = jax.shard_map(
            local_rollout,
            mesh=mesh_1d,
            in_specs=(P("pop"), P(), P()),
            out_specs=(P("pop"), P(), P("pop")),
            check_vma=False,
        )
        pop_sharding = NamedSharding(mesh_1d, P("pop"))

        def generation(state, key, stats):
            k1, k2 = jax.random.split(key)
            values = pgpe_ask(k1, state, popsize=popsize)
            values = jax.lax.with_sharding_constraint(values, pop_sharding)
            scores, stats, per_shard_steps = sharded_rollout(values, k2, stats)
            state = pgpe_tell(state, values, scores)
            return state, stats, per_shard_steps, scores

        gen = jax.jit(generation, donate_argnums=(0,))
        return gen, gen

    def build_host_compact():
        # the sharded lane-compacting runner (host-orchestrated chunks over
        # shard_mapped building blocks): ask and tell stay jitted programs
        # around it, with the population pinned to the pop sharding
        pop_sharding = NamedSharding(mesh_1d, P("pop"))

        def sharded_ask(k, s):
            return jax.lax.with_sharding_constraint(
                pgpe_ask(k, s, popsize=popsize), pop_sharding
            )

        ask_jit = jax.jit(sharded_ask)
        tell_jit = jax.jit(pgpe_tell, donate_argnums=(0,))
        first_gen = [True]
        ckw = compact_kwargs(
            cfg,
            n_shards=mesh_size,
            params=policy.parameter_count,
            mesh_label=mesh_label_of(mesh_1d),
        )

        def gen(state, key, stats):
            k1, k2 = jax.random.split(key)
            values = ask_jit(k1, state)
            result, per_shard_steps = run_vectorized_rollout_compacting_sharded(
                env, policy, values, k2, stats,
                mesh=mesh_1d,
                num_episodes=1,
                episode_length=episode_length,
                compute_dtype=compute_dtype,
                **ckw,
                # compile the full width-descent chain during the warmup
                # generation so no compile lands in the timed loop
                prewarm=first_gen[0],
                return_per_shard_steps=True,
            )
            first_gen[0] = False
            state = tell_jit(state, values, result.scores)
            return state, result.stats, per_shard_steps, result.scores

        return gen, None

    builders = {
        "gspmd": build_gspmd,
        "shard_map": build_shard_map,
        "host_compact": build_host_compact,
    }

    key = jax.random.key(0)
    runs = {}  # variant -> mutable harness state
    for name in variants:
        gen, capture_target = builders[name]()
        state = fresh_pgpe_state(policy.parameter_count)
        # TWO warmup generations: the first compiles for the fresh
        # (uncommitted) state layout, and — under GSPMD donation — returns a
        # state committed to the compiler's chosen sharding, which the second
        # call compiles the steady-state program for. Timing starts only once
        # the layouts have reached their fixed point (the retrace sentinel
        # keeps this honest: steady_compiles must stay 0).
        stats = stats0
        for _ in range(2):
            key, sub = jax.random.split(key)
            state, stats, per_shard, scores = gen(state, sub, stats)
            jax.block_until_ready(scores)
        print(
            f"[{name}] compiled; warmup per-shard steps="
            f"{np.asarray(per_shard).tolist()}",
            file=sys.stderr,
        )
        runs[name] = {
            "gen": gen,
            "capture": capture_target,
            "state": state,
            "stats": stats,
            "shard_steps": np.zeros(np.asarray(per_shard).shape[0], dtype=np.int64),
            "samples": [],  # steps/s per timed sample
            "total_steps": 0,
            "scores": scores,
        }

    # program ledger (BENCH_LEDGER, like bench.py): AOT-capture each
    # variant's whole-generation program — compile wall-time, FLOPs, peak
    # memory, runtime donation verification — outside the timed loop. The
    # compact path is host-orchestrated (no single program): columns null.
    records = {}
    if cfg["ledger"]:
        from evotorch_tpu.observability import ledger as program_ledger
        from evotorch_tpu.observability.programs import abstract_like

        for name, run in runs.items():
            if run["capture"] is None:
                continue
            records[name] = program_ledger.capture(
                f"bench_multichip.generation[{eval_mode}][{name}]",
                run["capture"],
                abstract_like(fresh_pgpe_state(policy.parameter_count)),
                jax.random.key(0),
                abstract_like(stats0),
                shape={
                    "env": cfg["env_name"],
                    "popsize": popsize,
                    "episode_length": episode_length,
                    "mesh": mesh_label_of(mesh),
                    "spmd": name,
                },
            )

    # timed samples, INTERLEAVED across variants (±20% run-to-run on this
    # box: back-to-back blocks would hand one variant the quiet half)
    repeats = int(os.environ.get("BENCH_AB_REPEATS", "3")) if spmd == "ab" else 1
    steady_compiles = 0
    for _ in range(repeats):
        for name in variants:
            run = runs[name]
            gen = run["gen"]
            state, stats = run["state"], run["stats"]
            with track_compiles() as compile_log:
                t0 = time.perf_counter()
                sample_steps = 0
                for _ in range(generations):
                    key, sub = jax.random.split(key)
                    state, stats, per_shard, scores = gen(state, sub, stats)
                    jax.block_until_ready(scores)
                    run["shard_steps"] += np.asarray(per_shard)
                    sample_steps += int(np.sum(np.asarray(per_shard)))
                elapsed = time.perf_counter() - t0
            steady_compiles += compile_log.count
            if compile_log.count:
                print(
                    f"[{name}] STEADY-STATE COMPILES: {compile_log.names}",
                    file=sys.stderr,
                )
            run.update(state=state, stats=stats, scores=scores)
            run["total_steps"] += sample_steps
            run["samples"].append(sample_steps / elapsed)

    medians = {name: statistics.median(run["samples"]) for name, run in runs.items()}
    for name, run in runs.items():
        print(
            f"[{name}] {repeats}x{generations} generations, "
            f"{run['total_steps']} env-steps; median "
            f"{medians[name]:.0f} steps/s; mean score "
            f"{float(jnp.mean(run['scores'])):.3f}; per-shard steps "
            f"{run['shard_steps'].tolist()}",
            file=sys.stderr,
        )

    span_ab = {}
    if cfg["span"] is not None and "gspmd" in variants:
        # BENCH_SPAN on the sharded path: K generations of the SAME GSPMD
        # recipe scanned into one donated program (parallel.make_training_span
        # at THIS mesh) vs the per-generation program dispatched K times from
        # the host loop — interleaved median-of-N samples of one span each.
        # Absent for episodes_compact (host-orchestrated, cannot be fused)
        # and for the legacy shard_map-only runs.
        from bench_common import tuned_span
        from evotorch_tpu.parallel import make_training_span

        span_k, span_src = tuned_span(
            cfg, params=policy.parameter_count, mesh_label=mesh_label_of(mesh)
        )
        ask_fn, tell_fn, rkw = gspmd_recipe()
        span_fn = make_training_span(
            env,
            policy,
            ask=ask_fn,
            tell=tell_fn,
            popsize=popsize,
            span=span_k,
            mesh=mesh,
            num_episodes=1,
            episode_length=episode_length,
            compute_dtype=compute_dtype,
            eval_mode=eval_mode,
            **rkw,
        )
        # two warmups (fresh layout, then the steady-state layout-committed
        # program under donation); the hostloop leg reuses the gspmd
        # generation already at ITS layout fixed point from the loop above
        sp_state, sp_stats = fresh_pgpe_state(policy.parameter_count), stats0
        for _ in range(2):
            key, sub = jax.random.split(key)
            sp_state, scores, sp_stats, steps, _ = span_fn(
                sp_state, jax.random.split(sub, span_k), sp_stats
            )
            jax.block_until_ready(scores)
        host_gen = runs["gspmd"]["gen"]
        hl_state, hl_stats = runs["gspmd"]["state"], runs["gspmd"]["stats"]
        span_samples = {"hostloop": [], "span": []}
        for _ in range(cfg["span_ab_repeats"]):
            with track_compiles() as compile_log:
                t0 = time.perf_counter()
                sample_steps = 0
                for _ in range(span_k):
                    key, sub = jax.random.split(key)
                    hl_state, hl_stats, per_shard, scores = host_gen(
                        hl_state, sub, hl_stats
                    )
                    jax.block_until_ready(scores)
                    sample_steps += int(np.sum(np.asarray(per_shard)))
                span_samples["hostloop"].append(
                    sample_steps / (time.perf_counter() - t0)
                )
            steady_compiles += compile_log.count
            with track_compiles() as compile_log:
                t0 = time.perf_counter()
                key, sub = jax.random.split(key)
                sp_state, scores, sp_stats, steps, _ = span_fn(
                    sp_state, jax.random.split(sub, span_k), sp_stats
                )
                jax.block_until_ready(scores)
                span_samples["span"].append(
                    int(np.sum(np.asarray(steps))) / (time.perf_counter() - t0)
                )
            steady_compiles += compile_log.count
        med_hl = statistics.median(span_samples["hostloop"])
        med_sp = statistics.median(span_samples["span"])
        print(
            f"[span_ab/{eval_mode}] span={span_k}, "
            f"{cfg['span_ab_repeats']} interleaved samples: hostloop "
            f"{med_hl:.0f} vs span {med_sp:.0f} steps/s "
            f"({med_sp / med_hl:.2f}x)",
            file=sys.stderr,
        )
        span_ab = {
            "span": span_k,
            "span_speedup": round(med_sp / med_hl, 3),
            "span_value": round(med_sp, 1),
            "hostloop_value": round(med_hl, 1),
        }
        if cfg["tuned"]:
            span_ab["span_config_source"] = span_src

    primary = variants[0]
    steps_per_sec = medians[primary]
    record = records.get(primary)
    ledger_cols = {}
    if cfg["ledger"]:
        ledger_cols = (
            ledger_columns(
                record,
                steps_per_sec=steps_per_sec,
                steps_per_generation=runs[primary]["total_steps"]
                / (repeats * generations),
                param_count=policy.parameter_count,
            )
            if record is not None
            else {
                "compile_seconds": None,
                "flops_per_step": None,
                "peak_hbm_bytes": None,
                "model_efficiency": None,
            }
        )
        # runtime-verified donation of the donated evolution state: True
        # iff every donate_argnums buffer was actually aliased by XLA
        ledger_cols["donation_verified"] = (
            (not record.donation.missing) if record is not None
            and record.donation is not None else None
        )

    line = {
        "metric": "pgpe_sharded_rollout_env_steps_per_sec",
        "value": round(steps_per_sec, 1),
        "unit": "env_steps/sec",
        "vs_baseline": round(steps_per_sec / 1_000_000, 4),
        **ledger_cols,
        "spmd": primary,
        "steady_compiles": steady_compiles,
        "mesh": {name: int(size) for name, size in mesh.shape.items()},
        "mesh_label": mesh_label_of(mesh),
        "per_shard_steps": runs[primary]["shard_steps"].tolist(),
        "env": cfg["env_name"],
        "popsize": popsize,
        "episode_length": episode_length,
        "eval_mode": eval_mode,
        "compute_dtype": str(compute_dtype.__name__ if compute_dtype else "float32"),
        "backend": "cpu-mesh" if use_cpu else "tpu",
    }
    if cfg["tuned"] and eval_mode == "episodes_refill" and refill_src is not None:
        line["tuned_config_source"] = refill_src
    if trunk_delta:
        # BENCH_TRUNK_DELTA=1 only (default line stays byte-compatible)
        line["policy_form"] = "trunk_delta"
        line["trunk_rank"] = trunk_cfg["rank"]
        line["trunk_block"] = trunk_cfg["trunk_block"]
        if cfg["tuned"]:
            line["trunk_config_source"] = trunk_src
    if span_ab:
        # BENCH_SPAN only (default line stays byte-compatible)
        line.update(span_ab)
    if spmd == "ab":
        line["spmd_speedup"] = round(medians["gspmd"] / medians["shard_map"], 3)
        line["shard_map_value"] = round(medians["shard_map"], 1)
        line["ab_samples"] = {
            name: [round(s, 1) for s in run["samples"]] for name, run in runs.items()
        }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
