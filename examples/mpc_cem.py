"""Model-predictive control with functional CEM
(reference Model_Predictive_Control_with_CEM notebooks).

At each control step, CEM plans a short action sequence against the known
(differentiable, jitted) Pendulum dynamics, executes the first action, and
replans — the whole planner is one jitted function.
"""

from _common import setup_platform

args = setup_platform()

import jax
import jax.numpy as jnp

from evotorch_tpu.algorithms.functional import cem, cem_ask, cem_tell
from evotorch_tpu.envs import Pendulum

HORIZON = 15
PLAN_ITERS = 8
POP = 100


def main():
    env = Pendulum()

    def plan_cost(env_state, action_seqs):
        # action_seqs: (N, HORIZON)
        def rollout(seq):
            def step(carry, a):
                state = carry
                state, _obs, reward, _done = env.step(state, a[None])
                return state, reward

            _, rewards = jax.lax.scan(step, env_state, seq)
            return -jnp.sum(rewards)

        return jax.vmap(rollout)(action_seqs)

    @jax.jit
    def plan(env_state, key):
        state = cem(
            center_init=jnp.zeros(HORIZON),
            parenthood_ratio=0.2,
            objective_sense="min",
            stdev_init=1.0,
        )

        def iteration(state, key):
            seqs = cem_ask(key, state, popsize=POP)
            costs = plan_cost(env_state, jnp.clip(seqs, -2.0, 2.0))
            return cem_tell(state, seqs, costs), None

        state, _ = jax.lax.scan(iteration, state, jax.random.split(key, PLAN_ITERS))
        return jnp.clip(state.center[0], -2.0, 2.0)

    key = jax.random.key(0)
    env_state, obs = env.reset(key)
    total = 0.0
    for t in range(args.generations or 100):
        key, sub = jax.random.split(key)
        action = plan(env_state, sub)
        env_state, obs, reward, done = env.step(env_state, action[None])
        total += float(reward)
    print("total reward over horizon:", round(total, 2))


if __name__ == "__main__":
    main()
