"""Multi-objective optimization (reference examples/scripts/moo_parallel.py).

The Kursawe function with a GA whose elitist selection is NSGA-II-style
pareto + crowding. Instead of Ray actors, evaluation can be sharded over the
device mesh with problem.use_sharded_evaluation().
"""

from _common import setup_platform

args = setup_platform()

import jax.numpy as jnp
import numpy as np

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms import GeneticAlgorithm
from evotorch_tpu.operators.real import GaussianMutation, SimulatedBinaryCrossOver


@vectorized
def kursawe(x):
    f1 = jnp.sum(
        -10 * jnp.exp(-0.2 * jnp.sqrt(x[:, :-1] ** 2 + x[:, 1:] ** 2)), axis=-1
    )
    f2 = jnp.sum(jnp.abs(x) ** 0.8 + 5 * jnp.sin(x**3), axis=-1)
    return jnp.stack([f1, f2], axis=1)


def main():
    problem = Problem(["min", "min"], kursawe, solution_length=3, initial_bounds=(-5.0, 5.0), seed=0)
    problem.use_sharded_evaluation()
    ga = GeneticAlgorithm(
        problem,
        operators=[
            SimulatedBinaryCrossOver(problem, tournament_size=4, eta=8.0),
            GaussianMutation(problem, stdev=0.03),
        ],
        popsize=64,
    )
    ga.run(args.generations or 100)
    fronts = ga.population.arg_pareto_sort()
    front0 = ga.population.evals[np.asarray(fronts[0])]
    print(f"pareto front size: {len(fronts[0])}")
    print("front objective ranges:", np.asarray(front0).min(0), np.asarray(front0).max(0))


if __name__ == "__main__":
    main()
