"""Feature-space illumination with MAP-Elites
(reference Feature_Space_Illumination_with_MAPElites.ipynb).

Fitness: Rastrigin; features: the first two decision variables. The archive
keeps the best solution per feature cell.
"""

from _common import setup_platform

args = setup_platform()

import jax.numpy as jnp
import numpy as np

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms import MAPElites
from evotorch_tpu.operators.real import GaussianMutation


@vectorized
def rastrigin_with_features(x):
    fitness = 10 * x.shape[-1] + jnp.sum(x**2 - 10 * jnp.cos(2 * jnp.pi * x), axis=-1)
    features = x[:, :2]
    return fitness[:, None], features


def main():
    problem = Problem(
        "min",
        rastrigin_with_features,
        solution_length=6,
        initial_bounds=(-5.12, 5.12),
        eval_data_length=2,
        seed=0,
    )
    grid = MAPElites.make_feature_grid([-5.12, -5.12], [5.12, 5.12], num_bins=[8, 8])
    searcher = MAPElites(problem, operators=[GaussianMutation(problem, stdev=0.5)], feature_grid=grid)
    searcher.run(args.generations or 50)
    filled = np.asarray(searcher.filled)
    print(f"archive cells filled: {filled.sum()}/{len(filled)}")
    best = float(np.nanmin(np.asarray(searcher.population.evals[:, 0])[filled]))
    print("best fitness in archive:", best)


if __name__ == "__main__":
    main()
