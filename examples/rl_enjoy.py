"""Run a saved RL solution (reference examples/scripts/rl_enjoy.py)."""

import argparse
import pickle

from _common import setup_platform

args = setup_platform()
_parser = argparse.ArgumentParser()
_parser.add_argument("--solution", default="rl_clipup_solution.pkl")
_extra, _ = _parser.parse_known_args()

import jax.numpy as jnp

from evotorch_tpu.neuroevolution import VecNE


def main():
    fname = _extra.solution
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
    )
    batch = problem.generate_batch(1)
    batch.set_values(jnp.asarray(payload["values"])[None, :])
    problem.evaluate(batch)
    print("episodic return:", float(batch.evals[0, 0]))


if __name__ == "__main__":
    main()
