"""Sustained learning-curve runner for the locomotion envs.

Produces the evidence a reference user recognizes (VERDICT r3 #7): a long
PGPE run whose per-generation population stats AND periodic center
evaluations are appended to a JSONL file. For envs with an alive bonus
(Humanoid), the center is additionally evaluated on a zero-bonus copy of the
env, so the report separates actual locomotion (velocity - ctrl cost) from
the survival plateau. HalfCheetah has no alive bonus at all (reward =
forward velocity - ctrl cost, ``envs/halfcheetah.py``), so any sustained
improvement there is real forward progress by construction.

Recipe follows the reference's ClipUp configurations
(reference ``examples/scripts/rl_clipup.py:170-206``).

    python locomotion_curve.py --env halfcheetah --cpu \
        --popsize 256 --generations 250 --out halfcheetah_curve.jsonl
"""

import argparse
import json
import os
import sys
import time

# run from anywhere: the package lives one directory up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--env", default="halfcheetah")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--popsize", type=int, default=256)
    p.add_argument("--generations", type=int, default=250)
    p.add_argument("--episode-length", type=int, default=250)
    p.add_argument("--eval-every", type=int, default=10)
    p.add_argument("--eval-episodes", type=int, default=8)
    p.add_argument("--bf16", action="store_true")
    # ClipUp recipe (reference rl_clipup.py:110-114): lr = 0.75 * max_speed,
    # radius_init = 15 * max_speed; pass --center-lr / --radius-init to
    # override the derivation
    p.add_argument("--max-speed", type=float, default=0.12)
    p.add_argument("--center-lr", type=float, default=None)
    p.add_argument("--radius-init", type=float, default=None)
    p.add_argument("--stdev-lr", type=float, default=0.1)
    # the flagship-recipe knobs (reference rl_clipup.py:184-206): subtract
    # the per-step alive bonus from the SEARCH signal so standing still
    # isn't a local optimum ("auto" = the env's own alive_bonus), and grow
    # the population adaptively under an interaction budget
    p.add_argument("--decrease-rewards-by", default=None,
                   help="per-step reward decrement; 'auto' = env.alive_bonus")
    p.add_argument("--num-interactions", type=int, default=None)
    p.add_argument("--popsize-max", type=int, default=None)
    p.add_argument("--lowrank-rank", type=int, default=None)
    p.add_argument("--network", default=None,
                   help="policy DSL; default: 2x64-tanh MLP")
    p.add_argument("--out", default=None)
    p.add_argument("--seed", type=int, default=0)
    # fused training spans (docs/sharding.md "Fused multi-generation
    # training spans"): K generations of the SAME ClipUp recipe — run
    # through the functional PGPE state — scanned into ONE donated device
    # program per block (VecNE.make_training_span); the per-generation JSONL
    # rows are reconstructed host-side from the program's stacked outputs,
    # so the curve schema matches the host-loop path. Per-generation PRNG
    # keys derive from the ABSOLUTE generation index, so checkpoint resume
    # replays the exact uninterrupted trajectory.
    p.add_argument("--span", type=int, default=None,
                   help="fuse K generations per device dispatch; "
                        "--checkpoint-every rounds UP to the next span "
                        "boundary (the program only yields between blocks)")
    # durable checkpoint/resume (resilience.RunCheckpointer,
    # docs/resilience.md): with --checkpoint-dir the run saves a bundle
    # every --checkpoint-every generations and AUTO-RESUMES from the newest
    # valid bundle on restart — a SIGKILL costs at most one interval, and
    # the resumed trajectory is bit-identical to the uninterrupted one
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--checkpoint-keep", type=int, default=3)
    p.add_argument("--no-resume", action="store_true",
                   help="ignore existing bundles; start fresh (still saves)")
    return p.parse_args()


def span_checkpoint_every(every: int, span: int) -> int:
    """``--checkpoint-every`` aligned to fused-span boundaries: the scanned
    program only hands control back between K-generation blocks, so the
    cadence rounds UP to the next multiple of ``span`` (never down — down
    would checkpoint MORE often than asked). With the cadence a span
    multiple, ``maybe_save`` fires exactly at block ends and resume restarts
    on a block boundary — the resumed trajectory stays bit-identical."""
    return -(-int(every) // int(span)) * int(span)


def main():
    args = parse_args()
    if args.cpu:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        # first-device-use watchdog (docs/resilience.md): when the
        # accelerator tunnel is down, jax's first backend use hangs forever;
        # turn that into an actionable error before hours of curve are at
        # stake (EVOTORCH_DEVICE_TIMEOUT overrides the 60s deadline)
        from evotorch_tpu.resilience import probe_devices

        probe_devices()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution import VecNE
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    if args.span and (
        args.num_interactions or args.popsize_max or args.lowrank_rank
    ):
        raise SystemExit(
            "--span fuses a fixed-shape program; the adaptive "
            "--num-interactions/--popsize-max knobs and --lowrank-rank need "
            "the per-generation host loop"
        )

    out_path = args.out or f"{args.env}_curve.jsonl"
    compute_dtype = jnp.bfloat16 if args.bf16 else None
    center_lr = args.center_lr if args.center_lr is not None else 0.75 * args.max_speed
    radius_init = args.radius_init if args.radius_init is not None else 15 * args.max_speed

    decrease = args.decrease_rewards_by
    if decrease == "auto":
        decrease = float(getattr(make_env(args.env), "alive_bonus", 0.0)) or None
    elif decrease is not None:
        decrease = float(decrease)

    problem = VecNE(
        args.env,
        args.network
        or "Linear(obs_length, 64) >> Tanh() >> Linear(64, 64) >> Tanh()"
        " >> Linear(64, act_length)",
        observation_normalization=True,
        episode_length=args.episode_length,
        eval_mode="episodes",
        compute_dtype=compute_dtype,
        decrease_rewards_by=decrease,
        seed=args.seed,
    )
    searcher = None
    if not args.span:
        searcher = PGPE(
            problem,
            popsize=args.popsize,
            center_learning_rate=center_lr,
            stdev_learning_rate=args.stdev_lr,
            radius_init=radius_init,
            optimizer="clipup",
            optimizer_config={"max_speed": args.max_speed},
            ranking_method="centered",
            num_interactions=args.num_interactions,
            popsize_max=args.popsize_max,
            lowrank_rank=args.lowrank_rank,
        )

    # search-health watchdog (docs/observability.md "Search health"):
    # variance-gated plateau detection on the on-device score statistics
    # plus stdev-collapse vs the run's own starting spread; verdicts ride
    # the MetricsHub stream only (the curve JSONL stays byte-compatible)
    from evotorch_tpu.observability import Rule, SLOWatchdog

    watchdog = SLOWatchdog(
        [Rule("plateau", threshold=25), Rule("stdev_collapse", threshold=0.01)]
    )

    # durable resume: restore the whole searcher (functional state + PRNG
    # chain + obs-norm stats + counters ride inside its pickle) from the
    # newest valid bundle, then continue from the next generation appending
    # to the same JSONL — bit-identical to the run that was never killed
    ckpt = None
    start_gen = 1
    span_resume = None
    if args.checkpoint_dir:
        from evotorch_tpu.resilience import RunCheckpointer

        every = args.checkpoint_every
        if args.span:
            # the fused program only yields between K-generation blocks:
            # round the cadence UP to the next span boundary (documented on
            # the --span flag) so maybe_save fires exactly at block ends
            every = span_checkpoint_every(every, args.span)
        ckpt = RunCheckpointer(
            args.checkpoint_dir,
            keep=args.checkpoint_keep,
            every=every,
        )
        if not args.no_resume:
            loaded = ckpt.load_latest()
            if loaded is not None:
                gen_done, state = loaded
                if args.span:
                    # functional-state bundle; rehydrated in the span loop
                    span_resume = state
                else:
                    searcher = state["searcher"]
                    problem = searcher.problem
                start_gen = gen_done + 1
                # the bundle carries the health-detector window state, so
                # the resumed run's verdict timing is bit-identical to the
                # uninterrupted one (old bundles without it start fresh)
                if state.get("health"):
                    watchdog.load_state_dict(state["health"])
                print(
                    json.dumps({"resumed_from_generation": gen_done}),
                    flush=True,
                )

    # center-evaluation envs: the full reward, and (when the env pays an
    # alive bonus) a zero-bonus copy so the velocity term reports separately
    eval_env = problem.env
    try:
        nobonus_env = (
            make_env(args.env, alive_bonus=0.0)
            if getattr(eval_env, "alive_bonus", 0.0) != 0.0
            else None
        )
    except TypeError:
        nobonus_env = None

    def eval_center(center, step_count):
        # numpy, not jnp: the replicated center goes straight into the
        # jitted rollout dispatch, and a numpy argument is ~3x cheaper per
        # dispatch than a committed device array (CLAUDE.md r7 note)
        batch = np.repeat(np.asarray(center)[None], args.eval_episodes, axis=0)
        stats = problem.obs_norm.stats
        outs = {}
        for name, env in (("full", eval_env), ("no_alive_bonus", nobonus_env)):
            if env is None:
                continue
            r = run_vectorized_rollout(
                env,
                problem._policy,
                batch,
                jax.random.fold_in(jax.random.key(args.seed + 1), step_count),
                stats,
                num_episodes=1,
                episode_length=args.episode_length,
                eval_mode="episodes",
                # the center trained on normalized observations and must be
                # evaluated on them too (the stats argument is ignored
                # without the flag); eval-time stat updates are discarded
                observation_normalization=True,
                compute_dtype=compute_dtype,
            )
            outs[name] = float(jnp.mean(r.scores))
        return outs

    # EVOTORCH_METRICS=path: stream every per-generation row (plus the
    # lag-by-one decoded per-group telemetry and the counter registry)
    # through the MetricsHub — JSONL with a schema-versioned manifest first
    # line, or Prometheus text with a .prom suffix (docs/observability.md)
    from evotorch_tpu.observability import MetricsHub

    hub = MetricsHub.from_env(
        manifest={
            "source": "locomotion_curve",
            "env": args.env,
            "popsize": args.popsize,
            "episode_length": args.episode_length,
        }
    )

    t_start = time.time()
    if args.span:
        # --span K: blocks of K generations fused into one donated device
        # program; the host fetches the stacked (scores, telemetry, health,
        # center) outputs ONCE per block and reconstructs the per-generation
        # rows from them. Telemetry decodes per ROW from the same fetched
        # wire, so occupancy stays per-generation accurate; the block's
        # compile-count delta lands on its first row (nonzero on a warm
        # block is a retrace, exactly like the host-loop column).
        from evotorch_tpu.algorithms.functional import (
            get_functional_optimizer,
            pgpe,
            pgpe_ask,
            pgpe_health,
            pgpe_tell,
        )
        from evotorch_tpu.observability import GroupTelemetry
        from evotorch_tpu.observability.registry import counters

        span = int(args.span)
        state = pgpe(
            center_init=jnp.zeros(
                problem._policy.parameter_count, dtype=jnp.float32
            ),
            center_learning_rate=center_lr,
            stdev_learning_rate=args.stdev_lr,
            objective_sense="max",
            radius_init=radius_init,
            optimizer="clipup",
            optimizer_config={"max_speed": args.max_speed},
            ranking_method="centered",
        )
        best_eval = None
        if span_resume is not None:
            state = jax.tree_util.tree_map(jnp.asarray, span_resume["state"])
            problem.obs_norm.stats = jax.tree_util.tree_map(
                jnp.asarray, span_resume["obs_stats"]
            )
            problem._interaction_count = int(span_resume["interactions"])
            problem._episode_count = int(span_resume["episodes"])
            best_eval = span_resume.get("best_eval")

        def metrics_fn(s):
            # stdev/velocity norms AND the post-tell center of every
            # generation ride the scan ys, so the periodic center
            # evaluations need no extra device round trips
            m = dict(pgpe_health(s))
            m["center"] = get_functional_optimizer(s.optimizer)[1](
                s.optimizer_state
            )
            return m

        programs = {}

        def span_program(length):
            # one compile per distinct block length: every full block is
            # `span`; only a trailing remainder block compiles a second form
            if length not in programs:
                programs[length] = problem.make_training_span(
                    ask=lambda k, s: pgpe_ask(k, s, popsize=args.popsize),
                    tell=pgpe_tell,
                    popsize=args.popsize,
                    span=length,
                    state_metrics=metrics_fn,
                )
            return programs[length]

        base_key = jax.random.key(args.seed)
        centers_np = None
        with open(out_path, "a") as f:
            gen = start_gen
            while gen <= args.generations:
                length = min(span, args.generations - gen + 1)
                fn = span_program(length)
                # ABSOLUTE generation indices fold into the keys: a resumed
                # run regenerates the identical per-generation randomness
                keys = jax.vmap(lambda g: jax.random.fold_in(base_key, g))(
                    jnp.arange(gen, gen + length)
                )
                meters = counters.snapshot(("compiles",))
                result = fn(state, keys, problem.obs_norm.stats)
                state, scores, _stats, _steps, telemetry, health = result
                problem.consume_span(result[:5])
                block_compiles = counters.delta(meters)["compiles"]
                scores_np = np.asarray(scores)
                health_np = {k: np.asarray(v) for k, v in health.items()}
                centers_np = health_np.pop("center")
                telemetry_np = (
                    np.asarray(telemetry)
                    if telemetry is not None and telemetry.size
                    else None
                )
                for i in range(length):
                    g = gen + i
                    row_scores = scores_np[i]
                    gen_best = float(row_scores.max())
                    best_eval = (
                        gen_best
                        if best_eval is None
                        else max(best_eval, gen_best)
                    )
                    gt = (
                        GroupTelemetry.from_array(telemetry_np[i])
                        if telemetry_np is not None
                        else None
                    )
                    dec = gt.total() if gt is not None else None
                    row = {
                        "gen": g,
                        "mean_eval": float(row_scores.mean()),
                        "best_eval": best_eval,
                        "stdev_norm": float(health_np["stdev_norm"][i]),
                        "elapsed_s": round(time.time() - t_start, 1),
                        "occupancy": (
                            round(dec.occupancy, 4) if dec is not None else None
                        ),
                        "refill_events": (
                            dec.refill_events if dec is not None else None
                        ),
                        "steady_compiles": block_compiles if i == 0 else 0,
                    }
                    if "velocity_norm" in health_np:
                        row["clipup_velocity_norm"] = float(
                            health_np["velocity_norm"][i]
                        )
                    if g % args.eval_every == 0 or g == args.generations:
                        center_scores = eval_center(centers_np[i], g)
                        row["center_full"] = center_scores.get("full")
                        if "no_alive_bonus" in center_scores:
                            row["center_no_alive_bonus"] = center_scores[
                                "no_alive_bonus"
                            ]
                            row["center_bonus_term"] = (
                                center_scores["full"]
                                - center_scores["no_alive_bonus"]
                            )
                        print(json.dumps(row), flush=True)
                    f.write(json.dumps(row) + "\n")
                    f.flush()
                    report = watchdog.check(
                        gt, status={"stdev_norm": row["stdev_norm"]}
                    )
                    if hub is not None:
                        hub.emit({**row, **report.as_status()}, telemetry=gt)
                gen += length
                if ckpt is not None:
                    # save AFTER the block's rows are durably in the JSONL
                    # (same discipline as the host loop); the functional
                    # bundle carries everything a resume needs to replay
                    # the uninterrupted trajectory bit-identically
                    ckpt.maybe_save(
                        gen - 1,
                        {
                            "state": jax.tree_util.tree_map(np.asarray, state),
                            "obs_stats": jax.tree_util.tree_map(
                                np.asarray, problem.obs_norm.stats
                            ),
                            "interactions": int(problem._interaction_count),
                            "episodes": int(problem._episode_count),
                            "best_eval": best_eval,
                            "health": watchdog.state_dict(),
                        },
                    )
        print(
            json.dumps(
                {
                    "done": True,
                    "env": args.env,
                    "popsize": args.popsize,
                    "generations": args.generations,
                    "episode_length": args.episode_length,
                    "interactions": int(
                        problem.status["total_interaction_count"]
                    ),
                    "elapsed_s": round(time.time() - t_start, 1),
                    "final_center": eval_center(
                        centers_np[-1], args.generations
                    ),
                }
            ),
            flush=True,
        )
        return

    with open(out_path, "a") as f:
        for gen in range(start_gen, args.generations + 1):
            searcher.step()
            row = {
                "gen": gen,
                "mean_eval": float(searcher.status["mean_eval"]),
                "best_eval": float(searcher.status["best_eval"]),
                # plateau diagnostics (VERDICT r5 weak #4): a collapsing
                # stdev norm = premature convergence; a pinned ClipUp
                # velocity norm (== max_speed) = step-size ceiling — both
                # now published by the searcher itself (same values the
                # bespoke host-side norms here used to compute)
                "stdev_norm": searcher.status["stdev_norm"],
                "elapsed_s": round(time.time() - t_start, 1),
                # zero-sync eval telemetry (docs/observability.md): lane
                # occupancy + refill accounting of the previous generation's
                # evaluation, and this step's compile count from the always-on
                # registry — nonzero steady_compiles after gen 2 is a retrace
                "occupancy": searcher.status.get("eval_occupancy"),
                "refill_events": searcher.status.get("eval_refill_events"),
                "steady_compiles": searcher.status.get("compiles"),
            }
            velocity_norm = searcher.status.get("clipup_velocity_norm")
            if velocity_norm is not None:
                row["clipup_velocity_norm"] = velocity_norm
            if args.num_interactions is not None:
                row["popsize"] = int(searcher.status["popsize"])
            if args.lowrank_rank is not None:
                # subspace-exhaustion diagnostic (tools.lowrank.basis_capture):
                # persistently << 1 at a stalling rank (the rank-32 curve)
                row["basis_capture"] = searcher.status.get("basis_capture")
            if gen % args.eval_every == 0 or gen == args.generations:
                center_scores = eval_center(
                    searcher.status["center"], searcher.step_count
                )
                row["center_full"] = center_scores.get("full")
                if "no_alive_bonus" in center_scores:
                    # the velocity/bonus reward split: no_alive_bonus IS the
                    # velocity term (locomotion = velocity - ctrl cost); the
                    # bonus term is the survival plateau's share of the score
                    row["center_no_alive_bonus"] = center_scores["no_alive_bonus"]
                    row["center_bonus_term"] = (
                        center_scores["full"] - center_scores["no_alive_bonus"]
                    )
                print(json.dumps(row), flush=True)
            f.write(json.dumps(row) + "\n")
            f.flush()
            # health verdicts: plateau on the on-device score statistics
            # (lag-by-one telemetry) + stdev collapse vs the first-seen
            # baseline; surfaced on the hub stream, never in the curve row
            report = watchdog.check(
                problem.last_group_telemetry,
                status={"stdev_norm": row["stdev_norm"]},
            )
            if hub is not None:
                hub.emit(
                    {**row, **report.as_status()},
                    telemetry=problem.last_group_telemetry,
                )
            if ckpt is not None:
                # save AFTER the row is durably in the JSONL so a resume
                # never replays an already-written generation; the bundle
                # carries the health-detector window state alongside
                ckpt.maybe_save(
                    gen,
                    {"searcher": searcher, "health": watchdog.state_dict()},
                )
    print(
        json.dumps(
            {
                "done": True,
                "env": args.env,
                "popsize": args.popsize,
                "generations": args.generations,
                "episode_length": args.episode_length,
                "interactions": int(problem.status["total_interaction_count"]),
                "elapsed_s": round(time.time() - t_start, 1),
                "final_center": eval_center(
                    searcher.status["center"], searcher.step_count
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
