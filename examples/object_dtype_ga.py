"""Variable-length (object-dtype) evolution
(reference Evolving_Objects.ipynb / Genetic_Programming.ipynb territory).

Solutions are integer sequences of varying length; fitness rewards sequences
that sum close to a target while staying short. Object-dtype populations live
host-side (SURVEY.md §7): this path exists for problems that cannot be
expressed as fixed-shape arrays.
"""

from _common import setup_platform

args = setup_platform()

import numpy as np

from evotorch_tpu import Problem
from evotorch_tpu.algorithms import GeneticAlgorithm
from evotorch_tpu.operators.base import CopyingOperator
from evotorch_tpu.operators.sequence import CutAndSplice
from evotorch_tpu.core import SolutionBatch
from evotorch_tpu.tools import ObjectArray

TARGET = 42


class SequenceProblem(Problem):
    def __init__(self):
        super().__init__("max", dtype=object, seed=0)
        self._rng = np.random.default_rng(0)

    def _fill(self, n, key):
        arr = ObjectArray(n)
        for i in range(n):
            length = int(self._rng.integers(1, 8))
            arr[i] = [int(v) for v in self._rng.integers(0, 10, size=length)]
        return arr

    def _evaluate(self, solution):
        seq = list(solution.values)
        fitness = -abs(sum(seq) - TARGET) - 0.1 * len(seq)
        solution.set_evals(float(fitness))


class SequenceMutation(CopyingOperator):
    def __init__(self, problem):
        super().__init__(problem)
        self._rng = np.random.default_rng(1)

    def _do(self, batch):
        result = SolutionBatch(self._problem, len(batch), empty=True)
        for i in range(len(batch)):
            seq = list(batch[i].values)
            roll = self._rng.random()
            if roll < 0.3 and len(seq) > 1:
                seq.pop(int(self._rng.integers(len(seq))))
            elif roll < 0.6:
                seq.insert(int(self._rng.integers(len(seq) + 1)), int(self._rng.integers(0, 10)))
            elif seq:
                seq[int(self._rng.integers(len(seq)))] = int(self._rng.integers(0, 10))
            result[i].set_values(seq)
        return result


def main():
    problem = SequenceProblem()
    ga = GeneticAlgorithm(
        problem,
        operators=[CutAndSplice(problem, tournament_size=3), SequenceMutation(problem)],
        popsize=32,
    )
    ga.run(args.generations or 40)
    best = ga.status["best"]
    print("best sequence:", list(best.values), "fitness:", round(float(ga.status["best_eval"]), 2))


if __name__ == "__main__":
    main()
