"""PGPE + ClipUp on vectorized RL (reference examples/scripts/rl_clipup.py).

The Toklu et al. (2020) configuration style: PGPE with 0-centered ranking and
ClipUp, evaluated on a fully-jitted vectorized environment. The reference
fans evaluation out over Ray CPU actors; here one SPMD program rolls out the
whole population on device.
"""

from _common import setup_platform

args = setup_platform()

from evotorch_tpu.algorithms import PGPE
from evotorch_tpu.logging import PandasLogger, StdOutLogger
from evotorch_tpu.neuroevolution import VecNE


def main():
    problem = VecNE(
        "cartpole",
        "Linear(obs_length, act_length)",
        env_config={"continuous_actions": False},
        seed=42,
    )
    searcher = PGPE(
        problem,
        popsize=200,
        center_learning_rate=0.5,
        stdev_learning_rate=0.1,
        stdev_init=0.5,
        optimizer="clipup",
        optimizer_config={"max_speed": 1.0},
        ranking_method="centered",
    )
    StdOutLogger(searcher, interval=5)
    pandas_logger = PandasLogger(searcher)
    searcher.run(args.generations or 30)

    center = searcher.status["center"]
    problem.save_solution(center, "rl_clipup_solution.pkl")
    print(pandas_logger.to_dataframe()[["mean_eval", "pop_best_eval"]].tail())
    print("saved solution to rl_clipup_solution.pkl")


if __name__ == "__main__":
    main()
