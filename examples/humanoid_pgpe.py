"""Flagship workload: PGPE on the pure-JAX Humanoid (17 actuated DOF).

The north-star configuration (BASELINE.md / reference Brax-Humanoid recipe,
``examples/scripts/rl_clipup.py:198-206`` style): PGPE + ClipUp + centered
ranking at popsize 10k, the whole generation (ask -> 10k parallel 200-step
rollouts with contact dynamics -> tell) compiled as one program. On a single
TPU v5e chip this sustains >2M env-steps/s with ``compute_dtype=bfloat16``;
run with ``--cpu`` for a scaled-down smoke version on the host.
"""

import jax.numpy as jnp

from _common import setup_platform

args = setup_platform()

from evotorch_tpu.algorithms import PGPE
from evotorch_tpu.logging import StdOutLogger
from evotorch_tpu.neuroevolution import VecNE


def main():
    on_cpu = bool(args.cpu)
    problem = VecNE(
        "humanoid",
        "Linear(obs_length, 64) >> Tanh() >> Linear(64, 64) >> Tanh()"
        " >> Linear(64, act_length)",
        observation_normalization=True,
        episode_length=50 if on_cpu else 200,
        eval_mode="budget",  # every lane spends its full interaction budget
        compute_dtype=None if on_cpu else jnp.bfloat16,
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=256 if on_cpu else 10_000,
        center_learning_rate=0.06,
        stdev_learning_rate=0.1,
        radius_init=0.27,
        optimizer="clipup",
        optimizer_config={"max_speed": 0.12},
        ranking_method="centered",
    )
    StdOutLogger(searcher, interval=1 if on_cpu else 10)
    searcher.run(args.generations or (3 if on_cpu else 100))

    problem.save_solution(searcher.status["center"], "humanoid_center.pkl")
    print(
        f"best_eval={float(searcher.status['best_eval']):.2f} "
        f"interactions={int(searcher.status['total_interaction_count'])}"
    )


if __name__ == "__main__":
    main()
