"""Learning-curve runner for REAL gymnasium MuJoCo envs.

The real-physics counterpart of ``locomotion_curve.py``: PGPE + ClipUp over a
``GymNE`` problem whose lanes are stepped by the batched MuJoCo engine
(``envs.mujoco.MjVecEnv`` over ``mujoco.rollout`` — one device forward + one
threaded physics call per timestep for the whole lane block). Appends one
JSONL row per generation (population stats + stdev norm + ClipUp velocity
norm) and a periodic deterministic center evaluation, so the curve grounds
the framework's locomotion claims in the canonical benchmark rather than the
bespoke rigid-body simulator.

Defaults are sized for a 1-core box (popsize <= 64):

    python mujoco_curve.py --env InvertedPendulum-v5 --popsize 48 \
        --generations 40 --episode-length 200 --out ip_curve.jsonl

    python mujoco_curve.py --env Hopper-v5 --popsize 64 --generations 200
"""

import argparse
import json
import os
import sys
import time

# run from anywhere: the package lives one directory up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true",
                   help="accepted for smoke-tier uniformity; this runner"
                   " always uses the CPU backend (host-physics workload)")
    p.add_argument("--env", default="InvertedPendulum-v5")
    p.add_argument("--popsize", type=int, default=48)
    p.add_argument("--generations", type=int, default=40)
    p.add_argument("--episode-length", type=int, default=200)
    p.add_argument("--num-envs", type=int, default=None,
                   help="lane-block width (default: popsize, capped at 64)")
    p.add_argument("--eval-every", type=int, default=5)
    p.add_argument("--eval-episodes", type=int, default=4)
    # ClipUp recipe (reference rl_clipup.py:110-114)
    p.add_argument("--max-speed", type=float, default=0.15)
    p.add_argument("--center-lr", type=float, default=None)
    p.add_argument("--radius-init", type=float, default=None)
    p.add_argument("--stdev-lr", type=float, default=0.1)
    p.add_argument("--network", default=None,
                   help="policy DSL; default: linear obs->act")
    p.add_argument("--backend", default="auto", choices=("auto", "mujoco", "sync"),
                   help="lane engine (auto = MjVecEnv for supported -v5 envs)")
    p.add_argument("--out", default=None)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def main():
    args = parse_args()
    # host-physics workload: the policy forward is tiny, so always run JAX on
    # CPU (the TPU tunnel must not gate a MuJoCo curve — CLAUDE.md)
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from evotorch_tpu.algorithms import PGPE
    from evotorch_tpu.neuroevolution import GymNE

    out_path = args.out or f"{args.env.lower().replace('-', '_')}_curve.jsonl"
    center_lr = args.center_lr if args.center_lr is not None else 0.75 * args.max_speed
    radius_init = args.radius_init if args.radius_init is not None else 15 * args.max_speed
    num_envs = args.num_envs if args.num_envs is not None else min(args.popsize, 64)

    problem = GymNE(
        args.env,
        args.network or "Linear(obs_length, act_length)",
        observation_normalization=True,
        episode_length=args.episode_length,
        num_envs=num_envs,
        vector_env_backend=args.backend,
        seed=args.seed,
    )
    searcher = PGPE(
        problem,
        popsize=args.popsize,
        center_learning_rate=center_lr,
        stdev_learning_rate=args.stdev_lr,
        radius_init=radius_init,
        optimizer="clipup",
        optimizer_config={"max_speed": args.max_speed},
        ranking_method="centered",
    )

    vec_env = problem._make_vector_env()
    t_start = time.time()
    with open(out_path, "a") as f:
        header = {
            "env": args.env,
            "backend": type(vec_env).__name__,
            "popsize": args.popsize,
            "num_envs": num_envs,
            "episode_length": args.episode_length,
            "network": args.network or "Linear(obs_length, act_length)",
            "seed": args.seed,
        }
        f.write(json.dumps(header) + "\n")
        for gen in range(1, args.generations + 1):
            searcher.step()
            opt = searcher.optimizer
            row = {
                "gen": gen,
                "mean_eval": float(searcher.status["mean_eval"]),
                "best_eval": float(searcher.status["best_eval"]),
                "stdev_norm": float(jnp.linalg.norm(searcher.status["stdev"])),
                "interactions": int(problem.status["total_interaction_count"]),
                "elapsed_s": round(time.time() - t_start, 1),
            }
            if hasattr(opt, "_velocity"):
                row["clipup_velocity_norm"] = float(jnp.linalg.norm(opt._velocity))
            if gen % args.eval_every == 0 or gen == args.generations:
                center = jnp.asarray(searcher.status["center"])
                row["center_eval"] = problem.run_solution(
                    center, num_episodes=args.eval_episodes
                )
                print(json.dumps(row), flush=True)
            f.write(json.dumps(row) + "\n")
            f.flush()
    print(
        json.dumps(
            {
                "done": True,
                **header,
                "generations": args.generations,
                "interactions": int(problem.status["total_interaction_count"]),
                "episodes": int(problem.status["total_episode_count"]),
                "elapsed_s": round(time.time() - t_start, 1),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
