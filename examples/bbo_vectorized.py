"""Vectorized black-box optimization (reference examples/scripts/bbo_vectorized.py).

SNES and CMA-ES on 100-dimensional Rastrigin, everything vectorized on device.
"""

from _common import setup_platform

args = setup_platform()

import jax.numpy as jnp

from evotorch_tpu import Problem, vectorized
from evotorch_tpu.algorithms import CMAES, SNES
from evotorch_tpu.logging import StdOutLogger


@vectorized
def rastrigin(x):
    return 10 * x.shape[-1] + jnp.sum(x**2 - 10 * jnp.cos(2 * jnp.pi * x), axis=-1)


def main():
    gens = args.generations or 300

    problem = Problem("min", rastrigin, solution_length=100, initial_bounds=(-5.12, 5.12), seed=1)
    searcher = SNES(problem, popsize=1000, stdev_init=10.0)
    StdOutLogger(searcher, interval=max(1, gens // 10))
    searcher.run(gens)
    print("SNES best:", searcher.status["best_eval"])

    problem = Problem("min", rastrigin, solution_length=100, initial_bounds=(-5.12, 5.12), seed=2)
    searcher = CMAES(problem, stdev_init=2.0, popsize=64, separable=True)
    searcher.run(gens)
    print("CMA-ES (separable) best:", searcher.status["best_eval"])


if __name__ == "__main__":
    main()
