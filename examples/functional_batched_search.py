"""Batched searches with the functional API (reference Functional_API notebooks).

A *population of searches*: 8 independent CEM searches with different
hyperparameters run as one jitted program (extra leftmost dims on the state =
batch dims). The scanned multi-generation program comes from
``make_search_span`` — the repo's one scanned-generations idiom, shared with
the program ledger's ``functional_batched_search`` gate capture.
"""

from _common import setup_platform

args = setup_platform()

from functools import partial

import jax
import jax.numpy as jnp

from evotorch_tpu.algorithms.functional import cem, cem_ask, cem_tell, make_search_span


def sphere(x):
    return jnp.sum(x**2, axis=-1)


def main():
    num_searches = 8
    # each lane gets its own starting point
    centers = jax.random.normal(jax.random.key(0), (num_searches, 20)) * 3.0
    state = cem(
        center_init=centers,
        parenthood_ratio=0.5,
        objective_sense="min",
        stdev_init=2.0,
        stdev_max_change=0.2,
    )

    run = make_search_span(
        sphere,
        ask=partial(cem_ask, popsize=50),
        tell=cem_tell,
        metrics=lambda pop, fit: jnp.min(fit, axis=-1),
    )
    keys = jax.random.split(jax.random.key(1), args.generations or 100)
    state, best_per_gen = run(state, keys)
    print("final best per search:", jnp.round(best_per_gen[-1], 4))


if __name__ == "__main__":
    main()
