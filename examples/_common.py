"""Shared example plumbing: platform selection."""

import argparse
import os
import sys

# run from anywhere: the package lives one directory up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_platform():
    parser = argparse.ArgumentParser()
    parser.add_argument("--cpu", action="store_true", help="force the CPU backend")
    parser.add_argument("--generations", type=int, default=None)
    args, _ = parser.parse_known_args()
    if args.cpu:
        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
    return args
