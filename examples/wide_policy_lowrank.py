"""Wide policies at full speed: factored (low-rank) populations.

The MXU cannot amortize weights across ES lanes when every lane carries its
own parameters — growing the policy 64x64 -> 256x256 costs ~3.4x throughput
on a v5e (BENCH_NOTES.md). ``PGPE(..., lowrank_rank=k)`` restructures the
perturbation instead of the hardware: the population is
``theta_i = center + B z_i`` with a shared per-generation basis, evaluated
with (k+1) large shared-weight matmuls, and the dense ``(N, L)`` population
matrix is never materialized (for this 256x256 policy at popsize 10k it
would be ~3.9 GB).

Run: ``python wide_policy_lowrank.py --cpu --generations 5`` (scaled-down)
or on the TPU at full scale with no flags.
"""

import jax.numpy as jnp

from _common import setup_platform

args = setup_platform()

from evotorch_tpu.algorithms import PGPE
from evotorch_tpu.logging import StdOutLogger
from evotorch_tpu.neuroevolution import VecNE
from evotorch_tpu.tools.lowrank import LowRankParamsBatch


def main():
    on_cpu = bool(args.cpu)
    problem = VecNE(
        "humanoid",
        # a WIDE policy: 256x256 hidden (≈98k parameters) — the regime where
        # the dense per-lane forward collapses MXU utilization
        "Linear(obs_length, 256) >> Tanh() >> Linear(256, 256) >> Tanh()"
        " >> Linear(256, act_length)",
        observation_normalization=True,
        episode_length=25 if on_cpu else 200,
        eval_mode="budget",
        compute_dtype=None if on_cpu else jnp.bfloat16,
        seed=0,
    )
    searcher = PGPE(
        problem,
        popsize=64 if on_cpu else 10_000,
        center_learning_rate=0.06,
        stdev_learning_rate=0.1,
        radius_init=0.27,
        optimizer="clipup",
        optimizer_config={"max_speed": 0.12},
        ranking_method="centered",
        lowrank_rank=32,  # the whole difference: factored perturbations
    )
    StdOutLogger(searcher, interval=1 if on_cpu else 10)
    searcher.run(args.generations or (2 if on_cpu else 50))

    pop = searcher.population
    assert isinstance(pop.values, LowRankParamsBatch)  # never densified
    print(
        f"population held factored: coeffs {pop.values.coeffs.shape} + "
        f"basis {pop.values.basis.shape} instead of a dense "
        f"({len(pop)}, {problem.solution_length}) matrix; "
        f"best_eval={float(searcher.status['best_eval']):.2f}"
    )


if __name__ == "__main__":
    main()
