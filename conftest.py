"""Root conftest: configure JAX for CPU-mesh testing BEFORE jax initializes.

The reference tests "distributed" code via Ray local mode (reference
tests/conftest.py:24-40); our analog is a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).
"""

import os
import sys

# Force CPU for tests even when the shell preconfigures a TPU platform
# (JAX_PLATFORMS=axon): tests need the virtual 8-device mesh and full-f32
# matmul numerics. Benchmarks (bench.py) run on the real chip instead.
os.environ["JAX_PLATFORMS"] = "cpu"

# The TPU ("axon") PJRT plugin is injected at interpreter startup via
# sitecustomize in /root/.axon_site (PYTHONPATH), which pins
# jax_platforms='axon' in jax's config BEFORE this conftest runs — so setting
# the env var alone is not enough, and initializing the axon backend can hang
# indefinitely when the device tunnel is unhealthy. Override the config
# directly; jax then only ever initializes the CPU backend.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
