"""Root conftest: configure JAX for CPU-mesh testing BEFORE jax initializes.

The reference tests "distributed" code via Ray local mode (reference
tests/conftest.py:24-40); our analog is a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).
"""

import os
import sys

# Force CPU for tests even when the shell preconfigures a TPU platform
# (JAX_PLATFORMS=axon): tests need the virtual 8-device mesh and full-f32
# matmul numerics. Benchmarks (bench.py) run on the real chip instead.
os.environ["JAX_PLATFORMS"] = "cpu"

# The TPU ("axon") PJRT plugin is injected at interpreter startup via
# sitecustomize in /root/.axon_site (PYTHONPATH), which pins
# jax_platforms='axon' in jax's config BEFORE this conftest runs — so setting
# the env var alone is not enough, and initializing the axon backend can hang
# indefinitely when the device tunnel is unhealthy. Override the config
# directly; jax then only ever initializes the CPU backend.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compile cache for the suite (EVOTORCH_TEST_COMPILE_CACHE=0
# opts out). The fast tier is compile-dominated on this 1-core box — the
# same GSPMD programs are rebuilt module after module, and the suite
# outgrew its tier-1 budget on compile time alone. Entries are keyed on
# HLO + compile options, so the 8-virtual-device test programs never
# collide with bench/TPU entries; the warm-process acceptance test
# (test_gspmd.py) runs in subprocesses with its own tmp dir and never
# sees this cache. Retrace sentinels count at the lowering layer, so a
# disk hit still registers as a compile and steady-state zero-counts are
# unaffected; the ledger gate bands flops/peak_bytes, not compile time
# (and its capture fixture bypasses the cache — deserialized executables
# report +1408 bytes of peak memory on this backend). One behavioral
# difference a warm run DOES have: a deserialized donated program may write
# outputs in place into the donated input buffer, so numpy VIEWS of
# to-be-donated arrays (np.asarray without .copy()) mutate — snapshot with
# an explicit copy (see test_trunk_delta.py's center_before).
if os.environ.get("EVOTORCH_TEST_COMPILE_CACHE", "1") != "0":
    from evotorch_tpu.observability import enable_persistent_cache

    enable_persistent_cache(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "compile_cache",
            "tests",
        ),
        xla_caches=False,
    )
