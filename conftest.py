"""Root conftest: configure JAX for CPU-mesh testing BEFORE jax initializes.

The reference tests "distributed" code via Ray local mode (reference
tests/conftest.py:24-40); our analog is a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
