#!/bin/bash
# Run the full round-4 TPU measurement battery at the first healthy tunnel
# window. Each step appends JSON lines to bench_curves/tpu_r4/*.log so a
# tunnel drop mid-battery loses only the step in flight. Order = VERDICT r4
# priority: contracts table first, then lowrank MXU proof, then kernels,
# then learning curves.
set -u
cd "$(dirname "$0")/.."
OUT=bench_curves/tpu_r4
mkdir -p "$OUT"

probe() {
  timeout 40 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

run() { # name, command...
  local name=$1; shift
  echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
  ( "$@" 2>>"$OUT/$name.stderr" | tee -a "$OUT/$name.log" ) \
    && echo "=== $name OK ===" | tee -a "$OUT/battery.log" \
    || echo "=== $name FAILED ($?) ===" | tee -a "$OUT/battery.log"
}

if ! probe; then
  echo "TPU tunnel unhealthy; aborting" >&2
  exit 1
fi

# 1. the three-contract table, f32 then bf16 (same config as BENCH_NOTES r2b)
run bench_f32 python bench.py
run bench_bf16 env BENCH_BF16=1 python bench.py

# 2. the MXU claim: wide policy dense vs low-rank (budget contract isolates
#    the policy cost; episodes_compact shows the combined effect)
run wide_dense env BENCH_HIDDEN=256,256 BENCH_BF16=1 python bench.py
run wide_lowrank env BENCH_HIDDEN=256,256 BENCH_BF16=1 BENCH_LOWRANK=32 python bench.py

# 3. fused-kernel micro-bench (justifies/revokes the dispatch defaults)
run bench_ops python bench_ops.py

# 4. sharded bench on the single real chip (mesh of 1; exercise the path)
run bench_multichip python bench_multichip.py

# 5. learning evidence: HalfCheetah (no alive bonus) 200 gens at popsize 10k,
#    then Humanoid 100 gens with the velocity term reported separately
run curve_halfcheetah python examples/locomotion_curve.py --env halfcheetah \
  --popsize 10000 --generations 200 --episode-length 250 --eval-every 10 \
  --bf16 --out "$OUT/halfcheetah_tpu.jsonl"
run curve_humanoid python examples/locomotion_curve.py --env humanoid \
  --popsize 10000 --generations 100 --episode-length 200 --eval-every 5 \
  --bf16 --out "$OUT/humanoid_tpu.jsonl"

echo "battery complete" | tee -a "$OUT/battery.log"
