#!/bin/bash
# Run the full TPU measurement battery at the first healthy tunnel window.
# Each step appends JSON lines to bench_curves/tpu_r5/*.log so a tunnel drop
# mid-battery loses only the step in flight; completed steps leave a .ok
# stamp and are skipped on re-fire, so a second transient window resumes
# where the first one died instead of repeating it. Order = VERDICT r4
# priority: contracts table first, then lowrank MXU proof, then kernels,
# then learning curves.
set -u
set -o pipefail  # the .ok stamp is load-bearing: it must reflect the python
                 # command's status, not tee's
cd "$(dirname "$0")/.."
OUT=bench_curves/tpu_r5
mkdir -p "$OUT"

probe() {
  # require a NON-CPU backend: a bare jax.devices() probe false-fires when
  # the axon plugin silently falls back to CPU (seen 2026-08-04; the whole
  # battery ran on the 1-core CPU and stamped bogus .ok files)
  timeout 40 python -c \
    "import jax; ds=jax.devices(); assert ds and ds[0].platform != 'cpu', ds; print(ds)" \
    >/dev/null 2>&1
}

STEPS=()

run() { # name, timeout_seconds, command...
  # every step gets a hard timeout: if the tunnel drops between steps, a
  # fresh python's FIRST backend use hangs forever (CLAUDE.md), which would
  # wedge the watcher with the deadline never checked
  local name=$1 tmo=$2; shift 2
  STEPS+=("$name")
  if [ -e "$OUT/$name.ok" ]; then
    echo "=== $name already OK, skipping ===" | tee -a "$OUT/battery.log"
    return 0
  fi
  echo "=== $name ($tmo s max): $* ===" | tee -a "$OUT/battery.log"
  if ( timeout "$tmo" "$@" 2>>"$OUT/$name.stderr" | tee -a "$OUT/$name.log" ); then
    touch "$OUT/$name.ok"
    echo "=== $name OK ===" | tee -a "$OUT/battery.log"
  else
    echo "=== $name FAILED ($?) ===" | tee -a "$OUT/battery.log"
    if ! probe; then
      # tunnel died mid-battery: every remaining step would hang to its full
      # timeout (a fresh python's first backend use never returns). Abort;
      # the watcher resumes probing and the next window picks up from the
      # first unstamped step.
      echo "=== tunnel unhealthy after $name — aborting battery ===" \
        | tee -a "$OUT/battery.log"
      exit 3
    fi
  fi
}

if ! probe; then
  echo "TPU tunnel unhealthy; aborting" >&2
  exit 1
fi

# 1. the three-contract table, f32 then bf16 (same config as BENCH_NOTES r2b)
run bench_f32 1800 python bench.py
run bench_bf16 1800 env BENCH_BF16=1 python bench.py

# 1b. SLO verdict over the flagship f32 line (steady_compiles == 0 +
#     occupancy floor on every contract; docs/observability.md "Per-group
#     telemetry & SLOs"). Writes the one-word pass/fail verdict file that
#     tpu_watch.sh attaches to its battery_exited JSONL event.
# --min-model-efficiency is a LOOSE sanity floor (an order-of-magnitude
# collapse of the MFU column, not a tight target — the flagship 64x64
# policy is inherently low-MFU; docs/policies.md has the wide-policy story)
# --max-score-collapse is the search-health hook (docs/observability.md
# "Search health"): a near-zero score spread across a popsize-10k
# generation means the eval distribution degenerated (the score-side
# stdev-collapse signal), loose enough that a healthy flagship line never
# trips it
# --max-queue-wait-p99 is likewise loose: the refill queue-wait histogram
# tops out at its 64-step overflow bucket, so 1e6 only trips if the wire
# itself is corrupt — it pins the flag's plumbing on real hardware without
# betting the verdict on an untuned tail (docs/serving.md "SLOs")
run slo_check 300 python -m evotorch_tpu.observability.slo \
  --check-bench "$OUT/bench_f32.log" --min-model-efficiency 1e-5 \
  --max-score-collapse 1e6 \
  --max-queue-wait-p99 1e6 \
  --verdict-out "$OUT/slo_verdict.txt"

# 2. the MXU claim: wide policy dense vs low-rank (budget contract isolates
#    the policy cost; episodes_compact shows the combined effect)
run wide_dense 1800 env BENCH_HIDDEN=256,256 BENCH_BF16=1 python bench.py
run wide_lowrank 1800 env BENCH_HIDDEN=256,256 BENCH_BF16=1 BENCH_LOWRANK=32 python bench.py

# 2b. the shared-trunk + per-lane delta form at the wide shape (ISSUE 16):
#     BENCH_TRUNK_DELTA=1 measures all four trunk-delta contracts PLUS the
#     in-process interleaved dense-vs-trunk-delta A/B (median-of-3 samples,
#     trunk_delta_speedup on the JSON line) — the real-chip counterpart of
#     the CPU acceptance measurement (docs/policies.md)
run bigpolicy_bench 2400 env BENCH_HIDDEN=256,256 BENCH_BF16=1 \
  BENCH_TRUNK_DELTA=1 python bench.py

# 3. fused-kernel micro-bench (justifies/revokes the opt-in flags)
run bench_ops 1800 python bench_ops.py

# 3b. autotuner: search the refill + compaction schedules at the flagship
#     shape on the real chip — interleaved median-of-3 trials, on-device
#     occupancy readout, analytic (peak-HBM) pruning off the program
#     ledger — and persist the winners into the checked-in tuned-config
#     cache, so a few minutes of healthy tunnel self-tunes the flagship
#     shapes for real hardware (closes the telemetry->knobs loop;
#     docs/observability.md "The autotuner"; absorbs the old tune_compact
#     sweep as the compact knob group)
run autotune 2400 env BENCH_BF16=1 python -m evotorch_tpu.observability.autotune \
  --group refill,compact --timings-out "$OUT/autotune_timings.json"

# 3c. policy-form autotune at the WIDE shape: search trunk-delta rank x lane
#     blocking where the trunk GEMM actually dominates (256x256), persisting
#     the winner under the full workload-identity key the wide bench steps
#     consult (docs/policies.md; docs/observability.md "The autotuner")
run autotune_policy 2400 env BENCH_HIDDEN=256,256 BENCH_BF16=1 \
  python -m evotorch_tpu.observability.autotune \
  --group policy --timings-out "$OUT/autotune_policy_timings.json"

# 3d. fused-span autotune: sweep the span length K (each K is its own
#     compiled program; the ledger's compile_seconds records what long
#     spans cost) against the host-loop baseline on the real chip and
#     persist the winner — the span_bench step below consults it via
#     BENCH_SPAN=auto (docs/sharding.md "Fused multi-generation training
#     spans")
run autotune_span 2400 env BENCH_BF16=1 \
  python -m evotorch_tpu.observability.autotune \
  --group span --timings-out "$OUT/autotune_span_timings.json"

# 3e. fused-span A/B at the flagship shape: K generations scanned into ONE
#     donated GSPMD program vs the same body dispatched per generation from
#     the host (span_speedup on the JSON line; steady_compiles must be 0)
run span_bench 2400 env BENCH_BF16=1 BENCH_SPAN=auto python bench.py

# 3f. multi-tenant serving A/B at the flagship shape: 4 concurrent tenants
#     packed through ONE EvalServer's resident episodes_refill program vs
#     the same searches dispatched sequentially standalone (serve_speedup /
#     serve_occupancy / per-tenant queue-wait quantiles on the JSON line;
#     per-tenant scores asserted bit-identical to the standalone leg before
#     the clock starts — docs/serving.md)
run serve_bench 2400 env BENCH_BF16=1 BENCH_SERVE=1 python bench.py

# 4. sharded bench on the single real chip (mesh of 1; exercise the path)
run bench_multichip 1800 python bench_multichip.py

# 4a. GSPMD vs shard_map A/B on the same chip (BENCH_SPMD=ab: interleaved
#     median-of-3 samples each, spmd_speedup on the JSON line) — the
#     acceptance measurement of the named-sharding rewrite on real hardware
#     (docs/sharding.md); own stamp so a tunnel drop here doesn't re-run
#     the whole sharded step on resume
run sharded_bench 2400 env BENCH_SPMD=ab python bench_multichip.py

# 4b. program-ledger snapshot at FLAGSHIP shape on the real chip: compile
#     wall-time, cost-model FLOPs and analyzed peak HBM of every registered
#     program (one JSON line; compile-only, no timed rollouts) — the
#     hardware-ground-truth companion of the CPU-mesh gate baseline
#     (docs/observability.md "Program ledger")
run ledger_flagship 2400 python -m evotorch_tpu.observability.report \
  --flagship --json --no-measure

# 5. learning evidence: HalfCheetah (no alive bonus) 200 gens at popsize 10k,
#    then Humanoid 100 gens with the velocity term reported separately
# lr/radius pinned to the r4 values (the runner's defaults now derive from
# --max-speed) so the r5 curve stays comparable to halfcheetah_cpu_r4
# --checkpoint-dir: the curves are the longest steps in the battery, and a
# tunnel drop mid-curve used to cost the WHOLE run (the .ok stamp is
# all-or-nothing). With durable bundles (resilience.RunCheckpointer,
# docs/resilience.md) the re-fired step auto-resumes from the newest valid
# bundle — bit-identical to the uninterrupted run — so a drop costs at most
# one checkpoint interval.
run curve_halfcheetah 10800 python examples/locomotion_curve.py --env halfcheetah \
  --popsize 10000 --generations 200 --episode-length 250 --eval-every 10 \
  --center-lr 0.06 --radius-init 0.27 \
  --checkpoint-dir "$OUT/ck_halfcheetah" --checkpoint-every 10 \
  --bf16 --out "$OUT/halfcheetah_tpu.jsonl"
# the reference's pybullet-humanoid recipe shape (rl_clipup.py:199-206):
# tiny-traj 200 steps, popsize 10k, MLP-64, max_speed 0.15, obs-norm, and
# the alive bonus REMOVED from the search signal (the r4 curve trained on
# the bonus-inclusive signal and regressed — BENCH_NOTES r5)
run curve_humanoid 10800 python examples/locomotion_curve.py --env humanoid \
  --popsize 10000 --generations 100 --episode-length 200 --eval-every 5 \
  --decrease-rewards-by auto --max-speed 0.15 \
  --network "Linear(obs_length, 64) >> Tanh() >> Linear(64, act_length)" \
  --checkpoint-dir "$OUT/ck_humanoid" --checkpoint-every 5 \
  --bf16 --out "$OUT/humanoid_tpu.jsonl"

# every step above either .ok'd or failed; report complete only if all OK
missing=0
for stamp in "${STEPS[@]}"; do
  [ -e "$OUT/$stamp.ok" ] || missing=$((missing + 1))
done
if [ "$missing" -eq 0 ]; then
  echo "battery complete" | tee -a "$OUT/battery.log"
  exit 0
else
  echo "battery incomplete ($missing steps not OK)" | tee -a "$OUT/battery.log"
  exit 2
fi
