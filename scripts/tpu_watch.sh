#!/bin/bash
# Self-arming wrapper around scripts/tpu_window.sh (VERDICT r4 next-#1):
# probe the TPU tunnel every PROBE_SECONDS and fire the battery at the first
# healthy window, so no transient window can be missed by a human attention
# gap. The battery itself is resumable (per-step .ok stamps), so if the
# tunnel drops mid-run we go back to probing and the next window continues
# from the first unfinished step. Exits 0 when the battery completes, or
# non-zero at the WATCH_HOURS deadline.
set -u
cd "$(dirname "$0")/.."
OUT=bench_curves/tpu_r5
mkdir -p "$OUT"
# watcher heartbeats are operational noise, not results: the log lives at an
# UNTRACKED path (gitignored) so probe lines never churn a round's commit
LOG="$OUT/watch.log"
# machine-readable telemetry twin of the human log: one JSON object per
# probe/battery event (ts, event, healthy, platform), so TPU availability
# history is queryable (jq '.[] | select(.healthy)') — same untracked dir
EVENTS="$OUT/watch_events.jsonl"
PROBE_SECONDS=${PROBE_SECONDS:-180}
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))

stamp() { date -u +%FT%TZ; }
echo "$(stamp) watcher armed (pid $$, probe every ${PROBE_SECONDS}s)" >> "$LOG"

# emit_event <event> <healthy:true|false> <platform-or-empty> [extra-json-kv]
emit_event() {
  local platform_json="null"
  [ -n "$3" ] && platform_json="\"$3\""
  printf '{"ts":"%s","event":"%s","healthy":%s,"platform":%s%s}\n' \
    "$(stamp)" "$1" "$2" "$platform_json" "${4:+,$4}" >> "$EVENTS"
}
emit_event watcher_armed false "" "\"probe_seconds\":${PROBE_SECONDS}"

# the probe must see a NON-CPU backend: on 2026-08-04 the axon plugin
# stopped pinning the platform and jax fell back to CPU, so the bare
# "import jax; jax.devices()" probe false-fired the battery onto the 1-core
# CPU (cpu-fallback JSON + bogus .ok stamps, quarantined in
# bench_curves/tpu_r5/false_fire_cpu_r6/). A dead tunnel still hangs the
# probe (timeout -> unhealthy); a CPU fallback now fails the assert. The
# probe prints the observed platform so the JSONL event can distinguish a
# silent CPU fallback (healthy=false, platform="cpu") from a dead tunnel
# (healthy=false, platform=null).
PROBE_PLATFORM=""
probe_tpu() {
  PROBE_PLATFORM=$(timeout 40 python -c \
    "import jax; ds=jax.devices(); print(ds[0].platform if ds else '')" \
    2>/dev/null | tail -n 1)
  if [ -n "$PROBE_PLATFORM" ] && [ "$PROBE_PLATFORM" != "cpu" ]; then
    emit_event probe true "$PROBE_PLATFORM"
    return 0
  fi
  emit_event probe false "$PROBE_PLATFORM"
  return 1
}

healthy_fails=0  # consecutive battery failures with the tunnel still healthy
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe_tpu; then
    echo "$(stamp) tunnel HEALTHY — firing battery" >> "$LOG"
    emit_event battery_fired true "$PROBE_PLATFORM"
    bash scripts/tpu_window.sh >> "$LOG" 2>&1
    rc=$?
    echo "$(stamp) battery exited rc=$rc" >> "$LOG"
    # the slo_check step's verdict (pass/fail; null until that step has run)
    slo_json="null"
    if [ -r "$OUT/slo_verdict.txt" ]; then
      slo_json="\"$(head -n 1 "$OUT/slo_verdict.txt")\""
    fi
    emit_event battery_exited true "$PROBE_PLATFORM" "\"rc\":$rc,\"slo\":$slo_json"
    [ "$rc" -eq 0 ] && exit 0
    if [ "$rc" -eq 3 ]; then
      # tunnel-caused abort: not the battery's fault; probe at normal cadence
      healthy_fails=0
    else
      # a step failed with the tunnel healthy — likely deterministic. Back
      # off exponentially and cap the attempts so we don't burn a real TPU
      # window re-running the same failing step every few minutes.
      healthy_fails=$((healthy_fails + 1))
      if [ "$healthy_fails" -ge 5 ]; then
        echo "$(stamp) $healthy_fails consecutive healthy-tunnel failures — giving up" >> "$LOG"
        exit 1
      fi
      backoff=$(( PROBE_SECONDS * (1 << healthy_fails) ))
      [ "$backoff" -gt 3600 ] && backoff=3600
      echo "$(stamp) backing off ${backoff}s (healthy failure #$healthy_fails)" >> "$LOG"
      sleep "$backoff"
      continue
    fi
  else
    echo "$(stamp) probe: unhealthy" >> "$LOG"
  fi
  sleep "$PROBE_SECONDS"
done
echo "$(stamp) watcher deadline reached; battery did not complete" >> "$LOG"
exit 1
