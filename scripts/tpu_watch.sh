#!/bin/bash
# Self-arming wrapper around scripts/tpu_window.sh (VERDICT r4 next-#1):
# probe the TPU tunnel every PROBE_SECONDS and fire the battery at the first
# healthy window, so no transient window can be missed by a human attention
# gap. The battery itself is resumable (per-step .ok stamps), so if the
# tunnel drops mid-run we go back to probing and the next window continues
# from the first unfinished step. Exits 0 when the battery completes, or
# non-zero at the WATCH_HOURS deadline.
set -u
cd "$(dirname "$0")/.."
OUT=bench_curves/tpu_r5
mkdir -p "$OUT"
# watcher heartbeats are operational noise, not results: the log lives at an
# UNTRACKED path (gitignored) so probe lines never churn a round's commit
LOG="$OUT/watch.log"
PROBE_SECONDS=${PROBE_SECONDS:-180}
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))

stamp() { date -u +%FT%TZ; }
echo "$(stamp) watcher armed (pid $$, probe every ${PROBE_SECONDS}s)" >> "$LOG"

# the probe must see a NON-CPU backend: on 2026-08-04 the axon plugin
# stopped pinning the platform and jax fell back to CPU, so the bare
# "import jax; jax.devices()" probe false-fired the battery onto the 1-core
# CPU (cpu-fallback JSON + bogus .ok stamps, quarantined in
# bench_curves/tpu_r5/false_fire_cpu_r6/). A dead tunnel still hangs the
# probe (timeout -> unhealthy); a CPU fallback now fails the assert.
probe_tpu() {
  timeout 40 python -c \
    "import jax; ds=jax.devices(); assert ds and ds[0].platform != 'cpu', ds; print(ds)" \
    >/dev/null 2>&1
}

healthy_fails=0  # consecutive battery failures with the tunnel still healthy
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe_tpu; then
    echo "$(stamp) tunnel HEALTHY — firing battery" >> "$LOG"
    bash scripts/tpu_window.sh >> "$LOG" 2>&1
    rc=$?
    echo "$(stamp) battery exited rc=$rc" >> "$LOG"
    [ "$rc" -eq 0 ] && exit 0
    if [ "$rc" -eq 3 ]; then
      # tunnel-caused abort: not the battery's fault; probe at normal cadence
      healthy_fails=0
    else
      # a step failed with the tunnel healthy — likely deterministic. Back
      # off exponentially and cap the attempts so we don't burn a real TPU
      # window re-running the same failing step every few minutes.
      healthy_fails=$((healthy_fails + 1))
      if [ "$healthy_fails" -ge 5 ]; then
        echo "$(stamp) $healthy_fails consecutive healthy-tunnel failures — giving up" >> "$LOG"
        exit 1
      fi
      backoff=$(( PROBE_SECONDS * (1 << healthy_fails) ))
      [ "$backoff" -gt 3600 ] && backoff=3600
      echo "$(stamp) backing off ${backoff}s (healthy failure #$healthy_fails)" >> "$LOG"
      sleep "$backoff"
      continue
    fi
  else
    echo "$(stamp) probe: unhealthy" >> "$LOG"
  fi
  sleep "$PROBE_SECONDS"
done
echo "$(stamp) watcher deadline reached; battery did not complete" >> "$LOG"
exit 1
