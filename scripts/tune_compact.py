"""Sweep the lane-compaction knobs on real hardware.

VERDICT r4 weak #1: chunk_size=25 and the 256-lane width-menu floor were
chosen on a 1-core CPU, blind to the lane-tile/VMEM effects they are
designed around. This script measures the episodes contract at the flagship
config across a (chunk_size x min_width) grid — plus the monolithic
``episodes`` baseline — and prints one JSON line per combo so the defaults
can be justified or replaced with data (recorded in BENCH_NOTES.md).

Knobs: TUNE_POPSIZE (default 10000 TPU / 1024 CPU), TUNE_EPISODE_LENGTH
(200/100), TUNE_GENERATIONS (2), TUNE_CHUNKS ("10,25,50,100"),
TUNE_MINWIDTHS ("128,512,0"; 0 = the runner's own default floor, which
already resolves to 256 at the flagship popsize), BENCH_ENV /
BENCH_ENV_ARGS (same as bench.py), BENCH_BF16=1 for bfloat16 compute.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import build_policy, setup_backend  # noqa: E402


def main():
    use_cpu = setup_backend()
    import jax
    import jax.numpy as jnp

    from evotorch_tpu.algorithms.functional import pgpe_ask
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout,
        run_vectorized_rollout_compacting,
    )
    from bench_common import fresh_pgpe_state

    backend = "cpu" if use_cpu else jax.default_backend()
    popsize = int(os.environ.get("TUNE_POPSIZE", 1024 if use_cpu else 10_000))
    episode_length = int(os.environ.get("TUNE_EPISODE_LENGTH", 100 if use_cpu else 200))
    generations = int(os.environ.get("TUNE_GENERATIONS", 2))
    chunks = [int(c) for c in os.environ.get("TUNE_CHUNKS", "10,25,50,100").split(",")]
    # 256 is omitted from the default grid: at the flagship popsize the
    # runner's own floor (0 = default) resolves to 256 already, and
    # re-measuring it would waste ~25% of the TPU-window step budget
    widths = [int(w) for w in os.environ.get("TUNE_MINWIDTHS", "128,512,0").split(",")]
    compute_dtype = jnp.bfloat16 if os.environ.get("BENCH_BF16", "0") == "1" else None

    env = make_env(
        os.environ.get("BENCH_ENV", "humanoid"),
        **json.loads(os.environ.get("BENCH_ENV_ARGS", "{}")),
    )
    policy = build_policy(env)
    stats = RunningNorm(env.observation_size).stats
    state = fresh_pgpe_state(policy.parameter_count)
    values = jax.jit(partial(pgpe_ask, popsize=popsize))(jax.random.key(0), state)
    jax.block_until_ready(values)
    common = dict(num_episodes=1, episode_length=episode_length,
                  compute_dtype=compute_dtype)

    def time_combo(runner_kwargs, compacting: bool):
        def once(key, prewarm=False):
            if compacting:
                r = run_vectorized_rollout_compacting(
                    env, policy, values, key, stats, prewarm=prewarm,
                    **runner_kwargs, **common,
                )
            else:
                r = run_vectorized_rollout(
                    env, policy, values, key, stats, eval_mode="episodes", **common
                )
            jax.block_until_ready(r.scores)
            return int(r.total_steps)

        once(jax.random.key(1), prewarm=True)  # compile (+ prewarm all jump pairs)
        t0 = time.perf_counter()
        steps = 0
        for g in range(generations):
            steps += once(jax.random.key(2 + g))
        dt = time.perf_counter() - t0
        return steps / dt

    base_sps = time_combo({}, compacting=False)
    print(json.dumps({
        "metric": "compact_tuning_steps_per_sec", "config": "episodes_monolithic",
        "steps_per_sec": round(base_sps, 1), "popsize": popsize,
        "episode_length": episode_length, "backend": backend,
        "compute_dtype": "bfloat16" if compute_dtype else "float32",
    }), flush=True)

    best = None
    for chunk in chunks:
        for width in widths:
            kwargs = {"chunk_size": chunk}
            if width:
                kwargs["min_width"] = width
            try:
                sps = time_combo(kwargs, compacting=True)
            except Exception as e:  # record instead of aborting the sweep
                print(json.dumps({
                    "metric": "compact_tuning_steps_per_sec",
                    "chunk_size": chunk, "min_width": width or "default",
                    "error": f"{type(e).__name__}: {e}"[:200],
                }), flush=True)
                continue
            row = {
                "metric": "compact_tuning_steps_per_sec",
                "chunk_size": chunk, "min_width": width or "default",
                "steps_per_sec": round(sps, 1),
                "speedup_vs_monolithic": round(sps / base_sps, 3),
                "backend": backend,
            }
            print(json.dumps(row), flush=True)
            if best is None or sps > best["steps_per_sec"]:
                best = row
    if best is not None:
        print(json.dumps({**best, "metric": "compact_tuning_best"}), flush=True)


if __name__ == "__main__":
    main()
