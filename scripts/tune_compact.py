"""DEPRECATED: absorbed into the autotuner CLI.

The chunk_size x min_width sweep this script ran is now the ``compact``
knob group of ``python -m evotorch_tpu.observability.autotune`` — which
adds interleaved median-of-3 trials, occupancy readout, retrace-sentinel
validation, analytic (peak-HBM) pruning, and persists the winner to the
tuned-config cache consulted by VecNE/bench (docs/observability.md "The
autotuner").

This shim maps the old TUNE_* env knobs onto the new CLI and forwards.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from evotorch_tpu.observability import autotune

    argv = ["--group", "compact"]
    if os.environ.get("TUNE_POPSIZE"):
        argv += ["--popsize", os.environ["TUNE_POPSIZE"]]
    if os.environ.get("TUNE_EPISODE_LENGTH"):
        argv += ["--episode-length", os.environ["TUNE_EPISODE_LENGTH"]]
    if os.environ.get("TUNE_CHUNKS"):
        argv += ["--chunks", os.environ["TUNE_CHUNKS"]]
    if os.environ.get("TUNE_MINWIDTHS"):
        # the old sweep used 0 for "the runner's own default floor"; the
        # autotuner grid takes concrete widths only
        widths = ",".join(
            w for w in os.environ["TUNE_MINWIDTHS"].split(",") if w.strip() not in ("", "0")
        )
        if widths:
            argv += ["--min-widths", widths]
    print(
        "scripts/tune_compact.py is deprecated; forwarding to:\n"
        f"  python -m evotorch_tpu.observability.autotune {' '.join(argv)}\n"
        "(BENCH_ENV / BENCH_BF16 / BENCH_POPSIZE etc. are honored as before)",
        file=sys.stderr,
    )
    return autotune.main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
