"""Capture a Perfetto trace of the pipelined real-MuJoCo host rollout.

Runs ``run_host_pipelined_rollout`` over an ``MjVecEnv`` with the span
tracer on and writes Chrome trace-event JSON: the main thread's
``s1.forward_dispatch`` / ``s2.actions_sync`` / ``s3.bookkeep_refill`` +
``device_forward`` spans on one track, the worker thread's ``physics``
spans on another — the Sebulba overlap, visible. Open the file at
https://ui.perfetto.dev. The committed reference trace lives at
``bench_curves/hopper_v5_pipeline_trace_r8.json``.

    python scripts/trace_host_pipeline.py --out trace.json \
        --env Hopper-v5 --popsize 48 --num-envs 16 --episode-length 200
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--env", default="Hopper-v5")
    p.add_argument("--popsize", type=int, default=48)
    p.add_argument("--num-envs", type=int, default=16)
    p.add_argument("--episode-length", type=int, default=200)
    # 2 blocks even on a 1-core box: the point of the trace is to SHOW the
    # worker-thread physics overlapping the main thread's forward dispatch
    # (mujoco.rollout releases the GIL, so the overlap is real even here)
    p.add_argument("--blocks", type=int, default=2)
    p.add_argument("--out", default="hopper_pipeline_trace.json")
    args = p.parse_args()

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import gymnasium as gym
    import jax.numpy as jnp
    import numpy as np

    from evotorch_tpu.envs.mujoco.mjvecenv import MjVecEnv
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.hostvecenv import run_host_pipelined_rollout
    from evotorch_tpu.observability import tracer

    probe = gym.make(args.env)
    obs_dim = int(np.prod(probe.observation_space.shape))
    act_dim = int(np.prod(probe.action_space.shape))
    probe.close()
    policy = FlatParamsPolicy(
        Linear(obs_dim, 64) >> Tanh() >> Linear(64, act_dim)
    )
    rng = np.random.default_rng(0)
    params = jnp.asarray(
        rng.normal(size=(args.popsize, policy.parameter_count)) * 0.5, jnp.float32
    )

    def fresh_vec():
        vec = MjVecEnv(lambda: gym.make(args.env), args.num_envs)
        vec.seed(range(1000, 1000 + args.num_envs))
        return vec

    # warmup OUTSIDE the trace: the jit compile would dwarf the steady-state
    # spans the trace exists to show
    vec = fresh_vec()
    run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=3,
        mode="pipelined", num_blocks=args.blocks,
    )
    vec.close()

    t = tracer.start_tracing(args.out)
    vec = fresh_vec()
    result = run_host_pipelined_rollout(
        vec, policy, params, num_episodes=1, episode_length=args.episode_length,
        mode="pipelined", num_blocks=args.blocks,
    )
    vec.close()
    path = tracer.stop_tracing()
    print(
        json.dumps(
            {
                "trace": path,
                "events": len(t.events()),
                "env": args.env,
                "popsize": args.popsize,
                "num_envs": args.num_envs,
                "blocks": args.blocks,
                "interactions": result["interactions"],
                "episodes": result["episodes"],
                "occupancy": round(result["occupancy"], 4),
            }
        )
    )


if __name__ == "__main__":
    main()
