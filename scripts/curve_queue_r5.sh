#!/bin/bash
# Round-5 locomotion evidence queue: wait for any running curve to finish,
# then run the remaining envs sequentially with the recipe that made
# Humanoid walk (alive bonus removed from the search signal, ClipUp
# max_speed 0.15, MLP-64, adaptive popsize under an interaction budget).
set -u
cd "$(dirname "$0")/.."
while pgrep -f "python locomotion_curve" >/dev/null; do sleep 30; done
for envname in walker2d hopper ant; do
  nice -n 15 python examples/locomotion_curve.py --env "$envname" --cpu \
    --popsize 200 --generations 300 --episode-length 200 --eval-every 10 \
    --decrease-rewards-by auto --num-interactions 30000 --popsize-max 1600 \
    --max-speed 0.15 \
    --network "Linear(obs_length, 64) >> Tanh() >> Linear(64, act_length)" \
    --out "bench_curves/${envname}_cpu_r5.jsonl" \
    > "bench_curves/${envname}_cpu_r5.log" 2>&1
done
echo done > bench_curves/curve_queue_r5.done
