#!/bin/bash
# Second round-5 evidence queue: after the popsize-10k Humanoid run frees
# the core, demonstrate that the FACTORED (low-rank) population path learns
# the flagship env end-to-end — the algorithmic-soundness complement to the
# equality tests and throughput numbers.
set -u
cd "$(dirname "$0")/.."
while pgrep -f "python locomotion_curve" >/dev/null; do sleep 60; done
nice -n 15 python examples/locomotion_curve.py --env humanoid --cpu \
  --popsize 200 --generations 300 --episode-length 200 --eval-every 10 \
  --decrease-rewards-by auto --max-speed 0.15 --lowrank-rank 32 \
  --network "Linear(obs_length, 64) >> Tanh() >> Linear(64, act_length)" \
  --out bench_curves/humanoid_cpu_r5_lowrank.jsonl \
  > bench_curves/humanoid_cpu_r5_lowrank.log 2>&1
echo done > bench_curves/curve_queue2_r5.done
