#!/bin/bash
# Third round-5 evidence queue (idle-core work while the TPU watcher waits):
# ant to its ceiling, then recurrent policies (RNN/LSTM) learning hopper —
# end-to-end training evidence for the recurrent rollout path.
set -u
cd "$(dirname "$0")/.."
while pgrep -f "python locomotion_curve" >/dev/null; do sleep 60; done
nice -n 15 python examples/locomotion_curve.py --env ant --cpu \
  --popsize 200 --generations 1000 --episode-length 200 --eval-every 20 \
  --decrease-rewards-by auto --num-interactions 30000 --popsize-max 1600 \
  --max-speed 0.15 \
  --network "Linear(obs_length, 64) >> Tanh() >> Linear(64, act_length)" \
  --out bench_curves/ant_cpu_r5_1000.jsonl \
  > bench_curves/ant_cpu_r5_1000.log 2>&1
nice -n 15 python examples/locomotion_curve.py --env hopper --cpu \
  --popsize 200 --generations 300 --episode-length 200 --eval-every 10 \
  --max-speed 0.15 \
  --network "RNN(obs_length, 32) >> Linear(32, act_length)" \
  --out bench_curves/hopper_rnn_cpu_r5.jsonl \
  > bench_curves/hopper_rnn_cpu_r5.log 2>&1
nice -n 15 python examples/locomotion_curve.py --env hopper --cpu \
  --popsize 200 --generations 300 --episode-length 200 --eval-every 10 \
  --max-speed 0.15 \
  --network "LSTM(obs_length, 32) >> Linear(32, act_length)" \
  --out bench_curves/hopper_lstm_cpu_r5.jsonl \
  > bench_curves/hopper_lstm_cpu_r5.log 2>&1
