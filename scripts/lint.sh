#!/bin/bash
# graftlint entry point: run the JAX correctness/performance static-analysis
# suite (evotorch_tpu/analysis) over the gated surface — evotorch_tpu/,
# bench*.py, examples/, __graft_entry__.py and scripts/*.py — and exit
# non-zero on any non-baselined finding (or stale baseline entry).
#
# Pure-AST: finishes in a few seconds, never touches a jax backend, safe with
# the TPU tunnel down. Pass extra args through (e.g. --no-baseline to see the
# grandfathered findings, --checkers prng,retrace for a subset).
set -euo pipefail
cd "$(dirname "$0")/.."
# force the CPU platform config for the unavoidable `import jax` at package
# import: the linter itself never initializes a backend, but the axon plugin
# pins the platform at interpreter startup (see CLAUDE.md)
exec env JAX_PLATFORMS=cpu python -m evotorch_tpu.analysis "$@"
