"""Benchmark: PGPE + fully-vectorized neuroevolution rollout throughput.

The driver runs this on real TPU hardware and records the single JSON line
printed to stdout. Metric: environment steps per second through the flagship
path — ``run_vectorized_rollout`` (one jitted program containing the whole
population x env x time loop) driven by PGPE, popsize 10k, MLP policy on the
pure-JAX Humanoid locomotion env (17 actuated DOF, 109-dim obs, contact
dynamics on an 11-body maximal-coordinates sim — the Humanoid-class flagship
matching the reference's Brax Humanoid north star; see BASELINE.md:
>1M env-steps/sec). ``BENCH_ENV`` selects any registered env
(e.g. ``hopper`` reproduces the round-1 SLIP-hopper numbers).

ALL FOUR evaluation contracts are measured every run (VERDICT r2 #1): the
throughput-optimal ``budget`` contract and the reference's own ``episodes``
contract three ways — monolithic (paid in full), through the lane-compacting
runner (``episodes_compact``), and through the work-conserving lane-refill
scheduler (``episodes_refill``, continuous batching). ``BENCH_EVAL_MODE``
picks which one is the line's primary ``value``; ``compaction_speedup`` and
``refill_speedup`` are the in-run A/Bs against monolithic ``episodes``.

``vs_baseline`` = env_steps_per_sec / 1_000_000 (the north-star target).

The line also carries the zero-sync eval telemetry (docs/observability.md):
``occupancy`` (counted interactions / executed lane-step slots, primary
contract; per-mode values inside ``modes``), ``refill_events`` (items the
refill scheduler recycled lanes for) and ``steady_compiles`` (retrace
sentinel count over every timed loop — anything but 0 is a retrace bug).
``BENCH_TELEMETRY=0`` compiles the accumulator-free programs (the overhead
A/B baseline). Each mode also reports ``queue_wait_p50``/``queue_wait_p99``
(decoded from the on-device queue-wait histograms); ``BENCH_GROUPS=G``
round-robins group ids across the population and switches the wire to the
per-group matrix (the per-group accounting overhead shape);
``EVOTORCH_METRICS=path`` streams the line + decoded per-group telemetry +
counter registry through the MetricsHub (JSONL manifest-first, or
Prometheus text with a ``.prom`` suffix).

The SEARCH-HEALTH plane (docs/observability.md "Search health") rides the
same wire: per-mode ``score_mean``/``score_std`` decoded from the on-device
float32 score-statistics block (per-group lists
``score_mean_by_group``/``score_std_by_group`` at ``BENCH_GROUPS>1``), with
the primary contract's pair hoisted top-level — what ``slo --check-bench
--max-score-collapse`` / ``--min-score-snr`` read. ``BENCH_HEALTH=0``
compiles the health-free (schema v3) programs — both the overhead A/B
baseline and the byte-compat escape hatch.

The program LEDGER (docs/observability.md "Program ledger") adds, per
contract and hoisted top-level for the primary one: ``compile_seconds``
(AOT compile wall-time of the contract's program), ``flops_per_step``
(cost-model FLOPs per counted env-step), ``peak_hbm_bytes`` (analyzed peak
footprint — donation-aware, a dropped ``donate_argnums`` inflates it) and
``model_efficiency`` (MFU-style: achieved MODEL FLOP rate —
2 x param_count useful FLOPs per counted env-step — vs the nominal
per-backend peak; ``EVOTORCH_PEAK_FLOPS`` overrides; see
bench_common.ledger_columns for why the cost-model FLOPs are NOT the
numerator). ``BENCH_LEDGER=0`` skips the capture
(one extra untimed trace+compile per contract) and keeps the line
byte-compatible with pre-ledger rounds.

The refill / compaction schedules resolve through the TUNED-CONFIG cache
(docs/observability.md "The autotuner"): explicit ``BENCH_REFILL_*`` /
``BENCH_COMPACT_*`` knobs override, else a cache hit for this
(env, popsize, episode length/count, params, dtype, machine) applies the autotuner's measured winner, else the
engine defaults. The line carries ``tuned_config_source``
(override / cache / fallback; per-contract copies and the effective
refill width/period inside ``modes``). ``BENCH_TUNED=0`` disables both
the consult and the new keys — the line is then byte-compatible with
r9/r10 output.

``BENCH_TRUNK_DELTA=1`` evaluates the shared-trunk + per-lane
low-rank-delta policy form (docs/policies.md) through all four contracts
and ALSO times an interleaved dense-vs-trunk-delta A/B of the primary
contract (``BENCH_TRUNK_AB_REPEATS`` samples each, default 3, medians):
``trunk_delta_speedup`` / ``dense_value`` land on the line together with
the effective ``trunk_rank`` / ``trunk_block`` (explicit
``BENCH_TRUNK_RANK`` / ``BENCH_TRUNK_BLOCK`` override, else the tuned
``policy`` group's winner for this shape, else rank 4 unblocked). With the
ledger on, every line also self-describes with ``hidden`` /
``param_count`` / ``policy_form`` (dense / lowrank / trunk_delta).

``BENCH_SERVE=1`` runs the multi-tenant SERVING A/B (evotorch_tpu/serving,
docs/serving.md): ``BENCH_SERVE_TENANTS`` (default 4) concurrent searches,
each popsize/T solutions per generation, packed through ONE
``EvalServer``'s resident ``episodes_refill`` program vs the same searches
dispatched sequentially standalone — interleaved median of
``BENCH_SERVE_AB_REPEATS`` samples (default 3), per-tenant packed scores
asserted bit-identical to the standalone leg during warmup. Adds
``serve_speedup`` / ``serve_value`` / ``sequential_value`` /
``serve_occupancy`` and the served queue-wait quantiles
(``serve_queue_wait_p50``/``p99``, ``*_by_tenant`` lists — what
``slo --check-bench --max-queue-wait-p99`` reads). Off by default; line
byte-compatible.

``BENCH_COMPILE_CACHE=1`` enables the persistent XLA compilation cache
(observability/compilecache.py; dir override ``EVOTORCH_COMPILE_CACHE_DIR``)
and appends a ``compile_cache`` block — hit/miss counters and cold/warm
provenance, so a recorded ``compile_seconds`` can be attributed to a real
compile vs a cache deserialize. Default off; line byte-compatible.

``BENCH_BACKEND=mujoco`` additionally measures the REAL-MuJoCo host path
(``MjVecEnv`` over ``mujoco.rollout``): the PR-2 synchronous fixed-chunk loop
vs the Sebulba-style pipelined refill scheduler, reported as
``mj_sync_steps_per_sec`` / ``mj_steps_per_sec`` / ``mj_pipeline_speedup``
columns on the same JSON line (knobs: ``BENCH_MJ_ENV``, ``BENCH_MJ_POPSIZE``,
``BENCH_MJ_NUM_ENVS``, ``BENCH_MJ_EPISODE_LENGTH``, ``BENCH_MJ_BLOCKS``,
``BENCH_MJ_REPEATS`` — median of N, this box times ±20% run-to-run —
``EVOTORCH_MJ_NTHREAD``). Off by default: the bespoke-sim line is unchanged.
"""

import json
import os
import statistics
import sys
import time
from functools import partial

from bench_common import (
    bench_config,
    bench_hidden,
    build_policy,
    fresh_pgpe_state,
    ledger_columns,
    measure_mujoco,
    setup_backend,
    tuned_compact,
    tuned_policy,
    tuned_refill,
)


def main():
    use_cpu = setup_backend()
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    from evotorch_tpu.algorithms.functional import (
        pgpe_ask,
        pgpe_ask_lowrank,
        pgpe_ask_trunk_delta,
        pgpe_tell,
        pgpe_tell_lowrank,
        pgpe_tell_trunk_delta,
    )
    from evotorch_tpu.analysis import track_compiles
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import (
        run_vectorized_rollout,
        run_vectorized_rollout_compacting,
    )
    from evotorch_tpu.observability import GroupTelemetry, MetricsHub
    from evotorch_tpu.observability import ledger as program_ledger
    from evotorch_tpu.observability.inventory import capture_compact_chunk
    from evotorch_tpu.observability.programs import abstract_like

    cfg = bench_config(use_cpu)
    if cfg["compile_cache"]:
        # BENCH_COMPILE_CACHE=1: persistent XLA compile cache — the second
        # process deserializes instead of recompiling; the line's
        # `compile_cache` block says which happened (cold/warm provenance)
        from evotorch_tpu.observability import enable_persistent_cache

        enable_persistent_cache()
    popsize = cfg["popsize"]
    episode_length = cfg["episode_length"]
    generations = cfg["generations"]
    compute_dtype = cfg["compute_dtype"]
    eval_mode = cfg["eval_mode"]
    lowrank = cfg["lowrank"]
    trunk_delta = cfg["trunk_delta"]
    if trunk_delta and lowrank:
        raise SystemExit("BENCH_TRUNK_DELTA=1 and BENCH_LOWRANK are exclusive")
    env = make_env(cfg["env_name"], **cfg["env_kwargs"])
    policy = build_policy(env)
    trunk_cfg, trunk_src = {}, None
    if trunk_delta:
        # rank / lane blocking resolve like the schedules: explicit
        # BENCH_TRUNK_* knobs override, else the tuned-config cache's
        # `policy` group (autotune --group policy), else rank 4 unblocked
        trunk_cfg, trunk_src = tuned_policy(cfg, params=policy.parameter_count)
        ask = partial(
            pgpe_ask_trunk_delta, rank=trunk_cfg["rank"], policy=policy
        )
        tell = pgpe_tell_trunk_delta
    elif lowrank:
        ask = partial(pgpe_ask_lowrank, rank=lowrank)
        tell = pgpe_tell_lowrank
    else:
        ask, tell = pgpe_ask, pgpe_tell
    print(
        f"devices={jax.devices()} popsize={popsize} params={policy.parameter_count} "
        f"episode_length={episode_length} compute_dtype={compute_dtype or 'float32'}",
        file=sys.stderr,
    )

    stats = RunningNorm(env.observation_size).stats

    # the refill / compaction schedules, resolved ONCE with provenance:
    # explicit BENCH_* knobs override, else (BENCH_TUNED=1, the default) the
    # autotuner's tuned-config cache for this (env, popsize, episode length/count, params, dtype, machine), else
    # the engine defaults (docs/observability.md "The autotuner")
    compact_cfg, compact_src = tuned_compact(cfg, params=policy.parameter_count)
    refill_cfg, refill_src = tuned_refill(cfg, params=policy.parameter_count)

    rollout_kwargs = dict(
        num_episodes=1,
        episode_length=episode_length,
        compute_dtype=compute_dtype,
        telemetry=cfg["telemetry"],
        # BENCH_HEALTH=0: compile the health-plane-free (schema v3)
        # programs — the overhead A/B baseline for the score-statistics
        # block (docs/observability.md "Search health")
        health=cfg["health"],
    )
    num_groups = cfg["num_groups"] if cfg["telemetry"] else 0
    if num_groups > 1:
        # BENCH_GROUPS=G: round-robin group ids over the population — the
        # telemetry wire becomes the per-group (G, 14) matrix, the overhead
        # A/B shape for the segment-summed accounting
        rollout_kwargs["groups"] = jnp.arange(popsize, dtype=jnp.int32) % num_groups
        rollout_kwargs["num_groups"] = num_groups

    def measure_mode(mode, key):
        """Run warmup + ``generations`` timed generations of one contract;
        returns (steps_per_sec, generations_per_sec, key, telemetry,
        steady_compiles). Each mode gets a fresh optimizer state: the jitted
        generation DONATES it (``donate_argnums``), so the ask-tell hot loop
        reuses the state and population buffers in place instead of
        allocating per generation — sharing one state object across modes
        would hand a donated (invalidated) buffer to the next mode's first
        call. The telemetry vector rides out of the same jitted program as
        the scores (zero extra dispatches) and is decoded once, after the
        clock stops; the timed loop runs under the retrace sentinel, so a
        steady-state recompile shows up as a nonzero ``steady_compiles``."""
        state = fresh_pgpe_state(policy.parameter_count)
        if mode == "episodes_compact":
            ask_jit = jax.jit(partial(ask, popsize=popsize))
            # donate the state like the monolithic modes' jitted generation
            # below: tell is state-in/state-out, so the update runs in place
            tell_jit = jax.jit(tell, donate_argnums=(0,))
            ckw = compact_cfg

            def gen(state, key, prewarm=False):
                k1, k2 = jax.random.split(key)
                values = ask_jit(k1, state)
                result = run_vectorized_rollout_compacting(
                    env, policy, values, k2, stats, prewarm=prewarm,
                    **ckw, **rollout_kwargs,
                )
                state = tell_jit(state, values, result.scores)
                return state, result.total_steps, result.scores, result.telemetry

            key, sub = jax.random.split(key)
            state, steps, scores, telemetry = gen(state, sub, prewarm=True)
            jax.block_until_ready(scores)
        else:
            extra = dict(refill_cfg) if mode == "episodes_refill" else {}
            if trunk_delta:
                # static lane-block size of the trunk-delta forward (0 = one
                # block); monolithic modes only — the compacting runner's
                # width descent already rules out a fixed lane blocking
                extra["trunk_block"] = trunk_cfg["trunk_block"]

            def generation(state, key):
                k1, k2 = jax.random.split(key)
                values = ask(k1, state, popsize=popsize)
                result = run_vectorized_rollout(
                    env, policy, values, k2, stats, eval_mode=mode,
                    **extra, **rollout_kwargs,
                )
                state = tell(state, values, result.scores)
                return state, result.total_steps, result.scores, result.telemetry

            # donate the optimizer state: ask/tell and the rollout carry run
            # allocation-free generation to generation
            gen = jax.jit(generation, donate_argnums=(0,))
            key, sub = jax.random.split(key)
            state, steps, scores, telemetry = gen(state, sub)
            jax.block_until_ready(scores)
        print(f"[{mode}] compiled; warmup steps={int(steps)}", file=sys.stderr)

        with track_compiles() as compile_log:
            t0 = time.perf_counter()
            total_steps = 0
            for _ in range(generations):
                key, sub = jax.random.split(key)
                state, steps, scores, telemetry = gen(state, sub)
                jax.block_until_ready(scores)
                total_steps += int(steps)
            elapsed = time.perf_counter() - t0
        gdec = (
            GroupTelemetry.from_array(telemetry) if telemetry is not None else None
        )
        decoded = gdec.total() if gdec is not None else None
        print(
            f"[{mode}] {generations} generations, {total_steps} env-steps in "
            f"{elapsed:.2f}s; mean score {float(jnp.mean(scores)):.3f}"
            + (f"; {decoded.summary()}" if decoded is not None else "")
            + (
                f"; STEADY-STATE COMPILES: {compile_log.names}"
                if compile_log.count
                else ""
            ),
            file=sys.stderr,
        )
        # program ledger (BENCH_LEDGER=1, the default): AOT-capture the
        # contract's compiled program — compile wall-time, cost-model FLOPs,
        # analyzed peak memory, donation verification — OUTSIDE every timed
        # region (lowering on ShapeDtypeStructs, so the donated state is
        # never consumed; costs one extra trace+compile per contract)
        record = None
        if cfg["ledger"]:
            shape = {
                "env": cfg["env_name"],
                "popsize": popsize,
                "episode_length": episode_length,
            }
            if trunk_delta:
                shape["rank"] = trunk_cfg["rank"]
            if mode == "episodes_compact" and trunk_delta:
                # capture_compact_chunk builds a DENSE params batch — its
                # record would mislabel the trunk-delta chunk program's
                # FLOPs/memory, so the compact columns stay null here
                record = None
            elif mode == "episodes_compact":
                record = capture_compact_chunk(
                    program_ledger, env, policy, popsize, episode_length,
                    chunk_size=ckw["chunk_size"],
                    compute_dtype=compute_dtype,
                    telemetry=cfg["telemetry"],
                    name="bench.compact_chunk",
                    shape=dict(shape, chunk=ckw["chunk_size"]),
                )
            else:
                record = program_ledger.capture(
                    f"bench.generation[{mode}]",
                    gen,
                    abstract_like(fresh_pgpe_state(policy.parameter_count)),
                    jax.random.key(0),
                    shape=shape,
                )
        return (
            total_steps / elapsed,
            generations / elapsed,
            key,
            gdec,
            compile_log.count,
            record,
        )

    key = jax.random.key(0)
    modes = {}
    # ALL FOUR contracts, every run (VERDICT r3 weak #3): budget (the
    # throughput-optimal contract), monolithic episodes (the reference's
    # contract, paid in full), episodes_compact (lane compaction) and
    # episodes_refill (the work-conserving refill scheduler) — so both
    # episodes-contract optimizations are in-run A/Bs against the monolith
    all_modes = [eval_mode] + [
        m
        for m in ("budget", "episodes", "episodes_compact", "episodes_refill")
        if m != eval_mode
    ]
    telemetry_by_mode = {}
    group_telemetry_by_mode = {}
    steady_compiles = 0
    for mode in all_modes:
        sps, gps, key, mode_groups, mode_compiles, record = measure_mode(
            mode, key
        )
        mode_telemetry = mode_groups.total() if mode_groups is not None else None
        telemetry_by_mode[mode] = mode_telemetry
        group_telemetry_by_mode[mode] = mode_groups
        steady_compiles += mode_compiles
        modes[mode] = {
            "value": round(sps, 1),
            "vs_baseline": round(sps / 1_000_000, 4),
            "generations_per_sec": round(gps, 3),
        }
        if mode_telemetry is not None:
            modes[mode]["occupancy"] = round(mode_telemetry.occupancy, 4)
            # queue-wait tail decoded from the on-device histograms — refill
            # is the only contract whose lanes wait, so the other modes read
            # 0.0 (absent entirely under BENCH_TELEMETRY=0)
            modes[mode]["queue_wait_p50"] = mode_groups.queue_wait_quantile(0.5)
            modes[mode]["queue_wait_p99"] = mode_groups.queue_wait_quantile(0.99)
        if mode_groups is not None and mode_groups.has_health:
            # search-health plane (schema v4): the contract's score
            # statistics, decoded from the same wire — absent entirely
            # under BENCH_HEALTH=0 so those lines stay byte-compatible
            # NOT named `stats`: that local is the RunningNorm stats every
            # rollout closure reads — shadowing it here hands a dict to the
            # next mode's trace
            sstats = mode_groups.score_stats()
            if sstats["count"] > 0:
                modes[mode]["score_mean"] = round(sstats["mean"], 6)
                modes[mode]["score_std"] = round(sstats["std"], 6)
            if mode_groups.num_groups > 1:
                rows = mode_groups.to_rows()
                modes[mode]["score_mean_by_group"] = [
                    round(r["score_mean"], 6) for r in rows
                ]
                modes[mode]["score_std_by_group"] = [
                    round(r["score_std"], 6) for r in rows
                ]
        if record is not None:
            # the compact record covers ONE full-width chunk, not a whole
            # generation: its per-step denominator is the chunk's executed
            # lane-step slots (docs/observability.md "Program ledger")
            if mode == "episodes_compact":
                steps_per_gen = compact_cfg["chunk_size"] * popsize
                modes[mode].update(
                    ledger_columns(
                        record,
                        steps_per_sec=sps,
                        steps_per_generation=steps_per_gen,
                        param_count=policy.parameter_count,
                    )
                )
            else:
                modes[mode].update(
                    ledger_columns(
                        record,
                        steps_per_sec=sps,
                        steps_per_generation=(sps / gps if gps else None),
                        param_count=policy.parameter_count,
                    )
                )

    trunk_ab = {}
    if trunk_delta:
        # BENCH_TRUNK_DELTA=1: the headline policy-form A/B — dense per-lane
        # vs shared-trunk + delta on the primary contract (budget when the
        # primary is the host-orchestrated compact runner), INTERLEAVED
        # median-of-N samples (this box times ±20% run-to-run;
        # BENCH_TRUNK_AB_REPEATS, default 3). Both programs compile once,
        # outside every timed loop, and run under the retrace sentinel.
        ab_mode = eval_mode if eval_mode != "episodes_compact" else "budget"
        ab_extra = dict(refill_cfg) if ab_mode == "episodes_refill" else {}
        # the dense leg ignores trunk_block (net/vecrl.py _forward_ctx)
        ab_extra["trunk_block"] = trunk_cfg["trunk_block"]

        def build_ab_gen(ask_fn, tell_fn):
            def generation(state, key):
                k1, k2 = jax.random.split(key)
                values = ask_fn(k1, state)
                result = run_vectorized_rollout(
                    env, policy, values, k2, stats, eval_mode=ab_mode,
                    **ab_extra, **rollout_kwargs,
                )
                state = tell_fn(state, values, result.scores)
                return state, result.total_steps, result.scores

            return jax.jit(generation, donate_argnums=(0,))

        ab_runs = {}
        for form, ask_fn, tell_fn in (
            ("dense", lambda k, s: pgpe_ask(k, s, popsize=popsize), pgpe_tell),
            ("trunk_delta", lambda k, s: ask(k, s, popsize=popsize), tell),
        ):
            gen_ab = build_ab_gen(ask_fn, tell_fn)
            st = fresh_pgpe_state(policy.parameter_count)
            key, sub = jax.random.split(key)
            st, _, scores = gen_ab(st, sub)
            jax.block_until_ready(scores)
            ab_runs[form] = {"gen": gen_ab, "state": st, "samples": []}
        ab_repeats = int(os.environ.get("BENCH_TRUNK_AB_REPEATS", "3"))
        for _ in range(ab_repeats):
            for form, run in ab_runs.items():
                gen_ab, st = run["gen"], run["state"]
                with track_compiles() as compile_log:
                    t0 = time.perf_counter()
                    sample_steps = 0
                    for _ in range(generations):
                        key, sub = jax.random.split(key)
                        st, steps, scores = gen_ab(st, sub)
                        jax.block_until_ready(scores)
                        sample_steps += int(steps)
                    elapsed = time.perf_counter() - t0
                steady_compiles += compile_log.count
                run["state"] = st
                run["samples"].append(sample_steps / elapsed)
        med = {f: statistics.median(r["samples"]) for f, r in ab_runs.items()}
        print(
            f"[trunk_ab/{ab_mode}] {ab_repeats} interleaved samples: dense "
            f"{med['dense']:.0f} vs trunk_delta {med['trunk_delta']:.0f} "
            f"steps/s ({med['trunk_delta'] / med['dense']:.2f}x)",
            file=sys.stderr,
        )
        trunk_ab = {
            "dense_value": round(med["dense"], 1),
            "trunk_delta_speedup": round(med["trunk_delta"] / med["dense"], 3),
            "trunk_ab_mode": ab_mode,
        }

    span_ab = {}
    span_record = None
    if cfg["span"] is not None:
        # BENCH_SPAN: the fused-span headline A/B — K generations scanned
        # into ONE donated GSPMD program (parallel.make_training_span) vs
        # the SAME generation body dispatched K times from the host loop
        # (parallel.make_generation_step, same default mesh), on the primary
        # contract (budget when the primary is the host-orchestrated compact
        # runner, which cannot be fused). INTERLEAVED median-of-N samples of
        # one span each (BENCH_SPAN_AB_REPEATS, default 3); both programs
        # warm up TWICE before the clock — with donation the first call
        # compiles the fresh-layout program and the second the steady-state
        # layout-committed one — and every timed loop runs under the retrace
        # sentinel.
        from bench_common import tuned_span
        from evotorch_tpu.parallel import (
            default_mesh,
            make_generation_step,
            make_training_span,
        )

        span_k, span_src = tuned_span(cfg, params=policy.parameter_count)
        span_ab_mode = eval_mode if eval_mode != "episodes_compact" else "budget"
        span_kwargs = dict(rollout_kwargs)
        span_kwargs["eval_mode"] = span_ab_mode
        if span_ab_mode == "episodes_refill":
            span_kwargs.update(refill_cfg)
        if trunk_delta:
            span_kwargs["trunk_block"] = trunk_cfg["trunk_block"]
        span_mesh = default_mesh(("pop",))

        def span_ask(k, s):
            return ask(k, s, popsize=popsize)

        gen_step = make_generation_step(
            env, policy, ask=span_ask, tell=tell, popsize=popsize,
            mesh=span_mesh, **span_kwargs,
        )
        span_fn = make_training_span(
            env, policy, ask=span_ask, tell=tell, popsize=popsize,
            span=span_k, mesh=span_mesh, **span_kwargs,
        )
        ab_stats = RunningNorm(env.observation_size).stats

        def host_sample(state, key):
            steps_total = 0
            out = None
            for _ in range(span_k):
                key, sub = jax.random.split(key)
                state, scores, _, steps, _ = gen_step(state, sub, ab_stats)
                steps_total += int(steps)
                out = scores
            jax.block_until_ready(out)
            return state, key, steps_total

        def span_sample(state, key):
            key, sub = jax.random.split(key)
            state, scores, _, steps, _ = span_fn(
                state, jax.random.split(sub, span_k), ab_stats
            )
            jax.block_until_ready(scores)
            return state, key, int(steps.sum())

        span_runs = {}
        for leg, sampler in (("hostloop", host_sample), ("span", span_sample)):
            st = fresh_pgpe_state(policy.parameter_count)
            key, leg_key = jax.random.split(key)
            st, leg_key, _ = sampler(st, leg_key)  # compile (fresh layout)
            st, leg_key, _ = sampler(st, leg_key)  # steady-state layout
            span_runs[leg] = {
                "sampler": sampler, "state": st, "key": leg_key, "samples": [],
            }
        for _ in range(cfg["span_ab_repeats"]):
            for leg, run in span_runs.items():
                with track_compiles() as compile_log:
                    t0 = time.perf_counter()
                    run["state"], run["key"], sample_steps = run["sampler"](
                        run["state"], run["key"]
                    )
                    elapsed = time.perf_counter() - t0
                steady_compiles += compile_log.count
                run["samples"].append(sample_steps / elapsed)
                run["steps"] = sample_steps
        med_span = {
            leg: statistics.median(r["samples"]) for leg, r in span_runs.items()
        }
        print(
            f"[span_ab/{span_ab_mode}] span={span_k}, "
            f"{cfg['span_ab_repeats']} interleaved samples: hostloop "
            f"{med_span['hostloop']:.0f} vs span {med_span['span']:.0f} "
            f"steps/s ({med_span['span'] / med_span['hostloop']:.2f}x)",
            file=sys.stderr,
        )
        span_ab = {
            "span": span_k,
            "span_speedup": round(med_span["span"] / med_span["hostloop"], 3),
            "span_value": round(med_span["span"], 1),
            "hostloop_value": round(med_span["hostloop"], 1),
            "span_ab_mode": span_ab_mode,
        }
        if cfg["tuned"]:
            span_ab["span_config_source"] = span_src
        if cfg["ledger"]:
            # AOT-capture the span program itself (outside every timed
            # region; the key array must be concrete — lowering folds it)
            span_record = program_ledger.capture(
                "bench.training_span",
                span_fn,
                abstract_like(fresh_pgpe_state(policy.parameter_count)),
                jax.random.split(jax.random.key(0), span_k),
                abstract_like(ab_stats),
                shape={
                    "env": cfg["env_name"],
                    "popsize": popsize,
                    "episode_length": episode_length,
                    "span": span_k,
                },
            )

    serve_ab = {}
    if cfg["serve"]:
        # BENCH_SERVE=1: the multi-tenant serving A/B (docs/serving.md) —
        # BENCH_SERVE_TENANTS concurrent searches, each popsize/T solutions
        # per generation, packed through ONE EvalServer's resident
        # episodes_refill program (the telemetry group id is the tenant id)
        # vs the SAME searches dispatched sequentially standalone. The
        # warmup round asserts per-tenant packed scores bit-identical to
        # the standalone leg (same work — the speedup is pure packing and
        # dispatch amortization). INTERLEAVED median-of-N samples
        # (BENCH_SERVE_AB_REPEATS, default 3); both legs warm twice before
        # the clock and every timed loop runs under the retrace sentinel —
        # per-generation submits re-dispatch the resident program, so any
        # steady-state compile is a retrace bug.
        import numpy as np

        from evotorch_tpu.serving import EvalServer

        serve_tenants = cfg["serve_tenants"]
        tenant_pop = max(1, popsize // serve_tenants)
        server = EvalServer(
            env,
            policy,
            slab_size=tenant_pop * serve_tenants,
            max_tenants=serve_tenants,
            refill_width=refill_cfg.get("refill_width"),
            refill_period=refill_cfg.get("refill_period") or 1,
            num_episodes=1,
            episode_length=episode_length,
            compute_dtype=compute_dtype,
            health=cfg["health"],
        )
        handles = [server.admit(f"bench{t}") for t in range(serve_tenants)]
        key, vkey, skey = jax.random.split(key, 3)
        # numpy parameter matrices: what a host-side search hands the
        # server (and ~3x cheaper per jitted dispatch than device arrays)
        tenant_values = [
            np.asarray(
                jax.random.normal(
                    jax.random.fold_in(vkey, t),
                    (tenant_pop, policy.parameter_count),
                ),
                dtype=np.float32,
            )
            for t in range(serve_tenants)
        ]
        tenant_keys = [jax.random.fold_in(skey, t) for t in range(serve_tenants)]

        def standalone_run(values, k):
            result = run_vectorized_rollout(
                env, policy, values, k, None,
                eval_mode="episodes_refill",
                num_episodes=1,
                episode_length=episode_length,
                compute_dtype=compute_dtype,
                telemetry=True,
                health=cfg["health"],
            )
            return result.scores, result.total_steps

        standalone_fn = jax.jit(standalone_run)

        def serve_sample():
            futures = [
                server.submit(handles[t], tenant_values[t], key=tenant_keys[t])
                for t in range(serve_tenants)
            ]
            server.drain()
            results = [f.result() for f in futures]
            steps = sum(int(r.total_steps) for r in results)
            return steps, [np.asarray(r.scores) for r in results]

        def sequential_sample():
            steps = 0
            all_scores = []
            for t in range(serve_tenants):
                scores, st = standalone_fn(tenant_values[t], tenant_keys[t])
                jax.block_until_ready(scores)
                steps += int(st)
                all_scores.append(np.asarray(scores))
            return steps, all_scores

        serve_runs = {"serve": serve_sample, "sequential": sequential_sample}
        warm_scores = {}
        for leg, sampler in serve_runs.items():
            sampler()  # compile
            _, warm_scores[leg] = sampler()  # steady state
        for t in range(serve_tenants):
            if not np.array_equal(
                warm_scores["serve"][t], warm_scores["sequential"][t]
            ):
                raise SystemExit(
                    f"serve A/B: tenant {t} packed scores diverged from the"
                    " standalone leg — tenant isolation bug"
                )
        serve_samples = {leg: [] for leg in serve_runs}
        for _ in range(cfg["serve_ab_repeats"]):
            for leg, sampler in serve_runs.items():
                with track_compiles() as compile_log:
                    t0 = time.perf_counter()
                    sample_steps, _ = sampler()
                    elapsed = time.perf_counter() - t0
                steady_compiles += compile_log.count
                serve_samples[leg].append(sample_steps / elapsed)
        med_serve = {
            leg: statistics.median(s) for leg, s in serve_samples.items()
        }
        print(
            f"[serve_ab] {serve_tenants} tenants x {tenant_pop},"
            f" {cfg['serve_ab_repeats']} interleaved samples: sequential"
            f" {med_serve['sequential']:.0f} vs served"
            f" {med_serve['serve']:.0f} steps/s"
            f" ({med_serve['serve'] / med_serve['sequential']:.2f}x),"
            f" occupancy {server.occupancy():.3f}",
            file=sys.stderr,
        )
        tenant_rows = [h.telemetry for h in handles]
        merged_row = tenant_rows[0]
        for row in tenant_rows[1:]:
            merged_row = merged_row + row
        serve_ab = {
            "serve_tenants": serve_tenants,
            "serve_speedup": round(
                med_serve["serve"] / med_serve["sequential"], 3
            ),
            "serve_value": round(med_serve["serve"], 1),
            "sequential_value": round(med_serve["sequential"], 1),
            "serve_occupancy": round(server.occupancy(), 4),
            "serve_queue_wait_p50": merged_row.queue_wait_quantile(0.5),
            "serve_queue_wait_p99": merged_row.queue_wait_quantile(0.99),
            "serve_queue_wait_p50_by_tenant": [
                row.queue_wait_quantile(0.5) for row in tenant_rows
            ],
            "serve_queue_wait_p99_by_tenant": [
                row.queue_wait_quantile(0.99) for row in tenant_rows
            ],
        }

    primary = modes[eval_mode]
    # the episodes-contract headline is the best runner of that contract
    episodes_runners = [
        m
        for m in ("episodes", "episodes_compact", "episodes_refill")
        if m in modes
    ]
    episodes_key = max(episodes_runners, key=lambda m: modes[m]["value"])

    def speedup_vs_episodes(mode):
        if mode not in modes or modes.get("episodes", {}).get("value", 0) <= 0:
            return None
        return round(modes[mode]["value"] / modes["episodes"]["value"], 3)

    line = {
        "metric": "pgpe_vectorized_rollout_env_steps_per_sec",
        "value": primary["value"],
        "unit": "env_steps/sec",
        "vs_baseline": primary["vs_baseline"],
        "generations_per_sec": primary["generations_per_sec"],
        "episodes_mode_value": modes[episodes_key]["value"],
        "episodes_mode_vs_baseline": modes[episodes_key]["vs_baseline"],
        "compaction_speedup": speedup_vs_episodes("episodes_compact"),
        "refill_speedup": speedup_vs_episodes("episodes_refill"),
        # on-device eval telemetry (observability.devicemetrics): the primary
        # contract's occupancy, the refill scheduler's refill/wait accounting,
        # and the retrace sentinel's steady-state compile count across every
        # timed loop (anything but 0 is a retrace bug)
        "occupancy": (
            round(telemetry_by_mode[eval_mode].occupancy, 4)
            if telemetry_by_mode.get(eval_mode) is not None
            else None
        ),
        "refill_events": (
            telemetry_by_mode["episodes_refill"].refill_events
            if telemetry_by_mode.get("episodes_refill") is not None
            else None
        ),
        "steady_compiles": steady_compiles,
        "modes": modes,
        "env": cfg["env_name"],
        "env_args": cfg["env_kwargs"],
        "popsize": popsize,
        "episode_length": episode_length,
        "eval_mode": eval_mode,
        "lowrank": lowrank,
        "compute_dtype": str(compute_dtype.__name__ if compute_dtype else "float32"),
        "backend": "cpu-fallback" if use_cpu else "tpu",
    }
    primary_groups = group_telemetry_by_mode.get(eval_mode)
    if primary_groups is not None and primary_groups.has_health:
        # the primary contract's score statistics hoisted top-level (what
        # `slo --check-bench --max-score-collapse/--min-score-snr` reads);
        # absent entirely under BENCH_HEALTH=0 / BENCH_TELEMETRY=0 so
        # those lines stay byte-compatible
        line["score_mean"] = modes[eval_mode].get("score_mean")
        line["score_std"] = modes[eval_mode].get("score_std")
    if cfg["tuned"]:
        # schedule provenance (absent entirely under BENCH_TUNED=0 so the
        # line stays byte-compatible with pre-autotuner rounds): the
        # headline `tuned_config_source` is the refill contract's — the
        # knob the r8 occupancy readout proved mistuned — with per-contract
        # sources and the EFFECTIVE refill schedule inside `modes`
        from evotorch_tpu.neuroevolution.net.vecrl import _default_refill_width

        line["tuned_config_source"] = refill_src
        modes["episodes_refill"]["tuned_config_source"] = refill_src
        # the EFFECTIVE schedule: on the fallback branch the engine runs
        # its work/8 default width, not "null" — the tuned-vs-fallback A/B
        # needs both lines to say what actually ran
        modes["episodes_refill"]["refill_width"] = refill_cfg.get(
            "refill_width", _default_refill_width(popsize)
        )
        modes["episodes_refill"]["refill_period"] = refill_cfg.get("refill_period")
        modes["episodes_compact"]["tuned_config_source"] = compact_src
    if trunk_delta:
        # BENCH_TRUNK_DELTA=1 only: the policy-form A/B columns and the
        # effective rank / lane blocking (absent by default, so the
        # default line stays byte-compatible)
        line.update(trunk_ab)
        line["trunk_rank"] = trunk_cfg["rank"]
        line["trunk_block"] = trunk_cfg["trunk_block"]
        if cfg["tuned"]:
            line["trunk_config_source"] = trunk_src
    if cfg["serve"]:
        # BENCH_SERVE=1 only: the multi-tenant serving A/B columns
        # (absent by default, so the default line stays byte-compatible)
        line.update(serve_ab)
    if cfg["span"] is not None:
        # BENCH_SPAN only: the fused-span A/B columns (absent by default,
        # so the default line stays byte-compatible with PR-18 output)
        line.update(span_ab)
        if span_record is not None:
            # the span program's own ledger figures: its cost-model FLOPs
            # cover the WHOLE K-generation scan, so the per-step
            # denominator is the span's counted env-steps
            line["span_program"] = ledger_columns(
                span_record,
                steps_per_sec=span_ab["span_value"],
                steps_per_generation=span_runs["span"].get("steps"),
                param_count=policy.parameter_count,
            )
    if cfg["ledger"]:
        # the primary contract's program-ledger figures, hoisted next to
        # `value` (per-contract copies live inside `modes`); absent entirely
        # under BENCH_LEDGER=0 so the line stays byte-compatible
        for column in (
            "compile_seconds",
            "flops_per_step",
            "peak_hbm_bytes",
            "model_efficiency",
        ):
            line[column] = primary.get(column)
        # self-description for bench_curves/ policy-shape sweeps (rides the
        # ledger gate so BENCH_LEDGER=0 lines stay byte-compatible)
        line["hidden"] = bench_hidden()
        line["param_count"] = policy.parameter_count
        line["policy_form"] = (
            "trunk_delta" if trunk_delta else "lowrank" if lowrank else "dense"
        )
    if cfg["compile_cache"]:
        # hit/miss counters from the persistent compile cache plus the
        # derived provenance: "warm" = every program this process compiled
        # was deserialized from the cache (a prior process paid the
        # compiles), "cold" = at least one real compile, "mixed" otherwise
        from evotorch_tpu.observability import cache_stats

        stats_cc = cache_stats()
        hits, misses = stats_cc["hits"], stats_cc["misses"]
        provenance = (
            "warm" if misses == 0 and hits > 0
            else "cold" if hits == 0
            else "mixed"
        )
        line["compile_cache"] = {
            "provenance": provenance,
            "hits": hits,
            "misses": misses,
            "dir": stats_cc["dir"],
        }
    if cfg["mj_backend"]:
        # BENCH_BACKEND=mujoco: append the real-MuJoCo host-path columns
        # (sync chunked loop vs pipelined refill scheduler over MjVecEnv);
        # off by default so the line above stays byte-compatible
        line.update(measure_mujoco(cfg))
    hub = MetricsHub.from_env(
        manifest={
            "source": "bench",
            "mesh": "none",
            "env": cfg["env_name"],
            "popsize": popsize,
            "num_groups": num_groups,
            "tuned_config_source": line.get("tuned_config_source"),
        }
    )
    if hub is not None:
        # EVOTORCH_METRICS=path: the same line (plus the primary contract's
        # decoded per-group telemetry and the counter registry) as one
        # schema-versioned stream record
        hub.emit(line, telemetry=group_telemetry_by_mode.get(eval_mode))
    print(json.dumps(line))


if __name__ == "__main__":
    main()
