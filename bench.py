"""Benchmark: PGPE + fully-vectorized neuroevolution rollout throughput.

The driver runs this on real TPU hardware and records the single JSON line
printed to stdout. Metric: environment steps per second through the flagship
path — ``run_vectorized_rollout`` (one jitted program containing the whole
population x env x time loop) driven by PGPE, popsize 10k, MLP policy on the
pure-JAX Humanoid locomotion env (17 actuated DOF, 109-dim obs, contact
dynamics on an 11-body maximal-coordinates sim — the Humanoid-class flagship
matching the reference's Brax Humanoid north star; see BASELINE.md:
>1M env-steps/sec). ``BENCH_ENV`` selects any registered env
(e.g. ``hopper`` reproduces the round-1 SLIP-hopper numbers).

``vs_baseline`` = env_steps_per_sec / 1_000_000 (the north-star target).
"""

import json
import os
import subprocess
import sys
import time


def _tpu_healthy() -> bool:
    """Probe backend init in a subprocess: the axon plugin can hang forever
    when its tunnel is unhealthy, which must not stall the benchmark driver."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            timeout=120,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    use_cpu = not _tpu_healthy()
    if use_cpu:
        print("TPU backend unhealthy; falling back to CPU", file=sys.stderr)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    if use_cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    from evotorch_tpu.algorithms.functional import pgpe, pgpe_ask, pgpe_tell
    from evotorch_tpu.envs import make_env
    from evotorch_tpu.neuroevolution.net import FlatParamsPolicy, Linear, Tanh
    from evotorch_tpu.neuroevolution.net.runningnorm import RunningNorm
    from evotorch_tpu.neuroevolution.net.vecrl import run_vectorized_rollout

    # on the CPU fallback, default to smaller sizes so the benchmark cannot
    # stall the driver (popsize 10k x 200 steps is a TPU-sized program)
    default_popsize = 1024 if use_cpu else 10_000
    default_episode_length = 100 if use_cpu else 200
    popsize = int(os.environ.get("BENCH_POPSIZE", default_popsize))
    episode_length = int(os.environ.get("BENCH_EPISODE_LENGTH", default_episode_length))
    generations = int(os.environ.get("BENCH_GENERATIONS", 3))
    # opt-in: bf16 changes the measured compute dtype, so keep the default
    # comparable with previously recorded f32 baselines
    compute_dtype = jnp.bfloat16 if os.environ.get("BENCH_BF16", "0") == "1" else None
    # "budget" (default): fixed interaction budget per lane with auto-reset —
    # every lane active on every step, so every computed env step is a
    # genuine counted interaction. "episodes" reproduces the reference's
    # idle-when-done masking (conservative counting; see net/vecrl.py).
    eval_mode = os.environ.get("BENCH_EVAL_MODE", "budget")

    env_name = os.environ.get("BENCH_ENV", "humanoid")
    # BENCH_ENV_ARGS: JSON kwargs for the env factory (e.g. '{"n_links": 6}'
    # reproduces the previously-benchmarked 6-link swimmer)
    env_kwargs = json.loads(os.environ.get("BENCH_ENV_ARGS", "{}"))
    env = make_env(env_name, **env_kwargs)
    # BENCH_HIDDEN: comma-separated hidden widths (default "64,64") — the
    # MXU-headroom knob: ES rollouts are env-bound, so the policy can grow
    # orders of magnitude before it shows up in steps/s
    hidden = [
        int(h) for h in os.environ.get("BENCH_HIDDEN", "64,64").split(",") if h
    ]
    net = Linear(env.observation_size, hidden[0])
    for a, b in zip(hidden, hidden[1:] + [None]):
        net = net >> Tanh()
        net = net >> Linear(a, b if b is not None else env.action_size)
    policy = FlatParamsPolicy(net)
    print(
        f"devices={jax.devices()} popsize={popsize} params={policy.parameter_count} "
        f"episode_length={episode_length} compute_dtype={compute_dtype or 'float32'}",
        file=sys.stderr,
    )

    stats = RunningNorm(env.observation_size).stats
    state = pgpe(
        center_init=jnp.zeros(policy.parameter_count, dtype=jnp.float32),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )

    def generation(state, key):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask(k1, state, popsize=popsize)
        result = run_vectorized_rollout(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=episode_length,
            compute_dtype=compute_dtype,
            eval_mode=eval_mode,
        )
        state = pgpe_tell(state, values, result.scores)
        return state, result.total_steps, result.scores

    gen_jit = jax.jit(generation)

    key = jax.random.key(0)
    # warmup/compile
    key, sub = jax.random.split(key)
    state, steps, scores = gen_jit(state, sub)
    jax.block_until_ready(scores)
    print(f"compiled; warmup steps={int(steps)}", file=sys.stderr)

    t0 = time.perf_counter()
    total_steps = 0
    for _ in range(generations):
        key, sub = jax.random.split(key)
        state, steps, scores = gen_jit(state, sub)
        jax.block_until_ready(scores)
        total_steps += int(steps)
    elapsed = time.perf_counter() - t0

    steps_per_sec = total_steps / elapsed
    generations_per_sec = generations / elapsed
    print(
        f"{generations} generations, {total_steps} env-steps in {elapsed:.2f}s; "
        f"mean score {float(jnp.mean(scores)):.3f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "pgpe_vectorized_rollout_env_steps_per_sec",
                "value": round(steps_per_sec, 1),
                "unit": "env_steps/sec",
                "vs_baseline": round(steps_per_sec / 1_000_000, 4),
                "generations_per_sec": round(generations_per_sec, 3),
                "env": env_name,
                "env_args": env_kwargs,
                "popsize": popsize,
                "episode_length": episode_length,
                "eval_mode": eval_mode,
                "compute_dtype": str(compute_dtype.__name__ if compute_dtype else "float32"),
                "backend": "cpu-fallback" if use_cpu else "tpu",
            }
        )
    )


if __name__ == "__main__":
    main()
