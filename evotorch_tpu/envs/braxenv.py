"""Brax adapter (import-gated: brax is not baked into this image).

Parity: the reference's ``VectorEnvFromBrax`` (``net/vecrl.py:1366-1490``)
wraps brax envs with jitted reset/step and dlpack conversion to torch. Here
no conversion is needed — a brax env already satisfies our pure protocol; the
adapter only reshapes its API (brax State -> EnvState, truncation handling).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..tools.pytree import replace
from .base import Env, EnvState, Space

__all__ = ["BraxEnvAdapter"]


class BraxEnvAdapter(Env):
    def __init__(self, env_name: str, *, episode_length: int = 1000, **brax_kwargs):
        try:
            import brax.envs as brax_envs
        except ImportError as e:
            raise ImportError(
                "brax is not installed in this environment; use the pure-JAX "
                "envs (cartpole/pendulum/acrobot/swimmer/...) instead"
            ) from e
        self._env = brax_envs.get_environment(env_name, **brax_kwargs)
        self.max_episode_steps = int(episode_length)
        obs_size = int(self._env.observation_size)
        act_size = int(self._env.action_size)
        self.observation_space = Space(shape=(obs_size,))
        self.action_space = Space(
            shape=(act_size,), lb=-jnp.ones(act_size), ub=jnp.ones(act_size)
        )

    def reset(self, key) -> Tuple[EnvState, jnp.ndarray]:
        key, sub = jax.random.split(key)
        brax_state = self._env.reset(sub)
        state = EnvState(obs_state=brax_state, t=jnp.zeros((), jnp.int32), key=key)
        return state, brax_state.obs

    def step(self, state: EnvState, action):
        brax_state = self._env.step(state.obs_state, jnp.asarray(action))
        t = state.t + 1
        done = (brax_state.done > 0) | (t >= self.max_episode_steps)
        new_state = replace(state, obs_state=brax_state, t=t)
        return new_state, brax_state.obs, brax_state.reward, done
