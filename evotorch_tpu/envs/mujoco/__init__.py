"""Real-MuJoCo evaluation backend.

The pure-JAX envs in ``evotorch_tpu.envs`` are this framework's TPU-native
throughput substrate; this subpackage grounds them in the *canonical*
benchmark: real gymnasium ``-v5`` MuJoCo locomotion. It provides

- :class:`MjVecEnv` (``mjvecenv.py``) — a batched host rollout engine that
  steps N real MuJoCo models per call through ``mujoco.rollout``'s threaded
  API, recomputing each ``-v5`` family's observation / reward terms /
  termination from raw physics state so the per-term decomposition
  (forward velocity, control cost, healthy bonus) is available every step.
  API-compatible with ``net.hostvecenv.SyncVectorEnv``, so the batched
  policy-forward evaluation loop (one device call per timestep for the whole
  lane block) works unchanged on real physics.
- :func:`make_host_vector_env` — the backend chooser ``GymNE`` uses when
  ``num_envs > 1``: ``MjVecEnv`` for supported MuJoCo envs, the generic
  gymnasium ``SyncVectorEnv`` for everything else.
- ``fidelity.py`` — a matched-action parity harness that drives a native
  rigid-body env and its real ``-v5`` counterpart with identical action
  sequences and reports per-reward-term divergence (the measured statement
  behind every "Hopper/HalfCheetah/... semantics" docstring claim).

``mujoco`` (3.4.0 in this image) and ``gymnasium`` are OPTIONAL dependencies
of the wider package: importing ``evotorch_tpu.envs.mujoco`` itself is always
safe; the submodules import ``mujoco`` at their top level and are loaded
lazily, so the guard is :func:`mujoco_available` (or catching ``ImportError``
around the lazy attribute access).
"""

from __future__ import annotations

from importlib import import_module, util

__all__ = [
    "MjVecEnv",
    "make_host_vector_env",
    "mujoco_available",
    "run_fidelity",
    "format_fidelity_markdown",
]

_LAZY = {
    "MjVecEnv": ".mjvecenv",
    "make_host_vector_env": ".mjvecenv",
    "run_fidelity": ".fidelity",
    "format_fidelity_markdown": ".fidelity",
}


def mujoco_available() -> bool:
    """True when both ``mujoco`` and ``gymnasium`` are importable (cheap:
    spec lookup only, no module import)."""
    return util.find_spec("mujoco") is not None and util.find_spec("gymnasium") is not None


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(import_module(target, __name__), name)
