"""Batched host rollout engine over real MuJoCo models.

``MjVecEnv`` steps N gymnasium ``-v5`` MuJoCo environments in lockstep by
driving their raw ``MjModel``s through ``mujoco.rollout`` — MuJoCo's native
threaded batched stepper — instead of N sequential ``env.step`` calls. One
``rollout`` call per control timestep advances every active lane by
``frame_skip`` physics substeps; observation, reward terms and termination
are then recomputed *from the physics state* by a per-family table
(:class:`_V5Family`), which is what makes the per-term reward decomposition
(forward velocity / control cost / healthy bonus) available on every step —
the fidelity harness (``fidelity.py``) and BENCH_NOTES both consume it.

Faithfulness: ``FULLPHYSICS``-state round-tripping through ``rollout`` with
``nstep = frame_skip`` reproduces gymnasium's own ``do_simulation`` stepping
to ~1e-15 (measured on Hopper-v5 over a full episode — the integrator path is
identical, only the Python driver differs); resets go through each lane's own
``env.reset()`` so reset-noise distributions and seeding are exactly
gymnasium's. The v5 reward/termination math below is transcribed from
``gymnasium/envs/mujoco/*_v5.py`` and asserted equivalent (rewards AND
observations) against real ``env.step`` lanes in ``tests/test_mujoco.py``.

The class is API-compatible with ``net.hostvecenv.SyncVectorEnv`` (``reset``
/ ``step(actions, active)`` / ``_reset_one`` / ``seed`` / ``close``), so both
host rollout engines run unchanged on real physics: the synchronous
``run_host_vectorized_rollout`` loop and the Sebulba-style
``run_host_pipelined_rollout`` scheduler (Podracer, arXiv:2104.06272 —
batched host physics overlapping the device policy forward). Under the
pipelined scheduler, ``step`` is called **block-sliced** (the ``active`` mask
covers one lane block) from a single worker thread while the main thread may
``_reset_one`` lanes of a *different* block; that is safe because every
per-lane buffer (``_state`` rows, ``_steps``, the lane's own env) is touched
by exactly one block at a time, and ``_pool.rollout`` copies its
``_state[idx]`` slice per call. ``last_terms`` consequently reflects the most
recent *block's* step, not the whole width, when pipelined.

Envs outside the supported family table (or with non-default observation
flags) fall back to the generic ``SyncVectorEnv`` via
:func:`make_host_vector_env`.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Type, Union

import numpy as np

import mujoco
from mujoco import rollout as mj_rollout

__all__ = ["MjVecEnv", "make_host_vector_env"]

_FULLPHYSICS = mujoco.mjtState.mjSTATE_FULLPHYSICS


# --------------------------------------------------------------------------
# -v5 family table: observation / reward terms / termination from raw state
# --------------------------------------------------------------------------
class _V5Family:
    """Vectorized re-implementation of one gymnasium ``-v5`` family's
    observation, reward decomposition and termination as pure functions of
    ``(qpos, qvel, action)`` batches (lane-leading shapes ``(B, ...)``).

    Weights/ranges are read from the live env instance at construction, so
    ``env_config`` overrides (e.g. a custom ``ctrl_cost_weight``) are
    honored; *structural* overrides (e.g. including the root x in the
    observation) make :meth:`supports` return False and route the env to the
    generic fallback instead.
    """

    #: value of ``_exclude_current_positions_from_observation`` this family's
    #: ``obs()`` assumes; None = the env has no such flag
    expects_exclude_x: Optional[bool] = True

    def __init__(self, env):
        u = env.unwrapped
        self.dt = float(u.dt)
        self.forward_reward_weight = float(getattr(u, "_forward_reward_weight", 0.0))
        self.ctrl_cost_weight = float(getattr(u, "_ctrl_cost_weight", 0.0))
        self.healthy_reward = float(getattr(u, "_healthy_reward", 0.0))
        self.terminate_when_unhealthy = bool(getattr(u, "_terminate_when_unhealthy", False))
        zr = getattr(u, "_healthy_z_range", (-np.inf, np.inf))
        ar = getattr(u, "_healthy_angle_range", (-np.inf, np.inf))
        sr = getattr(u, "_healthy_state_range", (-np.inf, np.inf))
        self.healthy_z_range = (float(zr[0]), float(zr[1]))
        self.healthy_angle_range = (float(ar[0]), float(ar[1]))
        self.healthy_state_range = (float(sr[0]), float(sr[1]))

    @classmethod
    def supports(cls, env) -> bool:
        if cls.expects_exclude_x is None:
            return True
        flag = getattr(env.unwrapped, "_exclude_current_positions_from_observation", None)
        return bool(flag) == cls.expects_exclude_x

    # -- the three per-family functions (B-leading batches) -----------------
    def obs(self, qpos: np.ndarray, qvel: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def is_healthy(self, qpos: np.ndarray, qvel: np.ndarray) -> np.ndarray:
        return np.ones(qpos.shape[0], dtype=bool)

    def reward_terms(self, x_vel, action, qpos, qvel):
        """-> ``(reward (B,), terminated (B,), terms: dict[str, (B,)])``."""
        raise NotImplementedError

    # shared pieces
    def _ctrl_cost(self, action: np.ndarray) -> np.ndarray:
        return self.ctrl_cost_weight * np.sum(np.square(action), axis=-1)


class _RunnerFamily(_V5Family):
    """forward - ctrl_cost, no termination (HalfCheetah-v5 / Swimmer-v5)."""

    qpos_skip = 1
    clip_qvel: Optional[float] = None

    def obs(self, qpos, qvel):
        v = qvel if self.clip_qvel is None else np.clip(qvel, -self.clip_qvel, self.clip_qvel)
        return np.concatenate([qpos[:, self.qpos_skip :], v], axis=1)

    def reward_terms(self, x_vel, action, qpos, qvel):
        forward = self.forward_reward_weight * x_vel
        ctrl = self._ctrl_cost(action)
        terms = {"x_velocity": x_vel, "reward_forward": forward, "reward_ctrl": -ctrl}
        return forward - ctrl, np.zeros(qpos.shape[0], dtype=bool), terms


class _HalfCheetahFamily(_RunnerFamily):
    qpos_skip = 1


class _SwimmerFamily(_RunnerFamily):
    qpos_skip = 2


class _WalkerFamily(_V5Family):
    """forward + healthy*bonus - ctrl_cost, unhealthy terminates
    (Walker2d-v5; Hopper-v5 adds the state-range check)."""

    check_state_range = False

    def obs(self, qpos, qvel):
        return np.concatenate([qpos[:, 1:], np.clip(qvel, -10.0, 10.0)], axis=1)

    def is_healthy(self, qpos, qvel):
        z, angle = qpos[:, 1], qpos[:, 2]
        lo_z, hi_z = self.healthy_z_range
        lo_a, hi_a = self.healthy_angle_range
        healthy = (z > lo_z) & (z < hi_z) & (angle > lo_a) & (angle < hi_a)
        if self.check_state_range:
            lo_s, hi_s = self.healthy_state_range
            state = np.concatenate([qpos[:, 2:], qvel], axis=1)
            healthy &= np.all((state > lo_s) & (state < hi_s), axis=1)
        return healthy

    def reward_terms(self, x_vel, action, qpos, qvel):
        healthy = self.is_healthy(qpos, qvel)
        forward = self.forward_reward_weight * x_vel
        survive = self.healthy_reward * healthy
        ctrl = self._ctrl_cost(action)
        terminated = (
            ~healthy if self.terminate_when_unhealthy else np.zeros_like(healthy)
        )
        terms = {
            "x_velocity": x_vel,
            "reward_forward": forward,
            "reward_ctrl": -ctrl,
            "reward_survive": survive,
        }
        return forward + survive - ctrl, terminated, terms


class _HopperFamily(_WalkerFamily):
    check_state_range = True


class _InvertedPendulumFamily(_V5Family):
    """reward 1 while upright; |pole angle| > 0.2 (or non-finite obs)
    terminates (InvertedPendulum-v5)."""

    expects_exclude_x = None

    def obs(self, qpos, qvel):
        return np.concatenate([qpos, qvel], axis=1)

    def reward_terms(self, x_vel, action, qpos, qvel):
        obs = self.obs(qpos, qvel)
        terminated = ~np.isfinite(obs).all(axis=1) | (np.abs(qpos[:, 1]) > 0.2)
        reward = (~terminated).astype(np.float64)
        return reward, terminated, {"reward_survive": reward}


_FAMILIES: Dict[str, Type[_V5Family]] = {
    "HalfCheetah-v5": _HalfCheetahFamily,
    "Swimmer-v5": _SwimmerFamily,
    "Walker2d-v5": _WalkerFamily,
    "Hopper-v5": _HopperFamily,
    "InvertedPendulum-v5": _InvertedPendulumFamily,
}


def _family_for(env) -> Optional[Type[_V5Family]]:
    spec = getattr(env, "spec", None)
    cls = _FAMILIES.get(getattr(spec, "id", ""))
    if cls is not None and cls.supports(env):
        return cls
    return None


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------
class MjVecEnv:
    """Steps ``num_envs`` real MuJoCo envs in lockstep via ``mujoco.rollout``.

    Same contract as ``SyncVectorEnv``: ``reset() -> (N, obs_dim)`` float32;
    ``step(actions, active) -> (obs, rewards, dones)`` with eager auto-reset
    on done lanes and NaN dummy observations on inactive ones. Additionally
    exposes ``last_terms`` — the per-lane reward decomposition of the most
    recent step (``x_velocity`` / ``reward_forward`` / ``reward_ctrl`` /
    ``reward_survive``, NaN on inactive lanes) — and honors each env's own
    gymnasium TimeLimit.
    """

    def __init__(
        self,
        env_fn: Union[Callable, Sequence[Callable], Sequence],
        num_envs: Optional[int] = None,
        *,
        nthread: Optional[int] = None,
    ):
        self.envs = _instantiate(env_fn, num_envs)
        env0 = self.envs[0]
        fam_cls = _family_for(env0)
        if fam_cls is None:
            raise ValueError(
                f"MjVecEnv does not support {getattr(env0.spec, 'id', env0)!r}"
                f" (supported -v5 families: {sorted(_FAMILIES)}, with default"
                " observation flags); use SyncVectorEnv / make_host_vector_env"
            )
        self.family: _V5Family = fam_cls(env0)
        u0 = env0.unwrapped
        self._models = [e.unwrapped.model for e in self.envs]
        self._nq = int(u0.model.nq)
        self._nv = int(u0.model.nv)
        self._frame_skip = int(u0.frame_skip)
        self._nstate = mujoco.mj_stateSize(u0.model, _FULLPHYSICS)
        n = len(self.envs)
        self._state = np.zeros((n, self._nstate), dtype=np.float64)
        self._steps = np.zeros(n, dtype=np.int64)
        spec = getattr(env0, "spec", None)
        self._max_episode_steps = getattr(spec, "max_episode_steps", None)

        self.observation_space = env0.observation_space
        self.action_space = env0.action_space
        self._obs_dim = int(np.prod(env0.observation_space.shape))

        if nthread is None:
            # EVOTORCH_MJ_NTHREAD overrides the physics thread-pool width
            # (mujoco.rollout's nthread). The default saturates the machine —
            # which on a 1-core box means nthread=1, i.e. NO physics
            # parallelism: the pipelined scheduler's overlap gains there come
            # from lane refill, not threading (docs/neuroevolution.md).
            env_nthread = os.environ.get("EVOTORCH_MJ_NTHREAD", "")
            if env_nthread:
                nthread = int(env_nthread)
            else:
                nthread = max(1, min(n, os.cpu_count() or 1))
        self.nthread = int(nthread)
        self._pool = mj_rollout.Rollout(nthread=int(nthread))
        self._scratch = [mujoco.MjData(self._models[0]) for _ in range(int(nthread))]
        self.last_terms: Dict[str, np.ndarray] = {}

    # ------------------------------------------------- SyncVectorEnv contract
    @property
    def num_envs(self) -> int:
        return len(self.envs)

    @property
    def is_discrete(self) -> bool:
        return hasattr(self.action_space, "n")

    def _pull_state(self, i: int):
        mujoco.mj_getState(
            self._models[i], self.envs[i].unwrapped.data, self._state[i], _FULLPHYSICS
        )

    def _reset_one(self, i: int) -> np.ndarray:
        out = self.envs[i].reset()
        if isinstance(out, tuple):
            out = out[0]
        self._pull_state(i)
        self._steps[i] = 0
        return np.asarray(out, dtype=np.float32).reshape(-1)

    def reset(self) -> np.ndarray:
        return np.stack([self._reset_one(i) for i in range(self.num_envs)])

    def step(self, actions, active: Optional[np.ndarray] = None):
        n = self.num_envs
        obs = np.full((n, self._obs_dim), np.nan, dtype=np.float32)
        rewards = np.zeros(n, dtype=np.float32)
        dones = np.zeros(n, dtype=bool)
        idx = np.arange(n) if active is None else np.flatnonzero(np.asarray(active)[:n])
        self.last_terms = {}
        if idx.size == 0:
            return obs, rewards, dones

        acts = np.asarray(actions, dtype=np.float64).reshape((n, -1))[idx]
        x_before = self._state[idx, 1]  # FULLPHYSICS layout: [time, qpos, qvel, act]
        ctrl = np.ascontiguousarray(
            np.repeat(acts[:, None, :], self._frame_skip, axis=1)
        )
        out_state, _ = self._pool.rollout(
            [self._models[i] for i in idx], self._scratch, self._state[idx], ctrl
        )
        new_state = out_state[:, -1, :]
        qpos = new_state[:, 1 : 1 + self._nq]
        qvel = new_state[:, 1 + self._nq : 1 + self._nq + self._nv]
        x_vel = (qpos[:, 0] - x_before) / self.family.dt

        reward, terminated, terms = self.family.reward_terms(x_vel, acts, qpos, qvel)
        self._state[idx] = new_state
        self._steps[idx] += 1
        done = terminated.copy()
        if self._max_episode_steps is not None:
            done |= self._steps[idx] >= int(self._max_episode_steps)

        obs[idx] = self.family.obs(qpos, qvel).astype(np.float32)
        rewards[idx] = reward
        dones[idx] = done
        for term_name, values in terms.items():
            full = np.full(n, np.nan)
            full[idx] = values
            self.last_terms[term_name] = full
        for j, i in enumerate(idx):
            if done[j]:
                obs[i] = self._reset_one(i)
        return obs, rewards, dones

    def seed(self, seeds: Sequence[int]):
        for i, s in enumerate(seeds[: self.num_envs]):
            try:
                self.envs[i].reset(seed=int(s))
            except TypeError:
                continue
            self._pull_state(i)
            self._steps[i] = 0

    def close(self):
        self._pool.close()
        for env in self.envs:
            if hasattr(env, "close"):
                env.close()


def _instantiate(env_fn, num_envs) -> List:
    """Accept a single factory + count, a sequence of factories, or a
    sequence of already-constructed envs."""
    if callable(env_fn):
        if num_envs is None:
            raise ValueError("Give num_envs when env_fn is a single factory")
        return [env_fn() for _ in range(int(num_envs))]
    items = list(env_fn)
    return [item() if callable(item) else item for item in items]


def make_host_vector_env(env_fn: Callable, num_envs: int, *, nthread: Optional[int] = None):
    """Backend chooser for ``GymNE``'s vectorized host evaluation: a real
    MuJoCo batched engine when the env is a supported ``-v5`` family, the
    generic lockstep ``SyncVectorEnv`` otherwise. The probe env is reused as
    lane 0 either way (never constructed twice). ``nthread`` feeds
    ``mujoco.rollout``'s thread pool (default: ``EVOTORCH_MJ_NTHREAD`` or
    one thread per core)."""
    from ...neuroevolution.net.hostvecenv import SyncVectorEnv

    probe = env_fn()
    rest = [env_fn for _ in range(int(num_envs) - 1)]
    if _family_for(probe) is not None:
        return MjVecEnv([probe] + rest, nthread=nthread)
    return SyncVectorEnv([lambda: probe] + rest)
