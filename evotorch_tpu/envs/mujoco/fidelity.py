"""Matched-action env-fidelity harness: native rigid-body vs real MuJoCo.

The native envs (``envs/halfcheetah.py`` etc.) claim ``-v4``/``-v5``-class
semantics in their docstrings; this module turns those claims into *measured*
statements. Both simulators are driven with **identical action sequences**
(smooth AR(1) exploration noise, plus an all-zero sequence — the zero-action
drift diagnostic), per-step reward terms are recorded on each side
(``batch_reward_terms`` on the native envs, ``MjVecEnv.last_terms`` on the
real ones), and the report summarizes per-term divergence: means on each
side, mean absolute per-step difference, and the correlation of the
per-step traces over the steps where both sims are still alive.

What this does and does not establish: the two engines integrate different
body plans with different contact models, so per-step traces are *not*
expected to match — the comparison measures whether the native tasks put the
policy in the same reward regime (velocity scale, control-cost scale,
survival behaviour) as the canonical benchmark. Scores earned on the native
sims are comparable to gymnasium scores only to the extent this report says
they are.

Run as a module (host physics + CPU JAX; safe with the TPU tunnel down)::

    python -m evotorch_tpu.envs.mujoco.fidelity \
        --pairs halfcheetah,walker2d --seqs 8 --steps 300 \
        --out bench_curves/fidelity_r6.json --markdown
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["PAIRS", "run_fidelity", "format_fidelity_markdown"]

# native registry name -> (real gymnasium id, native env kwargs)
PAIRS: Dict[str, tuple] = {
    "halfcheetah": ("HalfCheetah-v5", {}),
    "walker2d": ("Walker2d-v5", {}),
    # survival-only pair: the native cartpole is the closest dynamics match
    # to InvertedPendulum-v5 (cart + pole, |angle| termination); only the
    # total-reward / episode statistics are comparable
    "cartpole": ("InvertedPendulum-v5", {"continuous_actions": True}),
}


def _action_sequences(rng: np.random.Generator, n_seqs: int, n_steps: int, act_dim: int):
    """Smooth AR(1) exploration actions in [-1, 1]; sequence 0 is all-zero
    (the zero-action drift check — free reward on either sim shows up as a
    nonzero velocity mean in that lane)."""
    rho, amp = 0.8, 0.6
    acts = np.zeros((n_seqs, n_steps, act_dim))
    for s in range(1, n_seqs):
        a = np.zeros(act_dim)
        for t in range(n_steps):
            a = rho * a + np.sqrt(1.0 - rho * rho) * rng.normal(0.0, amp, act_dim)
            acts[s, t] = a
    return np.clip(acts, -1.0, 1.0)


def _native_trajectories(env, actions: np.ndarray, seed: int) -> Dict[str, np.ndarray]:
    """Drive the native env with ``actions`` ``(S, T, na)``; returns per-step
    ``(S, T)`` term traces (NaN once a lane's episode has ended) + ``alive``.

    The per-call ``jax.jit`` wrappers below are baselined graftlint
    ``retrace`` findings: ``run_fidelity`` constructs a FRESH env per pair
    and drives it through here exactly once, so one trace per env is
    inherent — and caching the wrappers on env identity would never hit
    while pinning dead envs (and their executables) for the process
    lifetime."""
    import jax
    import jax.numpy as jnp

    S, T, _ = actions.shape
    keys = jax.random.split(jax.random.key(seed), S)
    batched = bool(getattr(env, "batched_native", False))
    if batched:
        state, _ = env.batch_reset(keys)
        step = jax.jit(env.batch_step)
    else:
        state, _ = jax.vmap(env.reset)(keys)
        step = jax.jit(jax.vmap(env.step))
    terms_fn = None
    if hasattr(env, "batch_reward_terms"):
        terms_fn = jax.jit(env.batch_reward_terms)
    has_terms = terms_fn is not None

    out = {"reward_total": np.full((S, T), np.nan), "alive": np.zeros((S, T), bool)}
    active = np.ones(S, dtype=bool)
    for t in range(T):
        a = jnp.asarray(actions[:, t, :])
        state, _, reward, done = step(state, a)
        reward, done = np.asarray(reward), np.asarray(done)
        out["reward_total"][active, t] = reward[active]
        out["alive"][:, t] = active
        if has_terms:
            terms = terms_fn(state.obs_state, jnp.clip(a, -1.0, 1.0).T)
            for name in ("x_velocity", "reward_ctrl", "reward_survive"):
                trace = out.setdefault(name, np.full((S, T), np.nan))
                trace[active, t] = np.asarray(terms[name])[active]
        active = active & ~done
        if not active.any():
            break
    return out


def _mujoco_trajectories(env_id: str, actions: np.ndarray, seed: int) -> Dict[str, np.ndarray]:
    """Same trace collection on the real env through :class:`MjVecEnv` (one
    lane per action sequence, single episode per lane)."""
    import gymnasium as gym

    from .mjvecenv import MjVecEnv

    S, T, _ = actions.shape
    venv = MjVecEnv(lambda: gym.make(env_id), S)
    try:
        venv.seed([seed + i for i in range(S)])
        venv.reset()
        out = {"reward_total": np.full((S, T), np.nan), "alive": np.zeros((S, T), bool)}
        active = np.ones(S, dtype=bool)
        for t in range(T):
            _, rewards, dones = venv.step(actions[:, t, :], active=active)
            out["reward_total"][active, t] = rewards[active]
            out["alive"][:, t] = active
            for name in ("x_velocity", "reward_ctrl", "reward_survive"):
                if name in venv.last_terms:
                    trace = out.setdefault(name, np.full((S, T), np.nan))
                    trace[active, t] = venv.last_terms[name][active]
            active = active & ~dones
            if not active.any():
                break
        return out
    finally:
        venv.close()


def _term_summary(native: np.ndarray, mujoco: np.ndarray, both: np.ndarray) -> dict:
    a, b = native[both], mujoco[both]
    summary = {
        "native_mean": float(np.nanmean(native)),
        "mujoco_mean": float(np.nanmean(mujoco)),
        "matched_steps": int(both.sum()),
    }
    if a.size >= 2:
        summary["mean_abs_diff"] = float(np.mean(np.abs(a - b)))
        sa, sb = np.std(a), np.std(b)
        summary["corr"] = (
            float(np.corrcoef(a, b)[0, 1]) if sa > 1e-12 and sb > 1e-12 else None
        )
    return summary


def run_fidelity(
    pairs: Optional[Sequence[str]] = None,
    *,
    n_seqs: int = 8,
    n_steps: int = 300,
    seed: int = 0,
) -> dict:
    """Run the matched-action comparison for each named pair (default: all of
    :data:`PAIRS`) and return the report dict (JSON-serializable)."""
    from ..registry import make_env

    names = list(PAIRS) if pairs is None else list(pairs)
    report = {
        "config": {"n_seqs": n_seqs, "n_steps": n_steps, "seed": seed},
        "pairs": {},
    }
    rng = np.random.default_rng(seed)
    for name in names:
        env_id, native_kwargs = PAIRS[name]
        env = make_env(name, **native_kwargs)
        act_dim = int(np.prod(env.action_space.shape))
        import gymnasium as gym

        probe = gym.make(env_id)
        mj_act_dim = int(np.prod(probe.action_space.shape))
        probe.close()
        if act_dim != mj_act_dim:
            raise ValueError(
                f"{name}: native action dim {act_dim} != {env_id} dim {mj_act_dim}"
            )
        actions = _action_sequences(rng, n_seqs, n_steps, act_dim)
        native = _native_trajectories(env, actions, seed)
        mujoco = _mujoco_trajectories(env_id, actions, seed)

        both = native["alive"] & mujoco["alive"]
        terms = {}
        for term in ("x_velocity", "reward_ctrl", "reward_survive", "reward_total"):
            if term in native and term in mujoco:
                terms[term] = _term_summary(native[term], mujoco[term], both)
        # zero-action drift: lane 0 carries the all-zero action sequence
        zero_drift = {}
        for side, traces in (("native", native), ("mujoco", mujoco)):
            if "x_velocity" in traces:
                lane = traces["x_velocity"][0]
                zero_drift[f"{side}_mean_velocity"] = float(np.nanmean(lane))
        pair_report = {
            "mujoco_env": env_id,
            "action_dim": act_dim,
            "native_weights": {
                "forward_reward_weight": float(getattr(env, "forward_reward_weight", 0.0)),
                "ctrl_cost_weight": float(getattr(env, "ctrl_cost_weight", 0.0)),
                "alive_bonus": float(getattr(env, "alive_bonus", 0.0)),
            },
            "terms": terms,
            "episode": {
                "native_mean_length": float(native["alive"].sum(axis=1).mean()),
                "mujoco_mean_length": float(mujoco["alive"].sum(axis=1).mean()),
            },
        }
        if zero_drift:
            pair_report["zero_action_drift"] = zero_drift
        report["pairs"][name] = pair_report
    return report


def format_fidelity_markdown(report: dict) -> str:
    """The BENCH_NOTES fidelity section: one table per pair."""
    cfg = report["config"]
    lines = [
        "### Env-fidelity: native rigid-body vs real MuJoCo `-v5` (matched actions)",
        "",
        f"Harness: `python -m evotorch_tpu.envs.mujoco.fidelity` — "
        f"{cfg['n_seqs']} AR(1) action sequences (one all-zero) x "
        f"{cfg['n_steps']} steps, seed {cfg['seed']}. Per-step terms compared "
        "over the steps where both sims are alive.",
        "",
    ]
    for name, pair in report["pairs"].items():
        lines.append(f"**{name} vs {pair['mujoco_env']}**")
        lines.append("")
        lines.append("| term | native mean | mujoco mean | mean abs diff | corr |")
        lines.append("|---|---|---|---|---|")
        for term, s in pair["terms"].items():
            corr = s.get("corr")
            lines.append(
                f"| {term} | {s['native_mean']:+.3f} | {s['mujoco_mean']:+.3f} | "
                f"{s.get('mean_abs_diff', float('nan')):.3f} | "
                f"{'n/a' if corr is None else f'{corr:+.2f}'} |"
            )
        ep = pair["episode"]
        lines.append(
            f"| episode length | {ep['native_mean_length']:.0f} | "
            f"{ep['mujoco_mean_length']:.0f} | | |"
        )
        drift = pair.get("zero_action_drift")
        if drift:
            lines.append("")
            lines.append(
                "Zero-action drift (mean forward velocity, all-zero lane): "
                + ", ".join(f"{k} = {v:+.3f} m/s" for k, v in drift.items())
            )
        lines.append("")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--pairs", default=None, help="comma list (default: all)")
    parser.add_argument("--seqs", type=int, default=8)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None, help="write the JSON report here")
    parser.add_argument("--markdown", action="store_true", help="print the BENCH_NOTES section")
    args = parser.parse_args(argv)

    # host-physics harness: force the CPU backend before any JAX device use
    # (the axon PJRT plugin hangs when the TPU tunnel is down — CLAUDE.md)
    import os

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")

    pairs = None if args.pairs is None else [p.strip() for p in args.pairs.split(",") if p.strip()]
    report = run_fidelity(pairs, n_seqs=args.seqs, n_steps=args.steps, seed=args.seed)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    if args.markdown:
        print(format_fidelity_markdown(report))
    else:
        print(json.dumps(report))


if __name__ == "__main__":
    main()
