"""Maximal-coordinates rigid-body dynamics engine (pure JAX, TPU-first).

This is the substrate for the Humanoid-class flagship workloads: a small
articulated-body simulator in *maximal coordinates* — every body carries its
full 13-dim state (position, quaternion, linear and angular velocity), joints
are stiff spring-damper constraints, and ground contact is a penalty model
with clamped Coulomb-style friction. That formulation (the one Brax v1's
"spring" backend demonstrated for exactly these locomotion tasks) is chosen
deliberately over generalized coordinates: every stage is a fixed-shape
stacked-array computation (gather over joint endpoints, scatter-add of forces,
elementwise integration) with no per-body recursion, so a whole population of
environments vectorizes to ``(popsize, n_bodies, ...)`` arrays with plain
``jax.vmap`` and runs as one fused XLA program.

Parity note: the reference has no simulator of its own — it reaches Brax
through a torch<->jax dlpack bridge (``/root/reference/src/evotorch/
neuroevolution/net/vecrl.py:1366-1490``, ``VectorEnvFromBrax``). Here the
simulator is native to the framework, so the entire population x env x time
loop stays inside one jitted program (``net/vecrl.py:run_vectorized_rollout``).

Conventions
-----------
- Quaternions are ``(w, x, y, z)``.
- Model reference pose: all body frames axis-aligned with the world (identity
  quaternions), origins at each body's center of mass. Joint anchors and axes
  are given in those body frames; relative joint rotation is therefore
  identity in the reference pose.
- Ground is the plane ``z = 0``; gravity points along ``-z``.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "BodyState",
    "System",
    "SystemBuilder",
    "quat_mul",
    "quat_conj",
    "quat_rotate",
    "quat_rotate_inv",
    "quat_to_rotvec",
    "quat_integrate",
    "physics_substep",
    "physics_step",
    "physics_step_batched",
    "joint_angles",
    "joint_velocities",
    "joint_angles_batched",
    "joint_velocities_batched",
    "sphere_penetrations",
    "sphere_penetrations_batched",
    "capsule_inertia",
    "sphere_inertia",
]


# ---------------------------------------------------------------------------
# Quaternion kernels
# ---------------------------------------------------------------------------


def quat_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Hamilton product ``a * b`` over the last axis (``(..., 4)``)."""
    aw, ax, ay, az = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    bw, bx, by, bz = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ],
        axis=-1,
    )


def quat_conj(q: jnp.ndarray) -> jnp.ndarray:
    return q * jnp.asarray([1.0, -1.0, -1.0, -1.0], dtype=q.dtype)


def quat_rotate(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Rotate vector(s) ``v`` by quaternion(s) ``q`` (broadcast over leading
    axes). Uses the 15-mul expansion rather than two Hamilton products."""
    qw = q[..., :1]
    qv = q[..., 1:]
    t = 2.0 * jnp.cross(qv, v)
    return v + qw * t + jnp.cross(qv, t)


def quat_rotate_inv(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    return quat_rotate(quat_conj(q), v)


def quat_to_rotvec(q: jnp.ndarray) -> jnp.ndarray:
    """Log map: quaternion -> axis-angle vector (``(..., 3)``), taking the
    shortest arc. Safe at identity (series limit ``2 * xyz``)."""
    q = jnp.where(q[..., :1] < 0.0, -q, q)  # shortest rotation
    w = q[..., 0]
    xyz = q[..., 1:]
    s = jnp.linalg.norm(xyz, axis=-1)
    angle = 2.0 * jnp.arctan2(s, w)
    # angle/s -> 2/w as s -> 0; keep the division finite everywhere
    scale = jnp.where(s < 1e-7, 2.0, angle / jnp.maximum(s, 1e-12))
    return xyz * scale[..., None]


def quat_integrate(q: jnp.ndarray, omega_world: jnp.ndarray, h) -> jnp.ndarray:
    """First-order quaternion update from a world-frame angular velocity."""
    zero = jnp.zeros_like(omega_world[..., :1])
    omega_q = jnp.concatenate([zero, omega_world], axis=-1)
    q_new = q + 0.5 * h * quat_mul(omega_q, q)
    return q_new / jnp.linalg.norm(q_new, axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# System description + state
# ---------------------------------------------------------------------------


class BodyState(NamedTuple):
    """Dynamic state of all bodies: stacked ``(n_bodies, ...)`` arrays."""

    pos: jnp.ndarray  # (nb, 3) world COM positions
    quat: jnp.ndarray  # (nb, 4) world orientations (w, x, y, z)
    vel: jnp.ndarray  # (nb, 3) world linear velocities
    ang: jnp.ndarray  # (nb, 3) world angular velocities


class System(NamedTuple):
    """Static model description. Arrays here are constants closed over by the
    jitted step, not traced state."""

    # bodies
    mass: jnp.ndarray  # (nb,)
    inertia: jnp.ndarray  # (nb, 3) diagonal body-frame inertia
    # joints
    joint_parent: np.ndarray  # (nj,) int — static gather indices
    joint_child: np.ndarray  # (nj,) int
    anchor_p: jnp.ndarray  # (nj, 3) anchor in parent body frame
    anchor_c: jnp.ndarray  # (nj, 3) anchor in child body frame
    axes: jnp.ndarray  # (nj, 3, 3) joint axes (rows) in parent body frame
    free: jnp.ndarray  # (nj, 3) 1.0 where the axis is a free DOF
    limit_lo: jnp.ndarray  # (nj, 3) lower joint limit per axis (rad)
    limit_hi: jnp.ndarray  # (nj, 3)
    gear: jnp.ndarray  # (nj, 3) actuator torque limit per free axis
    act_index: np.ndarray  # (nj, 3) int — index into the action vector,
    #                         ``num_act`` for unactuated axes (see step)
    num_act: int
    # actuation mode: "torque" (action scales gear directly, MuJoCo-style) or
    # "position" (action maps to a target joint angle inside the limit range;
    # a PD servo with gains act_kp/act_kd tracks it, torque-clipped at gear)
    act_mode: str
    act_kp: jnp.ndarray  # (nj, 3)
    act_kd: jnp.ndarray  # (nj, 3)
    # colliders (spheres vs. ground plane z=0)
    sph_body: np.ndarray  # (ns,) int
    sph_offset: jnp.ndarray  # (ns, 3) in body frame
    sph_radius: jnp.ndarray  # (ns,)
    # per-joint constraint gains. These are derived from target constraint
    # frequencies and the *reduced* mass/inertia of each joint's body pair
    # (k = w^2 m_red, c = 2 zeta w m_red), so light limbs and heavy trunks
    # are equally far from the explicit-integration stability boundary —
    # scalar gains would make arm constraints 1000x stiffer (relative to
    # inertia) than hip constraints.
    pos_k: jnp.ndarray  # (nj,)
    pos_c: jnp.ndarray  # (nj,)
    ang_k: jnp.ndarray  # (nj, 3) per joint axis
    ang_c: jnp.ndarray  # (nj, 3)
    limit_k: jnp.ndarray  # (nj, 3)
    tone_k: jnp.ndarray  # (nj, 3) passive spring toward 0 on free axes
    joint_damping: jnp.ndarray  # (nj, 3) free-axis damping
    # material parameters
    gravity: jnp.ndarray  # (3,)
    contact_k: float
    contact_c: float
    friction_mu: float
    tangent_damping: float
    max_vel: float
    max_ang: float

    @property
    def num_bodies(self) -> int:
        return int(self.mass.shape[0])

    @property
    def num_joints(self) -> int:
        return int(self.anchor_p.shape[0])


# ---------------------------------------------------------------------------
# Dynamics — population-minor ("batch-trailing") formulation
# ---------------------------------------------------------------------------
#
# TPU vector registers are (8 sublanes x 128 lanes) tiles over the two
# minor-most axes. Arrays shaped (popsize, nb, 3) — what `vmap` over a
# single-env step produces — put 3 elements in the 128-lane axis: ~2% lane
# utilization, and the rollout loop carry materializes that padding every
# substep. The engine therefore computes natively on *batch-trailing* arrays
# (nb, 3, B): the population axis fills the lanes, the component axis sits in
# sublanes, and all body gathers/scatters become static row selections /
# one-hot einsum contractions (dense matmuls). Measured on a v5e, this layout
# is >10x faster than the vmap layout for the same loop-carried arithmetic.
# The single-instance API (`physics_step` etc.) is the B=1 special case, so
# there is exactly one implementation of the dynamics.


def _bcross(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Cross product over the component axis -2 (``(..., 3, B)`` layout)."""
    a0, a1, a2 = a[..., 0, :], a[..., 1, :], a[..., 2, :]
    b0, b1, b2 = b[..., 0, :], b[..., 1, :], b[..., 2, :]
    return jnp.stack(
        (a1 * b2 - a2 * b1, a2 * b0 - a0 * b2, a0 * b1 - a1 * b0), axis=-2
    )


def _bquat_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    aw, ax, ay, az = a[..., 0, :], a[..., 1, :], a[..., 2, :], a[..., 3, :]
    bw, bx, by, bz = b[..., 0, :], b[..., 1, :], b[..., 2, :], b[..., 3, :]
    return jnp.stack(
        (
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ),
        axis=-2,
    )


def _bquat_conj(q: jnp.ndarray) -> jnp.ndarray:
    return q * jnp.asarray([1.0, -1.0, -1.0, -1.0], dtype=q.dtype)[:, None]


def _bquat_rotate(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    qw = q[..., :1, :]
    qv = q[..., 1:, :]
    t = 2.0 * _bcross(qv, v)
    return v + qw * t + _bcross(qv, t)


def _bquat_rotate_inv(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    return _bquat_rotate(_bquat_conj(q), v)


def _bquat_to_rotvec(q: jnp.ndarray) -> jnp.ndarray:
    q = jnp.where(q[..., :1, :] < 0.0, -q, q)  # shortest rotation
    w = q[..., 0, :]
    xyz = q[..., 1:, :]
    s = jnp.sqrt(jnp.sum(xyz * xyz, axis=-2))
    angle = 2.0 * jnp.arctan2(s, w)
    scale = jnp.where(s < 1e-7, 2.0, angle / jnp.maximum(s, 1e-12))
    return xyz * scale[..., None, :]


def _bquat_integrate(q: jnp.ndarray, omega_world: jnp.ndarray, h) -> jnp.ndarray:
    zero = jnp.zeros_like(omega_world[..., :1, :])
    omega_q = jnp.concatenate([zero, omega_world], axis=-2)
    q_new = q + 0.5 * h * _bquat_mul(omega_q, q)
    return q_new / jnp.sqrt(jnp.sum(q_new * q_new, axis=-2, keepdims=True))


def _bquat_to_mat(q: jnp.ndarray) -> jnp.ndarray:
    """Rotation matrices ``(..., 3, 3, B)`` from quaternions ``(..., 4, B)``.

    The substep rotates ~8 vectors per body quat (joint anchors, relative
    angular velocities, torques, contact offsets, the body-frame angular
    update): building the matrix once (~20 flops) and applying it at 15
    flops/vector halves the rotation arithmetic vs the 30-flop quat-rotate
    formula — the substep is VPU-flop/fusion bound (BENCH_NOTES.md
    utilization analysis), so this is a direct attack on the dominant cost."""
    w, x, y, z = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    xx, yy, zz = x * x, y * y, z * z
    xy, xz, yz = x * y, x * z, y * z
    wx, wy, wz = w * x, w * y, w * z
    one = jnp.ones_like(w)
    r0 = jnp.stack((one - 2 * (yy + zz), 2 * (xy - wz), 2 * (xz + wy)), axis=-2)
    r1 = jnp.stack((2 * (xy + wz), one - 2 * (xx + zz), 2 * (yz - wx)), axis=-2)
    r2 = jnp.stack((2 * (xz - wy), 2 * (yz + wx), one - 2 * (xx + yy)), axis=-2)
    return jnp.stack((r0, r1, r2), axis=-3)


def _bmat_rotate(R: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Apply ``(..., 3, 3, B)`` rotation matrices to ``(..., 3, B)`` vectors."""
    return jnp.sum(R * v[..., None, :, :], axis=-2)


def _bmat_rotate_inv(R: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Apply the transposed (inverse) rotations."""
    return jnp.sum(R * v[..., :, None, :], axis=-3)


def _one_hot(idx: np.ndarray, n: int, dtype) -> jnp.ndarray:
    """Static selection matrix (len(idx), n); body scatters become matmuls."""
    return jnp.asarray(np.eye(n, dtype=np.float32)[np.asarray(idx)], dtype=dtype)


def _scatter_bodies(hot: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Accumulate per-joint/per-sphere wrenches ``(nj, 3, B)`` onto bodies
    ``(nb, 3, B)`` via a dense one-hot contraction (TPU scatters serialize;
    a (nb, nj) x (nj, 3B) matmul does not)."""
    return jnp.einsum("jb,jkB->bkB", hot, v)


def _joint_forces_batched(sys: System, st: BodyState, actions: jnp.ndarray, R: jnp.ndarray):
    """Per-joint constraint + limit + actuation wrenches for a whole
    population: state arrays ``(nb, comp, B)``, actions ``(num_act, B)``,
    ``R`` the per-body rotation matrices (built once per substep).
    Returns force/torque accumulators ``(nb, 3, B)``."""
    p, c = sys.joint_parent, sys.joint_child
    pq, cq = st.quat[p], st.quat[c]  # (nj, 4, B) — static row gathers
    Rp, Rc = R[p], R[c]
    pp, cp = st.pos[p], st.pos[c]
    pv, cv = st.vel[p], st.vel[c]
    pw, cw = st.ang[p], st.ang[c]

    # --- positional constraint: pull the two anchor points together
    ra = _bmat_rotate(Rp, sys.anchor_p[:, :, None])  # world lever arms
    rb = _bmat_rotate(Rc, sys.anchor_c[:, :, None])
    err = (cp + rb) - (pp + ra)
    verr = (cv + _bcross(cw, rb)) - (pv + _bcross(pw, ra))
    fj = -sys.pos_k[:, None, None] * err - sys.pos_c[:, None, None] * verr

    nb = st.pos.shape[0]
    dtype = st.pos.dtype
    c_hot = _one_hot(c, nb, dtype)
    p_hot = _one_hot(p, nb, dtype)
    inc = c_hot - p_hot  # force on child, reaction on parent
    f = _scatter_bodies(inc, fj)
    tau = _scatter_bodies(c_hot, _bcross(rb, fj)) - _scatter_bodies(
        p_hot, _bcross(ra, fj)
    )

    # --- angular: relative rotation decomposed onto the joint axes
    q_rel = _bquat_mul(_bquat_conj(pq), cq)
    phi = _bquat_to_rotvec(q_rel)  # (nj, 3, B), parent frame
    w_rel = _bmat_rotate_inv(Rp, cw - pw)

    # components along the (orthonormal) joint axes; since the axes form a
    # complete basis, the whole angular response is expressed per component,
    # which lets every axis carry its own gain (a thigh's inertia about its
    # long axis is ~6x smaller than across it — shared gains would put the
    # twist axis past the explicit-integration stability bound)
    phi_comp = jnp.einsum("jak,jkB->jaB", sys.axes, phi)  # (nj, 3, B)
    w_comp = jnp.einsum("jak,jkB->jaB", sys.axes, w_rel)

    limit_hi = sys.limit_hi[:, :, None]
    limit_lo = sys.limit_lo[:, :, None]
    gear = sys.gear[:, :, None]
    over = jnp.maximum(phi_comp - limit_hi, 0.0)
    under = jnp.maximum(limit_lo - phi_comp, 0.0)
    act = jnp.concatenate(
        [actions, jnp.zeros((1,) + actions.shape[1:], dtype=actions.dtype)]
    )
    drive = act[sys.act_index]  # (nj, 3, B); 0 for unactuated axes
    actuated = (gear > 0.0).astype(dtype)
    if sys.act_mode == "position":
        # action in [-1, 1] maps to a target angle: 0 is the reference pose,
        # +/-1 the joint limits; a torque-clipped PD servo tracks it
        target = jnp.where(drive >= 0.0, drive * limit_hi, -drive * limit_lo)
        pd = sys.act_kp[:, :, None] * (target - phi_comp) - sys.act_kd[:, :, None] * w_comp
        act_torque = actuated * jnp.clip(pd, -gear, gear)
    else:
        act_torque = gear * drive
    free = sys.free[:, :, None]
    locked = 1.0 - free
    comp_torque = locked * (
        -sys.ang_k[:, :, None] * phi_comp - sys.ang_c[:, :, None] * w_comp
    ) + free * (
        sys.limit_k[:, :, None] * (under - over)
        - sys.tone_k[:, :, None] * phi_comp
        - sys.joint_damping[:, :, None] * w_comp
        + act_torque
    )
    tau_j = jnp.einsum("jak,jaB->jkB", sys.axes, comp_torque)

    tau_w = _bmat_rotate(Rp, tau_j)  # parent frame -> world
    tau = tau + _scatter_bodies(inc, tau_w)
    return f, tau


def _contact_forces_batched(sys: System, st: BodyState, R: jnp.ndarray):
    """Sphere-vs-ground penalty contacts with clamped viscous friction,
    population-batched (``(ns, 3, B)`` intermediates)."""
    b = sys.sph_body
    dtype = st.pos.dtype
    r_off = _bmat_rotate(R[b], sys.sph_offset[:, :, None])
    pen = sys.sph_radius[:, None] - (st.pos[b][..., 2, :] + r_off[..., 2, :])
    in_contact = pen > 0.0

    # velocity of the lowest point of each sphere
    e_z = jnp.asarray([0.0, 0.0, 1.0], dtype=dtype)[:, None]
    rel = r_off - sys.sph_radius[:, None, None] * e_z
    vc = st.vel[b] + _bcross(st.ang[b], rel)

    fn = jnp.maximum(sys.contact_k * pen - sys.contact_c * vc[..., 2, :], 0.0)
    fn = jnp.where(in_contact, fn, 0.0)

    vt = vc * jnp.asarray([1.0, 1.0, 0.0], dtype=dtype)[:, None]
    vt_norm = jnp.sqrt(vt[..., 0, :] ** 2 + vt[..., 1, :] ** 2)
    # clamped viscous friction: viscous at small slip, Coulomb cap mu*N above
    ft_mag = jnp.minimum(sys.friction_mu * fn, sys.tangent_damping * vt_norm)
    ft = -vt * (ft_mag / jnp.maximum(vt_norm, 1e-6))[..., None, :]
    fc = ft + fn[..., None, :] * e_z

    nb = st.pos.shape[0]
    s_hot = _one_hot(b, nb, dtype)
    f = _scatter_bodies(s_hot, fc)
    tau = _scatter_bodies(s_hot, _bcross(rel, fc))
    return f, tau


def physics_substep_batched(
    sys: System, st: BodyState, actions: jnp.ndarray, h
) -> BodyState:
    """One semi-implicit Euler substep for a population: ``st`` arrays are
    ``(nb, comp, B)``, ``actions`` ``(num_act, B)``."""
    # per-body rotation matrices, built ONCE and shared by every rotation in
    # the substep (joints, contacts, body-frame angular update)
    R = _bquat_to_mat(st.quat)
    fj, tj = _joint_forces_batched(sys, st, actions, R)
    fc, tc = _contact_forces_batched(sys, st, R)
    mass = sys.mass[:, None, None]
    f = fj + fc + mass * sys.gravity[None, :, None]
    tau = tj + tc

    vel = st.vel + h * f / mass
    # angular update in the body frame, where the inertia tensor is diagonal
    inertia = sys.inertia[:, :, None]
    w_body = _bmat_rotate_inv(R, st.ang)
    tau_body = _bmat_rotate_inv(R, tau)
    w_body = w_body + h * (tau_body - _bcross(w_body, inertia * w_body)) / inertia
    ang = _bmat_rotate(R, w_body)

    # stability clamps: cap velocities so stiff-spring transients cannot blow up
    vel = jnp.clip(vel, -sys.max_vel, sys.max_vel)
    ang = jnp.clip(ang, -sys.max_ang, sys.max_ang)

    pos = st.pos + h * vel
    quat = _bquat_integrate(st.quat, ang, h)
    return BodyState(pos=pos, quat=quat, vel=vel, ang=ang)


def physics_step_batched(
    sys: System, st: BodyState, actions: jnp.ndarray, dt: float, substeps: int
) -> BodyState:
    """One control step = ``substeps`` substeps with the action held. Unrolled
    (``substeps`` is static and small) so XLA can fuse across substeps."""
    h = dt / substeps
    for _ in range(int(substeps)):
        st = physics_substep_batched(sys, st, actions, h)
    return st


# -- single-instance API: the B=1 special case ------------------------------


def _to_batched(st: BodyState) -> BodyState:
    return BodyState(*(x[..., None] for x in st))


def _from_batched(st: BodyState) -> BodyState:
    return BodyState(*(x[..., 0] for x in st))


def physics_substep(sys: System, st: BodyState, actions: jnp.ndarray, h) -> BodyState:
    """One semi-implicit Euler substep for all bodies (single instance)."""
    out = physics_substep_batched(sys, _to_batched(st), actions[..., None], h)
    return _from_batched(out)


def physics_step(
    sys: System, st: BodyState, actions: jnp.ndarray, dt: float, substeps: int
) -> BodyState:
    """One control step = ``substeps`` physics substeps with the action held."""
    out = physics_step_batched(
        sys, _to_batched(st), actions[..., None], dt, substeps
    )
    return _from_batched(out)


# ---------------------------------------------------------------------------
# Measurements (observations)
# ---------------------------------------------------------------------------


def joint_angles_batched(sys: System, st: BodyState) -> jnp.ndarray:
    """Rotation of each joint decomposed onto its axes, ``(nj, 3, B)``."""
    pq = st.quat[sys.joint_parent]
    cq = st.quat[sys.joint_child]
    phi = _bquat_to_rotvec(_bquat_mul(_bquat_conj(pq), cq))
    return jnp.einsum("jak,jkB->jaB", sys.axes, phi)


def joint_velocities_batched(sys: System, st: BodyState) -> jnp.ndarray:
    """Relative angular velocity of each joint on its axes, ``(nj, 3, B)``."""
    p, c = sys.joint_parent, sys.joint_child
    w_rel = _bquat_rotate_inv(st.quat[p], st.ang[c] - st.ang[p])
    return jnp.einsum("jak,jkB->jaB", sys.axes, w_rel)


def sphere_penetrations_batched(sys: System, st: BodyState) -> jnp.ndarray:
    """Ground penetration depth per collider sphere (``(ns, B)``, >= 0)."""
    b = sys.sph_body
    r_off = _bquat_rotate(st.quat[b], sys.sph_offset[:, :, None])
    center_z = st.pos[b][..., 2, :] + r_off[..., 2, :]
    return jnp.maximum(sys.sph_radius[:, None] - center_z, 0.0)


def joint_angles(sys: System, st: BodyState) -> jnp.ndarray:
    """Rotation of each joint decomposed onto its axes, ``(nj, 3)``."""
    return joint_angles_batched(sys, _to_batched(st))[..., 0]


def joint_velocities(sys: System, st: BodyState) -> jnp.ndarray:
    """Relative angular velocity of each joint on its axes, ``(nj, 3)``."""
    return joint_velocities_batched(sys, _to_batched(st))[..., 0]


def sphere_penetrations(sys: System, st: BodyState) -> jnp.ndarray:
    """Ground penetration depth per collider sphere (``(ns,)``, clipped >=0)."""
    return sphere_penetrations_batched(sys, _to_batched(st))[..., 0]


# ---------------------------------------------------------------------------
# Inertia helpers + builder
# ---------------------------------------------------------------------------


def capsule_inertia(mass: float, radius: float, length: float, axis: str) -> np.ndarray:
    """Diagonal inertia of a capsule approximated as a solid cylinder of the
    same total length, aligned with ``axis`` in {'x','y','z'}."""
    i_axis = 0.5 * mass * radius**2
    i_perp = mass * (3.0 * radius**2 + length**2) / 12.0
    diag = {"x": (i_axis, i_perp, i_perp), "y": (i_perp, i_axis, i_perp), "z": (i_perp, i_perp, i_axis)}
    return np.asarray(diag[axis], dtype=np.float64)


def sphere_inertia(mass: float, radius: float) -> np.ndarray:
    i = 0.4 * mass * radius**2
    return np.asarray([i, i, i], dtype=np.float64)


def _orthonormal_axes() -> np.ndarray:
    return np.eye(3, dtype=np.float64)


class SystemBuilder:
    """Incrementally assemble a :class:`System` in the reference pose.

    Bodies are declared with world COM positions (identity orientation);
    joints with world anchor points and world axes — the builder converts
    everything to body frames (trivially, since the reference pose is
    axis-aligned).
    """

    def __init__(
        self,
        *,
        gravity: float = -9.81,
        omega_pos: float = 250.0,
        omega_ang: float = 150.0,
        zeta: float = 1.0,
        limit_gain: float = 4.0,
        tone_ratio: float = 0.1,
        free_damping_ratio: float = 0.1,
        contact_k: float = 20_000.0,
        contact_c: float = 60.0,
        friction_mu: float = 1.0,
        tangent_damping: float = 400.0,
        max_vel: float = 50.0,
        max_ang: float = 40.0,
        act_mode: str = "torque",
        act_kp_ratio: float = 1.0,
        act_kd_ratio: float = 1.0,
    ):
        """``omega_pos``/``omega_ang`` (rad/s) are the target constraint
        frequencies; actual spring constants are scaled per joint by the
        reduced mass/inertia of the connected body pair, keeping every
        constraint at the same distance from the semi-implicit-Euler
        stability boundary (``h * omega < 2``). ``zeta`` is the damping
        ratio; ``limit_gain`` scales limit springs relative to the lock
        spring; ``tone_ratio`` adds a weak passive spring pulling free DOF
        toward the reference pose (muscle tone); ``free_damping_ratio``
        scales free-axis damping relative to the lock damping."""
        if act_mode not in ("torque", "position"):
            raise ValueError(f"act_mode must be 'torque' or 'position', got {act_mode!r}")
        self._params = dict(
            gravity=np.asarray([0.0, 0.0, gravity]),
            omega_pos=omega_pos,
            omega_ang=omega_ang,
            zeta=zeta,
            limit_gain=limit_gain,
            tone_ratio=tone_ratio,
            free_damping_ratio=free_damping_ratio,
            contact_k=contact_k,
            contact_c=contact_c,
            friction_mu=friction_mu,
            tangent_damping=tangent_damping,
            max_vel=max_vel,
            max_ang=max_ang,
            act_mode=act_mode,
            act_kp_ratio=act_kp_ratio,
            act_kd_ratio=act_kd_ratio,
        )
        self._names: List[str] = []
        self._pos: List[np.ndarray] = []
        self._mass: List[float] = []
        self._inertia: List[np.ndarray] = []
        self._joints: List[dict] = []
        self._spheres: List[Tuple[int, np.ndarray, float]] = []

    # -- bodies ------------------------------------------------------------
    def add_body(self, name: str, pos, mass: float, inertia) -> int:
        idx = len(self._names)
        self._names.append(name)
        self._pos.append(np.asarray(pos, dtype=np.float64))
        self._mass.append(float(mass))
        self._inertia.append(np.asarray(inertia, dtype=np.float64))
        return idx

    def body_index(self, name: str) -> int:
        return self._names.index(name)

    @property
    def body_positions(self) -> np.ndarray:
        return np.stack(self._pos)

    # -- joints ------------------------------------------------------------
    def add_joint(
        self,
        parent: str,
        child: str,
        world_anchor,
        *,
        free_axes: Sequence[str],
        limits: Sequence[Tuple[float, float]],
        gears: Sequence[float],
        axes: Optional[np.ndarray] = None,
        tone: Optional[float] = None,
    ):
        """``free_axes`` names rows of ``axes`` (default world x/y/z) that are
        free DOF, in action order; ``limits``/``gears`` align with them.
        ``tone`` (Nm/rad) overrides the default passive spring toward the
        reference pose on this joint's free axes — posture joints that must
        resist inverted-pendulum gravity torques passively need more than the
        inertia-scaled default."""
        if not (len(free_axes) == len(limits) == len(gears)):
            raise ValueError(
                f"free_axes/limits/gears must align: got {len(free_axes)}/"
                f"{len(limits)}/{len(gears)} for joint {parent}->{child}"
            )
        p = self.body_index(parent)
        c = self.body_index(child)
        anchor = np.asarray(world_anchor, dtype=np.float64)
        axes = _orthonormal_axes() if axes is None else np.asarray(axes, dtype=np.float64)
        name_to_row = {"x": 0, "y": 1, "z": 2}
        free = np.zeros(3)
        lo = np.zeros(3)
        hi = np.zeros(3)
        gear = np.zeros(3)
        order = []
        for ax_name, (l, u), g in zip(free_axes, limits, gears):
            row = name_to_row[ax_name]
            free[row] = 1.0
            lo[row], hi[row] = float(l), float(u)
            gear[row] = float(g)
            order.append(row)
        self._joints.append(
            dict(
                parent=p,
                child=c,
                anchor_p=anchor - self._pos[p],
                anchor_c=anchor - self._pos[c],
                axes=axes,
                free=free,
                lo=lo,
                hi=hi,
                gear=gear,
                order=order,
                tone=tone,
            )
        )

    # -- colliders ---------------------------------------------------------
    def add_sphere(self, body: str, world_center, radius: float):
        b = self.body_index(body)
        center = np.asarray(world_center, dtype=np.float64)
        self._spheres.append((b, center - self._pos[b], float(radius)))

    # -- finalize ----------------------------------------------------------
    def build(self) -> Tuple[System, jnp.ndarray]:
        """Returns ``(system, default_pose_positions)``; action indices are
        assigned in joint declaration order, then per-joint axis order."""
        def stack(key_or_rows, shape):
            rows = (
                [s[key_or_rows] for s in self._joints]
                if isinstance(key_or_rows, str)
                else key_or_rows
            )
            if not rows:
                return np.zeros((0,) + shape)
            return np.stack(rows)

        nj = len(self._joints)
        act_index = np.full((nj, 3), -1, dtype=np.int64)
        n_act = 0
        for j, spec in enumerate(self._joints):
            for row in spec["order"]:
                act_index[j, row] = n_act
                n_act += 1
        act_index[act_index < 0] = n_act  # points at the appended zero action

        # per-joint gains from target frequencies x reduced mass/inertia
        masses = np.asarray(self._mass)
        i_mean = np.stack(self._inertia).mean(axis=1)
        jp = np.asarray([s["parent"] for s in self._joints], dtype=np.int64)
        jc = np.asarray([s["child"] for s in self._joints], dtype=np.int64)
        # constraint-space effective mass: anchor forces also spin the bodies
        # through their lever arms (r^2/I), which for slender bodies dominates
        # 1/m — ignoring it puts the rotational response of light links past
        # the explicit-integration stability bound.
        r_p2 = np.sum(stack("anchor_p", (3,)) ** 2, axis=1)
        r_c2 = np.sum(stack("anchor_c", (3,)) ** 2, axis=1)
        inv_m_eff = 1.0 / masses[jp] + 1.0 / masses[jc] + r_p2 / i_mean[jp] + r_c2 / i_mean[jc]
        m_eff = 1.0 / inv_m_eff
        # per-axis reduced inertia: joint axes are world-aligned in the
        # reference pose, so axis a pairs with inertia component a of each body
        inertias = np.stack(self._inertia)
        i_red = inertias[jp] * inertias[jc] / (inertias[jp] + inertias[jc])  # (nj, 3)
        P = self._params
        pos_k = P["omega_pos"] ** 2 * m_eff
        pos_c = 2.0 * P["zeta"] * P["omega_pos"] * m_eff
        ang_k = P["omega_ang"] ** 2 * i_red
        ang_c = 2.0 * P["zeta"] * P["omega_ang"] * i_red

        f32 = jnp.float32
        sys = System(
            mass=jnp.asarray(self._mass, dtype=f32),
            inertia=jnp.asarray(np.stack(self._inertia), dtype=f32),
            joint_parent=jp,
            joint_child=jc,
            anchor_p=jnp.asarray(stack("anchor_p", (3,)), dtype=f32),
            anchor_c=jnp.asarray(stack("anchor_c", (3,)), dtype=f32),
            axes=jnp.asarray(stack("axes", (3, 3)), dtype=f32),
            free=jnp.asarray(stack("free", (3,)), dtype=f32),
            limit_lo=jnp.asarray(stack("lo", (3,)), dtype=f32),
            limit_hi=jnp.asarray(stack("hi", (3,)), dtype=f32),
            gear=jnp.asarray(stack("gear", (3,)), dtype=f32),
            act_index=act_index,
            num_act=n_act,
            act_mode=P["act_mode"],
            act_kp=jnp.asarray(P["act_kp_ratio"] * ang_k, dtype=f32),
            act_kd=jnp.asarray(P["act_kd_ratio"] * ang_c, dtype=f32),
            sph_body=np.asarray([s[0] for s in self._spheres], dtype=np.int64),
            sph_offset=jnp.asarray(stack([s[1] for s in self._spheres], (3,)), dtype=f32),
            sph_radius=jnp.asarray(np.asarray([s[2] for s in self._spheres]), dtype=f32),
            pos_k=jnp.asarray(pos_k, dtype=f32),
            pos_c=jnp.asarray(pos_c, dtype=f32),
            ang_k=jnp.asarray(ang_k, dtype=f32),
            ang_c=jnp.asarray(ang_c, dtype=f32),
            limit_k=jnp.asarray(P["limit_gain"] * ang_k, dtype=f32),
            tone_k=jnp.asarray(
                stack(
                    [
                        P["tone_ratio"] * k if s["tone"] is None else np.full(3, s["tone"])
                        for k, s in zip(ang_k, self._joints)
                    ],
                    (3,),
                ),
                dtype=f32,
            ),
            joint_damping=jnp.asarray(P["free_damping_ratio"] * ang_c, dtype=f32),
            gravity=jnp.asarray(P["gravity"], dtype=f32),
            contact_k=P["contact_k"],
            contact_c=P["contact_c"],
            friction_mu=P["friction_mu"],
            tangent_damping=P["tangent_damping"],
            max_vel=P["max_vel"],
            max_ang=P["max_ang"],
        )
        return sys, jnp.asarray(self.body_positions, dtype=f32)
