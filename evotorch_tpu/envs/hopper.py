"""Planar SLIP hopper: locomotion with contact dynamics.

A spring-loaded inverted pendulum (SLIP) monopod — the canonical reduced
model of running (Blickhan 1989; Raibert's hoppers). Unlike the toy swimmer,
this env has genuine hybrid dynamics (ballistic flight, compliant stance,
touchdown/liftoff events), all expressed with ``jnp.where`` phase masking so
the whole thing stays jittable — the benchmark stand-in for Brax-style
locomotion in this image (Brax is not installed).

Controls: target leg angle during flight (foot placement) and stance thrust
(spring precompression, Raibert's energy-injection scheme). Reward: forward
velocity minus control cost; the episode ends when the body falls.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..tools.pytree import replace
from .base import Env, EnvState, Space

__all__ = ["Hopper"]


class Hopper(Env):
    max_episode_steps = 1000

    def __init__(self):
        self.observation_space = Space(shape=(7,))
        self.action_space = Space(
            shape=(2,), lb=jnp.array([-1.0, 0.0]), ub=jnp.array([1.0, 1.0])
        )
        self.g = 9.81
        self.m = 1.0  # body mass
        self.r0 = 1.0  # rest leg length
        self.k = 150.0  # spring stiffness
        self.dt = 0.02
        self.substeps = 4
        self.max_leg_angle = 0.5  # rad, from vertical
        self.max_thrust = 0.15  # max spring precompression (m)
        self.fall_height = 0.35

    # state vector: [x, z, vx, vz, leg_angle, foot_x, in_stance]
    def _obs(self, s):
        x, z, vx, vz, theta, foot_x, stance = s
        # leg compression is observable in stance
        r = jnp.where(
            stance > 0.5,
            jnp.sqrt(jnp.maximum((x - foot_x) ** 2 + z**2, 1e-6)),
            self.r0,
        )
        return jnp.stack([z, vx, vz, theta, r, stance, jnp.sin(theta)])

    def reset(self, key):
        key, sub = jax.random.split(key)
        perturb = jax.random.uniform(sub, (2,), minval=-0.05, maxval=0.05)
        s = jnp.array([0.0, 1.05 + perturb[0], 0.0 + perturb[1], 0.0, 0.0, 0.0, 0.0])
        return EnvState(obs_state=s, t=jnp.zeros((), jnp.int32), key=key), self._obs(s)

    def _substep(self, s, action):
        x, z, vx, vz, theta, foot_x, stance = s
        # step() already clipped the action to the space bounds
        target_angle = self.max_leg_angle * action[0]
        thrust = self.max_thrust * action[1]
        h = self.dt / self.substeps

        # flight: ballistic body, leg servos toward the target angle
        theta_flight = theta + jnp.clip(target_angle - theta, -8.0 * h, 8.0 * h)
        z_flight = z + h * vz
        x_flight = x + h * vx
        vz_flight = vz - h * self.g

        # touchdown check (after the flight integration)
        foot_height = z_flight - self.r0 * jnp.cos(theta_flight)
        touchdown = (stance < 0.5) & (foot_height <= 0.0) & (vz_flight < 0.0)
        new_foot_x = jnp.where(
            touchdown, x_flight + self.r0 * jnp.sin(theta_flight), foot_x
        )

        # stance: spring force along the leg (with thrust precompression).
        # the contact is unilateral — the ground can only push, so the spring
        # force clamps at zero once the leg extends past its (precompressed)
        # rest length; without the clamp the leg would act as a tether and
        # yank fast forward hops back down
        dx = x - new_foot_x
        r = jnp.sqrt(jnp.maximum(dx**2 + z**2, 1e-6))
        leg_dir_x = dx / r
        leg_dir_z = z / r
        spring_force = jnp.maximum(self.k * (self.r0 + thrust - r), 0.0)
        ax = spring_force * leg_dir_x / self.m
        az = spring_force * leg_dir_z / self.m - self.g
        vx_stance = vx + h * ax
        vz_stance = vz + h * az
        x_stance = x + h * vx_stance
        z_stance = z + h * vz_stance
        # same sign convention as flight: positive theta = foot forward of body
        theta_stance = jnp.arctan2(new_foot_x - x_stance, z_stance)

        # liftoff: the leg reached its rest length (force has hit zero)
        r_new = jnp.sqrt(jnp.maximum((x_stance - new_foot_x) ** 2 + z_stance**2, 1e-6))
        liftoff = (stance > 0.5) & (r_new >= self.r0 + thrust)

        in_stance = jnp.where(stance > 0.5, ~liftoff, touchdown)

        pick = lambda a, b: jnp.where(stance > 0.5, a, b)  # noqa: E731
        s_next = jnp.stack(
            [
                pick(x_stance, x_flight),
                pick(z_stance, z_flight),
                pick(vx_stance, vx),
                pick(vz_stance, vz_flight),
                pick(theta_stance, theta_flight),
                new_foot_x,
                in_stance.astype(jnp.float32),
            ]
        )
        return s_next

    def step(self, state: EnvState, action):
        action = jnp.clip(
            jnp.reshape(action, (2,)), self.action_space.lb, self.action_space.ub
        )
        s = state.obs_state

        def body(i, s):
            return self._substep(s, action)

        s = jax.lax.fori_loop(0, self.substeps, body, s)
        t = state.t + 1
        fallen = s[1] < self.fall_height
        done = fallen | (t >= self.max_episode_steps)
        reward = s[2] - 0.001 * jnp.sum(action**2) + 0.5  # forward speed + alive
        reward = jnp.where(fallen, reward - 2.0, reward)
        return replace(state, obs_state=s, t=t), self._obs(s), reward, done
