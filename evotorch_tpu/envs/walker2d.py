"""Walker2D: planar biped locomotion on the maximal-coordinates engine (6 DOF).

A MuJoCo-Walker2d-class biped: torso plus two legs of thigh / shin / foot,
7 bodies and 6 actuated rotational DOF (hip, knee, ankle per leg, all about
the y axis). The MuJoCo original lives in a 2-D world; here the engine is
3-D and the task sets ``planar = True``, which projects each control step
back onto the x-z sagittal plane (``locomotion.py``) — the TPU-native form
of simply not modelling the lateral DOF. Reward mirrors ``Walker2d-v4``:
forward velocity + alive bonus - control cost, terminating outside the
healthy height band.

This is one of BASELINE.md's five PGPE recipe environments (reference
``examples/scripts/rl_clipup.py:170-177``); the reference reaches it through
gym/MuJoCo, this framework natively.
"""

from __future__ import annotations

from .locomotion import RigidBodyLocomotionEnv
from .rigidbody import SystemBuilder, capsule_inertia

__all__ = ["Walker2D"]


def _build_walker(act_mode: str = "position"):
    b = SystemBuilder(
        omega_pos=200.0,
        omega_ang=200.0,
        zeta=1.0,
        limit_gain=4.0,
        tone_ratio=0.1,
        free_damping_ratio=0.1,
        contact_k=15_000.0,
        contact_c=300.0,
        friction_mu=1.0,
        tangent_damping=300.0,
        act_mode=act_mode,
        act_kp_ratio=2.0,
    )

    # Bodies (x forward, z up, ground 0); proportions track the MuJoCo
    # walker2d: torso 0.4, thigh 0.45, shin 0.5, foot 0.2 along x. The legs
    # sit at y=+/-0.05 for plausible inertia; the planar projection keeps
    # them in their plane.
    b.add_body("torso", (0, 0, 1.25), 3.7, capsule_inertia(3.7, 0.07, 0.40, "z"))
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.05 * sy
        b.add_body(f"{side}_thigh", (0, y, 0.825), 4.0, capsule_inertia(4.0, 0.05, 0.45, "z"))
        b.add_body(f"{side}_shin", (0, y, 0.35), 2.7, capsule_inertia(2.7, 0.04, 0.50, "z"))
        b.add_body(f"{side}_foot", (0.06, y, 0.06), 3.2, capsule_inertia(3.2, 0.05, 0.20, "x"))

    # Joints: 6 actuated DOF, all about y (sagittal plane). Action layout:
    #   0 r_hip, 1 r_knee, 2 r_ankle, 3 l_hip, 4 l_knee, 5 l_ankle
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.05 * sy
        b.add_joint(
            "torso", f"{side}_thigh", (0, y, 1.05),
            free_axes=("y",), limits=[(-1.0, 1.2)], gears=(80.0,),
        )
        b.add_joint(
            f"{side}_thigh", f"{side}_shin", (0, y, 0.60),
            free_axes=("y",), limits=[(-2.6, 0.05)], gears=(60.0,),
        )
        b.add_joint(
            f"{side}_shin", f"{side}_foot", (0, y, 0.10),
            free_axes=("y",), limits=[(-0.8, 0.8)], gears=(30.0,),
        )

    # Colliders: heel + toe per foot first (contact depths observed).
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.05 * sy
        b.add_sphere(f"{side}_foot", (-0.03, y, 0.05), 0.05)  # heel
        b.add_sphere(f"{side}_foot", (0.16, y, 0.05), 0.05)  # toe
    b.add_sphere("torso", (0, 0, 1.25), 0.07)

    return b.build()


class Walker2D(RigidBodyLocomotionEnv):
    """Planar biped locomotion; ``Walker2d-v4``-style reward and DOF budget
    (6 actuated DOF: hip/knee/ankle per leg, sagittal plane only)."""

    planar = True

    def __init__(
        self,
        *,
        forward_reward_weight: float = 1.0,
        alive_bonus: float = 1.0,
        ctrl_cost_weight: float = 0.001,
        healthy_z_range=(0.8, 2.0),
        reset_noise_scale: float = 0.005,
        act_mode: str = "position",
        dt: float = 0.015,
        substeps: int = 8,
    ):
        self.sys, self._default_pos = _build_walker(act_mode)
        self.dt = float(dt)
        self.substeps = int(substeps)
        self.forward_reward_weight = forward_reward_weight
        self.alive_bonus = alive_bonus
        self.ctrl_cost_weight = ctrl_cost_weight
        self.healthy_z_range = healthy_z_range
        self.reset_noise_scale = reset_noise_scale
        self._finalize_spaces()
