"""HalfCheetah: planar galloper on the maximal-coordinates engine (6 DOF).

A MuJoCo-HalfCheetah-class planar runner: a long horizontal torso with one
back and one front leg (thigh / shin / foot each), 7 bodies and 6 actuated
rotational DOF about y. Like the MuJoCo original the task is planar
(``planar = True`` -> sagittal-plane projection, ``locomotion.py``) and
**never terminates** — the cheetah is free to tumble; the episode runs its
full length and reward is purely ``forward_velocity - ctrl_cost``
(``HalfCheetah-v4`` semantics: no alive bonus, no healthy band).

Part of the BASELINE.md recipe-environment coverage (reference
``examples/scripts/rl_clipup.py``); the reference reaches it through
gym/MuJoCo, this framework natively.
"""

from __future__ import annotations

import jax.numpy as jnp

from .locomotion import RigidBodyLocomotionEnv
from .rigidbody import SystemBuilder, capsule_inertia

__all__ = ["HalfCheetah"]


def _build_halfcheetah(act_mode: str = "position"):
    b = SystemBuilder(
        omega_pos=200.0,
        omega_ang=200.0,
        zeta=1.0,
        limit_gain=4.0,
        tone_ratio=0.1,
        free_damping_ratio=0.1,
        contact_k=15_000.0,
        # near-critical contact damping (c_crit ~= 2*sqrt(k * m/leg) ~= 650):
        # underdamped feet micro-bounce, and with a single foot sphere the
        # bounce rectified through friction into a steady 1.5 m/s zero-action
        # glide — free reward. Heel+toe feet symmetric about the ankle plus
        # this damping bound the zero-action drift to a +/-0.1 m rock.
        contact_c=600.0,
        friction_mu=1.0,
        tangent_damping=300.0,
        act_mode=act_mode,
        act_kp_ratio=2.0,
    )

    # Bodies (x forward, z up): a 1.0 m horizontal torso at hip height with a
    # back leg hanging from its rear and a front leg from its nose, each
    # thigh 0.29 / shin 0.26 / foot. Masses track the MuJoCo cheetah (~14 kg).
    # The foot capsule length also sets the tangent-damping stability margin
    # (c * r^2 / I * h < 2, rigidbody.py): 0.16 keeps it ~1.4.
    z0 = 0.60
    b.add_body("torso", (0, 0, z0), 6.4, capsule_inertia(6.4, 0.046, 1.0, "x"))
    for part, px in (("back", -0.5), ("front", 0.5)):
        b.add_body(f"{part}_thigh", (px, 0, z0 - 0.145), 1.5, capsule_inertia(1.5, 0.045, 0.29, "z"))
        b.add_body(f"{part}_shin", (px, 0, z0 - 0.42), 1.2, capsule_inertia(1.2, 0.04, 0.26, "z"))
        b.add_body(f"{part}_foot", (px, 0, z0 - 0.52), 0.9, capsule_inertia(0.9, 0.04, 0.16, "x"))

    # Joints: 6 actuated DOF about y. Action layout:
    #   0 back_hip, 1 back_knee, 2 back_ankle,
    #   3 front_hip, 4 front_knee, 5 front_ankle
    # Ranges loosely track the MuJoCo cheetah's asymmetric hips/knees.
    for part, px, hip, knee, ankle in (
        ("back", -0.5, (-0.6, 1.0), (-1.2, 0.8), (-0.5, 0.8)),
        ("front", 0.5, (-1.0, 0.7), (-1.1, 0.8), (-0.5, 0.5)),
    ):
        b.add_joint(
            "torso", f"{part}_thigh", (px, 0, z0),
            free_axes=("y",), limits=[hip], gears=(90.0,),
        )
        b.add_joint(
            f"{part}_thigh", f"{part}_shin", (px, 0, z0 - 0.29),
            free_axes=("y",), limits=[knee], gears=(60.0,),
        )
        b.add_joint(
            f"{part}_shin", f"{part}_foot", (px, 0, z0 - 0.55),
            free_axes=("y",), limits=[ankle], gears=(30.0,),
        )

    # Colliders: heel + toe per foot first (observed contacts), then torso.
    for part, px in (("back", -0.5), ("front", 0.5)):
        b.add_sphere(f"{part}_foot", (px - 0.055, 0, z0 - 0.55), 0.046)  # heel
        b.add_sphere(f"{part}_foot", (px + 0.055, 0, z0 - 0.55), 0.046)  # toe
    b.add_sphere("torso", (-0.5, 0, z0), 0.046)
    b.add_sphere("torso", (0.55, 0, z0 + 0.05), 0.046)  # head
    return b.build()


class HalfCheetah(RigidBodyLocomotionEnv):
    """Planar cheetah; ``HalfCheetah-v4`` semantics: 6 actuated DOF, pure
    ``forward_velocity - 0.1 * ||action||^2`` reward, no termination."""

    planar = True
    n_contact_obs = 4

    def __init__(
        self,
        *,
        forward_reward_weight: float = 1.0,
        ctrl_cost_weight: float = 0.1,
        reset_noise_scale: float = 0.005,
        act_mode: str = "position",
        dt: float = 0.015,
        substeps: int = 8,
    ):
        self.sys, self._default_pos = _build_halfcheetah(act_mode)
        self.dt = float(dt)
        self.substeps = int(substeps)
        self.forward_reward_weight = forward_reward_weight
        self.alive_bonus = 0.0
        self.ctrl_cost_weight = ctrl_cost_weight
        self.reset_noise_scale = reset_noise_scale
        self._finalize_spaces()

    def _batch_reward_done(self, st, actions_minor, t):
        # HalfCheetah never terminates: tumbling is allowed, only the time
        # limit ends the episode (gymnasium HalfCheetah-v4 semantics)
        forward_vel = st.vel[0, 0, :]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(actions_minor * actions_minor, axis=0)
        reward = self.forward_reward_weight * forward_vel - ctrl_cost
        done = t >= self.max_episode_steps
        return reward, done

    def batch_reward_terms(self, st, actions_minor):
        """No alive bonus and no healthy band (HalfCheetah-v5 semantics):
        the survive term is identically zero and every state is healthy."""
        B = st.pos.shape[-1]
        forward_vel = st.vel[0, 0, :]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(actions_minor * actions_minor, axis=0)
        return {
            "x_velocity": forward_vel,
            "reward_forward": self.forward_reward_weight * forward_vel,
            "reward_ctrl": -ctrl_cost,
            "reward_survive": jnp.zeros(B),
            "healthy": jnp.ones(B, dtype=bool),
        }
