"""Environment registry.

Parity: the reference resolves env strings like ``"gym::Humanoid-v4"`` or
``"brax::humanoid"`` (``vecgymne.py:496-570``, ``net/vecrl.py:764-860``).
Here plain names resolve to the pure-JAX envs; ``"brax::<name>"`` adapts a
brax env when brax is importable.
"""

from __future__ import annotations

from typing import Callable, Dict

from .base import Env

__all__ = ["canonical_env_key", "make_env", "register_env"]

_REGISTRY: Dict[str, Callable[..., Env]] = {}
#: normalized alias (registered name OR factory class name) -> the ONE
#: canonical key (the first name the factory was registered under), so
#: "walker"/"walker2d"/Walker2D and "halfcheetah"/"half_cheetah" all
#: resolve to a single identity — the tuned-config cache keys on it
_CANONICAL: Dict[str, str] = {}


def register_env(name: str, factory: Callable[..., Env]):
    key = name.lower()
    # aliases of an already-registered factory fold to its first name
    existing = [k for k, f in _REGISTRY.items() if f is factory]
    canonical = _CANONICAL[existing[0]] if existing else key
    _REGISTRY[key] = factory
    _CANONICAL[key] = canonical
    if isinstance(factory, type):
        # a live instance's identity is its class name (Swimmer2D() must
        # hit entries tuned via the registered string "swimmer")
        _CANONICAL.setdefault(factory.__name__.lower(), canonical)


def _normalize(name: str) -> str:
    key = name.lower().replace("-", "_")
    for suffix in ("_v0", "_v1", "_v2", "_v3", "_v4", "_v5"):
        if key.endswith(suffix):
            key = key[: -len(suffix)]
    return key


def canonical_env_key(name: str) -> str:
    """The registry's canonical form of an env name — lowercase, dashes
    folded, gym-style version suffixes stripped (``"CartPole-v1"`` →
    ``"cartpole"``), registry aliases and factory class names folded to
    one key (``"half_cheetah"`` → ``"halfcheetah"``, ``"swimmer2d"`` →
    ``"swimmer"``). THE one normalization: :func:`make_env` resolves with
    it and the tuned-config cache keys on it
    (``observability.timings.canonical_env_label``), so the two cannot
    drift."""
    key = _normalize(name)
    return _CANONICAL.get(key, key)


def make_env(name: str, **kwargs) -> Env:
    """Instantiate an environment by name.

    Plain names (``"cartpole"``, ``"pendulum"``, ``"acrobot"``,
    ``"mountain_car_continuous"``, ``"swimmer"``, ``"hopper"``) resolve to the
    pure-JAX suite. ``"brax::<env>"`` adapts brax (requires brax installed)."""
    if name.startswith("brax::"):
        from .braxenv import BraxEnvAdapter

        return BraxEnvAdapter(name[len("brax::") :], **kwargs)
    key = canonical_env_key(name)
    if key not in _REGISTRY:
        raise ValueError(f"Unknown environment: {name!r} (known: {sorted(_REGISTRY)})")
    return _REGISTRY[key](**kwargs)


def _register_defaults():
    from .classic import Acrobot, CartPole, MountainCarContinuous, Pendulum, Swimmer2D

    register_env("cartpole", CartPole)
    register_env("pendulum", Pendulum)
    register_env("acrobot", Acrobot)
    register_env("mountain_car_continuous", MountainCarContinuous)
    register_env("mountaincarcontinuous", MountainCarContinuous)
    register_env("swimmer", Swimmer2D)

    from .hopper import Hopper

    register_env("hopper", Hopper)

    from .humanoid import Humanoid

    register_env("humanoid", Humanoid)

    from .ant import Ant

    register_env("ant", Ant)

    from .walker2d import Walker2D

    register_env("walker2d", Walker2D)
    register_env("walker", Walker2D)

    from .halfcheetah import HalfCheetah

    register_env("halfcheetah", HalfCheetah)
    register_env("half_cheetah", HalfCheetah)


_register_defaults()
