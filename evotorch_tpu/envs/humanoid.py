"""Humanoid: the flagship 3-D locomotion workload (pure JAX, 17 actuated DOF).

A MuJoCo-Humanoid-class biped built on the maximal-coordinates engine in
``rigidbody.py``: 11 rigid bodies (torso, lower waist, pelvis, two thighs,
two shins with feet, two upper and two lower arms), 10 joints carrying 17
actuated rotational DOF (abdomen 3, hips 2x3, knees 2x1, shoulders 2x2,
elbows 2x1 — the same DOF budget as Gymnasium's ``Humanoid-v4``), penalty
ground contact on heel/toe/hand/pelvis/torso/head spheres, and a 109-dim
observation. Reward shaping follows the MuJoCo task: forward velocity plus
alive bonus minus control cost, terminating when the torso leaves the healthy
height band.

This is the workload class the reference reaches only through external Brax
(``/root/reference/src/evotorch/neuroevolution/net/vecrl.py:1366-1490``) and
whose PGPE recipe defines the north-star benchmark (``BASELINE.md``:
popsize 10k). Everything here is jit/vmap-native, so the whole population
rolls out inside one XLA program.
"""

from __future__ import annotations

from .locomotion import RigidBodyLocomotionEnv
from .rigidbody import SystemBuilder, capsule_inertia

__all__ = ["Humanoid"]


def _build_humanoid(act_mode: str = "position"):
    b = SystemBuilder(
        omega_pos=250.0,
        omega_ang=200.0,
        zeta=1.0,
        limit_gain=4.0,
        tone_ratio=0.1,
        free_damping_ratio=0.1,
        contact_k=20_000.0,
        # near-critical contact damping: underdamped feet micro-bounce at
        # ~13 Hz, and the bounce rectifies through friction into a steady
        # yaw drift (vibration-motor effect) that topples passive standing
        contact_c=350.0,
        friction_mu=1.0,
        # bounded by lever-arm stability: c * r^2 / I_shin * h < 2
        tangent_damping=350.0,
        act_mode=act_mode,
    )

    # Bodies: world COM positions in the standing reference pose
    # (x forward, y left, z up; ground at z=0). Proportions and masses track
    # the classic MuJoCo humanoid (~37 kg).
    b.add_body("torso", (0, 0, 1.25), 8.3, capsule_inertia(8.3, 0.11, 0.30, "z"))
    b.add_body("lwaist", (0, 0, 1.05), 2.0, capsule_inertia(2.0, 0.11, 0.16, "z"))
    b.add_body("pelvis", (0, 0, 0.92), 6.0, capsule_inertia(6.0, 0.10, 0.26, "y"))
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        b.add_body(f"{side}_thigh", (0, y, 0.63), 4.5, capsule_inertia(4.5, 0.06, 0.42, "z"))
        b.add_body(f"{side}_shin", (0, y, 0.25), 3.0, capsule_inertia(3.0, 0.05, 0.40, "z"))
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.17 * sy
        b.add_body(f"{side}_upper_arm", (0, y, 1.24), 1.6, capsule_inertia(1.6, 0.04, 0.28, "z"))
        b.add_body(f"{side}_lower_arm", (0, y, 0.98), 1.2, capsule_inertia(1.2, 0.035, 0.24, "z"))

    # Joints: 17 actuated DOF. Free-axis order fixes the action layout:
    #   0 abdomen_z, 1 abdomen_y, 2 abdomen_x,
    #   3 r_hip_x, 4 r_hip_z, 5 r_hip_y, 6 r_knee,
    #   7 l_hip_x, 8 l_hip_z, 9 l_hip_y, 10 l_knee,
    #   11 r_shoulder_x, 12 r_shoulder_y, 13 r_elbow,
    #   14 l_shoulder_x, 15 l_shoulder_y, 16 l_elbow
    b.add_joint(
        "torso", "lwaist", (0, 0, 1.13),
        free_axes=("z", "y"), limits=[(-0.79, 0.79), (-1.31, 0.52)], gears=(40.0, 40.0),
    )
    b.add_joint(
        "lwaist", "pelvis", (0, 0, 1.00),
        free_axes=("x",), limits=[(-0.61, 0.61)], gears=(40.0,),
    )
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        # hip_x limits mirror left/right: adduction is toward the body.
        hip_x = (-0.61, 0.17) if sy < 0 else (-0.17, 0.61)
        hip_z = (-1.05, 0.61) if sy < 0 else (-0.61, 1.05)
        b.add_joint(
            "pelvis", f"{side}_thigh", (0, y, 0.84),
            free_axes=("x", "z", "y"),
            limits=[hip_x, hip_z, (-1.92, 0.35)],
            gears=(40.0, 40.0, 120.0),
        )
        b.add_joint(
            f"{side}_thigh", f"{side}_shin", (0, y, 0.42),
            free_axes=("y",), limits=[(-0.05, 2.70)], gears=(80.0,),
        )
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.17 * sy
        sh_x = (-1.48, 1.05) if sy < 0 else (-1.05, 1.48)
        b.add_joint(
            "torso", f"{side}_upper_arm", (0, y, 1.38),
            free_axes=("x", "y"), limits=[sh_x, (-1.48, 1.05)], gears=(25.0, 25.0),
        )
        b.add_joint(
            f"{side}_upper_arm", f"{side}_lower_arm", (0, y, 1.10),
            free_axes=("y",), limits=[(-2.27, 0.05)], gears=(25.0,),
        )

    # Colliders. The first four spheres are the feet (heel + toe per side) —
    # the observation exposes their contact state.
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        b.add_sphere(f"{side}_shin", (-0.08, y, 0.045), 0.045)  # heel
        b.add_sphere(f"{side}_shin", (0.15, y, 0.045), 0.045)  # toe
    b.add_sphere("right_lower_arm", (0, -0.17, 0.87), 0.05)  # hand
    b.add_sphere("left_lower_arm", (0, 0.17, 0.87), 0.05)
    b.add_sphere("pelvis", (0, 0, 0.92), 0.09)
    b.add_sphere("torso", (0, 0, 1.25), 0.11)
    b.add_sphere("torso", (0, 0, 1.50), 0.09)  # head

    return b.build()


class Humanoid(RigidBodyLocomotionEnv):
    """3-D humanoid locomotion (the flagship workload). Observation: the
    standard locomotion layout of :class:`RigidBodyLocomotionEnv` (109-dim
    here: 17 joint angle/velocity pairs, 10 non-torso bodies, 4 foot contact
    depths — right heel/toe, left heel/toe).

    Action: 17 values in ``[-1, 1]``. With the default ``act_mode="position"``
    they are PD servo targets (0 = reference pose, +/-1 = joint limits,
    torque-clipped at the per-DOF gear); with ``act_mode="torque"`` they are
    raw torques scaled by gear (``Humanoid-v4`` semantics).
    Reward: ``1.25 * forward_velocity + 5.0 - 0.1 * ||action||^2`` while the
    torso stays in the healthy height band, mirroring ``Humanoid-v4``.
    """

    def __init__(
        self,
        *,
        forward_reward_weight: float = 1.25,
        alive_bonus: float = 5.0,
        ctrl_cost_weight: float = 0.1,
        healthy_z_range=(0.85, 1.75),
        reset_noise_scale: float = 0.01,
        act_mode: str = "position",
        dt: float = 0.015,
        substeps: int = 8,
    ):
        """``act_mode="position"`` (default): actions are PD target angles —
        zero action actively holds the reference pose, which makes standing
        metastable and gait discovery tractable for ES (the choice modern
        Brax/MJX humanoid-training setups make). ``act_mode="torque"``
        reproduces the MuJoCo ``Humanoid-v4`` raw-torque semantics."""
        self.sys, self._default_pos = _build_humanoid(act_mode)
        # the default h = dt/substeps = 1.875ms keeps a ~5x margin from the
        # integrator stability boundary; validated in _finalize_spaces
        self.dt = float(dt)
        self.substeps = int(substeps)
        self.forward_reward_weight = forward_reward_weight
        self.alive_bonus = alive_bonus
        self.ctrl_cost_weight = ctrl_cost_weight
        self.healthy_z_range = healthy_z_range
        self.reset_noise_scale = reset_noise_scale
        self._finalize_spaces()
