"""Humanoid: the flagship 3-D locomotion workload (pure JAX, 17 actuated DOF).

A MuJoCo-Humanoid-class biped built on the maximal-coordinates engine in
``rigidbody.py``: 11 rigid bodies (torso, lower waist, pelvis, two thighs,
two shins with feet, two upper and two lower arms), 10 joints carrying 17
actuated rotational DOF (abdomen 3, hips 2x3, knees 2x1, shoulders 2x2,
elbows 2x1 — the same DOF budget as Gymnasium's ``Humanoid-v4``), penalty
ground contact on heel/toe/hand/pelvis/torso/head spheres, and a 109-dim
observation. Reward shaping follows the MuJoCo task: forward velocity plus
alive bonus minus control cost, terminating when the torso leaves the healthy
height band.

This is the workload class the reference reaches only through external Brax
(``/root/reference/src/evotorch/neuroevolution/net/vecrl.py:1366-1490``) and
whose PGPE recipe defines the north-star benchmark (``BASELINE.md``:
popsize 10k). Everything here is jit/vmap-native, so the whole population
rolls out inside one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as np

from ..tools.pytree import replace
from .base import Env, EnvState, Space
from .rigidbody import (
    BodyState,
    SystemBuilder,
    capsule_inertia,
    joint_angles,
    joint_velocities,
    joint_angles_batched,
    joint_velocities_batched,
    physics_step,
    physics_step_batched,
    sphere_penetrations,
    sphere_penetrations_batched,
)

__all__ = ["Humanoid"]


def _build_humanoid(act_mode: str = "position"):
    b = SystemBuilder(
        omega_pos=250.0,
        omega_ang=200.0,
        zeta=1.0,
        limit_gain=4.0,
        tone_ratio=0.1,
        free_damping_ratio=0.1,
        contact_k=20_000.0,
        # near-critical contact damping: underdamped feet micro-bounce at
        # ~13 Hz, and the bounce rectifies through friction into a steady
        # yaw drift (vibration-motor effect) that topples passive standing
        contact_c=350.0,
        friction_mu=1.0,
        # bounded by lever-arm stability: c * r^2 / I_shin * h < 2
        tangent_damping=350.0,
        act_mode=act_mode,
    )

    # Bodies: world COM positions in the standing reference pose
    # (x forward, y left, z up; ground at z=0). Proportions and masses track
    # the classic MuJoCo humanoid (~37 kg).
    b.add_body("torso", (0, 0, 1.25), 8.3, capsule_inertia(8.3, 0.11, 0.30, "z"))
    b.add_body("lwaist", (0, 0, 1.05), 2.0, capsule_inertia(2.0, 0.11, 0.16, "z"))
    b.add_body("pelvis", (0, 0, 0.92), 6.0, capsule_inertia(6.0, 0.10, 0.26, "y"))
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        b.add_body(f"{side}_thigh", (0, y, 0.63), 4.5, capsule_inertia(4.5, 0.06, 0.42, "z"))
        b.add_body(f"{side}_shin", (0, y, 0.25), 3.0, capsule_inertia(3.0, 0.05, 0.40, "z"))
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.17 * sy
        b.add_body(f"{side}_upper_arm", (0, y, 1.24), 1.6, capsule_inertia(1.6, 0.04, 0.28, "z"))
        b.add_body(f"{side}_lower_arm", (0, y, 0.98), 1.2, capsule_inertia(1.2, 0.035, 0.24, "z"))

    # Joints: 17 actuated DOF. Free-axis order fixes the action layout:
    #   0 abdomen_z, 1 abdomen_y, 2 abdomen_x,
    #   3 r_hip_x, 4 r_hip_z, 5 r_hip_y, 6 r_knee,
    #   7 l_hip_x, 8 l_hip_z, 9 l_hip_y, 10 l_knee,
    #   11 r_shoulder_x, 12 r_shoulder_y, 13 r_elbow,
    #   14 l_shoulder_x, 15 l_shoulder_y, 16 l_elbow
    b.add_joint(
        "torso", "lwaist", (0, 0, 1.13),
        free_axes=("z", "y"), limits=[(-0.79, 0.79), (-1.31, 0.52)], gears=(40.0, 40.0),
    )
    b.add_joint(
        "lwaist", "pelvis", (0, 0, 1.00),
        free_axes=("x",), limits=[(-0.61, 0.61)], gears=(40.0,),
    )
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        # hip_x limits mirror left/right: adduction is toward the body.
        hip_x = (-0.61, 0.17) if sy < 0 else (-0.17, 0.61)
        hip_z = (-1.05, 0.61) if sy < 0 else (-0.61, 1.05)
        b.add_joint(
            "pelvis", f"{side}_thigh", (0, y, 0.84),
            free_axes=("x", "z", "y"),
            limits=[hip_x, hip_z, (-1.92, 0.35)],
            gears=(40.0, 40.0, 120.0),
        )
        b.add_joint(
            f"{side}_thigh", f"{side}_shin", (0, y, 0.42),
            free_axes=("y",), limits=[(-0.05, 2.70)], gears=(80.0,),
        )
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.17 * sy
        sh_x = (-1.48, 1.05) if sy < 0 else (-1.05, 1.48)
        b.add_joint(
            "torso", f"{side}_upper_arm", (0, y, 1.38),
            free_axes=("x", "y"), limits=[sh_x, (-1.48, 1.05)], gears=(25.0, 25.0),
        )
        b.add_joint(
            f"{side}_upper_arm", f"{side}_lower_arm", (0, y, 1.10),
            free_axes=("y",), limits=[(-2.27, 0.05)], gears=(25.0,),
        )

    # Colliders. The first four spheres are the feet (heel + toe per side) —
    # the observation exposes their contact state.
    for side, sy in (("right", -1.0), ("left", 1.0)):
        y = 0.1 * sy
        b.add_sphere(f"{side}_shin", (-0.08, y, 0.045), 0.045)  # heel
        b.add_sphere(f"{side}_shin", (0.15, y, 0.045), 0.045)  # toe
    b.add_sphere("right_lower_arm", (0, -0.17, 0.87), 0.05)  # hand
    b.add_sphere("left_lower_arm", (0, 0.17, 0.87), 0.05)
    b.add_sphere("pelvis", (0, 0, 0.92), 0.09)
    b.add_sphere("torso", (0, 0, 1.25), 0.11)
    b.add_sphere("torso", (0, 0, 1.50), 0.09)  # head

    return b.build()


class Humanoid(Env):
    """3-D humanoid locomotion. Observation (109-dim):

    ====== =====================================================
    dims   content
    ====== =====================================================
    1      torso height
    4      torso orientation quaternion
    3      torso linear velocity (world)
    3      torso angular velocity (world)
    17     joint angles (action-DOF order)
    17     joint angular velocities (action-DOF order)
    30     non-torso body COM positions relative to the torso
    30     non-torso body velocities relative to the torso
    4      foot contact depths (right heel/toe, left heel/toe)
    ====== =====================================================

    Action: 17 values in ``[-1, 1]``. With the default ``act_mode="position"``
    they are PD servo targets (0 = reference pose, +/-1 = joint limits,
    torque-clipped at the per-DOF gear); with ``act_mode="torque"`` they are
    raw torques scaled by gear (``Humanoid-v4`` semantics).
    Reward: ``1.25 * forward_velocity + 5.0 - 0.1 * ||action||^2`` while the
    torso stays in the healthy height band, mirroring ``Humanoid-v4``.
    """

    max_episode_steps = 1000
    # the hot path: population-minor physics (rigidbody.py layout note)
    batched_native = True

    def __init__(
        self,
        *,
        forward_reward_weight: float = 1.25,
        alive_bonus: float = 5.0,
        ctrl_cost_weight: float = 0.1,
        healthy_z_range=(0.85, 1.75),
        reset_noise_scale: float = 0.01,
        act_mode: str = "position",
    ):
        """``act_mode="position"`` (default): actions are PD target angles —
        zero action actively holds the reference pose, which makes standing
        metastable and gait discovery tractable for ES (the choice modern
        Brax/MJX humanoid-training setups make). ``act_mode="torque"``
        reproduces the MuJoCo ``Humanoid-v4`` raw-torque semantics."""
        self.sys, self._default_pos = _build_humanoid(act_mode)
        self.dt = 0.015
        self.substeps = 8
        self.forward_reward_weight = forward_reward_weight
        self.alive_bonus = alive_bonus
        self.ctrl_cost_weight = ctrl_cost_weight
        self.healthy_z_range = healthy_z_range
        self.reset_noise_scale = reset_noise_scale

        na = self.sys.num_act
        self.action_space = Space(shape=(na,), lb=-jnp.ones(na), ub=jnp.ones(na))
        self.observation_space = Space(shape=(self._obs_dim(),))

        # static selection matrix flattening per-joint axis components
        # (nj, 3) -> the action-DOF order; batched _free_components is then a
        # dense (na, nj*3) x (nj*3, B) matmul instead of a scatter
        nj = self.sys.num_joints
        idx = np.asarray(self.sys.act_index).reshape(-1)  # (nj*3,)
        sel = np.zeros((na, nj * 3), dtype=np.float32)
        for flat_pos, a in enumerate(idx):
            if a < na:
                sel[a, flat_pos] = 1.0
        self._free_sel = jnp.asarray(sel)

    def _obs_dim(self) -> int:
        nb = self.sys.num_bodies
        return 1 + 4 + 3 + 3 + self.sys.num_act + self.sys.num_act + 2 * 3 * (nb - 1) + 4

    # -- helpers -----------------------------------------------------------
    def _free_components(self, comps: jnp.ndarray) -> jnp.ndarray:
        """Flatten per-joint axis components ``(nj, 3)`` to the 17-dim action
        layout using the builder's action-index map."""
        idx = self.sys.act_index  # (nj, 3) with num_act marking unactuated
        # invert the map: out[idx[j, a]] = comps[j, a]; unactuated axes all
        # land on the extra scratch slot, which is dropped
        out = jnp.zeros(self.sys.num_act + 1, comps.dtype)
        out = out.at[idx.reshape(-1)].set(comps.reshape(-1))
        return out[: self.sys.num_act]

    def _obs(self, st: BodyState) -> jnp.ndarray:
        torso_pos = st.pos[0]
        rel_pos = (st.pos[1:] - torso_pos).reshape(-1)
        rel_vel = (st.vel[1:] - st.vel[0]).reshape(-1)
        ja = self._free_components(joint_angles(self.sys, st))
        jv = self._free_components(joint_velocities(self.sys, st))
        feet = sphere_penetrations(self.sys, st)[:4]
        return jnp.concatenate(
            [
                torso_pos[2:3],
                st.quat[0],
                st.vel[0],
                st.ang[0],
                ja,
                jv,
                rel_pos,
                rel_vel,
                feet,
            ]
        )

    # -- batched-native protocol (population-minor state layout) -----------
    def _batch_free_components(self, comps: jnp.ndarray) -> jnp.ndarray:
        """``(nj, 3, B)`` axis components -> ``(na, B)`` action-DOF order."""
        nj = self.sys.num_joints
        return self._free_sel @ comps.reshape(nj * 3, -1)

    def _batch_obs(self, st: BodyState) -> jnp.ndarray:
        """Observation for a population state ``(nb, comp, B)`` -> ``(B, obs)``.
        Field order matches :meth:`_obs` exactly."""
        B = st.pos.shape[-1]
        ja = self._batch_free_components(joint_angles_batched(self.sys, st))
        jv = self._batch_free_components(joint_velocities_batched(self.sys, st))
        obs = jnp.concatenate(
            [
                st.pos[0, 2:3, :],  # torso height (1, B)
                st.quat[0],  # (4, B)
                st.vel[0],  # (3, B)
                st.ang[0],  # (3, B)
                ja,  # (na, B)
                jv,  # (na, B)
                (st.pos[1:] - st.pos[:1]).reshape(-1, B),
                (st.vel[1:] - st.vel[:1]).reshape(-1, B),
                sphere_penetrations_batched(self.sys, st)[:4],  # feet (4, B)
            ],
            axis=0,
        )
        return obs.T

    def batch_reset(self, keys):
        """Reset ``B`` lanes at once; ``keys`` is a ``(B,)`` key array."""
        B = keys.shape[0]
        nb = self.sys.num_bodies
        split = jax.vmap(lambda k: jax.random.split(k, 3))(keys)  # (B, 3) keys
        noise = self.reset_noise_scale
        vel = noise * jax.vmap(lambda k: jax.random.normal(k, (nb, 3)))(split[:, 1])
        ang = noise * jax.vmap(lambda k: jax.random.normal(k, (nb, 3)))(split[:, 2])
        st = BodyState(
            pos=jnp.broadcast_to(self._default_pos[..., None], (nb, 3, B)),
            quat=jnp.broadcast_to(
                jnp.asarray([1.0, 0.0, 0.0, 0.0])[None, :, None], (nb, 4, B)
            ),
            vel=jnp.moveaxis(vel, 0, -1),
            ang=jnp.moveaxis(ang, 0, -1),
        )
        state = EnvState(
            obs_state=st, t=jnp.zeros((B,), jnp.int32), key=split[:, 0]
        )
        return state, self._batch_obs(st)

    def batch_step(self, state: EnvState, actions):
        """Step ``B`` lanes: ``actions`` ``(B, na)`` -> leading-batch outputs."""
        actions = jnp.clip(actions, self.action_space.lb, self.action_space.ub)
        a = actions.T  # (na, B): population-minor for the physics
        st = physics_step_batched(self.sys, state.obs_state, a, self.dt, self.substeps)
        t = state.t + 1

        z = st.pos[0, 2, :]
        lo, hi = self.healthy_z_range
        unhealthy = (z < lo) | (z > hi)
        done = unhealthy | (t >= self.max_episode_steps)

        forward_vel = st.vel[0, 0, :]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(a * a, axis=0)
        reward = self.forward_reward_weight * forward_vel + self.alive_bonus - ctrl_cost
        reward = jnp.where(unhealthy, reward - self.alive_bonus, reward)

        return replace(state, obs_state=st, t=t), self._batch_obs(st), reward, done

    def batch_where(self, mask, a: EnvState, b: EnvState) -> EnvState:
        """Per-lane state select: lane i takes ``a`` where ``mask[i]`` else
        ``b`` (the rollout driver's auto-reset). Field-explicit — the body
        state is batch-trailing while ``t``/``key`` are batch-leading, so a
        generic shape-sniffing tree_map would be ambiguous."""
        obs_state = jax.tree_util.tree_map(
            lambda x, y: jnp.where(mask[None, None, :], x, y),
            a.obs_state,
            b.obs_state,
        )
        t = jnp.where(mask, a.t, b.t)
        ka, kb = a.key, b.key
        if jnp.issubdtype(ka.dtype, jax.dtypes.prng_key):
            kd = jnp.where(
                mask[:, None], jax.random.key_data(ka), jax.random.key_data(kb)
            )
            key = jax.random.wrap_key_data(kd)
        else:  # legacy raw uint32 keys, (B, 2)
            key = jnp.where(mask[:, None], ka, kb)
        return EnvState(obs_state=obs_state, t=t, key=key)

    # -- Env protocol ------------------------------------------------------
    def reset(self, key):
        key, k1, k2 = jax.random.split(key, 3)
        nb = self.sys.num_bodies
        noise = self.reset_noise_scale
        vel = noise * jax.random.normal(k1, (nb, 3))
        ang = noise * jax.random.normal(k2, (nb, 3))
        st = BodyState(
            pos=self._default_pos,
            quat=jnp.tile(jnp.asarray([1.0, 0.0, 0.0, 0.0]), (nb, 1)),
            vel=vel,
            ang=ang,
        )
        return EnvState(obs_state=st, t=jnp.zeros((), jnp.int32), key=key), self._obs(st)

    def step(self, state: EnvState, action):
        action = jnp.clip(
            jnp.reshape(action, (self.sys.num_act,)),
            self.action_space.lb,
            self.action_space.ub,
        )
        st = physics_step(self.sys, state.obs_state, action, self.dt, self.substeps)
        t = state.t + 1

        z = st.pos[0, 2]
        lo, hi = self.healthy_z_range
        unhealthy = (z < lo) | (z > hi)
        done = unhealthy | (t >= self.max_episode_steps)

        forward_vel = st.vel[0, 0]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(action**2)
        reward = self.forward_reward_weight * forward_vel + self.alive_bonus - ctrl_cost
        reward = jnp.where(unhealthy, reward - self.alive_bonus, reward)

        return replace(state, obs_state=st, t=t), self._obs(st), reward, done
