"""Environment protocol for fully-jitted rollouts."""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..tools.pytree import pytree_dataclass

__all__ = ["Space", "EnvState", "Env"]


class Space(NamedTuple):
    """Box or Discrete space description."""

    shape: tuple
    lb: Optional[jnp.ndarray] = None  # None for discrete
    ub: Optional[jnp.ndarray] = None
    n: Optional[int] = None  # number of actions when discrete

    @property
    def is_discrete(self) -> bool:
        return self.n is not None


@pytree_dataclass
class EnvState:
    """Generic env state: dynamics state + time + PRNG key."""

    obs_state: Any
    t: jnp.ndarray
    key: Any


class Env:
    """A pure, jittable environment.

    - ``reset(key) -> (state, obs)``
    - ``step(state, action) -> (state, obs, reward, done)``

    Both are pure functions of their inputs; vectorization over envs is plain
    ``jax.vmap``, and auto-reset is implemented by the rollout driver
    (``neuroevolution.vecneproblem``) with ``jnp.where`` masking.

    **Natively-batched envs** (``batched_native = True``) additionally provide

    - ``batch_reset(keys) -> (states, obs)`` with ``obs`` ``(B, obs_dim)``
    - ``batch_step(states, actions) -> (states, obs, rewards, dones)`` with
      leading-batch ``(B, ...)`` actions/obs/rewards/dones
    - ``batch_where(mask, a, b)`` — per-lane state selection (auto-reset)
    - ``batch_take(states, idx)`` — gather lanes by index (lane compaction;
      required for ``run_vectorized_rollout_compacting``)

    and may lay out their *internal* state pytree however they like. The
    rollout engine calls these instead of ``vmap(step)``. The point is TPU
    register tiling: ``vmap`` puts the population axis leading, which leaves
    tiny trailing dims (3, 4) in the 128-lane axis of every vector register
    and every loop-carried buffer. A batched-native env keeps the population
    in the minor axis (see ``rigidbody.py``) for >10x the throughput."""

    observation_space: Space
    action_space: Space
    max_episode_steps: Optional[int] = None
    batched_native: bool = False

    @property
    def observation_size(self) -> int:
        return int(self.observation_space.shape[0])

    @property
    def action_size(self) -> int:
        if self.action_space.is_discrete:
            return int(self.action_space.n)
        return int(self.action_space.shape[0])

    def batch_shard_spec(self, axis_name: str):
        """``PartitionSpec`` pytree (or prefix) describing how a *batched*
        state of this env shards its lane axis over ``axis_name`` — used by
        the sharded lane-compacting runner, whose loop carry crosses
        ``shard_map`` boundaries between chunks. The default covers the
        ``vmap`` path (lane-leading leaves); batched-native envs with other
        layouts (e.g. the batch-trailing rigid-body states) override it."""
        from jax.sharding import PartitionSpec

        return PartitionSpec(axis_name)

    def reset(self, key) -> Tuple[EnvState, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state: EnvState, action) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError
