"""Environment protocol for fully-jitted rollouts."""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..tools.pytree import pytree_dataclass

__all__ = ["Space", "EnvState", "Env"]


class Space(NamedTuple):
    """Box or Discrete space description."""

    shape: tuple
    lb: Optional[jnp.ndarray] = None  # None for discrete
    ub: Optional[jnp.ndarray] = None
    n: Optional[int] = None  # number of actions when discrete

    @property
    def is_discrete(self) -> bool:
        return self.n is not None


@pytree_dataclass
class EnvState:
    """Generic env state: dynamics state + time + PRNG key."""

    obs_state: Any
    t: jnp.ndarray
    key: Any


class Env:
    """A pure, jittable environment.

    - ``reset(key) -> (state, obs)``
    - ``step(state, action) -> (state, obs, reward, done)``

    Both are pure functions of their inputs; vectorization over envs is plain
    ``jax.vmap``, and auto-reset is implemented by the rollout driver
    (``neuroevolution.vecneproblem``) with ``jnp.where`` masking."""

    observation_space: Space
    action_space: Space
    max_episode_steps: Optional[int] = None

    @property
    def observation_size(self) -> int:
        return int(self.observation_space.shape[0])

    @property
    def action_size(self) -> int:
        if self.action_space.is_discrete:
            return int(self.action_space.n)
        return int(self.action_space.shape[0])

    def reset(self, key) -> Tuple[EnvState, jnp.ndarray]:
        raise NotImplementedError

    def step(self, state: EnvState, action) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError
