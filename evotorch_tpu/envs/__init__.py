"""Pure-JAX vectorized environments.

The reference's vectorized-RL layer (``net/vecrl.py``) bridges to Brax/gym
through dlpack conversions and wrapper stacks (``vecrl.py:362-613``
``TorchWrapper``, ``vecrl.py:1366-1490`` ``VectorEnvFromBrax``). On TPU the
right substrate is environments whose ``reset``/``step`` are themselves pure
jittable functions, so whole rollouts compile into one ``lax.scan`` with
auto-reset inside the program (SURVEY.md §3.4 "keep the whole loop inside one
jitted while_loop/scan").

``make_env("cartpole")`` returns such an env; ``"brax::<name>"`` adapts a
brax env when brax is installed (import-gated), mirroring the reference's
``"gym::"``/``"brax::"`` registry strings (``vecgymne.py:496-570``).

The ``mujoco`` subpackage (``envs/mujoco/``, import-gated on the optional
``mujoco`` + ``gymnasium`` packages) is the REAL-physics counterpart: a
batched host rollout engine over real gymnasium ``-v5`` models
(``MjVecEnv``) and the matched-action fidelity harness that measures how
far these native envs diverge from their MuJoCo namesakes
(``docs/neuroevolution.md``).
"""

from .base import Env, EnvState, Space
from .classic import Acrobot, CartPole, MountainCarContinuous, Pendulum, Swimmer2D
from .hopper import Hopper
from .ant import Ant
from .humanoid import Humanoid
from .walker2d import Walker2D
from .halfcheetah import HalfCheetah
from .registry import make_env, register_env

__all__ = [
    "Env",
    "EnvState",
    "Space",
    "CartPole",
    "Pendulum",
    "Acrobot",
    "MountainCarContinuous",
    "Swimmer2D",
    "Hopper",
    "Humanoid",
    "Ant",
    "Walker2D",
    "HalfCheetah",
    "make_env",
    "register_env",
]
