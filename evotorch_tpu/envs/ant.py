"""Ant: quadruped locomotion on the maximal-coordinates engine (8 DOF).

A MuJoCo-Ant-class quadruped: a central torso sphere and four legs (upper
link extending horizontally along +x/+y/-x/-y, lower link dropping to a foot
sphere), 8 joints carrying 8 actuated rotational DOF (per leg: hip swing
about z, knee lift about the horizontal axis perpendicular to the leg).
Observation: the standard locomotion layout of
:class:`RigidBodyLocomotionEnv` (79-dim here: 8 joint angle/velocity pairs,
8 non-torso bodies, 4 foot contact depths). Reward mirrors ``Ant-v4``:
forward velocity + alive bonus - control cost, terminating outside the
healthy height band.

This is the second body plan on the engine (after ``humanoid.py``) and the
classic Brax showcase task the reference reaches only through the external
dlpack bridge (``/root/reference/src/evotorch/neuroevolution/net/
vecrl.py:1366-1490``).
"""

from __future__ import annotations

import numpy as np

from .locomotion import RigidBodyLocomotionEnv
from .rigidbody import SystemBuilder, capsule_inertia, sphere_inertia

__all__ = ["Ant"]


def _build_ant(act_mode: str = "position"):
    b = SystemBuilder(
        omega_pos=200.0,
        omega_ang=200.0,
        zeta=1.0,
        limit_gain=4.0,
        tone_ratio=0.1,
        free_damping_ratio=0.1,
        contact_k=15_000.0,
        contact_c=300.0,
        friction_mu=1.0,
        tangent_damping=300.0,
        act_mode=act_mode,
        # stronger servos than the inertia-scaled default: leg links are
        # light, so act_kp would otherwise lose to gravity torques
        act_kp_ratio=2.0,
    )

    # Bodies: torso sphere + 4 legs; reference pose with legs extended
    # horizontally and lower legs vertical down to the feet. z up, ground 0.
    z0 = 0.55
    b.add_body("torso", (0, 0, z0), 10.0, sphere_inertia(10.0, 0.25))
    # leg directions: +x, +y, -x, -y; per-leg body/axis naming
    dirs = {"front": (1.0, 0.0), "left": (0.0, 1.0), "back": (-1.0, 0.0), "right": (0.0, -1.0)}
    for name, (dx, dy) in dirs.items():
        horizontal = "x" if dx != 0.0 else "y"  # upper-leg long axis
        ux, uy = 0.425 * dx, 0.425 * dy  # upper-leg COM (hip at 0.25, len 0.35)
        b.add_body(
            f"{name}_upper",
            (ux, uy, z0),
            1.5,
            capsule_inertia(1.5, 0.05, 0.35, horizontal),
        )
        lx, ly = 0.6 * dx, 0.6 * dy  # lower leg hangs from the knee at 0.6
        b.add_body(
            f"{name}_lower",
            (lx, ly, z0 - 0.21),
            1.2,
            capsule_inertia(1.2, 0.04, 0.42, "z"),
        )

    # Joints: per leg, hip swing about z + knee lift about the horizontal
    # axis perpendicular to the leg direction (both world-aligned in the
    # reference pose, as the engine's axis/inertia pairing assumes) —
    # 2 actuated DOF per leg, the Ant-v4 budget.
    for name, (dx, dy) in dirs.items():
        lift_axis = "y" if dx != 0.0 else "x"
        b.add_joint(
            "torso",
            f"{name}_upper",
            (0.25 * dx, 0.25 * dy, z0),
            free_axes=("z",),
            limits=[(-0.6, 0.6)],
            gears=(40.0,),
            tone=40.0,  # posture support (see humanoid's posture joints)
        )
        b.add_joint(
            f"{name}_upper",
            f"{name}_lower",
            (0.6 * dx, 0.6 * dy, z0),
            free_axes=(lift_axis,),
            limits=[(-0.9, 0.9)],
            gears=(60.0,),
            tone=40.0,
        )

    # Colliders: the four feet first (their contact depths are observed),
    # then the torso.
    for name, (dx, dy) in dirs.items():
        b.add_sphere(f"{name}_lower", (0.6 * dx, 0.6 * dy, z0 - 0.44), 0.08)
    b.add_sphere("torso", (0, 0, z0), 0.25)

    return b.build()


class Ant(RigidBodyLocomotionEnv):
    """Quadruped locomotion; ``Ant-v4``-style reward and DOF budget:
    8 actuated DOF over 8 joints (per leg: hip swing about z, knee lift
    about the horizontal axis perpendicular to the leg)."""

    def __init__(
        self,
        *,
        forward_reward_weight: float = 1.0,
        alive_bonus: float = 1.0,
        ctrl_cost_weight: float = 0.5,
        healthy_z_range=(0.2, 1.0),
        reset_noise_scale: float = 0.01,
        act_mode: str = "position",
        dt: float = 0.015,
        substeps: int = 8,
    ):
        self.sys, self._default_pos = _build_ant(act_mode)
        self.dt = float(dt)
        self.substeps = int(substeps)
        self.forward_reward_weight = forward_reward_weight
        self.alive_bonus = alive_bonus
        self.ctrl_cost_weight = ctrl_cost_weight
        self.healthy_z_range = healthy_z_range
        self.reset_noise_scale = reset_noise_scale
        self._finalize_spaces()
