"""Shared base for rigid-body locomotion environments (batched-native).

Everything the maximal-coordinates engine needs to expose a locomotion task
lives here once: population-minor ``batch_reset`` / ``batch_step`` /
``batch_where`` (the ``Env.batched_native`` protocol the rollout engine
prefers — see ``rigidbody.py``'s layout note), the standard MuJoCo-style
reward (forward velocity + alive bonus - control cost, terminating outside a
healthy height band), and the common observation layout:

====================  =====================================================
dims                  content
====================  =====================================================
1                     torso height
4                     torso orientation quaternion
3                     torso linear velocity (world)
3                     torso angular velocity (world)
num_act               joint angles (action-DOF order)
num_act               joint angular velocities (action-DOF order)
3 * (num_bodies - 1)  non-torso body COM positions relative to the torso
3 * (num_bodies - 1)  non-torso body velocities relative to the torso
n_contact_obs         ground contact depths of the first collider spheres
====================  =====================================================

The single-instance ``reset``/``step`` API is the B=1 special case of the
batched protocol, so each concrete env carries exactly one implementation of
its dynamics, observation and reward. Subclasses provide the body plan
(a built ``System`` + default pose) and the task constants.

Parity note: the reference reaches this workload class only through external
Brax (``/root/reference/src/evotorch/neuroevolution/net/vecrl.py:1366-1490``);
here the simulator is native, so whole populations roll out inside one XLA
program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tools.pytree import replace
from .base import Env, EnvState, Space
from .rigidbody import (
    BodyState,
    joint_angles_batched,
    joint_velocities_batched,
    physics_step_batched,
    sphere_penetrations_batched,
)

__all__ = ["RigidBodyLocomotionEnv"]


class RigidBodyLocomotionEnv(Env):
    """Base class: subclasses set ``sys``/``_default_pos`` (the body plan),
    ``dt``/``substeps``, reward weights and ``n_contact_obs`` before calling
    ``_finalize_spaces()``."""

    batched_native = True
    max_episode_steps = 1000
    n_contact_obs = 4
    # planar tasks (Walker2D, HalfCheetah): constrain motion to the x-z
    # sagittal plane, the engine's form of MuJoCo's 2-D worlds (those tasks
    # simply omit the lateral DOF). Each control step projects the state back
    # onto the plane: lateral velocity, roll and yaw rates are zeroed, body y
    # snaps to the body plan's offsets, and orientations project onto pure
    # y-rotations.
    planar = False
    # largest per-substep h the default joint stiffness tolerates; the
    # semi-implicit Euler boundary is h * omega < 2 and the stiffest default
    # constraint frequency is omega ~= 250 rad/s, so 8ms keeps a safe margin
    integrator_h_budget = 0.008

    # reward constants (MuJoCo locomotion family defaults; subclasses override)
    forward_reward_weight = 1.25
    alive_bonus = 5.0
    ctrl_cost_weight = 0.1
    healthy_z_range = (0.2, 2.0)
    reset_noise_scale = 0.01

    # -- construction helpers ------------------------------------------------
    def _finalize_spaces(self):
        """Derive action/observation spaces + the static action-DOF selection
        matrix from the built system, and validate the integrator step.
        Call at the end of ``__init__``."""
        if self.substeps < 1:
            raise ValueError(f"substeps must be >= 1, got {self.substeps}")
        if self.dt / self.substeps > self.integrator_h_budget:
            raise ValueError(
                f"dt/substeps = {self.dt / self.substeps:.4f}s exceeds the"
                f" integrator stability budget ({self.integrator_h_budget}s"
                " at the default joint stiffness); increase substeps or"
                " lower dt"
            )
        na = self.sys.num_act
        self.action_space = Space(shape=(na,), lb=-jnp.ones(na), ub=jnp.ones(na))
        self.observation_space = Space(shape=(self._obs_dim(),))

        # static selection matrix flattening per-joint axis components
        # (nj, 3) -> the action-DOF order; _batch_free_components is then a
        # dense (na, nj*3) x (nj*3, B) matmul instead of a scatter
        nj = self.sys.num_joints
        idx = np.asarray(self.sys.act_index).reshape(-1)  # (nj*3,)
        sel = np.zeros((na, nj * 3), dtype=np.float32)
        for flat_pos, a in enumerate(idx):
            if a < na:
                sel[a, flat_pos] = 1.0
        self._free_sel = jnp.asarray(sel)

    def _obs_dim(self) -> int:
        nb = self.sys.num_bodies
        na = self.sys.num_act
        return 1 + 4 + 3 + 3 + 2 * na + 2 * 3 * (nb - 1) + self.n_contact_obs

    # -- observation ---------------------------------------------------------
    def _batch_free_components(self, comps: jnp.ndarray) -> jnp.ndarray:
        """``(nj, 3, B)`` axis components -> ``(na, B)`` action-DOF order."""
        nj = self.sys.num_joints
        return self._free_sel @ comps.reshape(nj * 3, -1)

    def _batch_obs(self, st: BodyState) -> jnp.ndarray:
        """Observation for a population state ``(nb, comp, B)`` -> ``(B, obs)``."""
        B = st.pos.shape[-1]
        ja = self._batch_free_components(joint_angles_batched(self.sys, st))
        jv = self._batch_free_components(joint_velocities_batched(self.sys, st))
        obs = jnp.concatenate(
            [
                st.pos[0, 2:3, :],  # torso height (1, B)
                st.quat[0],  # (4, B)
                st.vel[0],  # (3, B)
                st.ang[0],  # (3, B)
                ja,  # (na, B)
                jv,  # (na, B)
                (st.pos[1:] - st.pos[:1]).reshape(-1, B),
                (st.vel[1:] - st.vel[:1]).reshape(-1, B),
                sphere_penetrations_batched(self.sys, st)[: self.n_contact_obs],
            ],
            axis=0,
        )
        return obs.T

    # -- reward / termination (override for task variants) -------------------
    def _batch_reward_done(self, st: BodyState, actions_minor: jnp.ndarray, t):
        """``actions_minor`` is ``(na, B)`` (clipped). Returns
        ``(reward (B,), done (B,))``."""
        z = st.pos[0, 2, :]
        lo, hi = self.healthy_z_range
        unhealthy = (z < lo) | (z > hi)
        done = unhealthy | (t >= self.max_episode_steps)

        forward_vel = st.vel[0, 0, :]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(actions_minor * actions_minor, axis=0)
        reward = self.forward_reward_weight * forward_vel + self.alive_bonus - ctrl_cost
        reward = jnp.where(unhealthy, reward - self.alive_bonus, reward)
        return reward, done

    def batch_reward_terms(self, st: BodyState, actions_minor: jnp.ndarray):
        """Per-term decomposition of the step reward — the same quantities
        gymnasium's ``-v5`` envs expose in ``info`` (``reward_forward`` /
        ``reward_ctrl`` / ``reward_survive``), so the env-fidelity harness
        (``envs/mujoco/fidelity.py``) can compare the native simulator and
        the real env term by term. ``actions_minor`` is ``(na, B)``; returns
        a dict of ``(B,)`` arrays whose signed sum
        (``reward_forward + reward_ctrl + reward_survive``) equals the
        reward returned by :meth:`batch_step`."""
        z = st.pos[0, 2, :]
        lo, hi = self.healthy_z_range
        healthy = (z >= lo) & (z <= hi)
        forward_vel = st.vel[0, 0, :]
        ctrl_cost = self.ctrl_cost_weight * jnp.sum(actions_minor * actions_minor, axis=0)
        return {
            "x_velocity": forward_vel,
            "reward_forward": self.forward_reward_weight * forward_vel,
            "reward_ctrl": -ctrl_cost,
            "reward_survive": self.alive_bonus * healthy,
            "healthy": healthy,
        }

    # -- batched-native protocol ---------------------------------------------
    def batch_reset(self, keys):
        """Reset ``B`` lanes at once; ``keys`` is a ``(B,)`` key array."""
        B = keys.shape[0]
        nb = self.sys.num_bodies
        split = jax.vmap(lambda k: jax.random.split(k, 3))(keys)  # (B, 3) keys
        noise = self.reset_noise_scale
        vel = noise * jax.vmap(lambda k: jax.random.normal(k, (nb, 3)))(split[:, 1])
        ang = noise * jax.vmap(lambda k: jax.random.normal(k, (nb, 3)))(split[:, 2])
        st = BodyState(
            pos=jnp.broadcast_to(self._default_pos[..., None], (nb, 3, B)),
            quat=jnp.broadcast_to(
                jnp.asarray([1.0, 0.0, 0.0, 0.0])[None, :, None], (nb, 4, B)
            ),
            vel=jnp.moveaxis(vel, 0, -1),
            ang=jnp.moveaxis(ang, 0, -1),
        )
        state = EnvState(obs_state=st, t=jnp.zeros((B,), jnp.int32), key=split[:, 0])
        return state, self._batch_obs(st)

    def _planar_project(self, st: BodyState) -> BodyState:
        pos = st.pos.at[:, 1, :].set(self._default_pos[:, 1][:, None])
        vel = st.vel.at[:, 1, :].set(0.0)
        ang = st.ang.at[:, 0, :].set(0.0).at[:, 2, :].set(0.0)
        w, y = st.quat[:, 0, :], st.quat[:, 2, :]
        norm = jnp.sqrt(jnp.maximum(w * w + y * y, 1e-12))
        quat = jnp.stack(
            [w / norm, jnp.zeros_like(w), y / norm, jnp.zeros_like(w)], axis=1
        )
        return BodyState(pos=pos, quat=quat, vel=vel, ang=ang)

    def batch_step(self, state: EnvState, actions):
        """Step ``B`` lanes: ``actions`` ``(B, na)`` -> leading-batch outputs."""
        actions = jnp.clip(actions, self.action_space.lb, self.action_space.ub)
        a = actions.T  # (na, B): population-minor for the physics
        st = physics_step_batched(self.sys, state.obs_state, a, self.dt, self.substeps)
        if self.planar:
            st = self._planar_project(st)
        t = state.t + 1
        reward, done = self._batch_reward_done(st, a, t)
        return replace(state, obs_state=st, t=t), self._batch_obs(st), reward, done

    def batch_where(self, mask, a: EnvState, b: EnvState) -> EnvState:
        """Per-lane state select: lane i takes ``a`` where ``mask[i]`` else
        ``b`` (the rollout driver's auto-reset). Field-explicit — the body
        state is batch-trailing while ``t``/``key`` are batch-leading, so a
        generic shape-sniffing tree_map would be ambiguous."""
        obs_state = jax.tree_util.tree_map(
            lambda x, y: jnp.where(mask[None, None, :], x, y),
            a.obs_state,
            b.obs_state,
        )
        t = jnp.where(mask, a.t, b.t)
        ka, kb = a.key, b.key
        if jnp.issubdtype(ka.dtype, jax.dtypes.prng_key):
            kd = jnp.where(
                mask[:, None], jax.random.key_data(ka), jax.random.key_data(kb)
            )
            key = jax.random.wrap_key_data(kd)
        else:  # legacy raw uint32 keys, (B, 2)
            key = jnp.where(mask[:, None], ka, kb)
        return EnvState(obs_state=obs_state, t=t, key=key)

    def batch_take(self, state: EnvState, idx) -> EnvState:
        """Gather lanes ``idx`` (the rollout engine's lane compaction). The
        body state is batch-trailing, ``t``/``key`` batch-leading."""
        obs_state = jax.tree_util.tree_map(lambda x: x[..., idx], state.obs_state)
        return EnvState(obs_state=obs_state, t=state.t[idx], key=state.key[idx])

    def batch_shard_spec(self, axis_name: str):
        """The body state is batch-trailing ``(nb, dim, B)`` — shard its LAST
        axis; ``t``/``key`` are batch-leading."""
        from jax.sharding import PartitionSpec as P

        return EnvState(
            obs_state=P(None, None, axis_name),
            t=P(axis_name),
            key=P(axis_name),
        )

    # -- single-instance API: the B=1 special case ---------------------------
    @staticmethod
    def _key_as_batch(key) -> jnp.ndarray:
        """One PRNG key -> a (1,)-batch of keys; legacy raw uint32 keys (a
        ``(2,)`` array) become a ``(1, 2)`` batch."""
        key = jnp.asarray(key)
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            return jnp.reshape(key, (1,))
        return jnp.reshape(key, (1, -1))

    def _to_single(self, state: EnvState) -> EnvState:
        st = state.obs_state
        return EnvState(
            obs_state=BodyState(*(x[..., 0] for x in st)),
            t=state.t[0],
            key=state.key[0],
        )

    def _to_batched(self, state: EnvState) -> EnvState:
        st = state.obs_state
        return EnvState(
            obs_state=BodyState(*(x[..., None] for x in st)),
            t=state.t[None],
            key=self._key_as_batch(state.key),
        )

    def reset(self, key):
        state, obs = self.batch_reset(self._key_as_batch(key))
        return self._to_single(state), obs[0]

    def step(self, state: EnvState, action):
        bstate, obs, reward, done = self.batch_step(
            self._to_batched(state), jnp.reshape(action, (1, -1))
        )
        return self._to_single(bstate), obs[0], reward[0], done[0]
