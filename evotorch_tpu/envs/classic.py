"""Classic-control environments in pure JAX.

Dynamics follow the standard gym formulations (CartPole: Barto, Sutton &
Anderson 1983; Pendulum; Acrobot: Sutton 1996; MountainCarContinuous: Moore
1990) so evolved policies are comparable to policies evolved on gym's
versions. Each env's ``reset``/``step`` is pure and jittable; a whole
``(population x env x time)`` rollout compiles into one XLA program (the
TPU-native replacement for the reference's dlpack torch<->jax ping-pong,
SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..tools.pytree import replace
from .base import Env, EnvState, Space

__all__ = ["CartPole", "Pendulum", "Acrobot", "MountainCarContinuous", "Swimmer2D"]


class CartPole(Env):
    """CartPole-v1 dynamics. ``continuous_actions=True`` exposes a Box(-1, 1)
    action mapped to force direction (for policies without argmax heads)."""

    max_episode_steps = 500

    def __init__(self, *, continuous_actions: bool = False):
        self.continuous = bool(continuous_actions)
        self.observation_space = Space(shape=(4,))
        if self.continuous:
            self.action_space = Space(shape=(1,), lb=jnp.array([-1.0]), ub=jnp.array([1.0]))
        else:
            self.action_space = Space(shape=(), n=2)

        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masspole + self.masscart
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * jnp.pi / 360
        self.x_threshold = 2.4

    def reset(self, key) -> Tuple[EnvState, jnp.ndarray]:
        key, sub = jax.random.split(key)
        obs = jax.random.uniform(sub, (4,), minval=-0.05, maxval=0.05)
        return EnvState(obs_state=obs, t=jnp.zeros((), jnp.int32), key=key), obs

    def step(self, state: EnvState, action):
        x, x_dot, theta, theta_dot = state.obs_state
        if self.continuous:
            force = self.force_mag * jnp.clip(jnp.reshape(action, ())[None][0], -1.0, 1.0)
        else:
            act = jnp.reshape(action, ()).astype(jnp.int32)
            force = jnp.where(act == 1, self.force_mag, -self.force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (force + self.polemass_length * theta_dot**2 * sintheta) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / self.total_mass)
        )
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        obs = jnp.stack([x, x_dot, theta, theta_dot])
        t = state.t + 1
        done = (
            (jnp.abs(x) > self.x_threshold)
            | (jnp.abs(theta) > self.theta_threshold)
            | (t >= self.max_episode_steps)
        )
        reward = jnp.ones(())
        return replace(state, obs_state=obs, t=t), obs, reward, done


class Pendulum(Env):
    """Pendulum-v1 dynamics: swing-up with torque penalty."""

    max_episode_steps = 200

    def __init__(self):
        self.observation_space = Space(shape=(3,))
        self.action_space = Space(shape=(1,), lb=jnp.array([-2.0]), ub=jnp.array([2.0]))
        self.max_speed = 8.0
        self.max_torque = 2.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.l = 1.0

    def _obs(self, th, thdot):
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def reset(self, key):
        key, sub = jax.random.split(key)
        th = jax.random.uniform(sub, (), minval=-jnp.pi, maxval=jnp.pi)
        key, sub = jax.random.split(key)
        thdot = jax.random.uniform(sub, (), minval=-1.0, maxval=1.0)
        state = EnvState(obs_state=jnp.stack([th, thdot]), t=jnp.zeros((), jnp.int32), key=key)
        return state, self._obs(th, thdot)

    def step(self, state, action):
        th, thdot = state.obs_state
        u = jnp.clip(jnp.reshape(action, ()), -self.max_torque, self.max_torque)
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (3 * self.g / (2 * self.l) * jnp.sin(th) + 3.0 / (self.m * self.l**2) * u) * self.dt
        newthdot = jnp.clip(newthdot, -self.max_speed, self.max_speed)
        newth = th + newthdot * self.dt
        t = state.t + 1
        done = t >= self.max_episode_steps
        new_state = replace(state, obs_state=jnp.stack([newth, newthdot]), t=t)
        return new_state, self._obs(newth, newthdot), -cost, done


class Acrobot(Env):
    """Acrobot-v1 dynamics (two-link underactuated swing-up)."""

    max_episode_steps = 500

    def __init__(self):
        self.observation_space = Space(shape=(6,))
        self.action_space = Space(shape=(), n=3)
        self.dt = 0.2
        self.link_length_1 = 1.0
        self.link_length_2 = 1.0
        self.link_mass_1 = 1.0
        self.link_mass_2 = 1.0
        self.link_com_pos_1 = 0.5
        self.link_com_pos_2 = 0.5
        self.link_moi = 1.0
        self.max_vel_1 = 4 * jnp.pi
        self.max_vel_2 = 9 * jnp.pi

    def _obs(self, s):
        th1, th2, dth1, dth2 = s
        return jnp.stack([jnp.cos(th1), jnp.sin(th1), jnp.cos(th2), jnp.sin(th2), dth1, dth2])

    def reset(self, key):
        key, sub = jax.random.split(key)
        s = jax.random.uniform(sub, (4,), minval=-0.1, maxval=0.1)
        return EnvState(obs_state=s, t=jnp.zeros((), jnp.int32), key=key), self._obs(s)

    def _dynamics(self, s_augmented):
        m1, m2 = self.link_mass_1, self.link_mass_2
        l1 = self.link_length_1
        lc1, lc2 = self.link_com_pos_1, self.link_com_pos_2
        I1 = I2 = self.link_moi
        g = 9.8
        th1, th2, dth1, dth2, a = s_augmented
        d1 = m1 * lc1**2 + m2 * (l1**2 + lc2**2 + 2 * l1 * lc2 * jnp.cos(th2)) + I1 + I2
        d2 = m2 * (lc2**2 + l1 * lc2 * jnp.cos(th2)) + I2
        phi2 = m2 * lc2 * g * jnp.cos(th1 + th2 - jnp.pi / 2)
        phi1 = (
            -m2 * l1 * lc2 * dth2**2 * jnp.sin(th2)
            - 2 * m2 * l1 * lc2 * dth2 * dth1 * jnp.sin(th2)
            + (m1 * lc1 + m2 * l1) * g * jnp.cos(th1 - jnp.pi / 2)
            + phi2
        )
        ddth2 = (a + d2 / d1 * phi1 - m2 * l1 * lc2 * dth1**2 * jnp.sin(th2) - phi2) / (
            m2 * lc2**2 + I2 - d2**2 / d1
        )
        ddth1 = -(d2 * ddth2 + phi1) / d1
        return jnp.stack([dth1, dth2, ddth1, ddth2, jnp.zeros(())])

    def step(self, state, action):
        act = jnp.reshape(action, ()).astype(jnp.int32)
        torque = act.astype(jnp.float32) - 1.0  # {-1, 0, +1}
        s_augmented = jnp.concatenate([state.obs_state, torque[None]])
        # rk4 integration over dt
        dt = self.dt

        def deriv(y):
            return self._dynamics(y)

        k1 = deriv(s_augmented)
        k2 = deriv(s_augmented + dt / 2 * k1)
        k3 = deriv(s_augmented + dt / 2 * k2)
        k4 = deriv(s_augmented + dt * k3)
        ns = s_augmented + dt / 6 * (k1 + 2 * k2 + 2 * k3 + k4)
        th1 = ((ns[0] + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        th2 = ((ns[1] + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        dth1 = jnp.clip(ns[2], -self.max_vel_1, self.max_vel_1)
        dth2 = jnp.clip(ns[3], -self.max_vel_2, self.max_vel_2)
        s = jnp.stack([th1, th2, dth1, dth2])
        t = state.t + 1
        solved = -jnp.cos(th1) - jnp.cos(th2 + th1) > 1.0
        done = solved | (t >= self.max_episode_steps)
        reward = jnp.where(solved, 0.0, -1.0)
        return replace(state, obs_state=s, t=t), self._obs(s), reward, done


class MountainCarContinuous(Env):
    """MountainCarContinuous-v0 dynamics."""

    max_episode_steps = 999

    def __init__(self):
        self.observation_space = Space(shape=(2,))
        self.action_space = Space(shape=(1,), lb=jnp.array([-1.0]), ub=jnp.array([1.0]))
        self.min_position = -1.2
        self.max_position = 0.6
        self.max_speed = 0.07
        self.goal_position = 0.45
        self.power = 0.0015

    def reset(self, key):
        key, sub = jax.random.split(key)
        position = jax.random.uniform(sub, (), minval=-0.6, maxval=-0.4)
        s = jnp.stack([position, jnp.zeros(())])
        return EnvState(obs_state=s, t=jnp.zeros((), jnp.int32), key=key), s

    def step(self, state, action):
        position, velocity = state.obs_state
        force = jnp.clip(jnp.reshape(action, ()), -1.0, 1.0)
        velocity = velocity + force * self.power - 0.0025 * jnp.cos(3 * position)
        velocity = jnp.clip(velocity, -self.max_speed, self.max_speed)
        position = jnp.clip(position + velocity, self.min_position, self.max_position)
        velocity = jnp.where((position <= self.min_position) & (velocity < 0), 0.0, velocity)
        s = jnp.stack([position, velocity])
        t = state.t + 1
        goal = position >= self.goal_position
        done = goal | (t >= self.max_episode_steps)
        reward = jnp.where(goal, 100.0, 0.0) - 0.1 * force**2
        return replace(state, obs_state=s, t=t), s, reward, done


class Swimmer2D(Env):
    """A light n-link planar swimmer: a chain of links in a viscous fluid,
    rewarded for forward velocity of its head. A MuJoCo-free locomotion task
    with MXU-friendly per-step linear algebra — the benchmark stand-in for
    Brax-style locomotion (the reference uses Brax envs here,
    ``vecgymne.py:496-570``; Brax is not installed in this image)."""

    max_episode_steps = 1000

    def __init__(self, n_links: int = 3):
        self.n_links = int(n_links)
        # obs: link angles (n), angular velocities (n), head velocity (2)
        self.observation_space = Space(shape=(2 * self.n_links + 2,))
        n_act = self.n_links - 1
        self.action_space = Space(
            shape=(n_act,), lb=-jnp.ones(n_act), ub=jnp.ones(n_act)
        )
        self.dt = 0.04
        self.viscosity = 0.1
        self.torque_scale = 1.0

    def reset(self, key):
        key, sub = jax.random.split(key)
        n = self.n_links
        angles = jax.random.uniform(sub, (n,), minval=-0.1, maxval=0.1)
        omega = jnp.zeros(n)
        head_vel = jnp.zeros(2)
        s = jnp.concatenate([angles, omega, head_vel])
        return EnvState(obs_state=s, t=jnp.zeros((), jnp.int32), key=key), s

    def step(self, state, action):
        n = self.n_links
        s = state.obs_state
        angles, omega, head_vel = s[:n], s[n : 2 * n], s[2 * n :]
        torque = self.torque_scale * jnp.clip(jnp.reshape(action, (n - 1,)), -1.0, 1.0)
        # joint torques act on adjacent links with opposite signs
        joint_torque = jnp.zeros(n).at[:-1].add(torque).at[1:].add(-torque)
        # viscous drag opposes angular velocity; lateral drag on each link
        # couples into forward thrust when links oscillate out of phase
        alpha = joint_torque - self.viscosity * 30.0 * omega
        omega = omega + self.dt * alpha
        angles = angles + self.dt * omega
        # net thrust: sum of lateral link motions projected on the body axis
        lateral = jnp.sin(angles) * omega
        thrust = jnp.sum(lateral * jnp.cos(angles)) / n
        head_vel = 0.9 * head_vel + self.dt * jnp.stack([jnp.abs(thrust), thrust])
        s = jnp.concatenate([angles, omega, head_vel])
        t = state.t + 1
        reward = head_vel[0] - 0.0001 * jnp.sum(torque**2)
        done = t >= self.max_episode_steps
        return replace(state, obs_state=s, t=t), s, reward, done
