"""Fused centered-rank utility kernel.

Transforms a fitness vector into centered utilities (``tools/ranking.py``
semantics) with the rank computation fused in one kernel. The XLA fallback is
the library implementation. The Pallas path materializes an O(n^2) comparison
block in VMEM, so it targets *mid-sized* populations (n up to ~2000, i.e.
n^2 * 4 bytes within the ~16 MB VMEM budget); for larger populations use the
default XLA path, whose argsorts scale O(n log n).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..tools.ranking import centered_xla as _xla_centered

__all__ = ["fused_centered_rank"]


def _pallas_kernel(fit_ref, out_ref):
    fit = fit_ref[:]  # one fitness vector (batch dims handled by vmap)
    n = fit.shape[-1]
    # rank of each element = number of strictly-smaller elements plus the
    # number of equal elements appearing earlier (stable tie-break), computed
    # as one O(n^2) comparison block living entirely in VMEM — beats the
    # double argsort's three HBM round-trips for mid-sized populations.
    # NaNs order LAST (argsort semantics: jnp.argsort places NaN at the end),
    # so a NaN fitness ranks "best" exactly as in the XLA path — the total
    # order is lexicographic on (isnan, value, index)
    col = fit[:, None]
    row = fit[None, :]
    col_nan = jnp.isnan(col)
    row_nan = jnp.isnan(row)
    idx = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    jdx = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    value_smaller = (row < col) | (~row_nan & col_nan)  # non-NaN < NaN
    equal = (row == col) | (row_nan & col_nan)  # NaN == NaN for the tie-break
    smaller = value_smaller | (equal & (jdx < idx))
    ranks = jnp.sum(smaller.astype(jnp.float32), axis=-1)
    out_ref[:] = ranks / (n - 1) - 0.5


@functools.partial(jax.jit, static_argnames=("higher_is_better", "use_pallas", "interpret"))
def fused_centered_rank(
    fitnesses: jnp.ndarray,
    *,
    higher_is_better: bool = True,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Centered ranks in ``[-0.5, 0.5]`` along the last axis."""
    x = jnp.asarray(fitnesses)
    if not use_pallas or x.dtype not in (
        jnp.float32,
        jnp.bfloat16,
        jnp.float16,
        jnp.int16,
        jnp.int8,
        jnp.uint16,
        jnp.uint8,
    ):
        # the kernel ranks in f32, so only dtypes whose values embed in f32
        # exactly may take it; f64 (and int32/int64 values >= 2^24) would
        # collide distinct fitnesses in f32, get index tie-breaks, and
        # diverge from centered_xla (which ranks in the input dtype)
        return _xla_centered(x, higher_is_better=higher_is_better)

    from jax.experimental import pallas as pl

    # no Mosaic lowering off-TPU: interpret there (tests; the tools/ranking
    # dispatcher only auto-selects this path on TPU anyway)
    interpret = interpret or jax.default_backend() != "tpu"

    if x.shape[-1] == 1:
        # degenerate population: match the XLA fallback (zeros, no 0/0)
        return jnp.zeros_like(x)

    signed = (x if higher_is_better else -x).astype(jnp.float32)
    batch_shape = signed.shape[:-1]
    flat = signed.reshape((-1, signed.shape[-1]))

    call = pl.pallas_call(
        _pallas_kernel,
        out_shape=jax.ShapeDtypeStruct((signed.shape[-1],), jnp.float32),
        interpret=interpret,
    )
    out = jax.vmap(call)(flat)
    out = out.reshape(batch_shape + (signed.shape[-1],)) if batch_shape else out[0]
    return out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) else out
