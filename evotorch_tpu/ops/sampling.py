"""Fused antithetic-Gaussian sampling kernel.

Computes the PGPE `ask` population
``[mu + sigma*e0, mu - sigma*e0, mu + sigma*e1, ...]`` with the noise
generated on-chip (``pltpu.prng_random_bits`` + Box-Muller) and scaled in
VMEM — the noise tensor never exists in HBM. Mirrors
``SymmetricSeparableGaussian._sample`` (evotorch_tpu/distributions.py), whose
XLA form is the fallback.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["sample_symmetric_gaussian"]

_TWO_PI = 2.0 * math.pi


def _xla_fallback(key, mu, sigma, num_directions):
    eps = jax.random.normal(key, (num_directions, mu.shape[-1]), dtype=mu.dtype) * sigma
    return jnp.stack([mu + eps, mu - eps], axis=1).reshape(2 * num_directions, mu.shape[-1])


def _bits_to_unit_float(bits):
    """Random bits -> float32 in [1, 2) via the mantissa trick. Mosaic has no
    integer->float cast, and ``prng_random_bits`` has historically yielded
    signed int32 on some jax versions — bitcasts sidestep both."""
    bits = jax.lax.bitcast_convert_type(bits, jnp.uint32)
    mantissa = jax.lax.shift_right_logical(bits, jnp.uint32(9))
    return jax.lax.bitcast_convert_type(
        jax.lax.bitwise_or(mantissa, jnp.uint32(0x3F800000)), jnp.float32
    )


def _box_muller(bits_a, bits_b):
    """Standard-normal noise from two random-bit draws (runs inside the
    kernel)."""
    u1 = 2.0 - _bits_to_unit_float(bits_a)  # in (0, 1]: log never sees 0
    u2 = _bits_to_unit_float(bits_b) - 1.0  # in [0, 1)
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(_TWO_PI * u2)


def _scale_blocks(eps, mu, sigma, out_ref):
    """Fused scale + antithetic blocks: plane 0 = mu+scaled, plane 1 =
    mu-scaled (Mosaic cannot lower strided interleaved stores; the caller
    interleaves the two contiguous planes with a free XLA reshape)."""
    scaled = eps * sigma
    out_ref[0, :, :] = mu + scaled
    out_ref[1, :, :] = mu - scaled


def _pallas_kernel(seed_ref, mu_ref, sigma_ref, out_ref):
    # on-chip PRNG: TPU-only primitives (no CPU interpret lowering exists)
    from jax.experimental.pallas import tpu as pltpu

    pltpu.prng_seed(seed_ref[0])
    half, length = out_ref.shape[1], out_ref.shape[2]
    bits_a = pltpu.prng_random_bits((half, length))
    bits_b = pltpu.prng_random_bits((half, length))
    eps = _box_muller(bits_a, bits_b)
    _scale_blocks(eps, mu_ref[:], sigma_ref[:], out_ref)


def _pallas_kernel_with_noise(eps_ref, mu_ref, sigma_ref, out_ref):
    # variant taking pre-drawn noise: used for interpret-mode testing of the
    # fused scale/antithetic structure on CPU
    _scale_blocks(eps_ref[:], mu_ref[:], sigma_ref[:], out_ref)


@functools.partial(jax.jit, static_argnames=("num_solutions", "use_pallas", "interpret"))
def sample_symmetric_gaussian(
    key,
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    num_solutions: int,
    *,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sample an antithetic population of ``num_solutions`` (even) solutions.

    ``use_pallas=True`` runs the fused TPU kernel (``interpret=True`` for
    CPU-side testing); the default is the XLA path, which produces the same
    distribution (different streams: XLA threefry vs on-chip PRNG)."""
    if num_solutions % 2 != 0:
        raise ValueError(f"num_solutions must be even, got {num_solutions}")
    half = num_solutions // 2
    if not use_pallas:
        return _xla_fallback(key, mu, sigma, half)

    from jax.experimental import pallas as pl

    length = mu.shape[-1]
    out_shape = jax.ShapeDtypeStruct((2, half, length), mu.dtype)

    def interleave(planes):
        # (2, half, L) -> interleaved (2*half, L): [mu+e0, mu-e0, mu+e1, ...]
        return planes.transpose(1, 0, 2).reshape(num_solutions, length)

    if interpret:
        # the TPU PRNG primitives have no CPU lowering; draw the noise with
        # the XLA PRNG and interpret only the fused scale/antithetic part
        eps = jax.random.normal(key, (half, length), dtype=mu.dtype)
        planes = pl.pallas_call(
            _pallas_kernel_with_noise, out_shape=out_shape, interpret=True
        )(eps, mu, sigma)
        return interleave(planes)
    seed = jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
    return interleave(pl.pallas_call(_pallas_kernel, out_shape=out_shape)(seed, mu, sigma))
