"""Fused antithetic-Gaussian sampling kernel.

Computes the PGPE `ask` population
``[mu + sigma*e0, mu - sigma*e0, mu + sigma*e1, ...]`` with the noise
generated on-chip (``pltpu.prng_random_bits`` + Box-Muller) and scaled in
VMEM — the noise tensor never exists in HBM. Mirrors
``SymmetricSeparableGaussian._sample`` (evotorch_tpu/distributions.py), whose
XLA form is the fallback.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["sample_symmetric_gaussian"]

_TWO_PI = 2.0 * math.pi


def _xla_fallback(key, mu, sigma, num_directions):
    eps = jax.random.normal(key, (num_directions, mu.shape[-1]), dtype=mu.dtype) * sigma
    return jnp.stack([mu + eps, mu - eps], axis=1).reshape(2 * num_directions, mu.shape[-1])


def _box_muller(bits_a, bits_b):
    """Standard-normal noise from two uint32 draws (runs inside the kernel)."""
    u1 = (bits_a.astype(jnp.float32) + 1.0) / 4294967296.0
    u2 = bits_b.astype(jnp.float32) / 4294967296.0
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(_TWO_PI * u2)


def _scale_interleave(eps, mu, sigma, out_ref):
    """Fused scale + antithetic interleave into the output block."""
    scaled = eps * sigma
    out_ref[0::2, :] = mu + scaled
    out_ref[1::2, :] = mu - scaled


def _pallas_kernel(seed_ref, mu_ref, sigma_ref, out_ref):
    # on-chip PRNG: TPU-only primitives (no CPU interpret lowering exists)
    from jax.experimental.pallas import tpu as pltpu

    pltpu.prng_seed(seed_ref[0])
    half, length = out_ref.shape[0] // 2, out_ref.shape[1]
    bits_a = pltpu.prng_random_bits((half, length))
    bits_b = pltpu.prng_random_bits((half, length))
    eps = _box_muller(bits_a, bits_b)
    _scale_interleave(eps, mu_ref[:], sigma_ref[:], out_ref)


def _pallas_kernel_with_noise(eps_ref, mu_ref, sigma_ref, out_ref):
    # variant taking pre-drawn noise: used for interpret-mode testing of the
    # fused scale/interleave structure on CPU
    _scale_interleave(eps_ref[:], mu_ref[:], sigma_ref[:], out_ref)


@functools.partial(jax.jit, static_argnames=("num_solutions", "use_pallas", "interpret"))
def sample_symmetric_gaussian(
    key,
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    num_solutions: int,
    *,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sample an antithetic population of ``num_solutions`` (even) solutions.

    ``use_pallas=True`` runs the fused TPU kernel (``interpret=True`` for
    CPU-side testing); the default is the XLA path, which produces the same
    distribution (different streams: XLA threefry vs on-chip PRNG)."""
    if num_solutions % 2 != 0:
        raise ValueError(f"num_solutions must be even, got {num_solutions}")
    half = num_solutions // 2
    if not use_pallas:
        return _xla_fallback(key, mu, sigma, half)

    from jax.experimental import pallas as pl

    out_shape = jax.ShapeDtypeStruct((num_solutions, mu.shape[-1]), mu.dtype)
    if interpret:
        # the TPU PRNG primitives have no CPU lowering; draw the noise with
        # the XLA PRNG and interpret only the fused scale/interleave
        eps = jax.random.normal(key, (half, mu.shape[-1]), dtype=mu.dtype)
        return pl.pallas_call(
            _pallas_kernel_with_noise, out_shape=out_shape, interpret=True
        )(eps, mu, sigma)
    seed = jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
    return pl.pallas_call(_pallas_kernel, out_shape=out_shape)(seed, mu, sigma)
