"""Pallas TPU kernels for the hot ops.

The compute-heavy paths of this framework (population matmuls, rollouts) are
already MXU-shaped through XLA; these kernels cover the ops where explicit
VMEM scheduling wins:

- ``sample_symmetric_gaussian``: fused on-chip sampling of antithetic
  populations (PRNG + scale + interleave without HBM round-trips for the
  noise tensor) — the `ask` hot-op of PGPE at popsize 10k+.
- ``fused_centered_rank``: rank -> centered-utility transform fused over a
  fitness vector.

Every kernel has an XLA fallback (used automatically on CPU or when Pallas
lowering is unavailable), so behavior is identical everywhere; tests exercise
the kernels in Pallas interpret mode.
"""

from .sampling import sample_symmetric_gaussian
from .ranking import fused_centered_rank

__all__ = ["sample_symmetric_gaussian", "fused_centered_rank"]
