"""Pallas TPU kernels for the hot ops.

The compute-heavy paths of this framework (population matmuls, rollouts) are
already MXU-shaped through XLA; these kernels cover the ops where explicit
VMEM scheduling wins:

- ``sample_symmetric_gaussian``: fused on-chip sampling of antithetic
  populations (PRNG + scale + interleave without HBM round-trips for the
  noise tensor) — the `ask` hot-op of PGPE at popsize 10k+.
- ``fused_centered_rank``: rank -> centered-utility transform fused over a
  fitness vector.

Every kernel has an XLA fallback (the default path), distributionally
equivalent but not bit-identical (different PRNG streams). CPU tests exercise
the fused math in Pallas interpret mode; the on-chip-PRNG production kernel
is covered by a TPU-gated test (tests/test_ops.py::test_pallas_sampling_on_tpu).
"""

from .sampling import sample_symmetric_gaussian
from .ranking import fused_centered_rank

__all__ = ["sample_symmetric_gaussian", "fused_centered_rank"]
