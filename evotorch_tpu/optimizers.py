"""Stateful optimizer adapters exposing ``ascent(grad)``.

Parity: reference ``optimizers.py`` — ``ClipUp`` (``optimizers.py:231-418``),
``Adam``/``SGD`` adapters (``optimizers.py:101-229``), ``get_optimizer_class``
(``optimizers.py:421-456``). Each adapter is a thin host-side wrapper around
the corresponding pure functional step (``algorithms/functional/func*.py``),
so the math is written once and is jit-compiled. An ``OptaxOptimizer`` adapter
plays the role of the reference's generic ``TorchOptimizer``
(``optimizers.py:31-98``), accepting any optax ``GradientTransformation``.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Callable, Optional

import jax.numpy as jnp

from .tools.misc import ensure_array_length_and_dtype, to_jax_dtype

__all__ = ["ClipUp", "Adam", "SGD", "OptaxOptimizer", "get_optimizer_class"]


class _FunctionalWrapper:
    """Base for stateful wrappers: the optimizer state tracks a virtual center
    starting at 0; ``ascent(grad)`` returns the center delta."""

    def __init__(self, *, solution_length: int, dtype="float32"):
        self._dtype = to_jax_dtype(dtype)
        self._length = int(solution_length)

    def _zero_center(self):
        return jnp.zeros(self._length, dtype=self._dtype)

    def _coerce(self, grad):
        return ensure_array_length_and_dtype(
            grad, self._length, self._dtype, about=f"{type(self).__name__}.ascent"
        )


class ClipUp(_FunctionalWrapper):
    """The ClipUp optimizer (Toklu et al. 2020; reference
    ``optimizers.py:231-418``): normalize the gradient to ``stepsize``,
    momentum-accumulate, clip velocity norm to ``max_speed``
    (default ``2 * stepsize``)."""

    _param_group_items = {"lr": "_stepsize", "max_speed": "_max_speed", "momentum": "_momentum"}
    _param_group_item_lb = {"lr": 0.0, "max_speed": 0.0, "momentum": 0.0}
    _param_group_item_ub = {"momentum": 1.0}

    def __init__(
        self,
        *,
        solution_length: int,
        dtype="float32",
        stepsize: float,
        momentum: float = 0.9,
        max_speed: Optional[float] = None,
    ):
        super().__init__(solution_length=solution_length, dtype=dtype)
        stepsize = float(stepsize)
        momentum = float(momentum)
        max_speed = stepsize * 2.0 if max_speed is None else float(max_speed)
        if stepsize < 0.0:
            raise ValueError(f"Invalid stepsize: {stepsize}")
        if momentum < 0.0 or momentum > 1.0:
            raise ValueError(f"Invalid momentum: {momentum}")
        if max_speed < 0.0:
            raise ValueError(f"Invalid max_speed: {max_speed}")
        self._stepsize = stepsize
        self._momentum = momentum
        self._max_speed = max_speed
        self._velocity = jnp.zeros(self._length, dtype=self._dtype)
        self._param_groups = (ClipUpParameterGroup(self),)

    def ascent(self, globalg, *, cloned_result: bool = True) -> jnp.ndarray:
        grad = self._coerce(globalg)
        from .algorithms.functional.funcclipup import _clipup_step

        velocity, _ = _clipup_step(
            grad,
            jnp.zeros_like(self._velocity),
            self._velocity,
            jnp.asarray(self._stepsize, dtype=self._dtype),
            jnp.asarray(self._momentum, dtype=self._dtype),
            jnp.asarray(self._max_speed, dtype=self._dtype),
        )
        self._velocity = velocity
        return velocity

    @property
    def contained_optimizer(self) -> "ClipUp":
        return self

    @property
    def param_groups(self) -> tuple:
        return self._param_groups


class ClipUpParameterGroup(Mapping):
    """Mapping view over ClipUp hyperparameters, allowing mid-run mutation
    (reference ``optimizers.py:382-418``)."""

    def __init__(self, clipup: ClipUp):
        self.clipup = clipup

    def __getitem__(self, key: str) -> float:
        return getattr(self.clipup, ClipUp._param_group_items[key])

    def __setitem__(self, key: str, value: float):
        attrname = ClipUp._param_group_items[key]
        value = float(value)
        lb = ClipUp._param_group_item_lb.get(key)
        if lb is not None and value < lb:
            raise ValueError(f"Invalid value for {key!r}: {value}")
        ub = ClipUp._param_group_item_ub.get(key)
        if ub is not None and value > ub:
            raise ValueError(f"Invalid value for {key!r}: {value}")
        setattr(self.clipup, attrname, value)

    def __iter__(self):
        return iter(ClipUp._param_group_items)

    def __len__(self):
        return len(ClipUp._param_group_items)

    def __repr__(self):
        return f"<{type(self).__name__}: {dict(self)}>"


class Adam(_FunctionalWrapper):
    """Adam with ``ascent`` semantics (reference ``optimizers.py:101-170``)."""

    def __init__(
        self,
        *,
        solution_length: int,
        dtype="float32",
        stepsize: Optional[float] = None,
        beta1: Optional[float] = None,
        beta2: Optional[float] = None,
        epsilon: Optional[float] = None,
        amsgrad: Optional[bool] = None,
    ):
        super().__init__(solution_length=solution_length, dtype=dtype)
        if amsgrad:
            raise NotImplementedError("amsgrad is not supported by the TPU Adam adapter")
        self._stepsize = 0.001 if stepsize is None else float(stepsize)
        self._beta1 = 0.9 if beta1 is None else float(beta1)
        self._beta2 = 0.999 if beta2 is None else float(beta2)
        self._epsilon = 1e-8 if epsilon is None else float(epsilon)
        self._m = jnp.zeros(self._length, dtype=self._dtype)
        self._v = jnp.zeros(self._length, dtype=self._dtype)
        self._t = jnp.zeros((), dtype=self._dtype)

    def ascent(self, globalg, *, cloned_result: bool = True) -> jnp.ndarray:
        grad = self._coerce(globalg)
        from .algorithms.functional.funcadam import _adam_step

        center, m, v, t = _adam_step(
            grad,
            jnp.zeros(self._length, dtype=self._dtype),
            jnp.asarray(self._stepsize, dtype=self._dtype),
            jnp.asarray(self._beta1, dtype=self._dtype),
            jnp.asarray(self._beta2, dtype=self._dtype),
            jnp.asarray(self._epsilon, dtype=self._dtype),
            self._m,
            self._v,
            self._t,
        )
        self._m, self._v, self._t = m, v, t
        return center

    @property
    def contained_optimizer(self) -> "Adam":
        return self


class SGD(_FunctionalWrapper):
    """SGD (optionally with momentum) with ``ascent`` semantics
    (reference ``optimizers.py:173-229``)."""

    def __init__(
        self,
        *,
        solution_length: int,
        dtype="float32",
        stepsize: float,
        momentum: Optional[float] = None,
    ):
        super().__init__(solution_length=solution_length, dtype=dtype)
        self._stepsize = float(stepsize)
        self._momentum = 0.0 if momentum is None else float(momentum)
        self._velocity = jnp.zeros(self._length, dtype=self._dtype)

    def ascent(self, globalg, *, cloned_result: bool = True) -> jnp.ndarray:
        grad = self._coerce(globalg)
        from .algorithms.functional.funcsgd import _sgd_step

        velocity, _ = _sgd_step(
            grad,
            jnp.zeros_like(self._velocity),
            self._velocity,
            jnp.asarray(self._stepsize, dtype=self._dtype),
            jnp.asarray(self._momentum, dtype=self._dtype),
        )
        self._velocity = velocity
        return velocity

    @property
    def contained_optimizer(self) -> "SGD":
        return self


class OptaxOptimizer:
    """Adapter exposing ``ascent(grad)`` over any optax
    ``GradientTransformation`` — the analog of the reference's generic
    ``TorchOptimizer`` (``optimizers.py:31-98``).

    Note: optax transforms *descend*: feeding the ascent gradient directly and
    negating the resulting update preserves ascent semantics (the gradient
    statistics inside the transform are sign-symmetric)."""

    def __init__(self, transformation, *, solution_length: int, dtype="float32"):
        self._dtype = to_jax_dtype(dtype)
        self._length = int(solution_length)
        self._tx = transformation
        self._opt_state = self._tx.init(jnp.zeros(self._length, dtype=self._dtype))

    def ascent(self, globalg, *, cloned_result: bool = True) -> jnp.ndarray:
        grad = ensure_array_length_and_dtype(globalg, self._length, self._dtype, about="OptaxOptimizer.ascent")
        updates, self._opt_state = self._tx.update(grad, self._opt_state)
        return -jnp.asarray(updates)

    @property
    def contained_optimizer(self):
        return self._tx


def get_optimizer_class(s: str, optimizer_config: Optional[dict] = None) -> Callable:
    """String -> optimizer class or configured factory
    (reference ``optimizers.py:421-456``)."""
    if s in ("clipsgd", "clipsga", "clipup"):
        cls = ClipUp
    elif s == "adam":
        cls = Adam
    elif s in ("sgd", "sga"):
        cls = SGD
    else:
        raise ValueError(f"Unknown optimizer: {s!r}")
    if optimizer_config is None:
        return cls

    def factory(*args, **kwargs):
        conf = dict(optimizer_config)
        conf.update(kwargs)
        return cls(*args, **conf)

    return factory
