"""Ready-made policy model families.

The reference ships its model zoo inside ``neuroevolution/net/layers.py``
(MLPs via ``FeedForwardNet``, ``StructuredControlNet``, ``LocomotorNet``,
single-step RNN/LSTM). This package packages those into policy factories with
evolution-friendly defaults, for use as ``VecNE``/``GymNE`` network specs or
standalone.
"""

from .policies import (
    LinearPolicy,
    LSTMPolicy,
    MLPPolicy,
    RNNPolicy,
    locomotor_policy,
    structured_control_policy,
)

__all__ = [
    "LinearPolicy",
    "LSTMPolicy",
    "MLPPolicy",
    "RNNPolicy",
    "locomotor_policy",
    "structured_control_policy",
]
