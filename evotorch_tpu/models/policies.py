"""Policy factories over the functional layer system."""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

from ..neuroevolution.net.layers import (
    LSTM,
    RNN,
    Apply,
    Linear,
    LocomotorNet,
    Module,
    Sequential,
    StructuredControlNet,
)

__all__ = [
    "LinearPolicy",
    "MLPPolicy",
    "RNNPolicy",
    "LSTMPolicy",
    "structured_control_policy",
    "locomotor_policy",
]


def LinearPolicy(obs_length: int, act_length: int, *, bias: bool = True) -> Module:
    """The classic ES linear controller."""
    return Linear(obs_length, act_length, bias=bias)


def MLPPolicy(
    obs_length: int,
    act_length: int,
    *,
    hidden: Sequence[int] = (64, 64),
    activation: Callable = jnp.tanh,
    final_activation: Callable = None,
) -> Module:
    """Tanh MLP, the standard ES policy (e.g. Salimans et al. 2017)."""
    modules = []
    in_size = obs_length
    for h in hidden:
        modules.append(Linear(in_size, int(h)))
        modules.append(Apply(activation))
        in_size = int(h)
    modules.append(Linear(in_size, act_length))
    if final_activation is not None:
        modules.append(Apply(final_activation))
    return Sequential(modules)


def RNNPolicy(obs_length: int, act_length: int, *, hidden_size: int = 64) -> Module:
    """Single-step Elman RNN policy for partially observable tasks."""
    return RNN(obs_length, hidden_size) >> Linear(hidden_size, act_length)


def LSTMPolicy(obs_length: int, act_length: int, *, hidden_size: int = 64) -> Module:
    return LSTM(obs_length, hidden_size) >> Linear(hidden_size, act_length)


def structured_control_policy(
    obs_length: int, act_length: int, *, num_layers: int = 2, hidden_size: int = 32
) -> Module:
    """Structured Control Net policy (reference ``layers.py:377-467``)."""
    return StructuredControlNet(
        in_features=obs_length,
        out_features=act_length,
        num_layers=num_layers,
        hidden_size=hidden_size,
    )


def locomotor_policy(obs_length: int, act_length: int, *, num_sinusoids: int = 16) -> Module:
    """Locomotor Net policy (reference ``layers.py:470-568``)."""
    return LocomotorNet(
        in_features=obs_length, out_features=act_length, num_sinusoids=num_sinusoids
    )
