"""evotorch_tpu: a TPU-native (JAX/XLA/pjit/shard_map) evolutionary
computation framework with the capabilities of EvoTorch (nnaisense/evotorch).

Design stance (SURVEY.md §7): the pure-functional ask/tell layer is the core —
pytree states, ``jit``/``vmap``/``shard_map`` everywhere — and thin stateful
wrappers reproduce the reference's OO ergonomics (Problem / SearchAlgorithm /
status / loggers) on top. Ray actors are replaced by SPMD over the device mesh.

Package entry parity: reference ``src/evotorch/__init__.py:29-38`` re-exports
``Problem, Solution, SolutionBatch, ProblemBoundEvaluator`` and subpackages.
"""

from . import algorithms, checkpoint, decorators, distributions, envs, logging, models, neuroevolution, operators, ops, optimizers, parallel, testing, tools, utils
from .core import Problem, ProblemBoundEvaluator, Solution, SolutionBatch, SolutionBatchPieces
from .decorators import expects_ndim, on_aux_device, on_cuda, on_device, pass_info, rowwise, vectorized

__all__ = [
    "algorithms",
    "Problem",
    "ProblemBoundEvaluator",
    "Solution",
    "SolutionBatch",
    "SolutionBatchPieces",
    "checkpoint",
    "decorators",
    "distributions",
    "envs",
    "models",
    "ops",
    "testing",
    "utils",
    "logging",
    "neuroevolution",
    "operators",
    "optimizers",
    "parallel",
    "tools",
    "expects_ndim",
    "on_aux_device",
    "on_cuda",
    "on_device",
    "pass_info",
    "rowwise",
    "vectorized",
]

__version__ = "0.1.0"
