"""evotorch_tpu: a TPU-native (JAX/XLA/pjit/shard_map) evolutionary
computation framework with the capabilities of EvoTorch (nnaisense/evotorch).

Design stance (SURVEY.md §7): the pure-functional ask/tell layer is the core —
pytree states, ``jit``/``vmap``/``shard_map`` everywhere — and thin stateful
wrappers reproduce the reference's OO ergonomics (Problem / SearchAlgorithm /
status / loggers) on top. Ray actors are replaced by SPMD over the device mesh.

Package entry parity: reference ``src/evotorch/__init__.py:29-38`` re-exports
``Problem, Solution, SolutionBatch, ProblemBoundEvaluator`` and subpackages.
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # this codebase targets the stable `jax.shard_map(..., check_vma=...)`
    # API; on older jax (<= 0.4.x) the same functionality lives at
    # `jax.experimental.shard_map.shard_map(..., check_rep=...)` — install a
    # signature-adapting alias so every call site works on both
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def _shard_map_compat(
        f, mesh=None, *, in_specs, out_specs, check_vma=True, **kwargs
    ):
        # mesh stays positional-or-keyword: the stable jax.shard_map accepts
        # `jax.shard_map(f, mesh, in_specs=..., out_specs=...)`
        kwargs.setdefault("check_rep", check_vma)
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    _jax.shard_map = _shard_map_compat

from . import algorithms, checkpoint, decorators, distributions, envs, logging, models, neuroevolution, operators, ops, optimizers, parallel, testing, tools, utils
from .core import Problem, ProblemBoundEvaluator, Solution, SolutionBatch, SolutionBatchPieces
from .decorators import expects_ndim, on_aux_device, on_cuda, on_device, pass_info, rowwise, vectorized

__all__ = [
    "algorithms",
    "Problem",
    "ProblemBoundEvaluator",
    "Solution",
    "SolutionBatch",
    "SolutionBatchPieces",
    "checkpoint",
    "decorators",
    "distributions",
    "envs",
    "models",
    "ops",
    "testing",
    "utils",
    "logging",
    "neuroevolution",
    "operators",
    "optimizers",
    "parallel",
    "tools",
    "expects_ndim",
    "on_aux_device",
    "on_cuda",
    "on_device",
    "pass_info",
    "rowwise",
    "vectorized",
]

__version__ = "0.1.0"
