"""Request/future plumbing of the evaluation service.

One :class:`EvalRequest` is one tenant's "evaluate these N solutions" call.
The server slices it into per-solution *items*, packs items from many
requests into fixed-width ``episodes_refill`` slabs, and assembles each
request's scores back as its items finish — across as many device dispatches
as packing needs. The client-facing handle is the :class:`EvalFuture`: a
``Future``-style object whose ``result()`` *drives* the owning server until
the request is complete (the in-process server is synchronous — there is no
background thread to wait on, so waiting IS serving; see docs/serving.md).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

__all__ = ["EvalFuture", "EvalRequest"]


class EvalRequest:
    """One tenant's pending evaluation: an ``(n, P)`` parameter matrix, the
    tenant's base PRNG key for this call, and the assembly buffers the
    packer fills as items complete. Internal to the server; clients hold
    the :class:`EvalFuture` (``request.future``)."""

    def __init__(self, request_id: int, tenant, values, key, server):
        self.request_id = int(request_id)
        self.tenant = tenant
        self.values = values  # (n, P), host or device
        self.key = key  # typed PRNG key (scalar) — the tenant's base key
        self.key_data = None  # raw key data (numpy), set by server.submit
        self.num_solutions = int(values.shape[0])
        # assembly state -----------------------------------------------------
        self.next_item = 0  # first not-yet-packed solution index
        self.pending_items = self.num_solutions  # packed-but-unfinished + unpacked
        self.scores = np.full(self.num_solutions, np.nan, dtype=np.float64)
        self.telemetry = None  # accumulated GroupTelemetry (tenant's row)
        self.submit_dispatch = None  # server dispatch counter at submit time
        self.future = EvalFuture(self, server)

    @property
    def done(self) -> bool:
        return self.pending_items == 0

    def take_items(self, k: int) -> range:
        """Claim the next ``k`` (at most) unpacked solution indices."""
        start = self.next_item
        stop = min(start + int(k), self.num_solutions)
        self.next_item = stop
        return range(start, stop)


class EvalFuture:
    """Handle on a submitted evaluation.

    ``done()`` is a cheap poll; ``result()`` drives the owning server's
    dispatch loop until this request completes, then returns a
    ``RolloutResult``-compatible record (scores / stats / counters /
    telemetry wire) — what :class:`~evotorch_tpu.serving.RemoteEvalBackend`
    hands back to an unmodified ``VecNE``. ``result()`` may therefore
    execute device work for OTHER tenants too (their items share the
    slabs); that is the point of the service.
    """

    def __init__(self, request: EvalRequest, server):
        self._request = request
        self._server = server
        self._lock = threading.Lock()
        self._result: Optional[Any] = None
        self._error: Optional[BaseException] = None

    @property
    def request_id(self) -> int:
        return self._request.request_id

    @property
    def tenant(self):
        return self._request.tenant

    def done(self) -> bool:
        with self._lock:
            return self._result is not None or self._error is not None

    def set_result(self, result) -> None:
        with self._lock:
            self._result = result

    def set_error(self, error: BaseException) -> None:
        with self._lock:
            self._error = error

    def result(self, *, max_dispatches: Optional[int] = None):
        """Drive the server until this request is complete; return the
        evaluation record. ``max_dispatches`` bounds the number of device
        dispatches this call may execute (None = until done)."""
        dispatched = 0
        while not self.done():
            served = self._server.step()
            dispatched += 1
            if served == 0 and not self.done():
                raise RuntimeError(
                    f"request {self.request_id} cannot complete: the server"
                    " has no pending work for it (was its tenant departed?)"
                )
            if max_dispatches is not None and dispatched >= max_dispatches:
                break
        with self._lock:
            if self._error is not None:
                raise self._error
            if self._result is None:
                raise TimeoutError(
                    f"request {self.request_id} still pending after"
                    f" {dispatched} dispatches"
                )
            return self._result
