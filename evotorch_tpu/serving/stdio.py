"""JSONL-over-stdio front for out-of-process clients.

``python -m evotorch_tpu.serving --env cartpole --slab 16`` runs an
:class:`EvalServer` behind a line protocol: one JSON object per request
line on stdin, one JSON object per response line on stdout (stderr is free
for logs). Every response carries ``ok`` and echoes ``op``; failures are
``{"ok": false, "error": ...}`` and never kill the server. The protocol is
deliberately tiny — it is the out-of-process escape hatch, not the fast
path (in-process clients use :class:`RemoteEvalBackend`); docs/serving.md
documents each op with examples.

Ops: ``admit`` ``submit`` ``poll`` ``step`` ``result`` ``depart``
``status`` ``shutdown``.
"""

from __future__ import annotations

import json
from typing import Dict

import numpy as np

from .server import EvalServer

__all__ = ["serve_stdio"]


def _op_admit(server, futures, msg):
    tenant = server.admit(name=msg.get("tenant"))
    return {"tenant": tenant.name, "group": tenant.group}


def _op_submit(server, futures, msg):
    import jax

    tenant = _tenant(server, msg)
    values = np.asarray(msg["values"], dtype=np.float32)
    if values.ndim != 2:
        raise ValueError(f"values must be 2-D (n, params), got shape {values.shape}")
    key = None
    if "seed" in msg:
        key = jax.random.key(int(msg["seed"]))
    future = server.submit(tenant, values, key)
    futures[future.request_id] = future
    return {"request_id": future.request_id, "num_solutions": int(values.shape[0])}


def _op_poll(server, futures, msg):
    return {"done": _future(futures, msg).done()}


def _op_step(server, futures, msg):
    return {"served": server.step()}


def _op_result(server, futures, msg):
    future = _future(futures, msg)
    result = future.result()
    del futures[int(msg["request_id"])]
    tenant = future.tenant
    out = {
        "scores": [float(s) for s in np.asarray(result.scores)],
        "env_steps": int(result.total_steps),
        "episodes": int(result.total_episodes),
    }
    if tenant.telemetry is not None:
        out["queue_wait_p50"] = tenant.telemetry.queue_wait_quantile(0.5)
        out["queue_wait_p99"] = tenant.telemetry.queue_wait_quantile(0.99)
    return out


def _op_depart(server, futures, msg):
    tenant = _tenant(server, msg)
    server.depart(tenant, cancel=bool(msg.get("cancel", False)))
    return {"tenant": tenant.name}


def _op_status(server, futures, msg):
    return server.status()


_OPS = {
    "admit": _op_admit,
    "submit": _op_submit,
    "poll": _op_poll,
    "step": _op_step,
    "result": _op_result,
    "depart": _op_depart,
    "status": _op_status,
}


def _tenant(server: EvalServer, msg: dict):
    name = msg.get("tenant")
    for tenant in server.tenants:
        if tenant.name == name:
            return tenant
    raise ValueError(f"unknown tenant {name!r}")


def _future(futures: Dict[int, object], msg: dict):
    request_id = int(msg["request_id"])
    if request_id not in futures:
        raise ValueError(f"unknown request_id {request_id}")
    return futures[request_id]


def serve_stdio(server: EvalServer, infile, outfile) -> int:
    """Run the line protocol until EOF or a ``shutdown`` op; returns the
    number of requests handled. Pure function of its streams — the tests
    drive it with StringIO pairs."""
    handled = 0
    futures: Dict[int, object] = {}
    for raw in infile:
        raw = raw.strip()
        if not raw:
            continue
        handled += 1
        try:
            msg = json.loads(raw)
            op = msg.get("op")
            if op == "shutdown":
                _write(outfile, {"ok": True, "op": "shutdown"})
                break
            handler = _OPS.get(op)
            if handler is None:
                raise ValueError(f"unknown op {op!r}")
            response = {"ok": True, "op": op}
            response.update(handler(server, futures, msg))
            if "id" in msg:
                response["id"] = msg["id"]
            _write(outfile, response)
        except Exception as exc:  # graftlint: allow(swallow): every failure is reported back on the protocol stream as an error line
            _write(outfile, {"ok": False, "error": f"{type(exc).__name__}: {exc}"})
    return handled


def _write(outfile, obj: dict) -> None:
    outfile.write(json.dumps(obj, sort_keys=True))
    outfile.write("\n")
    outfile.flush()
