"""``python -m evotorch_tpu.serving`` — the stdio evaluation service.

Builds one :class:`EvalServer` from CLI flags and speaks the JSONL
protocol on stdin/stdout (docs/serving.md "The JSONL protocol"). The
policy form is a tanh MLP over ``--hidden`` (empty = linear), matching
the bench/locomotion policy builder convention.
"""

from __future__ import annotations

import argparse
import sys


def _build_policy(env, hidden: str):
    from ..neuroevolution.net import FlatParamsPolicy, Linear, Tanh

    sizes = [int(h) for h in hidden.split(",") if h.strip()] if hidden else []
    widths = [env.observation_size, *sizes, env.action_size]
    net = None
    for n_in, n_out in zip(widths[:-1], widths[1:]):
        layer = Linear(n_in, n_out) >> Tanh()
        net = layer if net is None else net >> layer
    return FlatParamsPolicy(net)


def _main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m evotorch_tpu.serving",
        description="JSONL-over-stdio multi-tenant evaluation service",
    )
    parser.add_argument("--env", required=True, help="registry env name")
    parser.add_argument("--hidden", default="", help="MLP hidden sizes, e.g. 64,64")
    parser.add_argument("--slab", type=int, required=True, help="slab size (rows/dispatch)")
    parser.add_argument("--width", type=int, default=None, help="refill lane width")
    parser.add_argument("--max-tenants", type=int, default=4)
    parser.add_argument("--num-episodes", type=int, default=1)
    parser.add_argument("--episode-length", type=int, default=None)
    parser.add_argument("--obs-norm", action="store_true")
    parser.add_argument(
        "--admission", default="fifo", choices=("fifo", "starvation")
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--cpu", action="store_true", help="force the CPU backend (8 virtual devices)"
    )
    args = parser.parse_args(argv)

    if args.cpu:
        import os

        os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")

    import evotorch_tpu  # noqa: F401  (shard_map alias install)

    from ..envs import make_env
    from .server import EvalServer
    from .stdio import serve_stdio

    env = make_env(args.env)
    server = EvalServer(
        env,
        _build_policy(env, args.hidden),
        slab_size=args.slab,
        max_tenants=args.max_tenants,
        refill_width=args.width,
        num_episodes=args.num_episodes,
        episode_length=args.episode_length,
        observation_normalization=args.obs_norm,
        admission=args.admission,
        seed=args.seed,
    )
    print(
        f"serving {args.env} slab={args.slab} max_tenants={args.max_tenants}"
        f" program={server.program.key}",
        file=sys.stderr,
    )
    serve_stdio(server, sys.stdin, sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
