"""The VecNE-side adapter: evaluate an unmodified problem through a server.

``VecNE(..., eval_backend=RemoteEvalBackend(server))`` (or
``eval_backend=server`` — the problem wraps it) reroutes every generation's
rollout dispatch through the shared :class:`~evotorch_tpu.serving.EvalServer`
instead of compiling the problem's own program: the backend admits itself as
a tenant, submits each batch under the problem's OWN next PRNG key, drives
the server until the request completes (serving other tenants' items along
the way — that is the sharing), and hands back a ``RolloutResult`` the
problem consumes exactly as it consumes its standalone engines' — scores,
obs-norm stats (the tenant's slot), step/episode counters and a standard
telemetry wire. Searchers never know.

Bit-identity: at ``num_episodes == 1`` without observation normalization,
the scores a problem sees through the backend are bit-identical to the
scores the same problem computes standalone with the same seed — the
engine's per-item key derivation is packing-invariant (docs/serving.md
"Isolation").
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .server import EvalServer, Tenant

__all__ = ["RemoteEvalBackend"]


class RemoteEvalBackend:
    """Adapter between one ``VecNE`` problem and a shared :class:`EvalServer`.

    One backend = one tenant. Construct with a server (the backend admits
    itself, optionally under ``name``) or with an existing tenant handle.
    ``close()`` departs the tenant and frees its group row.
    """

    def __init__(
        self,
        server: EvalServer,
        *,
        tenant: Optional[Tenant] = None,
        name: Optional[str] = None,
    ):
        if not isinstance(server, EvalServer):
            raise TypeError(
                f"server must be an EvalServer, got {type(server).__name__}"
            )
        self.server = server
        self.tenant = tenant if tenant is not None else server.admit(name=name)
        self._checked_for = None

    # ------------------------------------------------------------- lifecycle
    def close(self, *, cancel: bool = False) -> None:
        self.server.depart(self.tenant, cancel=cancel)

    def __enter__(self) -> "RemoteEvalBackend":
        return self

    def __exit__(self, *exc) -> None:
        self.close(cancel=True)

    # ------------------------------------------------------------ validation
    def _check_problem(self, problem) -> None:
        """The residency contract: the server runs ONE program, so every
        attached problem must describe the same eval contract. Checked once
        per problem (the attributes are construction-time constants)."""
        if self._checked_for is problem:
            return
        server = self.server
        mismatches = []
        if problem._policy.parameter_count != server.policy.parameter_count:
            mismatches.append(
                f"parameter_count {problem._policy.parameter_count} !="
                f" {server.policy.parameter_count}"
            )
        if problem._env.observation_size != server.env.observation_size:
            mismatches.append("observation_size differs")
        if problem._env.action_size != server.env.action_size:
            mismatches.append("action_size differs")
        if problem._num_episodes != server.num_episodes:
            mismatches.append(
                f"num_episodes {problem._num_episodes} != {server.num_episodes}"
            )
        if problem._episode_length != (
            None if server.episode_length is None else int(server.episode_length)
        ):
            mismatches.append(
                f"episode_length {problem._episode_length} != {server.episode_length}"
            )
        if problem._observation_normalization != server.observation_normalization:
            mismatches.append("observation_normalization differs")
        if problem._eval_mode == "budget":
            mismatches.append(
                "the server serves the episodes contract; a budget-contract"
                " problem cannot evaluate through it"
            )
        if mismatches:
            raise ValueError(
                "problem is incompatible with the server's resident program: "
                + "; ".join(mismatches)
            )
        self._checked_for = problem

    # ------------------------------------------------------------- evaluate
    def evaluate(self, problem, values, key, groups=None):
        """One generation's rollout dispatch, served remotely. Matches the
        ``RolloutResult`` contract of ``problem._rollout_batch``."""
        from ..tools.lowrank import is_factored

        if groups is not None:
            raise ValueError(
                "solution_groups cannot ride through a RemoteEvalBackend:"
                " the server's group axis IS the tenant axis"
            )
        if is_factored(values):
            raise ValueError(
                "factored (low-rank / trunk-delta) populations are not"
                " servable yet; densify or evaluate standalone"
            )
        self._check_problem(problem)
        stats = (
            problem._obs_norm.stats if problem._observation_normalization else None
        )
        future = self.server.submit(
            self.tenant, np.asarray(values), key, stats=stats
        )
        result = future.result()
        if problem._nonfinite_quarantine:
            # standalone semantics, tenant-locally: the worst-finite (or
            # fixed-penalty) replacement pool is THIS tenant's scores only —
            # the server never applies the batch-worst rule across a
            # multi-tenant slab (docs/serving.md "Isolation")
            from ..neuroevolution.net.vecrl import _quarantine_nonfinite

            scores, _ = _quarantine_nonfinite(
                result.scores, penalty=problem._nonfinite_penalty
            )
            result = result._replace(scores=scores)
        return result
