"""Fairness policies for the evaluation service's slab packer.

An :class:`AdmissionPolicy` answers one question per dispatch: *in what
order do the tenants with pending work get lanes?* The packer walks the
returned order, taking each tenant's queued items FIFO until the slab is
full — so the policy controls inter-tenant fairness while intra-tenant
order stays submission order.

Policies are driven by the per-group queue-wait histograms the refill
engine already accumulates on-device (``GroupTelemetry.hist``) — per-tenant
tail-wait accounting at zero extra sync cost, which is what makes a
starvation-aware policy cheap enough to run every dispatch
(docs/serving.md "Fairness").
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = [
    "AdmissionPolicy",
    "FIFOAdmission",
    "StarvationAwareAdmission",
]


class AdmissionPolicy:
    """Base interface: order tenants for one packing round."""

    def order(self, tenants: Sequence, server) -> List:
        """Return ``tenants`` (those with pending items, pre-filtered by the
        server: admitted, not suspended) in service order — first gets
        lanes first."""
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__ + "()"


class FIFOAdmission(AdmissionPolicy):
    """Serve the tenant whose OLDEST pending request was submitted first.

    Ties (same submit dispatch) break by admission order. With a single
    tenant this degenerates to plain FIFO over its requests, which is the
    standalone-equivalent schedule the bit-identity tests rely on."""

    def order(self, tenants: Sequence, server) -> List:
        return sorted(
            tenants,
            key=lambda t: (t.oldest_pending_dispatch(), t.group),
        )


class StarvationAwareAdmission(AdmissionPolicy):
    """Weighted fairness off the on-device queue-wait histograms.

    Each tenant's priority is its cumulative *starvation share* — the
    fraction of its refilled items that waited in the histogram's overflow
    bucket (>= 64 loop steps; the same figure the ``starvation_share`` SLO
    rule gates on) — tie-broken by tail wait (p99) and then FIFO order. A
    tenant that has been repeatedly out-packed accumulates overflow-bucket
    mass and floats to the front of the next rounds until its tail
    recovers; tenants with no histogrammed waits yet rank by FIFO.

    ``bias`` (default 0) adds a constant to every NEW tenant's priority so
    fresh admissions are not starved by incumbents' clean histories.
    """

    def __init__(self, *, bias: float = 0.0):
        self.bias = float(bias)

    def order(self, tenants: Sequence, server) -> List:
        def priority(t):
            gt = t.telemetry
            if gt is None:
                starvation, tail = self.bias, 0.0
            else:
                starvation = gt.starvation_share()
                tail = gt.queue_wait_quantile(0.99)
            # descending starvation/tail, ascending FIFO key
            return (-starvation, -tail, t.oldest_pending_dispatch(), t.group)

        return sorted(tenants, key=priority)

    def __repr__(self):
        return f"StarvationAwareAdmission(bias={self.bias})"


def resolve_policy(policy) -> AdmissionPolicy:
    """Coerce a policy spec: an instance passes through; None = FIFO; the
    strings "fifo" / "starvation" name the built-ins."""
    if policy is None:
        return FIFOAdmission()
    if isinstance(policy, AdmissionPolicy):
        return policy
    if isinstance(policy, str):
        name = policy.lower()
        if name == "fifo":
            return FIFOAdmission()
        if name in ("starvation", "starvation_aware"):
            return StarvationAwareAdmission()
        raise ValueError(f"unknown admission policy {policy!r}")
    raise TypeError(
        f"admission policy must be an AdmissionPolicy, a name or None,"
        f" got {type(policy).__name__}"
    )
