"""The in-process multi-tenant evaluation server.

One :class:`EvalServer` owns ONE resident compiled ``episodes_refill``
program (``parallel.make_resident_rollout_program``) and keeps it saturated
with (solution, episode) items from many concurrent searches — vLLM-style
continuous batching where the telemetry group id IS the tenant id:

- the slab (``slab_size`` parameter rows), the lane width, the group-row
  count (``max_tenants + 1``) and the mesh layout are fixed at server
  construction — the residency key;
- everything per-dispatch — which tenant owns which slab row, each item's
  tenant-local lane id, each tenant's base PRNG key, the stacked obs-norm
  slots — is a TRACED program input, so tenants admitting, departing and
  churning re-dispatch the same executable (steady_compiles == 0; the
  serving tests pin it with the retrace sentinel);
- group row 0 is RESERVED for padding: a partially-filled slab repeats a
  real row's parameters into the idle lanes but charges their steps to
  group 0, so no tenant's occupancy/score statistics ever see them.

Isolation guarantees (docs/serving.md): per-tenant PRNG (each item's key
chain derives from ITS request's base key via ``solution_keys``, exactly
the standalone derivation — per-tenant scores are bit-identical to the
tenant evaluating alone), per-tenant obs-norm slots (stacked
``CollectedStats``; a slot resets on departure), per-tenant telemetry
rows, and per-tenant SLO admission control (a tenant tripping its
watchdog stops being able to submit, it does not take the server down).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .admission import AdmissionPolicy, resolve_policy
from .requests import EvalFuture, EvalRequest

__all__ = ["EvalServer", "Tenant"]


class Tenant:
    """One admitted search: a group row, a FIFO of pending requests, the
    cumulative telemetry accounting and the SLO admission state."""

    def __init__(self, group: int, name: str, admitted_dispatch: int, watchdog=None):
        self.group = int(group)
        self.name = str(name)
        self.admitted_dispatch = int(admitted_dispatch)
        self.watchdog = watchdog
        self.pending: deque = deque()  # EvalRequests with unpacked/unfinished items
        self.telemetry = None  # cumulative GroupTelemetry (this tenant's row)
        self.suspended = False
        self.slo_report = None
        self.requests_served = 0

    @property
    def pending_items(self) -> int:
        return sum(r.pending_items for r in self.pending)

    def oldest_pending_dispatch(self) -> int:
        """Submit-time dispatch index of the oldest pending request (the
        FIFO admission key); large when nothing is pending."""
        if not self.pending:
            return 1 << 62
        return self.pending[0].submit_dispatch

    def __repr__(self):
        state = "suspended" if self.suspended else "active"
        return (
            f"Tenant({self.name!r}, group={self.group}, {state},"
            f" pending={self.pending_items})"
        )


class EvalServer:
    """Long-running in-process evaluation service over one resident program.

    Parameters
    ----------
    env : str or Env — the (shared) evaluation environment.
    network : a net Module or FlatParamsPolicy — the (shared) policy form;
        every tenant's solutions must be this policy's flat parameters.
    slab_size : parameter rows per dispatch (the packing width).
    max_tenants : group rows 1..max_tenants (row 0 is the padding group).
    refill_width / refill_period : the refill engine's lane schedule
        (width defaults to the engine's own default for the slab).
    num_episodes / episode_length / observation_normalization /
    compute_dtype : the eval contract, shared by all tenants (a tenant
        needing a different contract needs a different server; residency
        means ONE program).
    admission : AdmissionPolicy | "fifo" | "starvation" | None — inter-tenant
        packing order (docs/serving.md "Fairness").
    slo : SLO rule list — each tenant gets its OWN stateful watchdog over
        these rules; a violating tenant is suspended (submit refuses) while
        its already-queued work drains.
    metrics : a MetricsHub — per-dispatch rows with the per-tenant telemetry
        breakdown.
    mesh : optional device mesh; the slab is GSPMD-pinned to it inside the
        resident program (scores stay bit-identical to unsharded).
    nonfinite_penalty : enables non-finite score quarantine with a FIXED
        penalty. The batch-worst-finite default is deliberately NOT offered
        here: the "batch" is the whole multi-tenant slab, so the worst
        finite score would leak across tenant boundaries.
    seed : folds the per-dispatch engine key (unused for item randomness —
        that comes from each request's base key — but still a program input).
    """

    def __init__(
        self,
        env,
        network,
        *,
        slab_size: int,
        max_tenants: int = 4,
        refill_width: Optional[int] = None,
        refill_period: int = 1,
        num_episodes: int = 1,
        episode_length: Optional[int] = None,
        observation_normalization: bool = False,
        compute_dtype=None,
        admission=None,
        slo=None,
        metrics=None,
        mesh=None,
        health: bool = True,
        nonfinite_penalty: Optional[float] = None,
        seed: int = 0,
        seed_stride: Optional[int] = None,
    ):
        import jax

        from ..envs import Env, make_env
        from ..neuroevolution.net.functional import FlatParamsPolicy
        from ..neuroevolution.net.layers import Module
        from ..parallel.evaluate import make_resident_rollout_program

        if isinstance(env, str):
            env = make_env(env)
        if not isinstance(env, Env):
            raise TypeError(f"env must be a string or Env, got {type(env).__name__}")
        if isinstance(network, FlatParamsPolicy):
            policy = network
        elif isinstance(network, Module):
            policy = FlatParamsPolicy(network)
        else:
            raise TypeError(
                "network must be a net Module or FlatParamsPolicy,"
                f" got {type(network).__name__}"
            )
        self.env = env
        self.policy = policy
        self.slab_size = int(slab_size)
        if self.slab_size < 1:
            raise ValueError(f"slab_size must be >= 1, got {slab_size}")
        self.max_tenants = int(max_tenants)
        if self.max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, got {max_tenants}")
        self.num_groups = self.max_tenants + 1  # group 0 = padding
        self.num_episodes = int(num_episodes)
        self.episode_length = episode_length
        self.observation_normalization = bool(observation_normalization)
        self.compute_dtype = compute_dtype
        # one static stride for the whole slab: at num_episodes == 1 the
        # episode index is always 0 so it never enters the item seeds; at
        # num_episodes > 1 a standalone run matches bit-for-bit when it
        # passes seed_stride=server.seed_stride (docs/serving.md)
        self.seed_stride = int(seed_stride) if seed_stride is not None else self.slab_size
        self._admission: AdmissionPolicy = resolve_policy(admission)
        self._slo_rules = slo
        self._metrics = metrics
        self._mesh = mesh

        rollout_kwargs = dict(
            num_episodes=self.num_episodes,
            episode_length=self.episode_length,
            observation_normalization=self.observation_normalization,
            compute_dtype=compute_dtype,
            num_groups=self.num_groups,
            seed_stride=self.seed_stride,
            refill_period=int(refill_period),
            telemetry=True,  # the server's accounting plane — not optional
            health=bool(health),
        )
        if refill_width is not None:
            rollout_kwargs["refill_width"] = int(refill_width)
        if nonfinite_penalty is not None:
            rollout_kwargs["nonfinite_quarantine"] = True
            rollout_kwargs["nonfinite_penalty"] = float(nonfinite_penalty)
        self.program = make_resident_rollout_program(
            env, policy, mesh=mesh, **rollout_kwargs
        )

        self._key = jax.random.key(int(seed))
        if self.observation_normalization:
            from ..neuroevolution.net.runningnorm import group_stats_init

            self._stats = group_stats_init(self.num_groups, env.observation_size)
        else:
            self._stats = None
        self._lock = threading.RLock()
        self._tenants: Dict[int, Tenant] = {}  # group -> Tenant
        self._by_name: Dict[str, Tenant] = {}
        self._next_request_id = 0
        self._dispatch_count = 0
        self._items_served = 0
        self._score_dtype = None

    # ------------------------------------------------------ tenant lifecycle
    def admit(self, name: Optional[str] = None) -> Tenant:
        """Register a tenant; returns its handle. Raises when all
        ``max_tenants`` group rows are occupied."""
        with self._lock:
            free = [g for g in range(1, self.num_groups) if g not in self._tenants]
            if not free:
                raise RuntimeError(
                    f"server is full: {self.max_tenants} tenants admitted"
                )
            group = free[0]
            if name is None:
                name = f"tenant{group}"
            if name in self._by_name:
                raise ValueError(f"tenant name {name!r} already admitted")
            watchdog = None
            if self._slo_rules is not None:
                from ..observability.slo import SLOWatchdog

                # each tenant gets its OWN stateful watchdog so the health
                # trend windows never mix across tenants
                watchdog = SLOWatchdog(self._slo_rules)
            tenant = Tenant(group, name, self._dispatch_count, watchdog)
            self._tenants[group] = tenant
            self._by_name[name] = tenant
            return tenant

    def depart(self, tenant: Tenant, *, cancel: bool = False) -> None:
        """Release a tenant's group row. Pending requests either forbid the
        departure (default) or are cancelled (their futures raise). The
        tenant's obs-norm slot is zeroed, so the row is clean for the next
        admission — lane rebinding on churn, no retrace (the slot is a
        traced input)."""
        with self._lock:
            if self._tenants.get(tenant.group) is not tenant:
                raise ValueError(f"{tenant!r} is not admitted on this server")
            if tenant.pending and not cancel:
                raise RuntimeError(
                    f"{tenant!r} has pending work; drain it or depart(cancel=True)"
                )
            for req in tenant.pending:
                req.future.set_error(
                    RuntimeError(
                        f"request {req.request_id} cancelled: tenant"
                        f" {tenant.name!r} departed"
                    )
                )
            tenant.pending.clear()
            if self._stats is not None:
                from ..neuroevolution.net.runningnorm import CollectedStats

                g = tenant.group
                self._stats = CollectedStats(
                    count=self._stats.count.at[g].set(0.0),
                    sum=self._stats.sum.at[g].set(0.0),
                    sum_of_squares=self._stats.sum_of_squares.at[g].set(0.0),
                )
            del self._tenants[tenant.group]
            del self._by_name[tenant.name]

    @property
    def tenants(self) -> Tuple[Tenant, ...]:
        with self._lock:
            return tuple(self._tenants[g] for g in sorted(self._tenants))

    # -------------------------------------------------------------- obs-norm
    def tenant_stats(self, tenant: Tenant):
        """The tenant's current obs-norm slot as a plain CollectedStats
        (None when the server runs without observation normalization)."""
        if self._stats is None:
            return None
        from ..neuroevolution.net.runningnorm import stats_slot

        return stats_slot(self._stats, tenant.group)

    def _seed_tenant_stats(self, tenant: Tenant, stats) -> None:
        """Overwrite the tenant's slot from a submitted (unstacked) stats
        pytree — how a resuming search re-seeds its normalization history."""
        from ..neuroevolution.net.runningnorm import CollectedStats

        g = tenant.group
        self._stats = CollectedStats(
            count=self._stats.count.at[g].set(stats.count),
            sum=self._stats.sum.at[g].set(stats.sum),
            sum_of_squares=self._stats.sum_of_squares.at[g].set(stats.sum_of_squares),
        )

    # ------------------------------------------------------------- submission
    def submit(self, tenant: Tenant, values, key=None, *, stats=None) -> EvalFuture:
        """Queue one evaluation of an ``(n, P)`` parameter matrix under the
        tenant's identity; returns the :class:`EvalFuture`.

        ``key`` is the request's base PRNG key (typed or legacy uint32);
        item ``i`` of the request evaluates with exactly the randomness a
        standalone ``episodes_refill`` run over the same matrix and key
        would give it, whatever the packing. Defaults to a key folded from
        the server seed and the request id (reproducible, but NOT any
        standalone run's key — pass the search's own key for bit-identity).
        """
        import jax
        import jax.numpy as jnp

        with self._lock:
            if self._tenants.get(tenant.group) is not tenant:
                raise ValueError(f"{tenant!r} is not admitted on this server")
            if tenant.suspended:
                raise RuntimeError(
                    f"tenant {tenant.name!r} is suspended by its SLO watchdog"
                    f" ({tenant.slo_report.summary() if tenant.slo_report else 'no report'})"
                )
            values = np.asarray(values, dtype=np.float32)
            if values.ndim != 2 or values.shape[1] != self.policy.parameter_count:
                raise ValueError(
                    f"values must be (n, {self.policy.parameter_count}),"
                    f" got {values.shape}"
                )
            if key is None:
                key = jax.random.fold_in(self._key, self._next_request_id)
            else:
                key = jnp.asarray(key)
                if not jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                    key = jax.random.wrap_key_data(key)
            if stats is not None:
                if self._stats is None:
                    raise ValueError(
                        "stats submitted but the server runs without"
                        " observation normalization"
                    )
                self._seed_tenant_stats(tenant, stats)
            request = EvalRequest(self._next_request_id, tenant, values, key, self)
            # the key's raw data, snapshotted ONCE: the packer broadcasts it
            # into the slab's key rows host-side (no per-dispatch device
            # sync, no per-item key stack)
            request.key_data = np.asarray(jax.random.key_data(key))
            request.submit_dispatch = self._dispatch_count
            self._next_request_id += 1
            tenant.pending.append(request)
            return request.future

    # --------------------------------------------------------------- serving
    def step(self) -> int:
        """Pack ONE slab from the pending queues and dispatch it; returns
        the number of real (non-padding) items served (0 = nothing
        pending, no dispatch)."""
        import jax

        with self._lock:
            runs = self._pack()
            if not runs:
                return 0
            n_packed = sum(count for _, _, count in runs)
            slab, lane_ids, groups, solution_keys = self._slab_arrays(
                runs, n_packed
            )
            out = self.program(
                slab,
                jax.random.fold_in(self._key, self._dispatch_count),
                self._stats,
                lane_ids,
                groups,
                solution_keys,
            )
            self._dispatch_count += 1
            self._items_served += n_packed
            if self._stats is not None:
                self._stats = out.stats
            self._credit(runs, out)
            return n_packed

    def drain(self) -> int:
        """Serve until every queue is empty; returns dispatches executed."""
        dispatches = 0
        while self.step():
            dispatches += 1
        return dispatches

    def _pack(self) -> List[Tuple[EvalRequest, int, int]]:
        """The packing round: walk tenants in the admission policy's order,
        taking each tenant's queued items FIFO, until the slab is full or
        nothing is pending. Suspended tenants still DRAIN (suspension
        gates new submits, not queued work — no deadlocked futures).
        Returns contiguous runs ``(request, first_item, count)`` so the
        slab materializes with slice copies, not a per-row host loop."""
        ready = [t for t in self._tenants.values() if t.pending]
        if not ready:
            return []
        runs: List[Tuple[EvalRequest, int, int]] = []
        n_packed = 0
        for tenant in self._admission.order(ready, self):
            for request in tenant.pending:
                room = self.slab_size - n_packed
                if room <= 0:
                    break
                taken = request.take_items(room)
                if len(taken):
                    runs.append((request, taken.start, len(taken)))
                    n_packed += len(taken)
            if n_packed >= self.slab_size:
                break
        return runs

    def _slab_arrays(self, runs, n_packed: int):
        """Materialize one dispatch's traced inputs. Idle rows repeat the
        first packed row (same compute shape, so the program never sees a
        ragged slab) but bind to group 0 — the reserved padding group no
        tenant reads. Everything is built with per-run slice copies on the
        host (numpy in, per the dispatch-cost note in CLAUDE.md) — one
        ``wrap_key_data`` upload replaces a per-item key stack."""
        import jax

        slab = np.empty((self.slab_size, self.policy.parameter_count), dtype=np.float32)
        lane_ids = np.empty(self.slab_size, dtype=np.int32)
        groups = np.empty(self.slab_size, dtype=np.int32)
        key_rows = np.empty(
            (self.slab_size,) + runs[0][0].key_data.shape, dtype=runs[0][0].key_data.dtype
        )
        row = 0
        for request, start, count in runs:
            stop = row + count
            slab[row:stop] = request.values[start : start + count]
            # request-local item indices: the standalone seed identity
            lane_ids[row:stop] = np.arange(start, start + count, dtype=np.int32)
            groups[row:stop] = request.tenant.group
            key_rows[row:stop] = request.key_data
            row = stop
        if row < self.slab_size:
            slab[row:] = slab[0]
            lane_ids[row:] = lane_ids[0]
            groups[row:] = 0
            key_rows[row:] = key_rows[0]
        return slab, lane_ids, groups, jax.random.wrap_key_data(key_rows)

    def _credit(self, runs, out) -> None:
        """Distribute one dispatch's results: per-item scores into their
        requests, the per-group telemetry rows into tenant/request
        accounting, SLO verdicts into admission state, completed requests
        into their futures."""
        from ..observability.devicemetrics import GroupTelemetry

        scores = np.asarray(out.scores)
        if self._score_dtype is None:
            self._score_dtype = scores.dtype
        gt = GroupTelemetry.from_array(np.asarray(out.telemetry))

        touched_requests: List[EvalRequest] = []
        touched_tenants: List[Tenant] = []
        row0 = 0
        for request, start, count in runs:
            request.scores[start : start + count] = scores[row0 : row0 + count]
            request.pending_items -= count
            row0 += count
            if not touched_requests or touched_requests[-1] is not request:
                touched_requests.append(request)
            tenant = request.tenant
            if not touched_tenants or touched_tenants[-1] is not tenant:
                touched_tenants.append(tenant)

        row_cache: Dict[int, GroupTelemetry] = {}

        def tenant_row(g: int) -> GroupTelemetry:
            if g not in row_cache:
                row_cache[g] = GroupTelemetry(
                    data=gt.data[g : g + 1].copy(),
                    health=None if gt.health is None else gt.health[g : g + 1].copy(),
                )
            return row_cache[g]

        for tenant in touched_tenants:
            row = tenant_row(tenant.group)
            tenant.telemetry = row if tenant.telemetry is None else tenant.telemetry + row
            if tenant.watchdog is not None:
                report = tenant.watchdog.check(tenant.telemetry)
                tenant.slo_report = report
                if not report.ok:
                    tenant.suspended = True
        for request in touched_requests:
            # a tenant's dispatch row covers ALL its lanes this dispatch;
            # when a tenant runs requests concurrently, each touched request
            # accrues the shared row (per-request figures are then an
            # over-count; per-TENANT figures stay exact — docs/serving.md)
            row = tenant_row(request.tenant.group)
            request.telemetry = (
                row if request.telemetry is None else request.telemetry + row
            )
            if request.done:
                request.tenant.pending.remove(request)
                request.tenant.requests_served += 1
                self._finish(request)
        if self._metrics is not None:
            self._metrics.emit(
                {
                    "dispatch": self._dispatch_count - 1,
                    "served": row0,
                    "slab": self.slab_size,
                    "tenants": {
                        t.name: t.group for t in self._tenants.values()
                    },
                },
                telemetry=gt,
            )

    def _finish(self, request: EvalRequest) -> None:
        """Assemble a completed request's RolloutResult-compatible record."""
        import jax.numpy as jnp

        from ..neuroevolution.net.vecrl import RolloutResult

        total = request.telemetry.total()
        result = RolloutResult(
            scores=jnp.asarray(request.scores.astype(self._score_dtype)),
            stats=self.tenant_stats(request.tenant),
            total_steps=total.env_steps,
            total_episodes=total.episodes,
            telemetry=request.telemetry.to_wire(),
        )
        request.future.set_result(result)

    # ------------------------------------------------------------- inspection
    @property
    def dispatches(self) -> int:
        return self._dispatch_count

    @property
    def items_served(self) -> int:
        return self._items_served

    def occupancy(self) -> float:
        """Share of dispatched slab rows carrying real tenant items (the
        rest were group-0 padding), cumulative over the server's life;
        0.0 before the first dispatch."""
        total_rows = self._dispatch_count * self.slab_size
        if total_rows == 0:
            return 0.0
        return self._items_served / total_rows

    def status(self) -> dict:
        """JSON-safe service summary (the stdio front's ``status`` op)."""
        with self._lock:
            tenants = {}
            for t in self._tenants.values():
                entry = {
                    "group": t.group,
                    "suspended": bool(t.suspended),
                    "pending_items": t.pending_items,
                    "requests_served": t.requests_served,
                }
                if t.telemetry is not None:
                    entry["queue_wait_p50"] = t.telemetry.queue_wait_quantile(0.5)
                    entry["queue_wait_p99"] = t.telemetry.queue_wait_quantile(0.99)
                    entry["starvation_share"] = round(t.telemetry.starvation_share(), 6)
                    entry["env_steps"] = t.telemetry.total().env_steps
                    entry["episodes"] = t.telemetry.total().episodes
                if t.slo_report is not None:
                    entry.update(t.slo_report.as_status())
                tenants[t.name] = entry
            return {
                "slab_size": self.slab_size,
                "max_tenants": self.max_tenants,
                "dispatches": self._dispatch_count,
                "items_served": self._items_served,
                "occupancy": round(self.occupancy(), 6),
                "admission": repr(self._admission),
                "program_key": list(self.program.key),
                "tenants": tenants,
            }
