"""Multi-tenant continuous-batching evaluation service (docs/serving.md).

The front door the refill engine was built for: one long-running
:class:`EvalServer` keeps ONE compiled ``episodes_refill`` rollout program
resident and packs (solution, episode) items from many concurrent searches
into its fixed-width device loop — continuous batching where the telemetry
group id is the tenant id. ``RemoteEvalBackend`` plugs an unmodified
``VecNE`` into a shared server (``eval_backend=``); ``python -m
evotorch_tpu.serving`` is the JSONL-over-stdio front for out-of-process
clients.
"""

from .admission import AdmissionPolicy, FIFOAdmission, StarvationAwareAdmission
from .backend import RemoteEvalBackend
from .requests import EvalFuture, EvalRequest
from .server import EvalServer, Tenant
from .stdio import serve_stdio

__all__ = [
    "AdmissionPolicy",
    "EvalFuture",
    "EvalRequest",
    "EvalServer",
    "FIFOAdmission",
    "RemoteEvalBackend",
    "StarvationAwareAdmission",
    "Tenant",
    "serve_stdio",
]
