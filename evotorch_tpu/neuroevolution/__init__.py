"""Neuroevolution problem layers (L6).

Parity: reference ``neuroevolution/__init__.py`` — ``NEProblem``, ``GymNE``,
``VecGymNE``, ``SupervisedNE`` plus the ``net`` subpackage.
"""

from . import net
from .gymne import GymNE
from .neproblem import BaseNEProblem, NEProblem
from .supervisedne import SupervisedNE
from .vecneproblem import VecGymNE, VecNE

__all__ = [
    "net",
    "GymNE",
    "BaseNEProblem",
    "NEProblem",
    "SupervisedNE",
    "VecGymNE",
    "VecNE",
]
